#!/usr/bin/env python
"""OOM preflight planner: fits/doesn't-fit per sharding/batch config,
from lowering-only cost data — no execution, no tunnel round-trips paid
per candidate beyond the AOT compile.

    python tools/memory_planner.py --hbm-gb 16
    python tools/memory_planner.py --hbm-gb 16 --devices 8 \
        --configs dp8,dp4xmp2,dp2xmp4 --batches 4,8 --hidden 512 --layers 4

For each candidate (dp × mp × pp mesh split, batch size — the pp
column rides the planner's shared enumeration, capped by the probe's
``--layers`` stage depth and ``PT_AUTOSHARD_PP_MAX``) the planner
builds the model under that mesh, AOT-compiles the full train step
(fwd+bwd+optimizer — `jit/train_step.py`; pp>1 candidates compile the
pipeline-staged probe), and reads XLA's own executable memory
accounting (`monitor/memory.py:executable_record`;
per-device for SPMD executables) against the ``--hbm-gb`` budget. A
90 s tunnel compile that would end in an OOM becomes a table row
instead (PAPERS: *GSPMD*, *Memory-efficient array redistribution* — the
sharding choice IS the memory plan).

The number judged is ``args + temp`` bytes per device: parameters,
optimizer state, batch, and every XLA temporary live during the step —
the high-water mark that has to fit. Host-side RAM is used to
materialize parameters for lowering; the device never runs.

With ``PT_EXEC_CACHE=<dir>`` in the environment (or ``--exec-cache``),
candidate executables come from the AOT executable cache
(``paddle_tpu/jit/exec_cache.py``): a repeated sweep — the planner's
normal usage — deserializes every already-seen candidate instead of
recompiling it, and each row says which (``exec_cache: hit|miss``).

Exit code: 0 when at least one candidate fits, 3 when none do, 2 on
setup errors — so a driver can gate a launch on the verdict.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _autoshard_mod(name):
    """Load `paddle_tpu/autoshard/<name>.py` BY FILE PATH — these
    modules are stdlib-pure, and a package import would pull the whole
    jax-backed paddle_tpu __init__ into the parent process the
    corrected-child re-exec exists to keep light (CLI/arg errors must
    surface before any backend initializes)."""
    import importlib.util

    path = os.path.join(ROOT, "paddle_tpu", "autoshard", f"{name}.py")
    spec = importlib.util.spec_from_file_location(
        f"_autoshard_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _candidates_mod():
    """`paddle_tpu.autoshard.candidates` — the planner's enumeration is
    the ONE code path (ISSUE 10 satellite: this tool's private copies
    moved there)."""
    return _autoshard_mod("candidates")


def parse_mesh(token: str) -> dict:
    """``dp4xmp2`` -> {"dp": 4, "mp": 2} (either axis optional)."""
    return _candidates_mod().parse_mesh(token)


def default_meshes(n_devices: int) -> list:
    """(dp, mp) factorizations of the device count, dp-heavy first."""
    return _candidates_mod().default_meshes(n_devices)


def candidates(args, n_devices: int) -> list:
    c = _candidates_mod()
    # the pp column rides the shared enumeration (ISSUE 15): default
    # sweeps include pipeline candidates up to the probe's stage-able
    # depth (--layers), bounded by PT_AUTOSHARD_PP_MAX
    return c.enumerate_candidates(
        n_devices, args.configs, str(args.batches),
        pp_max=c.pp_cap(args.layers), stage_depth=args.layers)


def plan_one(cand: dict, args) -> dict:
    """One candidate: mesh init -> model -> AOT compile -> per-device
    memory record -> verdict — via the sharding planner's shared
    child-lowering API (`paddle_tpu/autoshard/lowering.py`, where this
    function's body moved). Tears the mesh down before returning."""
    sys.path.insert(0, ROOT)
    from paddle_tpu.autoshard.lowering import ProbeSpec, lower_candidate

    return lower_candidate(cand, ProbeSpec.from_args(args),
                           hbm_gb=args.hbm_gb)


def render(rows: list, hbm_gb: float, n_devices: int) -> str:
    out = [f"== memory planner: budget {hbm_gb:.2f} GiB/device, "
           f"{n_devices} devices =="]
    hdr = (f"{'config':<18}{'per-dev peak':>14}{'args':>10}{'temp':>10}"
           f"{'out':>10}  verdict")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        if "error" in r:
            out.append(f"{r['label']:<18}{'—':>14}{'—':>10}{'—':>10}"
                       f"{'—':>10}  ERROR ({r['error'][:40]})")
            continue
        gib = 2**30
        out.append(
            f"{r['label']:<18}"
            f"{r['peak_bytes'] / gib:>11.3f} GiB"
            f"{r['args_bytes'] / gib:>10.3f}"
            f"{r['temp_bytes'] / gib:>10.3f}"
            f"{r['output_bytes'] / gib:>10.3f}"
            f"  {'FITS' if r['fits'] else 'DOES NOT FIT'}")
    n_fit = sum(1 for r in rows if r.get("fits"))
    out.append(f"verdict: {n_fit}/{len(rows)} candidate config(s) fit in "
               f"{hbm_gb:.2f} GiB/device")
    return "\n".join(out)


def plan(args, n_devices: int) -> list:
    rows = []
    for cand in candidates(args, n_devices):
        try:
            rows.append(plan_one(cand, args))
        except Exception as e:  # noqa: BLE001 — one broken candidate
            # must not hide the others' verdicts
            rows.append({"label": _candidates_mod().candidate_label(cand),
                         **cand, "error": f"{type(e).__name__}: {e}"})
    return rows


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description="Fits/doesn't-fit preflight over sharding/batch "
                    "candidates from lowering-only memory accounting.")
    ap.add_argument("--hbm-gb", type=float, default=16.0,
                    help="per-device HBM budget in GiB (default 16 — one "
                         "v5e chip)")
    ap.add_argument("--devices", type=int, default=8,
                    help="mesh size; a virtual CPU mesh of this many "
                         "devices is forced (default 8)")
    ap.add_argument("--configs", default=None,
                    help="comma list of mesh splits, e.g. "
                         "'dp8,dp4xmp2,dp2xmp4' (default: all power-of-2 "
                         "dp×mp factorizations of --devices)")
    ap.add_argument("--batches", default="8",
                    help="comma list of global batch sizes (default 8)")
    # probe dims shared with tools/shard_plan.py (one sweep, two tools)
    _autoshard_mod("cli").add_probe_args(ap)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + 3 mesh candidates (CI smoke)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line with the rows as well")
    ap.add_argument("--exec-cache", default=None, metavar="DIR",
                    help="AOT executable cache dir for the candidate "
                         "compiles (default: inherit PT_EXEC_CACHE) — a "
                         "repeated sweep then deserializes instead of "
                         "recompiling every (dp×mp, batch) candidate")
    return ap


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    _cli = _autoshard_mod("cli")
    if args.smoke:
        _cli.apply_smoke(args)

    # the planner needs its virtual mesh BEFORE jax initializes a
    # backend; the host sitecustomize pins the tunneled TPU at
    # interpreter start, so re-exec in a corrected child environment
    # (shared dance: autoshard/cli.py — PT_EXEC_CACHE rides into the
    # child so repeated sweeps pay XLA compilation once per candidate
    # signature EVER, not once per invocation)
    if os.environ.get("_PT_PLANNER_CHILD") != "1":
        return _cli.reexec_virtual_child(
            __file__, "memory_planner",
            argv if argv is not None else sys.argv[1:],
            args.devices, "_PT_PLANNER_CHILD",
            exec_cache=args.exec_cache)

    import jax

    jax.config.update("jax_platforms", "cpu")
    n = len(jax.devices())
    if n < args.devices:
        print(f"memory_planner: need {args.devices} devices, have {n}",
              file=sys.stderr)
        return 2
    sys.path.insert(0, ROOT)
    try:
        rows = plan(args, args.devices)
    except ValueError as e:
        # bad --configs tokens / factorizations: name the problem, rc 2
        msg = str(e)
        print(msg if msg.startswith("memory_planner:")
              else f"memory_planner: {msg}", file=sys.stderr)
        return 2
    print(render(rows, args.hbm_gb, args.devices), flush=True)
    cache_stats = None
    try:
        from paddle_tpu.jit import exec_cache

        if exec_cache.enabled():
            cache_stats = exec_cache.stats()
            print(f"exec cache: {cache_stats['disk_hits']} disk hit(s), "
                  f"{cache_stats['mem_hits']} mem hit(s), "
                  f"{cache_stats['misses']} miss(es), "
                  f"{cache_stats['compile_ms_saved']:.0f} compile-ms "
                  f"saved ({cache_stats['dir']})", flush=True)
    except Exception:  # noqa: BLE001 — stats must not break the verdict
        pass
    if args.json:
        obj = {"memory_planner": {
            "hbm_gb": args.hbm_gb, "devices": args.devices,
            "rows": rows}}
        if cache_stats is not None:
            obj["memory_planner"]["exec_cache"] = cache_stats
        print(json.dumps(obj), flush=True)
    if not rows:
        return 2
    return 0 if any(r.get("fits") for r in rows) else 3


if __name__ == "__main__":
    sys.exit(main())
