#!/usr/bin/env python
"""OOM preflight planner: fits/doesn't-fit per sharding/batch config,
from lowering-only cost data — no execution, no tunnel round-trips paid
per candidate beyond the AOT compile.

    python tools/memory_planner.py --hbm-gb 16
    python tools/memory_planner.py --hbm-gb 16 --devices 8 \
        --configs dp8,dp4xmp2,dp2xmp4 --batches 4,8 --hidden 512 --layers 4

For each candidate (dp × mp mesh split, batch size) the planner builds
the model under that mesh, AOT-compiles the full train step
(fwd+bwd+optimizer — `jit/train_step.py`), and reads XLA's own
executable memory accounting (`monitor/memory.py:executable_record`;
per-device for SPMD executables) against the ``--hbm-gb`` budget. A
90 s tunnel compile that would end in an OOM becomes a table row
instead (PAPERS: *GSPMD*, *Memory-efficient array redistribution* — the
sharding choice IS the memory plan).

The number judged is ``args + temp`` bytes per device: parameters,
optimizer state, batch, and every XLA temporary live during the step —
the high-water mark that has to fit. Host-side RAM is used to
materialize parameters for lowering; the device never runs.

With ``PT_EXEC_CACHE=<dir>`` in the environment (or ``--exec-cache``),
candidate executables come from the AOT executable cache
(``paddle_tpu/jit/exec_cache.py``): a repeated sweep — the planner's
normal usage — deserializes every already-seen candidate instead of
recompiling it, and each row says which (``exec_cache: hit|miss``).

Exit code: 0 when at least one candidate fits, 3 when none do, 2 on
setup errors — so a driver can gate a launch on the verdict.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parse_mesh(token: str) -> dict:
    """``dp4xmp2`` -> {"dp": 4, "mp": 2} (either axis optional)."""
    out = {"dp": 1, "mp": 1}
    for part in token.lower().split("x"):
        part = part.strip()
        if not part:
            continue
        for axis in ("dp", "mp"):
            if part.startswith(axis):
                out[axis] = int(part[len(axis):])
                break
        else:
            raise ValueError(f"memory_planner: bad mesh token {part!r} "
                             f"in {token!r} (expected dpN / mpN / dpNxmpM)")
    return out


def default_meshes(n_devices: int) -> list:
    """(dp, mp) factorizations of the device count, dp-heavy first."""
    out = []
    mp = 1
    while mp <= n_devices:
        if n_devices % mp == 0:
            out.append({"dp": n_devices // mp, "mp": mp})
        mp *= 2
    return out


def candidates(args, n_devices: int) -> list:
    meshes = ([parse_mesh(t) for t in args.configs.split(",")]
              if args.configs else default_meshes(n_devices))
    batches = [int(b) for b in str(args.batches).split(",")]
    out = []
    for m in meshes:
        if m["dp"] * m["mp"] != n_devices:
            raise ValueError(
                f"memory_planner: dp{m['dp']}xmp{m['mp']} does not "
                f"factorize {n_devices} devices")
        for b in batches:
            out.append({**m, "batch": b})
    return out


def plan_one(cand: dict, args) -> dict:
    """One candidate: mesh init -> model -> AOT compile -> per-device
    memory record -> verdict. Tears the mesh down before returning."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.distributed import env as env_mod, fleet
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.monitor import memory as memobs

    dp, mp, batch = cand["dp"], cand["mp"], cand["batch"]
    label = f"dp{dp}·mp{mp} b{batch}"
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    try:
        cfg = LlamaConfig(
            vocab_size=args.vocab, hidden_size=args.hidden,
            intermediate_size=args.intermediate or args.hidden * 3,
            num_hidden_layers=args.layers, num_attention_heads=args.heads,
            max_position_embeddings=args.seq,
            sequence_parallel=mp > 1,
            use_parallel_cross_entropy=mp > 1)
        pt.seed(0)
        model = LlamaForCausalLM(cfg)
        opt = pt.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
        step = TrainStep(model, opt, lambda m, i, l: m(i, l))
        ids = pt.to_tensor(np.random.randint(
            0, cfg.vocab_size, (batch, args.seq)))
        from paddle_tpu.jit import exec_cache

        hits_before = (exec_cache.stats()["mem_hits"]
                       + exec_cache.stats()["disk_hits"])
        rec = memobs.executable_record(step, ids, ids, name=label)
        rec.update(cand)
        rec["label"] = label
        rec["fits"] = rec["peak_bytes"] <= args.hbm_gb * 2**30
        if exec_cache.enabled():
            st = exec_cache.stats()
            rec["exec_cache"] = ("hit" if st["mem_hits"] + st["disk_hits"]
                                 > hits_before else "miss")
        return rec
    finally:
        env_mod.reset_env()


def render(rows: list, hbm_gb: float, n_devices: int) -> str:
    out = [f"== memory planner: budget {hbm_gb:.2f} GiB/device, "
           f"{n_devices} devices =="]
    hdr = (f"{'config':<18}{'per-dev peak':>14}{'args':>10}{'temp':>10}"
           f"{'out':>10}  verdict")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        if "error" in r:
            out.append(f"{r['label']:<18}{'—':>14}{'—':>10}{'—':>10}"
                       f"{'—':>10}  ERROR ({r['error'][:40]})")
            continue
        gib = 2**30
        out.append(
            f"{r['label']:<18}"
            f"{r['peak_bytes'] / gib:>11.3f} GiB"
            f"{r['args_bytes'] / gib:>10.3f}"
            f"{r['temp_bytes'] / gib:>10.3f}"
            f"{r['output_bytes'] / gib:>10.3f}"
            f"  {'FITS' if r['fits'] else 'DOES NOT FIT'}")
    n_fit = sum(1 for r in rows if r.get("fits"))
    out.append(f"verdict: {n_fit}/{len(rows)} candidate config(s) fit in "
               f"{hbm_gb:.2f} GiB/device")
    return "\n".join(out)


def plan(args, n_devices: int) -> list:
    rows = []
    for cand in candidates(args, n_devices):
        try:
            rows.append(plan_one(cand, args))
        except Exception as e:  # noqa: BLE001 — one broken candidate
            # must not hide the others' verdicts
            rows.append({"label": f"dp{cand['dp']}·mp{cand['mp']} "
                                  f"b{cand['batch']}",
                         **cand, "error": f"{type(e).__name__}: {e}"})
    return rows


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description="Fits/doesn't-fit preflight over sharding/batch "
                    "candidates from lowering-only memory accounting.")
    ap.add_argument("--hbm-gb", type=float, default=16.0,
                    help="per-device HBM budget in GiB (default 16 — one "
                         "v5e chip)")
    ap.add_argument("--devices", type=int, default=8,
                    help="mesh size; a virtual CPU mesh of this many "
                         "devices is forced (default 8)")
    ap.add_argument("--configs", default=None,
                    help="comma list of mesh splits, e.g. "
                         "'dp8,dp4xmp2,dp2xmp4' (default: all power-of-2 "
                         "dp×mp factorizations of --devices)")
    ap.add_argument("--batches", default="8",
                    help="comma list of global batch sizes (default 8)")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--intermediate", type=int, default=0,
                    help="FFN width (default 3*hidden)")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + 3 mesh candidates (CI smoke)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line with the rows as well")
    ap.add_argument("--exec-cache", default=None, metavar="DIR",
                    help="AOT executable cache dir for the candidate "
                         "compiles (default: inherit PT_EXEC_CACHE) — a "
                         "repeated sweep then deserializes instead of "
                         "recompiling every (dp×mp, batch) candidate")
    return ap


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    if args.smoke:
        args.hidden, args.layers, args.heads = 64, 2, 4
        args.seq, args.vocab, args.batches = 32, 512, "8"
        if not args.configs:
            args.configs = "dp8,dp4xmp2,dp2xmp4"

    # the planner needs its virtual mesh BEFORE jax initializes a
    # backend; the host sitecustomize pins the tunneled TPU at
    # interpreter start, so (like __graft_entry__.dryrun_multichip)
    # re-exec in a corrected child environment
    if os.environ.get("_PT_PLANNER_CHILD") != "1":
        env = dict(os.environ)
        env["_PT_PLANNER_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        # PT_EXEC_CACHE rides into the child (dict(os.environ) carries an
        # inherited value; --exec-cache overrides) so the planner's normal
        # usage — repeated sweeps — pays XLA compilation once per candidate
        # signature EVER, not once per invocation
        if args.exec_cache:
            env["PT_EXEC_CACHE"] = os.path.abspath(args.exec_cache)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(
            f"--xla_force_host_platform_device_count={args.devices}")
        env["XLA_FLAGS"] = " ".join(flags)
        code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
                "import sys; sys.path.insert(0, %r); "
                "sys.path.insert(0, %r); "
                "import importlib.util; "
                "spec = importlib.util.spec_from_file_location("
                "'memory_planner', %r); "
                "mod = importlib.util.module_from_spec(spec); "
                "spec.loader.exec_module(mod); "
                "sys.exit(mod.main(%r))"
                % (ROOT, os.path.join(ROOT, "tools"),
                   os.path.abspath(__file__),
                   argv if argv is not None else sys.argv[1:]))
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              cwd=ROOT, timeout=1800)
        return proc.returncode

    import jax

    jax.config.update("jax_platforms", "cpu")
    n = len(jax.devices())
    if n < args.devices:
        print(f"memory_planner: need {args.devices} devices, have {n}",
              file=sys.stderr)
        return 2
    sys.path.insert(0, ROOT)
    try:
        rows = plan(args, args.devices)
    except ValueError as e:
        # bad --configs tokens / factorizations: name the problem, rc 2
        msg = str(e)
        print(msg if msg.startswith("memory_planner:")
              else f"memory_planner: {msg}", file=sys.stderr)
        return 2
    print(render(rows, args.hbm_gb, args.devices), flush=True)
    cache_stats = None
    try:
        from paddle_tpu.jit import exec_cache

        if exec_cache.enabled():
            cache_stats = exec_cache.stats()
            print(f"exec cache: {cache_stats['disk_hits']} disk hit(s), "
                  f"{cache_stats['mem_hits']} mem hit(s), "
                  f"{cache_stats['misses']} miss(es), "
                  f"{cache_stats['compile_ms_saved']:.0f} compile-ms "
                  f"saved ({cache_stats['dir']})", flush=True)
    except Exception:  # noqa: BLE001 — stats must not break the verdict
        pass
    if args.json:
        obj = {"memory_planner": {
            "hbm_gb": args.hbm_gb, "devices": args.devices,
            "rows": rows}}
        if cache_stats is not None:
            obj["memory_planner"]["exec_cache"] = cache_stats
        print(json.dumps(obj), flush=True)
    if not rows:
        return 2
    return 0 if any(r.get("fits") for r in rows) else 3


if __name__ == "__main__":
    sys.exit(main())
