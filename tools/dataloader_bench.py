"""Threads vs processes for Python-heavy vs numpy-heavy __getitem__.

Backs the DataLoader worker_mode default (io/reader.py, PERF.md "Input
pipeline"). Run on a MULTI-CORE host for the scaling question — a 1-core
box (the round-5 CI box) can only show the serial rates and the IPC tax.
"""
import time, threading, queue, multiprocessing as mp
import numpy as np

N_ITEMS = 512

def py_heavy(i):
    # tokenizer-ish: pure-Python loop + small-object churn (GIL-bound)
    rng = np.random.RandomState(i)
    s = rng.randint(0, 255, 2048).tolist()
    toks = []
    for b in s:
        toks.append((b * 131 + 7) % 30000)
        if b % 7 == 0:
            toks.append(b)
    arr = np.asarray(toks[:1024], np.int32)
    return np.pad(arr, (0, 1024 - len(arr)))

def np_heavy(i):
    # decode/augment-ish: big numpy ops (GIL released)
    rng = np.random.RandomState(i)
    img = rng.randint(0, 255, (224, 224, 3)).astype(np.float32)
    img = img[::-1].copy()
    img = (img - img.mean((0, 1))) / (img.std((0, 1)) + 1e-5)
    return img.transpose(2, 0, 1)

def bench_serial(fn):
    t0 = time.perf_counter()
    for i in range(N_ITEMS):
        fn(i)
    return N_ITEMS / (time.perf_counter() - t0)

def bench_threads(fn, n):
    q_in = queue.Queue(); done = []
    for i in range(N_ITEMS): q_in.put(i)
    def w():
        while True:
            try: i = q_in.get_nowait()
            except queue.Empty: return
            done.append(fn(i) is not None)
    t0 = time.perf_counter()
    ts = [threading.Thread(target=w) for _ in range(n)]
    [t.start() for t in ts]; [t.join() for t in ts]
    return N_ITEMS / (time.perf_counter() - t0)

def bench_procs(fn, n):
    with mp.get_context("fork").Pool(n) as pool:
        pool.map(fn, range(n))  # real warm-up: every worker forks + runs once
        t0 = time.perf_counter()
        list(pool.imap_unordered(fn, range(N_ITEMS), chunksize=8))
        return N_ITEMS / (time.perf_counter() - t0)

for name, fn in [("py_heavy", py_heavy), ("np_heavy", np_heavy)]:
    ser = bench_serial(fn)
    print(f"{name}: serial {ser:.0f} it/s")
    for n in (4, 8):
        print(f"  threads x{n}: {bench_threads(fn, n):.0f} it/s")
    for n in (4, 8):
        print(f"  procs   x{n}: {bench_procs(fn, n):.0f} it/s")
