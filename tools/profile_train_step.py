"""Capture an xplane trace of the compiled headline train step on the
live chip and print the MFU breakdown (VERDICT r3 next-round item 3).

Usage: python tools/profile_train_step.py [--steps 5] [--outdir profiles/]

Captures `jax.profiler.trace` around the bench model's TrainStep, then
parses the xplane proto for per-op-category time (matmul / attention /
optimizer / other / host gaps) and appends the summary to
PERF_MEASUREMENTS.json. One command so a brief tunnel window suffices;
run via hwbench or standalone whenever the chip is up.
"""
from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _trace_files(outdir):
    return set(glob.glob(os.path.join(
        outdir, "**", "*.trace.json.gz"), recursive=True))


def _breakdown_from_xplane(paths):
    """Best-effort xplane parse: per-op self-time grouped by name class,
    over exactly the trace files THIS run produced (repeat runs into the
    same outdir must not double-count)."""
    rows = {}
    for path in sorted(paths):
        with gzip.open(path, "rt") as f:
            trace = json.load(f)
        events = trace.get("traceEvents", [])
        # device lanes only: host thread slices would overcount wall time
        pid_names = {ev.get("pid"): ev.get("args", {}).get("name", "")
                     for ev in events
                     if ev.get("ph") == "M"
                     and ev.get("name") == "process_name"}
        device_pids = {pid for pid, name in pid_names.items()
                       if any(k in name for k in ("TPU", "/device",
                                                  "Device", "XLA Op"))}
        # within a device pid, keep the per-op lane only: module/step
        # lanes span whole steps and would double-count everything
        tid_names = {(ev.get("pid"), ev.get("tid")):
                     ev.get("args", {}).get("name", "")
                     for ev in events
                     if ev.get("ph") == "M"
                     and ev.get("name") == "thread_name"}
        op_tids = {key for key, name in tid_names.items()
                   if "XLA Ops" in name}
        for ev in events:
            if ev.get("ph") != "X" or "dur" not in ev:
                continue
            if device_pids and ev.get("pid") not in device_pids:
                continue
            if op_tids and (ev.get("pid"), ev.get("tid")) not in op_tids:
                continue
            name = ev.get("name", "")
            hlo_cat = (ev.get("args") or {}).get("hlo_category", "")
            low = (hlo_cat + " " + name).lower()
            if any(k in low for k in ("fusion", "dot", "conv", "matmul")):
                cat = "matmul/fusion"
            elif any(k in low for k in ("custom-call", "mosaic", "flash")):
                cat = "custom-call(pallas)"
            elif any(k in low for k in ("all-reduce", "all-gather",
                                        "collective", "permute")):
                cat = "collective"
            elif any(k in low for k in ("copy", "transpose", "reshape",
                                        "bitcast")):
                cat = "data-movement"
            else:
                cat = "other"
            rows[cat] = rows.get(cat, 0.0) + ev["dur"] / 1e6  # us -> s
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--outdir", default="profiles")
    ap.add_argument("--model", choices=("llama", "resnet"),
                    default="llama",
                    help="which bench step to profile (resnet: the "
                         "round-4 verdict's 0.130-MFU fix-it item)")
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import (_peak_flops, build_headline_trainstep,
                       enable_compilation_cache)

    enable_compilation_cache()
    backend = jax.default_backend()
    print(f"profile_train_step: backend={backend} model={args.model}",
          flush=True)
    on_cpu = backend == "cpu"

    import paddle_tpu as pt

    # the EXACT bench model/step — the profile must be attributable to
    # the bench number
    if args.model == "resnet":
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks"))
        from baseline_configs import build_resnet_trainstep

        model, step, ids, labels, batch, seq = build_resnet_trainstep(
            on_cpu)  # (x, y, batch, hw) in the resnet case
        flops_per_unit = 3 * 4.1e9 if seq == 224 else 0.0  # per image
        bench_metric = "resnet50_train_imgs_per_sec_per_chip"
        profile_metric = "resnet50_train_profile_device_busy_frac"
        units_per_step = batch
        # data_format must be in the match: hwbench interleaves NCHW and
        # NHWC bench records at the same batch, and a busy fraction
        # computed against the other layout's step wall is misattributed
        fmt = os.environ.get("PT_RESNET_FORMAT", "NCHW")
        match = {"batch": batch, "data_format": fmt}
        extra_tags = {"model": "resnet", "data_format": fmt,
                      "batch": batch}
    else:
        model, step, batch, seq = build_headline_trainstep(on_cpu)
        vocab = model.config.vocab_size
        ids = pt.to_tensor(np.random.randint(0, vocab, (batch, seq)))
        labels = pt.to_tensor(np.random.randint(0, vocab, (batch, seq)))
        flops_per_unit = model.flops_per_token(seq)
        bench_metric = "llama_train_tokens_per_sec_per_chip"
        profile_metric = "llama_train_profile_device_busy_frac"
        units_per_step = batch * seq
        match = {"batch": batch, "seq": seq,
                 "ce_chunk": model.config.ce_chunk_size}
        extra_tags = {"model": "llama", "batch": batch, "seq": seq}

    # warm/compile outside the trace
    float(np.asarray(step(ids, labels).numpy()).sum())
    os.makedirs(args.outdir, exist_ok=True)
    before = _trace_files(args.outdir)
    # python/host tracers off: their ~1M events per few steps exhaust the
    # trace budget and truncate away the device per-op lane — the one
    # lane this tool exists to read
    try:
        opts = jax.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        opts.host_tracer_level = 1
    except AttributeError:  # older jax: no options — trace anyway
        opts = None
    t0 = time.perf_counter()
    if opts is not None:
        jax.profiler.start_trace(args.outdir, profiler_options=opts)
    else:  # older jax predates the kwarg too — omit it entirely
        jax.profiler.start_trace(args.outdir)
    try:
        for _ in range(args.steps):
            loss = step(ids, labels)
        float(np.asarray(loss.numpy()).sum())  # transfer-backed sync
    finally:
        jax.profiler.stop_trace()
    wall = time.perf_counter() - t0
    tokens_per_sec = units_per_step * args.steps / wall
    mfu = (tokens_per_sec * flops_per_unit
           / _peak_flops(jax.devices()[0])) if flops_per_unit else 0.0
    print(f"traced {args.steps} steps in {wall:.3f}s "
          f"({tokens_per_sec:.0f} units/s, traced-wall mfu {mfu:.4f} — "
          f"profiler-inflated, informational only)", flush=True)

    rows = _breakdown_from_xplane(_trace_files(args.outdir) - before)
    if on_cpu:
        print("(CPU: no device lane in the trace — host-thread slices "
              "below overcount; the breakdown is meaningful on TPU)",
              flush=True)
    if rows:
        total = sum(rows.values())
        print("device-time breakdown (self time):", flush=True)
        for cat, secs in sorted(rows.items(), key=lambda kv: -kv[1]):
            print(f"  {cat:24s} {secs:8.4f}s  {secs / total:6.1%}",
                  flush=True)
        # the traced wall is profiler-inflated (trace IO, host tracer),
        # so busy-vs-traced-wall would understate 40x. The honest
        # denominator is the un-profiled bench step wall from the
        # last-good persisted headline measurement at the same config.
        device_s_per_step = total / args.steps
        print(f"  device time / step: {device_s_per_step * 1e3:.1f} ms",
              flush=True)
        device_busy = None
        try:
            from paddle_tpu.utils import measurements as _m

            lg = _m.last_good(bench_metric, match=match)
            if lg:
                bench_step_wall = units_per_step / lg["value"]
                device_busy = device_s_per_step / bench_step_wall
                print(f"  device busy vs bench step wall "
                      f"({bench_step_wall * 1e3:.1f} ms): "
                      f"{device_busy:.1%}", flush=True)
        except Exception:  # noqa: BLE001 — busy frac is optional
            pass
    else:
        device_busy = device_s_per_step = None
        print("no trace events parsed — breakdown unavailable "
              "(trace format drift?); NOT recording a busy fraction",
              flush=True)

    if not on_cpu:
        from paddle_tpu.utils import measurements as meas

        # persist the DEVICE-BUSY fraction, not traced-wall MFU: the
        # traced wall is profiler-inflated ~12-40x, so a metric named
        # "mfu" computed from it is junk data that contradicts its own
        # name (round-4 verdict weak #4). Throughput truth lives in the
        # bench metric; this record carries the profile breakdown.
        meas.record_or_warn(
            profile_metric,
            round(device_busy, 4) if device_busy is not None else -1.0,
            "fraction",
            extra={"note": "device-time/step over the last-good bench "
                           "step wall at the same config; -1 = no "
                           "matching bench record or no device lane",
                   "traced_wall_units_per_sec":
                       round(tokens_per_sec, 1),
                   "breakdown_s": ({k: round(v, 4)
                                    for k, v in rows.items()}
                                   if rows else None),
                   "device_s_per_step": (round(device_s_per_step, 4)
                                         if device_s_per_step is not None
                                         else None),
                   "steps": args.steps, "outdir": args.outdir,
                   **extra_tags})
    return 0


if __name__ == "__main__":
    sys.exit(main())
