"""Generate docs/API_PARITY.md: the reference-__all__ sweep as a table.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python tools/gen_api_parity.py
"""
from __future__ import annotations

import argparse
import ast
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

R = "/root/reference/python/paddle/"  # overridden by --reference


def ref_all(path):
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"reference file missing: {path} — a moved/renamed upstream "
            "file must fail the sweep, not silently count as 100%")
    tree = ast.parse(open(path).read())
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for e in node.value.elts:
                        try:
                            v = ast.literal_eval(e)
                            if isinstance(v, str):
                                names.append(v)
                        except Exception:  # noqa: BLE001
                            pass
    return set(names)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference/python/paddle/",
                    help="reference python/paddle checkout root")
    args = ap.parse_args()
    global R
    R = args.reference.rstrip("/") + "/"

    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt

    pairs = [
        ("paddle", "__init__.py", pt),
        ("paddle.nn", "nn/__init__.py", pt.nn),
        ("paddle.nn.functional", "nn/functional/__init__.py",
         pt.nn.functional),
        ("paddle.nn.initializer", "nn/initializer/__init__.py",
         pt.nn.initializer),
        ("paddle.nn.utils", "nn/utils/__init__.py", pt.nn.utils),
        ("paddle.linalg", "linalg.py", pt.linalg),
        ("paddle.optimizer", "optimizer/__init__.py", pt.optimizer),
        ("paddle.optimizer.lr", "optimizer/lr.py", pt.optimizer.lr),
        ("paddle.io", "io/__init__.py", pt.io),
        ("paddle.metric", "metric/__init__.py", pt.metric),
        ("paddle.amp", "amp/__init__.py", pt.amp),
        ("paddle.autograd", "autograd/__init__.py", pt.autograd),
        ("paddle.jit", "jit/__init__.py", pt.jit),
        ("paddle.distribution", "distribution/__init__.py",
         pt.distribution),
        ("paddle.distribution.transform", "distribution/transform.py",
         pt.distribution.transform),
        ("paddle.vision", "vision/__init__.py", pt.vision),
        ("paddle.vision.transforms", "vision/transforms/__init__.py",
         pt.vision.transforms),
        ("paddle.vision.ops", "vision/ops.py", pt.vision.ops),
        ("paddle.vision.datasets", "vision/datasets/__init__.py",
         pt.vision.datasets),
        ("paddle.signal", "signal.py", pt.signal),
        ("paddle.fft", "fft.py", pt.fft),
        ("paddle.distributed", "distributed/__init__.py", pt.distributed),
        ("paddle.distributed.rpc", "distributed/rpc/__init__.py",
         pt.distributed.rpc),
        ("paddle.distributed.fleet", "distributed/fleet/__init__.py",
         pt.distributed.fleet),
        ("paddle.distributed.fleet.utils",
         "distributed/fleet/utils/__init__.py",
         pt.distributed.fleet.utils),
        ("paddle.sparse", "sparse/__init__.py", pt.sparse),
        ("paddle.sparse.nn", "sparse/nn/__init__.py", pt.sparse.nn),
        ("paddle.static", "static/__init__.py", pt.static),
        ("paddle.incubate", "incubate/__init__.py", pt.incubate),
        ("paddle.incubate.nn", "incubate/nn/__init__.py", pt.incubate.nn),
        ("paddle.text", "text/__init__.py", pt.text),
        ("paddle.audio", "audio/__init__.py", pt.audio),
        ("paddle.audio.functional", "audio/functional/__init__.py",
         pt.audio.functional),
        ("paddle.geometric", "geometric/__init__.py", pt.geometric),
        ("paddle.profiler", "profiler/__init__.py", pt.profiler),
        ("paddle.quantization", "quantization/__init__.py",
         pt.quantization),
        ("paddle.utils", "utils/__init__.py", pt.utils),
    ]
    rows = []
    total = covered = raising = 0
    for label, rel, obj in pairs:
        names = ref_all(R + rel)
        if not names:
            raise RuntimeError(
                f"{rel}: parsed ZERO names from the reference __all__ — "
                "the sweep would silently undercount; fix the path or "
                "the parser")
        missing = sorted(n for n in names if not hasattr(obj, n))
        # documented-exclusion stubs: the name resolves but any use raises
        # with rationale (marked by the factories' __excluded__ attribute)
        stubs = sorted(
            n for n in names
            if hasattr(obj, n)
            and getattr(getattr(obj, n), "__excluded__", None))
        total += len(names)
        covered += len(names) - len(missing)
        raising += len(stubs)
        rows.append((label, len(names), len(missing), len(stubs),
                     ", ".join(missing) or "—"))

    # Tensor methods
    tree = ast.parse(open(R + "tensor/__init__.py").read())
    tnames = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in (
                        "tensor_method_func", "magic_method_func"):
                    for e in node.value.elts:
                        try:
                            v = ast.literal_eval(e)
                            if isinstance(v, str):
                                tnames.append(v)
                        except Exception:  # noqa: BLE001
                            pass
    import numpy as np

    t = pt.to_tensor(np.ones((2, 2), np.float32))
    tmiss = sorted(n for n in set(tnames) if not hasattr(t, n))
    total += len(set(tnames))
    covered += len(set(tnames)) - len(tmiss)
    rows.append(("paddle.Tensor (methods)", len(set(tnames)), len(tmiss),
                 0, ", ".join(tmiss) or "—"))

    working = covered - raising
    out = ["# API_PARITY — reference `__all__` sweep",
           "",
           "Generated by `tools/gen_api_parity.py` against the reference "
           "checkout; `tests/test_api_surface.py` enforces the same sweep "
           "in CI.",
           "",
           f"**Coverage: {covered}/{total} public names resolve "
           f"({covered / max(total, 1):.1%}); of those, {raising} are "
           f"documented-exclusion stubs that raise with rationale on use "
           f"(PS/RPC/IPU — README 'Scope'), leaving {working} working "
           f"names ({working / max(total, 1):.1%}).** The "
           "`resolves-but-raises` column separates working surface from "
           "stub surface per namespace.",
           "",
           "| namespace | names | missing | resolves-but-raises | which missing |",
           "|---|---|---|---|---|"]
    for label, n, m, rb, which in rows:
        out.append(f"| {label} | {n} | {m} | {rb} | {which} |")
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "API_PARITY.md"),
            "w") as f:
        f.write("\n".join(out) + "\n")
    print(f"docs/API_PARITY.md: {covered}/{total} "
          f"({covered / max(total, 1):.1%})")


if __name__ == "__main__":
    main()
