#!/usr/bin/env python
"""Automatic sharding planner driver: plan → launch → resume hybrid
runs with zero hand-written PartitionSpecs (docs/AUTOSHARD.md).

    python tools/shard_plan.py plan --devices 8 --hbm-gb 16
    python tools/shard_plan.py plan --smoke          # tier-1 CPU proof
    python tools/shard_plan.py launch --plan shard_plan.json train.py args
    python tools/shard_plan.py resume --devices 2 --configs dp1xmp2 \
        --from ckpt_dir train.py args
    python tools/shard_plan.py bench                 # hwbench row

``plan`` enumerates every legal (dp × mp × pp, batch) candidate for
the device count (pipeline depth capped by the probe's stage-able
layer count and ``PT_AUTOSHARD_PP_MAX``), AOT-lowers each on a virtual
mesh (pp>1 candidates compile the GPipe-in-XLA PipelineLayer schedule;
no execution; with ``PT_EXEC_CACHE`` a repeat sweep pays ZERO fresh
XLA compiles — the JSON line's ``fresh_compiles`` proves it), applies
the HBM-fit hard constraint + the compute/comms roofline
(`paddle_tpu/autoshard/cost.py` — pipeline candidates carry the
``(pp−1)/n_micro`` bubble and the ppermute handoff wire term), and
writes the winner as a deterministic ``shard_plan.json`` — same
inputs, byte-identical file, now also recording ``pp``/``n_micro``/the
layer→stage assignment. Exit codes mirror memory_planner: 0 a winner
exists, 3 nothing fits, 2 setup error.

``launch`` starts the plan's run through `paddle_tpu.distributed.launch`
(the launcher stamps ``PT_SHARD_PLAN`` into every worker; scripts call
``autoshard.apply_plan`` and never name an axis). ``resume`` replans
(or takes ``--plan``) and relaunches with ``PT_SHARD_RESUME=<ckpt>`` so
the run continues from its newest complete checkpoint at the NEW
(dp × mp) — reshard-on-load (docs/RESILIENCE.md) does the conversion.

``bench`` is the hwbench row: a timeboxed sweep + a short measured run
of the winner (and the runner-up when one fits), persisting the
planned-vs-measured delta to PERF_MEASUREMENTS.json on hardware; CPU
runs are marked smoke and never enter the store.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD_FLAG = "_PT_SHARD_PLAN_CHILD"


def _cli():
    """`paddle_tpu.autoshard.cli` — probe args, smoke geometry, and the
    corrected-child re-exec shared with tools/memory_planner.py. Loaded
    BY FILE PATH: it is stdlib-pure, and a package import would pull
    jax into the parent process before the corrected-child re-exec."""
    import importlib.util

    path = os.path.join(ROOT, "paddle_tpu", "autoshard", "cli.py")
    spec = importlib.util.spec_from_file_location("_autoshard_cli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _add_sweep_args(ap) -> None:
    ap.add_argument("--devices", type=int, default=8,
                    help="mesh size; a virtual CPU mesh of this many "
                         "devices is forced (default 8)")
    ap.add_argument("--hbm-gb", type=float, default=16.0,
                    help="per-device HBM budget in GiB (default 16 — one "
                         "v5e chip)")
    ap.add_argument("--configs", default=None,
                    help="comma list of mesh splits, e.g. "
                         "'dp8,dp4xmp2,dp2xpp2' (default: all power-of-2 "
                         "dp×mp×pp factorizations of --devices, pp capped "
                         "by the probe's --layers and PT_AUTOSHARD_PP_MAX)")
    ap.add_argument("--batches", default="8",
                    help="comma list of global batch sizes (default 8)")
    ap.add_argument("--out", default="shard_plan.json",
                    help="plan output path (default ./shard_plan.json)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny probe + 4 mesh candidates incl. a pp2 "
                         "pipeline (the tier-1 CPU pipeline proof, "
                         "kernel-search convention)")
    ap.add_argument("--exec-cache", default=None, metavar="DIR",
                    help="AOT executable cache dir for the candidate "
                         "compiles (default: inherit PT_EXEC_CACHE) — a "
                         "repeated sweep then pays zero fresh XLA compiles")
    _cli().add_probe_args(ap)


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="shard_plan",
        description="Plan, launch and resume hybrid (dp×mp) runs with "
                    "no hand-written PartitionSpecs.")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("plan", help="sweep candidates, emit shard_plan.json")
    _add_sweep_args(p)

    l = sub.add_parser("launch", help="launch a planned run")
    l.add_argument("--plan", default="shard_plan.json")
    l.add_argument("--log-dir", default="log")
    l.add_argument("--max-restart", type=int, default=3)
    l.add_argument("--nproc", type=int, default=1,
                   help="processes per host (SPMD default 1)")
    l.add_argument("script")
    l.add_argument("script_args", nargs=argparse.REMAINDER)

    r = sub.add_parser(
        "resume", help="replan for the CURRENT topology and resume a "
                       "checkpoint saved at another (dp×mp)")
    r.add_argument("--plan", default=None,
                   help="use this plan instead of replanning")
    r.add_argument("--from", dest="resume_from", required=True,
                   help="checkpoint dir of the run to resume")
    r.add_argument("--log-dir", default="log")
    r.add_argument("--max-restart", type=int, default=3)
    r.add_argument("--nproc", type=int, default=1)
    _add_sweep_args(r)
    r.add_argument("script")
    r.add_argument("script_args", nargs=argparse.REMAINDER)

    b = sub.add_parser("bench", help="hwbench row: planned vs measured")
    _add_sweep_args(b)
    b.add_argument("--steps", type=int, default=8,
                   help="measured steps per judged candidate (default 8)")
    return ap


# -- plan --------------------------------------------------------------------

def _reexec_child(args, argv, force_cpu: bool = True,
                  timeout: int = 1800) -> int:
    return _cli().reexec_virtual_child(
        __file__, "shard_plan", argv, args.devices, _CHILD_FLAG,
        exec_cache=getattr(args, "exec_cache", None), force_cpu=force_cpu,
        timeout=timeout)


def _render_rows(rows, hbm_gb: float, devices: int) -> str:
    out = [f"== shard planner: budget {hbm_gb:.2f} GiB/device, "
           f"{devices} devices =="]
    hdr = (f"{'config':<18}{'per-dev peak':>14}{'comms MiB':>11}"
           f"{'est ms':>9}{'est tok/s':>12}  verdict")
    out.append(hdr)
    out.append("-" * len(hdr))
    gib = 2**30
    for r in rows:
        if "error" in r:
            out.append(f"{r['label']:<18}{'—':>14}{'—':>11}{'—':>9}"
                       f"{'—':>12}  ERROR ({r['error'][:40]})")
            continue
        comms = (r.get("collectives") or {}).get("total_wire_bytes", 0)
        est = r.get("est_step_ms")
        tps = r.get("est_tokens_per_sec")
        out.append(
            f"{r['label']:<18}"
            f"{r['peak_bytes'] / gib:>11.3f} GiB"
            f"{comms / 2**20:>11.2f}"
            f"{est if est is not None else '—':>9}"
            f"{tps if tps is not None else '—':>12}"
            f"  {'FITS' if r.get('fits') else 'DOES NOT FIT'}")
    return "\n".join(out)


def cmd_plan(args, argv) -> int:
    if args.smoke:
        _cli().apply_smoke(args)
    args.out = os.path.abspath(args.out)
    if os.environ.get(_CHILD_FLAG) != "1":
        # the child runs with cwd=ROOT — pin the out path to the
        # INVOKING directory before re-exec (argparse last-wins)
        return _reexec_child(args, list(argv) + ["--out", args.out])

    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < args.devices:
        print(f"shard_plan: need {args.devices} devices, have "
              f"{len(jax.devices())}", file=sys.stderr)
        return 2
    sys.path.insert(0, ROOT)
    from paddle_tpu import autoshard
    from paddle_tpu.jit import exec_cache

    spec = autoshard.ProbeSpec(
        vocab=args.vocab, hidden=args.hidden,
        intermediate=args.intermediate, layers=args.layers,
        heads=args.heads, seq=args.seq,
        moe_experts=getattr(args, "moe_experts", 0) or 0)
    try:
        plan, rows = autoshard.make_plan(
            args.devices, args.hbm_gb, spec=spec,
            configs=args.configs, batches=args.batches)
    except ValueError as e:
        print(f"shard_plan: {e}", file=sys.stderr)
        return 2
    print(_render_rows(rows, args.hbm_gb, args.devices), flush=True)
    stats = exec_cache.stats() if exec_cache.enabled() else None
    line = {"shard_plan": {
        "devices": args.devices, "hbm_gb": args.hbm_gb,
        "candidates": len(rows),
        "feasible": sum(1 for r in rows if r.get("fits")),
        # the exec-cache-warm acceptance number: misses == fresh XLA
        # compiles this sweep paid (0 on a warm repeat)
        "fresh_compiles": stats["misses"] if stats else None,
        "exec_cache": bool(stats),
    }}
    if plan is None:
        print("shard_plan: no candidate fits the HBM budget — not "
              "emitting a plan", flush=True)
        print(json.dumps(line), flush=True)
        return 3
    plan.save(args.out)
    line["shard_plan"].update(plan.summary())
    line["shard_plan"]["out"] = args.out
    print(f"winner: {plan.winner} -> {args.out} "
          f"(digest {plan.digest()})", flush=True)
    if stats is not None:
        print(f"exec cache: {stats['disk_hits']} disk hit(s), "
              f"{stats['mem_hits']} mem hit(s), {stats['misses']} "
              f"miss(es) ({stats['dir']})", flush=True)
    print(json.dumps(line), flush=True)
    return 0


# -- launch / resume ---------------------------------------------------------

def _launch(plan_path: str, args, resume_from: str | None = None) -> int:
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--shard_plan", os.path.abspath(plan_path),
           "--log_dir", args.log_dir,
           "--max_restart", str(args.max_restart),
           "--nproc_per_node", str(args.nproc),
           args.script] + list(args.script_args)
    env = dict(os.environ)
    if resume_from is not None:
        env["PT_SHARD_RESUME"] = os.path.abspath(resume_from)
    print("shard_plan: exec " + " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=os.getcwd())


def cmd_launch(args) -> int:
    if not os.path.exists(args.plan):
        print(f"shard_plan: no plan at {args.plan!r} — run "
              f"`shard_plan.py plan` first", file=sys.stderr)
        return 2
    return _launch(args.plan, args)


def cmd_resume(args, argv) -> int:
    args.out = os.path.abspath(args.out)
    plan_path = args.plan
    if plan_path is None:
        # replan for the topology we are resuming INTO; the checkpoint
        # reshards on load, so the saved (dp×mp) does not constrain it
        plan_path = args.out
        plan_argv = ["plan"] + _sweep_argv(args)
        rc = main(plan_argv)
        if rc != 0:
            return rc
    if not os.path.exists(plan_path):
        print(f"shard_plan: no plan at {plan_path!r}", file=sys.stderr)
        return 2
    return _launch(plan_path, args, resume_from=args.resume_from)


def _sweep_argv(args) -> list:
    out = ["--devices", str(args.devices), "--hbm-gb", str(args.hbm_gb),
           "--batches", str(args.batches), "--out", args.out,
           "--hidden", str(args.hidden), "--layers", str(args.layers),
           "--heads", str(args.heads), "--seq", str(args.seq),
           "--vocab", str(args.vocab),
           "--intermediate", str(args.intermediate)]
    if args.configs:
        out += ["--configs", args.configs]
    if args.smoke:
        out += ["--smoke"]
    if getattr(args, "exec_cache", None):
        out += ["--exec-cache", args.exec_cache]
    return out


# -- bench (the hwbench row) -------------------------------------------------

def cmd_bench(args, argv) -> int:
    """Plan on the virtual mesh, then measure the winner (and the
    runner-up when one fits) for a few real steps — the planned-vs-
    measured delta is the number that calibrates the cost model."""
    if args.smoke:
        _cli().apply_smoke(args)
    if os.environ.get(_CHILD_FLAG) != "1":
        # measure on the real backend when the tunnel is up; otherwise
        # the CPU smoke (marked, never a baseline)
        sys.path.insert(0, ROOT)
        try:
            from bench import _probe_backend

            backend = _probe_backend()
        except Exception:  # noqa: BLE001 — dead tunnel = cpu smoke
            backend = "cpu"
        # inside hwbench's 2400 s row timebox, with headroom for the
        # parent's probe + teardown
        return _reexec_child(args, argv, force_cpu=backend != "tpu",
                             timeout=2100)

    import jax

    sys.path.insert(0, ROOT)
    from paddle_tpu import autoshard

    backend = jax.default_backend()
    if backend != "cpu":
        args.devices = len(jax.devices())
    spec = autoshard.ProbeSpec(
        vocab=args.vocab, hidden=args.hidden,
        intermediate=args.intermediate, layers=args.layers,
        heads=args.heads, seq=args.seq,
        moe_experts=getattr(args, "moe_experts", 0) or 0)
    plan, rows = autoshard.make_plan(
        args.devices, args.hbm_gb, spec=spec,
        configs=args.configs, batches=args.batches)
    if plan is None:
        print(json.dumps({"metric": "shard_plan_planned_vs_measured",
                          "value": 0.0, "error": "no feasible candidate"}),
              flush=True)
        return 3
    ranked = autoshard.rank_candidates(rows)
    judged = []
    for row in ranked[:2]:
        cand = {"dp": row["dp"], "mp": row["mp"], "batch": row["batch"]}
        measured = _measure_candidate(cand, spec, steps=args.steps)
        judged.append({**cand, "label": row["label"],
                       "est_tokens_per_sec": row.get("est_tokens_per_sec"),
                       "measured_tokens_per_sec": measured})
    winner = judged[0]
    planned_first = (len(judged) < 2
                     or (winner["measured_tokens_per_sec"] or 0)
                     >= (judged[1]["measured_tokens_per_sec"] or 0))
    line = {
        "metric": "shard_plan_planned_vs_measured",
        "value": winner["measured_tokens_per_sec"],
        "unit": "tokens/s",
        "devices": args.devices,
        "shard_plan": plan.summary(),
        "judged": judged,
        "planned_winner_measured_best": bool(planned_first),
    }
    if backend == "cpu":
        # smoke runs never enter the store — PERF_MEASUREMENTS.json is
        # the hardware record (serving_bench convention)
        line["note"] = "cpu smoke mode; not a TPU number"
    else:
        try:
            from paddle_tpu.utils import measurements as _meas

            _meas.record_rec_or_warn(dict(line), backend=backend)
        except Exception as e:  # noqa: BLE001 — persistence is
            # best-effort after a successful measurement
            print(f"shard_plan: persist failed: {e}", file=sys.stderr)
    print(json.dumps(line), flush=True)
    return 0


def _measure_candidate(cand: dict, spec, steps: int = 8) -> float | None:
    """Short measured run of one candidate on the live backend: tokens/s
    over ``steps`` timed steps (1 warmup), honest through the tunnel
    (device_sync fences — CLAUDE.md timing rules). The probe comes from
    the SAME builder the planning sweep lowered (`autoshard.build_probe`
    — dp-sharded batch included), so the measured program is the one
    the plan's memory/comms account described."""
    from paddle_tpu.autoshard import build_probe
    from paddle_tpu.distributed import env as env_mod
    from paddle_tpu.utils.timing import device_sync

    try:
        try:
            step, ids, _model = build_probe(cand, spec)
            loss = step(ids, ids)  # warmup: trace+compile
            device_sync(loss._data)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = step(ids, ids)
            device_sync(loss._data)
            dt = time.perf_counter() - t0
            return round(cand["batch"] * spec.seq * steps / dt, 2)
        finally:
            env_mod.reset_env()
    except Exception as e:  # noqa: BLE001 — one candidate's failure must
        # not kill the row; the delta is simply not judged for it
        print(f"shard_plan: measure failed for {cand}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    args = build_argparser().parse_args(argv)
    if args.cmd == "plan":
        return cmd_plan(args, argv)
    if args.cmd == "launch":
        return cmd_launch(args)
    if args.cmd == "resume":
        return cmd_resume(args, argv)
    if args.cmd == "bench":
        return cmd_bench(args, argv)
    return 2


if __name__ == "__main__":
    sys.exit(main())
