#!/usr/bin/env python
"""Run the kernel search harness (ops/pallas/search.py) on the live backend.

    python tools/kernel_search.py [--families a,b] [--iters 20]
    python tools/kernel_search.py --smoke          # CPU pipeline proof

Hardware run: for every registered family (flash blocks, head-batched
flash, paged attention — default: all), enumerate the candidate space,
interpret-parity-filter every candidate, time the survivors with the
two-fori-loop discipline, and persist the best row (device + commit
provenance) to ``paddle_tpu/ops/pallas/kernel_tune.json``. Engagement
flips happen ONLY through those rows (measured-faster-than-composite);
a summary metric lands in PERF_MEASUREMENTS.json. Run whenever a chip
is reachable (hwbench ``kernel_search`` stage).

``--smoke`` proves the full pipeline (enumerate -> parity filter ->
timing path) on CPU in interpret mode at tiny shapes: rows go to a
TEMPORARY table (unless --table/PT_KERNEL_TUNE_PATH overrides) and are
stamped backend=cpu/interpret=true, which ``search.engaged`` refuses —
a smoke run can never flip an engagement. Tier-1 runs it
(tests/test_kernel_search.py).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CPU interpret-mode pipeline proof at tiny "
                         "shapes; never produces engagement rows")
    ap.add_argument("--families", default=None,
                    help="comma-separated family names (default: all "
                         "registered)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--table", default=None,
                    help="tune-table path override (also "
                         "PT_KERNEL_TUNE_PATH)")
    args = ap.parse_args()

    if args.smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.table:
        os.environ["PT_KERNEL_TUNE_PATH"] = args.table
    elif args.smoke and not os.environ.get("PT_KERNEL_TUNE_PATH"):
        # a smoke run must not dirty the committed table
        os.environ["PT_KERNEL_TUNE_PATH"] = os.path.join(
            tempfile.mkdtemp(prefix="kernel_search_smoke_"),
            "kernel_tune.json")

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import enable_compilation_cache

    enable_compilation_cache()
    backend = jax.default_backend()
    smoke = args.smoke or backend == "cpu"
    print(f"kernel_search: backend={backend} smoke={smoke}",
          file=sys.stderr, flush=True)
    if backend == "cpu" and not args.smoke:
        print("kernel_search: no TPU — wall-clock search on CPU is "
              "meaningless; run with --smoke for the pipeline proof",
              file=sys.stderr, flush=True)
        return 1

    import paddle_tpu.ops.pallas  # noqa: F401 — registers the families
    from paddle_tpu.ops.pallas import search

    if args.families:
        names = args.families.split(",")
    elif smoke:
        names = sorted(search.FAMILIES)
    else:
        # hardware default: the families with NO rows yet. The flash
        # family's block search is already served by the (earlier)
        # hwbench flashtune stage — re-searching it here would spend
        # the timebox twice; pass --families flash to force it.
        names = [n for n in sorted(search.FAMILIES) if n != "flash"]
    iters = 2 if smoke else args.iters
    entries = []
    failures = []
    for name in names:
        fam = search.FAMILIES.get(name)
        if fam is None:
            print(f"kernel_search: unknown family {name!r} (have "
                  f"{sorted(search.FAMILIES)})", file=sys.stderr,
                  flush=True)
            return 2
        try:
            entries.extend(search.search_family(fam, iters=iters,
                                                smoke=smoke))
        except Exception as e:  # noqa: BLE001 — one family must not
            failures.append((name, f"{type(e).__name__}: {e}"))
            print(f"kernel_search: family {name} failed: {e}",
                  file=sys.stderr, flush=True)  # cost the others

    engaged = [e for e in entries if e.get("ratio", 0) > 1.0]
    rec = {
        "metric": "kernel_search_shapes",
        "value": float(len(entries)),
        "unit": "shapes",
        "families": names,
        "engaged_shapes": len(engaged),
        "rows": {f"{e['family']}:{e['key']}": e.get("ratio")
                 for e in entries},
        "table": search.table_path(),
        "failures": dict(failures),
    }
    if smoke:
        rec["note"] = "cpu smoke mode; not a TPU number"
    else:
        from paddle_tpu.utils import measurements as _meas

        _meas.record_rec_or_warn(rec)
    print(json.dumps(rec), flush=True)
    if not entries:
        return 3  # nothing searched — retryable
    return 1 if failures else 0  # partial rows persisted either way


if __name__ == "__main__":
    sys.exit(main())
