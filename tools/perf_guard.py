#!/usr/bin/env python
"""Perf regression guard: fresh bench line vs the last-good hardware record.

    python tools/perf_guard.py fresh.json [--store PERF_MEASUREMENTS.json]
    some_bench | tail -1 | python tools/perf_guard.py -

``fresh.json`` holds a bench's one-line JSON (the last parseable object
with a ``metric`` key wins, so a whole bench log can be piped in; ``-``
reads stdin). The guard compares it against the most recent real-hardware
record for the same metric in the measurement store
(``PERF_MEASUREMENTS.json`` — see ``paddle_tpu/utils/measurements.py``)
and exits nonzero with a human-readable verdict when the run regressed:

- throughput below last-good by more than ``--throughput-drop`` (10%)
- MFU below last-good by more than ``--mfu-drop`` (10%)
- peak HBM above last-good by more than ``--hbm-growth`` (10%): the step
  got hungrier — the config that fit yesterday may OOM tomorrow
  (``peak_hbm_gib`` from the line or its ``memory`` sub-object, vs the
  baseline record's ``extra.peak_hbm_gib``)
- cold-start compile wall-time above last-good by more than
  ``--compile-growth`` (50%) **and** ``--compile-slack-ms`` (2000 ms)
  absolute: ``telemetry.compile_ms_total`` vs the baseline record's
  ``extra.compile_ms_total``. This is the executable-cache regression
  gate (``jit/exec_cache.py``): a warm ``PT_EXEC_CACHE`` run pays ~0
  compile ms, so a cache that stops hitting (key churn, serialization
  break) fails the bench the same way a throughput drop does; the
  absolute slack keeps sub-second compile noise from tripping it
- serving p99 time-to-first-token above last-good by more than
  ``--ttft-growth`` (25%): ``ttft_ms_p99`` from a
  ``benchmarks/serving_bench.py`` line vs the baseline record's
  ``extra.ttft_ms_p99`` — the tail-latency gate; the aggregate tokens/s
  drop is the same ``--throughput-drop`` check every metric gets
- serving ``prefix_hit_rate`` below last-good by more than
  ``--prefix-hit-drop`` (25%): the shared-prompt trace stopped sharing
  KV blocks (chain-key churn or a publish regression in
  ``serving/kv_cache.py``'s prefix index) — the cached-TTFT win
  evaporated even when this run's tail happens to pass. Skipped when
  either side lacks the field or the baseline rate is 0
- serving speculative ``accept_rate`` below last-good by more than
  ``--accept-drop`` (25%): the drafter stopped matching the workload
  (``serving/speculative.py`` regression or a verify-step acceptance
  bug) — the tokens-per-decode-step multiplier evaporated. Spec-off
  lines never carry the field, so they skip; ``spec``/``spec_k`` are
  sweep-config keys, so spec and plain serving rows never cross-judge
- serving router ``affinity_hit_rate`` below last-good by more than
  ``--affinity-drop`` (25%): the multi-replica router stopped routing
  same-prefix requests to the replica that already holds their KV
  blocks (affinity-index churn or a dispatch regression in
  ``serving/router.py``) — every replica re-prefills the shared prompt
  and the scale-out win evaporated. Single-engine lines never carry
  the field, so they skip; ``replicas`` is a sweep-config key, so
  routed and single-engine rows never cross-judge
- ``goodput_frac`` below last-good by more than ``--goodput-drop``
  (10%): the run's goodput ledger (``monitor/goodput.py`` — the
  wall-clock share spent in ``productive_step``; ``bench.py`` and
  ``tools/soak.py`` lines carry it) says the same workload now burns
  its wall somewhere unproductive — compile storm, checkpoint stalls,
  or input waits; the line's ``goodput`` buckets name which. Skipped
  when either side lacks the field or the baseline is 0
- a changed sharding plan (``--plan-drift``): a fresh hardware line
  whose ``shard_plan`` sub-object (from ``tools/shard_plan.py``) names
  a different (dp, mp, pp, batch) than the last-good record's
  ``extra.shard_plan`` for the SAME device count (pre-PP records read
  as pp=1 baselines) — a silently-changed
  cost model must not flip production sharding without a human reading
  this verdict. Missing baselines, missing plan fields, other
  topologies, and CPU smokes skip the check
- a fresh SLO breach (``--slo-breach``): a fresh hardware line whose
  ``slo`` sub-object (``serving_bench`` with the live telemetry plane
  armed — docs/OBSERVABILITY.md) reports ``breaches > 0`` when the
  last-good record's ``extra.slo`` had zero — the burn-rate watchdog
  fired on a trace that used to meet its ``PT_SLO_*`` targets. The
  target values are sweep-config keys, so lines judged against
  different targets never cross-compare; lines or baselines without
  the sub-object (live plane off, pre-SLO records) skip, CPU smokes
  skip with the rest
- a new compiled-program audit finding (``--audit``): a fresh hardware
  line whose ``program_audit`` sub-object (``analysis/program_audit.py``,
  armed by ``PT_PROGRAM_AUDIT=1``) reports a (rule, label) finding
  absent from the last-good record's ``extra.program_audit`` —
  replicated-dp compute, dropped donation, host callbacks, or retrace
  churn appeared since the baseline. Lines or baselines without the
  sub-object skip the check; CPU smokes skip with the rest of the
  hardware comparisons
- a Pallas kernel family engaged in the last-good record but running on
  the composite in the fresh line (``kernels`` sub-object — the
  ``{family: engaged}`` map benches embed from
  ``ops.pallas.search.engagement_report``): a lost engagement means the
  tune table stopped matching (device change, key churn, a deleted
  row) and the measured win silently evaporated. Families absent from
  the fresh line are wildcards; CPU smokes skip the check
- any post-warmup retrace (``telemetry.post_warmup_retraces`` > 0): a
  shape changed inside the timed loop, so the number includes an XLA
  compile and the next run won't reproduce it
- prefetch starvation rate above ``--max-starvation-rate``: the loader,
  not the device, bounded the measurement
- a zero/absent value or an embedded ``error`` field (the bench died)

CPU smoke lines (dead tunnel) skip the hardware comparisons — a laptop
number vs a TPU record is not a regression — but still fail on retrace
storms and errors. ``bench.py`` embeds this module's verdict in its JSON
line (``"guard"`` sub-object) and ``tools/hwbench.py`` prints it per
bench, so a silent regression can't land in the measurement store
unnoticed.

Pure stdlib: runs anywhere the artifacts land, no jax import.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_THRESHOLDS = {
    # fractional drop vs last-good before the check fails
    "throughput_drop": 0.10,
    "mfu_drop": 0.10,
    # any retrace after warmup is a storm: the timed loop recompiled
    "max_post_warmup_retraces": 0,
    # starvations per timed step before the run counts as input-bound
    "max_starvation_rate": 0.25,
    # fractional peak-HBM growth vs last-good before the check fails
    "hbm_growth": 0.10,
    # cold-start compile wall-time vs last-good: fails only past BOTH the
    # fractional growth and the absolute slack (compile time is noisy at
    # small absolute values; a lost exec-cache warm start is neither)
    "compile_growth": 0.50,
    "compile_slack_ms": 2000.0,
    # serving gate: fractional p99 time-to-first-token growth vs the
    # last-good record before the check fails (serving_bench lines carry
    # ttft_ms_p99; the aggregate tokens/s drop rides the generic
    # throughput check — the metric's value IS tokens/s)
    "ttft_growth": 0.25,
    # request-attribution gate: fractional growth of serving_bench's
    # attribution.queue_share (mean queue-wait fraction of end-to-end
    # request latency) vs the last-good record before the check fails —
    # a grown queue share means requests wait longer for lanes at the
    # SAME workload (scheduler regression, slower prefill backing up
    # admissions, or shrunk effective pool). Only fails past BOTH the
    # fractional growth and the absolute slack (tiny shares are noisy:
    # 0.01 → 0.02 is not a regression); skips when either side lacks
    # the attribution sub-object or the baseline share is 0
    "queue_share_growth": 0.25,
    "queue_share_slack": 0.05,
    # prefix-cache gate: fractional drop of serving_bench's
    # prefix_hit_rate vs the last-good record before the check fails —
    # a collapsed hit rate means the shared-prompt workload stopped
    # sharing (chain-key churn, publish regression, or cold-LRU
    # thrash) and the TTFT win silently evaporated. Skips when either
    # side lacks the field or the baseline rate is 0 (a trace with no
    # shared prefix pins nothing), and on CPU smokes with the rest
    "prefix_hit_drop": 0.25,
    # speculative-decoding gate: fractional drop of serving_bench's
    # accept_rate (accepted/proposed draft tokens) vs the last-good
    # record before the check fails — a collapsed accept rate means the
    # drafter stopped matching the workload (drafter regression, trace
    # change, or a verify-step acceptance bug) and the
    # tokens-per-decode-step win silently evaporated. Skips when either
    # side lacks the field (spec-off lines never carry it) or the
    # baseline rate is 0, and on CPU smokes with the rest
    "accept_drop": 0.25,
    # replica-router gate: fractional drop of serving_bench's
    # affinity_hit_rate (router dispatches that landed on a replica
    # already holding the prompt's prefix blocks) vs the last-good
    # record before the check fails — a collapsed hit rate means every
    # replica re-prefills the shared prompt (affinity-index churn or a
    # dispatch regression) and the multi-replica TTFT win silently
    # evaporated. Skips when either side lacks the field (single-engine
    # lines never carry it) or the baseline rate is 0, and on CPU
    # smokes with the rest
    "affinity_drop": 0.25,
    # resilience gate: fractional growth of the blocking checkpoint-save
    # cost (tools/soak.py lines carry ckpt_save_ms_p50 — the quiesce +
    # host-snapshot time the cadence planner budgets against) vs the
    # last-good record, past an absolute slack (small-model saves are
    # noisy at single-digit ms)
    "save_cost_growth": 0.50,
    "save_cost_slack_ms": 250.0,
    # goodput gate (--goodput-drop): fractional drop of the line's
    # goodput_frac (wall-clock share spent in productive_step — the
    # run's goodput ledger, monitor/goodput.py; bench.py and
    # tools/soak.py lines carry it) vs the last-good record before the
    # check fails — a collapsed goodput fraction means the same
    # workload now burns its wall somewhere unproductive (compile
    # storm, checkpoint stalls, input waits). Skips when either side
    # lacks the field or the baseline is 0, and on CPU smokes with
    # the rest
    "goodput_drop": 0.10,
    # sharding-plan drift gate: on by default; --no-plan-drift disables
    "plan_drift": True,
    # program-audit gate (--audit / --no-audit): a fresh hardware line
    # whose program_audit sub-object (analysis/program_audit.py,
    # PT_PROGRAM_AUDIT=1) reports findings ABSENT from the last-good
    # record fails — a compiled-invariant break (replicated dp, dropped
    # donation, host callbacks, retrace churn) must not land silently.
    # CPU smokes and baselines without the sub-object skip, matching the
    # --ttft-growth convention
    "audit": True,
    # SLO-breach gate (--slo-breach / --no-slo-breach): a fresh
    # hardware line whose slo sub-object (serving_bench with the live
    # plane armed) counts breaches > 0 fails when the last-good record
    # breached zero times at the SAME PT_SLO_* targets — a latency
    # regression crossed the burn-rate watchdog's line, not just a
    # percentile wiggle. Both-sides-have-the-sub-object required;
    # baselines that already breached ride forward (fixing the SLO is
    # a separate act from regressing it)
    "slo_breach": True,
}


def peak_hbm_of(line: dict) -> float | None:
    """``peak_hbm_gib`` from a bench line (top level or the ``memory``
    sub-object) — the one accessor both the gate and the report use."""
    v = line.get("peak_hbm_gib")
    if v is None:
        v = (line.get("memory") or {}).get("peak_hbm_gib")
    return v


def _default_store() -> str:
    override = os.environ.get("PT_MEASUREMENTS_PATH")
    if override:
        return override
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(here), "PERF_MEASUREMENTS.json")


def find_bench_line(text: str) -> dict | None:
    """Last parseable JSON object with a ``metric`` key in ``text`` —
    tolerates a bench's full stdout log. The ONE scanner for bench lines
    (the CLI and tools/hwbench.py both call it, so the format can't
    drift between them)."""
    found = None
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            obj = json.loads(raw)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            found = obj
    return found


def load_fresh(path: str) -> dict:
    """:func:`find_bench_line` over a file (``-`` = stdin)."""
    text = sys.stdin.read() if path == "-" else open(path).read()
    found = find_bench_line(text)
    if found is None:
        raise ValueError(f"no bench JSON line (object with a 'metric' "
                         f"key) found in {path!r}")
    return found


# sweep knobs that change what the number measures: a baseline is only
# comparable at the same config (CLAUDE.md PT_BENCH_BATCH / ce-chunk A/Bs
# persist under the SAME metric name). The serving keys pin the bench's
# offered load + engine geometry (int8_weights also rides decode_bench
# lines): a 64-request trace legitimately queues deeper than a
# 32-request one, so judging p99 TTFT across them would false-fail (or
# mask) the gate. Keys a baseline record predates are wildcards — see
# last_good.
CONFIG_KEYS = ("batch", "seq", "ce_chunk",
               "requests", "arrival_rate_per_s", "lanes", "block_size",
               "int8_weights", "kv_int8", "devices", "pp",
               "shared_prefix_tokens", "prefix_cache", "spec", "spec_k",
               "replicas", "slo_ttft_ms_p99", "slo_tpot_ms_p99")

# keys whose ABSENCE from an old record means the knob's default, not a
# wildcard: records persisted before the prefix cache existed WERE
# shared=0 / cache-on runs, so a fresh shared-prefix line must not
# judge itself against them (a 64-token-longer-prompt workload), while
# a fresh plain line keeps its pre-PR baselines. Likewise records from
# before speculative decoding were plain-decode (spec-off) runs: a
# fresh spec-on line gets no pre-spec baseline, a fresh spec-off line
# keeps its history
# ... and pp: records persisted before the planner's pipeline axis
# existed WERE pp=1 runs, so a fresh pp>1 row never judges itself
# against them while pp=1 rows keep their pre-PP baselines
# ... and replicas: records persisted before the multi-replica router
# existed WERE single-engine (replicas=1) runs, so a fresh routed row
# never judges itself against them while single-engine rows keep their
# pre-router baselines
# ... and kv_int8: records persisted before the int8 KV pool existed
# WERE bf16-pool runs — an int8 line reads half the KV bytes per
# decode step, so letting it judge (or be judged by) a bf16 baseline
# would cross-compare different byte models
CONFIG_KEY_DEFAULTS = {"shared_prefix_tokens": 0, "prefix_cache": True,
                       "spec": False, "spec_k": 0, "pp": 1,
                       "replicas": 1, "kv_int8": False,
                       # absent = no SLO target armed (pre-live-plane
                       # records and target-off runs are the same config)
                       "slo_ttft_ms_p99": None, "slo_tpot_ms_p99": None}


def config_match(fresh: dict) -> dict:
    """The sweep-config filter a fresh line implies: ``{key: value}`` for
    every :data:`CONFIG_KEYS` entry the line carries."""
    return {k: fresh[k] for k in CONFIG_KEYS if k in fresh}


def last_good(store_path: str, metric: str, fresh: dict | None = None,
              match: dict | None = None) -> dict | None:
    """Most recent real-hardware record for ``metric`` — the stdlib twin
    of ``utils/measurements.last_good`` (this tool must run with no
    package import, e.g. on a box that only has the artifacts).

    Benches persist their number BEFORE the guard runs (a dying tunnel
    must not erase the measurement), so when judging a line that may
    already be in the store pass it as ``fresh``: the newest records
    whose value matches it are skipped — comparing a run to itself would
    make the gate always-pass. ``match`` filters on the record's
    ``extra`` fields (e.g. ``{"batch": 8, "seq": 1024}``) so A/B sweep
    points at other configs are skipped instead of becoming a false
    baseline."""
    try:
        with open(store_path) as f:
            data = json.load(f)
        records = data.get("records", [])
    except (OSError, ValueError):
        return None
    skipping_self = fresh is not None
    for rec in reversed(records):
        if not (isinstance(rec, dict) and rec.get("metric") == metric
                and rec.get("backend") not in (None, "cpu", "unknown")):
            continue
        ex = rec.get("extra") or {}
        # a key ABSENT from a record's extra is a wildcard, not a
        # mismatch: records persisted before a config knob existed
        # (e.g. pre-serving decode lines without int8_weights) must
        # stay eligible baselines for the gates they anchored —
        # except CONFIG_KEY_DEFAULTS keys, where absence means the
        # knob's default value (pre-knob behavior)
        if match and any(
                (ex[k] if k in ex else CONFIG_KEY_DEFAULTS.get(k, v))
                != v for k, v in match.items()):
            continue
        if skipping_self and rec.get("value") == fresh.get("value"):
            continue
        # past the newest self-matching records, stop skipping: an older
        # record that happens to share the value is a real baseline
        skipping_self = False
        return rec
    return None


def _is_cpu_smoke(fresh: dict) -> bool:
    note = str(fresh.get("note", ""))
    return ("cpu smoke" in note or "tpu unavailable" in note
            or "last_good_tpu" in fresh)


def evaluate(fresh: dict, baseline: dict | None, thresholds: dict | None
             = None, hardware: bool | None = None) -> dict:
    """Check a fresh bench line; returns the verdict dict.

    ``hardware=False`` (default: inferred from the line's CPU-smoke
    markers) skips the throughput/MFU comparison — the runtime-health
    checks (error, retrace storm, starvation) always apply.
    """
    th = dict(DEFAULT_THRESHOLDS)
    if thresholds:
        th.update(thresholds)
    if hardware is None:
        hardware = not _is_cpu_smoke(fresh)
    checks = []

    def check(name, ok, detail):
        checks.append({"name": name, "ok": bool(ok), "detail": detail})

    err = fresh.get("error")
    value = fresh.get("value") or 0.0
    check("emitted", err is None and value > 0,
          f"error: {err}" if err is not None else f"value {value}")

    tel = fresh.get("telemetry") or {}
    pwr = tel.get("post_warmup_retraces")
    if pwr is not None:
        check("retraces", pwr <= th["max_post_warmup_retraces"],
              f"{pwr} post-warmup retrace(s)" + (
                  " — the timed loop recompiled (shape churn); the "
                  "number includes an XLA compile" if pwr else ""))
    starved = tel.get("prefetch_starvations")
    steps = tel.get("steps")
    if starved is not None and steps:
        rate = starved / steps
        check("starvation", rate <= th["max_starvation_rate"],
              f"{starved} starvation(s) / {steps} steps = {rate:.2f} "
              f"(max {th['max_starvation_rate']})")

    compared = False
    if hardware and baseline is not None and baseline.get("value"):
        compared = True
        base_v = baseline["value"]
        drop = 1.0 - value / base_v
        check("throughput", drop <= th["throughput_drop"],
              f"{value:.2f} vs last-good {base_v:.2f} "
              f"({'-' if drop > 0 else '+'}{abs(drop) * 100:.1f}%, "
              f"max drop {th['throughput_drop'] * 100:.0f}%)")
        mfu = fresh.get("mfu")
        base_mfu = (baseline.get("extra") or {}).get("mfu")
        if mfu and base_mfu:
            mdrop = 1.0 - mfu / base_mfu
            check("mfu", mdrop <= th["mfu_drop"],
                  f"{mfu:.4f} vs last-good {base_mfu:.4f} "
                  f"({'-' if mdrop > 0 else '+'}{abs(mdrop) * 100:.1f}%)")
        cms = tel.get("compile_ms_total")
        base_cms = (baseline.get("extra") or {}).get("compile_ms_total")
        # cache-on vs cache-off is an A/B dimension like batch size: a
        # run without PT_EXEC_CACHE judged against a warm-cache 0 ms
        # baseline would false-fail, so mismatched states skip the gate
        base_ec = (baseline.get("extra") or {}).get("exec_cache_enabled")
        if base_ec is not None and bool(base_ec) != bool(
                tel.get("exec_cache")):
            cms = None
        # presence check, not truthiness: 0.0 is the HEALTHY warm-cache
        # baseline, and the zero→huge cold start is exactly the
        # regression this gate exists to catch (growth is undefined
        # there — gate on the absolute slack alone)
        if cms is not None and base_cms is not None:
            over = cms - base_cms
            if base_cms > 0:
                growth = cms / base_cms - 1.0
                failed = (growth > th["compile_growth"]
                          and over > th["compile_slack_ms"])
                detail = (f"{cms:.0f} ms vs last-good {base_cms:.0f} ms "
                          f"({'+' if growth > 0 else '-'}"
                          f"{abs(growth) * 100:.1f}%, max growth "
                          f"{th['compile_growth'] * 100:.0f}% past "
                          f"{th['compile_slack_ms']:.0f} ms slack)")
            else:
                failed = over > th["compile_slack_ms"]
                detail = (f"{cms:.0f} ms vs last-good 0 ms (warm-cache "
                          f"baseline; max {th['compile_slack_ms']:.0f} ms "
                          f"slack)")
            check("compile_ms", not failed, detail
                  + (" — exec cache stopped saving compiles "
                     "(jit/exec_cache.py key churn or a dead disk tier?)"
                     if failed else ""))
        ttft = fresh.get("ttft_ms_p99")
        base_ttft = (baseline.get("extra") or {}).get("ttft_ms_p99")
        if ttft and base_ttft:
            tgrowth = ttft / base_ttft - 1.0
            check("ttft_p99", tgrowth <= th["ttft_growth"],
                  f"{ttft:.1f} ms vs last-good {base_ttft:.1f} ms "
                  f"({'+' if tgrowth > 0 else '-'}"
                  f"{abs(tgrowth) * 100:.1f}%, max growth "
                  f"{th['ttft_growth'] * 100:.0f}%)"
                  + (" — tail latency regressed (scheduler queueing or "
                     "prefill got slower)" if tgrowth > th["ttft_growth"]
                     else ""))
        qs = (fresh.get("attribution") or {}).get("queue_share")
        base_qs = ((baseline.get("extra") or {}).get("attribution")
                   or {}).get("queue_share")
        if qs is not None and base_qs:
            qgrowth = qs / base_qs - 1.0
            qover = qs - base_qs
            qfail = (qgrowth > th["queue_share_growth"]
                     and qover > th["queue_share_slack"])
            check("queue_share", not qfail,
                  f"queue share {qs:.3f} vs last-good {base_qs:.3f} "
                  f"({'+' if qgrowth > 0 else '-'}"
                  f"{abs(qgrowth) * 100:.1f}%, max growth "
                  f"{th['queue_share_growth'] * 100:.0f}% past "
                  f"{th['queue_share_slack']:.2f} absolute slack)"
                  + (" — requests wait longer for lanes at the same "
                     "workload (scheduler regression, slower prefill, "
                     "or a shrunk effective pool?)" if qfail else ""))
        phr = fresh.get("prefix_hit_rate")
        base_phr = (baseline.get("extra") or {}).get("prefix_hit_rate")
        if phr is not None and base_phr:
            pdrop = 1.0 - phr / base_phr
            check("prefix_hit", pdrop <= th["prefix_hit_drop"],
                  f"hit rate {phr:.3f} vs last-good {base_phr:.3f} "
                  f"({'-' if pdrop > 0 else '+'}{abs(pdrop) * 100:.1f}%,"
                  f" max drop {th['prefix_hit_drop'] * 100:.0f}%)"
                  + (" — prefix sharing collapsed (chain-key churn, a "
                     "publish regression, or cold-LRU thrash?)"
                     if pdrop > th["prefix_hit_drop"] else ""))
        ar = fresh.get("accept_rate")
        base_ar = (baseline.get("extra") or {}).get("accept_rate")
        if ar is not None and base_ar:
            adrop = 1.0 - ar / base_ar
            check("accept_rate", adrop <= th["accept_drop"],
                  f"accept rate {ar:.3f} vs last-good {base_ar:.3f} "
                  f"({'-' if adrop > 0 else '+'}{abs(adrop) * 100:.1f}%,"
                  f" max drop {th['accept_drop'] * 100:.0f}%)"
                  + (" — speculation stopped accepting (drafter "
                     "regression, workload change, or a verify-step "
                     "acceptance bug?)"
                     if adrop > th["accept_drop"] else ""))
        ahr = fresh.get("affinity_hit_rate")
        base_ahr = (baseline.get("extra") or {}).get("affinity_hit_rate")
        if ahr is not None and base_ahr:
            hdrop = 1.0 - ahr / base_ahr
            check("affinity_hit", hdrop <= th["affinity_drop"],
                  f"affinity hit rate {ahr:.3f} vs last-good "
                  f"{base_ahr:.3f} "
                  f"({'-' if hdrop > 0 else '+'}{abs(hdrop) * 100:.1f}%,"
                  f" max drop {th['affinity_drop'] * 100:.0f}%)"
                  + (" — prefix-affinity dispatch collapsed (affinity-"
                     "index churn or a router dispatch regression?)"
                     if hdrop > th["affinity_drop"] else ""))
        sms = fresh.get("ckpt_save_ms_p50")
        base_sms = (baseline.get("extra") or {}).get("ckpt_save_ms_p50")
        if sms is not None and base_sms:
            sgrowth = sms / base_sms - 1.0
            sover = sms - base_sms
            sfail = (sgrowth > th["save_cost_growth"]
                     and sover > th["save_cost_slack_ms"])
            check("ckpt_save_ms", not sfail,
                  f"{sms:.1f} ms vs last-good {base_sms:.1f} ms "
                  f"({'+' if sgrowth > 0 else '-'}"
                  f"{abs(sgrowth) * 100:.1f}%, max growth "
                  f"{th['save_cost_growth'] * 100:.0f}% past "
                  f"{th['save_cost_slack_ms']:.0f} ms slack)"
                  + (" — checkpointing got more expensive (the cadence "
                     "planner will save less often for the same "
                     "overhead budget)" if sfail else ""))
        gf = fresh.get("goodput_frac")
        base_gf = (baseline.get("extra") or {}).get("goodput_frac")
        if gf is not None and base_gf:
            gdrop = 1.0 - gf / base_gf
            check("goodput_frac", gdrop <= th["goodput_drop"],
                  f"goodput {gf:.3f} vs last-good {base_gf:.3f} "
                  f"({'-' if gdrop > 0 else '+'}{abs(gdrop) * 100:.1f}%,"
                  f" max drop {th['goodput_drop'] * 100:.0f}%)"
                  + (" — the run's wall-clock went unproductive "
                     "(compile storm, checkpoint stalls, or input "
                     "waits — read the goodput buckets in the line)"
                     if gdrop > th["goodput_drop"] else ""))
        plan = fresh.get("shard_plan")
        base_plan = (baseline.get("extra") or {}).get("shard_plan")
        if (th.get("plan_drift") and isinstance(plan, dict)
                and isinstance(base_plan, dict)
                and plan.get("devices") == base_plan.get("devices")):
            # pp default 1: records from before the planner's pipeline
            # axis existed were pp=1 plans, not wildcards
            def _axis(p, k):
                return p.get(k, 1 if k == "pp" else None)

            drift = [k for k in ("dp", "mp", "pp", "batch")
                     if _axis(plan, k) != _axis(base_plan, k)]
            check("plan_drift", not drift,
                  (f"planned dp{plan.get('dp')}×mp{plan.get('mp')}"
                   f"×pp{_axis(plan, 'pp')} "
                   f"b{plan.get('batch')} matches last-good"
                   if not drift else
                   f"plan changed for the same topology "
                   f"({plan.get('devices')} devices): "
                   + ", ".join(f"{k} {_axis(base_plan, k)}→{_axis(plan, k)}"
                               for k in drift)
                   + " — the cost model flipped production sharding; "
                     "re-measure both configs before trusting it"))
        pa = fresh.get("program_audit")
        base_pa = (baseline.get("extra") or {}).get("program_audit")
        if (th.get("audit") and isinstance(pa, dict)
                and isinstance(base_pa, dict)):
            # a finding is "new" when its (rule, label) pair is absent
            # from the last-good record — known/accepted findings ride
            # the baseline forward, fresh invariant breaks fail
            known = {(f.get("rule"), f.get("label"))
                     for f in base_pa.get("findings", [])
                     if isinstance(f, dict)}
            new = [f for f in pa.get("findings", [])
                   if isinstance(f, dict)
                   and (f.get("rule"), f.get("label")) not in known]
            check("program_audit", not new,
                  ("no new compiled-program findings "
                   f"({len(pa.get('findings', []))} total, all in "
                   "baseline)" if not new else
                   "new compiled-program finding(s) vs last-good: "
                   + "; ".join(
                       f"{f.get('rule')} {f.get('name')} "
                       f"[{f.get('label')}]" for f in new)
                   + " — a program invariant broke since the baseline "
                     "(see analysis/program_audit.py)"))
        slo = fresh.get("slo")
        base_slo = (baseline.get("extra") or {}).get("slo")
        if (th.get("slo_breach") and isinstance(slo, dict)
                and isinstance(base_slo, dict)):
            # config-key matching already pinned the PT_SLO_* targets,
            # so both sides judged the same line in the sand; a
            # baseline that already breached rides forward (fixing an
            # SLO is a separate act from regressing into one)
            breaches = int(slo.get("breaches") or 0)
            base_breaches = int(base_slo.get("breaches") or 0)
            regressed = breaches > 0 and base_breaches == 0
            check("slo_breach", not regressed,
                  (f"{breaches} breach(es), last-good had "
                   f"{base_breaches}"
                   + (" — the burn-rate watchdog fired on a trace that "
                      "used to meet its SLO targets (worst burn "
                      f"{slo.get('worst_burn')}; see "
                      "docs/OBSERVABILITY.md)" if regressed else "")))
        kern = fresh.get("kernels")
        base_kern = (baseline.get("extra") or {}).get("kernels")
        if kern is not None and base_kern:
            # engaged in the baseline but composite now -> regression;
            # a family the fresh line doesn't report is a wildcard
            # (that bench simply didn't exercise it this run)
            lost = sorted(k for k, v in base_kern.items()
                          if v and kern.get(k) is False)
            check("kernel_engagement", not lost,
                  ("all engaged kernel families still engaged"
                   if not lost else
                   f"engaged in last-good but composite now: "
                   f"{', '.join(lost)} — tune-table row no longer "
                   f"matches (device change or key churn?)"))
        hbm = peak_hbm_of(fresh)
        base_hbm = (baseline.get("extra") or {}).get("peak_hbm_gib")
        if hbm and base_hbm:
            growth = hbm / base_hbm - 1.0
            check("peak_hbm", growth <= th["hbm_growth"],
                  f"{hbm:.2f} GiB vs last-good {base_hbm:.2f} GiB "
                  f"({'+' if growth > 0 else '-'}{abs(growth) * 100:.1f}%, "
                  f"max growth {th['hbm_growth'] * 100:.0f}%)")
    elif not hardware:
        check("hardware", True,
              "cpu smoke line — throughput not compared to the TPU record")

    verdict = {
        "ok": all(c["ok"] for c in checks),
        "checks": checks,
        "compared": compared,
    }
    if baseline is not None:
        verdict["baseline"] = {
            "value": baseline.get("value"),
            "commit": baseline.get("commit"),
            "timestamp": baseline.get("timestamp"),
        }
    return verdict


def format_verdict(metric: str, verdict: dict) -> str:
    lines = [f"== perf guard: {metric} =="]
    for c in verdict["checks"]:
        mark = "ok  " if c["ok"] else "FAIL"
        lines.append(f"  [{mark}] {c['name']:<12} {c['detail']}")
    base = verdict.get("baseline")
    if base:
        lines.append(f"  baseline: {base['value']} "
                     f"@ {base.get('commit', '?')} ({base.get('timestamp')})")
    elif verdict["compared"] is False:
        lines.append("  no last-good hardware baseline in the store")
    lines.append("verdict: " + (
        "PASS" if verdict["ok"]
        else "REGRESSION — do not trust/land this number"))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate a fresh bench JSON line against the last-good "
                    "record in PERF_MEASUREMENTS.json.")
    ap.add_argument("fresh", help="file with the bench JSON line ('-' = "
                                  "stdin; a full bench log is fine)")
    ap.add_argument("--store", default=None,
                    help="measurement store (default: repo-root "
                         "PERF_MEASUREMENTS.json, or $PT_MEASUREMENTS_PATH)")
    ap.add_argument("--throughput-drop", type=float,
                    default=DEFAULT_THRESHOLDS["throughput_drop"],
                    help="max fractional throughput drop (default 0.10)")
    ap.add_argument("--mfu-drop", type=float,
                    default=DEFAULT_THRESHOLDS["mfu_drop"],
                    help="max fractional MFU drop (default 0.10)")
    ap.add_argument("--max-starvation-rate", type=float,
                    default=DEFAULT_THRESHOLDS["max_starvation_rate"],
                    help="max prefetch starvations per step (default 0.25)")
    ap.add_argument("--hbm-growth", type=float,
                    default=DEFAULT_THRESHOLDS["hbm_growth"],
                    help="max fractional peak-HBM growth (default 0.10)")
    ap.add_argument("--compile-growth", type=float,
                    default=DEFAULT_THRESHOLDS["compile_growth"],
                    help="max fractional compile wall-time growth vs "
                         "last-good (default 0.50; only fails past "
                         "--compile-slack-ms absolute)")
    ap.add_argument("--compile-slack-ms", type=float,
                    default=DEFAULT_THRESHOLDS["compile_slack_ms"],
                    help="absolute compile-ms headroom before the growth "
                         "gate can fail (default 2000)")
    ap.add_argument("--ttft-growth", type=float,
                    default=DEFAULT_THRESHOLDS["ttft_growth"],
                    help="max fractional p99 TTFT growth vs last-good "
                         "for serving bench lines (default 0.25)")
    ap.add_argument("--queue-share-growth", type=float,
                    default=DEFAULT_THRESHOLDS["queue_share_growth"],
                    help="max fractional growth of the serving bench's "
                         "attribution.queue_share vs last-good (default "
                         "0.25; only fails past --queue-share-slack, "
                         "skipped when either side lacks the "
                         "attribution sub-object)")
    ap.add_argument("--queue-share-slack", type=float,
                    default=DEFAULT_THRESHOLDS["queue_share_slack"],
                    help="absolute queue-share headroom before the "
                         "growth gate can fail (default 0.05)")
    ap.add_argument("--prefix-hit-drop", type=float,
                    default=DEFAULT_THRESHOLDS["prefix_hit_drop"],
                    help="max fractional prefix_hit_rate drop vs "
                         "last-good for serving bench lines (default "
                         "0.25; skipped when the baseline rate is 0)")
    ap.add_argument("--accept-drop", type=float,
                    default=DEFAULT_THRESHOLDS["accept_drop"],
                    help="max fractional speculative accept_rate drop "
                         "vs last-good for serving bench lines (default "
                         "0.25; skipped when either side lacks the "
                         "field or the baseline rate is 0)")
    ap.add_argument("--affinity-drop", type=float,
                    default=DEFAULT_THRESHOLDS["affinity_drop"],
                    help="max fractional router affinity_hit_rate drop "
                         "vs last-good for serving bench lines (default "
                         "0.25; skipped when either side lacks the "
                         "field or the baseline rate is 0)")
    ap.add_argument("--save-cost-growth", type=float,
                    default=DEFAULT_THRESHOLDS["save_cost_growth"],
                    help="max fractional checkpoint-save blocking-cost "
                         "growth vs last-good for soak lines (default "
                         "0.50; only fails past --save-cost-slack-ms)")
    ap.add_argument("--save-cost-slack-ms", type=float,
                    default=DEFAULT_THRESHOLDS["save_cost_slack_ms"],
                    help="absolute save-cost headroom before the growth "
                         "gate can fail (default 250)")
    ap.add_argument("--goodput-drop", type=float,
                    default=DEFAULT_THRESHOLDS["goodput_drop"],
                    help="max fractional goodput_frac drop vs last-good "
                         "for lines carrying the goodput ledger "
                         "(default 0.10; skipped when either side lacks "
                         "the field)")
    ap.add_argument("--plan-drift", dest="plan_drift",
                    action="store_true", default=True,
                    help="fail a hardware line whose shard_plan differs "
                         "from the last-good record's for the same "
                         "topology (default on)")
    ap.add_argument("--no-plan-drift", dest="plan_drift",
                    action="store_false",
                    help="disable the sharding-plan drift gate")
    ap.add_argument("--audit", dest="audit", action="store_true",
                    default=True,
                    help="fail a hardware line whose program_audit "
                         "sub-object reports findings absent from the "
                         "last-good record (default on; skips when "
                         "either side lacks the sub-object)")
    ap.add_argument("--no-audit", dest="audit", action="store_false",
                    help="disable the program-audit gate")
    ap.add_argument("--slo-breach", dest="slo_breach",
                    action="store_true", default=True,
                    help="fail a hardware line whose slo sub-object "
                         "counts breaches when the last-good record "
                         "(same PT_SLO_* targets) breached zero times "
                         "(default on; skips when either side lacks "
                         "the sub-object)")
    ap.add_argument("--no-slo-breach", dest="slo_breach",
                    action="store_false",
                    help="disable the SLO-breach gate")
    ap.add_argument("--require-baseline", action="store_true",
                    help="fail when the store has no last-good hardware "
                         "record for the metric")
    ap.add_argument("--hardware", choices=("auto", "yes", "no"),
                    default="auto",
                    help="treat the fresh line as a hardware number "
                         "(default: infer from its cpu-smoke markers)")
    args = ap.parse_args(argv)
    try:
        fresh = load_fresh(args.fresh)
    except (OSError, ValueError) as e:
        print(f"perf_guard: {e}", file=sys.stderr)
        return 2
    store = args.store or _default_store()
    # pass the fresh line so its own already-persisted record (benches
    # write the store before the guard runs) is never its baseline, and
    # its sweep config so other-config A/B points are skipped
    baseline = last_good(store, fresh["metric"], fresh=fresh,
                         match=config_match(fresh))
    hardware = {"auto": None, "yes": True, "no": False}[args.hardware]
    verdict = evaluate(
        fresh, baseline,
        thresholds={"throughput_drop": args.throughput_drop,
                    "mfu_drop": args.mfu_drop,
                    "max_starvation_rate": args.max_starvation_rate,
                    "hbm_growth": args.hbm_growth,
                    "compile_growth": args.compile_growth,
                    "compile_slack_ms": args.compile_slack_ms,
                    "ttft_growth": args.ttft_growth,
                    "queue_share_growth": args.queue_share_growth,
                    "queue_share_slack": args.queue_share_slack,
                    "prefix_hit_drop": args.prefix_hit_drop,
                    "accept_drop": args.accept_drop,
                    "affinity_drop": args.affinity_drop,
                    "save_cost_growth": args.save_cost_growth,
                    "save_cost_slack_ms": args.save_cost_slack_ms,
                    "goodput_drop": args.goodput_drop,
                    "plan_drift": args.plan_drift,
                    "audit": args.audit,
                    "slo_breach": args.slo_breach},
        hardware=hardware)
    if args.require_baseline and baseline is None:
        verdict["ok"] = False
        verdict["checks"].append({
            "name": "baseline", "ok": False,
            "detail": f"no hardware record for {fresh['metric']!r} "
                      f"in {store}"})
    print(format_verdict(fresh["metric"], verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
