"""Opportunistic hardware-bench orchestrator.

Probes the TPU tunnel; when it is up, runs every benchmark in value order,
each in its own subprocess with a timeout, so one hang cannot cost the
others.  Every successful run persists its numbers to
``PERF_MEASUREMENTS.json`` (see ``paddle_tpu/utils/measurements.py``) the
moment they exist — run this whenever the chip is reachable during a
round, not only at bench time.

Usage: python tools/hwbench.py [--only headline,decode,bert,resnet,ernie]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BENCHES = [
    # (name, argv, timeout_s, env) — round-5 value order (VERDICT r4
    # "Next round"): clean-tree headline + loss curve first, then 7B
    # geometry, then the ResNet layout A/B, then the rest.
    ("headline", [sys.executable, "bench.py"], 2700, None),
    # async-pipeline A/B (docs/ASYNC_PIPELINE.md): bounded in-flight
    # stepping vs per-step host sync. Each records under its own metric
    # suffix (…_async / …_syncstep) with host_blocked_ms_per_step, so the
    # tunnel-RTT-off-the-critical-path claim gets a hardware number.
    ("headline_async", [sys.executable, "bench.py"], 2700,
     {"PT_BENCH_ASYNC": "1"}),
    ("headline_syncstep", [sys.executable, "bench.py"], 2700,
     {"PT_BENCH_ASYNC": "sync"}),
    ("loss_curve", [sys.executable, "tools/loss_curve.py",
                    "--steps", "200"], 2700, None),
    ("llama7b", [sys.executable, "benchmarks/llama7b_geometry.py"],
     2400, None),
    ("resnet", [sys.executable, "benchmarks/baseline_configs.py",
                "--resnet-only"], 2400, None),
    ("resnet_nhwc", [sys.executable, "benchmarks/baseline_configs.py",
                     "--resnet-only"], 2400, {"PT_RESNET_FORMAT": "NHWC"}),
    ("resnet_profile", [sys.executable, "tools/profile_train_step.py",
                        "--model", "resnet"], 1800, None),
    ("decode", [sys.executable, "benchmarks/decode_bench.py"], 1800, None),
    ("decode_int8", [sys.executable, "benchmarks/decode_bench.py"],
     1800, {"PT_DECODE_INT8": "1"}),
    # continuous-batching serving runtime (docs/SERVING.md): smoke-sized
    # Poisson trace, timeboxed — tokens/s + p50/p99 TTFT vs the decode
    # HBM roofline; the guard's --ttft-growth gate judges the tail.
    # Spec pinned off: these two rows keep judging against their
    # pre-speculation baselines (spec/spec_k are guard config keys)
    ("serving", [sys.executable, "benchmarks/serving_bench.py"], 1800,
     {"PT_SERVE_BENCH_REQUESTS": "32", "PT_SERVE_SPEC": "0"}),
    # prefix-cache KV sharing (docs/SERVING.md): the same Poisson trace
    # with every prompt opening on one 64-token shared system prompt —
    # persists prefix_hit_rate + the cached-vs-cold TTFT A/B next to
    # the plain serving row; perf_guard --prefix-hit-drop pins the rate
    ("serving_prefix", [sys.executable, "benchmarks/serving_bench.py"],
     1800, {"PT_SERVE_BENCH_REQUESTS": "32",
            "PT_SERVE_BENCH_SHARED": "64", "PT_SERVE_SPEC": "0"}),
    # speculative decoding (docs/SERVING.md): the repetition-friendly
    # trace (tiled-motif prompts, spec_k=4) with the embedded spec-off
    # replay — persists accept_rate + tokens_per_decode_step and the
    # decode-rounds A/B; perf_guard --accept-drop pins the accept rate
    ("serving_spec", [sys.executable, "benchmarks/serving_bench.py"],
     1800, {"PT_SERVE_BENCH_REQUESTS": "32",
            "PT_SERVE_BENCH_SPEC_K": "4",
            "PT_SERVE_BENCH_SPEC_AB": "1"}),
    # multi-replica router (docs/SERVING.md "Replica router"): the
    # shared-prefix trace dispatched over 3 in-process replicas —
    # persists affinity_hit_rate + load_balance_spread next to the
    # single-engine rows (replicas is a guard config key, so they never
    # cross-judge); perf_guard --affinity-drop pins the hit rate
    ("serving_router", [sys.executable, "benchmarks/serving_bench.py"],
     1800, {"PT_SERVE_BENCH_REQUESTS": "32",
            "PT_SERVE_BENCH_SHARED": "64", "PT_SERVE_SPEC": "0",
            "PT_SERVE_BENCH_REPLICAS": "3"}),
    # int8 KV block pool (docs/SERVING.md "int8 KV"): the plain serving
    # trace with the pool quantized + the embedded bf16 replay — persists
    # kv_bytes_per_token / allocatable_tokens (the half-HBM capacity
    # claim) and the quantize-cost A/B; kv_int8 is a guard config key,
    # so this row never cross-judges the bf16 serving row
    ("serving_int8kv", [sys.executable, "benchmarks/serving_bench.py"],
     1800, {"PT_SERVE_BENCH_REQUESTS": "32", "PT_SERVE_SPEC": "0",
            "PT_SERVE_KV_INT8": "1", "PT_SERVE_BENCH_KV_AB": "1"}),
    # resilience soak (docs/RESILIENCE.md): fault-injected (crash +
    # poisoned batch) run through launcher relaunch + resume + NaN skip,
    # gated on loss slope / memory growth / the save-cost guard; the
    # persisted ckpt_save_ms_p50 anchors perf_guard --save-cost-growth
    ("soak", [sys.executable, "tools/soak.py", "--steps", "600"], 2400,
     None),
    ("bert", [sys.executable, "benchmarks/baseline_configs.py",
              "--bert-only"], 1800, None),
    ("ernie", [sys.executable, "benchmarks/ernie_bench.py"], 1800, None),
    ("longcontext", [sys.executable, "benchmarks/longcontext_bench.py"],
     2400, None),
    ("host_overhead", [sys.executable,
                       "benchmarks/host_overhead_bench.py"], 1200, None),
    ("flashtune", [sys.executable, "tools/flash_autotune.py"], 2400, None),
    # kernel search harness (docs/KERNELS.md): enumerate + parity-filter
    # + time the candidate spaces for every registered family (head-
    # batched flash, paged attention, paged_attention_int8, flash
    # blocks) and persist the engagement rows the runtime flips on —
    # the timeboxed stage that settles the disengaged-by-default
    # kernels (now incl. the quantized-gather int8 family) next chip-up
    ("kernel_search", [sys.executable, "tools/kernel_search.py"], 2400,
     None),
    # automatic sharding planner (docs/AUTOSHARD.md): timeboxed candidate
    # sweep — dp×mp×pp since ISSUE 15, so pipeline candidates are judged
    # and measured too — + a short measured run of the winner and
    # runner-up; persists the planned-vs-measured throughput delta (the
    # cost-model calibration number, incl. the bubble model's first
    # hardware anchor) and the (dp, mp, pp, batch) plan the guard's
    # --plan-drift gate pins for this topology
    ("shard_plan", [sys.executable, "tools/shard_plan.py", "bench"],
     2400, None),
    ("profile", [sys.executable, "tools/profile_train_step.py"], 1800,
     None),
    # queued PR-6 follow-up (ROADMAP item 5 remainder): cold-vs-warm
    # compile_ms_total through the tunnel + proof the tunneled PJRT
    # plugin supports serialize_executable (runs bench.py twice)
    ("exec_cache_tunnel",
     [sys.executable, "tools/exec_cache_tunnel_probe.py"], 5400, None),
]


def _guard_check(name: str, stdout: str):
    """Run tools/perf_guard.py over a finished bench's stdout: judge the
    fresh line against the last-good record BEFORE the next bench runs,
    so a regression is called out while the chip is still up to re-measure.
    Returns True/False, or None when the output carries no bench line."""
    try:
        if ROOT not in sys.path:
            sys.path.insert(0, ROOT)
        from bench import _load_perf_guard

        guard = _load_perf_guard()
        fresh = guard.find_bench_line(stdout)
        if fresh is None:
            return None
        # bench.py embeds its own verdict (judged against the pre-record
        # baseline); for benches that don't embed, judge here — passing
        # `fresh` so the record the bench just persisted is not used as
        # its own baseline, and its sweep config so other-config A/B
        # points are skipped
        verdict = fresh.get("guard") or guard.evaluate(
            fresh, guard.last_good(guard._default_store(), fresh["metric"],
                                   fresh=fresh,
                                   match=guard.config_match(fresh)))
        ok = bool(verdict.get("ok"))
        if not ok:
            fails = [c["name"] for c in verdict.get("checks", [])
                     if not c.get("ok")]
            print(f"hwbench: {name} PERF GUARD FAILED "
                  f"({', '.join(fails) or 'unknown'})", flush=True)
        else:
            print(f"hwbench: {name} guard ok", flush=True)
        return ok
    except Exception as e:  # noqa: BLE001 — the guard must not stop the sweep
        print(f"hwbench: {name} guard errored: {e}", flush=True)
        return None


def _memory_status(name: str, stdout: str):
    """Peak-HBM + numerics-sentinel + goodput status from a finished
    bench's JSON line — printed per bench and returned for the summary,
    so memory and goodput regressions get the same while-the-chip-is-up
    visibility as throughput. (The benches themselves persist these
    fields into their PERF_MEASUREMENTS.json records — bench.py and
    soak.py carry ``goodput_frac`` in their extras, which anchors
    perf_guard --goodput-drop; this is the live readout.)"""
    try:
        if ROOT not in sys.path:
            sys.path.insert(0, ROOT)
        from bench import _load_perf_guard

        guard = _load_perf_guard()
        line = guard.find_bench_line(stdout)
        if line is None:
            return None
        out = {}
        hbm = guard.peak_hbm_of(line)
        if hbm is not None:
            out["peak_hbm_gib"] = hbm
        mem = line.get("memory") or {}
        if "nan_check" in mem:
            out["nan_check"] = mem["nan_check"]
        elif "nan_check" in line:
            out["nan_check"] = line["nan_check"]
        if line.get("goodput_frac") is not None:
            out["goodput_frac"] = line["goodput_frac"]
        if out:
            parts = []
            if "peak_hbm_gib" in out:
                parts.append(f"peak HBM {out['peak_hbm_gib']} GiB")
            if "nan_check" in out:
                parts.append("nan-check "
                             + ("armed" if out["nan_check"] else "off"))
            if "goodput_frac" in out:
                parts.append(f"goodput {out['goodput_frac']:.1%}")
            print(f"hwbench: {name} memory: {', '.join(parts)}", flush=True)
        return out or None
    except Exception as e:  # noqa: BLE001 — a readout, never a gate
        print(f"hwbench: {name} memory status errored: {e}", flush=True)
        return None


def probe() -> str:
    """Reuse bench.py's probe: it pins the platform config past the host
    sitecustomize override and retries transient UNAVAILABLE with backoff —
    a plain `import jax` probe falsely reports 'no TPU' in both cases."""
    sys.path.insert(0, ROOT)
    from bench import _probe_backend

    try:
        return _probe_backend()
    except RuntimeError as e:
        return f"error: {e}"


def main() -> int:
    only = None
    if "--only" in sys.argv:
        only = set(sys.argv[sys.argv.index("--only") + 1].split(","))
    backend = probe()
    print(f"hwbench: backend={backend}", flush=True)
    if backend != "tpu":
        print("hwbench: no TPU — nothing to measure", flush=True)
        return 1
    results = {}
    for name, argv, timeout_s, extra_env in BENCHES:
        if only and name not in only:
            continue
        if not os.path.exists(os.path.join(ROOT, argv[1])):
            print(f"hwbench: {name}: script missing, skipped", flush=True)
            continue
        env = None
        if extra_env:
            env = dict(os.environ)
            env.update(extra_env)
        t0 = time.time()
        print(f"hwbench: running {name} ...", flush=True)
        try:
            proc = subprocess.run(argv, cwd=ROOT, capture_output=True,
                                  text=True, timeout=timeout_s, env=env)
            out = proc.stdout.strip().splitlines()
            results[name] = {"rc": proc.returncode,
                             "secs": round(time.time() - t0, 1),
                             "lines": out[-3:]}
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            print(f"hwbench: {name} rc={proc.returncode} "
                  f"({results[name]['secs']}s)", flush=True)
            for ln in out[-3:]:
                print(f"  {ln}", flush=True)
            if proc.returncode == 0:
                results[name]["guard_ok"] = _guard_check(name, proc.stdout)
                mem = _memory_status(name, proc.stdout)
                if mem:
                    results[name]["memory"] = mem
            if proc.returncode != 0:
                for ln in tail:
                    print(f"  [stderr] {ln}", flush=True)
        except subprocess.TimeoutExpired:
            results[name] = {"rc": -1, "secs": timeout_s,
                             "lines": ["timeout"]}
            print(f"hwbench: {name} TIMED OUT after {timeout_s}s",
                  flush=True)
    summary = {"hwbench_summary": {
        k: v["rc"] for k, v in results.items()}}
    mem_map = {k: v["memory"] for k, v in results.items() if "memory" in v}
    if mem_map:
        summary["hwbench_memory"] = mem_map
    print(json.dumps(summary), flush=True)
    # a run in which nothing was measured must be retryable by exit code
    if not results or all(v["rc"] != 0 for v in results.values()):
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
