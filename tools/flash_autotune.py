"""Run the flash-attention block-size autotuner on the live backend.

Usage: python tools/flash_autotune.py [--iters 20] [--shapes bh,sq,sk,d,causal ...]

Writes `paddle_tpu/ops/pallas/flash_tune.json` (block choices + kernel-vs-
composite ratios with device provenance) and records a summary metric to
PERF_MEASUREMENTS.json. Run whenever a chip is reachable (hwbench stage).
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--shapes", nargs="*", default=None,
                    help="bh,sq,sk,d,causal tuples")
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import enable_compilation_cache

    enable_compilation_cache()
    backend = jax.default_backend()
    print(f"flash_autotune: backend={backend}", flush=True)
    if backend == "cpu":
        print("flash_autotune: no TPU — tuning wall-clock on CPU is "
              "meaningless; exiting", flush=True)
        return 1

    from paddle_tpu.ops.pallas import autotune

    if args.shapes:
        shapes = []
        for s in args.shapes:
            bh, sq, sk, d, causal = s.split(",")
            shapes.append((int(bh), int(sq), int(sk), int(d),
                           causal.lower() in ("1", "true", "c")))
    else:
        shapes = autotune.STANDARD_SHAPES

    entries = []
    for bh, sq, sk, d, causal in shapes:
        print(f"tuning bh={bh} s={sq}x{sk} d={d} causal={causal}",
              flush=True)
        entries.append(autotune.tune_shape(bh, sq, sk, d, causal,
                                           iters=args.iters))

    for bh, sq, sk, d, causal, p_drop in autotune.VARIANT_SHAPES:
        print(f"variant bh={bh} s={sq}x{sk} d={d} causal={causal} "
              f"dropout={p_drop}", flush=True)
        try:
            entries.append(autotune.tune_variant_ratio(
                bh, sq, sk, d, causal, p_drop, iters=args.iters))
        except Exception as e:  # noqa: BLE001 — variants must not
            print(f"  variant failed: {e}", flush=True)  # cost the base rows

    from paddle_tpu.utils import measurements as meas

    base = [e for e in entries if not e.get("dropout")]
    wins = sum(1 for e in base if e.get("ratio_fwd_bwd", 0) > 1.0)
    meas.record_or_warn(
        "flash_autotune_shapes_kernel_wins", float(wins), "shapes",
        extra={"tuned": len(base), "variants": len(entries) - len(base),
               "entries": {
                   autotune._key(e["sq"], e["sk"], e["d"], e["causal"],
                                 e.get("dropout", 0.0)):
                   e.get("ratio_fwd_bwd") for e in entries}})
    print(f"flash_autotune: {wins}/{len(base)} base shapes favor the "
          f"kernel (+{len(entries) - len(base)} variant rows); cache at "
          f"paddle_tpu/ops/pallas/flash_tune.json", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
