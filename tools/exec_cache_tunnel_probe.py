#!/usr/bin/env python
"""Cold-vs-warm compile wall-time through the exec cache — the queued
PR-6 hardware follow-up (ROADMAP item 5 remainder).

Runs ``bench.py`` twice in child processes against a fresh
``PT_EXEC_CACHE`` directory: the COLD run must compile and serialize,
the WARM run must deserialize and pay ~zero fresh XLA compiles. The
delta is the cold-start saving the cache buys on this backend, and the
warm run's disk-hit count is the proof that the (tunneled) PJRT plugin
supports ``serialize_executable`` — which the CPU-only proof in
tests/test_exec_cache.py cannot establish.

Usage: python tools/exec_cache_tunnel_probe.py
Prints one JSON line: {"metric": "exec_cache_cold_warm_compile_ms", ...}
with ``serialize_executable_ok`` as the plugin-support verdict.
Wired as an hwbench row; persists to PERF_MEASUREMENTS.json on hardware.
"""
from __future__ import annotations

import importlib.util
import json
import os
import shutil
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _find_bench_line(text: str):
    """perf_guard.find_bench_line by path (tools/ is not a package)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "perf_guard.py")
    spec = importlib.util.spec_from_file_location("perf_guard", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.find_bench_line(text)


def summarize(cold: dict, warm: dict) -> dict:
    """The probe's verdict from the two bench lines (pure — unit-tested
    without subprocesses). ``serialize_executable_ok`` requires the cold
    run to have SERIALIZED artifacts and the warm run to have LOADED
    them (disk hits) — a backend whose executables don't round-trip
    fails the second leg (every load error falls back to a fresh
    compile and counts in ``errors``)."""
    tel_c = cold.get("telemetry") or {}
    tel_w = warm.get("telemetry") or {}
    ec_c = tel_c.get("exec_cache") or {}
    ec_w = tel_w.get("exec_cache") or {}
    cold_ms = tel_c.get("compile_ms_total")
    warm_ms = tel_w.get("compile_ms_total")
    ok = bool(ec_c.get("serialized", 0) > 0
              and ec_w.get("disk_hits", 0) > 0)
    rec = {
        "metric": "exec_cache_cold_warm_compile_ms",
        "value": (round(cold_ms - warm_ms, 1)
                  if cold_ms is not None and warm_ms is not None
                  else None),
        "unit": "ms",
        "compile_ms_cold": cold_ms,
        "compile_ms_warm": warm_ms,
        "serialized_cold": ec_c.get("serialized", 0),
        "disk_hits_warm": ec_w.get("disk_hits", 0),
        "deserialize_errors_warm": ec_w.get("errors", 0),
        "serialize_executable_ok": ok,
        "headline_metric": cold.get("metric"),
    }
    note = cold.get("note") or warm.get("note")
    if note:
        rec["note"] = note
    return rec


def main() -> int:
    cache_dir = os.environ.get(
        "PT_EXEC_CACHE_PROBE_DIR",
        os.path.expanduser("~/.cache/paddle_tpu_exec_cache_probe"))
    # cold must be COLD: wipe any artifacts from a previous probe
    shutil.rmtree(cache_dir, ignore_errors=True)
    env = dict(os.environ)
    env["PT_EXEC_CACHE"] = cache_dir
    lines = []
    for phase in ("cold", "warm"):
        proc = subprocess.run(
            [sys.executable, "bench.py"], cwd=ROOT, env=env,
            capture_output=True, text=True)
        line = _find_bench_line(proc.stdout)
        if proc.returncode != 0 or line is None:
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            print(json.dumps({
                "metric": "exec_cache_cold_warm_compile_ms",
                "value": None, "unit": "ms",
                "error": f"{phase} bench failed rc={proc.returncode}: "
                         f"{' | '.join(tail)}"}), flush=True)
            return 1
        print(f"probe: {phase} compile_ms_total="
              f"{(line.get('telemetry') or {}).get('compile_ms_total')}",
              file=sys.stderr, flush=True)
        lines.append(line)
    rec = summarize(*lines)
    if "note" not in rec:  # hardware lines persist with provenance
        sys.path.insert(0, ROOT)
        from paddle_tpu.utils import measurements as _meas

        # backend facts come from the CHILD's already-probed line; don't
        # re-touch a possibly flaky tunnel from this process
        _meas.record_rec_or_warn(rec, backend="tpu", device="tunneled-tpu")
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
