#!/usr/bin/env python
"""Production-soak gate: an unattended fault-injected run must FINISH.

    python tools/soak.py --smoke                 # tier-1: CPU, ~1 min
    python tools/soak.py --steps 2000            # hardware soak row

The driver launches a training worker under the babysitting launcher
(``python -m paddle_tpu.distributed.launch --max_restart``) with the full
resilience stack armed — planned async checkpoints
(``hapi.fit(checkpoint_dir=)``), resume-from-latest-complete
(``resume_from=``), and NaN skip-and-continue (``nan_policy="skip"``) —
then injects the two faults that kill real long runs:

- ``PT_SOAK_CRASH_AT=<step>``: the worker ``os._exit``\\ s mid-run on its
  first life (async checkpoint writers die mid-write — torn checkpoints
  are part of the test); the launcher relaunches it
  (``PADDLE_RESTART_COUNT``) and it must resume from the last COMPLETE
  checkpoint, never a torn one.
- ``PT_SOAK_POISON_AT=<batch>``: one batch of NaNs; the numerics
  sentinel + skip policy must drop it and continue.
- ``PT_SOAK_HANG_AT=<batch>``: a sleep inside a host callback boundary
  (``PT_SOAK_HANG_S``, 2.5 s) freezes the step counter; the hang
  watchdog (``monitor/watchdog.py``, ``PT_HANG_MIN_S=1`` in the soak
  env) must trip mid-hang and write a blackbox artifact NAMING the hung
  step (``PT_HANG_BLACKBOX``) while policy ``warn`` lets the run go on.

The run's FINAL STATE is then gated — not just "no stack trace":

- loss-curve slope: mean(last quarter) < mean(first quarter) — the model
  learned through the crash and the poison;
- memory growth: live-census peak in the last third ≤ 10% over the first
  third (a leaking resume would show here);
- crash/skip proofs: ≥ 2 lives with a complete resume point when a crash
  was injected; ≥ 1 skipped batch when poison was;
- perf guard: the emitted line judged against the last-good record
  (``tools/perf_guard.py`` — including the ``--save-cost-growth``
  checkpoint-overhead gate via ``ckpt_save_ms_p50``).

Emits ONE JSON verdict line (the bench-line contract: ``metric`` =
``soak``) and exits 0 iff every gate passed. Hardware runs persist to
``PERF_MEASUREMENTS.json``. ``tools/hwbench.py`` carries a timeboxed soak
row; ``tests/test_resilience.py`` runs ``--smoke`` in tier-1.

``--router`` is the serving twin: an in-process replica-kill drain
scenario (docs/SERVING.md "Replica router") — a 3-replica
``RouterEngine`` serves a shared-prefix trace, one replica's ``step()``
starts raising mid-flight (``PT_SOAK_ROUTER_KILL`` picks the victim,
``PT_SOAK_ROUTER_KILL_AT`` the step), and the gate demands every
request finish on the survivors byte-identical to a no-failure
single-engine run, with the blackbox postmortem naming the dead
replica. Same one-JSON-verdict-line contract (``metric`` =
``soak_router``), exit 0 iff all checks hold.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE_STEPS = 48
SMOKE_BATCH = 8


# -- worker ------------------------------------------------------------------

def _worker(workdir: str) -> int:
    """One launcher-managed life of the soak training loop: hapi fit with
    the full resilience stack, fault injection from PT_SOAK_* env."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import monitor, resilience

    restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0") or 0)
    steps = int(os.environ.get("PT_SOAK_STEPS", str(SMOKE_STEPS)))
    batch = int(os.environ.get("PT_SOAK_BATCH", str(SMOKE_BATCH)))
    crash_at = int(os.environ.get("PT_SOAK_CRASH_AT", "-1"))
    poison_at = int(os.environ.get("PT_SOAK_POISON_AT", "-1"))
    hang_at = int(os.environ.get("PT_SOAK_HANG_AT", "-1"))
    hang_s = float(os.environ.get("PT_SOAK_HANG_S", "2.5"))
    ckpt_dir = os.path.join(workdir, "ckpt")

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 1))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, nn.MSELoss())

    rng = np.random.default_rng(0)
    xs = rng.standard_normal((steps * batch, 16)).astype("float32")
    w_true = rng.standard_normal((16, 1)).astype("float32")
    ys = xs @ w_true
    if poison_at >= 0:
        # one poisoned BATCH: the sentinel must trip, the policy must skip
        xs[poison_at * batch:(poison_at + 1) * batch] = np.nan
    ds = [(xs[i], ys[i]) for i in range(steps * batch)]

    from paddle_tpu.distributed import checkpoint as dckpt

    resumed = resilience.latest_complete(ckpt_dir)
    resumed_step = resumed[0] if resumed else None
    # torn-proof captured AT RESUME TIME: later GC removes torn dirs, so
    # a post-hoc scan by the driver could never catch a selector that
    # regressed into picking an incomplete checkpoint
    resumed_complete = (bool(dckpt.is_complete(resumed[1]))
                        if resumed else None)

    class CrashAt(paddle.callbacks.Callback):
        """Hard mid-run failure: os._exit skips every flush/join — the
        async checkpoint writer dies mid-write, exactly like a
        preemption. The blackbox postmortem is the ONE thing written
        first (os._exit skips atexit too, so this is its only chance) —
        the driver asserts the artifact exists and parses."""

        def __init__(self, at):
            self.at = at
            self.n = 0

        def on_train_batch_end(self, step, logs=None):
            self.n += 1
            if self.n == self.at:
                from paddle_tpu.monitor import blackbox

                blackbox.dump(reason="PT_SOAK_CRASH_AT",
                              error=f"injected crash at batch {self.n}")
                os._exit(23)

    class HangAt(paddle.callbacks.Callback):
        """Injected hang: a sleep inside a host callback boundary — the
        step counter stops, exactly like a wedged collective from the
        watchdog's viewpoint. PT_HANG_MIN_S is short in the soak env, so
        the hang watchdog (monitor/watchdog.py) must trip mid-sleep,
        write its blackbox artifact naming the hung step, and (policy
        ``warn``) let the run continue — the driver gates on the
        artifact."""

        def __init__(self, at, hold_s):
            self.at = at
            self.hold_s = hold_s
            self.n = 0

        def on_train_batch_end(self, step, logs=None):
            self.n += 1
            if self.n == self.at:
                time.sleep(self.hold_s)

    cbks = []
    if restart == 0 and crash_at >= 0:
        cbks.append(CrashAt(crash_at))
    if restart == 0 and hang_at >= 0:
        cbks.append(HangAt(hang_at, hang_s))

    t0 = time.perf_counter()
    model.fit(ds, batch_size=batch, epochs=1, shuffle=False, verbose=0,
              log_freq=5, checkpoint_dir=ckpt_dir, resume_from=ckpt_dir,
              nan_policy="skip", callbacks=cbks)
    wall = time.perf_counter() - t0

    counters = monitor.snapshot()["counters"]
    params = np.concatenate([
        np.asarray(p._data).ravel().astype(np.float64)
        for p in net.parameters()])
    summary = {
        "life": restart,
        "resumed_from": resumed_step,
        "resumed_from_complete": resumed_complete,
        "finished": True,
        "wall_s": round(wall, 3),
        "skipped_batches": counters.get("resilience/skipped_batches", 0),
        "saves": counters.get("resilience/saves", 0),
        "crash_resumes": counters.get("resilience/crash_resumes", 0),
        "hang_trips": counters.get("monitor/hang_trips", 0),
        "params_finite": bool(np.isfinite(params).all()),
        "params_sum": float(params.sum()),
    }
    with open(os.path.join(workdir, f"life_{restart}.json"), "w") as f:
        json.dump(summary, f)
    print("SOAK_WORKER_OK", restart, flush=True)
    return 0


# -- router drain leg --------------------------------------------------------

def _router_leg(args) -> int:
    """``--router``: the serving engine's crash-survival twin of the
    training soak — kill one of three router replicas mid-trace and
    gate on the drain contract (finish on survivors, byte-identical
    tokens, postmortem names the victim)."""
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    import jax

    smoke = args.smoke or not os.environ.get("JAX_PLATFORMS", "").strip()
    if smoke:
        # CPU pin the proven way (CLAUDE.md): the env var alone is
        # overridden by the host sitecustomize
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (
        RouterConfig, RouterEngine, ServingConfig, ServingEngine,
    )

    wd = args.out or tempfile.mkdtemp(prefix="pt_soak_router_")
    os.makedirs(wd, exist_ok=True)
    bb_path = os.path.join(wd, "router_blackbox.json")
    os.environ["PT_SERVE_BLACKBOX"] = bb_path
    victim = int(os.environ.get("PT_SOAK_ROUTER_KILL", "0"))
    kill_at = int(os.environ.get("PT_SOAK_ROUTER_KILL_AT", "2"))

    pt.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    model.eval()
    geom = ServingConfig(max_lanes=3, block_size=4, prefill_chunk=8,
                         max_seq_len=32)
    # shared-prefix trace: affinity funnels it onto ONE replica, so
    # killing that replica drains a full complement of in-flight work
    rng = np.random.RandomState(0)
    prefix = rng.randint(0, model.config.vocab_size, (8,)) \
        .astype(np.int32)
    work = []
    for _ in range(12):
        sfx = rng.randint(0, model.config.vocab_size,
                          (int(rng.randint(1, 6)),)).astype(np.int32)
        work.append((np.concatenate([prefix, sfx]),
                     int(rng.randint(4, 10))))

    print(f"soak --router: smoke={smoke} replicas=3 victim={victim} "
          f"kill_at={kill_at} workdir={wd}", flush=True)
    t0 = time.perf_counter()
    single = ServingEngine(model, geom)
    for i, (p, n) in enumerate(work):
        single.submit(p, max_new_tokens=n, request_id=f"r{i}")
    base = single.run()

    router = RouterEngine(model, geom,
                          RouterConfig(replicas=3, mode="inproc"))
    for i, (p, n) in enumerate(work):
        router.submit(p, max_new_tokens=n, request_id=f"r{i}")
    eng = router._replicas[victim]._engine
    real_step = eng.step
    calls = {"n": 0}

    def flaky_step():
        calls["n"] += 1
        if calls["n"] > kill_at:
            raise RuntimeError(
                f"soak-injected replica {victim} failure")
        return real_step()

    eng.step = flaky_step

    # live telemetry plane over the drain (docs/OBSERVABILITY.md): an
    # in-process /metrics endpoint on an ephemeral port, polled through
    # the kill — the healthz gate below requires the dead replica to be
    # visible in /healthz within the same driving step that killed it
    import urllib.request

    from paddle_tpu.monitor import exporter as _exporter

    port = _exporter.start(0)

    def _healthz():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            return json.loads(r.read().decode())

    healthz_ok = port is not None
    healthz_err = None if port else "exporter failed to start"
    killed_step = dead_reported_step = None
    steps_driven = 0
    last_health = None
    while router.has_work():
        router.step()
        steps_driven += 1
        if killed_step is None and calls["n"] > kill_at:
            killed_step = steps_driven
        if port:
            try:
                last_health = _healthz()
            except (OSError, ValueError) as e:
                healthz_ok, healthz_err = False, f"scrape failed: {e}"
                port = None
                continue
            if (last_health.get("dead_replicas")
                    and dead_reported_step is None):
                dead_reported_step = steps_driven
    outs = router.pop_finished()
    _exporter.stop()
    wall = time.perf_counter() - t0

    checks = []

    def check(name, ok, detail):
        checks.append({"name": name, "ok": bool(ok), "detail": detail})

    check("finished_all", set(outs) == set(base),
          f"{len(outs)}/{len(work)} requests finished after the kill")
    ident = all(np.array_equal(outs[k], base[k])
                for k in base if k in outs)
    check("token_identity", ident and set(outs) == set(base),
          "drained requests byte-identical to the no-failure run"
          if ident else "token mismatch after drain")
    c = router.counters
    check("drain", c["dead_replicas"] == 1 and c["redispatches"] > 0
          and victim in router._dead,
          f"dead_replicas={c['dead_replicas']} "
          f"redispatches={c['redispatches']} dead={router._dead}")
    bb_ok, bb_detail = False, f"missing: {bb_path}"
    try:
        with open(bb_path) as f:
            bb = json.load(f)
        srv = bb.get("state", {}).get("serving_router", {})
        bb_ok = (bb.get("reason") == "router_replica_dead"
                 and str(victim) in srv.get("dead", {}))
        bb_detail = (f"reason={bb.get('reason')} "
                     f"dead={srv.get('dead')}")
    except OSError:
        pass
    except ValueError as e:
        bb_detail = f"unparseable: {e}"
    check("blackbox", bb_ok, bb_detail)
    check("healthz", healthz_ok and killed_step is not None
          and dead_reported_step == killed_step,
          healthz_err or (f"dead replica visible in /healthz at step "
                          f"{dead_reported_step} (killed at step "
                          f"{killed_step}); degraded="
                          f"{(last_health or {}).get('degraded')}"))

    line = {
        "metric": "soak_router",
        "value": len(outs),
        "unit": "requests",
        "replicas": 3,
        "victim": victim,
        "kill_at": kill_at,
        "redispatched": c["redispatches"],
        "dispatches_per_replica": list(router.dispatch_counts),
        "killed_step": killed_step,
        "dead_reported_step": dead_reported_step,
        "wall_s": round(wall, 3),
        "checks": [{k: ch[k] for k in ("name", "ok")} for ch in checks],
    }
    if smoke:
        line["note"] = "cpu smoke; replica-kill drain proof"
    ok = all(ch["ok"] for ch in checks)
    line["ok"] = ok
    for ch in checks:
        mark = "ok  " if ch["ok"] else "FAIL"
        print(f"  [{mark}] {ch['name']:<16} {ch.get('detail', '')}",
              flush=True)
    print(json.dumps(line), flush=True)
    return 0 if ok else 3


# -- driver ------------------------------------------------------------------

def _read_jsonl(path):
    """(step_lines, run_ends) across ALL lives appended to the sink."""
    steps, ends = [], []
    try:
        with open(path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    line = json.loads(raw)
                except ValueError:
                    continue
                if not isinstance(line, dict):
                    continue
                if "step" in line:
                    steps.append(line)
                elif line.get("event") == "run_end":
                    ends.append(line)
    except OSError:
        pass
    return steps, ends


def _load_perf_guard():
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "perf_guard.py")
    spec = importlib.util.spec_from_file_location("perf_guard", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _scan_checkpoints(ckpt_dir):
    """(complete_steps, torn_steps) by manifest presence — pure stdlib
    (the worker's resume selector additionally size-verifies shards)."""
    complete, torn = [], []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return complete, torn
    for name in names:
        if not name.startswith("step-"):
            continue
        try:
            step = int(name.split("-", 1)[1])
        except ValueError:
            continue
        if os.path.exists(os.path.join(ckpt_dir, name, "MANIFEST.json")):
            complete.append(step)
        else:
            torn.append(step)
    return sorted(complete), sorted(torn)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fault-injected soak run gated on loss slope, memory "
                    "growth, crash/NaN survival, and the perf guard.")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 sizing: CPU, ~50 steps, ~1 min")
    ap.add_argument("--steps", type=int, default=None,
                    help="total train steps (default: 48 smoke / 2000)")
    ap.add_argument("--out", default=None,
                    help="workdir (default: a fresh temp dir)")
    ap.add_argument("--router", action="store_true",
                    help="serving replica-kill drain leg: 3-replica "
                         "router, one injected step() failure, gated "
                         "on survivors finishing byte-identical")
    ap.add_argument("--worker", default=None, metavar="WORKDIR",
                    help=argparse.SUPPRESS)  # internal: launcher payload
    args = ap.parse_args(argv)
    if args.worker:
        return _worker(args.worker)
    if args.router:
        return _router_leg(args)

    smoke = args.smoke
    if not smoke:
        sys.path.insert(0, ROOT)
        try:
            from bench import _probe_backend

            smoke = _probe_backend() == "cpu"
        except Exception as e:  # noqa: BLE001 — dead tunnel -> smoke
            print(f"soak: backend probe failed ({e}); falling back to "
                  f"cpu smoke", file=sys.stderr)
            smoke = True
    steps = args.steps or (SMOKE_STEPS if smoke else 2000)
    batch = int(os.environ.get("PT_SOAK_BATCH", str(SMOKE_BATCH)))
    crash_at = int(os.environ.get("PT_SOAK_CRASH_AT",
                                  str(max(2, steps // 3))))
    poison_at = int(os.environ.get("PT_SOAK_POISON_AT",
                                   str(max(3, (2 * steps) // 3))))
    # hang BEFORE the crash: injected once, on the first life
    hang_at = int(os.environ.get("PT_SOAK_HANG_AT",
                                 str(max(1, steps // 6))))

    wd = args.out or tempfile.mkdtemp(prefix="pt_soak_")
    os.makedirs(wd, exist_ok=True)
    sink = os.path.join(wd, "steps.jsonl")
    env = dict(os.environ)
    env.update({
        "PT_SOAK_STEPS": str(steps),
        "PT_SOAK_BATCH": str(batch),
        "PT_SOAK_CRASH_AT": str(crash_at),
        "PT_SOAK_POISON_AT": str(poison_at),
        "PT_SOAK_HANG_AT": str(hang_at),
        "PT_MONITOR": "1",
        "PT_MONITOR_SINK": sink,
        "PT_MONITOR_MEM": "1",
        # crash postmortem lands in the workdir, not the repo cwd
        "PT_SERVE_BLACKBOX": os.path.join(wd, "serving_blackbox.json"),
        # hang watchdog: short deadline floor so the injected sleep
        # trips it; its artifact lands separately from the crash one
        "PT_HANG_MIN_S": env.get("PT_HANG_MIN_S") or "1",
        "PT_HANG_BLACKBOX": os.path.join(wd, "hang_blackbox.json"),
        # warm relaunch pays zero fresh XLA compiles (jit/exec_cache.py)
        "PT_EXEC_CACHE": env.get("PT_EXEC_CACHE")
        or os.path.join(wd, "exec_cache"),
        "PYTHONPATH": ROOT + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("PADDLE_RESTART_COUNT", None)
    if smoke:
        env["JAX_PLATFORMS"] = "cpu"
        # a ~50-step smoke must exercise the planner AND still save often
        # enough to have a resume point near the crash: a tiny model's
        # save cost (~60 ms) vs its step time (~4 ms) would honestly plan
        # a sparser cadence than the smoke has steps
        env.setdefault("PT_CKPT_OVERHEAD_PCT", "40")
        env.setdefault("PT_CKPT_MAX_INTERVAL", "4")
    print(f"soak: smoke={smoke} steps={steps} crash_at={crash_at} "
          f"poison_at={poison_at} hang_at={hang_at} workdir={wd}",
          flush=True)

    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restart", "2", "--log_dir", os.path.join(wd, "log"),
         os.path.abspath(__file__), "--worker", wd],
        cwd=ROOT, env=env, capture_output=True, text=True,
        timeout=3600 if not smoke else 900)
    wall = time.perf_counter() - t0

    lives = []
    for name in sorted(os.listdir(wd)):
        if name.startswith("life_") and name.endswith(".json"):
            with open(os.path.join(wd, name)) as f:
                lives.append(json.load(f))
    step_lines, run_ends = _read_jsonl(sink)
    complete_ckpts, torn_ckpts = _scan_checkpoints(
        os.path.join(wd, "ckpt"))

    checks = []

    def check(name, ok, detail):
        checks.append({"name": name, "ok": bool(ok), "detail": detail})

    worker_logs = ""
    logdir = os.path.join(wd, "log")
    if os.path.isdir(logdir):
        for lg in sorted(os.listdir(logdir)):
            try:
                with open(os.path.join(logdir, lg)) as f:
                    worker_logs += f.read()[-2000:]
            except OSError:
                pass
    check("launcher", proc.returncode == 0,
          f"rc={proc.returncode}" + (
              f"; stderr: {proc.stderr[-500:]}; logs: {worker_logs[-800:]}"
              if proc.returncode != 0 else ""))
    final = lives[-1] if lives else {}
    # a crashed life never writes its summary (os._exit), so the life
    # count comes from the final life's restart index, not file count
    n_lives = (final.get("life", 0) + 1) if lives else 0
    check("finished", bool(final.get("finished"))
          and bool(final.get("params_finite")),
          f"{n_lives} live(s); final life finished="
          f"{final.get('finished')} params_finite="
          f"{final.get('params_finite')}")

    if crash_at >= 0:
        relaunched = [lv for lv in lives if lv.get("life", 0) > 0]
        res_from = [lv.get("resumed_from") for lv in relaunched]
        res_ok = (n_lives >= 2 and res_from
                  and all(s is not None for s in res_from))
        # the resume selector must have picked a COMPLETE checkpoint — a
        # torn one (crash mid-write) is never a resume point. Judged from
        # the worker's RESUME-TIME verification (post-run GC removes torn
        # dirs, so a driver-side scan would be vacuous)
        untorn = all(lv.get("resumed_from_complete") is True
                     for lv in relaunched)
        check("crash_resume", res_ok and untorn,
              f"lives={n_lives} resumed_from={res_from} "
              f"resume_point_complete={untorn} "
              f"complete={complete_ckpts[-3:]} torn={torn_ckpts}")
        # the injected crash must leave a parseable blackbox postmortem
        # (monitor/blackbox.py — written before os._exit, atomically)
        bb_path = env["PT_SERVE_BLACKBOX"]
        bb_ok, bb_detail = False, f"missing: {bb_path}"
        try:
            with open(bb_path) as f:
                bb = json.load(f)
            bb_ok = (isinstance(bb.get("spans"), list)
                     and isinstance(bb.get("state"), dict)
                     and bb.get("reason") == "PT_SOAK_CRASH_AT")
            bb_detail = (f"reason={bb.get('reason')} "
                         f"spans={len(bb.get('spans', []))} "
                         f"state_keys={sorted(bb.get('state', {}))}")
        except OSError:
            pass
        except ValueError as e:
            bb_detail = f"unparseable: {e}"
        check("blackbox", bb_ok, bb_detail)
    skipped = sum(lv.get("skipped_batches", 0) for lv in lives)
    if poison_at >= 0:
        check("nan_skip", skipped >= 1,
              f"{skipped} batch(es) skipped (poison at {poison_at})")
    if hang_at >= 0:
        # the injected hang must leave a parseable watchdog artifact
        # NAMING the hung step (the first life hangs after batch
        # `hang_at`, so step hang_at+1 is the one that never landed
        # within deadline)
        hb_path = env["PT_HANG_BLACKBOX"]
        hang_ok, hang_detail = False, f"missing: {hb_path}"
        try:
            with open(hb_path) as f:
                hb = json.load(f)
            trip = (hb.get("state", {}).get("training_watchdog", {})
                    or {}).get("last_trip") or {}
            hang_ok = (hb.get("reason") == "hang_watchdog"
                       and trip.get("hung_step") == hang_at + 1
                       and bool(trip.get("stacks")))
            hang_detail = (f"reason={hb.get('reason')} "
                           f"hung_step={trip.get('hung_step')} "
                           f"(expected {hang_at + 1}) "
                           f"stacks={len(trip.get('stacks') or {})} "
                           f"thread(s)")
        except OSError:
            pass
        except ValueError as e:
            hang_detail = f"unparseable: {e}"
        check("hang_watchdog", hang_ok, hang_detail)

    losses = [(s["step"], s["loss"]) for s in step_lines if "loss" in s]
    if len(losses) >= 8:
        vals = [v for _, v in losses]
        q = max(1, len(vals) // 4)
        first, last = vals[:q], vals[-q:]
        slope_ok = statistics.fmean(last) < statistics.fmean(first)
        check("loss_slope", slope_ok,
              f"mean(first {q})={statistics.fmean(first):.4f} -> "
              f"mean(last {q})={statistics.fmean(last):.4f} over "
              f"{len(vals)} logged losses")
    else:
        check("loss_slope", False,
              f"only {len(losses)} logged losses — not enough to judge")

    mem_series = [s["memory"].get("live_bytes", 0) for s in step_lines
                  if isinstance(s.get("memory"), dict)]
    peak_live = max(mem_series) if mem_series else None
    if len(mem_series) >= 9:
        third = len(mem_series) // 3
        early = max(mem_series[:third])
        late = max(mem_series[-third:])
        slack = 32 << 20  # small-model census noise floor
        mem_ok = late <= early * 1.10 + slack
        check("memory_growth", mem_ok,
              f"live-census peak first third {early / 2**20:.1f} MiB -> "
              f"last third {late / 2**20:.1f} MiB (max +10%)")

    ips = [s["ips"] for s in step_lines if s.get("ips")]
    value = round(statistics.median(ips), 3) if ips else 0.0
    final_end = run_ends[-1] if run_ends else {}
    save_h = (final_end.get("totals", {}).get("histograms", {})
              .get("resilience/save_ms")) or {}
    saves_total = sum(lv.get("saves", 0) for lv in lives)

    line = {
        "metric": "soak",
        "value": value,
        "unit": "samples/s",
        "steps": steps,
        "batch": batch,
        "lives": n_lives,
        "crash_at": crash_at,
        "poison_at": poison_at,
        "skipped_batches": skipped,
        "ckpt_saves": saves_total,
        "ckpt_complete": len(complete_ckpts),
        "ckpt_torn": len(torn_ckpts),
        "last_checkpoint_step": final_end.get("last_checkpoint_step"),
        "wall_s": round(wall, 3),
    }
    if save_h:
        line["ckpt_save_ms_p50"] = save_h.get("p50")
        line["ckpt_save_ms_max"] = save_h.get("max")
    gp = final_end.get("goodput") or {}
    if gp.get("goodput_frac") is not None:
        # the final life's wall-clock classification (run_end.goodput)
        line["goodput_frac"] = round(gp["goodput_frac"], 4)
    if hang_at >= 0:
        # from the artifact, not the life summaries: the hanging life is
        # the one the injected crash kills before it writes its summary
        try:
            with open(env["PT_HANG_BLACKBOX"]) as f:
                line["hang_trips"] = (json.load(f).get("state", {})
                                      .get("training_watchdog", {})
                                      or {}).get("trips", 0)
        except (OSError, ValueError):
            line["hang_trips"] = 0
    if losses:
        line["loss_first"] = losses[0][1]
        line["loss_last"] = losses[-1][1]
    if peak_live is not None:
        line["memory"] = {"peak_live_gib": round(peak_live / 2**30, 4)}
    if smoke:
        line["note"] = "cpu smoke; the hardware soak row needs the chip"

    guard = _load_perf_guard()
    baseline = guard.last_good(guard._default_store(), "soak",
                               fresh=line, match=guard.config_match(line))
    verdict = guard.evaluate(line, baseline,
                             hardware=None if not smoke else False)
    checks.extend(verdict["checks"])
    line["guard"] = verdict
    line["checks"] = [{k: c[k] for k in ("name", "ok")} for c in checks]
    ok = all(c["ok"] for c in checks)
    line["ok"] = ok

    if not smoke:
        try:
            from paddle_tpu.utils import measurements as meas

            extra = {k: line[k] for k in (
                "steps", "batch", "lives", "skipped_batches",
                "ckpt_saves", "ckpt_save_ms_p50", "goodput_frac",
                "wall_s") if k in line}
            meas.record("soak", value, "samples/s", extra=extra)
        except Exception as e:  # noqa: BLE001 — persist must not gate
            print(f"soak: measurement persist failed: {e}",
                  file=sys.stderr)

    for c in checks:
        mark = "ok  " if c["ok"] else "FAIL"
        print(f"  [{mark}] {c['name']:<16} {c.get('detail', '')}",
              flush=True)
    print(json.dumps(line), flush=True)
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
