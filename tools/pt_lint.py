#!/usr/bin/env python
"""Invariant lint over the tree (rules PTL001–PTL005).

    python tools/pt_lint.py [paths...] [--json] [--select PTL001]

Thin launcher for ``paddle_tpu.analysis.cli`` (also installed as the
``pt-lint`` console entry) that works from any cwd — and, like
``tools/perf_guard.py``, without importing the package (so no jax):
the analysis modules are loaded straight off the source tree. Rule
catalog + incident history: ``docs/STATIC_ANALYSIS.md``. The tier-1
clean-tree gate lives in ``tests/test_static_analysis.py``.
"""
from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS = os.path.join(_REPO, "paddle_tpu", "analysis")
if _ANALYSIS not in sys.path:
    sys.path.insert(0, _ANALYSIS)

import cli  # noqa: E402  — paddle_tpu/analysis/cli.py, package-free

if __name__ == "__main__":
    # default scope: the repo this script lives in, not the cwd
    os.chdir(_REPO)
    sys.exit(cli.main())
