"""Generate docs/OP_COVERAGE.md — the audit of every op in the reference's
YAML registry (`paddle/phi/api/yaml/ops.yaml` + `legacy_ops.yaml`, the
"single source of truth" SURVEY §2.1 calls the best part of the design)
against this framework's public surface.

Usage:  python tools/gen_op_coverage.py [--reference /root/reference]

Statuses:
  implemented  — a public paddle_tpu function/method covers the op
                 (auto-discovered by name, or via the ALIASES table when
                 the python-surface name differs from the kernel name)
  delegated    — the op's role is intentionally played by XLA or another
                 part of the TPU design (fusion ops, memcpy, layout)
  excluded     — GPU-/PS-/legacy-specific; listed with rationale
  absent       — a real gap (counts against coverage)
"""
from __future__ import annotations

import argparse
import importlib
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# op name -> "module:attr" on the paddle_tpu surface
ALIASES = {
    # interpolation family -> one interpolate entry point
    "bicubic_interp": "paddle_tpu.nn.functional:interpolate",
    "bilinear_interp": "paddle_tpu.nn.functional:interpolate",
    "linear_interp": "paddle_tpu.nn.functional:interpolate",
    "nearest_interp": "paddle_tpu.nn.functional:interpolate",
    "trilinear_interp": "paddle_tpu.nn.functional:interpolate",
    # fft kernels -> paddle_tpu.fft module
    "fft_c2c": "paddle_tpu.fft:fft",
    "fft_r2c": "paddle_tpu.fft:rfft",
    "fft_c2r": "paddle_tpu.fft:irfft",
    # attention
    "flash_attn": "paddle_tpu.nn.functional:scaled_dot_product_attention",
    "flash_attn_unpadded":
        "paddle_tpu.nn.functional:scaled_dot_product_attention",
    "memory_efficient_attention":
        "paddle_tpu.nn.functional:scaled_dot_product_attention",
    # naming differences
    "cross_entropy_with_softmax":
        "paddle_tpu.nn.functional:softmax_with_cross_entropy",
    "elementwise_pow": "paddle_tpu.tensor.math:pow",
    "mean_all": "paddle_tpu.tensor.math:mean",
    "reverse": "paddle_tpu.tensor.manipulation:flip",
    "split_with_num": "paddle_tpu.tensor.manipulation:split",
    "repeat_interleave_with_tensor_index":
        "paddle_tpu.tensor.manipulation:repeat_interleave",
    "uniform_inplace": "paddle_tpu.tensor.random:uniform_",
    "p_norm": "paddle_tpu.tensor.linalg:norm",
    "matrix_rank_tol": "paddle_tpu.tensor.linalg:matrix_rank",
    "shape": "paddle_tpu.framework.core:Tensor.shape",
    "fill": "paddle_tpu.tensor.manipulation:fill",
    "full_int_array": "paddle_tpu.tensor.creation:full",
    "full_batch_size_like": "paddle_tpu.tensor.creation:full_like",
    "assign_value_": "paddle_tpu.tensor.creation:assign",
    "assign_out_": "paddle_tpu.tensor.creation:assign",
    "warpctc": "paddle_tpu.nn.functional:ctc_loss",
    "truncated_gaussian_random": "paddle_tpu.nn.initializer:TruncatedNormal",
    "gaussian": "paddle_tpu.tensor.random:normal",
    "pool2d": "paddle_tpu.nn.functional:avg_pool2d",
    "pool3d": "paddle_tpu.nn.functional:avg_pool3d",
    "max_pool2d_with_index": "paddle_tpu.nn.functional:max_pool2d",
    "max_pool3d_with_index": "paddle_tpu.nn.functional:max_pool3d",
    "unpool": "paddle_tpu.nn.functional:max_unpool2d",
    "unpool3d": "paddle_tpu.nn.functional:max_unpool3d",
    "squared_l2_norm": "paddle_tpu.tensor.math:squared_l2_norm",
    "clip_by_norm": "paddle_tpu.tensor.math:clip_by_norm",
    "frobenius_norm": "paddle_tpu.tensor.math:frobenius_norm",
    "depthwise_conv2d": "paddle_tpu.nn.functional:conv2d",
    "depthwise_conv2d_transpose": "paddle_tpu.nn.functional:conv2d_transpose",
    "check_numerics": "paddle_tpu.amp.debugging:check_numerics",
    "check_finite_and_unscale_": "paddle_tpu.amp.grad_scaler:GradScaler",
    "update_loss_scaling_": "paddle_tpu.amp.grad_scaler:GradScaler",
    # geometric family (segment_pool backs all segment_* python APIs)
    "segment_pool": "paddle_tpu.geometric:segment_sum",
    "send_u_recv": "paddle_tpu.geometric:send_u_recv",
    "send_ue_recv": "paddle_tpu.geometric:send_ue_recv",
    "send_uv": "paddle_tpu.geometric:send_uv",
    "reindex_graph": "paddle_tpu.geometric:reindex_graph",
    "weighted_sample_neighbors":
        "paddle_tpu.geometric:weighted_sample_neighbors",
    # signal
    "frame": "paddle_tpu.signal:frame",
    "overlap_add": "paddle_tpu.signal:overlap_add",
    # vision/detection
    "box_coder": "paddle_tpu.vision.ops:box_coder",
    "prior_box": "paddle_tpu.vision.ops:prior_box",
    "yolo_box": "paddle_tpu.vision.ops:yolo_box",
    "nms": "paddle_tpu.vision.ops:nms",
    "matrix_nms": "paddle_tpu.vision.ops:matrix_nms",
    "multiclass_nms3": "paddle_tpu.vision.ops:multiclass_nms",
    "generate_proposals": "paddle_tpu.vision.ops:generate_proposals",
    "distribute_fpn_proposals":
        "paddle_tpu.vision.ops:distribute_fpn_proposals",
    "roi_align": "paddle_tpu.vision.ops:roi_align",
    "roi_pool": "paddle_tpu.vision.ops:roi_pool",
    "psroi_pool": "paddle_tpu.vision.ops:psroi_pool",
    "deformable_conv": "paddle_tpu.vision.ops:deform_conv2d",
    "decode_jpeg": "paddle_tpu.vision.ops:decode_jpeg",
    "hsigmoid_loss": "paddle_tpu.nn.functional:hsigmoid_loss",
    "huber_loss": "paddle_tpu.nn.functional:huber_loss",
    "edit_distance": "paddle_tpu.nn.functional:edit_distance",
    "gather_tree": "paddle_tpu.nn.functional:gather_tree",
    "temporal_shift": "paddle_tpu.nn.functional:temporal_shift",
    "thresholded_relu": "paddle_tpu.nn.functional:thresholded_relu",
    "sigmoid_cross_entropy_with_logits":
        "paddle_tpu.nn.functional:binary_cross_entropy_with_logits",
    "class_center_sample": "paddle_tpu.nn.functional:class_center_sample",
    "margin_cross_entropy": "paddle_tpu.distributed.fleet.meta_parallel:"
                            "ParallelCrossEntropy",
    "diag_embed": "paddle_tpu.tensor.manipulation:diag_embed",
    "fill_diagonal": "paddle_tpu.tensor.manipulation:fill_diagonal",
    "fill_diagonal_tensor":
        "paddle_tpu.tensor.manipulation:fill_diagonal_tensor",
    "inverse": "paddle_tpu.tensor.math:inverse",
    "logit": "paddle_tpu.tensor.math:logit",
    "polygamma": "paddle_tpu.tensor.math:polygamma",
    "renorm": "paddle_tpu.tensor.math:renorm",
    "i0e": "paddle_tpu.tensor.math:i0e",
    "i1": "paddle_tpu.tensor.math:i1",
    "i1e": "paddle_tpu.tensor.math:i1e",
    "lu_unpack": "paddle_tpu.tensor.linalg:lu_unpack",
    "all": "paddle_tpu.tensor.logic:all",
    "any": "paddle_tpu.tensor.logic:any",
    "copy_to": "paddle_tpu.framework.core:Tensor.to",
    "memcpy_d2h": "paddle_tpu.framework.core:Tensor.numpy",
    "memcpy_h2d": "paddle_tpu.framework.core:to_tensor",
    "rnn": "paddle_tpu.nn.layer.rnn:RNN",
    "sync_batch_norm_": "paddle_tpu.nn.layer.norm:SyncBatchNorm",
    "embedding_grad_dense": "paddle_tpu.nn.functional:embedding",
    "viterbi_decode": "paddle_tpu.text:viterbi_decode",
    "average_accumulates_": "paddle_tpu.incubate:ModelAverage",
}

DELEGATED = {
    "coalesce_tensor": "gradient fusion is XLA's job under the whole-step "
                       "compiled TrainStep (SURVEY §2.6 TPU-build)",
    "fused_adam_": "optimizer fusion falls out of the single compiled "
                   "train step (jit/train_step.py)",
    "merged_adam_": "same — XLA fuses the per-param update loop",
    "merged_momentum_": "same — XLA fuses the per-param update loop",
    "fused_softmax_mask_upper_triangle":
        "XLA fuses mask+softmax; the flash-attention Pallas kernel covers "
        "the fused-attention case",
    "trans_layout": "XLA owns layout assignment on TPU",
    "npu_identity": "device-specific identity; PJRT handles placement",
    "merge_selected_rows": "no SelectedRows type — sparse grads are "
                           "IndexedSlices-free by design (dense scatter)",
    "feed_with_place": "executor feed plumbing; jit arguments serve this "
                       "role (static/__init__.py)",
    "shaddow_output": "executor fetch plumbing; jit outputs serve this role",
}

EXCLUDED = {
    "llm_int8_matmul": "CUDA int8 GEMM path; TPU quantization rides the "
                       "quantization/ QAT-PTQ module (bf16/int8 via XLA)",
    "matmul_int8": "same",
    "weight_only_matmul": "same",
    "quant_for_compress": "weight-only-quant packing for the above",
    "warprnnt": "external warp-rnnt CUDA library binding (RNN-T loss); "
                "documented exclusion (README)",
}


def collect_surface():
    import paddle_tpu as pt

    mods = [
        "", "tensor.math", "tensor.creation", "tensor.manipulation",
        "tensor.logic", "tensor.linalg", "tensor.random", "tensor.search",
        "tensor.stat", "tensor.einsum", "nn.functional", "fft", "signal",
        "geometric", "vision.ops", "incubate.nn", "sparse", "text",
        "distribution", "metric", "optimizer", "nn", "amp", "quantization",
        "nn.initializer",
    ]
    names = {}
    for m in mods:
        try:
            mod = importlib.import_module(
                "paddle_tpu" + ("." + m if m else ""))
        except Exception:
            continue
        for n in dir(mod):
            if not n.startswith("_"):
                names.setdefault(n.lower(), f"paddle_tpu.{m}" if m else
                                 "paddle_tpu")
    return names


def parse_ops(reference):
    out = []
    for fname in ("ops.yaml", "legacy_ops.yaml"):
        path = Path(reference) / "paddle/phi/api/yaml" / fname
        for line in path.read_text().splitlines():
            m = re.match(r"- op\s*:\s*(\w+)", line)
            if m:
                out.append((m.group(1), fname))
    return out


def resolve_alias(spec):
    mod, _, attr = spec.partition(":")
    try:
        m = importlib.import_module(mod)
        obj = m
        for part in attr.split("."):
            obj = getattr(obj, part)
        return True
    except Exception:
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    args = ap.parse_args()

    surface = collect_surface()
    ops = parse_ops(args.reference)

    rows = []
    counts = {"implemented": 0, "delegated": 0, "excluded": 0, "absent": 0}
    for op, src in sorted(ops):
        base = op.rstrip("_")
        if op in ALIASES or base in ALIASES:
            spec = ALIASES.get(op, ALIASES.get(base))
            ok = resolve_alias(spec)
            status = "implemented" if ok else "absent"
            where = spec.replace(":", ".") if ok else \
                f"alias target missing: {spec}"
        elif base in surface or base.replace("_", "") in surface:
            key = base if base in surface else base.replace("_", "")
            status, where = "implemented", f"{surface[key]}.{base}"
        elif op in DELEGATED or base in DELEGATED:
            status = "delegated"
            where = DELEGATED.get(op, DELEGATED.get(base))
        elif op in EXCLUDED or base in EXCLUDED:
            status = "excluded"
            where = EXCLUDED.get(op, EXCLUDED.get(base))
        else:
            status, where = "absent", ""
        counts[status] += 1
        rows.append((op, src, status, where))

    total = len(rows)
    cov = counts["implemented"] + counts["delegated"]
    lines = [
        "# OP_COVERAGE — audit vs the reference YAML op registry",
        "",
        f"Generated by `tools/gen_op_coverage.py` against "
        f"`paddle/phi/api/yaml/ops.yaml` (+ `legacy_ops.yaml`): "
        f"**{total} ops**.",
        "",
        f"| status | count | share |",
        f"|---|---|---|",
    ]
    for k in ("implemented", "delegated", "excluded", "absent"):
        lines.append(f"| {k} | {counts[k]} | {counts[k] / total:.1%} |")
    lines += [
        "",
        f"**Coverage (implemented + delegated): {cov}/{total} = "
        f"{cov / total:.1%}** (target ≥80%; excluded ops are "
        f"GPU/PS-specific with rationale below, absent ops are real gaps).",
        "",
        "| op | yaml | status | where / why |",
        "|---|---|---|---|",
    ]
    for op, src, status, where in rows:
        lines.append(f"| `{op}` | {src.split('.')[0]} | {status} | {where} |")
    out = REPO / "docs" / "OP_COVERAGE.md"
    out.parent.mkdir(exist_ok=True)
    out.write_text("\n".join(lines) + "\n")
    print(f"{out}: {counts} -> coverage "
          f"{cov}/{total} = {cov / total:.1%}")
    absent = [op for op, _, st, _ in rows if st == "absent"]
    if absent:
        print("absent:", " ".join(absent))


if __name__ == "__main__":
    sys.path.insert(0, str(REPO))
    main()
