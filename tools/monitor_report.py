#!/usr/bin/env python
"""Join a monitor StepLogger JSONL run with a profiler chrome trace into
one summary table.

    python tools/monitor_report.py run.jsonl [--trace trace.json] [--top 10]
    python tools/monitor_report.py run.jsonl --trace trace.json --spans
    python tools/monitor_report.py run.jsonl --bench bench.log
    python tools/monitor_report.py run.jsonl --metrics metrics.txt

Sections: run overview (steps, wall, loss, ips), counter totals, the async
pipeline (prefetch staging/starvation, AsyncStepper bound waits, hapi host
syncs, host_blocked_ms_per_step), the AOT executable cache (hit rate,
compile-ms saved/paid, tier + serialization latencies — from the
`jit/exec_cache_*` metrics or a bench line's `telemetry.exec_cache`),
the Pallas kernel account (`pallas/*` engagement + `search/*` harness
counters, and a bench line's `kernels` engagement map —
docs/KERNELS.md), device memory (peak HBM / live-census
peaks from the memory observatory, per-executable breakdown), the perf
guard verdict (the `guard` sub-object bench.py embeds — rendered from the
run_end line, or from a bench log via `--bench`), retrace timeline (which
step retraced — the recompile smoking gun), tunnel-sync latency
percentiles, and — when a chrome trace from
`paddle_tpu.profiler.Profiler.export` (or `monitor.export_spans`) is
given — the top dispatched ops and the monitor counter tracks found on
the timeline, so one report correlates the JSONL run with the trace.

The "SLO / live windows" section renders the live telemetry plane
(``paddle_tpu/monitor/live.py`` — docs/OBSERVABILITY.md): streaming
sketch percentiles (TTFT/TPOT/queue-wait/accept-rate), the armed
``PT_SLO_*`` targets, fast/slow burn-rate state, and the breach count —
from the run_end line's ``live`` snapshot, or from a SAVED ``/metrics``
exposition (``--metrics FILE``, e.g. ``curl :9100/metrics > f``) where
it also derives the per-replica dispatch share the router's
``{replica=}`` labels carry.

`--spans` adds the host-blocked-time attribution pass: the flight
recorder's `ph:"X"` spans (`paddle_tpu/monitor/spans.py`) are decomposed
per StepLogger step window into {sync, fence_wait, prefetch_starvation,
compile, dispatch, other} by a priority sweep (nested spans — a
device_sync inside an AsyncStepper fence — count once, under the outer
category), which is exactly the breakdown that explains a bench line's
`host_blocked_ms_per_step`.

Pure stdlib: runs anywhere the artifacts land, no jax import.
"""
from __future__ import annotations

import argparse
import json
import sys

# attribution buckets in priority order (an overlapping slice counts under
# the earliest matching category) — mirrors
# paddle_tpu/monitor/spans.py:ATTRIBUTION_CATEGORIES, restated here so the
# tool stays stdlib-only with no package import
ATTRIBUTION_CATEGORIES = (
    "fence_wait", "prefetch_starvation", "compile", "dispatch", "sync",
)


def load_jsonl(path):
    """(step_lines, begin, end) from a StepLogger file; tolerates junk
    lines (a crashed run must still be reportable)."""
    steps, begin, end = [], None, None
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                line = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(line, dict):
                continue
            if "step" in line:
                steps.append(line)
            elif line.get("event") == "run_begin" and begin is None:
                begin = line
            elif line.get("event") == "run_end":
                end = line  # last one wins (appended runs)
    return steps, begin, end


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0


def _table(rows, widths):
    out = []
    for row in rows:
        out.append("".join(
            f"{str(c):<{w}}" if i == 0 else f"{str(c):>{w}}"
            for i, (c, w) in enumerate(zip(row, widths))))
    return out


def _counter_totals(steps, end):
    if end and end.get("totals", {}).get("counters"):
        return dict(end["totals"]["counters"])
    totals = {}
    for s in steps:
        for k, v in s.get("counters", {}).items():
            totals[k] = totals.get(k, 0) + v
    return totals


def _fmt_gib(n_bytes):
    return f"{n_bytes / 2**30:.3f} GiB"


def find_bench_line(text):
    """tools/perf_guard.py:find_bench_line — THE one scanner for bench
    lines (its contract) — loaded from the sibling file so the scan rule
    cannot drift between the guard, hwbench, and this report. Still
    stdlib-only: tools/ is not a package, so load by path."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "perf_guard.py")
    spec = importlib.util.spec_from_file_location("perf_guard", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.find_bench_line(text)


def render_guard(guard, out, source=""):
    """The perf-guard verdict sub-object (`tools/perf_guard.py` schema:
    {ok, checks: [{name, ok, detail}], compared, baseline?})."""
    out.append("")
    out.append(f"-- perf guard{source} --")
    for c in guard.get("checks", []):
        mark = "ok  " if c.get("ok") else "FAIL"
        out.append(f"  [{mark}] {c.get('name', '?'):<12} "
                   f"{c.get('detail', '')}")
    base = guard.get("baseline")
    if base:
        out.append(f"  baseline: {base.get('value')} "
                   f"@ {base.get('commit', '?')} ({base.get('timestamp')})")
    elif not guard.get("compared"):
        out.append("  (no hardware baseline compared)")
    out.append("verdict: " + ("PASS" if guard.get("ok")
                              else "REGRESSION — do not trust/land "
                                   "this number"))


def render_exec_cache(out, totals=None, hists=None, bench_tel=None,
                      source=""):
    """The AOT executable cache's account (``jit/exec_cache_*`` counters
    and histograms from a monitor run, and/or the ``telemetry.exec_cache``
    stats sub-object a bench line carries): hit rate and the compile
    wall-time the cache saved."""
    totals, hists = totals or {}, hists or {}
    tel = bench_tel or {}
    ec = tel.get("exec_cache") or {}
    hits = totals.get("jit/exec_cache_hit", 0) or (
        ec.get("mem_hits", 0) + ec.get("disk_hits", 0))
    misses = totals.get("jit/exec_cache_miss", 0) or ec.get("misses", 0)
    # a cache-off monitor run still carries compile_ms_total — the
    # cold-vs-warm A/B needs the cost line even with zero cache traffic
    if not (hits or misses or ec or "compile_ms_total" in tel):
        return
    out.append("")
    out.append(f"-- exec cache (AOT executables){source} --")
    line = f"hits {hits}   misses {misses}"
    if hits or misses:
        line += f"   hit rate {hits / (hits + misses):.2f}"
    out.append(line)
    if ec:
        out.append(f"  tiers: mem {ec.get('mem_hits', 0)}   "
                   f"disk {ec.get('disk_hits', 0)}   "
                   f"serialized {ec.get('serialized', 0)}   "
                   f"errors {ec.get('errors', 0)}"
                   + (f"   dir {ec['dir']}" if ec.get("dir") else ""))
    saved = hists.get("jit/exec_cache_saved_ms")
    saved_ms = (saved["sum"] if saved
                else ec.get("compile_ms_saved") or 0.0)
    if saved_ms:
        out.append(f"compile ms saved (warm hits): {saved_ms:.0f}")
    if "compile_ms_total" in tel:
        out.append(f"compile ms paid this run: {tel['compile_ms_total']}"
                   + (f" ({tel.get('compile_count')} compile(s))"
                      if tel.get("compile_count") is not None else ""))
    for name, label in (("jit/exec_cache_deserialize_ms", "deserialize"),
                        ("jit/exec_cache_serialize_ms", "serialize")):
        h = hists.get(name)
        if h:
            out.append(f"  {label} ms: p50 {h['p50']}   max {h['max']} "
                       f"({h['count']} file(s))")


def render_serving(out, totals=None, hists=None, gauges=None, source=""):
    """The continuous-batching engine's account (``serving/*`` counters
    from ``paddle_tpu/serving/engine.py`` — docs/SERVING.md): lane
    traffic (admits / finished-lane evictions / capacity preemptions),
    prefill-vs-decode step mix, and the queue-wait histogram (TTFT's
    scheduler-side component)."""
    totals, hists, gauges = totals or {}, hists or {}, gauges or {}
    if not any(k.startswith("serving/") for k in
               (*totals, *hists, *gauges)):
        return
    out.append("")
    out.append(f"-- serving (continuous batching){source} --")
    admits = totals.get("serving/admits", 0)
    evictions = totals.get("serving/evictions", 0)
    preempts = totals.get("serving/preemptions", 0)
    out.append(f"admits {admits}   evictions (finished) {evictions}   "
               f"preemptions {preempts} "
               f"(requeued {totals.get('serving/requeues', 0)})")
    pre = totals.get("serving/prefill_steps", 0)
    dec = totals.get("serving/decode_steps", 0)
    ver = totals.get("serving/verify_steps", 0)
    line = f"prefill chunks {pre}   decode steps {dec}"
    if ver:
        line += f"   verify steps {ver}"
    if dec or ver:
        line += f"   ({pre / (dec + ver):.2f} prefill/decode ratio)"
    out.append(line)
    hit = totals.get("serving/prefix_hit_tokens", 0)
    miss = totals.get("serving/prefix_miss_tokens", 0)
    if hit or miss:
        out.append(f"prefix cache: {hit} cached + {miss} prefilled "
                   f"context tokens ({hit / (hit + miss):.0%} hit rate)")
    # speculative decoding (serving/speculative.py — docs/SERVING.md):
    # accept rate over proposed draft tokens + the tokens-per-round
    # multiplier the verify step bought
    prop = totals.get("serving/spec_proposed_tokens", 0)
    acc = totals.get("serving/spec_accepted_tokens", 0)
    bon = totals.get("serving/spec_bonus_tokens", 0)
    if prop or ver:
        line = f"speculative: {prop} proposed"
        if prop:
            line += f"   {acc} accepted ({acc / prop:.0%} accept rate)"
        line += f"   {bon} bonus"
        out.append(line)
        decoded = totals.get("serving/decoded_tokens", 0)
        if decoded and (dec + ver):
            out.append(f"tokens per decode step: "
                       f"{decoded / (dec + ver):.2f} "
                       f"({decoded} tokens / {dec + ver} rounds)")
        h = (hists or {}).get("serving/spec_accept_rate")
        if h:
            out.append(f"  accept rate per round: p50 {h['p50']}   "
                       f"p95 {h['p95']}   max {h['max']} "
                       f"({h['count']} round(s))")
    # int8 KV pool (docs/SERVING.md "int8 KV"): quantize-on-write
    # totals + the pool's resident bytes — counters only move when
    # kv_int8 is on, so a bf16 run renders nothing here
    qw = totals.get("serving/kv_quant_writes", 0)
    qt = totals.get("serving/kv_quant_tokens", 0)
    pool_b = gauges.get("serving/kv_pool_bytes")
    if qw or qt or pool_b:
        line = "kv pool: int8" if (qw or qt) else "kv pool:"
        if pool_b is not None:
            line += f"   {pool_b / 2**20:.1f} MiB resident"
        line += (f"   {qw} quantizing write(s)   "
                 f"{qt} token(s) quantized")
        out.append(line)
    lanes = gauges.get("serving/lanes_occupied")
    blocks = gauges.get("serving/free_blocks")
    shared = gauges.get("serving/shared_blocks")
    cold = gauges.get("serving/cold_blocks")
    if any(v is not None for v in (lanes, blocks, shared, cold)):
        parts = []
        if lanes is not None:
            parts.append(f"lanes occupied (last): {lanes:g}")
        if blocks is not None:
            parts.append(f"free KV blocks (last): {blocks:g}")
        if shared is not None:
            parts.append(f"shared (last): {shared:g}")
        if cold is not None:
            parts.append(f"cold-cached (last): {cold:g}")
        out.append("   ".join(parts))
    w = hists.get("serving/queue_wait_ms")
    if w:
        out.append(f"queue wait ms: p50 {w['p50']}   p95 {w['p95']}   "
                   f"max {w['max']} ({w['count']} admit(s))")


def render_router(out, totals=None, gauges=None, source=""):
    """The multi-replica router's account (``router/*`` counters from
    ``paddle_tpu/serving/router.py`` — docs/SERVING.md "Replica
    router"): dispatch volume with the affinity hit/miss split,
    drain traffic (redispatches after a replica death), and the
    per-replica dispatch + lane-occupancy spread."""
    totals, gauges = totals or {}, gauges or {}
    if not any(k.startswith("router/") for k in (*totals, *gauges)):
        return
    out.append("")
    out.append(f"-- serving router (replica dispatch){source} --")
    disp = totals.get("router/dispatches", 0)
    hits = totals.get("router/affinity_hits", 0)
    misses = totals.get("router/affinity_misses", 0)
    line = f"dispatches {disp}"
    if hits or misses:
        line += (f"   affinity hits {hits} / misses {misses} "
                 f"({hits / (hits + misses):.0%} hit rate)")
    out.append(line)
    redisp = totals.get("router/redispatches", 0)
    dead = totals.get("router/dead_replicas", 0)
    if redisp or dead:
        out.append(f"dead replicas {dead}   redispatched (drained) "
                   f"requests {redisp}")
    per = sorted((k.rsplit("/", 1)[1], v) for k, v in totals.items()
                 if k.startswith("router/dispatches/"))
    for idx, n in per:
        parts = [f"  replica {idx:<3} dispatches {n}"]
        lanes = gauges.get(f"router/lanes/{idx}")
        queued = gauges.get(f"router/queued/{idx}")
        if lanes is not None:
            parts.append(f"lanes (last) {lanes:g}")
        if queued is not None:
            parts.append(f"queued (last) {queued:g}")
        out.append("   ".join(parts))


def render_slo(out, live=None, source=""):
    """The live telemetry plane's account (the run_end line's ``live``
    sub-object — ``monitor/live.py:snapshot()``): streaming sketch
    percentiles per metric, the armed SLO targets, burn-rate state
    (fast/slow windows), and the breach count."""
    if not live:
        return
    out.append("")
    out.append(f"-- SLO / live windows{source} --")
    slo = live.get("slo") or {}
    out.append(f"engine steps {live.get('steps', 0)}   windows: fast "
               f"{slo.get('fast_window_steps', '?')} / slow "
               f"{slo.get('slow_window_steps', '?')} steps")
    targets = {k: v for k, v in (slo.get("targets") or {}).items() if v}
    if targets:
        out.append("targets: " + "   ".join(
            f"{k} {v:g} ms" for k, v in sorted(targets.items())))
    else:
        out.append("targets: none armed (PT_SLO_TTFT_MS_P99 / "
                   "PT_SLO_TPOT_MS_P99)")
    line = f"breaches: {slo.get('breaches', 0)}"
    if slo.get("fleet_breaches") is not None:
        line += f"   fleet total: {slo['fleet_breaches']}"
    out.append(line)
    last = slo.get("last_burn") or {}
    worst = slo.get("worst_burn") or {}
    for metric in sorted(set(last) | set(worst)):
        lb = last.get(metric) or {}
        out.append(f"  {metric}: burn fast {lb.get('fast', '-')} / "
                   f"slow {lb.get('slow', '-')}   worst "
                   f"{worst.get(metric, '-')} "
                   f"(fires at {slo.get('burn_fast_threshold', 14)}/"
                   f"{slo.get('burn_slow_threshold', 6)})")
    sketches = live.get("sketches") or {}
    if sketches:
        rows = [("metric", "count", "p50", "p90", "p99")]
        for name, s in sorted(sketches.items()):
            rows.append((name, s.get("count", 0), s.get("p50", "-"),
                         s.get("p90", "-"), s.get("p99", "-")))
        out.extend(_table(rows, (18, 8, 12, 12, 12)))
    if live.get("replicas_remote"):
        out.append("remote replicas merged: "
                   + ", ".join(str(r) for r in live["replicas_remote"]))


def parse_openmetrics(text):
    """``{name: [(labels_dict, value)]}`` from a saved ``/metrics``
    exposition (``monitor/exporter.py`` format). Comment/TYPE/EOF lines
    are skipped; unparseable lines are tolerated (a truncated scrape
    must still be reportable)."""
    series = {}
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        head, _, val = ln.rpartition(" ")
        try:
            value = float(val)
        except ValueError:
            continue
        name, labels = head, {}
        if "{" in head and head.endswith("}"):
            name, _, lab = head.partition("{")
            for part in lab[:-1].split(","):
                k, eq, v = part.partition("=")
                if eq:
                    labels[k.strip()] = v.strip().strip('"')
        series.setdefault(name, []).append((labels, value))
    return series


def render_metrics_file(series, out, source=""):
    """The SLO/live view of a saved ``/metrics`` exposition: live sketch
    summaries, targets + burn state, breach total, and the per-replica
    dispatch share from the router's ``{replica=}`` labels."""
    out.append("")
    out.append(f"-- SLO / live windows (/metrics){source} --")

    def _one(name, default=None):
        samples = series.get(name) or []
        return samples[0][1] if samples else default

    breaches = _one("pt_slo_breaches_total")
    if breaches is not None:
        out.append(f"breaches: {breaches:g}")
    targets = series.get("pt_slo_target_ms") or []
    if targets:
        out.append("targets: " + "   ".join(
            f"{lb.get('metric', '?')} {v:g} ms"
            for lb, v in sorted(targets,
                                key=lambda s: s[0].get("metric", ""))))
    burns = series.get("pt_slo_burn_rate") or []
    if burns:
        by_metric = {}
        for lb, v in burns:
            by_metric.setdefault(lb.get("metric", "?"), {})[
                lb.get("window", "?")] = v
        for metric in sorted(by_metric):
            w = by_metric[metric]
            out.append(f"  {metric}: burn fast {w.get('fast', '-')} / "
                       f"slow {w.get('slow', '-')}")
    live_names = sorted(
        n[:-len("_count")] for n in series
        if n.startswith("pt_live_") and n.endswith("_count"))
    if live_names:
        rows = [("metric", "count", "p50", "p90", "p99")]
        for base in live_names:
            q = {lb.get("quantile"): v
                 for lb, v in series.get(base, [])}
            rows.append((base[len("pt_live_"):],
                         f"{_one(base + '_count', 0):g}",
                         q.get("0.5", "-"), q.get("0.9", "-"),
                         q.get("0.99", "-")))
        out.extend(_table(rows, (18, 8, 12, 12, 12)))
    disp = [(lb.get("replica", "?"), v) for lb, v in
            series.get("pt_router_dispatches_total", [])
            if lb.get("replica") is not None]
    total_disp = sum(v for _, v in disp)
    if disp and total_disp:
        out.append("dispatch share:")
        for idx, v in sorted(disp):
            out.append(f"  replica {idx:<3} {v:g} "
                       f"({v / total_disp:.0%})")


def render_kernels(out, totals=None, gauges=None, bench_kernels=None,
                   source=""):
    """The Pallas kernel account (``pallas/*`` engagement counters and
    ``search/*`` harness counters from ``ops/pallas/search.py`` —
    docs/KERNELS.md): how often dispatch chose a kernel vs the XLA
    composite (per family), and what the last search run did."""
    totals, gauges = totals or {}, gauges or {}
    have = any(k.startswith(("pallas/", "search/"))
               for k in (*totals, *gauges))
    if not have and not bench_kernels:
        return
    out.append("")
    out.append(f"-- pallas kernels (engagement + search){source} --")
    eng = totals.get("pallas/engaged", 0)
    fb = totals.get("pallas/fallback_composite", 0)
    if eng or fb:
        line = f"engaged {eng}   composite fallbacks {fb}"
        if eng or fb:
            line += f"   (engage rate {eng / (eng + fb):.2f})"
        out.append(line)
        fams = sorted({k.rsplit("/", 1)[1] for k in totals
                       if k.startswith(("pallas/engaged/",
                                        "pallas/fallback/"))})
        for fam in fams:
            fe = totals.get(f"pallas/engaged/{fam}", 0)
            ff = totals.get(f"pallas/fallback/{fam}", 0)
            out.append(f"  {fam:<20} engaged {fe}   composite {ff}")
    timed = totals.get("search/candidates_timed", 0)
    rejects = totals.get("search/rejects", 0)
    if timed or rejects:
        out.append(f"search: candidates timed {timed}   rejects "
                   f"{rejects} (parity/compile pre-filter)")
    for name in sorted(gauges):
        if name.startswith("search/best_ratio/"):
            fam = name.split("search/best_ratio/", 1)[1]
            out.append(f"  best ratio {fam}: {gauges[name]:g} "
                       f"(>1 = kernel faster than composite)")
    if bench_kernels:
        line = ", ".join(f"{k}={'engaged' if v else 'composite'}"
                         for k, v in sorted(bench_kernels.items()))
        out.append(f"bench engagement: {line}")


def render_planner(out, totals=None, gauges=None, source=""):
    """The sharding planner's account (``planner/*`` counters from
    ``paddle_tpu/autoshard/planner.py`` — docs/AUTOSHARD.md) plus the
    per-axis collective-bytes split the cost model is judged against."""
    totals = totals or {}
    gauges = gauges or {}
    axis_bytes = {k.rsplit("/", 1)[1]: v for k, v in totals.items()
                  if k.startswith("collective/bytes/")}
    if not (axis_bytes
            or any(k.startswith("planner/") for k in totals)):
        return
    out.append("")
    out.append(f"-- sharding planner{source} --")
    cand = totals.get("planner/candidates", 0)
    if cand:
        out.append(f"candidates judged: {cand}   infeasible "
                   f"{totals.get('planner/infeasible', 0)}   errors "
                   f"{totals.get('planner/errors', 0)}   plans emitted "
                   f"{totals.get('planner/plans', 0)}")
        w = gauges.get("planner/winner_est_step_ms")
        if w is not None:
            out.append(f"winner roofline est: {w:g} ms/step")
    if axis_bytes:
        total = totals.get("collective/bytes", sum(axis_bytes.values()))
        parts = "   ".join(f"{ax} {_fmt_bytes(v)}"
                           for ax, v in sorted(axis_bytes.items()))
        out.append(f"collective bytes by axis: {parts}"
                   + (f"   (aggregate {_fmt_bytes(total)})"
                      if total else ""))


def render_pipeline(out, totals=None, gauges=None, source=""):
    """The pipeline-parallel account (``pipeline/*`` counters from
    ``fleet/meta_parallel/.../pp_layers.py`` — ISSUE 15): schedule
    shape (microbatches, ticks), the fill/drain bubble fraction, and
    the analytically-attributed ppermute handoff bytes (the compiled
    stage ring is invisible to the eager collective counters)."""
    totals, gauges = totals or {}, gauges or {}
    if not any(k.startswith("pipeline/") for k in totals):
        return
    out.append("")
    out.append(f"-- pipeline (pp stages){source} --")
    fwd = totals.get("pipeline/forwards", 0)
    micro = totals.get("pipeline/microbatches", 0)
    ticks = totals.get("pipeline/ticks", 0)
    out.append(f"pipelined forwards {fwd}   microbatches {micro}   "
               f"schedule ticks {ticks}")
    bub = gauges.get("pipeline/bubble_frac")
    if bub is not None:
        out.append(f"bubble: {bub * 100:.1f}% of ticks "
                   f"(fill/drain — shrink with more microbatches)")
    p2p = totals.get("pipeline/p2p_bytes", 0)
    if p2p:
        out.append(f"p2p handoff: {_fmt_bytes(p2p)} "
                   f"(also attributed to collective/bytes/pp)")


def render_resilience(out, totals=None, hists=None, end=None, source=""):
    """The resilience runtime's account (``resilience/*`` counters from
    ``paddle_tpu/resilience`` — docs/RESILIENCE.md): checkpoint traffic
    (saves + the blocking-cost histogram the cadence planner budgets
    against), restores split by crash resumes, NaN batches skipped, and
    the last COMPLETE checkpoint step the run_end line names (what a
    relaunch will resume from)."""
    totals, hists, end = totals or {}, hists or {}, end or {}
    ckpt_step = end.get("last_checkpoint_step")
    if not any(k.startswith("resilience/") for k in (*totals, *hists)) \
            and ckpt_step is None:
        return
    out.append("")
    out.append(f"-- resilience (checkpoints + NaN policy){source} --")
    saves = totals.get("resilience/saves", 0)
    restores = totals.get("resilience/restores", 0)
    crash = totals.get("resilience/crash_resumes", 0)
    out.append(f"saves {saves}   restores {restores} "
               f"(crash resumes {crash})")
    w = hists.get("resilience/save_ms")
    if w:
        out.append(f"  save blocking ms: p50 {w['p50']}   p95 {w['p95']}   "
                   f"max {w['max']} ({w['count']} save(s))")
    skipped = totals.get("resilience/skipped_batches", 0)
    if skipped:
        out.append(f"NaN batches skipped: {skipped} (params/LR/step "
                   f"untouched per skip)")
    if ckpt_step is not None:
        out.append(f"last complete checkpoint: step {ckpt_step}"
                   + (" — what a relaunch resumes from"
                      if end.get("error") else ""))


GOODPUT_BUCKETS = (
    "productive_step", "compile", "checkpoint_save_blocking",
    "nan_replay_or_skip", "restore_resume", "input_wait", "other",
)

_GOODPUT_VERDICTS = {
    "productive_step": "healthy: productive stepping dominates the wall",
    "compile": "compile-bound: XLA compiles ate the wall — warm the "
               "exec cache (PT_EXEC_CACHE) or check for retrace churn",
    "checkpoint_save_blocking": "checkpoint-bound: blocking save cost "
                                "dominates — raise PT_CKPT_OVERHEAD_PCT "
                                "or check the save path's throughput",
    "nan_replay_or_skip": "numerics-bound: NaN replay/skip cycles ate "
                          "the wall — the data or LR is poisoning steps",
    "restore_resume": "restore-bound: checkpoint restore dominates "
                      "(expected only on short relaunched runs)",
    "input_wait": "input-bound: the loader starved fit — raise prefetch "
                  "depth / loader workers",
    "other": "mostly unclassified wall (host bookkeeping between "
             "ledgered regions)",
}


def render_goodput(out, gp, source=""):
    """The goodput ledger's "where did the time go" account
    (``monitor/goodput.py`` — docs/OBSERVABILITY.md "Training goodput
    plane"): every wall-clock second of the run classified into the
    telescoping buckets, the goodput fraction, and a verdict naming
    the dominant non-productive bucket."""
    if not gp or not isinstance(gp, dict):
        return
    buckets = gp.get("buckets") or {}
    wall = gp.get("wall_s")
    if wall is None:
        wall = sum(v for v in buckets.values()
                   if isinstance(v, (int, float)))
    out.append("")
    out.append(f"-- goodput (where did the time go){source} --")
    line = f"wall: {wall:.3f} s"
    if gp.get("steps") is not None:
        line += f"   steps: {gp['steps']}"
    if gp.get("nan_steps"):
        line += f"   nan steps: {gp['nan_steps']}"
    out.append(line)
    if buckets and wall > 0:
        rows = []
        for name in GOODPUT_BUCKETS:
            if name not in buckets:
                continue
            s = buckets[name]
            rows.append((name, f"{s:.3f} s", f"{s / wall * 100:5.1f}%"))
        for name in sorted(set(buckets) - set(GOODPUT_BUCKETS)):
            s = buckets[name]
            rows.append((name, f"{s:.3f} s", f"{s / wall * 100:5.1f}%"))
        out.extend(_table(rows, (26, 14, 10)))
        ssum = sum(buckets.values())
        out.append(f"buckets sum: {ssum:.3f} s "
                   + ("(telescopes exactly)" if ssum == wall
                      else f"vs wall {wall:.3f} s — LEDGER BROKEN"))
    frac = gp.get("goodput_frac")
    if frac is None and wall and buckets.get("productive_step") is not None:
        frac = buckets["productive_step"] / wall
    if frac is not None:
        out.append(f"goodput_frac: {frac:.4f} "
                   f"({frac * 100:.1f}% of wall was productive stepping)")
    if buckets and wall > 0:
        dom = max(buckets, key=lambda b: buckets[b])
        if buckets[dom] > 0.2 * wall and dom in _GOODPUT_VERDICTS:
            out.append(f"verdict: {_GOODPUT_VERDICTS[dom]}")


def _load_heartbeat_mod():
    """``paddle_tpu/monitor/heartbeat.py`` loaded by path (its
    module-level imports are stdlib-only by contract) so the fleet
    section's parsing + detectors cannot drift from the launcher's —
    and this tool stays importable with no jax on the box."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "paddle_tpu", "monitor", "heartbeat.py")
    spec = importlib.util.spec_from_file_location("pt_heartbeat", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_fleet(path):
    """A fleet view from either a ``fleet.json`` snapshot (the
    launcher's scraped artifact) or a heartbeat DIRECTORY (re-run the
    detectors offline over the raw JSONL — a postmortem needs no live
    launcher). Returns the ``FleetMonitor.status()`` dict shape."""
    import os

    if os.path.isdir(path):
        hb = _load_heartbeat_mod()
        by_rank = hb.read_heartbeats(path)
        workers = {}
        last_ts = {}
        for rank, lines in sorted(by_rank.items()):
            if not lines:
                continue
            newest = lines[-1]
            workers[str(rank)] = {
                k: newest.get(k) for k in
                ("step", "loss", "step_ms", "goodput", "metrics_port")}
            last_ts[rank] = newest.get("ts") or 0.0
        steps = [w["step"] for w in workers.values()
                 if w.get("step") is not None]
        now = max(last_ts.values()) if last_ts else 0.0
        return {
            "nprocs": len(by_rank) or None,
            "workers": workers,
            "fleet": {"min_step": min(steps) if steps else None,
                      "max_step": max(steps) if steps else None,
                      "step_ms": None},
            "verdicts": {
                "straggler": hb.detect_straggler(by_rank),
                "desync": hb.detect_desync(by_rank),
                # offline: judge silence against the newest beat anywhere
                # in the fleet, not this tool's wall clock
                "silent": hb.detect_silent(by_rank, now=now),
            },
            "postmortem": None,
            "offline": True,
        }
    with open(path) as f:
        return json.load(f)


def render_fleet(out, fleet, source=""):
    """The launcher fleet view (``FleetMonitor.status()`` — per-worker
    table, merged step_ms, and the three latched detector verdicts:
    straggler / dp desync / silent worker, each naming its rank)."""
    if not fleet:
        return
    out.append("")
    off = " [offline re-detect]" if fleet.get("offline") else ""
    out.append(f"-- fleet (launcher workers){source}{off} --")
    workers = fleet.get("workers") or {}
    fl = fleet.get("fleet") or {}
    head = f"workers reporting: {len(workers)}"
    if fleet.get("nprocs"):
        head += f" / {fleet['nprocs']}"
    if fl.get("min_step") is not None:
        head += (f"   step span: {fl['min_step']}..{fl['max_step']}"
                 + (f" (skew {fl['max_step'] - fl['min_step']})"
                    if fl["max_step"] != fl["min_step"] else ""))
    out.append(head)
    if workers:
        rows = [("rank", "step", "step_ms", "loss", "age_s", "gp%")]
        for rank in sorted(workers, key=lambda r: int(r)):
            w = workers[rank] or {}
            gp = w.get("goodput") or {}
            tot = sum(v for v in gp.values()
                      if isinstance(v, (int, float))) if gp else 0.0
            gpp = (f"{gp.get('productive_step', 0.0) / tot * 100:.0f}"
                   if tot > 0 else "-")
            rows.append((rank, w.get("step", "-"),
                         w.get("step_ms", "-"),
                         (f"{w['loss']:.4f}"
                          if isinstance(w.get("loss"), (int, float))
                          else "-"),
                         w.get("age_s", "-"), gpp))
        out.extend(_table(rows, (6, 8, 10, 12, 9, 6)))
    sk = fl.get("step_ms")
    if sk:
        out.append(f"fleet step_ms (merged sketch): p50 {sk.get('p50')}   "
                   f"p90 {sk.get('p90')}   p99 {sk.get('p99')} "
                   f"({sk.get('count')} step(s))")
    verdicts = fleet.get("verdicts") or {}
    strag = verdicts.get("straggler")
    if strag:
        out.append(f"STRAGGLER: rank {strag.get('rank')} at step "
                   f"{strag.get('step')} — {strag.get('step_ms')} ms vs "
                   f"fleet median {strag.get('fleet_median_ms')} ms "
                   f"(threshold {strag.get('factor')}x)")
    desync = verdicts.get("desync")
    if desync:
        out.append(f"DP DESYNC: ranks {desync.get('ranks')} at step "
                   f"{desync.get('step')} — loss spread "
                   f"{desync.get('spread'):.6g} (rel "
                   f"{desync.get('rel_spread'):.3g} > tol "
                   f"{desync.get('tol'):.3g}); same-step losses must "
                   f"match across dp replicas")
    silent = verdicts.get("silent")
    if silent:
        out.append(f"SILENT WORKER: rank {silent.get('rank')} — no "
                   f"heartbeat for {silent.get('silent_s')}s (timeout "
                   f"{silent.get('timeout_s')}s, last step "
                   f"{silent.get('last_step')})")
    if fleet.get("postmortem"):
        out.append(f"postmortem: {fleet['postmortem']}")
    if not (strag or desync or silent):
        out.append("verdicts: none latched (fleet healthy)")


def render_memory(mem, out, steps=(), source=""):
    """The memory observatory's account: run-level peaks (+ sentinel
    state) and the per-step live-census trajectory when step lines
    carry `memory` sub-objects."""
    out.append("")
    out.append(f"-- device memory{source} --")
    peak = mem.get("peak_hbm_gib")
    if peak is not None:
        out.append(f"peak HBM: {peak:.3f} GiB"
                   + (f"   (source: {mem['source']})"
                      if mem.get("source") else ""))
    for key, label in (("peak_live_bytes", "peak live bytes (census)"),
                       ("peak_backend_bytes", "peak bytes (allocator)")):
        if mem.get(key):
            out.append(f"{label}: {_fmt_gib(mem[key])}")
    if mem.get("peak_live_gib") is not None and "peak_live_bytes" not in mem:
        out.append(f"peak live (census): {mem['peak_live_gib']:.3f} GiB")
    if mem.get("censuses"):
        out.append(f"censuses: {mem['censuses']}")
    if "nan_check" in mem:
        out.append(f"numerics sentinel: "
                   f"{'armed' if mem['nan_check'] else 'off'}")
    execs = mem.get("executables") or (
        [mem["executable"]] if mem.get("executable") else [])
    if execs:
        out.append("per-executable (args / temp / out -> peak):")
        for e in execs:
            out.append(f"  {e.get('name', '?'):<28}"
                       f"{_fmt_gib(e.get('args_bytes', 0)):>12} /"
                       f"{_fmt_gib(e.get('temp_bytes', 0)):>12} /"
                       f"{_fmt_gib(e.get('output_bytes', 0)):>12} -> "
                       f"{_fmt_gib(e.get('peak_bytes', 0))}"
                       + ("  (per-shard)" if e.get("per_shard") else ""))
    # per-step live-census trajectory from the step lines
    series = [(s["step"], s["memory"]) for s in steps
              if isinstance(s.get("memory"), dict)]
    if series:
        live = [m.get("live_bytes", 0) for _, m in series]
        peaks = [m.get("peak_live_bytes", 0) for _, m in series]
        hi_step = max(series, key=lambda sm: sm[1].get("live_bytes", 0))
        out.append(f"step census: {len(series)} step(s)   "
                   f"live min {_fmt_gib(min(live))}   "
                   f"max {_fmt_gib(max(live))} (step {hi_step[0]})   "
                   f"run peak {_fmt_gib(max(peaks))}")


# -- span attribution --------------------------------------------------------

def _merge_intervals(iv):
    """Union of (lo, hi) intervals as a sorted, disjoint list."""
    out = []
    for lo, hi in sorted(iv):
        if hi <= lo:
            continue
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _measure(iv):
    return sum(hi - lo for lo, hi in iv)


def _clip(iv, lo, hi):
    return [(max(a, lo), min(b, hi)) for a, b in iv
            if b > lo and a < hi]


def _subtract(iv, claimed):
    """`iv` minus `claimed` (both merged/disjoint, sorted)."""
    out = []
    for lo, hi in iv:
        cur = lo
        for c0, c1 in claimed:
            if c1 <= cur or c0 >= hi:
                continue
            if c0 > cur:
                out.append((cur, c0))
            cur = max(cur, c1)
            if cur >= hi:
                break
        if cur < hi:
            out.append((cur, hi))
    return out


def load_spans(trace_path):
    """(step_windows, intervals_by_cat) from a chrome trace's ``ph:"X"``
    span events, in trace-clock milliseconds. ``step_windows`` are the
    StepLogger step-marker spans; ``intervals_by_cat`` holds the
    attribution-bucket spans."""
    with open(trace_path) as f:
        trace = json.load(f)
    steps, by_cat = [], {c: [] for c in ATTRIBUTION_CATEGORIES}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        cat = ev.get("cat")
        try:
            t0 = float(ev["ts"]) / 1e3
            t1 = t0 + float(ev.get("dur", 0)) / 1e3
        except (KeyError, TypeError, ValueError):
            continue
        if cat == "step":
            steps.append((ev.get("name", "step/?"), t0, t1))
        elif cat in by_cat:
            by_cat[cat].append((t0, t1))
    steps.sort(key=lambda s: s[1])
    return steps, {c: _merge_intervals(v) for c, v in by_cat.items()}


def attribute_spans(steps, by_cat):
    """Decompose each step window into the attribution buckets.

    Priority sweep: categories claim time in ATTRIBUTION_CATEGORIES
    order, so a slice covered by several nested spans counts exactly
    once — bucket sums can never exceed the window. Without step markers
    the whole span extent is one window. Returns
    ``{"per_step": [...], "totals": {...}, "wall_ms": float}``.
    """
    if not steps:
        allspans = [iv for v in by_cat.values() for iv in v]
        if not allspans:
            return {"per_step": [], "totals": {}, "wall_ms": 0.0}
        lo = min(a for a, _ in allspans)
        hi = max(b for _, b in allspans)
        steps = [("run", lo, hi)]
    per_step = []
    totals = {c: 0.0 for c in ATTRIBUTION_CATEGORIES}
    wall = 0.0
    for name, lo, hi in steps:
        dur = hi - lo
        wall += dur
        claimed = []
        row = {"step": name, "dur_ms": dur}
        for cat in ATTRIBUTION_CATEGORIES:
            take = _subtract(_clip(by_cat.get(cat, []), lo, hi), claimed)
            got = _measure(take)
            row[cat] = got
            totals[cat] += got
            if take:
                claimed = _merge_intervals(claimed + take)
        row["other"] = max(0.0, dur - sum(row[c]
                                          for c in ATTRIBUTION_CATEGORIES))
        per_step.append(row)
    totals["other"] = max(0.0, wall - sum(totals.values()))
    return {"per_step": per_step, "totals": totals, "wall_ms": wall}


_VERDICTS = {
    "prefetch_starvation": "input-bound: the loader starved the step — "
                           "raise prefetch depth / loader workers",
    "fence_wait": "device-bound: the host out-ran the device to the "
                  "in-flight bound (healthy pipelining; the device is "
                  "the limiter)",
    "sync": "sync-bound: metric materializations dominate — check "
            "log_freq or a per-step .numpy() in a callback",
    "compile": "compile-bound: retrace storm — check for shape churn",
    "dispatch": "dispatch-bound: host-side enqueue cost dominates",
    "other": "mostly unattributed host time (python bookkeeping between "
             "instrumented regions)",
}


def render_attribution(att, out):
    out.append("")
    out.append("-- span attribution (host wall decomposition) --")
    totals, wall = att["totals"], att["wall_ms"]
    if not totals or wall <= 0:
        out.append("no spans found (was PT_MONITOR=1 set for the run?)")
        return
    n = len(att["per_step"])
    out.append(f"windows: {n}   wall: {wall:.3f} ms")
    rows = []
    for cat in (*ATTRIBUTION_CATEGORIES, "other"):
        ms = totals.get(cat, 0.0)
        rows.append((cat, f"{ms:.3f} ms", f"{ms / wall * 100:5.1f}%"))
    out.extend(_table(rows, (24, 16, 10)))
    attributed = wall - totals.get("other", 0.0)
    out.append(f"attributed: {attributed / wall * 100:.1f}% of "
               f"host wall across {n} window(s)")
    # the dominant category is the verdict
    dom = max(totals, key=lambda c: totals[c])
    if totals[dom] > 0.2 * wall:
        out.append(f"verdict: {_VERDICTS[dom]}")
    worst = [r for r in att["per_step"]
             if r["dur_ms"] > 0 and r["step"] != "run"]
    if worst:
        w = max(worst, key=lambda r: r["dur_ms"] - r["other"])
        parts = ", ".join(
            f"{c} {w[c]:.2f}ms" for c in ATTRIBUTION_CATEGORIES if w[c] > 0)
        if parts:
            out.append(f"worst window: {w['step']} "
                       f"(dur {w['dur_ms']:.2f}ms: {parts})")


# -- per-request serving journeys ---------------------------------------------

def load_request_spans(events_or_path):
    """``serving/request`` finish spans (cat ``serving_finish``) — each
    one is a whole request journey with the telescoping latency
    attribution in its args (docs/SERVING.md). Accepts a chrome-trace
    event list, a chrome-trace path, or a ``serving_blackbox.json``
    artifact path (its ``spans`` list uses the raw recorder tuple
    shape)."""
    if isinstance(events_or_path, str):
        with open(events_or_path) as f:
            data = json.load(f)
        if isinstance(data, dict) and "spans" in data:  # blackbox artifact
            return [dict(sp.get("args") or {}) for sp in data["spans"]
                    if sp.get("cat") == "serving_finish"]
        events = (data or {}).get("traceEvents", [])
    else:
        events = events_or_path or []
    return [dict(ev.get("args") or {}) for ev in events
            if ev.get("ph") == "X" and ev.get("cat") == "serving_finish"]


def render_requests(journeys, out, top=10, source=""):
    """Slowest-N request journeys, each decomposed into the phase
    buckets the engine billed (queue/prefill/decode/preempted — they sum
    to the request's end-to-end latency)."""
    if not journeys:
        return

    def _ms(v):
        return f"{v:.1f}" if isinstance(v, (int, float)) else "-"

    out.append("")
    out.append(f"-- requests (slowest {min(top, len(journeys))} of "
               f"{len(journeys)} journeys, ms){source} --")
    ordered = sorted(journeys,
                     key=lambda j: -(j.get("total_ms") or 0.0))
    rows = [("request", "total", "queue", "prefill", "decode",
             "preempted", "tokens", "pre", "spec")]
    for j in ordered[:top]:
        rows.append((j.get("trace_id") or j.get("request", "?"),
                     _ms(j.get("total_ms")), _ms(j.get("queue_ms")),
                     _ms(j.get("prefill_ms")), _ms(j.get("decode_ms")),
                     _ms(j.get("preempted_ms")), j.get("tokens", "-"),
                     j.get("preemptions", 0), j.get("spec_rounds", 0)))
    out.extend(_table(rows, (10, 10, 9, 9, 9, 11, 8, 5, 6)))
    tot = [j["total_ms"] for j in journeys
           if isinstance(j.get("total_ms"), (int, float))]
    qs = [j.get("queue_ms", 0.0) for j in journeys
          if isinstance(j.get("total_ms"), (int, float))]
    if tot:
        mean_t = sum(tot) / len(tot)
        line = (f"{len(journeys)} finished: total_ms mean "
                f"{mean_t:.1f}   max {max(tot):.1f}")
        if mean_t > 0:
            line += f"   queue share {sum(qs) / sum(tot):.1%}"
        out.append(line)


def render_request_attribution(att, out, source=""):
    """serving_bench's ``attribution`` sub-object: per-phase latency
    means that telescope to the measured end-to-end request latency
    (``phase_sum_vs_total`` ~ 1.0 is the engine's accounting proof)."""
    if not att:
        return
    out.append("")
    out.append(f"-- request attribution (phase means, ms){source} --")
    rows = []
    for key in ("queue_ms_mean", "prefill_ms_mean", "decode_ms_mean",
                "preempted_ms_mean", "total_ms_mean", "queue_ms_p99"):
        if att.get(key) is not None:
            rows.append((key, att[key]))
    out.extend(_table(rows, (24, 14)))
    if att.get("queue_share") is not None:
        out.append(f"queue share: {att['queue_share']:.1%} of request "
                   f"latency spent waiting for a lane")
    if att.get("phase_sum_vs_total") is not None:
        out.append(f"phase sum vs total: {att['phase_sum_vs_total']} "
                   f"(1.0 = the buckets telescope exactly)")
    extras = []
    for key in ("prefill_refunded_tokens", "spec_rounds",
                "accepted_tokens"):
        if att.get(key):
            extras.append(f"{key} {att[key]}")
    if extras:
        out.append("   ".join(extras))


def render(jsonl_path, trace_path=None, top=10, spans=False,
           bench_path=None, metrics_path=None, fleet_path=None):
    steps, begin, end = load_jsonl(jsonl_path)
    out = [f"== monitor run: {jsonl_path} =="]
    if begin:
        meta = begin.get("meta") or {}
        if meta:
            out.append("meta: " + ", ".join(
                f"{k}={v}" for k, v in meta.items() if v is not None))

    # -- run overview --
    n = len(steps)
    out.append("")
    out.append("-- run --")
    wall = (end or {}).get("wall_s")
    if wall is None and n:
        wall = sum(s.get("dur_ms", 0) for s in steps) / 1e3
    out.append(f"steps: {n}   wall: {wall:.3f} s" if wall is not None
               else f"steps: {n}")
    if n:
        durs = [s["dur_ms"] for s in steps if "dur_ms" in s]
        if durs:
            out.append(f"step dur_ms: mean {sum(durs) / len(durs):.3f}   "
                       f"min {min(durs):.3f}   max {max(durs):.3f}")
        losses = [(s["step"], s["loss"]) for s in steps if "loss" in s]
        if losses:
            out.append(f"loss: first {losses[0][1]:.6f} (step {losses[0][0]})"
                       f" -> last {losses[-1][1]:.6f} (step {losses[-1][0]})")
        elif end and end.get("loss") is not None:
            out.append(f"final loss: {end['loss']:.6f}")
        ips = [s["ips"] for s in steps if s.get("ips")]
        if ips:
            out.append(f"ips: mean {sum(ips) / len(ips):.2f}   "
                       f"max {max(ips):.2f}")

    # -- counter totals --
    totals = _counter_totals(steps, end)
    if totals:
        out.append("")
        out.append("-- counters (run total) --")
        rows = []
        for name in sorted(totals, key=lambda k: (-totals[k], k)):
            val = totals[name]
            rows.append((name, _fmt_bytes(val) if name.endswith("bytes")
                         else val))
        out.extend(_table(rows, (44, 16)))

    # -- async pipeline (PR 2 instrumentation: prefetch, AsyncStepper,
    #    hapi deferred syncs) --
    hists = (end or {}).get("totals", {}).get("histograms", {})
    gauges = (end or {}).get("totals", {}).get("gauges", {})
    pipe = []
    if totals.get("io/prefetch_batches") or totals.get(
            "io/prefetch_starvations"):
        staged = totals.get("io/prefetch_batches", 0)
        starved = totals.get("io/prefetch_starvations", 0)
        line = (f"prefetch: staged {staged}   starvations {starved}")
        if staged:
            line += f"   starvation rate {starved / staged:.3f}/batch"
        pipe.append(line)
        w = hists.get("io/prefetch_wait_ms")
        if w:
            pipe.append(f"  starved wait ms: p50 {w['p50']}   "
                        f"p95 {w['p95']}   max {w['max']}")
        depth = gauges.get("io/prefetch_depth")
        if depth is not None:
            pipe.append(f"  buffer depth (last): {depth:g}")
    if totals.get("async/bound_waits") or "async/steps_in_flight" in gauges:
        waits = totals.get("async/bound_waits", 0)
        line = f"async: bound waits {waits}"
        if n:
            line += f" over {n} steps ({waits / n:.2f}/step)"
        pipe.append(line)
        w = hists.get("async/bound_wait_ms")
        if w:
            pipe.append(f"  bound wait ms: p50 {w['p50']}   "
                        f"p95 {w['p95']}   max {w['max']}")
    if totals.get("hapi/host_syncs"):
        syncs = totals["hapi/host_syncs"]
        line = f"hapi host syncs: {syncs}"
        if n:
            line += (f"   ({n / syncs:.1f} steps/sync — the "
                     f"≤ 1-per-log-window guard)")
        pipe.append(line)
    hb = (end or {}).get("host_blocked_ms_per_step")
    if hb is None:
        hbs = [s["host_blocked_ms_per_step"] for s in steps
               if "host_blocked_ms_per_step" in s]
        hb = hbs[-1] if hbs else None
    if hb is not None:
        pipe.append(f"host_blocked_ms_per_step: {hb}")
    if pipe:
        out.append("")
        out.append("-- async pipeline --")
        out.extend(pipe)

    # -- exec cache (jit/exec_cache_* from the run's counters) --
    render_exec_cache(out, totals=totals,
                      hists=(end or {}).get("totals", {})
                      .get("histograms", {}))

    # -- serving runtime (serving/* from the continuous-batching engine) --
    render_serving(out, totals=totals,
                   hists=(end or {}).get("totals", {}).get("histograms", {}),
                   gauges=(end or {}).get("totals", {}).get("gauges", {}))

    # -- replica router (router/* from the multi-replica dispatcher) --
    render_router(out, totals=totals,
                  gauges=(end or {}).get("totals", {}).get("gauges", {}))

    # -- SLO / live windows (the run_end line's live snapshot) --
    render_slo(out, live=(end or {}).get("live"))

    # -- SLO / live windows from a saved /metrics exposition --
    if metrics_path:
        try:
            series = parse_openmetrics(open(metrics_path).read())
        except OSError as e:
            out.append("")
            out.append(f"unreadable metrics file: {e}")
        else:
            render_metrics_file(series, out,
                                source=f" {metrics_path}")

    # -- pallas kernels (pallas/* + search/* from the search harness) --
    render_kernels(out, totals=totals,
                   gauges=(end or {}).get("totals", {}).get("gauges", {}))

    # -- sharding planner (planner/* + collective/bytes/<axis>) --
    render_planner(out, totals=totals,
                   gauges=(end or {}).get("totals", {}).get("gauges", {}))

    # -- pipeline parallelism (pipeline/* schedule + ppermute account) --
    render_pipeline(out, totals=totals,
                    gauges=(end or {}).get("totals", {}).get("gauges", {}))

    # -- resilience runtime (resilience/* + run_end last_checkpoint_step) --
    render_resilience(out, totals=totals,
                      hists=(end or {}).get("totals", {})
                      .get("histograms", {}),
                      end=end)

    # -- goodput ledger (run_end's goodput sub-object — where did the
    #    wall-clock go) --
    render_goodput(out, (end or {}).get("goodput"))

    # -- fleet (--fleet: a launcher fleet.json snapshot or the raw
    #    heartbeat directory, detectors re-run offline) --
    if fleet_path:
        try:
            fleet = load_fleet(fleet_path)
        except (OSError, ValueError) as e:
            out.append("")
            out.append(f"unreadable fleet source: {e}")
        else:
            render_fleet(out, fleet, source=f" {fleet_path}")

    # -- device memory (observatory run_end sub-object and/or per-step
    #    censuses) --
    mem = (end or {}).get("memory")
    has_step_mem = any(isinstance(s.get("memory"), dict) for s in steps)
    if mem or has_step_mem:
        render_memory(mem or {}, out, steps=steps)

    # -- perf guard verdict (bench.py embeds it in run_end) --
    guard = (end or {}).get("guard")
    if guard:
        render_guard(guard, out)

    # -- bench line join (--bench): guard + memory from a bench log --
    if bench_path:
        read_ok = True
        try:
            line = find_bench_line(open(bench_path).read())
        except OSError as e:
            line = None
            read_ok = False
            out.append("")
            out.append(f"unreadable bench log: {e}")
        if line is not None:
            out.append("")
            out.append(f"-- bench line: {bench_path} --")
            out.append(f"{line.get('metric')}: {line.get('value')} "
                       f"{line.get('unit', '')}"
                       + (f"   mfu {line['mfu']}" if line.get("mfu")
                          else ""))
            mem_b = dict(line.get("memory") or {})
            if line.get("peak_hbm_gib") is not None:
                mem_b.setdefault("peak_hbm_gib", line["peak_hbm_gib"])
            if mem_b:
                render_memory(mem_b, out, source=" (bench)")
            tel_b = line.get("telemetry") or {}
            if tel_b.get("exec_cache") or "compile_ms_total" in tel_b:
                render_exec_cache(out, bench_tel=tel_b, source=" (bench)")
            if tel_b.get("serving"):
                # serving_bench embeds the counters prefix-stripped
                render_serving(
                    out, totals={f"serving/{k}": v
                                 for k, v in tel_b["serving"].items()},
                    source=" (bench)")
            if tel_b.get("router"):
                # serving_bench embeds the router counters the same way
                render_router(
                    out, totals={f"router/{k}": v
                                 for k, v in tel_b["router"].items()},
                    source=" (bench)")
            if line.get("attribution"):
                render_request_attribution(line["attribution"], out,
                                           source=" (bench)")
            if line.get("goodput"):
                render_goodput(out, line["goodput"], source=" (bench)")
            if line.get("kernels"):
                render_kernels(out, bench_kernels=line["kernels"],
                               source=" (bench)")
            if line.get("guard"):
                render_guard(line["guard"], out, source=" (bench)")
        elif read_ok:
            out.append("")
            out.append(f"no bench JSON line found in {bench_path!r}")

    # -- retrace timeline --
    retraces = [(s["step"], s["counters"]["jit/retraces"]) for s in steps
                if s.get("counters", {}).get("jit/retraces")]
    out.append("")
    out.append("-- retrace timeline --")
    if retraces:
        out.append("  ".join(f"step {st}: +{k}" for st, k in retraces))
        if len(retraces) > 1:
            out.append(f"WARNING: {len(retraces)} steps retraced — check "
                       f"for shape churn (each retrace is an XLA compile)")
    else:
        out.append("no retraces inside the logged window")

    # -- sync latency --
    hists = (end or {}).get("totals", {}).get("histograms", {})
    sync = hists.get("tunnel/sync_ms")
    if sync:
        out.append("")
        out.append("-- tunnel sync latency (ms) --")
        out.extend(_table(
            [("count", sync["count"]), ("mean", sync["mean"]),
             ("p50", sync["p50"]), ("p95", sync["p95"]),
             ("max", sync["max"])], (10, 14)))
    compile_h = hists.get("jit/compile_ms")
    if compile_h:
        out.append("")
        out.append("-- compile wall-time (ms) --")
        out.extend(_table(
            [("count", compile_h["count"]), ("mean", compile_h["mean"]),
             ("max", compile_h["max"])], (10, 14)))

    # -- chrome trace join --
    if trace_path:
        out.append("")
        out.append(f"-- chrome trace: {trace_path} --")
        try:
            with open(trace_path) as f:
                trace = json.load(f)
            events = trace.get("traceEvents", [])
        except (OSError, ValueError) as e:
            events = None
            out.append(f"unreadable trace: {e}")
        if events is not None:
            op_counts = {}
            for ev in events:
                if ev.get("cat") in ("op", "op_dispatch"):
                    name = ev.get("name", "?")
                    op_counts[name] = op_counts.get(name, 0) + 1
            counter_tracks = sorted({
                ev.get("name", "?") for ev in events if ev.get("ph") == "C"})
            out.append(f"events: {len(events)}   "
                       f"counter tracks: {len(counter_tracks)}")
            if op_counts:
                out.append(f"top {top} dispatched ops:")
                rows = sorted(op_counts.items(),
                              key=lambda kv: (-kv[1], kv[0]))[:top]
                out.extend(_table(rows, (44, 10)))
            if counter_tracks:
                out.append("counter tracks: " + ", ".join(counter_tracks))
            lanes = sorted({
                (ev.get("args") or {}).get("name", "?") for ev in events
                if ev.get("ph") == "M"
                and ev.get("name") == "thread_name"})
            if lanes:
                out.append("span lanes: " + ", ".join(lanes))
            # per-request journeys: the engine's serving/request finish
            # spans carry the whole telescoped attribution per request
            render_requests(load_request_spans(events), out, top=top)

    # -- span attribution --
    if spans:
        span_src = spans if isinstance(spans, str) else trace_path
        if not span_src:
            out.append("")
            out.append("--spans needs a trace (pass --trace, or "
                       "--spans PATH)")
        else:
            try:
                st, by_cat = load_spans(span_src)
                render_attribution(attribute_spans(st, by_cat), out)
            except (OSError, ValueError) as e:
                out.append("")
                out.append(f"unreadable span trace: {e}")

    return "\n".join(out)


def _selftest():
    """Render a fully synthesized run (StepLogger JSONL + spans chrome
    trace + bench line) and assert every section the serving-trace stack
    depends on actually renders — the tier-1 smoke for this tool (pure
    stdlib: no jax, no engine, no fixture files to go stale)."""
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        jsonl = os.path.join(td, "run.jsonl")
        with open(jsonl, "w") as f:
            for line in (
                {"event": "run_begin", "ts": 0.0, "pid": 1,
                 "monitor_enabled": True, "meta": {"source": "selftest"}},
                {"step": 1, "ts": 0.1, "dur_ms": 10.0, "loss": 2.5,
                 "ips": 100.0, "counters": {"jit/retraces": 1}},
                {"step": 2, "ts": 0.2, "dur_ms": 9.0, "loss": 2.4,
                 "ips": 110.0},
                {"event": "run_end", "ts": 0.3, "steps": 2, "wall_s": 0.02,
                 "goodput": {"wall_s": 10.0,
                             "buckets": {"productive_step": 8.0,
                                         "compile": 1.5,
                                         "checkpoint_save_blocking": 0.25,
                                         "nan_replay_or_skip": 0.0,
                                         "restore_resume": 0.0,
                                         "input_wait": 0.25,
                                         "other": 0.0},
                             "goodput_frac": 0.8, "steps": 2,
                             "nan_steps": 0},
                 "totals": {"counters": {
                     "serving/admits": 2, "serving/evictions": 2,
                     "serving/prefill_steps": 4, "serving/decode_steps": 9,
                     "serving/prefix_hit_tokens": 16,
                     "serving/prefix_miss_tokens": 48},
                     "histograms": {}, "gauges": {}},
                 "live": {"steps": 9, "sketches": {
                     "ttft_ms": {"count": 2, "sum": 52.0, "p50": 12.3,
                                 "p90": 40.1, "p99": 40.1}},
                     "slo": {"targets": {"ttft_ms_p99": 25.0,
                                         "tpot_ms_p99": None},
                             "breaches": 1,
                             "worst_burn": {"ttft_ms": 50.0},
                             "last_burn": {"ttft_ms": {"fast": 50.0,
                                                       "slow": 11.1}},
                             "fast_window_steps": 12,
                             "slow_window_steps": 120,
                             "burn_fast_threshold": 14.0,
                             "burn_slow_threshold": 6.0}}},
            ):
                f.write(json.dumps(line) + "\n")
        trace = os.path.join(td, "trace.json")

        def _req(i, total, queue, prefill, decode, preempted, pre=0):
            return {"ph": "X", "name": "serving/request",
                    "cat": "serving_finish", "pid": 1,
                    "tid": f"req/r{i}", "ts": i * 1000.0, "dur": total * 1e3,
                    "args": {"request": i, "trace_id": f"r{i}",
                             "tokens": 8, "preemptions": pre,
                             "total_ms": total, "queue_ms": queue,
                             "prefill_ms": prefill, "decode_ms": decode,
                             "preempted_ms": preempted,
                             "prefill_refunded_tokens": 0,
                             "spec_rounds": 0, "accepted_tokens": 0}}

        with open(trace, "w") as f:
            json.dump({"traceEvents": [
                {"ph": "M", "name": "thread_name", "pid": 1, "tid": "steps",
                 "args": {"name": "steps"}},
                {"ph": "X", "name": "step/1", "cat": "step", "pid": 1,
                 "tid": "steps", "ts": 0.0, "dur": 10000.0},
                {"ph": "X", "name": "tunnel/sync", "cat": "sync", "pid": 1,
                 "tid": "host", "ts": 2000.0, "dur": 3000.0},
                _req(1, 40.0, 5.0, 10.0, 25.0, 0.0),
                _req(2, 90.0, 20.0, 10.0, 40.0, 20.0, pre=1),
            ]}, f)
        bench = os.path.join(td, "bench.log")
        with open(bench, "w") as f:
            f.write(json.dumps({
                "metric": "serving_tokens_per_sec", "value": 123.4,
                "unit": "tokens/s", "ttft_ms_p50": 12.0,
                "attribution": {
                    "queue_ms_mean": 12.5, "prefill_ms_mean": 10.0,
                    "decode_ms_mean": 32.5, "preempted_ms_mean": 10.0,
                    "total_ms_mean": 65.0, "phase_sum_vs_total": 1.0,
                    "queue_share": 0.1923, "queue_ms_p99": 20.0,
                    "prefill_refunded_tokens": 4, "spec_rounds": 3,
                    "accepted_tokens": 5},
                "goodput": {"wall_s": 5.0,
                            "buckets": {"productive_step": 4.0,
                                        "compile": 1.0},
                            "goodput_frac": 0.8, "steps": 4},
                "telemetry": {"serving": {"admits": 2, "evictions": 2,
                                          "prefill_steps": 4,
                                          "decode_steps": 9}}}) + "\n")
        metrics_file = os.path.join(td, "metrics.txt")
        with open(metrics_file, "w") as f:
            f.write("\n".join((
                "# TYPE pt_router_dispatches counter",
                'pt_router_dispatches_total{replica="0"} 6',
                'pt_router_dispatches_total{replica="1"} 2',
                "# TYPE pt_live_ttft_ms summary",
                'pt_live_ttft_ms{quantile="0.5"} 12.3',
                'pt_live_ttft_ms{quantile="0.9"} 40.1',
                'pt_live_ttft_ms{quantile="0.99"} 40.1',
                "pt_live_ttft_ms_count 2",
                "pt_live_ttft_ms_sum 52.0",
                "# TYPE pt_slo_breaches counter",
                "pt_slo_breaches_total 1",
                "# TYPE pt_slo_target_ms gauge",
                'pt_slo_target_ms{metric="ttft_ms"} 25.0',
                "# TYPE pt_slo_burn_rate gauge",
                'pt_slo_burn_rate{metric="ttft_ms",window="fast"} 50.0',
                'pt_slo_burn_rate{metric="ttft_ms",window="slow"} 11.1',
                "# EOF", "")))
        # fleet fixture: 3 workers' heartbeat JSONL with an injected
        # straggler (rank 2 at step 2: 50ms vs fleet median 5ms) and a
        # dp desync (rank 2's step-3 loss diverges) — the offline
        # detectors in load_fleet() must latch + name both
        hb_dir = os.path.join(td, "heartbeats")
        os.makedirs(hb_dir)
        beats = {
            0: [(1, 5.0, 2.50), (2, 5.0, 2.40), (3, 5.0, 2.30)],
            1: [(1, 5.0, 2.50), (2, 5.0, 2.40), (3, 5.0, 2.30)],
            2: [(1, 5.0, 2.50), (2, 50.0, 2.40), (3, 5.0, 9.99)],
        }
        for rank, rows in beats.items():
            with open(os.path.join(hb_dir,
                                   f"heartbeat.{rank}.jsonl"), "w") as f:
                for step, ms, loss in rows:
                    f.write(json.dumps(
                        {"rank": rank, "step": step, "ts": 100.0 + step,
                         "step_ms": ms, "loss": loss,
                         "goodput": {"productive_step": 4.0,
                                     "compile": 1.0}}) + "\n")
        report = render(jsonl, trace_path=trace, top=5, spans=True,
                        bench_path=bench, metrics_path=metrics_file,
                        fleet_path=hb_dir)
        needed = (
            "-- run --",
            "-- counters (run total) --",
            "-- serving (continuous batching) --",
            "-- SLO / live windows --",
            "-- SLO / live windows (/metrics)",
            "-- goodput (where did the time go) --",
            "-- fleet (launcher workers)",
            "-- bench line:",
            "-- serving (continuous batching) (bench) --",
            "-- request attribution (phase means, ms) (bench) --",
            "-- goodput (where did the time go) (bench) --",
            "-- requests (slowest 2 of 2 journeys, ms) --",
            "-- retrace timeline --",
            "-- span attribution (host wall decomposition) --",
        )
        missing = [m for m in needed if m not in report]
        # the run_end live snapshot's SLO state must land in the text
        slo_ok = ("breaches: 1" in report
                  and "ttft_ms 25 ms" in report
                  and "replica 0" in report and "(75%)" in report)
        # the slowest journey must lead the requests table
        order_ok = report.find("r2") < report.find("r1") \
            or "r2" not in report
        # goodput: the exact-telescope proof + fraction must render
        gp_ok = ("goodput_frac: 0.8000" in report
                 and "(telescopes exactly)" in report)
        # fleet: both injected verdicts must latch and name rank 2
        fleet_ok = ("STRAGGLER: rank 2 at step 2" in report
                    and "DP DESYNC: ranks [0, 2] at step 3" in report)
        if missing or not order_ok or not slo_ok or not gp_ok \
                or not fleet_ok:
            print(report)
            print(f"selftest FAILED: missing={missing} "
                  f"order_ok={order_ok} slo_ok={slo_ok} "
                  f"gp_ok={gp_ok} fleet_ok={fleet_ok}",
                  file=sys.stderr)
            return 1
        print(f"monitor_report selftest ok "
              f"({len(report.splitlines())} lines, "
              f"{len(needed)} sections present)")
        return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Summarize a monitor JSONL run, optionally joined "
                    "with a profiler chrome trace.")
    ap.add_argument("jsonl", nargs="?", default=None,
                    help="StepLogger JSONL file")
    ap.add_argument("--trace", default=None,
                    help="chrome trace JSON from profiler.export or "
                         "monitor.export_spans")
    ap.add_argument("--top", type=int, default=10,
                    help="top-N ops from the trace (default 10)")
    ap.add_argument("--spans", nargs="?", const=True, default=False,
                    metavar="TRACE",
                    help="attribute host wall time per step into "
                         "{sync, fence_wait, prefetch_starvation, compile, "
                         "dispatch, other} from the flight-recorder spans "
                         "(in --trace, or in the given file)")
    ap.add_argument("--bench", default=None, metavar="LOG",
                    help="bench log/JSON line: render its guard verdict "
                         "and memory sub-object next to the run")
    ap.add_argument("--metrics", default=None, metavar="FILE",
                    help="saved /metrics OpenMetrics exposition "
                         "(monitor/exporter.py): render its SLO/live "
                         "view incl. per-replica dispatch share")
    ap.add_argument("--fleet", default=None, metavar="DIR-or-JSON",
                    help="launcher fleet view: a fleet.json snapshot, "
                         "or the PT_HEARTBEAT_DIR itself (straggler / "
                         "dp-desync / silent-worker detectors re-run "
                         "offline over the raw heartbeat JSONL)")
    ap.add_argument("--selftest", action="store_true",
                    help="render a synthesized run and assert every "
                         "section appears (tier-1 smoke; no jsonl needed)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.jsonl is None:
        ap.error("jsonl is required (or pass --selftest)")
    report = render(args.jsonl, trace_path=args.trace, top=args.top,
                    spans=args.spans, bench_path=args.bench,
                    metrics_path=args.metrics, fleet_path=args.fleet)
    print(report)
    return report


if __name__ == "__main__":
    _rc = main()
    sys.exit(_rc if isinstance(_rc, int) else 0)
