#!/usr/bin/env python
"""Join a monitor StepLogger JSONL run with a profiler chrome trace into
one summary table.

    python tools/monitor_report.py run.jsonl [--trace trace.json] [--top 10]

Sections: run overview (steps, wall, loss, ips), counter totals, retrace
timeline (which step retraced — the recompile smoking gun), tunnel-sync
latency percentiles, and — when a chrome trace from
`paddle_tpu.profiler.Profiler.export` is given — the top dispatched ops and
the monitor counter tracks found on the timeline, so one report correlates
the JSONL run with the trace.

Pure stdlib: runs anywhere the artifacts land, no jax import.
"""
from __future__ import annotations

import argparse
import json
import sys


def load_jsonl(path):
    """(step_lines, begin, end) from a StepLogger file; tolerates junk
    lines (a crashed run must still be reportable)."""
    steps, begin, end = [], None, None
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                line = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(line, dict):
                continue
            if "step" in line:
                steps.append(line)
            elif line.get("event") == "run_begin" and begin is None:
                begin = line
            elif line.get("event") == "run_end":
                end = line  # last one wins (appended runs)
    return steps, begin, end


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0


def _table(rows, widths):
    out = []
    for row in rows:
        out.append("".join(
            f"{str(c):<{w}}" if i == 0 else f"{str(c):>{w}}"
            for i, (c, w) in enumerate(zip(row, widths))))
    return out


def _counter_totals(steps, end):
    if end and end.get("totals", {}).get("counters"):
        return dict(end["totals"]["counters"])
    totals = {}
    for s in steps:
        for k, v in s.get("counters", {}).items():
            totals[k] = totals.get(k, 0) + v
    return totals


def render(jsonl_path, trace_path=None, top=10):
    steps, begin, end = load_jsonl(jsonl_path)
    out = [f"== monitor run: {jsonl_path} =="]
    if begin:
        meta = begin.get("meta") or {}
        if meta:
            out.append("meta: " + ", ".join(
                f"{k}={v}" for k, v in meta.items() if v is not None))

    # -- run overview --
    n = len(steps)
    out.append("")
    out.append("-- run --")
    wall = (end or {}).get("wall_s")
    if wall is None and n:
        wall = sum(s.get("dur_ms", 0) for s in steps) / 1e3
    out.append(f"steps: {n}   wall: {wall:.3f} s" if wall is not None
               else f"steps: {n}")
    if n:
        durs = [s["dur_ms"] for s in steps if "dur_ms" in s]
        if durs:
            out.append(f"step dur_ms: mean {sum(durs) / len(durs):.3f}   "
                       f"min {min(durs):.3f}   max {max(durs):.3f}")
        losses = [(s["step"], s["loss"]) for s in steps if "loss" in s]
        if losses:
            out.append(f"loss: first {losses[0][1]:.6f} (step {losses[0][0]})"
                       f" -> last {losses[-1][1]:.6f} (step {losses[-1][0]})")
        elif end and end.get("loss") is not None:
            out.append(f"final loss: {end['loss']:.6f}")
        ips = [s["ips"] for s in steps if s.get("ips")]
        if ips:
            out.append(f"ips: mean {sum(ips) / len(ips):.2f}   "
                       f"max {max(ips):.2f}")

    # -- counter totals --
    totals = _counter_totals(steps, end)
    if totals:
        out.append("")
        out.append("-- counters (run total) --")
        rows = []
        for name in sorted(totals, key=lambda k: (-totals[k], k)):
            val = totals[name]
            rows.append((name, _fmt_bytes(val) if name.endswith("bytes")
                         else val))
        out.extend(_table(rows, (44, 16)))

    # -- retrace timeline --
    retraces = [(s["step"], s["counters"]["jit/retraces"]) for s in steps
                if s.get("counters", {}).get("jit/retraces")]
    out.append("")
    out.append("-- retrace timeline --")
    if retraces:
        out.append("  ".join(f"step {st}: +{k}" for st, k in retraces))
        if len(retraces) > 1:
            out.append(f"WARNING: {len(retraces)} steps retraced — check "
                       f"for shape churn (each retrace is an XLA compile)")
    else:
        out.append("no retraces inside the logged window")

    # -- sync latency --
    hists = (end or {}).get("totals", {}).get("histograms", {})
    sync = hists.get("tunnel/sync_ms")
    if sync:
        out.append("")
        out.append("-- tunnel sync latency (ms) --")
        out.extend(_table(
            [("count", sync["count"]), ("mean", sync["mean"]),
             ("p50", sync["p50"]), ("p95", sync["p95"]),
             ("max", sync["max"])], (10, 14)))
    compile_h = hists.get("jit/compile_ms")
    if compile_h:
        out.append("")
        out.append("-- compile wall-time (ms) --")
        out.extend(_table(
            [("count", compile_h["count"]), ("mean", compile_h["mean"]),
             ("max", compile_h["max"])], (10, 14)))

    # -- chrome trace join --
    if trace_path:
        out.append("")
        out.append(f"-- chrome trace: {trace_path} --")
        try:
            with open(trace_path) as f:
                trace = json.load(f)
            events = trace.get("traceEvents", [])
        except (OSError, ValueError) as e:
            events = None
            out.append(f"unreadable trace: {e}")
        if events is not None:
            op_counts = {}
            for ev in events:
                if ev.get("cat") in ("op", "op_dispatch"):
                    name = ev.get("name", "?")
                    op_counts[name] = op_counts.get(name, 0) + 1
            counter_tracks = sorted({
                ev.get("name", "?") for ev in events if ev.get("ph") == "C"})
            out.append(f"events: {len(events)}   "
                       f"counter tracks: {len(counter_tracks)}")
            if op_counts:
                out.append(f"top {top} dispatched ops:")
                rows = sorted(op_counts.items(),
                              key=lambda kv: (-kv[1], kv[0]))[:top]
                out.extend(_table(rows, (44, 10)))
            if counter_tracks:
                out.append("counter tracks: " + ", ".join(counter_tracks))

    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Summarize a monitor JSONL run, optionally joined "
                    "with a profiler chrome trace.")
    ap.add_argument("jsonl", help="StepLogger JSONL file")
    ap.add_argument("--trace", default=None,
                    help="chrome trace JSON from profiler.export")
    ap.add_argument("--top", type=int, default=10,
                    help="top-N ops from the trace (default 10)")
    args = ap.parse_args(argv)
    report = render(args.jsonl, trace_path=args.trace, top=args.top)
    print(report)
    return report


if __name__ == "__main__":
    main()
