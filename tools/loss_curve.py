"""Hardware loss-curve artifact: does the TPU numeric path LEARN?

BASELINE's metric is throughput AND loss parity, but every round-4 TPU
record was throughput-only — nothing persisted showed the bf16 + Pallas
flash + donated-buffer headline step converging on the chip (CPU tests
converge, but bf16 matmuls and the flash kernel are exactly what CPU
tests don't cover). This runs the EXACT headline train step
(`bench.py:build_headline_trainstep` — same config the MFU number comes
from) for N steps on a fixed synthetic corpus with a learnable
structure, and persists the full loss series.

Pass criterion recorded with the data: mean(last 10%) < 0.8 * mean(first
10%) and the final loss is finite. Synthetic data is drawn once from a
fixed-seed Zipf-ish unigram + repeated n-gram templates so the model has
real structure to learn (pure-uniform random tokens plateau at
ln(vocab)).

Usage: python tools/loss_curve.py [--steps 200] [--smoke]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _corpus(vocab, n_tokens, seed=0):
    """Zipf unigrams + planted 8-gram templates: learnable structure."""
    rng = np.random.RandomState(seed)
    base = rng.zipf(1.3, n_tokens).astype(np.int64) % vocab
    templates = [rng.randint(0, vocab, 8) for _ in range(32)]
    i = 0
    while i + 8 < n_tokens:
        if rng.rand() < 0.3:
            base[i:i + 8] = templates[rng.randint(32)]
            i += 8
        else:
            i += 1
    return base


def main() -> int:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import _probe_backend, enable_compilation_cache

    enable_compilation_cache()
    smoke = "--smoke" in sys.argv
    steps = 200
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    if not smoke:
        try:
            backend = _probe_backend()
        except RuntimeError as e:
            print(f"loss_curve: backend unavailable: {e}", file=sys.stderr)
            return 2
        smoke = backend == "cpu"
    if smoke:
        steps = min(steps, 30)
    print(f"loss_curve: smoke={smoke} steps={steps}", flush=True)

    from bench import build_headline_trainstep

    import paddle_tpu as pt

    model, step, batch, seq = build_headline_trainstep(on_cpu=smoke)
    vocab = model.config.vocab_size
    corpus = _corpus(vocab, batch * seq * steps + steps + 1)

    losses = []
    t0 = time.perf_counter()
    for s in range(steps):
        lo = s * batch * seq
        chunk = corpus[lo:lo + batch * seq + 1]
        ids = pt.to_tensor(chunk[:-1].reshape(batch, seq))
        labels = pt.to_tensor(chunk[1:].reshape(batch, seq))
        loss = step(ids, labels)
        # per-step host read IS the sync; decode-style enqueue-ack
        # artifacts cannot fake a loss series
        losses.append(float(np.asarray(loss.numpy())))
        if s % 20 == 0 or s == steps - 1:
            print(f"  step {s:4d} loss {losses[-1]:.4f}", flush=True)
    wall = time.perf_counter() - t0

    head = float(np.mean(losses[:max(1, steps // 10)]))
    tail = float(np.mean(losses[-max(1, steps // 10):]))
    ok = np.isfinite(losses).all() and tail < 0.8 * head
    rec = {
        "metric": "llama_train_loss_curve",
        "value": round(tail, 4),
        "unit": "loss",
        "steps": steps, "batch": batch, "seq": seq,
        "loss_first10pct": round(head, 4),
        "loss_last10pct": round(tail, 4),
        "converging": bool(ok),
        "losses": [round(x, 4) for x in losses],
        "wall_s": round(wall, 1),
    }
    # memory + numerics provenance: peak HBM and sentinel status ride in
    # the persisted record like throughput does (allocator stats first,
    # XLA executable accounting as fallback — both best-effort: a flaky
    # tunnel must not cost the loss series)
    from paddle_tpu.monitor import memory as _memobs
    from paddle_tpu.monitor import numerics as _numerics

    rec["nan_check"] = _numerics.enabled()
    rec["losses_finite"] = bool(np.isfinite(losses).all())
    try:
        peak = _memobs.device_peak_gib()
        if peak is None:
            # AOT-compile fallback, SIGALRM-timeboxed: a tunnel that
            # hangs here must not cost the already-measured loss series
            # (the record below has not been persisted yet)
            import signal

            prev = signal.signal(
                signal.SIGALRM,
                lambda *_: (_ for _ in ()).throw(TimeoutError()))
            signal.alarm(300)
            try:
                mrec = _memobs.executable_record(
                    step, ids, labels, name="loss_curve/headline")
            finally:
                signal.signal(signal.SIGALRM, prev)
                signal.alarm(0)
            peak = round(mrec["peak_bytes"] / 2**30, 3)
        rec["peak_hbm_gib"] = peak
    except Exception as e:  # noqa: BLE001
        print(f"loss_curve: memory accounting unavailable: {e}",
              file=sys.stderr, flush=True)
    if smoke:
        rec["note"] = "cpu smoke; the hardware artifact needs the chip"
    else:
        from paddle_tpu.utils import measurements as meas

        meas.record_rec_or_warn(rec)
    line = {k: v for k, v in rec.items() if k != "losses"}
    print(json.dumps(line), flush=True)
    return 0 if (ok or smoke) else 3


if __name__ == "__main__":
    sys.exit(main())
