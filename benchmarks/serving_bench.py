"""Request-level serving throughput + latency for the continuous-
batching engine (`paddle_tpu/serving`), judged against the decode HBM
roofline (`benchmarks/decode_bench.py`'s byte model).

Replays a SEEDED Poisson arrival trace (exponential inter-arrivals,
uniform prompt/output lengths — same seed, same trace, every run) and
reports aggregate ``tokens/s`` plus p50/p99 time-to-first-token and
per-token decode latency in the standard one-JSON-line format.

Run: python benchmarks/serving_bench.py [--smoke]
Prints one JSON line: {"metric": "serving_tokens_per_sec", ...} with
``tokens_per_sec`` / ``ttft_ms_p50`` / ``ttft_ms_p99`` / ``tpot_ms_*``
plus the prefix-cache readout: ``prefix_hit_rate`` (cached fraction of
all (re-)prefilled context tokens) and the cached-vs-cold TTFT A/B
(``ttft_ms_p50_cached`` / ``ttft_ms_p50_cold`` — requests whose
admission hit the prefix cache vs requests that prefilled everything).

Knobs (seeded defaults; --smoke pins the small trace explicitly):
  PT_SERVE_BENCH_REQUESTS (64; smoke 8)    trace length
  PT_SERVE_BENCH_RATE     (4.0; smoke 50)  Poisson arrival rate, req/s
  PT_SERVE_BENCH_SEED     (0)    trace seed
  PT_SERVE_BENCH_SHARED   (0)    shared-system-prompt trace mode: every
                                 prompt opens with the SAME seeded
                                 N-token prefix (hwbench's
                                 ``serving_prefix`` row sets 64), so
                                 the prefix cache turns all but the
                                 first prefill of it into hits
  PT_SERVE_BENCH_SPEC_K   (0)    speculative-decoding trace mode
                                 (hwbench's ``serving_spec`` row sets
                                 4): the engine runs with spec_k=N and
                                 every prompt becomes a seeded tiled
                                 motif (repetition-friendly — the
                                 prompt-lookup drafter's win
                                 condition), so ``accept_rate`` /
                                 ``tokens_per_decode_step`` measure a
                                 workload speculation can actually
                                 serve
  PT_SERVE_BENCH_SPEC_AB  (0)    =1 replays the same trace once more
                                 with speculation off on a fresh
                                 engine and embeds the A/B
                                 (``spec_off`` sub-object: decode
                                 rounds + tokens/s the plain decode
                                 path needed)
  PT_SERVE_BENCH_REPLICAS (0)    multi-replica router mode (hwbench's
                                 ``serving_router`` row sets 3): the
                                 trace replays through a
                                 ``RouterEngine`` over N in-process
                                 replicas instead of one engine — the
                                 line gains ``replicas`` /
                                 ``affinity_hit_rate`` /
                                 ``dispatches_per_replica`` /
                                 ``load_balance_spread`` /
                                 ``redispatched`` (perf_guard's
                                 ``--affinity-drop`` gate judges the
                                 hit rate)
  PT_SERVE_BENCH_KV_AB    (0)    =1 (with PT_SERVE_KV_INT8=1, hwbench's
                                 ``serving_int8kv`` row) replays the
                                 same trace once more through a fresh
                                 engine whose pool stores the model
                                 dtype and embeds the A/B (``kv_bf16``
                                 sub-object: tokens/s, TTFT p50, pool
                                 bytes, allocatable_tokens, peak-HBM —
                                 the capacity line's denominator)
  PT_SERVE_*                     engine geometry (docs/SERVING.md)
  PT_SERVE_PREFIX_CACHE=0        share-nothing pool A/B
  PT_SERVE_SPEC=0                speculation off (plain decode) A/B
  PT_SERVE_KV_INT8=1             int8 KV block pool (half-HBM KV) A/B
  PT_DECODE_INT8=1               weight-only int8 decode A/B
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_decode_bench():
    """The HBM roofline helpers live in decode_bench (the ONE byte model
    both decode benches are judged against) — load by path, benchmarks/
    is not a package."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "decode_bench.py")
    spec = importlib.util.spec_from_file_location("decode_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def build_trace(n, rate, vocab, prompt_rng, new_rng, seed=0,
                shared_prefix=0, motif=0):
    """Seeded Poisson trace: ``[(arrival_s, prompt_ids, max_new)]``,
    arrival-sorted by construction. Deterministic for a (seed, n, rate,
    length-range, shared-prefix, motif) tuple — the replayable-input
    contract the scheduler property tests lean on. ``shared_prefix`` > 0
    is the shared-system-prompt mode: one seeded prefix of that many
    tokens opens EVERY prompt (per-request lengths still draw from
    ``prompt_rng`` for the unique suffix). ``motif`` > 0 is the
    repetition-friendly mode (PT_SERVE_BENCH_SPEC_K): each prompt is a
    per-request seeded ``motif``-token pattern tiled to its drawn
    length — the structure (code, quoted context, lists) prompt-lookup
    speculation exists for."""
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, vocab, size=(int(shared_prefix),)) \
        .astype(np.int32)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n))
    trace = []
    for i in range(n):
        plen = int(rng.randint(prompt_rng[0], prompt_rng[1] + 1))
        new = int(rng.randint(new_rng[0], new_rng[1] + 1))
        if motif:
            pat = rng.randint(0, vocab, size=(int(motif),))
            prompt = np.tile(pat, -(-plen // int(motif)))[:plen] \
                .astype(np.int32)
        else:
            prompt = rng.randint(0, vocab, size=(plen,)).astype(np.int32)
        if shared_prefix:
            prompt = np.concatenate([prefix, prompt])
        trace.append((float(arrivals[i]), prompt, new))
    return trace


def percentile(values, q):
    return float(np.percentile(np.asarray(values, np.float64), q)) \
        if values else None


def kv_byte_model(cfg, num_blocks, block_size, kv_el_bytes, scale_bytes):
    """The serving-KV byte model — ONE place the bench line and the
    capacity tests (tests/test_serving_kv_int8.py) read the same
    arithmetic. Per-token KV bytes follow the POOL's storage dtype
    (``kv_el_bytes`` is the pool array's own itemsize, not an assumed
    2-byte element) plus ``scale_bytes`` per (position, kv_head) — the
    fp32 amax scales `quantize_kv` stores alongside an int8 pool.

    ``allocatable_tokens`` divides the UNQUANTIZED pool's byte budget
    (the configured ``num_blocks`` at the model dtype — "equal
    PT_SERVE_BLOCKS byte budget") by the actual per-token cost: the
    bf16 pool lands exactly on ``num_blocks * block_size``, the int8
    pool on ``2d/(d+4)`` times that (1.94x at head_dim=128 — the
    capacity claim ISSUE 18 gates at >= 1.9x).

    Returns ``(kv_bytes_per_token, allocatable_tokens)``."""
    nkv = cfg.num_key_value_heads or cfg.num_attention_heads
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    base_el = 2 if cfg.dtype == "bfloat16" else 4
    per_tok = 2 * cfg.num_hidden_layers * nkv \
        * (head_dim * kv_el_bytes + scale_bytes)
    budget = (num_blocks * block_size
              * 2 * cfg.num_hidden_layers * nkv * head_dim * base_el)
    return per_tok, budget // per_tok


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import enable_compilation_cache

    enable_compilation_cache()
    smoke = "--smoke" in sys.argv or jax.default_backend() == "cpu"
    print(f"serving_bench: backend={jax.default_backend()} smoke={smoke}",
          file=sys.stderr, flush=True)

    import paddle_tpu as pt
    from paddle_tpu import monitor as _mon
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (
        RouterConfig, RouterEngine, ServingConfig, ServingEngine,
    )

    from paddle_tpu.monitor import live as _live

    if os.environ.get("PT_BENCH_MONITOR", "1") != "0":
        # same telemetry ride-along as bench.py: compile wall-time and
        # the serving/* counters land in the JSON line's telemetry
        _mon.enable()
        # the live plane rides along too: streaming sketches + the SLO
        # watchdog (PT_SLO_* targets) feed the line's `slo` sub-object,
        # and sketch-vs-exact p99 agreement is self-reported
        _live.enable()
        _live.reset()

    pt.seed(0)
    # documented defaults (module docstring): 64 requests at 4.0/s;
    # --smoke pins its small trace explicitly (8 at 50/s), env overrides
    # either way
    n_req_env = os.environ.get("PT_SERVE_BENCH_REQUESTS")
    rate_env = os.environ.get("PT_SERVE_BENCH_RATE")
    shared = int(os.environ.get("PT_SERVE_BENCH_SHARED", "0") or 0)
    # speculative trace mode (docs/SERVING.md): PT_SERVE_BENCH_SPEC_K=N
    # pins the engine's draft depth AND makes the prompts repetitive
    # (tiled seeded motifs) so prompt-lookup acceptance is measurable
    spec_k_env = int(os.environ.get("PT_SERVE_BENCH_SPEC_K", "0") or 0)
    spec_kw = {"spec": True, "spec_k": spec_k_env} if spec_k_env else {}
    motif = 4 if spec_k_env else 0
    # multi-replica router mode (docs/SERVING.md "Replica router"):
    # PT_SERVE_BENCH_REPLICAS=N replays the SAME trace through a
    # RouterEngine over N in-process replicas — prefix-affinity dispatch
    # on, so the shared-prefix trace (PT_SERVE_BENCH_SHARED) measures
    # what affinity is worth
    replicas = int(os.environ.get("PT_SERVE_BENCH_REPLICAS", "0") or 0)
    if smoke:
        cfg = LlamaConfig.tiny()
        n_req = int(n_req_env) if n_req_env else 8
        rate = float(rate_env) if rate_env else 50.0
        prompt_rng, new_rng = (3, 12), (4, 12)
        if spec_k_env:  # longer outputs give speculation room to help
            new_rng = (12, 24)
        make_cfg = lambda **kw: ServingConfig(  # noqa: E731
            max_lanes=int(os.environ.get("PT_SERVE_LANES", "4")),
            block_size=int(os.environ.get("PT_SERVE_BLOCK", "4")),
            prefill_chunk=int(
                os.environ.get("PT_SERVE_PREFILL_CHUNK", "8")),
            max_seq_len=int(os.environ.get("PT_SERVE_MAX_LEN",
                                           "48" if spec_k_env
                                           else "32")),
            **{**spec_kw, **kw})
        serve_cfg = make_cfg()
    else:
        # the headline-bench decode model (~0.44B, one v5e chip)
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12,
            max_position_embeddings=2048, dtype="bfloat16",
            use_parallel_cross_entropy=False)
        n_req = int(n_req_env) if n_req_env else 64
        rate = float(rate_env) if rate_env else 4.0
        prompt_rng, new_rng = (64, 192), (64, 256)
        make_cfg = lambda **kw: ServingConfig(  # noqa: E731
            max_seq_len=int(os.environ.get("PT_SERVE_MAX_LEN", "512")),
            **{**spec_kw, **kw})
        serve_cfg = make_cfg()
    seed = int(os.environ.get("PT_SERVE_BENCH_SEED", "0"))
    if shared and (serve_cfg.max_seq_len is None or
                   shared + prompt_rng[1] + new_rng[1]
                   > serve_cfg.max_seq_len):
        raise SystemExit(
            f"PT_SERVE_BENCH_SHARED={shared} would exceed max_seq_len "
            f"{serve_cfg.max_seq_len} with prompts up to "
            f"{prompt_rng[1]} + {new_rng[1]} new tokens — raise "
            f"PT_SERVE_MAX_LEN or shrink the shared prefix")

    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        for p in model.parameters():
            p._data = p._data.astype("bfloat16")
    model.eval()

    trace = build_trace(n_req, rate, cfg.vocab_size, prompt_rng, new_rng,
                        seed=seed, shared_prefix=shared, motif=motif)

    def replay(engine):
        """Submit each request when its arrival time passes, step the
        engine whenever it has work. Request timestamps (TTFT,
        per-token) come from the engine's own perf_counter clock; a
        host transfer per decode round makes the timing honest through
        the tunnel (the emitted token IS fetched — CLAUDE.md timing
        rules)."""
        reqs = []
        t0 = time.perf_counter()
        i = 0
        while i < len(trace) or engine.has_work():
            now = time.perf_counter() - t0
            while i < len(trace) and trace[i][0] <= now:
                _, prompt, new = trace[i]
                reqs.append(engine.submit(prompt, max_new_tokens=new))
                i += 1
            if engine.has_work():
                engine.step()
            elif i < len(trace):
                time.sleep(min(trace[i][0] - now, 0.02))
        return reqs, time.perf_counter() - t0

    if replicas > 1:
        engine = RouterEngine(
            model, serve_cfg, RouterConfig(replicas=replicas,
                                           mode="inproc"))
    else:
        engine = ServingEngine(model, serve_cfg)
    engine.warmup()  # compiles (or exec-cache-loads) outside the clock
    reqs, wall = replay(engine)
    # snapshot the monitor AND the exec-cache account NOW: the optional
    # spec-off A/B engine below must not leak its counters or cache
    # traffic into the main run's telemetry
    try:
        mon_snap = _mon.snapshot()
    except Exception:  # noqa: BLE001 — telemetry must not break the run
        mon_snap = None
    try:
        from paddle_tpu.jit import exec_cache as _ec_snap_mod

        ec_snap = (_ec_snap_mod.stats()
                   if _ec_snap_mod.enabled() else None)
    except Exception:  # noqa: BLE001
        ec_snap = None
    # live-plane snapshot NOW for the same reason: the A/B engines
    # below would keep feeding the shared sketches/watchdog
    try:
        live_snap = _live.snapshot() if _live.enabled() else None
        live_sketches = (_live.merged_sketches()
                         if _live.enabled() else {})
    except Exception:  # noqa: BLE001
        live_snap, live_sketches = None, {}

    stats = engine.stats()
    tokens = sum(len(r.output) for r in reqs)
    tps = tokens / wall if wall > 0 else 0.0
    ttft = [(r.t_first - r.t_submit) * 1e3 for r in reqs
            if r.t_first is not None]
    tpot = [(r.t_done - r.t_first) * 1e3 / (len(r.output) - 1)
            for r in reqs if r.t_done is not None and len(r.output) > 1]
    # prefix-cache readout: hit rate over every (re-)prefilled context
    # token, and the cached-vs-cold TTFT A/B — grouped by the FIRST
    # admission's cache credit (the prefill that set t_first; a later
    # recompute hit must not relabel a cold-TTFT request as cached)
    hit, miss = stats["prefix_hit_tokens"], stats["prefix_miss_tokens"]
    hit_rate = hit / (hit + miss) if (hit + miss) else 0.0
    ttft_cached = [(r.t_first - r.t_submit) * 1e3 for r in reqs
                   if r.t_first is not None and r.ttft_cached_tokens]
    ttft_cold = [(r.t_first - r.t_submit) * 1e3 for r in reqs
                 if r.t_first is not None and not r.ttft_cached_tokens]

    # per-request latency attribution (docs/SERVING.md): the engine's
    # telescoping clock bills every wall-ms of a request's life to
    # exactly one of {queue, prefill, decode, preempted}, so the phase
    # means sum to the measured end-to-end latency — phase_sum_vs_total
    # self-reports that identity (the acceptance bound is 5%), and
    # queue_share is what perf_guard --queue-share-growth judges
    fins = [r for r in reqs if r.t_done is not None]
    attribution = None
    if fins:
        def _mean(xs):
            return sum(xs) / len(xs)

        q_mean = _mean([r.queue_ms for r in fins])
        p_mean = _mean([r.prefill_ms for r in fins])
        d_mean = _mean([r.decode_ms for r in fins])
        pre_mean = _mean([r.preempted_ms for r in fins])
        total_mean = _mean([(r.t_done - r.t_submit) * 1e3 for r in fins])
        phase_sum = q_mean + p_mean + d_mean + pre_mean
        attribution = {
            "queue_ms_mean": round(q_mean, 3),
            "prefill_ms_mean": round(p_mean, 3),
            "decode_ms_mean": round(d_mean, 3),
            "preempted_ms_mean": round(pre_mean, 3),
            "total_ms_mean": round(total_mean, 3),
            "phase_sum_vs_total": (round(phase_sum / total_mean, 4)
                                   if total_mean > 0 else None),
            "queue_share": (round(q_mean / total_mean, 4)
                            if total_mean > 0 else None),
            "queue_ms_p99": round(percentile(
                [r.queue_ms for r in fins], 99), 3),
            "prefill_refunded_tokens": sum(
                r.prefill_refunded_tokens for r in fins),
            "spec_rounds": sum(r.spec_rounds for r in fins),
            "accepted_tokens": sum(r.accepted_tokens for r in fins),
        }

    # HBM roofline (decode_bench's byte model on the decode phase): per
    # step the chip reads every matmul weight once (lanes share the
    # read) + each live lane's KV prefix, writes one KV token per
    # layer/lane. kv_read_tokens is the engine's live-prefix count — the
    # bytes a paged-attention kernel would move; the XLA gathered step
    # reads whole tables, so measured-vs-model gap = paging overhead.
    db = _load_decode_bench()
    # byte-size facts from the engine's OWN param arrays — re-running
    # _collect_params would materialize a duplicate full weight copy
    # (~GBs held live in a bench whose point is HBM headroom)
    params = engine._params
    embed_nbytes = params["embed"].nbytes
    # decode_rounds = plain decode steps + speculative verify steps:
    # every round reads the matmul weights exactly once either way —
    # fewer rounds for the same tokens IS speculation's byte saving
    rounds = stats["decode_rounds"]
    lane_rows = (stats["decoded_tokens"] / max(rounds, 1))
    embed_row_bytes = lane_rows * cfg.hidden_size \
        * params["embed"].dtype.itemsize
    param_bytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(params)
    ) - embed_nbytes + embed_row_bytes
    # KV bytes from the pool's ACTUAL itemsize (+ scale bytes), not an
    # assumed 2-byte element — before int8 KV landed this line billed
    # every pool as bf16; worker-mode routers hold no local pool, so
    # they derive the itemsize from the config they dispatched
    kv_int8 = bool(stats.get("kv_int8", False))
    kpool = getattr(engine, "_kpool", None)
    kv_el_bytes = (int(kpool.dtype.itemsize) if kpool is not None
                   else 1 if kv_int8
                   else 2 if cfg.dtype == "bfloat16" else 4)
    scale_bytes = 4 if kv_int8 else 0  # one fp32 amax per (pos, kv_head)
    tok_kv_bytes, allocatable = kv_byte_model(
        cfg, stats["num_blocks"], stats["block_size"], kv_el_bytes,
        scale_bytes)
    decode_bytes = (rounds * param_bytes
                    + stats["kv_read_tokens"] * tok_kv_bytes
                    + stats["decoded_tokens"] * tok_kv_bytes)
    # the dense gathered read's byte model (every table slot, live or
    # not): with the paged kernel active the live-prefix model above is
    # what the chip actually moves, and util_dense - util is the
    # fraction of the pipe the paged read freed
    dense_bytes = (rounds * param_bytes
                   + stats["kv_dense_read_tokens"] * tok_kv_bytes
                   + stats["decoded_tokens"] * tok_kv_bytes)
    decode_wall = stats["decode_wall_s"] or 1e-9
    achieved_gbps = decode_bytes / decode_wall / 1e9
    dense_gbps = dense_bytes / decode_wall / 1e9
    peak = db._peak_hbm_gbps(jax.devices()[0])

    rec = {"metric": "serving_tokens_per_sec",
           "value": round(tps, 1), "unit": "tokens/s",
           "tokens_per_sec": round(tps, 1),
           "decode_tokens_per_sec": round(
               stats["decoded_tokens"] / decode_wall, 1),
           "ttft_ms_p50": round(percentile(ttft, 50), 2) if ttft else None,
           "ttft_ms_p99": round(percentile(ttft, 99), 2) if ttft else None,
           "tpot_ms_p50": round(percentile(tpot, 50), 3) if tpot else None,
           "tpot_ms_p99": round(percentile(tpot, 99), 3) if tpot else None,
           "attribution": attribution,
           "requests": len(reqs),
           "completed": stats["finished"],
           "generated_tokens": tokens,
           "arrival_rate_per_s": rate,
           "trace_seed": seed,
           "lanes": stats["lanes"],
           "block_size": stats["block_size"],
           "num_blocks": stats["num_blocks"],
           "prefill_chunk": stats["prefill_chunk"],
           "preemptions": stats["preemptions"],
           "decode_steps": stats["decode_steps"],
           "verify_steps": stats["verify_steps"],
           "decode_rounds": rounds,
           "prefill_chunks": stats["prefill_chunks"],
           # speculative decoding readout (docs/SERVING.md): accept_rate
           # = accepted/proposed draft tokens (post-trim), and the
           # tokens-per-round multiplier speculation bought; spec-off
           # lines omit accept_rate so perf_guard's --accept-drop gate
           # skips them
           "spec": bool(stats["spec"]),
           "spec_k": stats["spec_k"],
           "tokens_per_decode_step": round(
               stats["decoded_tokens"] / rounds, 3) if rounds else None,
           "prefix_cache": bool(stats["prefix_cache"]),
           "shared_prefix_tokens": shared,
           "prefix_hit_rate": round(hit_rate, 4),
           "prefix_hit_tokens": hit,
           "prefix_miss_tokens": miss,
           "ttft_ms_p50_cached": (round(percentile(ttft_cached, 50), 2)
                                  if ttft_cached else None),
           "ttft_ms_p50_cold": (round(percentile(ttft_cold, 50), 2)
                                if ttft_cold else None),
           "hbm_gb_per_s": round(achieved_gbps, 1),
           "hbm_model_bytes_per_step": int(
               decode_bytes / max(rounds, 1)),
           "hbm_peak_gb_per_s": peak,
           "hbm_util": (round(achieved_gbps / peak, 4) if peak else None),
           "int8_weights": serve_cfg.int8_weights,
           # int8-KV capacity line (docs/SERVING.md "int8 KV"):
           # kv_bytes_per_token follows the pool's own itemsize (+ fp32
           # scale bytes); allocatable_tokens is what the UNQUANTIZED
           # pool's byte budget buys at that rate — int8 reports ~1.94x
           # bf16's at head_dim=128 (the >=1.9x acceptance gate)
           "kv_int8": kv_int8,
           "kv_bytes_per_token": int(tok_kv_bytes),
           "allocatable_tokens": int(allocatable),
           "kv_pool_bytes": stats.get("kv_pool_bytes"),
           "paged_attention": bool(stats["paged_attention"]),
           "replicas": replicas if replicas > 1 else 1}
    if replicas > 1:
        # router readout: affinity hit rate is the --affinity-drop
        # gate's input; load_balance_spread = (max-min)/total dispatches
        # (0 = perfectly even, 1 = one replica took everything)
        disp = stats["dispatches_per_replica"]
        rec["affinity"] = bool(stats["affinity"])
        rec["affinity_hit_rate"] = round(stats["affinity_hit_rate"], 4)
        rec["dispatches_per_replica"] = disp
        rec["load_balance_spread"] = round(
            (max(disp) - min(disp)) / max(sum(disp), 1), 4)
        rec["redispatched"] = stats["router"]["redispatches"]
        rec["dead_replicas"] = stats["router"]["dead_replicas"]
    # SLO readout (docs/OBSERVABILITY.md "Live telemetry plane"): the
    # streaming-sketch view of the SAME run next to the exact-numpy
    # percentiles above — targets + breach count feed perf_guard's
    # --slo-breach gate, and sketch_err_pct self-reports the sketch's
    # honesty (must sit within one log-bucket width, ~5%, of exact)
    rec["slo_ttft_ms_p99"] = (float(os.environ["PT_SLO_TTFT_MS_P99"])
                              if os.environ.get("PT_SLO_TTFT_MS_P99")
                              else None)
    rec["slo_tpot_ms_p99"] = (float(os.environ["PT_SLO_TPOT_MS_P99"])
                              if os.environ.get("PT_SLO_TPOT_MS_P99")
                              else None)
    if live_snap is not None:
        lslo = live_snap["slo"]
        worst = lslo["worst_burn"]
        sk_ttft = live_sketches.get("ttft_ms")
        sketch_p99 = (round(sk_ttft.quantile(0.99), 3)
                      if sk_ttft is not None and sk_ttft.count else None)
        err_pct = None
        if ttft and sketch_p99 is not None:
            # nearest-rank exact, matching the sketch's own rank rule —
            # numpy's interpolated p99 differs by whole samples at
            # small n, which is not sketch error
            xs = sorted(ttft)
            exact_p99 = xs[min(len(xs) - 1,
                               max(0, -(-99 * len(xs) // 100) - 1))]
            if exact_p99:
                err_pct = round(
                    abs(sketch_p99 - exact_p99) / exact_p99 * 100, 3)
        rec["slo"] = {
            "targets": lslo["targets"],
            "breaches": lslo["breaches"],
            "worst_burn": (round(max(worst.values()), 3)
                           if worst else 0.0),
            "burn_windows": {"fast_steps": lslo["fast_window_steps"],
                             "slow_steps": lslo["slow_window_steps"]},
            "sketch_p99_ttft_ms": sketch_p99,
            "sketch_err_pct": err_pct,
        }
    if stats["spec"]:
        prop = stats["spec_proposed_tokens"]
        rec["accept_rate"] = round(
            stats["spec_accepted_tokens"] / prop, 4) if prop else 0.0
        rec["spec_proposed_tokens"] = prop
        rec["spec_accepted_tokens"] = stats["spec_accepted_tokens"]
        rec["spec_bonus_tokens"] = stats["spec_bonus_tokens"]
    if stats["spec"] and os.environ.get(
            "PT_SERVE_BENCH_SPEC_AB", "0") == "1":
        # spec-on vs spec-off A/B: the SAME trace through a fresh
        # plain-decode engine — the decode-rounds delta is the claim
        # ("one verify round advances several tokens"), the tokens/s
        # delta is what it was worth end to end on this box
        eng_off = ServingEngine(model, make_cfg(spec=False))
        eng_off.warmup()
        reqs_off, wall_off = replay(eng_off)
        st_off = eng_off.stats()
        toks_off = sum(len(r.output) for r in reqs_off)
        rec["spec_off"] = {
            "tokens_per_sec": round(toks_off / wall_off, 1)
            if wall_off > 0 else 0.0,
            "decode_rounds": st_off["decode_rounds"],
            "decode_tokens_per_sec": round(
                st_off["decoded_tokens"]
                / (st_off["decode_wall_s"] or 1e-9), 1),
        }
    if kv_int8 and os.environ.get("PT_SERVE_BENCH_KV_AB", "0") == "1":
        # int8-vs-bf16 KV A/B (hwbench's serving_int8kv row): the SAME
        # trace through a fresh engine whose pool stores the model
        # dtype — the allocatable_tokens delta is the HBM-capacity
        # claim, the tokens/s + TTFT delta is what quantize-on-write /
        # dequant-on-read cost end to end on this box
        eng_bf = ServingEngine(model, make_cfg(kv_int8=False))
        eng_bf.warmup()
        reqs_bf, wall_bf = replay(eng_bf)
        st_bf = eng_bf.stats()
        toks_bf = sum(len(r.output) for r in reqs_bf)
        ttft_bf = [(r.t_first - r.t_submit) * 1e3 for r in reqs_bf
                   if r.t_first is not None]
        tok_bf, alloc_bf = kv_byte_model(
            cfg, st_bf["num_blocks"], st_bf["block_size"],
            int(eng_bf._kpool.dtype.itemsize), 0)
        rec["kv_bf16"] = {
            "tokens_per_sec": round(toks_bf / wall_bf, 1)
            if wall_bf > 0 else 0.0,
            "ttft_ms_p50": (round(percentile(ttft_bf, 50), 2)
                            if ttft_bf else None),
            "kv_bytes_per_token": int(tok_bf),
            "allocatable_tokens": int(alloc_bf),
            "kv_pool_bytes": st_bf["kv_pool_bytes"],
        }
        try:
            from paddle_tpu.monitor import memory as _memobs

            pk = _memobs.device_peak_gib()
            if pk is not None:
                rec["kv_bf16"]["peak_hbm_gib"] = pk
        except Exception:  # noqa: BLE001 — a readout must not break the line
            pass
    if stats["paged_attention"] and peak:
        # the dense read this engine no longer performs, as utilization
        # (docs/KERNELS.md: the paged kernel's measured-win readout)
        rec["hbm_util_dense"] = round(dense_gbps / peak, 4)
        rec["hbm_util_delta"] = round((dense_gbps - achieved_gbps)
                                      / peak, 4)
    try:
        from paddle_tpu.ops.pallas import search as _ksearch

        # {family: engaged} for the guard's engagement-regression gate;
        # the serving engine's ACTUAL read path overrides the
        # table-derived view (forced modes included)
        kernels = _ksearch.engagement_report()
        # the engine reads through paged_attention_int8 when kv_int8 —
        # override the family it ACTUALLY routed, not the bf16 one
        kernels[stats.get("paged_family", "paged_attention")] = bool(
            stats["paged_attention"])
        rec["kernels"] = kernels
    except Exception:  # noqa: BLE001 — a readout must not break the line
        pass
    # runtime telemetry rides along like bench.py's line: compile cost
    # actually paid + exec-cache traffic (the warm-server-start proof)
    try:
        from paddle_tpu import monitor as _mon
        from paddle_tpu.jit import exec_cache as _ec

        tel = {}
        snap = mon_snap if mon_snap is not None else _mon.snapshot()
        _ch = snap["histograms"].get("jit/compile_ms")
        tel["compile_ms_total"] = round(_ch["sum"], 1) if _ch else 0.0
        # top-level too (→ the persisted record's extra): perf_guard's
        # --compile-growth gate reads baseline extra.compile_ms_total,
        # and exec_cache_enabled keeps cache-on/off runs from
        # false-judging each other — same shape as bench.py's record
        rec["compile_ms_total"] = tel["compile_ms_total"]
        rec["exec_cache_enabled"] = _ec.enabled()
        serv = {k.split("/", 1)[1]: v
                for k, v in snap["counters"].items()
                if k.startswith("serving/") and v}
        if serv:
            tel["serving"] = serv
        rout = {k.split("/", 1)[1]: v
                for k, v in snap["counters"].items()
                if k.startswith("router/") and v}
        if rout:
            tel["router"] = rout
        if _ec.enabled():
            tel["exec_cache"] = ec_snap if ec_snap is not None \
                else _ec.stats()
        rec["telemetry"] = tel
    except Exception:  # noqa: BLE001 — telemetry must not break the line
        pass
    if smoke:
        rec["note"] = "cpu smoke mode; not a TPU number"
    else:
        from paddle_tpu.utils import measurements as _meas

        _meas.record_rec_or_warn(rec)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
