"""MFU at Llama-2-7B GEOMETRY (BASELINE config 4 names 7B; round-4's
headline was 0.44B-shaped).

A 16 GiB chip cannot hold all of 7B + Adam + masters, but MFU is set by
the per-layer matmul shapes, not the layer count — so this benches a
2-layer stack with the exact 7B layer geometry (hidden 4096, 32 heads,
head_dim 128, ffn 11008, vocab 32000; reference Llama-2-7B config) and
persists `llama7b_geometry_tokens_per_sec_per_chip`. If MFU holds ≥0.6
here, the 0.44B headline claim generalizes to 7B shapes; if it drops,
that is the finding.

Memory at the default (2 layers + tied-size embed/lm_head ≈ 0.67B
params): bf16 params 1.3G + fp32 masters 2.7G + moments 5.3G ≈ 9.3G,
leaving ~6G for activations at b4×s1024 (flash kernel engaged at
s1024/d128 per flash_tune.json).

Usage: python benchmarks/llama7b_geometry.py [--smoke]
Knobs: PT_7B_LAYERS (2), PT_7B_BATCH (4), PT_7B_CE_CHUNK (4096 — the
[4096-row, 32000-vocab] fp32 logits would be 0.5G/microstep otherwise).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import _peak_flops, _probe_backend, enable_compilation_cache

    enable_compilation_cache()
    smoke = "--smoke" in sys.argv
    if not smoke:
        try:
            smoke = _probe_backend() == "cpu"
        except RuntimeError as e:
            print(f"llama7b_geometry: backend unavailable: {e}",
                  file=sys.stderr)
            return 2
    print(f"llama7b_geometry: smoke={smoke}", flush=True)

    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if smoke:
        layers, batch, seq, steps, warmup = 1, 1, 64, 2, 1
        vocab, hidden, heads, ffn = 1024, 256, 4, 704
    else:
        layers = int(os.environ.get("PT_7B_LAYERS", "2"))
        batch = int(os.environ.get("PT_7B_BATCH", "4"))
        seq, steps, warmup = 1024, 10, 2
        vocab, hidden, heads, ffn = 32000, 4096, 32, 11008
    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=ffn,
        num_hidden_layers=layers, num_attention_heads=heads,
        max_position_embeddings=seq, dtype="bfloat16",
        use_parallel_cross_entropy=False,
        ce_chunk_size=int(os.environ.get("PT_7B_CE_CHUNK", "4096")))
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    for p in model.parameters():
        p._data = p._data.astype("bfloat16")
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    step = TrainStep(model, opt, lambda m, i, l: m(i, l), donate=True)

    rng = np.random.RandomState(0)

    def batch_ids(i):
        return (pt.to_tensor(rng.randint(0, vocab, (batch, seq))),
                pt.to_tensor(rng.randint(0, vocab, (batch, seq))))

    for i in range(warmup):
        loss = step(*batch_ids(i))
    _ = float(np.asarray(loss.numpy()))  # transfer-backed sync
    t0 = time.perf_counter()
    for i in range(steps):
        loss = step(*batch_ids(i))
    final = float(np.asarray(loss.numpy()))  # sync
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    mfu = (tps * model.flops_per_token(seq) / _peak_flops(jax.devices()[0]))
    rec = {"metric": "llama7b_geometry_tokens_per_sec_per_chip",
           "value": round(tps, 1), "unit": "tokens/s",
           "mfu": round(mfu, 4), "layers": layers, "batch": batch,
           "seq": seq, "hidden": hidden, "heads": heads, "ffn": ffn,
           "model_params_b": round(n_params / 1e9, 3),
           "final_loss": round(final, 4)}
    if smoke:
        rec["note"] = "cpu smoke at shrunken geometry; not a TPU number"
    else:
        from paddle_tpu.utils import measurements as meas

        meas.record_rec_or_warn(rec)
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
