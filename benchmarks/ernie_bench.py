"""BASELINE config 5 single-chip proxy: ERNIE joint-pretraining throughput.

The real config-5 target (ERNIE-3.0 10B, semi-auto shard + pipeline on
v5p-32) needs a pod; the proxy here is a scaled ERNIE (same architecture:
shared trunk + NLU/NLG task branches, joint MLM+LM loss) sized to one v5e
chip, trained with the same whole-step-compiled TrainStep the pipe path
uses per stage.  Reference contract: BASELINE.md config 5.

Run: python benchmarks/ernie_bench.py [--smoke]
Prints one JSON line: {"metric": "ernie_pretrain_tokens_per_sec_per_chip"}.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import _peak_flops, enable_compilation_cache

    enable_compilation_cache()
    smoke = "--smoke" in sys.argv or jax.default_backend() == "cpu"
    print(f"ernie_bench: backend={jax.default_backend()} smoke={smoke}",
          file=sys.stderr, flush=True)

    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import ErnieConfig, ErnieForPretraining

    pt.seed(0)
    if smoke:
        cfg = ErnieConfig.tiny()
        batch, seq, steps, warmup = 2, 32, 2, 1
    else:
        # ~0.4B proxy of the 10B shape (trunk 16x1536/12h, task 4x512),
        # bf16 + fp32 masters; fits one v5e chip at b4 x s1024
        cfg = ErnieConfig(
            vocab_size=40000, hidden_size=1536, num_hidden_layers=16,
            num_attention_heads=12, intermediate_size=4096,
            task_hidden_size=512, num_task_layers=4,
            num_task_attention_heads=8, task_intermediate_size=2048,
            max_position_embeddings=1024, dtype="bfloat16",
            hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1)
        batch = int(os.environ.get("PT_ERNIE_BATCH", "4"))
        seq, steps, warmup = 1024, 10, 2
    model = ErnieForPretraining(cfg)
    if cfg.dtype == "bfloat16":
        for p in model.parameters():
            p._data = p._data.astype("bfloat16")
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=cfg.dtype == "bfloat16")

    def compute(m, ids, mlm_labels, lm_labels):
        return m(ids, mlm_labels=mlm_labels, lm_labels=lm_labels)

    step = TrainStep(model, opt, compute, donate=True)
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    mlm_labels = pt.to_tensor(np.where(rng.rand(batch, seq) < 0.15,
                                       ids.numpy(), -100))
    lm_labels = pt.to_tensor(ids.numpy())

    for _ in range(warmup):
        float(np.asarray(step(ids, mlm_labels, lm_labels).numpy()))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, mlm_labels, lm_labels)
    final = float(np.asarray(loss.numpy()))
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    tps = batch * seq * steps / dt
    rec = {"metric": "ernie_pretrain_tokens_per_sec_per_chip",
           "value": round(tps, 1), "unit": "tokens/s",
           "final_loss": round(final, 3),
           "params_b": round(sum(int(np.prod(p.shape))
                                 for p in model.parameters()) / 1e9, 3)}
    if smoke:
        rec["note"] = "cpu smoke mode; not a TPU number"
    else:
        rec["mfu"] = round(tps * model.flops_per_token(seq)
                           / _peak_flops(jax.devices()[0]), 4)
        from paddle_tpu.utils import measurements as _meas

        _meas.record_rec_or_warn(rec)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
