"""Long-context end-to-end training throughput on one chip (SURVEY §5.7).

Trains the headline Llama architecture at seq 4096/8192/16384 with the
Pallas flash kernel engaged (batch scaled down to hold tokens/step at
8192 while batch > 1; from seq 16384 on, batch floors at 1 and
tokens/step = seq) and prints one JSON line per seq. This is the model-level
long-context evidence on top of the kernel-level autotune table: the
flash kernel's O(seq) memory is what lets the full train step fit at
seq >= 8192, where the composite's s*s score materialization would not.

Run: python benchmarks/longcontext_bench.py [--smoke] [--seqs 4096,8192]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_seq(seq: int, smoke: bool):
    import jax
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    if smoke:
        cfg = LlamaConfig.tiny()
        batch, steps, warmup = 1, 2, 1
        seq = min(seq, 128)
    else:
        # headline architecture (bench.py), position table stretched to
        # seq; batch keeps tokens/step at 8192 while batch > 1 so HBM
        # headroom goes to the longer context, not more rows (from seq
        # 16384 the floor of batch=1 makes tokens/step = seq)
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12,
            max_position_embeddings=seq, dtype="bfloat16",
            use_parallel_cross_entropy=False,
            ce_chunk_size=int(os.environ.get("PT_BENCH_CE_CHUNK", "0")))
        batch, steps, warmup = max(8192 // seq, 1), 10, 2
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        for p in model.parameters():
            p._data = p._data.astype("bfloat16")
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=cfg.dtype == "bfloat16")
    step = TrainStep(model, opt, lambda m, i, l: m(i, l), donate=True)

    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))

    for _ in range(warmup):
        float(np.asarray(step(ids, labels).numpy()).sum())
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    final = float(np.asarray(loss.numpy()).sum())
    dt = time.perf_counter() - t0
    assert np.isfinite(final)

    tokens_per_sec = batch * seq * steps / dt
    out = {"metric": "llama_longcontext_train_tokens_per_sec_per_chip",
           "value": round(tokens_per_sec, 1), "unit": "tokens/s",
           "seq": seq, "batch": batch, "final_loss": round(final, 3)}
    if not smoke:
        from bench import _peak_flops

        out["mfu"] = round(
            tokens_per_sec * model.flops_per_token(seq)
            / _peak_flops(jax.devices()[0]), 4)
        from paddle_tpu.utils import measurements as _meas

        _meas.record_rec_or_warn(out)
    print(json.dumps(out), flush=True)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--seqs", default="4096,8192,16384")
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import enable_compilation_cache

    enable_compilation_cache()
    smoke = args.smoke or jax.default_backend() == "cpu"
    if smoke and not args.smoke:
        print("longcontext_bench: no TPU — smoke mode", flush=True)

    # same pre-flight as bench.py: a kernel that cannot lower must cost
    # perf, not the run
    from paddle_tpu.ops import pallas as _pallas

    try:
        _pallas.check_tpu_lowering()
    except Exception as e:  # noqa: BLE001
        _pallas.disable()
        print(f"longcontext_bench: pallas disabled: {e}", flush=True)

    for seq in (int(s) for s in args.seqs.split(",")):
        bench_seq(seq, smoke)
        if smoke:  # every smoke seq clamps to the same tiny config
            break
    return 0


if __name__ == "__main__":
    sys.exit(main())
