"""Autoregressive decode throughput (tokens/sec/chip) for the compiled
KV-cache generation loop (`models/generation.py`).

Run: python benchmarks/decode_bench.py [--smoke]
Prints one JSON line: {"metric": "llama_decode_tokens_per_sec_per_chip", ...}
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import enable_compilation_cache

    enable_compilation_cache()
    smoke = "--smoke" in sys.argv or jax.default_backend() == "cpu"
    print(f"decode_bench: backend={jax.default_backend()} smoke={smoke}",
          file=sys.stderr, flush=True)

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate

    pt.seed(0)
    if smoke:
        cfg = LlamaConfig.tiny()
        batch, prompt, new = 2, 8, 8
    else:
        # the headline-bench model size (~0.44B, fits one v5e chip)
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12,
            max_position_embeddings=2048, dtype="bfloat16",
            use_parallel_cross_entropy=False)
        batch = int(os.environ.get("PT_DECODE_BATCH", "128"))
        prompt, new = 128, 256
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        for p in model.parameters():
            p._data = p._data.astype("bfloat16")
    model.eval()
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, prompt)))

    # sync via host transfer ONLY: through the tunneled PJRT plugin
    # jax.block_until_ready acks enqueue, not completion — it measured a
    # 3-rep decode loop at 5 ms that the transfer-synced truth puts at
    # ~3.6 s (the round-3/round-4 "705k tok/s" records were this artifact)
    out = generate(model, ids, max_new_tokens=new)  # compile + warm
    _ = np.asarray(out.numpy())
    t0 = time.perf_counter()
    reps = 1 if smoke else 3
    for i in range(reps):
        out = generate(model, ids, max_new_tokens=new, seed=i)
    _ = np.asarray(out.numpy())
    dt = time.perf_counter() - t0
    tps = batch * new * reps / dt
    rec = {"metric": "llama_decode_tokens_per_sec_per_chip",
           "value": round(tps, 1), "unit": "tokens/s",
           "batch": batch, "prompt_len": prompt, "new_tokens": new}
    if smoke:
        rec["note"] = "cpu smoke mode; not a TPU number"
    else:
        from paddle_tpu.utils import measurements as _meas

        _meas.record_rec_or_warn(rec)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
