"""Autoregressive decode throughput (tokens/sec/chip) for the compiled
KV-cache generation loop (`models/generation.py`).

Run: python benchmarks/decode_bench.py [--smoke]
Prints one JSON line: {"metric": "llama_decode_tokens_per_sec_per_chip", ...}
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _peak_hbm_gbps(device):
    """Nominal HBM bandwidth by device kind (GB/s); None when unknown.
    v5e: 819 GB/s HBM2E (public spec)."""
    kind = getattr(device, "device_kind", "").lower()
    for tag, bw in (("v5 lite", 819.0), ("v5e", 819.0),
                    ("v5p", 2765.0), ("v5", 1228.0),
                    ("v4", 1228.0), ("v6", 1640.0)):
        if tag in kind:
            return bw
    return None


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import enable_compilation_cache

    enable_compilation_cache()
    smoke = "--smoke" in sys.argv or jax.default_backend() == "cpu"
    print(f"decode_bench: backend={jax.default_backend()} smoke={smoke}",
          file=sys.stderr, flush=True)

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate

    pt.seed(0)
    if smoke:
        cfg = LlamaConfig.tiny()
        batch, prompt, new = 2, 8, 8
    else:
        # the headline-bench model size (~0.44B, fits one v5e chip)
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12,
            max_position_embeddings=2048, dtype="bfloat16",
            use_parallel_cross_entropy=False)
        batch = int(os.environ.get("PT_DECODE_BATCH", "128"))
        prompt, new = 128, 256
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        for p in model.parameters():
            p._data = p._data.astype("bfloat16")
    model.eval()
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, prompt)))

    # sync via host transfer ONLY: through the tunneled PJRT plugin
    # jax.block_until_ready acks enqueue, not completion — it measured a
    # 3-rep decode loop at 5 ms that the transfer-synced truth puts at
    # ~3.6 s (the round-3/round-4 "705k tok/s" records were this artifact)
    out = generate(model, ids, max_new_tokens=new)  # compile + warm
    _ = np.asarray(out.numpy())
    t0 = time.perf_counter()
    reps = 1 if smoke else 3
    for i in range(reps):
        out = generate(model, ids, max_new_tokens=new, seed=i)
    _ = np.asarray(out.numpy())
    dt = time.perf_counter() - t0
    tps = batch * new * reps / dt

    # HBM accounting (round-4 verdict weak #2: decode is bandwidth-bound
    # — say how much of the pipe is actually used). Per decode step the
    # chip reads every weight once (batch shares the read) plus each
    # lane's live KV prefix, and writes one KV token per layer/lane.
    int8 = os.environ.get("PT_DECODE_INT8") == "1"
    from paddle_tpu.models import generation as _gen

    decode_params = _gen._collect_params(model, int8_weights=int8)
    # the embedding table is GATHERED (batch rows/step), not read whole:
    # count the actual row traffic, not the table size (~11% of total
    # bf16 bytes at the bench shape, more under int8)
    embed_nbytes = decode_params["embed"].nbytes
    embed_row_bytes = (batch * cfg.hidden_size
                       * decode_params["embed"].dtype.itemsize)
    param_bytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(decode_params)
    ) - embed_nbytes + embed_row_bytes
    kv_dtype_bytes = 2 if cfg.dtype == "bfloat16" else 4
    nkv = getattr(cfg, "num_key_value_heads", None) \
        or cfg.num_attention_heads
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    avg_len = prompt + new / 2.0
    kv_read = (batch * cfg.num_hidden_layers * 2 * nkv * head_dim
               * avg_len * kv_dtype_bytes)
    kv_write = (batch * cfg.num_hidden_layers * 2 * nkv * head_dim
                * kv_dtype_bytes)
    bytes_per_step = param_bytes + kv_read + kv_write
    steps = new * reps
    achieved_gbps = bytes_per_step * steps / dt / 1e9
    peak = _peak_hbm_gbps(jax.devices()[0])
    rec = {"metric": "llama_decode_tokens_per_sec_per_chip",
           "value": round(tps, 1), "unit": "tokens/s",
           "batch": batch, "prompt_len": prompt, "new_tokens": new,
           "hbm_gb_per_s": round(achieved_gbps, 1),
           "hbm_model_bytes_per_step": int(bytes_per_step),
           "hbm_peak_gb_per_s": peak,
           "hbm_util": (round(achieved_gbps / peak, 4)
                        if peak else None),
           "int8_weights": int8}
    if smoke:
        rec["note"] = "cpu smoke mode; not a TPU number"
    else:
        from paddle_tpu.utils import measurements as _meas

        _meas.record_rec_or_warn(rec)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
