"""BASELINE configs 2 and 3, measured end-to-end on one chip.

- config 2: ResNet50 (ImageNet shapes), compiled whole-step training
  (`TrainStep` — the static/@to_static path's engine), imgs/sec/chip.
- config 3: BERT-base masked-LM, AMP O2 (bf16 params + fp32 masters),
  flash-attention kernel engaged (head_dim 64), tokens/sec/chip.

Secondary to `bench.py` (the driver's headline metric stays the Llama
MFU); prints one JSON line per config for PERF.md. Run:
    python benchmarks/baseline_configs.py [--smoke]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sync(x):
    return float(np.asarray(x.numpy()).sum())


def build_resnet_trainstep(smoke):
    """The ONE ResNet50 model+step (shared with
    tools/profile_train_step.py --model resnet — a profile must be
    attributable to the bench number). Returns (model, step, x, y,
    batch, hw)."""
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.vision.models import resnet50

    pt.seed(0)
    if smoke:
        batch, hw, depth_kw = 4, 32, {"num_classes": 10}
    else:
        # b256 measured 2084 imgs/s vs 1984 at b128 (round 4); the
        # persistent compile cache amortizes the bigger compile the
        # round-3 tunnel couldn't afford. PT_RESNET_BATCH to sweep
        batch = int(os.environ.get("PT_RESNET_BATCH", "256"))
        hw, depth_kw = 224, {}
    # PT_RESNET_FORMAT=NHWC: channel-last end-to-end — the round-5
    # layout A/B against the 0.130-MFU NCHW measurement
    fmt = os.environ.get("PT_RESNET_FORMAT", "NCHW")
    depth_kw["data_format"] = fmt
    model = resnet50(**depth_kw)
    model = pt.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model.parameters(),
                                multi_precision=True)
    loss_fn = pt.nn.CrossEntropyLoss()

    def compute(m, x, y):
        return loss_fn(m(x), y)

    step = TrainStep(model, opt, compute, donate=True)
    shape = (batch, 3, hw, hw) if fmt == "NCHW" else (batch, hw, hw, 3)
    x = pt.to_tensor((np.random.randn(*shape) * 0.1).astype(np.float32))
    x = x.astype("bfloat16")
    y = pt.to_tensor(np.random.randint(
        0, model.num_classes, (batch, 1)).astype(np.int64))
    return model, step, x, y, batch, hw


def bench_resnet50(smoke):
    import jax

    if smoke:
        steps, warmup = 2, 1
    else:
        steps, warmup = 10, 2
    model, step, x, y, batch, hw = build_resnet_trainstep(smoke)

    for _ in range(warmup):
        _sync(step(x, y))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    final = _sync(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    imgs_per_sec = batch * steps / dt
    # ResNet50@224 fwd ~= 4.1 GFLOP/img (MACs x2); training ~= 3x fwd
    flops_img = 3 * 4.1e9 if hw == 224 else None
    out = {"metric": "resnet50_train_imgs_per_sec_per_chip",
           "value": round(imgs_per_sec, 1), "unit": "imgs/s",
           "batch": batch, "final_loss": round(final, 3),
           "data_format": os.environ.get("PT_RESNET_FORMAT", "NCHW")}
    if flops_img:
        from bench import _peak_flops  # same chip peak table

        out["mfu"] = round(imgs_per_sec * flops_img
                           / _peak_flops(jax.devices()[0]), 4)
    if not smoke:
        from paddle_tpu.utils import measurements as _meas

        _meas.record_rec_or_warn(out)
    print(json.dumps(out), flush=True)
    return out


def bench_bert_mlm(smoke):
    import jax
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import BertConfig, BertForMaskedLM

    pt.seed(0)
    if smoke:
        cfg = BertConfig.tiny()
        batch, seq, steps, warmup = 2, 32, 2, 1
    else:
        # reference-default attn dropout 0.1: the Pallas kernel now runs
        # dropout IN-KERNEL (counter-hash mask, flash_attention.py), so
        # the honest config no longer forces the composite path
        cfg = BertConfig(max_position_embeddings=512, dtype="bfloat16")
        batch = int(os.environ.get("PT_BERT_BATCH", "64"))
        seq, steps, warmup = 512, 10, 2
    model = BertForMaskedLM(cfg)
    model = pt.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)

    def compute(m, ids, labels):
        return m(ids, labels=labels)

    step = TrainStep(model, opt, compute, donate=True)
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = pt.to_tensor(np.where(rng.rand(batch, seq) < 0.15,
                                   ids.numpy(), -100))

    for _ in range(warmup):
        _sync(step(ids, labels))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    final = _sync(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    tokens_per_sec = batch * seq * steps / dt
    # 6*N per token (N = params excl. embeddings-as-lookup is close enough
    # to N_total for BERT-base) + attention matmul term 12*s*h per layer
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_tok = 6 * n_params + cfg.num_hidden_layers * 12 * seq * cfg.hidden_size
    out = {"metric": "bert_base_mlm_tokens_per_sec_per_chip",
           "value": round(tokens_per_sec, 1), "unit": "tokens/s",
           "batch": batch, "final_loss": round(final, 3),
           "params_m": round(n_params / 1e6, 1)}
    if not smoke:
        from bench import _peak_flops

        out["mfu"] = round(tokens_per_sec * flops_tok
                           / _peak_flops(jax.devices()[0]), 4)
        from paddle_tpu.utils import measurements as _meas

        _meas.record_rec_or_warn(out)
    print(json.dumps(out), flush=True)
    return out


def main():
    smoke = "--smoke" in sys.argv or None
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from bench import enable_compilation_cache

    enable_compilation_cache()
    if smoke is None:
        smoke = jax.default_backend() == "cpu"
    print(f"baseline_configs: backend={jax.default_backend()} "
          f"smoke={smoke}", file=sys.stderr, flush=True)

    # same pre-flight as bench.py: a kernel that cannot lower must cost
    # perf, not the run
    from paddle_tpu.ops import pallas as _pallas

    try:
        _pallas.check_tpu_lowering()
    except Exception as e:  # noqa: BLE001
        _pallas.disable()
        print(f"baseline_configs: pallas disabled: {e}", file=sys.stderr,
              flush=True)

    if "--bert-only" not in sys.argv:
        bench_resnet50(smoke)
    if "--resnet-only" not in sys.argv:
        bench_bert_mlm(smoke)


if __name__ == "__main__":
    main()
