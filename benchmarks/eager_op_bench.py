"""Eager op-dispatch micro-benchmark.

Reference parity: `test/cpp/eager/performance_tests/benchmark_fluid_cuda.cc`
(per-op eager latency). Measures µs/op for a chained eager op loop with
autograd recording, with and without the compiled-primitive cache in
`ops/dispatch.py` (SURVEY §7 hard part (a)).

Run: python benchmarks/eager_op_bench.py  (pin JAX_PLATFORMS=cpu for a
deterministic host-side number; on TPU the dispatch overhead is the same
python path).
"""
from __future__ import annotations

import os
import time

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def bench_loop(n_iter=200, size=16, disable_cache=False):
    import paddle_tpu as pt
    from paddle_tpu.ops import dispatch

    x = pt.to_tensor(np.random.randn(size, size).astype(np.float32))
    w = pt.to_tensor(np.random.randn(size, size).astype(np.float32),
                     stop_gradient=False)

    if disable_cache:
        orig = dispatch._get_primitive
        dispatch._get_primitive = lambda *a: None
    try:
        def step():
            y = pt.matmul(x, w)
            y = pt.tanh(y)
            y = y + x
            y = y * 0.5
            return y.sum()

        step().numpy()  # warm
        t0 = time.perf_counter()
        for _ in range(n_iter):
            out = step()
        out.numpy()
        dt = time.perf_counter() - t0
    finally:
        if disable_cache:
            dispatch._get_primitive = orig
    n_ops = 5 * n_iter
    return dt / n_ops * 1e6  # µs/op


def main():
    cold = bench_loop(disable_cache=True)
    warm = bench_loop(disable_cache=False)
    print(f"eager dispatch, 5-op chain with grad recording:")
    print(f"  uncached (per-call jax.vjp trace): {cold:9.1f} µs/op")
    print(f"  compiled-primitive cache:          {warm:9.1f} µs/op")
    print(f"  speedup: {cold / warm:.1f}x")


if __name__ == "__main__":
    main()
