"""Host-overhead (dispatch-gap) benchmark: sync vs async stepping, on CPU.

The async-pipeline win on hardware is keeping the host's ~70–95 ms tunnel
round-trip out of the device's critical path (docs/ASYNC_PIPELINE.md). The
tunnel is not always up, so this benchmark makes the win CI-measurable
WITHOUT it: on any backend, a loop that materializes the loss every step
("sync") blocks the host for the step's remaining compute plus a transfer,
every step — while the AsyncStepper loop only blocks when its in-flight
bound is hit, and the dispatch of step k+1 (plus all the host-side
bookkeeping around it) overlaps step k's execution.

Measured quantity: **host-blocked ms/step** — time the host spends waiting
on device results (the per-step `.numpy()` in sync mode; bound-fences +
final drain in async mode). Dispatch/bookkeeping time is reported
separately (``loop_ms_per_step``). The structural invariant this asserts —
async host-blocked < sync host-blocked — holds on every backend: the sync
loop serializes [dispatch → compute → transfer] while the async loop
overlaps dispatch with compute and pays one transfer per run, not per step.

Prints ONE JSON line. Exit 0 when the async loop wins (the default-tier
smoke test asserts the same via :func:`run`).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(hidden, depth):
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep

    pt.seed(0)
    layers = []
    for _ in range(depth):
        layers += [pt.nn.Linear(hidden, hidden), pt.nn.ReLU()]
    layers += [pt.nn.Linear(hidden, 1)]
    net = pt.nn.Sequential(*layers)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=net.parameters())
    step = TrainStep(net, opt, lambda m, x, y: ((m(x) - y) ** 2).mean())
    return step


def _prep_batch(rng, batch, hidden):
    """Per-step host-side input work (the stand-in for decode/augment/
    tokenize): synthesize and normalize a batch. Both loops pay this
    identically; only the async loop can overlap it with device compute."""
    import paddle_tpu as pt

    x = rng.standard_normal((batch, hidden)).astype(np.float32)
    x = (x - x.mean(axis=1, keepdims=True)) / (x.std(axis=1, keepdims=True)
                                               + 1e-6)
    y = rng.standard_normal((batch, 1)).astype(np.float32)
    return pt.to_tensor(x), pt.to_tensor(y)


def run(steps=40, max_in_flight=4, hidden=256, depth=4, batch=256):
    """Measure both loop disciplines on fresh TrainSteps.

    host-blocked = time waiting on DEVICE results only (the per-step
    `.numpy()` in sync mode; bound-fences + final drain in async mode).
    Batch prep is identical host work in both loops and is excluded from
    the blocked number — the async win is that prep/dispatch of step k+1
    overlaps step k's compute, shrinking the fence wait; the sync loop
    pays the full remaining compute + a transfer every step.
    """
    from paddle_tpu.jit.train_step import AsyncStepper

    # -- sync loop: loss materialized every step ----------------------------
    step = _build(hidden, depth)
    rng = np.random.RandomState(0)
    x, y = _prep_batch(rng, batch, hidden)
    for _ in range(3):  # warmup: compile + first dispatches
        float(step(x, y).numpy())
    sync_blocked = 0.0
    t0 = time.perf_counter()
    for _ in range(steps):
        x, y = _prep_batch(rng, batch, hidden)
        loss = step(x, y)
        t_b = time.perf_counter()
        float(loss.numpy())
        sync_blocked += time.perf_counter() - t_b
    sync_wall = time.perf_counter() - t0

    # -- async loop: bounded in-flight, deferred sync -----------------------
    step = _build(hidden, depth)
    rng = np.random.RandomState(0)
    x, y = _prep_batch(rng, batch, hidden)
    for _ in range(3):
        float(step(x, y).numpy())
    stepper = AsyncStepper(step, max_in_flight=max_in_flight)
    t0 = time.perf_counter()
    for _ in range(steps):
        x, y = _prep_batch(rng, batch, hidden)
        loss = stepper(x, y)
    last = stepper.drain()
    async_wall = time.perf_counter() - t0
    assert np.isfinite(float(last.numpy()))

    res = {
        "metric": "host_blocked_ms_per_step",
        "unit": "ms",
        "steps": steps,
        "max_in_flight": max_in_flight,
        "sync_host_blocked_ms_per_step": round(sync_blocked / steps * 1e3, 3),
        "async_host_blocked_ms_per_step": round(
            stepper.host_blocked_s / steps * 1e3, 3),
        "sync_wall_ms_per_step": round(sync_wall / steps * 1e3, 3),
        "async_wall_ms_per_step": round(async_wall / steps * 1e3, 3),
    }
    res["async_wins"] = (res["async_host_blocked_ms_per_step"]
                         < res["sync_host_blocked_ms_per_step"])
    return res


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    res = run(steps=int(os.environ.get("PT_HOSTBENCH_STEPS", "40")))
    res["backend"] = jax.default_backend()
    if res["backend"] != "cpu":
        # PERF_MEASUREMENTS.json is the hardware record — CPU smoke runs
        # stay out of it (same convention as bench.py)
        try:
            from paddle_tpu.utils import measurements as _meas

            _meas.record("host_blocked_ms_per_step_async",
                         res["async_host_blocked_ms_per_step"], "ms",
                         extra={k: v for k, v in res.items()
                                if k not in ("metric", "unit")})
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            print(f"host_overhead_bench: persist failed: {e}",
                  file=sys.stderr, flush=True)
    print(json.dumps(res), flush=True)
    return 0 if res["async_wins"] else 1


if __name__ == "__main__":
    sys.exit(main())
