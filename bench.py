"""Benchmark: Llama causal-LM training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is tokens/sec/chip for a compiled full train step (fwd+bwd+AdamW,
bf16 params with fp32 masters) on a ~0.44B-param Llama config (sized to one v5e chip) — the
single-chip proxy for BASELINE config 4. "vs_baseline" is model FLOPs
utilization (MFU) divided by the 0.45 north-star target from BASELINE.json,
so 1.0 means the 45%-MFU goal is met on this chip.
"""
from __future__ import annotations

import json
import time

import numpy as np


# bf16 peak FLOPs/s per chip by TPU generation (public spec sheets)
_PEAK = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for k, v in _PEAK.items():
        if k in kind:
            return v
    return 459e12  # assume v5p-class if unknown


def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:  # smoke-mode so local runs finish; real numbers need a chip
        cfg = LlamaConfig.tiny(use_parallel_cross_entropy=False)
        batch, seq, steps, warmup = 2, 64, 3, 1
    else:
        # sized for a single v5e chip (16G HBM): ~0.44B params, bf16 +
        # fp32 masters + Adam moments ≈ 6G, activations ≈ 4G at b4×s1024
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12,
            max_position_embeddings=1024, dtype="bfloat16",
            use_parallel_cross_entropy=False)
        batch, seq, steps, warmup = 4, 1024, 10, 2

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        for p in model.parameters():
            p._data = p._data.astype("bfloat16")
    opt = pt.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(),
        multi_precision=cfg.dtype == "bfloat16")
    step = TrainStep(model, opt, lambda m, i, l: m(i, l), donate=True)

    ids = pt.to_tensor(np.random.randint(0, cfg.vocab_size, (batch, seq)))
    labels = pt.to_tensor(np.random.randint(0, cfg.vocab_size, (batch, seq)))

    for _ in range(warmup):
        float(step(ids, labels).numpy())  # host transfer = real sync
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    final_loss = float(loss.numpy())  # chained through params: syncs all
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    tokens_per_sec = batch * seq * steps / dt
    flops_tok = model.flops_per_token(seq)
    mfu = tokens_per_sec * flops_tok / _peak_flops(jax.devices()[0])
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
