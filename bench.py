"""Benchmark: Llama causal-LM training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is tokens/sec/chip for a compiled full train step (fwd+bwd+AdamW,
bf16 params with fp32 masters) on a ~0.44B-param Llama config (sized to one v5e chip) — the
single-chip proxy for BASELINE config 4. "vs_baseline" is model FLOPs
utilization (MFU) divided by the 0.45 north-star target from BASELINE.json,
so 1.0 means the 45%-MFU goal is met on this chip.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

# PT_BENCH_ASYNC A/B (docs/ASYNC_PIPELINE.md): unset = the default lazy
# loop (dispatch all steps, one final sync); "1"/"on" = AsyncStepper with
# a bounded in-flight window (depth PT_BENCH_ASYNC_DEPTH, default 2);
# "sync"/"0" = materialize the loss EVERY step — the worst-case host-in-
# the-critical-path baseline the async pipeline is measured against.
# A/B runs record under suffixed metric names so the measurement store
# keeps the three populations separate.
_ASYNC_KNOB = os.environ.get("PT_BENCH_ASYNC", "").lower()
_ASYNC_MODES = {"": "default", "1": "async", "on": "async", "async": "async",
                "0": "sync", "sync": "sync"}
if _ASYNC_KNOB not in _ASYNC_MODES:
    # fail loudly: a typo'd A/B arm must not silently record into the
    # unsuffixed headline population in PERF_MEASUREMENTS.json
    raise SystemExit(
        f"bench: unknown PT_BENCH_ASYNC={_ASYNC_KNOB!r} "
        f"(expected one of {sorted(k for k in _ASYNC_MODES if k)})")
_ASYNC_MODE = _ASYNC_MODES[_ASYNC_KNOB]

_METRIC = "llama_train_tokens_per_sec_per_chip" + {
    "default": "", "async": "_async", "sync": "_syncstep"}[_ASYNC_MODE]

_PIN_PLATFORM = (
    "import os, jax\n"
    "_p = os.environ.get('JAX_PLATFORMS')\n"
    "if _p:\n"
    "    jax.config.update('jax_platforms', _p)\n"
)


def _emit(value, vs_baseline, **extra):
    """The one JSON line the driver parses. Exactly one call wins."""
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    print(json.dumps({
        "metric": _METRIC,
        "value": value,
        "unit": "tokens/s",
        "vs_baseline": vs_baseline,
        **extra,
    }), flush=True)


_EMITTED = False


def _probe_backend(timeout_s: int = 240) -> str:
    """Check the jax backend initializes, in a throwaway subprocess so a
    hung/held TPU cannot wedge this process. Returns the backend name.

    Round-1 failure mode (VERDICT §weak 2): the chip was held by a
    timed-out client and backend init raised UNAVAILABLE — so retry with
    backoff before giving up, and never let one attempt hang forever.
    """
    # honor JAX_PLATFORMS via jax.config: the host sitecustomize pins the
    # platform *config* at interpreter start, which silently overrides env
    # vars (round-1 driver failure — see VERDICT).
    code = (_PIN_PLATFORM +
            "import jax; "
            "print(jax.default_backend(), len(jax.devices()), flush=True)")
    last_err = "unknown"
    for attempt in range(5):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s)
            if proc.returncode == 0 and proc.stdout.strip():
                return proc.stdout.split()[0]
            last_err = (proc.stderr or proc.stdout)[-500:]
        except subprocess.TimeoutExpired:
            last_err = f"backend init timed out after {timeout_s}s"
        if attempt < 4:
            wait = 15 * (attempt + 1)
            print(f"bench: backend probe attempt {attempt + 1} failed "
                  f"({last_err.splitlines()[-1] if last_err.strip() else last_err}); "
                  f"retrying in {wait}s", file=sys.stderr, flush=True)
            time.sleep(wait)
    raise RuntimeError(f"jax backend unavailable after retries: {last_err}")


def _load_perf_guard():
    """tools/perf_guard.py as a module (tools/ is not a package; the
    guard stays pure-stdlib so the report boxes can run it too)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "perf_guard.py")
    spec = importlib.util.spec_from_file_location("perf_guard", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _guard_verdict(line: dict, on_cpu: bool, baseline) -> dict:
    """Judge this run's line against ``baseline``; on a CPU smoke the
    hardware comparison is skipped but the runtime-health checks (retrace
    storm, starvation, error) still gate.

    ``baseline`` must be the last-good record captured BEFORE this run
    persisted its own (main() does; None = no baseline) — re-reading the
    store here would hand back the run itself as "last good" and the
    drop gate would compare the number to itself."""
    guard = _load_perf_guard()
    verdict = guard.evaluate(line, baseline, hardware=not on_cpu)
    if not verdict["ok"]:
        print(guard.format_verdict(line["metric"], verdict),
              file=sys.stderr, flush=True)
    return verdict


def enable_compilation_cache():
    """Persistent XLA compilation cache: a brief tunnel window must
    suffice, so never pay the same compile twice across invocations."""
    from paddle_tpu.utils.xla_cache import enable_compilation_cache as _e

    _e("~/.cache/paddle_tpu_xla_cache")


# bf16 peak FLOPs/s per chip by TPU generation (public spec sheets)
_PEAK = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for k, v in _PEAK.items():
        if k in kind:
            return v
    return 459e12  # assume v5p-class if unknown


def build_headline_trainstep(on_cpu: bool):
    """The ONE headline model+step (also profiled by
    tools/profile_train_step.py — a profile must be attributable to the
    bench number, so the config lives in exactly one place).

    Returns (model, step, batch, seq)."""
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_cpu:  # smoke-mode so local runs finish; real numbers need a chip
        cfg = LlamaConfig.tiny(
            use_parallel_cross_entropy=False,
            ce_chunk_size=int(os.environ.get("PT_BENCH_CE_CHUNK", "0")))
        batch, seq = 2, 64
    else:
        # sized for a single v5e chip (16G HBM): ~0.44B params, bf16 +
        # fp32 masters + Adam moments ≈ 5.7G, activations ≈ 5.6G at the
        # b8×s1024 default (11.3 GiB peak measured; b12 hits 14.1 and
        # regresses — see PERF.md batch sweep).
        # PT_BENCH_CE_CHUNK>0 switches the loss to the chunked CE (no
        # [N, V] fp32 logits) — the candidate MFU lever to A/B on
        # hardware (see PERF.md).
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12,
            max_position_embeddings=1024, dtype="bfloat16",
            use_parallel_cross_entropy=False,
            ce_chunk_size=int(os.environ.get("PT_BENCH_CE_CHUNK", "0")))
        # b8 measured MFU 0.647 vs 0.578 at b4 (+12%: 8192 rows fill the
        # MXU M dim; b10/b12 regress on HBM pressure) — PT_BENCH_BATCH to A/B
        batch, seq = int(os.environ.get("PT_BENCH_BATCH", "8")), 1024
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        for p in model.parameters():
            p._data = p._data.astype("bfloat16")
    opt = pt.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(),
        multi_precision=cfg.dtype == "bfloat16")
    step = TrainStep(model, opt, lambda m, i, l: m(i, l), donate=True)
    return model, step, batch, seq


def main():
    tpu_note = None
    try:
        backend = _probe_backend()
    except RuntimeError as e:
        # Round-3 failure mode: the tunnel's remote-compile service went
        # UNAVAILABLE mid-round (after the chip had already produced a
        # measured MFU — see PERF.md). A dead tunnel must not zero the
        # round: run the CPU smoke so the JSON line still parses, and say
        # exactly what happened.
        backend = "cpu"
        tpu_note = f"tpu unavailable, CPU smoke fallback: {e}"[:300]
        print(f"bench: {tpu_note}", file=sys.stderr, flush=True)
        os.environ["JAX_PLATFORMS"] = "cpu"
    print(f"bench: backend={backend}", file=sys.stderr, flush=True)
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    enable_compilation_cache()

    import paddle_tpu as pt
    from paddle_tpu import monitor as _mon
    from paddle_tpu.monitor import memory as _memobs
    from paddle_tpu.monitor import numerics as _numerics

    if os.environ.get("PT_BENCH_MONITOR", "1") != "0":
        # runtime telemetry (retraces / compiles / tunnel syncs) rides along
        # in the JSON line; the cost is off the hot path — compiled steps
        # bypass eager dispatch, so only tracing and sync fences count.
        # The memory observatory is NOT armed here: its per-step census
        # (a live-array walk inside log_step) would ride inside the
        # timed loop — opt in with PT_MONITOR_MEM=1; the `memory`
        # sub-object below takes one census AFTER the loop either way.
        _mon.enable()

    # Pre-flight: Mosaic-lower every Pallas kernel before the timed run.
    # If a kernel fails to lower, fall back to the XLA composite path so
    # the bug degrades MFU instead of zeroing the round (round-2 failure
    # mode: the old lse BlockSpec failed on hardware and rc=1'd the bench).
    from paddle_tpu.ops import pallas as _pallas

    pallas_note = None
    try:
        _pallas.check_tpu_lowering()
    except Exception as e:  # noqa: BLE001 — containment, not correctness
        _pallas.disable()
        pallas_note = f"pallas disabled (lowering failed): {e}"[:300]
        print(f"bench: {pallas_note}", file=sys.stderr, flush=True)

    on_cpu = jax.default_backend() == "cpu"
    steps, warmup = (3, 1) if on_cpu else (10, 2)
    model, step, batch, seq = build_headline_trainstep(on_cpu)
    vocab = model.config.vocab_size

    ids = pt.to_tensor(np.random.randint(0, vocab, (batch, seq)))
    labels = pt.to_tensor(np.random.randint(0, vocab, (batch, seq)))

    # step-metrics JSONL sink (opt-in: the default bench writes no files);
    # per-step lines are async-dispatch timings, only the final loss syncs
    slog = None
    if _mon.enabled() and os.environ.get("PT_MONITOR", "0") not in ("", "0"):
        slog = _mon.StepLogger(
            os.environ.get("PT_MONITOR_SINK") or "bench_steps.jsonl",
            meta={"source": "bench.py", "backend": backend,
                  "batch": batch, "seq": seq})

    stepper = step
    if _ASYNC_MODE == "async":
        from paddle_tpu.jit.train_step import AsyncStepper

        stepper = AsyncStepper(step, max_in_flight=int(
            os.environ.get("PT_BENCH_ASYNC_DEPTH", "2")))

    # goodput ledger over the whole bench (warmup + timed loop): the
    # line then says where the wall went — XLA compiles land in the
    # `compile` bucket via the TrainStep slot, everything outside the
    # bracketed step calls is `other` (monitor/goodput.py)
    from paddle_tpu.monitor import goodput as _gp

    gled = None
    if os.environ.get("PT_GOODPUT", "1") not in ("", "0"):
        _gp.reset_run()
        gled = _gp.Ledger()
        _gp.activate(gled)

    for _ in range(warmup):
        if gled is not None:
            gled.enter("productive_step")
        try:
            float(step(ids, labels).numpy())  # host transfer = real sync
        finally:
            if gled is not None:
                gled.exit()
    # post-warmup retrace baseline + live watchpoint: a retrace INSIDE the
    # timed loop means the throughput number includes an XLA compile — the
    # warning fires mid-run (tools/perf_guard.py re-checks it post-hoc)
    retrace_base = starved_base = None
    if _mon.enabled():
        _c0 = _mon.snapshot().get("counters", {})
        retrace_base = _c0.get("jit/retraces", 0)
        # warmup starvations (cold loader) must not gate the timed loop
        starved_base = _c0.get("io/prefetch_starvations", 0)
        _mon.watchpoint(
            "jit/retraces", retrace_base,
            message="bench: post-warmup retrace storm — a batch signature "
                    "changed inside the timed loop; this run's throughput "
                    "includes an XLA compile")
    # host_blocked: wall time the host spends inside step dispatch (+ the
    # per-step materialization in sync mode, + drain in async mode) — the
    # dispatch-gap number the PT_BENCH_ASYNC A/B compares
    host_blocked = 0.0
    t0 = time.perf_counter()
    for _ in range(steps):
        t_h = time.perf_counter()
        if gled is not None:
            gled.enter("productive_step")
        loss = stepper(ids, labels)
        if _ASYNC_MODE == "sync":
            float(loss.numpy())  # per-step host round-trip (the baseline)
        if gled is not None:
            gled.exit()
        host_blocked += time.perf_counter() - t_h
        if slog is not None:
            slog.log_step(num_samples=batch * seq)
    if _ASYNC_MODE == "async":
        t_h = time.perf_counter()
        stepper.drain()
        dt_drain = time.perf_counter() - t_h
        host_blocked += dt_drain
        if gled is not None:
            # the drain finishes dispatched steps: productive wall,
            # charged without bumping the ledger's step count
            gled.charge("productive_step", dt_drain)
    final_loss = float(loss.numpy())  # chained through params: syncs all
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    tokens_per_sec = batch * seq * steps / dt
    flops_tok = model.flops_per_token(seq)
    mfu = tokens_per_sec * flops_tok / _peak_flops(jax.devices()[0])
    extra = {"mfu": round(mfu, 4), "model_params_b": round(
        sum(int(np.prod(p.shape)) for p in model.parameters()) / 1e9, 3),
        "stepping": _ASYNC_MODE,
        "host_blocked_ms_per_step": round(host_blocked / steps * 1e3, 3),
        # the sweep config in the line: what this number measured, and
        # the guard's baseline filter (a b16 sweep record must not judge
        # a b8 run)
        "batch": batch, "seq": seq,
        "ce_chunk": model.config.ce_chunk_size}
    if _ASYNC_MODE == "async":
        extra["async_depth"] = stepper.max_in_flight
    if gled is not None:
        # wall-clock classification for the whole bench run (exact
        # telescoping; tools/perf_guard.py --goodput-drop gates the frac)
        gsnap = gled.snapshot()
        extra["goodput"] = gsnap
        extra["goodput_frac"] = round(gsnap["goodput_frac"], 4)
    if tpu_note:
        extra["note"] = tpu_note
        extra["see"] = "PERF.md records any TPU numbers measured earlier"

    # compiled-program audit account (PT_PROGRAM_AUDIT=1 — every fresh
    # compile above was judged at the exec-cache chokepoint): rides the
    # line AND the persisted record, so tools/perf_guard.py --audit can
    # fail a future line whose findings are new vs this baseline
    program_audit = None
    try:
        from paddle_tpu.analysis import program_audit as _pa

        if _pa.enabled():
            program_audit = _pa.report()
            extra["program_audit"] = program_audit
    except Exception:  # noqa: BLE001 — the audit must not break the line
        pass

    from paddle_tpu.utils import measurements as _meas

    # cold-vs-warm compile accounting: total XLA compile wall-time this
    # process paid (jit/compile_ms histogram — exec_cache hits pay none),
    # read once here so both the persisted record and the telemetry
    # sub-object carry the same number the perf guard's compile gate reads
    compile_ms_total = compile_count = None
    if _mon.enabled():
        _ch = _mon.snapshot().get("histograms", {}).get("jit/compile_ms")
        compile_ms_total = round(_ch["sum"], 1) if _ch else 0.0
        compile_count = _ch["count"] if _ch else 0

    # the guard's baseline MUST be read before this run's record lands in
    # the store — otherwise last_good returns the run itself and the
    # throughput gate compares the number to itself (always-pass) — and
    # at THIS run's sweep config, so A/B points at other batch/seq/chunk
    # settings are never a false baseline
    try:
        guard_baseline = _meas.last_good(_METRIC, match={
            "batch": batch, "seq": seq,
            "ce_chunk": model.config.ce_chunk_size})
    except Exception:  # noqa: BLE001
        guard_baseline = None

    # memory sub-object (cheap views first, so the persisted record can
    # carry peak HBM even if the expensive AOT accounting below times out)
    mem_obj = {"nan_check": _numerics.enabled()}
    try:
        led = _memobs.ledger()
        if led is not None:
            # observatory armed (PT_MONITOR_MEM=1): per-step censuses ran;
            # one more post-loop, then report the honest running peak
            led.census(tag="bench_end")
            mem_obj["peak_live_gib"] = round(led.peak_live_bytes / 2**30, 3)
        else:
            # one census AFTER the timed loop (never inside it): the
            # end-state live bytes, not a peak
            mem_obj["live_gib_end"] = round(
                _memobs.live_census()["live_bytes"] / 2**30, 3)
        cheap_peak = _memobs.device_peak_gib()
        if cheap_peak is not None:
            mem_obj["peak_hbm_gib"] = cheap_peak
    except Exception:  # noqa: BLE001 — memory views must not break the line
        pass

    if not on_cpu:
        # Persist the hardware number the moment it exists — a tunnel that
        # dies after this line can no longer erase the round's truth.
        rec_extra = {"mfu": round(mfu, 4),
                     "vs_baseline": round(mfu / 0.45, 4),
                     "batch": batch, "seq": seq,
                     "ce_chunk": model.config.ce_chunk_size,
                     "stepping": _ASYNC_MODE,
                     "host_blocked_ms_per_step":
                         extra["host_blocked_ms_per_step"],
                     "model_params_b": extra["model_params_b"],
                     "nan_check": _numerics.enabled()}
        if compile_ms_total is not None:
            # the guard's cold-start compile gate baselines on this; the
            # enabled flag lets it skip cache-on vs cache-off apples-to-
            # oranges comparisons (a cache-off run is not a regression)
            from paddle_tpu.jit import exec_cache as _ec0

            rec_extra["compile_ms_total"] = compile_ms_total
            rec_extra["exec_cache_enabled"] = _ec0.enabled()
        if mem_obj.get("peak_hbm_gib") is not None:
            rec_extra["peak_hbm_gib"] = mem_obj["peak_hbm_gib"]
        if program_audit is not None:
            rec_extra["program_audit"] = program_audit
        if extra.get("goodput_frac") is not None:
            rec_extra["goodput_frac"] = extra["goodput_frac"]
        try:
            _meas.record(_METRIC, round(tokens_per_sec, 2), "tokens/s",
                         extra=rec_extra)
        except Exception as e:  # noqa: BLE001
            print(f"bench: measurement persist failed: {e}",
                  file=sys.stderr, flush=True)
    else:
        # CPU fallback: surface the last-good hardware record inline so
        # the driver's JSON carries the provenance-stamped TPU truth even
        # when the tunnel is dead at bench time.
        try:
            lg = _meas.last_good(_METRIC)
        except Exception:  # noqa: BLE001
            lg = None
        if lg is not None:
            extra["last_good_tpu"] = lg
            extra["mfu_last_good_tpu"] = lg.get("extra", {}).get("mfu")
    # HBM accounting is free now: memory_analysis is served from the same
    # executable-cache entry the timed loop ran (jit/exec_cache.py), so
    # no second AOT compile and no tunnel round beyond the one fetch —
    # the timeout guard this used to need is gone with the compile.
    try:
        if mem_obj.get("peak_hbm_gib") is not None:
            extra["peak_hbm_gib"] = mem_obj["peak_hbm_gib"]
        elif not on_cpu:
            # tunneled PJRT plugin exposes no allocator stats — use XLA's
            # own executable memory accounting (args incl. donated params
            # + temporaries = live HBM during the step)
            ma_rec = _memobs.executable_record(step, ids, labels,
                                               name="bench/headline")
            extra["peak_hbm_gib"] = round(ma_rec["peak_bytes"] / 2**30, 2)
            extra["hbm_args_gib"] = round(ma_rec["args_bytes"] / 2**30, 2)
            extra["hbm_temp_gib"] = round(ma_rec["temp_bytes"] / 2**30, 2)
            mem_obj["peak_hbm_gib"] = extra["peak_hbm_gib"]
            mem_obj["source"] = "xla_analysis"
            mem_obj["executable"] = ma_rec
            # back-fill the already-persisted record: on the tunneled
            # chip this analysis is the ONLY peak-HBM source, and the
            # perf guard's HBM gate needs it on the baseline
            _meas.annotate_last(
                _METRIC, {"peak_hbm_gib": extra["peak_hbm_gib"]},
                value=round(tokens_per_sec, 2))
    except Exception:
        pass
    if on_cpu and "note" not in extra:
        extra["note"] = "cpu smoke mode; not a TPU number"
    if pallas_note:
        extra["pallas"] = pallas_note
    # runtime-health sub-object: a surprise retrace or a sync storm shows
    # up next to the ips it explains (BENCH_r*.json keeps both)
    try:
        snap = _mon.snapshot()
        c = snap.get("counters", {})
        tel = {"retraces": c.get("jit/retraces", 0),
               "compiles": c.get("jit/compiles", 0),
               "sync_count": c.get("tunnel/syncs", 0),
               "steps": steps}
        if retrace_base is not None:
            tel["post_warmup_retraces"] = (
                c.get("jit/retraces", 0) - retrace_base)
        starved = c.get("io/prefetch_starvations", 0) - (starved_base or 0)
        if starved:
            tel["prefetch_starvations"] = starved
        h = snap.get("histograms", {}).get("tunnel/sync_ms")
        if h:
            tel["sync_ms_p50"] = h["p50"]
            tel["sync_ms_max"] = h["max"]
        # cold-vs-warm compile delta: total compile wall-time this process
        # paid — ~0 on a warm PT_EXEC_CACHE start, full XLA cost cold
        if compile_ms_total is not None:
            tel["compile_ms_total"] = compile_ms_total
            tel["compile_count"] = compile_count
        from paddle_tpu.jit import exec_cache as _ec

        if _ec.enabled():
            tel["exec_cache"] = _ec.stats()
        # per-step sink writes happen inside the timed loop: mark the
        # record so A/B comparisons don't conflate sink overhead with a
        # regression
        tel["sink_active"] = slog is not None
        nan_checks = c.get("numerics/checks", 0)
        if nan_checks:
            tel["nan_checks"] = nan_checks
        extra["telemetry"] = tel
    except Exception:  # noqa: BLE001 — telemetry must not break the line
        pass
    # device-memory sub-object rides next to telemetry: the peak the run
    # actually held, where the number came from, and the sentinel state
    extra["memory"] = mem_obj
    # regression-guard verdict rides along in the line (tools/perf_guard.py
    # is also a standalone CLI gate; embedding means BENCH_r*.json carries
    # the pass/fail next to the number it judges)
    try:
        extra["guard"] = _guard_verdict(
            {"metric": _METRIC, "value": round(tokens_per_sec, 2),
             "unit": "tokens/s", **extra}, on_cpu,
            baseline=guard_baseline)
    except Exception as e:  # noqa: BLE001 — the guard must not break the line
        print(f"bench: perf guard failed: {e}", file=sys.stderr, flush=True)
    if slog is not None:
        # run_end carries the guard verdict + memory account so
        # tools/monitor_report.py can render them from the JSONL alone
        slog.close(loss=final_loss,
                   tokens_per_sec=round(tokens_per_sec, 2),
                   host_blocked_ms_per_step=extra["host_blocked_ms_per_step"],
                   memory=mem_obj, guard=extra.get("guard"))
    if gled is not None:
        # after slog.close: the run_end line reads the active ledger
        _gp.deactivate(gled)
    _emit(round(tokens_per_sec, 2), round(mfu / 0.45, 4), **extra)


def _watchdog(seconds: int = 2700):
    """Guarantee a JSON line even if something hangs past the driver's
    patience: emit a structured failure and exit non-zero."""

    def _fire(signum, frame):
        _emit(0.0, 0.0, error=f"bench watchdog fired after {seconds}s")
        os._exit(3)

    signal.signal(signal.SIGALRM, _fire)
    signal.alarm(seconds)


if __name__ == "__main__":
    _watchdog()
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — the JSON line must happen
        _emit(0.0, 0.0, error=f"{type(e).__name__}: {e}"[:500])
        raise
