"""Multi-replica serving router (`paddle_tpu/serving/router`).

The ISSUE 17 acceptance spine, mirrored on test_serving.py's identity
discipline:

- **Token identity + compile-free scale-out** — a 3-replica router's
  greedy output is byte-identical to the single-engine
  :class:`ServingEngine` and to per-request ``generate()``, with
  process-wide exec-cache fresh compiles == 3 (replicas 2..N ride the
  warm cache) and zero retraces across a second wave.
- **Affinity wins** — on a shared-prefix trace, prefix-affinity
  dispatch pays strictly fewer total prefill chunks than affinity-off
  (least-loaded) routing, without touching a single emitted token.
- **Failure drain** — a replica whose ``step()`` raises mid-trace is
  marked dead; every request finishes on survivors with tokens
  identical to the no-failure run, and the blackbox artifact names the
  dead replica.
- **Determinism** — dispatch is in PTL005's scope: the same trace
  replays to byte-identical routing decisions.
- **Worker mode** — the process-per-replica deployment shape behind
  the same class produces the same tokens through the JSON-line pipe
  protocol.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate
from paddle_tpu.serving import (
    RouterConfig, RouterEngine, ServingConfig, ServingEngine,
)

GEOM = dict(max_lanes=3, block_size=4, prefill_chunk=8, max_seq_len=32)


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    m.eval()
    return m


def _reference(model, prompt, new):
    return generate(model, pt.to_tensor(np.asarray(prompt)[None, :]),
                    max_new_tokens=new).numpy()[0]


def _mixed_workload(model, rng, n):
    out = []
    for _ in range(n):
        plen, new = int(rng.randint(3, 13)), int(rng.randint(4, 10))
        prompt = rng.randint(0, model.config.vocab_size,
                             (plen,)).astype(np.int32)
        out.append((prompt, new))
    return out


def _shared_prefix_workload(model, rng, n, prefix_len=8):
    prefix = rng.randint(0, model.config.vocab_size,
                         (prefix_len,)).astype(np.int32)
    out = []
    for _ in range(n):
        suffix = rng.randint(
            0, model.config.vocab_size,
            (int(rng.randint(1, 6)),)).astype(np.int32)
        out.append((np.concatenate([prefix, suffix]),
                    int(rng.randint(4, 10))))
    return out


def _run(engine, work):
    for i, (p, n) in enumerate(work):
        engine.submit(p, max_new_tokens=n, request_id=f"r{i}")
    return engine.run()


# -- config -------------------------------------------------------------------

class TestRouterConfig:
    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("PT_SERVE_REPLICAS", "5")
        monkeypatch.setenv("PT_SERVE_AFFINITY", "0")
        rc = RouterConfig()
        assert rc.replicas == 5 and rc.affinity is False
        assert rc.mode == "inproc"

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv("PT_SERVE_REPLICAS", "5")
        assert RouterConfig(replicas=2).replicas == 2
        with pytest.raises(ValueError):
            RouterConfig(replicas=0)
        with pytest.raises(ValueError):
            RouterConfig(mode="bogus")

    def test_worker_mode_needs_factory(self):
        with pytest.raises(ValueError, match="factory"):
            RouterConfig(mode="worker")

    def test_inproc_needs_model(self):
        with pytest.raises(ValueError, match="model"):
            RouterEngine(config=GEOM,
                         router_config=RouterConfig(replicas=2))


# -- the acceptance spine -----------------------------------------------------

def test_router_token_identical_and_three_compiles(model, tmp_path):
    """THE tentpole proof: a 3-replica router is byte-identical to the
    single engine and to generate(), and the whole fleet compiles 3
    programs TOTAL — replica 1 pays prefill+decode+verify, replicas
    2..3 ride the warm exec cache. A second wave adds zero compiles
    (no retraces)."""
    from paddle_tpu.jit import exec_cache as ec

    rng = np.random.RandomState(0)
    work = _mixed_workload(model, rng, 9)
    assert len({p.size for p, _ in work}) > 1, "prompts all equal"
    ec.enable(str(tmp_path))
    ec.clear()
    try:
        router = RouterEngine(
            model, ServingConfig(**GEOM),
            RouterConfig(replicas=3, mode="inproc"))
        router.warmup()
        misses = ec.stats()["misses"]
        assert misses == 3, \
            f"3 replicas must share 3 compiled programs: {ec.stats()}"
        routed = _run(router, work)

        single = ServingEngine(model, ServingConfig(**GEOM))
        base = _run(single, work)
        assert set(routed) == set(base)
        for i, (p, n) in enumerate(work):
            ref = _reference(model, p, n)
            np.testing.assert_array_equal(
                routed[f"r{i}"], ref,
                err_msg=f"routed r{i} diverged from generate()")
            np.testing.assert_array_equal(routed[f"r{i}"], base[f"r{i}"])
        # second wave through the same router: still zero fresh compiles
        r2 = router.submit(work[0][0], max_new_tokens=5, request_id="w2")
        outs2 = router.run()
        np.testing.assert_array_equal(
            outs2["w2"], _reference(model, work[0][0], 5))
        assert ec.stats()["misses"] == 3, "router retraced!"
        assert r2.request_id == "w2"
        assert router.counters["dispatches"] == 10
        assert router.counters["finished"] == 10
    finally:
        ec.disable()
        ec.clear()


def test_router_affinity_beats_affinity_off(model):
    """On a shared-prefix trace, affinity-on funnels same-opening
    requests to the replica that already published their blocks —
    strictly fewer total prefill chunks than least-loaded spreading,
    same tokens byte for byte."""
    work = _shared_prefix_workload(model, np.random.RandomState(7), 9)
    results, chunks, stats = {}, {}, {}
    for label, aff in (("on", True), ("off", False)):
        router = RouterEngine(
            model, ServingConfig(**GEOM),
            RouterConfig(replicas=3, affinity=aff, mode="inproc"))
        results[label] = _run(router, work)
        s = router.stats()
        chunks[label] = s["prefill_chunks"]
        stats[label] = s
    assert chunks["on"] < chunks["off"], chunks
    assert stats["on"]["affinity_hit_rate"] > 0
    assert stats["off"]["affinity_hit_rate"] == 0
    # least-loaded actually spread the load (the A/B is not vacuous)
    spread_off = [c for c in stats["off"]["dispatches_per_replica"] if c]
    assert len(spread_off) == 3, stats["off"]
    for i in range(len(work)):
        ref = _reference(model, *work[i])
        np.testing.assert_array_equal(results["on"][f"r{i}"], ref)
        np.testing.assert_array_equal(results["off"][f"r{i}"], ref)


def test_router_replica_death_drains_to_survivors(model, tmp_path,
                                                  monkeypatch):
    """Kill a replica mid-trace (injected step() raise): every request
    — queued and in-flight on the dead replica — finishes on survivors
    with tokens identical to the no-failure run, and the blackbox
    artifact names the dead replica."""
    bb = tmp_path / "router_blackbox.json"
    monkeypatch.setenv("PT_SERVE_BLACKBOX", str(bb))
    work = _shared_prefix_workload(model, np.random.RandomState(3), 9)

    single = ServingEngine(model, ServingConfig(**GEOM))
    base = _run(single, work)

    router = RouterEngine(
        model, ServingConfig(**GEOM),
        RouterConfig(replicas=3, mode="inproc"))
    for i, (p, n) in enumerate(work):
        router.submit(p, max_new_tokens=n, request_id=f"r{i}")
    # a couple of healthy rounds so the affinity target is mid-flight
    router.step()
    router.step()

    def boom():
        raise RuntimeError("injected replica failure")

    monkeypatch.setattr(router._replicas[0]._engine, "step", boom)
    outs = router.run()
    assert set(outs) == set(base)
    for i in range(len(work)):
        np.testing.assert_array_equal(
            outs[f"r{i}"], base[f"r{i}"],
            err_msg=f"r{i} diverged after the drain")
    assert router.counters["dead_replicas"] == 1
    assert router.counters["redispatches"] > 0
    assert 0 in router._dead
    # survivors only from here on: replica 0 never dispatched again
    n_before = router.dispatch_counts[0]
    router.submit(work[0][0], max_new_tokens=4, request_id="after")
    router.run()
    assert router.dispatch_counts[0] == n_before
    # the postmortem artifact names the dead replica
    art = json.loads(bb.read_text())
    state = art["state"]["serving_router"]
    assert state["dead"] == {"0": "RuntimeError: injected replica "
                                  "failure"}
    assert state["replicas"][0]["dead"] is True
    assert state["replicas"][1]["dead"] is False
    assert art["reason"] == "router_replica_dead"


def test_router_all_dead_raises(model, monkeypatch):
    router = RouterEngine(
        model, ServingConfig(**GEOM),
        RouterConfig(replicas=2, mode="inproc"))
    router.submit([1, 2, 3], max_new_tokens=4, request_id="a")

    def boom():
        raise RuntimeError("down")

    monkeypatch.setattr(router._replicas[0]._engine, "step", boom)
    monkeypatch.setattr(router._replicas[1]._engine, "step", boom)
    with pytest.raises(RuntimeError, match="all 2 router replicas"):
        router.run()


def test_router_duplicate_request_id(model):
    router = RouterEngine(
        model, ServingConfig(**GEOM),
        RouterConfig(replicas=2, mode="inproc"))
    router.submit([1, 2, 3], max_new_tokens=4, request_id="dup")
    with pytest.raises(ValueError, match="duplicate"):
        router.submit([4, 5], max_new_tokens=4, request_id="dup")


def test_router_deterministic_dispatch(model):
    """PTL005's scope in action: the same submission trace routes
    byte-identically on a fresh router — per-replica dispatch counts
    and the full counter dict replay exactly."""
    work = _shared_prefix_workload(model, np.random.RandomState(11), 8)
    seen = []
    for _ in range(2):
        router = RouterEngine(
            model, ServingConfig(**GEOM),
            RouterConfig(replicas=3, mode="inproc"))
        _run(router, work)
        seen.append((list(router.dispatch_counts),
                     dict(router.counters)))
    assert seen[0] == seen[1]


# -- monitor contract ---------------------------------------------------------

def test_router_monitor_counters(model):
    assert "paddle_tpu.serving.router" in monitor.INSTRUMENTED_MODULES
    work = _shared_prefix_workload(model, np.random.RandomState(5), 6)
    was = monitor.enabled()
    monitor.enable()
    try:
        monitor.reset()
        router = RouterEngine(
            model, ServingConfig(**GEOM),
            RouterConfig(replicas=3, mode="inproc"))
        _run(router, work)
        snap = monitor.snapshot()["counters"]
        assert snap["router/dispatches"] == 6
        assert snap["router/affinity_hits"] \
            + snap["router/affinity_misses"] == 6
        assert snap["router/affinity_hits"] > 0
        assert snap.get("router/dispatches/0", 0) > 0
        assert snap.get("router/dead_replicas", 0) == 0
    finally:
        monitor.reset()
        if not was:
            monitor.disable()


def test_router_monitor_dead_counter(model, monkeypatch):
    was = monitor.enabled()
    monitor.enable()
    try:
        monitor.reset()
        router = RouterEngine(
            model, ServingConfig(**GEOM),
            RouterConfig(replicas=2, mode="inproc"))
        router.submit([1, 2, 3, 4, 5], max_new_tokens=4,
                      request_id="x")

        def boom():
            raise RuntimeError("down")

        monkeypatch.setattr(router._replicas[0]._engine, "step", boom)
        monkeypatch.setattr(router._replicas[1]._engine, "step", boom)
        with pytest.raises(RuntimeError):
            router.run()
        snap = monitor.snapshot()["counters"]
        assert snap["router/dead_replicas"] >= 1
        assert snap["router/redispatches"] >= 1
    finally:
        monitor.reset()
        if not was:
            monitor.disable()


# -- worker mode --------------------------------------------------------------

def test_router_worker_mode_token_identity(model, tmp_path):
    """The process-per-replica deployment shape: two subprocess workers
    behind the same RouterEngine class produce the same tokens as the
    in-process single engine, over the JSON-line pipe protocol."""
    factory = tmp_path / "rw_factory.py"
    factory.write_text(
        "import jax\n"
        # tests force CPU; the env var alone is overridden by the host
        # sitecustomize (CLAUDE.md), so the factory pins it in-process
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import paddle_tpu as pt\n"
        "from paddle_tpu.models.llama import LlamaConfig, "
        "LlamaForCausalLM\n"
        "def build():\n"
        "    pt.seed(0)\n"
        "    m = LlamaForCausalLM(LlamaConfig.tiny("
        "num_hidden_layers=2))\n"
        "    m.eval()\n"
        "    return m\n")
    old_pp = os.environ.get("PYTHONPATH")
    os.environ["PYTHONPATH"] = str(tmp_path) + os.pathsep \
        + (old_pp or "")
    work = _mixed_workload(model, np.random.RandomState(2), 4)
    single = ServingEngine(model, ServingConfig(**GEOM))
    base = _run(single, work)
    router = RouterEngine(
        config=GEOM,
        router_config=RouterConfig(replicas=2, mode="worker",
                                   worker_factory="rw_factory:build"))
    try:
        outs = _run(router, work)
        assert set(outs) == set(base)
        for i in range(len(work)):
            np.testing.assert_array_equal(outs[f"r{i}"], base[f"r{i}"])
        assert sum(router.dispatch_counts) == len(work)
        assert router.stats()["decoded_tokens"] > 0
    finally:
        router.close()
        if old_pp is None:
            os.environ.pop("PYTHONPATH", None)
        else:
            os.environ["PYTHONPATH"] = old_pp
