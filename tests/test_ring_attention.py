"""Ring attention (context parallel over 'sep') — the exceed-reference
feature (SURVEY §5.7). Numeric parity vs the dense composite path."""
import jax
import numpy as np
import pytest

import paddle_tpu as pt
from conftest import attn_qkv
from paddle_tpu.distribution import Normal  # noqa: F401 (op table)
from paddle_tpu.nn.functional.attention import _sdpa_reference
from paddle_tpu.ops.ring_attention import make_ring_attention


@pytest.mark.parametrize("causal", [False, True])
def test_forward_parity(mesh_dp2_sep4, causal):
    q, k, v = attn_qkv(h=2)
    ring = make_ring_attention(mesh_dp2_sep4, axis="sep", causal=causal)
    out = ring(q, k, v)
    ref = _sdpa_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grad_parity(mesh_dp2_sep4, causal):
    q, k, v = attn_qkv(h=2, seed=1)
    w = np.random.RandomState(2).randn(*np.shape(q)).astype(np.float32)
    ring = make_ring_attention(mesh_dp2_sep4, axis="sep", causal=causal)
    g1 = jax.grad(lambda *a: (ring(*a) * w).sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (_sdpa_reference(*a, causal=causal) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        scale = np.abs(np.asarray(b)).max() + 1e-9
        np.testing.assert_allclose(np.asarray(a) / scale,
                                   np.asarray(b) / scale, atol=1e-4)


def test_llama_with_context_parallel():
    from paddle_tpu.distributed import env as env_mod, fleet
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1, "pp_degree": 1,
                               "sep_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    try:
        cfg = LlamaConfig.tiny(context_parallel=True)
        model = LlamaForCausalLM(cfg)
        ids = pt.to_tensor(np.random.randint(0, 128, (2, 32)))
        labels = pt.to_tensor(np.random.randint(0, 128, (2, 32)))
        loss = model(ids, labels)
        assert np.isfinite(float(loss.numpy()))
        loss.backward()
        grads = [p.grad for p in model.parameters() if not p.stop_gradient]
        assert any(g is not None for g in grads)
    finally:
        env_mod.reset_env()


def test_sep_degree_one_falls_back():
    from paddle_tpu.distributed import env as env_mod
    from paddle_tpu.nn import functional as F

    env_mod.init_mesh(dp=-1)
    try:
        q = pt.randn([1, 16, 2, 8])
        out = F.ring_flash_attention(q, q, q, axis="sep", causal=True)
        assert out.shape == [1, 16, 2, 8]
    finally:
        env_mod.reset_env()


class TestFlashBackedRing:
    """VERDICT r3 weak #7: each ring step's local attention must run the
    Pallas flash kernel (fwd + two-pass bwd), not inline einsum math."""

    def test_auto_gate_picks_flash(self, mesh_dp2_sep4):
        from paddle_tpu.ops.ring_attention import _flash_serves

        assert _flash_serves(16, 16, None)      # test shapes engage
        assert not _flash_serves(8, 16, None)   # too short to tile
        assert not _flash_serves(16, 12, None)  # head_dim not 8-aligned
        assert not _flash_serves(16, 16, False)  # explicit off

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_matches_jnp_ring(self, mesh_dp2_sep4, causal):
        q, k, v = attn_qkv(h=2, seed=3)
        flash = make_ring_attention(mesh_dp2_sep4, axis="sep", causal=causal,
                                    use_flash=True)
        plain = make_ring_attention(mesh_dp2_sep4, axis="sep", causal=causal,
                                    use_flash=False)
        np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                                   np.asarray(plain(q, k, v)), atol=2e-5)

    def test_flash_grad_matches_jnp_ring(self, mesh_dp2_sep4):
        q, k, v = attn_qkv(h=2, seed=4)
        w = np.random.RandomState(5).randn(*np.shape(q)).astype(np.float32)
        flash = make_ring_attention(mesh_dp2_sep4, axis="sep", causal=True,
                                    use_flash=True)
        plain = make_ring_attention(mesh_dp2_sep4, axis="sep", causal=True,
                                    use_flash=False)
        gf = jax.grad(lambda *a: (flash(*a) * w).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        gp = jax.grad(lambda *a: (plain(*a) * w).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gp):
            scale = np.abs(np.asarray(b)).max() + 1e-9
            np.testing.assert_allclose(np.asarray(a) / scale,
                                       np.asarray(b) / scale, atol=1e-4)
