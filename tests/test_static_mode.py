"""paddle.static Program/Executor tests (reference
`test/legacy_test/test_executor_*.py`, `test_inference_model_io.py`)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn

static = paddle.static


def _build(prog, net):
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        out = net(x)
    return x, out


class TestStaticProgram:
    def test_record_and_run(self):
        prog = static.Program()
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x, out = _build(prog, net)
        assert len(prog.ops) == 3
        exe = static.Executor()
        feed = np.random.randn(3, 4).astype(np.float32)
        res, = exe.run(prog, feed={"x": feed}, fetch_list=[out])
        ref = np.maximum(feed @ net[0].weight.numpy() + net[0].bias.numpy(),
                         0) @ net[2].weight.numpy() + net[2].bias.numpy()
        np.testing.assert_allclose(res, ref, atol=1e-5)

    def test_program_tracks_weight_updates(self):
        prog = static.Program()
        net = nn.Sequential(nn.Linear(4, 2))
        x, out = _build(prog, net)
        exe = static.Executor()
        feed = np.ones((2, 4), np.float32)
        r1, = exe.run(prog, feed={"x": feed}, fetch_list=[out])
        net[0].weight.set_value(net[0].weight.numpy() * 2)
        r2, = exe.run(prog, feed={"x": feed}, fetch_list=[out])
        expect = feed @ net[0].weight.numpy() + net[0].bias.numpy()
        np.testing.assert_allclose(r2, expect, atol=1e-5)
        assert not np.allclose(r1, r2)

    def test_multiple_feeds_and_fetches(self):
        prog = static.Program()
        with static.program_guard(prog):
            a = static.data("a", [2, 3], "float32")
            b = static.data("b", [2, 3], "float32")
            s = a + b
            p = a * b
        exe = static.Executor()
        av = np.random.randn(2, 3).astype(np.float32)
        bv = np.random.randn(2, 3).astype(np.float32)
        rs, rp = exe.run(prog, feed={"a": av, "b": bv}, fetch_list=[s, p])
        np.testing.assert_allclose(rs, av + bv, atol=1e-6)
        np.testing.assert_allclose(rp, av * bv, atol=1e-6)

    def test_save_load_inference_model(self, tmp_path):
        prog = static.Program()
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x, out = _build(prog, net)
        exe = static.Executor()
        prefix = str(tmp_path / "model")
        static.save_inference_model(prefix, [x], [out], exe, program=prog)
        prog2, feeds, fetch_ids = static.load_inference_model(prefix, exe)
        feed = np.random.randn(3, 4).astype(np.float32)
        r1, = exe.run(prog, feed={"x": feed}, fetch_list=[out])
        r2, = exe.run(prog2, feed={"x": feed}, fetch_list=fetch_ids)
        np.testing.assert_allclose(r1, r2, atol=1e-6)

    def test_default_main_program(self):
        prog = static.default_main_program()
        assert isinstance(prog, static.Program)
