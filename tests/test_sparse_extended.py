"""Extended paddle.sparse surface (round-3: full reference __all__)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import sparse as sp


def _coo():
    idx = np.asarray([[0, 1, 2], [1, 0, 2]])
    vals = np.asarray([1.0, -2.0, 4.0], "float32")
    return sp.sparse_coo_tensor(idx, vals, shape=(3, 3))


def test_value_unaries_preserve_structure():
    x = _coo()
    out = sp.abs(x)
    np.testing.assert_allclose(out.values().numpy(), [1.0, 2.0, 4.0])
    np.testing.assert_array_equal(out.indices().numpy(),
                                  x.indices().numpy())
    np.testing.assert_allclose(sp.square(x).values().numpy(), [1., 4., 16.])
    np.testing.assert_allclose(sp.neg(x).values().numpy(), [-1., 2., -4.])
    np.testing.assert_allclose(
        sp.sqrt(sp.abs(x)).values().numpy(), np.sqrt([1., 2., 4.]),
        rtol=1e-6)


def test_pow_cast():
    x = _coo()
    np.testing.assert_allclose(sp.pow(x, 2).values().numpy(), [1., 4., 16.])
    y = sp.cast(x, value_dtype="float64")
    assert "float64" in str(y.values().numpy().dtype) or \
        "float32" in str(y.values().numpy().dtype)  # x32 canonicalized


def test_coalesce_merges_duplicates():
    idx = np.asarray([[0, 0, 1], [1, 1, 2]])
    vals = np.asarray([1.0, 2.0, 3.0], "float32")
    x = sp.sparse_coo_tensor(idx, vals, shape=(2, 3))
    c = sp.coalesce(x)
    d = c.to_dense().numpy()
    assert d[0, 1] == 3.0 and d[1, 2] == 3.0


def test_structure_ops():
    x = _coo()
    assert sp.is_same_shape(x, _coo())
    t = sp.transpose(x, [1, 0])
    np.testing.assert_allclose(t.to_dense().numpy(),
                               x.to_dense().numpy().T)
    r = sp.reshape(x, (9, 1))
    np.testing.assert_allclose(r.to_dense().numpy().reshape(3, 3),
                               x.to_dense().numpy())
    s = sp.slice(x, [0], [1], [3])
    np.testing.assert_allclose(s.to_dense().numpy(),
                               x.to_dense().numpy()[1:3])


def test_reductions_and_linalg():
    x = _coo()
    np.testing.assert_allclose(float(sp.sum(x).numpy()), 3.0)
    np.testing.assert_allclose(sp.sum(x, axis=1).numpy(),
                               x.to_dense().numpy().sum(1))
    v = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], "float32"))
    np.testing.assert_allclose(sp.mv(x, v).numpy(),
                               x.to_dense().numpy() @ v.numpy())
    d = paddle.to_tensor(np.eye(3, dtype="float32"))
    out = sp.addmm(d, x, d, beta=2.0, alpha=1.0)
    np.testing.assert_allclose(
        out.numpy(), 2 * np.eye(3) + x.to_dense().numpy(), rtol=1e-6)


def test_pca_lowrank_reconstructs():
    rng = np.random.default_rng(0)
    base = rng.standard_normal((20, 3)).astype("float32")
    a = base @ rng.standard_normal((3, 8)).astype("float32")  # rank 3
    paddle.seed(0)
    U, S, V = sp.pca_lowrank(paddle.to_tensor(a), q=3, center=True)
    ac = a - a.mean(0, keepdims=True)
    rec = U.numpy() @ np.diag(S.numpy()) @ V.numpy().T
    np.testing.assert_allclose(rec, ac, atol=1e-3)
