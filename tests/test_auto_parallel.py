"""Auto-parallel Engine + ProcessMesh tests (reference `test/auto_parallel/`)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import auto_parallel as ap
from paddle_tpu.distributed import env as env_mod, fleet


@pytest.fixture(scope="module", autouse=True)
def _mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    env_mod.reset_env()


class DS(pt.io.Dataset):
    def __len__(self):
        return 32

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        x = rng.randn(8).astype(np.float32)
        return x, np.array([x.sum()], dtype=np.float32)


def test_shard_tensor_with_placements():
    mesh = ap.ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
    x = pt.to_tensor(np.zeros((4, 8), np.float32))
    xs = ap.shard_tensor(x, mesh, [ap.Shard(0), ap.Replicate()])
    assert tuple(xs._data.sharding.spec)[0] == "dp"
    ys = ap.shard_tensor(x, mesh, [ap.Replicate(), ap.Shard(1)])
    assert tuple(ys._data.sharding.spec)[1] == "mp"


def test_engine_fit_evaluate(tmp_path):
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = pt.optimizer.Adam(0.01, parameters=net.parameters())
    eng = ap.Engine(model=net, loss=nn.MSELoss(), optimizer=opt)
    hist = eng.fit(DS(), batch_size=8, epochs=15, log_freq=1)
    assert hist[-1] < hist[0]
    logs = eng.evaluate(DS(), batch_size=8)
    assert logs["loss"] < hist[0]
    eng.save(str(tmp_path / "ckpt"))
    eng.load(str(tmp_path / "ckpt"))
