"""Native C++ components: TCPStore + cpp_extension custom op (reference
`test/cpp_extension/`, TCPStore tests in `test/collective`)."""
import textwrap
import threading
import time

import numpy as np
import pytest


class TestTCPStore:
    @pytest.fixture(scope="class")
    def stores(self):
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, port=0)
        worker = TCPStore(host="127.0.0.1", port=master.port)
        yield master, worker

    def test_set_get(self, stores):
        master, worker = stores
        master.set("k1", b"hello")
        assert worker.get("k1") == b"hello"

    def test_add_counter(self, stores):
        master, worker = stores
        assert worker.add("ctr", 5) == 5
        assert master.add("ctr", 2) == 7

    def test_blocking_wait(self, stores):
        master, worker = stores

        def setter():
            time.sleep(0.2)
            master.set("late", b"arrived")

        t = threading.Thread(target=setter)
        t.start()
        worker.wait(["late"], timeout=5)
        assert worker.get("late") == b"arrived"
        t.join()

    def test_missing_key_raises(self, stores):
        _, worker = stores
        with pytest.raises(KeyError):
            worker.get("missing")

    def test_wait_timeout(self, stores):
        _, worker = stores
        with pytest.raises(TimeoutError):
            worker.wait(["never_set"], timeout=0.3)

    def test_delete(self, stores):
        master, worker = stores
        master.set("gone", b"x")
        worker.delete_key("gone")
        with pytest.raises(KeyError):
            worker.get("gone")


class TestCppExtension:
    def test_load_and_custom_op(self, tmp_path):
        from paddle_tpu.utils.cpp_extension import (
            custom_op_from_library, load,
        )

        src = tmp_path / "my_op.cpp"
        src.write_text(textwrap.dedent("""
            #include <cstdint>
            extern "C" void relu_plus_one(const float* in, float* out,
                                          long long n) {
              for (long long i = 0; i < n; ++i)
                out[i] = (in[i] > 0 ? in[i] : 0.0f) + 1.0f;
            }
        """))
        lib = load("my_op_test", [str(src)],
                   build_directory=str(tmp_path))
        op = custom_op_from_library(lib, "relu_plus_one")

        import paddle_tpu as paddle

        x = paddle.to_tensor(np.array([-1.0, 2.0, -3.0, 4.0], np.float32))
        y = op(x)
        np.testing.assert_allclose(y.numpy(), [1.0, 3.0, 1.0, 5.0])

    def test_rebuild_cache(self, tmp_path):
        import os

        from paddle_tpu.utils.cpp_extension import load

        src = tmp_path / "noop.cpp"
        src.write_text('extern "C" int answer() { return 42; }')
        lib1 = load("noop", [str(src)], build_directory=str(tmp_path))
        n_so = len([f for f in os.listdir(tmp_path) if f.endswith(".so")])
        lib2 = load("noop", [str(src)], build_directory=str(tmp_path))
        n_so2 = len([f for f in os.listdir(tmp_path) if f.endswith(".so")])
        assert n_so == n_so2  # content unchanged -> no rebuild
        assert lib2.answer() == 42
