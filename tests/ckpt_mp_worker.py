"""Payload for the two-process sharded-checkpoint test.

Run by `python -m paddle_tpu.distributed.launch --nproc_per_node 2`
(see test_launch_multiprocess.py for the harness pattern). Exercises the
multi-host write path of `distributed.checkpoint`: each process writes
only its addressable replica_id==0 shard files, ownerless (host/0-d)
tensors are written by the coordinator alone, the cross-process barrier
runs before the coordinator publishes index.json, and reshard-on-load
assembles each process's regions from the shared directory.
"""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import paddle_tpu as pt  # noqa: E402
from paddle_tpu.framework.core import Tensor  # noqa: E402


def main():
    out_dir = sys.argv[1]
    ckpt_dir = os.path.join(out_dir, "ckpt")
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    pt.distributed.init_parallel_env()

    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.distributed import checkpoint as ckpt
    from paddle_tpu.distributed import env as dist_env

    mesh = dist_env.get_env().mesh
    n_dev = jax.device_count()

    # a dp-sharded tensor whose global value every process can recompute
    ref = np.arange(n_dev * 16, dtype=np.float32).reshape(n_dev, 16)
    sharding = NamedSharding(mesh, P("dp"))
    arr = jax.make_array_from_callback(
        ref.shape, sharding, lambda idx: ref[idx])
    x = Tensor(arr)
    scalar = Tensor(jax.device_put(np.float32(7.25),
                                   NamedSharding(mesh, P())))
    host_np = np.arange(5, dtype=np.float32)

    ckpt.save_state_dict({"w": x, "step": scalar, "host": host_np},
                         ckpt_dir)

    res = {"rank": rank, "process_count": jax.process_count()}
    with open(os.path.join(ckpt_dir, "index.json")) as f:
        index = json.load(f)
    res["format"] = index["format"]
    res["w_shards"] = len(index["tensors"]["w"]["shards"])
    # every shard file referenced by the index exists on the shared fs
    res["all_files_exist"] = all(
        os.path.exists(os.path.join(ckpt_dir, sh["file"]))
        for meta in index["tensors"].values() for sh in meta["shards"])

    # reshard-on-load into freshly-scrambled destinations
    dest = Tensor(jax.make_array_from_callback(
        ref.shape, sharding, lambda idx: np.zeros_like(ref[idx])))
    dscalar = Tensor(jax.device_put(np.float32(0.0),
                                    NamedSharding(mesh, P())))
    ckpt.load_state_dict({"w": dest, "step": dscalar}, ckpt_dir)
    got = np.concatenate([
        np.asarray(s.data).reshape(-1, 16)
        for s in sorted(dest._data.addressable_shards,
                        key=lambda s: s.index[0].start or 0)])
    lo = min((s.index[0].start or 0)
             for s in dest._data.addressable_shards)
    res["w_roundtrip"] = bool(
        np.allclose(got, ref[lo:lo + got.shape[0]]))
    res["scalar_roundtrip"] = float(np.asarray(
        dscalar._data.addressable_data(0)))
    host_back = ckpt.load_checkpoint(ckpt_dir)["host"]
    res["host_roundtrip"] = bool(np.allclose(host_back, host_np))

    with open(os.path.join(out_dir, f"ckptrank{rank}.json"), "w") as f:
        json.dump(res, f)
    print("CKPT_WORKER_OK", rank, flush=True)


if __name__ == "__main__":
    main()
