"""Chunked softmax cross-entropy (F.chunked_softmax_cross_entropy): the
large-vocab LM loss that never materializes [N, V] fp32 logits. Parity
with the dense path in values and grads, plus the Llama integration."""
import jax
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


def _dense_per_tok(h, w, lab):
    logits = (h.reshape(-1, h.shape[-1]) @ w).astype(np.float64)
    m = logits.max(-1, keepdims=True)
    lse = (m + np.log(np.exp(logits - m).sum(-1, keepdims=True)))[:, 0]
    safe = np.clip(lab.reshape(-1), 0, w.shape[1] - 1)
    return (lse - logits[np.arange(logits.shape[0]), safe]).reshape(lab.shape)


@pytest.mark.parametrize("chunk", [8, 16, 37, 64])
def test_value_parity_odd_vocab(chunk):
    rng = np.random.RandomState(0)
    h = rng.randn(2, 5, 16).astype(np.float32)
    w = rng.randn(16, 37).astype(np.float32)
    lab = rng.randint(0, 37, (2, 5)).astype(np.int64)
    lab[0, 0] = -100  # ignored positions are masked by the caller
    out = F.chunked_softmax_cross_entropy(
        pt.to_tensor(h), pt.to_tensor(w), pt.to_tensor(lab), chunk)
    ref = _dense_per_tok(h, w, lab)
    mask = lab >= 0
    np.testing.assert_allclose(out.numpy()[mask], ref[mask], rtol=1e-5,
                               atol=1e-5)


def test_grad_parity_vs_dense():
    rng = np.random.RandomState(1)
    h = rng.randn(3, 4, 8).astype(np.float32)
    w = rng.randn(8, 21).astype(np.float32)
    lab = rng.randint(0, 21, (3, 4)).astype(np.int64)

    # drive grads through the public Tensor tape
    ht = pt.to_tensor(h, stop_gradient=False)
    wt = pt.to_tensor(w, stop_gradient=False)
    loss = F.chunked_softmax_cross_entropy(ht, wt, pt.to_tensor(lab),
                                           8).mean()
    loss.backward()
    gh_c, gw_c = ht.grad.numpy(), wt.grad.numpy()

    ht2 = pt.to_tensor(h, stop_gradient=False)
    wt2 = pt.to_tensor(w, stop_gradient=False)
    logits = pt.matmul(ht2.reshape([-1, 8]), wt2).astype("float32")
    dense = F.cross_entropy(logits,
                            pt.to_tensor(lab.reshape(-1, 1)),
                            reduction="mean")
    dense.backward()
    np.testing.assert_allclose(float(loss.numpy()), float(dense.numpy()),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(gh_c, ht2.grad.numpy().reshape(gh_c.shape),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gw_c, wt2.grad.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_llama_integration_matches_dense_path():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig.tiny(use_parallel_cross_entropy=False)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)))
    labels_np = rng.randint(0, cfg.vocab_size, (2, 16))
    labels_np[0, :3] = -100
    labels = pt.to_tensor(labels_np)
    dense_loss = float(model(ids, labels).numpy())
    model.config.ce_chunk_size = 32  # same params, chunked loss path
    chunked_loss = float(model(ids, labels).numpy())
    np.testing.assert_allclose(chunked_loss, dense_loss, rtol=1e-5,
                               atol=1e-6)
    # generation path (labels=None) still returns logits
    model.eval()
    logits = model(ids)
    assert logits.shape == [2, 16, cfg.vocab_size]
