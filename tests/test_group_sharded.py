"""ZeRO group_sharded tests (virtual mesh, sharding axis = dp).

Mirrors reference `test/collective/fleet/dygraph_group_sharded_stage2.py`
numeric checks: sharded training matches unsharded training.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.jit.train_step import TrainStep

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles


@pytest.fixture(scope="module", autouse=True)
def _mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _mlp():
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _train(model, opt, steps=4):
    x = pt.to_tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
    y = pt.to_tensor(np.random.RandomState(1).randn(8, 4).astype(np.float32))
    losses = []
    for _ in range(steps):
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


class TestGroupSharded:
    def test_stage2_state_sharded(self):
        pt.seed(11)
        model = _mlp()
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, level="os_g")
        _train(model, opt, steps=2)
        # moments of the [16,32] weight are sharded over dp
        key = id(model[0].weight)
        spec = tuple(opt._accumulators[key]["moment1"].sharding.spec)
        assert "dp" in spec

    def test_stage2_matches_unsharded(self):
        pt.seed(12)
        m1 = _mlp()
        o1 = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m1.parameters())
        ref = _train(m1, o1)

        pt.seed(12)
        m2 = _mlp()
        o2 = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m2.parameters())
        m2, o2, _ = group_sharded_parallel(m2, o2, level="os_g")
        got = _train(m2, o2)
        np.testing.assert_allclose(ref, got, atol=1e-5)

    def test_stage3_params_sharded_and_match(self):
        pt.seed(13)
        m1 = _mlp()
        o1 = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m1.parameters())
        ref = _train(m1, o1)

        pt.seed(13)
        m2 = _mlp()
        o2 = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m2.parameters())
        m2, o2, _ = group_sharded_parallel(m2, o2, level="p_g_os")
        spec = tuple(m2[0].weight._data.sharding.spec)
        assert "dp" in spec
        got = _train(m2, o2)
        np.testing.assert_allclose(ref, got, atol=1e-5)

    def test_stage2_with_compiled_train_step(self):
        pt.seed(14)
        model = _mlp()
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, level="os_g")
        step = TrainStep(model, opt,
                         lambda m, x, y: ((m(x) - y) ** 2).mean())
        x = pt.to_tensor(np.random.randn(8, 16).astype(np.float32))
        y = pt.to_tensor(np.random.randn(8, 4).astype(np.float32))
        losses = [float(step(x, y).numpy()) for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_offload_eager_matches_unsharded(self):
        # offload=True: states+masters live in pinned host memory between
        # steps; numerics must match the unsharded run exactly
        pt.seed(15)
        m1 = _mlp()
        o1 = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m1.parameters())
        ref = _train(m1, o1)

        pt.seed(15)
        m2 = _mlp()
        o2 = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m2.parameters())
        m2, o2, _ = group_sharded_parallel(m2, o2, level="os_g",
                                           offload=True)
        got = _train(m2, o2)
        np.testing.assert_allclose(ref, got, atol=1e-5)
        key = id(m2[0].weight)
        m1st = o2._accumulators[key]["moment1"]
        assert m1st.sharding.memory_kind == "pinned_host"
        assert "dp" in tuple(m1st.sharding.spec)

    def test_offload_compiled_train_step(self):
        pt.seed(16)
        model = _mlp()
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, level="os_g",
                                               offload=True)
        step = TrainStep(model, opt,
                         lambda m, x, y: ((m(x) - y) ** 2).mean())
        x = pt.to_tensor(np.random.randn(8, 16).astype(np.float32))
        y = pt.to_tensor(np.random.randn(8, 4).astype(np.float32))
        losses = [float(step(x, y).numpy()) for _ in range(4)]
        assert losses[-1] < losses[0]
        # state returned by the compiled step is back in host memory
        st = step._flatten_state()
        assert all(a.sharding.memory_kind == "pinned_host" for a in st)

    def test_offload_stages_one_param_at_a_time(self, monkeypatch):
        # peak-HBM contract: the eager step brackets ONE param's state
        # (moments+master) between host<->device moves — never the whole
        # optimizer at once (round-5 review finding)
        import paddle_tpu.distributed.sharding.group_sharded as gs

        pt.seed(17)
        model = _mlp()
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, level="os_g",
                                               offload=True)
        _train(model, opt, steps=1)  # state exists, host-placed

        events = []
        orig_dev, orig_host = gs._dev_put, gs._host_put
        monkeypatch.setattr(gs, "_dev_put",
                            lambda a: events.append("d") or orig_dev(a))
        monkeypatch.setattr(gs, "_host_put",
                            lambda a: events.append("h") or orig_host(a))
        _train(model, opt, steps=1)
        assert "d" in events and "h" in events
        # at most one param's leaves (2 moments + <=2 extras) staged
        # device-ward before the host-ward parking of that same param
        run = max_run = 0
        for e in events:
            run = run + 1 if e == "d" else 0
            max_run = max(max_run, run)
        assert max_run <= 4, (max_run, events)
