"""Resilience runtime tests (paddle_tpu/resilience — docs/RESILIENCE.md):
planned checkpoints, torn-checkpoint fallback, crash-resume bit-exactness,
reshard-on-resume, NaN skip-and-continue, and the soak smoke gate."""
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import monitor, resilience
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.resilience import resume as rez

REPO = str(Path(__file__).parent.parent)


def _build(seed=0, lr=5e-2):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, nn.MSELoss())
    return model


def _dataset(n=48, poison_batch=None, batch=8):
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n, 8)).astype("float32")
    ys = xs @ rng.standard_normal((8, 1)).astype("float32")
    if poison_batch is not None:
        xs[poison_batch * batch:(poison_batch + 1) * batch] = np.nan
    return [(xs[i], ys[i]) for i in range(n)]


class _Cap(paddle.callbacks.Callback):
    """Per-batch loss capture + optional simulated crash (raise from the
    batch-end hook — fit's error path must still finalize checkpoints)."""

    def __init__(self, sink, crash_at=None):
        self.sink = sink
        self.crash_at = crash_at
        self.n = 0

    def on_train_batch_end(self, step, logs=None):
        self.sink.append(float(logs["loss"]))
        self.n += 1
        if self.crash_at is not None and self.n == self.crash_at:
            raise RuntimeError("simulated crash")


# -- CheckpointManager -------------------------------------------------------

def test_manager_save_gc_and_latest(tmp_path):
    model = _build()
    opt = model._optimizer
    ck = str(tmp_path / "ck")
    mgr = resilience.CheckpointManager(ck, keep=2, interval=1,
                                       async_save=False)
    for step in (1, 2, 3):
        mgr.save(step, rez.capture(model.network, opt, epoch=0,
                                   batch_in_epoch=step, step=step))
    # retention: keep=2 -> steps 1 GC'd, 2+3 survive
    assert [s for s, _ in resilience.complete_checkpoints(ck)] == [2, 3]
    step, path, manifest = resilience.latest_complete(ck)
    assert step == 3 and manifest["scalars"]["step"] == 3
    assert dckpt.is_complete(path)


def test_torn_checkpoint_never_selected(tmp_path):
    """Satellite regression: a checkpoint with a truncated shard file (a
    mid-save crash) must fail is_complete and be skipped by the resume
    selector in favor of the previous complete one."""
    model = _build()
    ck = str(tmp_path / "ck")
    mgr = resilience.CheckpointManager(ck, keep=3, interval=1,
                                       async_save=False)
    for step in (1, 2):
        mgr.save(step, rez.capture(model.network, model._optimizer,
                                   step=step))
    newest = resilience.step_dir(ck, 2)
    shard = next(p for p in sorted(os.listdir(newest))
                 if p.endswith(".npy"))
    fpath = os.path.join(newest, shard)
    with open(fpath, "r+b") as f:
        f.truncate(os.path.getsize(fpath) // 2)
    assert not dckpt.is_complete(newest)
    step, path, _ = resilience.latest_complete(ck)
    assert step == 1, "torn checkpoint must not be the resume point"
    # a manifest-less directory (killed before finalize) is torn too
    os.remove(os.path.join(resilience.step_dir(ck, 1), "MANIFEST.json"))
    assert resilience.latest_complete(ck) is None


def test_index_written_atomically(tmp_path):
    """index.json lands via tmp+rename: no .tmp residue, parseable, and
    every shard entry carries its payload size for is_complete."""
    path = str(tmp_path / "ck")
    model = _build()
    dckpt.save_state_dict(
        {k: v for k, v in model.network.state_dict().items()}, path)
    assert not os.path.exists(os.path.join(path, "index.json.tmp"))
    with open(os.path.join(path, "index.json")) as f:
        index = json.load(f)
    for meta in index["tensors"].values():
        for sh in meta["shards"]:
            assert sh["bytes"] > 0
            assert os.path.getsize(os.path.join(path, sh["file"])) \
                > sh["bytes"]


def test_chunk_streamed_shard_seals_atomically(tmp_path, monkeypatch):
    """Review finding: chunk-streamed shards allocate their full memmap
    up front, so size checks can't see a torn stream — the .tmp→final
    rename is the completeness marker."""
    monkeypatch.setattr(dckpt, "_CHUNK_BYTES", 256)
    big = paddle.to_tensor(
        np.arange(512, dtype=np.float32).reshape(64, 8))
    path = str(tmp_path / "ck")
    dckpt.save_state_dict({"big": big}, path)
    assert not any(n.endswith(".tmp") for n in os.listdir(path))
    assert dckpt.is_complete(path)
    np.testing.assert_array_equal(dckpt.load_checkpoint(path)["big"],
                                  np.asarray(big._data))
    # a writer killed mid-stream leaves only the .tmp (no final name)
    with open(os.path.join(path, "index.json")) as f:
        index = json.load(f)
    fname = index["tensors"]["big"]["shards"][0]["file"]
    os.rename(os.path.join(path, fname),
              os.path.join(path, fname + ".tmp"))
    assert not dckpt.is_complete(path)


def test_terminal_resave_never_tears_a_published_checkpoint(tmp_path):
    """Review finding: re-saving into a step dir must unpublish its
    manifest before rewriting files (manifest == complete invariant);
    and a resumed FINISHED run must not re-save its terminal step at
    all."""
    model = _build()
    ck = str(tmp_path / "ck")
    mgr = resilience.CheckpointManager(ck, interval=1, async_save=False)
    mgr.save(1, rez.capture(model.network, model._optimizer, step=1))
    mtime0 = os.path.getmtime(
        os.path.join(resilience.step_dir(ck, 1), "MANIFEST.json"))
    # re-save same step: old manifest removed before rewrite, republished
    mgr.save(1, rez.capture(model.network, model._optimizer, step=1))
    assert resilience.latest_complete(ck)[0] == 1
    assert os.path.getmtime(os.path.join(
        resilience.step_dir(ck, 1), "MANIFEST.json")) >= mtime0


def test_cadence_planner_math(tmp_path):
    mgr = resilience.CheckpointManager(str(tmp_path / "ck"), keep=2,
                                       overhead_pct=2.0, min_interval=1,
                                       max_interval=1000)
    # 1 s save, 100 ms steps, 2% budget -> every 500 steps
    assert mgr.plan_interval(1.0, 0.1) == 500
    # clamped at both ends
    assert mgr.plan_interval(0.0001, 10.0) == 1
    assert mgr.plan_interval(1000.0, 0.001) == 1000
    # no step-time estimate yet -> conservative floor
    assert mgr.plan_interval(1.0, None) == 1
    fixed = resilience.CheckpointManager(str(tmp_path / "ck2"),
                                         interval=7)
    assert fixed.plan_interval(1.0, 0.1) == 7


def test_async_save_quiesces_and_publishes(tmp_path):
    """Async path: save() returns fast, finalize() publishes the
    manifest, and the monitor counts the save under the None-slot
    contract."""
    model = _build()
    ck = str(tmp_path / "ck")
    monitor.enable()
    try:
        monitor.reset()
        mgr = resilience.CheckpointManager(ck, interval=1)
        mgr.save(1, rez.capture(model.network, model._optimizer, step=1))
        assert mgr.finalize() == 1
        assert mgr.last_complete_step == 1
        snap = monitor.snapshot()["counters"]
        assert snap.get("resilience/saves") == 1
        h = monitor.snapshot()["histograms"]["resilience/save_ms"]
        assert h["count"] == 1
    finally:
        monitor.disable()
        monitor.reset()


# -- fit integration ---------------------------------------------------------

def test_fit_crash_resume_bitexact(tmp_path, monkeypatch):
    """The acceptance core, in-process: a run killed mid-fit and resumed
    from its checkpoint finishes with params BIT-IDENTICAL to an
    uninterrupted run at the same topology."""
    monkeypatch.setenv("PT_CKPT_MAX_INTERVAL", "1")
    ds = _dataset()
    ck = str(tmp_path / "ck")

    clean = _build()
    lc = []
    clean.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
              log_freq=1, callbacks=[_Cap(lc)])

    m1 = _build()
    with pytest.raises(RuntimeError, match="simulated crash"):
        m1.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               log_freq=1, checkpoint_dir=ck,
               callbacks=[_Cap([], crash_at=8)])
    # crash raised from batch 8's end-hook, before its checkpoint: the
    # newest COMPLETE checkpoint is step 7 — or 6 when step 7's async
    # writer hadn't finished at crash time (the crash path polls, never
    # blocks on a possibly-stalled writer)
    last = resilience.latest_complete(ck)[0]
    assert last in (6, 7), last

    m2 = _build()
    l2 = []
    m2.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
           log_freq=1, resume_from=ck, callbacks=[_Cap(l2)])
    assert np.allclose(lc[last:], l2, atol=0), (lc[last:], l2)
    for (k, a), (_, b) in zip(clean.network.state_dict().items(),
                              m2.network.state_dict().items()):
        assert np.array_equal(np.asarray(a._data), np.asarray(b._data)), k


def test_fit_resume_of_finished_run_is_noop(tmp_path):
    ds = _dataset()
    ck = str(tmp_path / "ck")
    m1 = _build()
    m1.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
           checkpoint_dir=ck)
    m2 = _build()
    l2 = []
    m2.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
           resume_from=ck, callbacks=[_Cap(l2)])
    assert l2 == []  # terminal checkpoint says epoch==epochs: nothing left
    for (k, a), (_, b) in zip(m1.network.state_dict().items(),
                              m2.network.state_dict().items()):
        assert np.array_equal(np.asarray(a._data), np.asarray(b._data)), k


def test_restore_reads_legacy_optimizer_keys(tmp_path, monkeypatch):
    """A checkpoint written before the canonical (model state-dict)
    optimizer key scheme — keys under ``p.name``/``param_<i>`` — still
    resumes: the restore probes the legacy names when the canonical
    ones are absent (a crash-restart across that code change is
    exactly the resilience use case)."""
    import paddle_tpu.nn.functional as F

    def build():
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(8, 4))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        return net, opt

    net, opt = build()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype("float32"))
    loss = F.mse_loss(net(x), paddle.zeros([4, 4]))
    loss.backward()
    opt.step()
    # simulate the pre-canonical writer: no model-name map, so capture
    # falls back to p.name / param_<i> — the legacy key scheme
    monkeypatch.setattr(rez, "_param_name_map", lambda network: {})
    flat, scalars = rez.capture(net, opt, step=1)
    monkeypatch.undo()
    assert not any(k.startswith("opt.0.weight") for k in flat)
    ck = str(tmp_path / "ck")
    mgr = resilience.CheckpointManager(ck, interval=1, async_save=False)
    mgr.save(1, (flat, scalars))

    net2, opt2 = build()
    rez.restore_latest(net2, opt2, ck)
    for p, p2 in zip(opt._parameter_list, opt2._parameter_list):
        st, st2 = (opt._accumulators.get(id(p)),
                   opt2._accumulators.get(id(p2)))
        assert (st is None) == (st2 is None)
        if st is not None:
            np.testing.assert_array_equal(np.asarray(st["moment1"]),
                                          np.asarray(st2["moment1"]))
    assert opt2._global_step == 1


def test_restore_reshards_to_new_mesh(tmp_path):
    """Save with params (and optimizer moments) sharded over a 2-device
    mesh axis, restore into a 4-device layout: values identical, new
    placement honored — reshard-on-load, end to end through the
    resilience capture/restore path."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import paddle_tpu.nn.functional as F

    def build(n_dev):
        paddle.seed(3)
        net = nn.Linear(8, 4)
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("mp",))
        net.weight._data = jax.device_put(
            net.weight._data, NamedSharding(mesh, P(None, "mp")))
        net.bias._data = jax.device_put(
            net.bias._data, NamedSharding(mesh, P("mp")))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        return net, opt

    net2, opt2 = build(2)
    mesh2 = net2.weight._data.sharding.mesh
    x = paddle.Tensor(jax.device_put(
        np.random.RandomState(0).randn(4, 8).astype("float32"),
        NamedSharding(mesh2, P())))
    y = paddle.Tensor(jax.device_put(np.zeros((4, 4), dtype="float32"),
                                     NamedSharding(mesh2, P())))
    loss = F.mse_loss(net2(x), y)
    loss.backward()
    opt2.step()  # accumulators now exist (sharded like their params)
    ck = str(tmp_path / "ck")
    mgr = resilience.CheckpointManager(ck, interval=1, async_save=False)
    mgr.save(1, rez.capture(net2, opt2, step=1))

    net4, opt4 = build(4)
    scal = rez.restore_latest(net4, opt4, ck)
    assert scal["step"] == 1
    for (k, a), (_, b) in zip(net2.state_dict().items(),
                              net4.state_dict().items()):
        np.testing.assert_array_equal(np.asarray(a._data),
                                      np.asarray(b._data), err_msg=k)
    # destination placement (the 4-device mesh) was honored
    assert len(net4.weight._data.sharding.mesh.devices.ravel()) == 4
    # optimizer moments restored value-identical
    for p2, p4 in zip(opt2._parameter_list, opt4._parameter_list):
        st2 = opt2._accumulators[id(p2)]
        st4 = opt4._accumulators[id(p4)]
        assert sorted(st2) == sorted(st4)
        for key in st2:
            np.testing.assert_allclose(np.asarray(st2[key]),
                                       np.asarray(st4[key]), atol=0)


def test_fit_nan_skip_and_budget(monkeypatch):
    """nan_policy='skip': the poisoned batch is dropped (finite losses,
    one skip counted, step counters unaffected); an all-poison stream
    aborts after PT_NANSKIP_MAX consecutive failures."""
    ds = _dataset(poison_batch=2)
    m = _build()
    losses = []
    monitor.enable()
    try:
        monitor.reset()
        m.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
              log_freq=1, nan_policy="skip", callbacks=[_Cap(losses)])
        snap = monitor.snapshot()["counters"]
        assert len(losses) == 5 and np.isfinite(losses).all()
        assert snap.get("resilience/skipped_batches") == 1
        assert snap.get("numerics/failures") == 1
    finally:
        monitor.disable()
        monitor.reset()
    # a skipped step never happened: 5 updates -> global_step 5
    assert m._optimizer._global_step == 5

    monkeypatch.setenv("PT_NANSKIP_MAX", "2")
    bad = [(np.full(8, np.nan, np.float32), np.zeros(1, np.float32))
           for _ in range(24)]
    m2 = _build()
    with pytest.raises(resilience.SkipBudgetExceeded) as ei:
        m2.fit(bad, batch_size=8, epochs=1, shuffle=False, verbose=0,
               nan_policy="skip")
    assert ei.value.consecutive == 2
    from paddle_tpu.monitor.numerics import NonFiniteError

    assert isinstance(ei.value.__cause__, NonFiniteError)


def test_nan_skip_counts_toward_num_iters():
    """A poison-heavy stream cannot run the loop past num_iters: skipped
    batches count toward the iteration budget (review finding)."""
    bad = _dataset(poison_batch=0)  # first batch poisoned
    m = _build()
    seen = []
    m.fit(bad, batch_size=8, epochs=1, shuffle=False, verbose=0,
          log_freq=1, nan_policy="skip", num_iters=2,
          callbacks=[_Cap(seen)])
    # budget 2 = 1 skip + 1 trained batch
    assert len(seen) == 1
    assert m._optimizer._global_step == 1


def test_resume_mid_epoch_with_shuffle_warns(tmp_path, monkeypatch):
    """Review finding: the mid-epoch fast-forward only replays the same
    data under a deterministic order — resuming with the default
    unseeded shuffle must say so."""
    monkeypatch.setenv("PT_CKPT_MAX_INTERVAL", "1")
    ck = str(tmp_path / "ck")
    m1 = _build()
    with pytest.raises(RuntimeError, match="simulated crash"):
        m1.fit(_dataset(), batch_size=8, epochs=1, verbose=0,
               log_freq=1, shuffle=True, checkpoint_dir=ck,
               callbacks=[_Cap([], crash_at=3)])
    m2 = _build()
    with pytest.warns(UserWarning, match="unseeded shuffling loader"):
        m2.fit(_dataset(), batch_size=8, epochs=1, verbose=0,
               shuffle=True, resume_from=ck)


def test_restore_rejects_foreign_optimizer_state(tmp_path):
    """Review finding: a checkpoint saved under a different optimizer
    config must fail fast, not silently pair restored step counters with
    freshly-zeroed moments."""
    model = _build()
    ck = str(tmp_path / "ck")
    model.fit(_dataset(), batch_size=8, epochs=1, shuffle=False,
              verbose=0, num_iters=2, checkpoint_dir=ck)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    momentum = paddle.optimizer.Momentum(learning_rate=1e-2,
                                         parameters=net.parameters())
    with pytest.raises(KeyError, match="missing optimizer state"):
        rez.restore_latest(net, momentum, ck)


def test_nan_policy_rejects_unknown():
    m = _build()
    with pytest.raises(ValueError, match="nan_policy"):
        m.fit(_dataset(), batch_size=8, verbose=0, nan_policy="retry")


def test_trainstep_step_count_rolls_back_on_nonfinite():
    from paddle_tpu.monitor.numerics import NonFiniteError

    m = _build()
    step = m._train_step
    step._nan_check = True
    good = [np.ones((8, 8), np.float32), np.ones((8, 1), np.float32)]
    step(*good)
    assert step._step_count == 1
    bad = [np.full((8, 8), np.nan, np.float32),
           np.ones((8, 1), np.float32)]
    with pytest.raises(NonFiniteError) as ei:
        step(*bad)
    assert ei.value.step == 2  # the failed step's 1-based index...
    assert step._step_count == 1  # ...but the counter did not advance
    assert m._optimizer._global_step == 1
    step(*good)
    assert step._step_count == 2


# -- StepLogger / postmortem -------------------------------------------------

def test_run_end_names_last_checkpoint_step(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = monitor.StepLogger(path, meta={"source": "test"})
    log.log_step(loss=1.0)
    log.note_checkpoint(41)
    log.close(error="RuntimeError: boom")
    lines = [json.loads(ln) for ln in open(path)]
    end = [ln for ln in lines if ln.get("event") == "run_end"][-1]
    assert end["error"].startswith("RuntimeError")
    assert end["last_checkpoint_step"] == 41


def test_fit_crash_postmortem_carries_checkpoint_step(tmp_path,
                                                      monkeypatch):
    """Satellite: the crashed fit's run_end error record says what a
    relaunch will resume from (MonitorCallback.on_checkpoint ->
    StepLogger.note_checkpoint)."""
    monkeypatch.setenv("PT_CKPT_MAX_INTERVAL", "1")
    sink = str(tmp_path / "run.jsonl")
    monitor.enable()
    try:
        monitor.reset()
        from paddle_tpu.hapi.callbacks import MonitorCallback

        m = _build()
        cb = MonitorCallback(path=sink)
        with pytest.raises(RuntimeError, match="simulated crash"):
            m.fit(_dataset(), batch_size=8, epochs=1, shuffle=False,
                  verbose=0, log_freq=1,
                  checkpoint_dir=str(tmp_path / "ck"),
                  callbacks=[cb, _Cap([], crash_at=4)])
    finally:
        monitor.disable()
        monitor.reset()
    lines = [json.loads(ln) for ln in open(sink)]
    end = [ln for ln in lines if ln.get("event") == "run_end"][-1]
    assert "error" in end
    # the postmortem names EXACTLY what a relaunch will resume from —
    # step 3, or 2 when step 3's async writer hadn't finished at crash
    # time (the crash path never blocks on an in-flight writer)
    resumable = resilience.latest_complete(str(tmp_path / "ck"))[0]
    assert end["last_checkpoint_step"] == resumable
    assert resumable in (2, 3), resumable


def test_monitor_report_renders_resilience_section(tmp_path,
                                                   monkeypatch):
    monkeypatch.setenv("PT_CKPT_MAX_INTERVAL", "1")
    sink = str(tmp_path / "run.jsonl")
    monitor.enable()
    try:
        monitor.reset()
        from paddle_tpu.hapi.callbacks import MonitorCallback

        m = _build()
        m.fit(_dataset(poison_batch=3), batch_size=8, epochs=1,
              shuffle=False, verbose=0, log_freq=1, nan_policy="skip",
              checkpoint_dir=str(tmp_path / "ck"),
              callbacks=[MonitorCallback(path=sink)])
    finally:
        monitor.disable()
        monitor.reset()
    out = subprocess.run(
        [sys.executable, "tools/monitor_report.py", sink],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "resilience (checkpoints + NaN policy)" in out.stdout
    assert "NaN batches skipped: 1" in out.stdout
    assert "last complete checkpoint" in out.stdout


# -- soak smoke (the tier-1 acceptance gate) ---------------------------------

def test_soak_smoke_survives_crash_and_poison(tmp_path):
    """tools/soak.py --smoke with an injected crash AND an injected NaN
    batch: exits 0, emits one parseable JSON verdict with every gate ok,
    and the relaunched life resumed from a COMPLETE checkpoint."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PT_SOAK_CRASH_AT": "12", "PT_SOAK_POISON_AT": "24"})
    env.pop("PT_MONITOR", None)
    env.pop("PADDLE_RESTART_COUNT", None)
    proc = subprocess.run(
        [sys.executable, "tools/soak.py", "--smoke", "--steps", "36",
         "--out", str(tmp_path / "soak")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.startswith("{")][-1]
    assert line["metric"] == "soak" and line["ok"] is True
    assert line["lives"] == 2
    assert line["skipped_batches"] >= 1
    by_name = {c["name"]: c["ok"] for c in line["checks"]}
    for name in ("launcher", "finished", "crash_resume", "nan_skip",
                 "loss_slope", "emitted"):
        assert by_name.get(name) is True, (name, line["checks"])
