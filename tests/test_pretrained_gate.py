"""`pretrained=True` must raise, never silently return random weights.

Reference behavior: constructors load trained weights
(`python/paddle/vision/models/resnet.py:312`); with no egress the honest
TPU-side contract is an `UnavailableError` with the local-load recipe —
the same contract `vision/datasets.py` applies to `download=True`.
"""
import inspect

import pytest

import paddle_tpu.vision.models as M
from paddle_tpu.framework.errors import UnavailableError


def _constructors():
    out = []
    for name in sorted(set(dir(M))):
        fn = getattr(M, name)
        if name.startswith("_") or not callable(fn) or inspect.isclass(fn):
            continue
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):
            continue
        if "pretrained" in sig.parameters:
            out.append(name)
    return out


CTORS = _constructors()


def test_zoo_has_expected_breadth():
    # resnet x8, vgg x4, mobilenet x4, densenet x5, alexnet, squeezenet x2,
    # shufflenet x6, googlenet, inception_v3
    assert len(CTORS) >= 30, CTORS


@pytest.mark.parametrize("name", CTORS)
def test_pretrained_true_raises(name):
    with pytest.raises(UnavailableError, match="pretrained"):
        getattr(M, name)(pretrained=True)
