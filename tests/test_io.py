"""DataLoader worker-mode tests (reference
`io/dataloader/dataloader_iter.py`: single/multi-process iterators)."""
import numpy as np
import pytest

import paddle_tpu as pt


class _RangeDataset(pt.io.Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i], np.int64)




class TestProcessWorkers:
    """worker_mode='process' (reference _DataLoaderIterMultiProcess)."""

    def test_order_and_values(self):
        import paddle_tpu as pt

        ds = _RangeDataset(37)
        dl = pt.io.DataLoader(ds, batch_size=5, num_workers=2,
                              worker_mode="process")
        seen = []
        for b in dl:
            seen.extend(np.asarray(b.numpy()).ravel().tolist())
        assert seen == list(range(37))

    def test_worker_init_fn_runs_in_child_pids(self):
        import multiprocessing as mp
        import os

        import paddle_tpu as pt

        init_q = mp.get_context("fork").Queue()

        def init_fn(wid):
            init_q.put((wid, os.getpid()))

        class PidDataset(pt.io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return np.asarray([os.getpid()], np.int64)

        dl = pt.io.DataLoader(PidDataset(), batch_size=2, num_workers=2,
                              worker_mode="process",
                              worker_init_fn=init_fn)
        got = {int(np.asarray(b.numpy()).ravel()[0]) for b in dl}
        assert os.getpid() not in got  # work really ran out-of-process
        inits = [init_q.get(timeout=5) for _ in range(2)]
        assert sorted(w for w, _ in inits) == [0, 1]
        assert all(pid != os.getpid() for _, pid in inits)

    def test_worker_init_fn_error_fails_fast_thread_mode(self):
        import paddle_tpu as pt

        def bad_init(wid):
            raise ValueError("boom in init")

        dl = pt.io.DataLoader(_RangeDataset(8), batch_size=2,
                              num_workers=2, worker_init_fn=bad_init)
        with pytest.raises(RuntimeError, match="worker_init_fn failed"):
            list(dl)

    def test_dead_worker_process_raises_not_hangs(self):
        import paddle_tpu as pt

        class Killer(pt.io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 3:
                    import os

                    os._exit(17)  # simulated OOM-kill / segfault
                return np.asarray([i], np.int64)

        dl = pt.io.DataLoader(Killer(), batch_size=2, num_workers=2,
                              worker_mode="process")
        with pytest.raises(RuntimeError, match="died"):
            list(dl)

    def test_error_propagates(self):
        import paddle_tpu as pt

        class Bad(pt.io.Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                if i == 2:
                    raise ValueError("bad item 2")
                return np.zeros(2, np.float32)

        dl = pt.io.DataLoader(Bad(), batch_size=2, num_workers=2,
                              worker_mode="process")
        with pytest.raises(RuntimeError, match="bad item 2"):
            list(dl)

    def test_invalid_mode_raises(self):
        import paddle_tpu as pt

        with pytest.raises(Exception, match="worker_mode"):
            pt.io.DataLoader(_RangeDataset(4), batch_size=2,
                             worker_mode="greenlet")

    def test_thread_mode_worker_init_fn(self):
        import paddle_tpu as pt

        called = []
        dl = pt.io.DataLoader(_RangeDataset(8), batch_size=2,
                              num_workers=2,
                              worker_init_fn=lambda w: called.append(w))
        list(dl)
        assert sorted(called) == [0, 1]
