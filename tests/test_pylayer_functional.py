"""PyLayer + functional autograd tests (reference
`test/legacy_test/test_pylayer_op.py`, `test/autograd/`)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer, hessian, jacobian, jvp, vjp


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, grad):
                (x,) = ctx.saved_tensor()
                return grad * 3.0 * x * x

        x = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                             stop_gradient=False)
        y = Cube.apply(x)
        np.testing.assert_allclose(y.numpy(), [8.0, 27.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0, 27.0])

    def test_wrong_backward_detected_by_shape(self):
        class Bad(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x * 2

            @staticmethod
            def backward(ctx, grad):
                return grad  # claims d/dx = 1 (wrong value, right shape)

        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = Bad.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(3))  # user's rule

    def test_multi_output(self):
        class Split2(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x * 2, x * 3

            @staticmethod
            def backward(ctx, g1, g2):
                return g1 * 2 + g2 * 3

        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        a, b = Split2.apply(x)
        (a.sum() + b.sum()).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_inside_layer_training(self):
        import paddle_tpu.nn as nn

        class ScaledReLU(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return paddle.maximum(x, paddle.zeros_like(x)) * 2

            @staticmethod
            def backward(ctx, g):
                (x,) = ctx.saved_tensor()
                mask = paddle.to_tensor(
                    (x.numpy() > 0).astype(np.float32))
                return g * mask * 2

        lin = nn.Linear(4, 4)
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        y = ScaledReLU.apply(lin(x))
        y.mean().backward()
        assert lin.weight.grad is not None


class TestFunctional:
    def test_vjp(self):
        def f(x):
            return (x ** 3).sum()

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out, g = vjp(f, x)
        np.testing.assert_allclose(g.numpy(), [3.0, 12.0], rtol=1e-6)

    def test_jvp(self):
        def f(x):
            return x * x

        x = paddle.to_tensor(np.array([3.0], np.float32))
        v = paddle.to_tensor(np.array([1.0], np.float32))
        out, tangent = jvp(f, x, v)
        np.testing.assert_allclose(tangent.numpy(), [6.0], rtol=1e-6)

    def test_jacobian(self):
        def f(x):
            return x * x

        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        J = jacobian(f, x)
        np.testing.assert_allclose(J.numpy(), np.diag([2.0, 4.0, 6.0]),
                                   rtol=1e-6)

    def test_hessian(self):
        def f(x):
            return (x ** 3).sum()

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        H = hessian(f, x)
        np.testing.assert_allclose(H.numpy(), np.diag([6.0, 12.0]), rtol=1e-6)
