"""utils/timing.device_sync — the transfer-backed fence every wall-clock
measurement in this repo relies on (see PERF.md round-4 sync correction:
block_until_ready acks enqueue, not completion, through tunneled PJRT)."""
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.utils.timing import device_sync


def test_returns_input_unchanged():
    x = jnp.arange(6.0).reshape(2, 3)
    out = device_sync(x)
    assert out is x
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_pytree_and_scalar_and_empty():
    tree = {"a": jnp.ones((3,)), "b": [jnp.zeros(())]}
    assert device_sync(tree) is tree
    assert device_sync(jnp.float32(2.0)) is not None
    # no array leaves: must not raise
    assert device_sync({"note": "no arrays"}) is not None
    assert device_sync(None) is None


def test_fences_computation():
    # after device_sync the value must be host-readable instantly and
    # correct — i.e. the computation actually ran
    y = device_sync(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
    assert float(np.asarray(y)[0, 0]) == 64.0


@pytest.mark.slow
def test_longcontext_bench_smoke_emits_json():
    import pathlib

    root = str(pathlib.Path(__file__).resolve().parents[1])
    proc = subprocess.run(
        [sys.executable, "benchmarks/longcontext_bench.py", "--smoke"],
        capture_output=True, text=True, timeout=600, cwd=root)
    assert proc.returncode == 0, proc.stderr[-500:]
    import json

    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, proc.stdout
    rec = json.loads(lines[-1])
    assert rec["metric"] == "llama_longcontext_train_tokens_per_sec_per_chip"
    assert rec["value"] > 0
