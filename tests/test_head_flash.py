"""Head-batched flash kernel parity (interpret mode on CPU — the
fake-device strategy of test_pallas_flash.py, on the native
``[b, s, h, d]`` layout the kernel exists to keep)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import head_flash, search
from paddle_tpu.ops.pallas.flash_attention import (
    _flash_bhsd, _flash_bhsd_drop,
)
from paddle_tpu.ops.pallas.head_flash import hb_flash


@pytest.fixture(autouse=True)
def _highest_precision():
    old = jax.config.jax_default_matmul_precision
    jax.config.update("jax_default_matmul_precision", "highest")
    yield
    jax.config.update("jax_default_matmul_precision", old or "highest")


def _qkv(b=2, sq=64, sk=64, h=4, h_kv=None, d=32, seed=0):
    h_kv = h if h_kv is None else h_kv
    rng = np.random.RandomState(seed)
    q = rng.randn(b, sq, h, d).astype(np.float32)
    k = rng.randn(b, sk, h_kv, d).astype(np.float32)
    v = rng.randn(b, sk, h_kv, d).astype(np.float32)
    return q, k, v


def _reference(q, k, v, causal=False, kmask=None, window=0):
    """Native-layout fp32 composite with GQA (repeat) + bottom-right
    causal — the same convention as `_sdpa_reference` / the bhsd
    kernel."""
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    g = h // h_kv
    kr = np.repeat(np.asarray(k, np.float32), g, axis=2)
    vr = np.repeat(np.asarray(v, np.float32), g, axis=2)
    s = np.einsum("bqhd,bkhd->bhqk", np.asarray(q, np.float32),
                  kr) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        if window > 0:
            mask &= ~np.tril(np.ones((sq, sk), bool),
                             k=sk - sq - window)
        s = np.where(mask[None, None], s, -1e30)
    if kmask is not None:
        s = s + np.asarray(kmask, np.float32)[:, None, :, :]  # [b,1,1,sk]
    mx = s.max(-1, keepdims=True)
    e = np.exp(s - mx)
    p = e / e.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bkhd->bqhd", p, vr)
    # rows with every key masked output exactly 0 (flash >= 2.1)
    dead = (s <= -1e30 * 0.5).all(-1)
    out[np.transpose(dead, (0, 2, 1))] = 0.0
    return out


@pytest.mark.parametrize("causal", [False, True])
def test_forward_parity(causal):
    q, k, v = _qkv()
    out = hb_flash(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _reference(q, k, v, causal=causal),
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grad_parity(causal):
    q, k, v = _qkv(sq=64, sk=64)
    w = np.random.RandomState(1).randn(*q.shape).astype(np.float32)

    def kern(*a):
        return (hb_flash(*a, causal=causal, interpret=True) * w).sum()

    def comp(*a):
        fam = search.FAMILIES["flash_headbatch"]
        shape = (q.shape[0], q.shape[1], k.shape[1], q.shape[2],
                 k.shape[2], q.shape[3], causal)
        return (fam.build_composite(shape)(*a).astype(jnp.float32)
                * w).sum()

    g1 = jax.grad(kern, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(comp, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        scale = np.abs(np.asarray(b)).max() + 1e-9
        np.testing.assert_allclose(np.asarray(a) / scale,
                                   np.asarray(b) / scale, atol=1e-4)


def test_gqa_parity_grouped_in_tile():
    # h=6, h_kv=2: three query heads share each KV head with no repeat
    # materialization; also exercises non-power-of-two head counts
    q, k, v = _qkv(h=6, h_kv=2, d=32)
    out = hb_flash(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _reference(q, k, v, causal=True),
                               atol=2e-5)
    # GQA grads: dk/dv reduce over the grouped query heads in-tile
    g1 = jax.grad(lambda *a: (hb_flash(
        *a, causal=True, interpret=True) ** 2).sum(),
        argnums=(1, 2))(q, k, v)
    assert g1[0].shape == k.shape and g1[1].shape == v.shape
    assert float(jnp.abs(g1[0]).max()) > 0


@pytest.mark.parametrize("sq,sk", [(32, 64), (64, 32)])
def test_cross_length_causal_bottom_right(sq, sk):
    q, k, v = _qkv(sq=sq, sk=sk)
    out = np.asarray(hb_flash(q, k, v, causal=True, interpret=True))
    ref = _reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    if sq > sk:
        # bottom-right alignment: leading rows attend to NO key and
        # output exactly 0 (flash-attn >= 2.1 semantics)
        assert np.abs(out[:, :sq - sk]).max() == 0


def test_key_padding_mask_parity_and_cotangent():
    q, k, v = _qkv(sq=32, sk=64)
    b, sk = q.shape[0], k.shape[1]
    keep = np.arange(sk)[None, :] < np.array([40, 50])[:, None]
    km = np.where(keep, 0.0, -1e30).astype(np.float32)[:, None, :]

    out = hb_flash(q, k, v, kmask=jnp.asarray(km), causal=False,
                   interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _reference(q, k, v, kmask=km), atol=2e-5)

    # the in-kernel mask cotangent (summed over heads AND rows) matches
    # autodiff through the composite
    w = np.random.RandomState(3).randn(*q.shape).astype(np.float32)

    def kern(m):
        return (hb_flash(q, k, v, kmask=m, causal=False,
                         interpret=True) * w).sum()

    def comp(m):
        g = q.shape[2] // k.shape[2]
        kr = jnp.repeat(jnp.asarray(k), g, axis=2)
        vr = jnp.repeat(jnp.asarray(v), g, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", jnp.asarray(q),
                       kr) / np.sqrt(q.shape[3])
        s = s + m[:, None, :, :]
        p = jax.nn.softmax(s, axis=-1)
        return (jnp.einsum("bhqk,bkhd->bqhd", p, vr) * w).sum()

    g1 = jax.grad(kern)(jnp.asarray(km))
    g2 = jax.grad(comp)(jnp.asarray(km))
    live = np.asarray(keep)[:, None, :]
    np.testing.assert_allclose(np.asarray(g1)[live],
                               np.asarray(g2)[live], atol=1e-4)


def test_dropout_bit_identical_mask_vs_bhsd_kernel():
    """The head-batched kernel feeds `_keep_mask` the same flattened
    b·h + i head index the bhsd kernel's grid row carries, so for one
    seed the two kernels drop IDENTICAL elements — proven by comparing
    outputs (a single flipped mask bit shifts a value by O(1/keep)).
    Block shapes differ on purpose: the mask is a pure function of
    global coordinates, not of the tiling."""
    q, k, v = _qkv(b=2, sq=64, sk=64, h=4, d=32)
    seed = jnp.asarray([7, 9], jnp.int32)
    drop = 0.4
    out_hb = hb_flash(q, k, v, seed, causal=True, interpret=True,
                      block_q=32, block_k=32, dropout=drop)
    b, sq, h, d = q.shape
    qt = jnp.asarray(q).transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = jnp.asarray(k).transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    vt = jnp.asarray(v).transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    out_ref = _flash_bhsd_drop(
        qt, kt, vt, seed, True, 1.0 / np.sqrt(d), True, 64, 64, 0,
        drop).reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out_hb), np.asarray(out_ref),
                               atol=2e-5)
    # and dropout actually drops
    no_drop = hb_flash(q, k, v, causal=True, interpret=True)
    assert float(jnp.abs(out_hb - no_drop).max()) > 1e-3


def test_sliding_window_parity():
    q, k, v = _qkv(sq=64, sk=64)
    out = hb_flash(q, k, v, causal=True, interpret=True, window=16)
    np.testing.assert_allclose(
        np.asarray(out), _reference(q, k, v, causal=True, window=16),
        atol=2e-5)


def test_lse_layout_matches_outputs():
    # the backward consumes lse [b, sq, h, _LANES]; its first lane must
    # be the true per-row log-sum-exp (lane-broadcast)
    from paddle_tpu.ops.pallas.head_flash import _hb_fwd

    q, k, v = _qkv(sq=32, sk=32)
    out, lse = _hb_fwd(q, k, v, False, 1.0 / np.sqrt(q.shape[3]), True)
    assert lse.shape == (q.shape[0], q.shape[1], q.shape[2], 128)
    np.testing.assert_allclose(np.asarray(lse[..., 0]),
                               np.asarray(lse[..., 1]))


def test_flash_attention_kernel_routes_to_headbatch_on_engaged_row(
        monkeypatch):
    """Dispatch wiring: with a measured-faster flash_headbatch row for
    the exact shape key, `flash_attention_kernel` takes the head-batch
    path (no transposes) with the row's tuned blocks; without a row it
    never does."""
    from paddle_tpu.ops.pallas import flash_attention as fa

    q, k, v = _qkv(b=1, sq=128, sk=128, h=2, d=128)
    key = head_flash.shape_key(1, 128, 128, 2, 2, 128, True)
    calls = []

    def fake_hb(q_, k_, v_, seed, kmask, causal, scale, interpret,
                bq, bk, window, dropout):
        calls.append({"bq": bq, "bk": bk, "causal": causal,
                      "dropout": dropout})
        return jnp.zeros(q_.shape, q_.dtype)

    monkeypatch.setattr(head_flash, "hb_flash", fake_hb)
    monkeypatch.setattr(
        search, "engaged",
        lambda fam, k_: True if (fam, k_) == ("flash_headbatch", key)
        else None)
    monkeypatch.setattr(
        search, "best_config",
        lambda fam, k_: {"block_q": 64, "block_k": 128})
    out = fa.flash_attention_kernel(q, k, v, causal=True)
    assert calls == [{"bq": 64, "bk": 128, "causal": True,
                      "dropout": 0.0}]
    assert out.shape == q.shape
    # variant calls (dropout/kmask) carry different keys -> no routing
    calls.clear()
    fa.flash_attention_kernel(q, k, v, causal=False)
    assert calls == []


def test_check_lowering_is_registered():
    from paddle_tpu.ops import registry

    assert "tpu" in registry._OPS["flash_attention_headbatch"].kernels
    fn = registry._OPS["flash_attention_headbatch"].kernels["tpu"]
    assert fn.check_lowering is head_flash.check_lowering
