"""Per-request serving traces (ISSUE 16): lifecycle spans, TTFT/TPOT
attribution, and the black-box postmortem dump.

Four proof layers:

- **Token identity** — the traced engine (PT_MONITOR on) emits byte-
  identical tokens AND a byte-identical scheduler event ring vs the
  untraced engine: tracing is observation, never behavior.
- **Attribution telescoping** — every finished request's
  {queue, prefill, decode, preempted} buckets sum to its measured
  end-to-end latency (the engine advances ONE clock mark per phase
  boundary, so the identity is exact, not approximate), preempted
  requests bill their off-lane time to ``preempted_ms``, and the
  attribution stays on with the monitor off.
- **Span taxonomy** — queue-wait/prefill/round/finish spans land on the
  ``req/<trace_id>`` and ``serve/rounds`` lanes with the documented
  cats; spec rollback rounds record exactly one COMPLETE verify span
  each (a rewound ``pool_len`` cannot leave an open span).
- **Blackbox** — an engine raise writes ``serving_blackbox.json``
  (spans tail + scheduler state + finished journeys) without masking
  the error; a tiny ring cap still yields a well-formed artifact with
  ``spans_dropped`` accounting; ungated crash sites stay artifact-free.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.serving.engine as engine_mod
from paddle_tpu import monitor
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate
from paddle_tpu.monitor import blackbox
from paddle_tpu.monitor.spans import SpanRecorder
from paddle_tpu.serving import ServingConfig, ServingEngine


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    m.eval()
    return m


@pytest.fixture
def mon(tmp_path, monkeypatch):
    """Enabled monitor with clean metrics/spans; restores disabled-off."""
    monkeypatch.setenv("PT_MONITOR_SINK", str(tmp_path / "steps.jsonl"))
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


def _workload(model, seed=0, n=6, plen=(3, 11), new=(4, 11)):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, model.config.vocab_size,
                         (int(rng.randint(*plen)),)).astype(np.int32),
             int(rng.randint(*new))) for _ in range(n)]


def _run(model, work, **cfg_kw):
    cfg = ServingConfig(**{**dict(max_lanes=3, block_size=4,
                                  prefill_chunk=8, max_seq_len=32),
                           **cfg_kw})
    eng = ServingEngine(model, cfg)
    handles = [eng.submit(p, max_new_tokens=n) for p, n in work]
    outs = eng.run()
    return eng, [outs[h.request_id] for h in handles], handles


def _spans_by_name(name):
    return [s for s in monitor.spans().snapshot() if s[0] == name]


# -- token identity: tracing is observation -----------------------------------

class TestTracedIdentity:
    def test_traced_engine_tokens_and_events_identical(self, model, mon):
        work = _workload(model)
        eng_on, traced, _ = _run(model, work)
        traced_events = list(eng_on.scheduler.events)
        monitor.disable()
        try:
            eng_off, plain, _ = _run(model, work)
        finally:
            monitor.enable()
        # same tokens, same scheduler decisions, byte for byte: the
        # span/attribution layer never feeds back into behavior
        for a, b in zip(traced, plain):
            np.testing.assert_array_equal(a, b)

        def _norm(events):
            # request ids are a process-global counter: compare the two
            # rings with ids rebased to each run's first submit
            base = min(e[1] for e in events if e[0] == "submit")
            return [(e[0], e[1] - base, *e[2:]) for e in events]

        assert _norm(traced_events) == _norm(list(eng_off.scheduler.events))
        for (p, n), out in zip(work, plain):
            np.testing.assert_array_equal(
                out, generate(model, pt.to_tensor(np.asarray(p)[None, :]),
                              max_new_tokens=n).numpy()[0])


# -- attribution: telescoping latency buckets ---------------------------------

class TestAttribution:
    def test_buckets_sum_to_request_latency(self, model):
        # monitor OFF on purpose: attribution is always-on plain floats
        assert not monitor.enabled()
        eng, _, handles = _run(model, _workload(model))
        assert engine_mod._spans is None  # and yet:
        for h in handles:
            assert h.t_done is not None
            total = (h.t_done - h.t_submit) * 1e3
            parts = (h.queue_ms + h.prefill_ms + h.decode_ms
                     + h.preempted_ms)
            # exact telescoping (one clock mark per phase boundary) —
            # only float rounding separates the sum from the total
            assert parts == pytest.approx(total, rel=1e-6, abs=1e-3)
            assert h.prefill_ms > 0 and h.decode_ms > 0
            att = h.attribution()
            assert set(att) == {
                "queue_ms", "prefill_ms", "decode_ms", "preempted_ms",
                "prefill_refunded_tokens", "spec_rounds",
                "accepted_tokens"}

    def test_preempted_requests_bill_preempted_ms(self, model):
        # pressure geometry from test_serving's preemption proof
        eng, outs, handles = _run(
            model, _workload(model, seed=1, plen=(2, 9), new=(6, 12)),
            max_lanes=3, block_size=2, num_blocks=12, prefill_chunk=4,
            max_seq_len=20)
        assert eng.counters["preemptions"] > 0, "never preempted — vacuous"
        victims = [h for h in handles if h.preemptions]
        assert victims
        for h in victims:
            # off-lane wait after eviction is preempted time, not queue
            assert h.preempted_ms > 0
            total = (h.t_done - h.t_submit) * 1e3
            parts = (h.queue_ms + h.prefill_ms + h.decode_ms
                     + h.preempted_ms)
            assert parts == pytest.approx(total, rel=1e-6, abs=1e-3)


# -- span taxonomy ------------------------------------------------------------

class TestServingSpans:
    def test_request_lifecycle_spans(self, model, mon):
        eng, _, handles = _run(model, _workload(model))
        spans = monitor.spans().snapshot()
        lanes = {s[2] for s in spans}
        assert "serve/rounds" in lanes
        for h in handles:
            assert h.trace_id == f"r{h.request_id}"
            assert f"req/{h.trace_id}" in lanes
        by_name = {}
        for s in spans:
            by_name.setdefault(s[0], []).append(s)
        assert len(by_name["serving/queue_wait"]) == len(handles)
        assert len(by_name["serving/prefill"]) \
            >= len(handles)  # >= : recompute prefills add more
        assert sum(s[5]["chunks"] for s in by_name["serving/prefill"]) \
            == eng.counters["prefill_chunks"]
        rounds = by_name.get("serving/decode_round", []) \
            + by_name.get("serving/verify_round", [])
        assert len(rounds) == eng.counters["decode_steps"] \
            + eng.counters["verify_steps"]
        finishes = by_name["serving/request"]
        assert len(finishes) == len(handles)
        for s in finishes:
            args = s[5]
            assert s[1] == "serving_finish"
            parts = (args["queue_ms"] + args["prefill_ms"]
                     + args["decode_ms"] + args["preempted_ms"])
            assert parts == pytest.approx(args["total_ms"], abs=0.01)
            assert s[4] >= s[3]  # completed span, t1 >= t0

    def test_spec_rollback_closes_round_spans(self, model, mon):
        """Satellite 6: a verify round that REJECTS drafts (rolling
        pool_len back) must still record exactly one complete
        verify_round span — never an open/torn one — and token output
        must stay byte-identical to generate()."""
        rng = np.random.RandomState(3)
        motif = rng.randint(0, model.config.vocab_size, (4,))
        work = [(np.tile(motif, 4).astype(np.int32), 8) for _ in range(3)]
        eng, outs, _ = _run(model, work, spec=True, spec_k=4)
        assert eng.counters["verify_steps"] > 0, "spec never engaged"
        rejected = (eng.counters["spec_proposed_tokens"]
                    - eng.counters["spec_accepted_tokens"])
        vspans = _spans_by_name("serving/verify_round")
        assert len(vspans) == eng.counters["verify_steps"]
        for s in vspans:
            assert s[4] >= s[3], "open/torn round span"
            assert s[5]["accepted"] <= s[5]["proposed"]
        # token identity survives rollback (tolerate all-accepted runs,
        # but the motif workload normally rejects at least once)
        for (p, n), out in zip(work, outs):
            np.testing.assert_array_equal(
                out, generate(model, pt.to_tensor(np.asarray(p)[None, :]),
                              max_new_tokens=n).numpy()[0])
        if rejected:
            # the rewound lanes kept decoding: rounds after a rollback
            # still recorded (count above already pins one span/round)
            assert eng.counters["decoded_tokens"] > 0

    def test_preempt_marker_and_requeue_span(self, model, mon):
        eng, _, handles = _run(
            model, _workload(model, seed=1, plen=(2, 9), new=(6, 12)),
            max_lanes=3, block_size=2, num_blocks=12, prefill_chunk=4,
            max_seq_len=20)
        assert eng.counters["preemptions"] > 0
        marks = _spans_by_name("serving/preempt")
        assert len(marks) == eng.counters["preemptions"]
        for s in marks:
            assert s[3] == s[4]  # zero-length marker
        # every victim that got back on a lane recorded its off-lane
        # wait as a requeue_wait span on its own trace lane
        requeues = _spans_by_name("serving/requeue_wait")
        assert len(requeues) > 0
        assert all(s[5]["preemptions"] > 0 for s in requeues)


# -- ring cap + blackbox ------------------------------------------------------

class TestBlackbox:
    def test_ring_cap_evicts_cleanly_and_dump_stays_wellformed(
            self, model, tmp_path, monkeypatch):
        """Satellite 3: under a tiny span ring the oldest spans evict,
        the engine keeps running, and the blackbox artifact still emits
        well-formed (partial) journeys with honest drop accounting."""
        monkeypatch.setattr(monitor, "_span_recorder",
                            SpanRecorder(capacity=8))
        monkeypatch.setenv("PT_MONITOR_SINK",
                           str(tmp_path / "steps.jsonl"))
        monitor.reset()
        monitor.enable()
        try:
            eng, _, handles = _run(model, _workload(model))
            rec = monitor.spans()
            assert rec is engine_mod._spans  # the small ring got wired
            assert rec.count > 8 and rec.dropped > 0
            assert len(rec.snapshot()) <= 8
            out = blackbox.dump(path=str(tmp_path / "bb.json"),
                                reason="ring_cap_test")
            assert out is not None
            art = json.loads(open(out).read())
            assert art["version"] == 1
            assert art["spans_recorded"] == rec.count
            assert art["spans_dropped"] >= rec.dropped
            assert 0 < len(art["spans"]) <= 8
            for sp in art["spans"]:
                assert {"name", "cat", "lane", "t0", "t1",
                        "args"} <= set(sp)
            # every live engine registers a provider — find THIS one by
            # its finished journeys (earlier tests' engines may linger)
            eng_state = next(
                v for k, v in art["state"].items()
                if k.startswith("serving_engine")
                and len(v.get("finished_tail", [])) == len(handles))
            assert eng_state["scheduler"]["pool"]["free"] \
                + eng_state["scheduler"]["pool"]["used"] \
                + eng_state["scheduler"]["pool"]["cold"] \
                == eng_state["scheduler"]["pool"]["capacity"]
            # finished journeys survive even when their spans evicted
            for j in eng_state["finished_tail"]:
                assert j["total_ms"] is not None
        finally:
            monitor.disable()
            monitor.reset()

    def test_engine_raise_writes_blackbox(self, model, tmp_path,
                                          monkeypatch):
        bb = tmp_path / "serving_blackbox.json"
        monkeypatch.setenv("PT_SERVE_BLACKBOX", str(bb))
        eng, _, _ = _run(model, _workload(model, n=2))

        def boom(*a, **kw):
            raise ValueError("injected prefill failure")

        monkeypatch.setattr(eng, "_prefill", boom)
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=3)
        with pytest.raises(ValueError, match="injected prefill"):
            eng.run()
        assert bb.exists()
        art = json.loads(bb.read_text())
        assert art["reason"] == "serving_engine_raise"
        assert "injected prefill" in art["error"]
        assert isinstance(art["spans"], list)
        # the mid-flight request is captured with its partial journey
        # (scan: every live engine registers a provider)
        live = [v["scheduler"] for k, v in art["state"].items()
                if k.startswith("serving_engine")
                and v.get("scheduler", {}).get("requests")]
        assert live, "no live requests in the postmortem"
        assert {"trace_id", "state", "queue_ms",
                "decode_ms"} <= set(live[-1]["requests"][0])

    def test_raise_without_audience_stays_artifact_free(
            self, model, tmp_path, monkeypatch):
        monkeypatch.delenv("PT_SERVE_BLACKBOX", raising=False)
        monkeypatch.chdir(tmp_path)
        assert not monitor.enabled()
        eng, _, _ = _run(model, _workload(model, n=2))
        monkeypatch.setattr(
            eng, "_prefill",
            lambda *a, **kw: (_ for _ in ()).throw(ValueError("x")))
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=3)
        with pytest.raises(ValueError):
            eng.run()
        assert not os.path.exists(blackbox.DEFAULT_PATH)

    def test_env_zero_disables_even_with_monitor(self, mon, tmp_path,
                                                 monkeypatch):
        monkeypatch.setenv("PT_SERVE_BLACKBOX", "0")
        monkeypatch.chdir(tmp_path)
        assert blackbox.maybe_dump(reason="gated") is None
        assert not os.path.exists(blackbox.DEFAULT_PATH)
