"""Distributed checkpoint reshard-on-load tests (reference
`test/auto_parallel/test_dist_saver.py` + converter tests)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import fleet


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _tp_model(mp):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8 // mp, "mp_degree": mp,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    from paddle_tpu.distributed.fleet.meta_parallel import ColumnParallelLinear

    pt.seed(5)
    return ColumnParallelLinear(8, 16, gather_output=True)


def test_save_load_reshard_tp2_to_tp4(tmp_path):
    m2 = _tp_model(mp=2)
    w_ref = m2.weight.numpy().copy()
    ckpt.save_state_dict({"w": m2.weight, "b": m2.bias}, str(tmp_path))

    m4 = _tp_model(mp=4)
    m4.weight.set_value(np.zeros_like(w_ref))  # scramble, then restore
    ckpt.load_state_dict({"w": m4.weight, "b": m4.bias}, str(tmp_path))
    np.testing.assert_allclose(m4.weight.numpy(), w_ref)
    # destination keeps ITS OWN (tp4) sharding after load
    assert tuple(m4.weight._data.sharding.spec) == (None, "mp")


def test_async_save(tmp_path):
    x = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    t = ckpt.save_state_dict({"x": x}, str(tmp_path), async_save=True)
    t.join()
    loaded = ckpt.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(loaded["x"], x.numpy())


def test_shape_mismatch_raises(tmp_path):
    x = pt.to_tensor(np.zeros((2, 2), np.float32))
    ckpt.save_state_dict({"x": x}, str(tmp_path))
    y = pt.to_tensor(np.zeros((3, 3), np.float32))
    with pytest.raises(ValueError):
        ckpt.load_state_dict({"x": y}, str(tmp_path))


def test_missing_key_raises(tmp_path):
    x = pt.to_tensor(np.zeros(2, np.float32))
    ckpt.save_state_dict({"a": x}, str(tmp_path))
    with pytest.raises(KeyError):
        ckpt.load_state_dict({"zz": x}, str(tmp_path))
