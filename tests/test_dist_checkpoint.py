"""Distributed checkpoint reshard-on-load tests (reference
`test/auto_parallel/test_dist_saver.py` + converter tests)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import fleet


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _tp_model(mp):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8 // mp, "mp_degree": mp,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    from paddle_tpu.distributed.fleet.meta_parallel import ColumnParallelLinear

    pt.seed(5)
    return ColumnParallelLinear(8, 16, gather_output=True)


def test_save_load_reshard_tp2_to_tp4(tmp_path):
    m2 = _tp_model(mp=2)
    w_ref = m2.weight.numpy().copy()
    ckpt.save_state_dict({"w": m2.weight, "b": m2.bias}, str(tmp_path))

    m4 = _tp_model(mp=4)
    m4.weight.set_value(np.zeros_like(w_ref))  # scramble, then restore
    ckpt.load_state_dict({"w": m4.weight, "b": m4.bias}, str(tmp_path))
    np.testing.assert_allclose(m4.weight.numpy(), w_ref)
    # destination keeps ITS OWN (tp4) sharding after load
    assert tuple(m4.weight._data.sharding.spec) == (None, "mp")


def test_async_save(tmp_path):
    x = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    t = ckpt.save_state_dict({"x": x}, str(tmp_path), async_save=True)
    t.join()
    loaded = ckpt.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(loaded["x"], x.numpy())


def test_shape_mismatch_raises(tmp_path):
    x = pt.to_tensor(np.zeros((2, 2), np.float32))
    ckpt.save_state_dict({"x": x}, str(tmp_path))
    y = pt.to_tensor(np.zeros((3, 3), np.float32))
    with pytest.raises(ValueError):
        ckpt.load_state_dict({"x": y}, str(tmp_path))


def test_missing_key_raises(tmp_path):
    x = pt.to_tensor(np.zeros(2, np.float32))
    ckpt.save_state_dict({"a": x}, str(tmp_path))
    with pytest.raises(KeyError):
        ckpt.load_state_dict({"zz": x}, str(tmp_path))


def test_sharded_save_writes_per_region_files(tmp_path):
    # tp2: weight [8, 16] sharded (None, 'mp') over mp=2 -> 2 region files,
    # each holding HALF the tensor — no whole-tensor file on disk
    import json
    import os

    m2 = _tp_model(mp=2)
    ckpt.save_state_dict({"w": m2.weight}, str(tmp_path))
    with open(tmp_path / "index.json") as f:
        idx = json.load(f)
    assert idx["format"] == 2
    shards = idx["tensors"]["w"]["shards"]
    assert len(shards) == 2
    sizes = [os.path.getsize(tmp_path / s["file"]) for s in shards]
    nbytes = m2.weight.numpy().nbytes
    for sz in sizes:
        assert sz < nbytes  # strictly smaller than the global tensor
    # regions tile the tensor exactly
    covered = sorted(tuple(map(tuple, s["index"])) for s in shards)
    assert covered == [((0, 8), (0, 8)), ((0, 8), (8, 16))]


def test_bf16_roundtrip(tmp_path):
    # .npy stores bfloat16 as raw V2 bytes; the loader must re-view with
    # the recorded dtype (latent v1 bug: casting V2 to float raises)
    x = pt.to_tensor(np.arange(8, dtype=np.float32)).astype("bfloat16")
    ckpt.save_state_dict({"x": x}, str(tmp_path))
    y = pt.to_tensor(np.zeros(8, np.float32)).astype("bfloat16")
    ckpt.load_state_dict({"x": y}, str(tmp_path))
    np.testing.assert_allclose(y.astype("float32").numpy(),
                               np.arange(8, dtype=np.float32))
    host = ckpt.load_checkpoint(str(tmp_path))
    assert str(host["x"].dtype) == "bfloat16"


def test_chunked_streaming_large_unsharded(tmp_path, monkeypatch):
    # single-device tensors above the chunk threshold stream through a
    # memmap in row-chunks rather than one giant write
    monkeypatch.setattr(ckpt, "_CHUNK_BYTES", 1024)
    x = pt.to_tensor(np.random.RandomState(0).randn(64, 32)
                     .astype(np.float32))  # 8 KiB > 1 KiB chunks
    ckpt.save_state_dict({"x": x}, str(tmp_path))
    loaded = ckpt.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(loaded["x"], x.numpy())


def test_async_save_bounded(tmp_path, monkeypatch):
    # tiny in-flight budget: producer must hand shards through the queue
    # piece by piece and the result must still be byte-identical
    monkeypatch.setattr(ckpt, "_CHUNK_BYTES", 512)
    vals = {f"t{i}": pt.to_tensor(
        np.random.RandomState(i).randn(32, 16).astype(np.float32))
        for i in range(4)}
    t = ckpt.save_state_dict(vals, str(tmp_path), async_save=True,
                             max_inflight_bytes=2048)
    t.join()
    loaded = ckpt.load_checkpoint(str(tmp_path))
    for k, v in vals.items():
        np.testing.assert_allclose(loaded[k], v.numpy())


def test_v1_format_backward_compat(tmp_path):
    # v1 checkpoints ({'file': ...} entries, no 'shards') still load
    import json

    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.save(tmp_path / "x.npy", arr)
    with open(tmp_path / "index.json", "w") as f:
        json.dump({"tensors": {"x": {"file": "x.npy", "shape": [2, 3],
                                     "dtype": "float32", "spec": None}}}, f)
    dest = pt.to_tensor(np.zeros((2, 3), np.float32))
    ckpt.load_state_dict({"x": dest}, str(tmp_path))
    np.testing.assert_allclose(dest.numpy(), arr)
    np.testing.assert_allclose(ckpt.load_checkpoint(str(tmp_path))["x"], arr)


def test_reshard_load_reads_only_needed_shards(tmp_path):
    # tp4 destination loading a tp2 checkpoint: each device's region cb
    # must assemble from the overlapping tp2 shard files only. Delete one
    # tp2 shard file and ask for a region inside the OTHER shard -> works;
    # the full load then fails (proving per-region reads are real).
    import json
    import os

    m2 = _tp_model(mp=2)
    w_ref = m2.weight.numpy().copy()
    ckpt.save_state_dict({"w": m2.weight}, str(tmp_path))
    with open(tmp_path / "index.json") as f:
        meta = json.load(f)["tensors"]["w"]
    region = ckpt._read_region(str(tmp_path), meta, [[0, 8], [0, 8]])
    np.testing.assert_allclose(region, w_ref[:, :8])
    os.remove(tmp_path / meta["shards"][1]["file"])
    region = ckpt._read_region(str(tmp_path), meta, [[0, 8], [0, 8]])
    np.testing.assert_allclose(region, w_ref[:, :8])  # still fine
    with pytest.raises(FileNotFoundError):
        ckpt._read_region(str(tmp_path), meta, [[0, 8], [0, 16]])


def test_no_full_tensor_host_gather_on_save(tmp_path):
    # the scale contract (SURVEY 5.4 / round-4 verdict missing #3): saving
    # a dp8-sharded tensor must never snapshot more than one shard's bytes
    # at a time. Account every host piece handed to the writer; the max
    # must be global_nbytes/8, not global_nbytes. Holds at any scale.
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from paddle_tpu.distributed import env as env_mod

    import paddle_tpu.distributed.fleet as fl

    strategy = fl.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1}
    fl.init(is_collective=True, strategy=strategy)
    x = pt.to_tensor(np.random.RandomState(0)
                     .randn(128, 256).astype(np.float32))
    arr = x._data
    mesh = env_mod.get_mesh()
    arr = jax.device_put(arr, NamedSharding(mesh, PartitionSpec("dp")))
    x._data = arr
    global_nbytes = 128 * 256 * 4

    pieces = []
    orig = ckpt._emit_tensor

    def spying_emit(key, a, entries, sink, **kw):
        def spy_sink(item, nbytes):
            pieces.append(nbytes)
            sink(item, nbytes)
        return orig(key, a, entries, spy_sink, **kw)

    ckpt._emit_tensor, emit = spying_emit, ckpt._emit_tensor
    try:
        ckpt.save_state_dict({"x": x}, str(tmp_path))
    finally:
        ckpt._emit_tensor = emit
    assert pieces and max(pieces) <= global_nbytes // 8
    loaded = ckpt.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(loaded["x"], np.asarray(arr))


def test_async_writer_failure_surfaces_and_no_deadlock(tmp_path,
                                                       monkeypatch):
    # a dying writer must (a) unblock a producer waiting on the byte
    # budget and (b) raise at join()/wait_all() — not silently pass
    boom = RuntimeError("disk full")

    def bad_save(*a, **k):
        raise boom

    monkeypatch.setattr(ckpt.np, "save", bad_save)
    monkeypatch.setattr(ckpt, "_CHUNK_BYTES", 1 << 30)
    vals = {f"t{i}": pt.to_tensor(
        np.zeros((64, 64), np.float32)) for i in range(8)}
    with pytest.raises(RuntimeError, match="writer failed"):
        # tiny budget: producer must block on the queue, then be released
        # by the failure rather than deadlocking
        t = ckpt.save_state_dict(vals, str(tmp_path), async_save=True,
                                 max_inflight_bytes=16384)
        t.join()
    ckpt._pending.clear()


def test_async_snapshot_is_owned_copy(tmp_path):
    # mutating the source AFTER save_state_dict returns must not corrupt
    # the checkpoint (views would): round-1 ADVICE hazard, re-found in
    # round 5 for host-ndarray inputs
    src = np.arange(16, dtype=np.float32).reshape(4, 4)
    want = src.copy()
    t = ckpt.save_state_dict({"x": src}, str(tmp_path), async_save=True)
    src[:] = -1.0
    t.join()
    np.testing.assert_allclose(ckpt.load_checkpoint(str(tmp_path))["x"],
                               want)


def test_async_index_published_at_join(tmp_path):
    # index.json is the completeness marker: it must not exist until
    # join() runs the finalize (barrier + coordinator index write)
    import os

    x = pt.to_tensor(np.arange(8, dtype=np.float32))
    t = ckpt.save_state_dict({"x": x}, str(tmp_path), async_save=True)
    t.join()
    assert os.path.exists(tmp_path / "index.json")
    np.testing.assert_allclose(ckpt.load_checkpoint(str(tmp_path))["x"],
                               x.numpy())


def test_scalar_keeps_mesh_placement_on_load(tmp_path):
    # 0-d tensors must come back with the destination's sharding, not
    # SingleDeviceSharding (round-5 review finding)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    import paddle_tpu.distributed.fleet as fl
    from paddle_tpu.distributed import env as env_mod

    strategy = fl.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1}
    fl.init(is_collective=True, strategy=strategy)
    s = pt.to_tensor(np.float32(3.5))
    mesh = env_mod.get_mesh()
    s._data = jax.device_put(s._data, NamedSharding(mesh, PartitionSpec()))
    ckpt.save_state_dict({"s": s}, str(tmp_path))
    d = pt.to_tensor(np.float32(0.0))
    d._data = jax.device_put(d._data, NamedSharding(mesh, PartitionSpec()))
    ckpt.load_state_dict({"s": d}, str(tmp_path))
    assert float(d.numpy()) == 3.5
    assert isinstance(d._data.sharding, NamedSharding)
    assert len(d._data.sharding.device_set) == 8
