"""Kernel search harness (`ops/pallas/search.py` + `tools/kernel_search.py`).

Four layers:

- **Tune table** — fcntl-locked atomic read-modify-write (the
  durability fix for the old bare-write `flash_tune.json` tear),
  one-shot legacy migration, device filtering.
- **Engagement rules** — measured-faster-than-composite only; CPU /
  interpret rows never engage; verdicts never transfer across keys.
- **The search pipeline** — candidate enumeration + pruning, the
  mandatory interpret-parity pre-filter (a wrong-but-fast candidate is
  rejected before timing), persisted provenance, monitor counters.
- **Tier-1 CLI smoke** — `python tools/kernel_search.py --smoke` runs
  enumerate -> parity-filter -> timing for every registered family on
  CPU and exits 0 (the acceptance criterion).
"""
import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import monitor
from paddle_tpu.ops.pallas import autotune, head_flash, search
from paddle_tpu.ops.pallas import paged_attention as pa

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def table(tmp_path, monkeypatch):
    """Isolated unified table + isolated legacy flash cache (the
    loader-fallback migration reads it)."""
    path = str(tmp_path / "kernel_tune.json")
    monkeypatch.setenv("PT_KERNEL_TUNE_PATH", path)
    monkeypatch.setattr(search, "_table_cache", None)
    monkeypatch.setattr(autotune, "_CACHE_PATH",
                        str(tmp_path / "flash_tune.json"))
    monkeypatch.setattr(autotune, "_cache", None)
    return path


def _hw_row(key, ratio, family="famx", **extra):
    row = {"family": family, "key": key, "config": {"block_q": 128},
           "ratio": ratio, "t_kernel_ms": 1.0,
           "t_composite_ms": ratio, "backend": "tpu",
           "device": search._device_kind(), "interpret": False}
    row.update(extra)
    return row


def _put(family, key, row):
    search.update_table(
        lambda d: d.setdefault("families", {}).setdefault(
            family, {"entries": {}})["entries"].update({key: row}))


# -- tune table ---------------------------------------------------------------

class TestTable:
    def test_update_table_merges_concurrent_writers(self, table):
        # two read-modify-writes that never see each other's in-memory
        # state: the locked reload keeps both rows (the old
        # save_cache-style full overwrite dropped one)
        _put("a", "k1", _hw_row("k1", 1.5, family="a"))
        search._table_cache = None  # forget — like a second process
        _put("b", "k2", _hw_row("k2", 0.5, family="b"))
        data = search.load_table(refresh=True)
        assert "k1" in data["families"]["a"]["entries"]
        assert "k2" in data["families"]["b"]["entries"]

    def test_write_is_atomic_no_partial_file(self, table):
        _put("a", "k1", _hw_row("k1", 1.5, family="a"))
        # the table on disk is always complete valid JSON
        with open(table) as f:
            data = json.load(f)
        assert data["families"]["a"]["entries"]["k1"]["ratio"] == 1.5
        # no stray tmp files left behind
        stray = [f for f in os.listdir(os.path.dirname(table))
                 if f.startswith(".kernel_tune_")]
        assert stray == []

    def test_legacy_flash_migration_loader_fallback(self, table):
        # rows in the OLD flash_tune.json appear under the flash
        # namespace with ratio/config aliases — without touching disk
        autotune.save_cache({"entries": {
            autotune._key(1024, 1024, 128, True): {
                "sq": 1024, "sk": 1024, "d": 128, "causal": True,
                "block_q": 256, "block_k": 512, "ratio_fwd_bwd": 3.4,
                "backend": "tpu", "device": search._device_kind()}}})
        data = search.load_table(refresh=True)
        row = data["families"]["flash"]["entries"][
            autotune._key(1024, 1024, 128, True)]
        assert row["migrated_from"] == "flash_tune.json"
        assert row["ratio"] == 3.4
        assert row["config"] == {"block_q": 256, "block_k": 512}
        # and the unified row feeds engagement
        assert search.engaged(
            "flash", autotune._key(1024, 1024, 128, True)) is True

    def test_unified_row_wins_over_migrated(self, table):
        key = autotune._key(512, 512, 64, True)
        autotune.save_cache({"entries": {key: {
            "sq": 512, "sk": 512, "d": 64, "causal": True,
            "block_q": 128, "block_k": 128, "ratio_fwd_bwd": 0.7,
            "backend": "tpu", "device": search._device_kind()}}})
        _put("flash", key, _hw_row(key, 1.2, family="flash"))
        assert search.engaged("flash", key) is True  # unified row wins

    def test_other_device_rows_ignored(self, table):
        _put("famx", "k", _hw_row("k", 2.0, device="TPU v99"))
        assert search.lookup("famx", "k") is None
        assert search.engaged("famx", "k") is None

    def test_autotune_save_cache_locked_atomic(self, table):
        # the legacy writer now uses the same discipline: lock sidecar
        # + no partial file
        autotune.save_cache({"entries": {"x": {"sq": 1}}})
        assert os.path.exists(autotune._CACHE_PATH + ".lock")
        with open(autotune._CACHE_PATH) as f:
            assert json.load(f)["entries"]["x"]["sq"] == 1

    def test_autotune_update_cache_merges(self, table):
        autotune.update_cache(
            lambda c: c.setdefault("entries", {}).update({"a": {"v": 1}}))
        autotune._cache = None  # second-process view
        autotune.update_cache(
            lambda c: c.setdefault("entries", {}).update({"b": {"v": 2}}))
        cache = autotune.load_cache()
        assert set(cache["entries"]) >= {"a", "b"}


# -- engagement rules ---------------------------------------------------------

class TestEngagement:
    def test_no_row_returns_none(self, table):
        assert search.engaged("famx", "nope") is None
        assert search.best_config("famx", "nope") is None

    def test_measured_faster_engages(self, table):
        _put("famx", "k", _hw_row("k", 1.3))
        assert search.engaged("famx", "k") is True
        assert search.best_config("famx", "k") == {"block_q": 128}

    def test_measured_slower_disengages(self, table):
        _put("famx", "k", _hw_row("k", 0.8))
        assert search.engaged("famx", "k") is False

    def test_cpu_and_interpret_rows_never_engage(self, table):
        # the smoke CLI persists backend=cpu / interpret=true rows;
        # their wall-clock is meaningless and must not flip anything
        _put("famx", "kc", _hw_row("kc", 5.0, backend="cpu"))
        _put("famx", "ki", _hw_row("ki", 5.0, interpret=True))
        assert search.engaged("famx", "kc") is None
        assert search.engaged("famx", "ki") is None

    def test_verdict_is_exact_key_only(self, table):
        _put("famx", "k1", _hw_row("k1", 2.0))
        assert search.engaged("famx", "k2") is None

    def test_decide_counts_engagement(self, table):
        was = monitor.enabled()
        monitor.enable()
        try:
            base = monitor.snapshot()["counters"]
            _put("famx", "k", _hw_row("k", 1.3))
            assert search.decide("famx", "k") is True
            assert search.decide("famx", "missing") is False
            got = monitor.snapshot()["counters"]
            assert got.get("pallas/engaged", 0) - base.get(
                "pallas/engaged", 0) == 1
            assert got.get("pallas/fallback_composite", 0) - base.get(
                "pallas/fallback_composite", 0) == 1
            assert got.get("pallas/engaged/famx", 0) >= 1
        finally:
            if not was:
                monitor.disable()

    def test_engagement_report_shapes(self, table):
        _put("fam_a", "k", _hw_row("k", 1.5, family="fam_a"))
        _put("fam_b", "k", _hw_row("k", 0.5, family="fam_b"))
        _put("fam_c", "k", _hw_row("k", 9.9, family="fam_c",
                                   backend="cpu"))
        search.register_family(type("FamA", (search.KernelFamily,),
                                    {"name": "fam_a"})())
        search.register_family(type("FamB", (search.KernelFamily,),
                                    {"name": "fam_b"})())
        search.register_family(type("FamC", (search.KernelFamily,),
                                    {"name": "fam_c"})())
        try:
            rep = search.engagement_report()
            assert rep["fam_a"] is True
            assert rep["fam_b"] is False
            # cpu rows carry no verdict — and a family with NO hardware
            # verdict must still report False (not absent), so a
            # deleted row reads as a lost engagement, not a wildcard
            assert rep["fam_c"] is False
        finally:
            for n in ("fam_a", "fam_b", "fam_c"):
                search.FAMILIES.pop(n, None)


# -- candidate spaces ---------------------------------------------------------

class TestCandidates:
    def test_headbatch_blocks_tile_and_fit_vmem(self):
        fam = search.FAMILIES["flash_headbatch"]
        shape = (8, 1024, 1024, 12, 12, 128, True)
        cands = fam.candidates(shape)
        assert cands, "empty candidate space"
        for c in cands:
            assert 1024 % c["block_q"] == 0
            assert 1024 % c["block_k"] == 0
            assert head_flash.vmem_bytes(shape, c) <= fam.vmem_budget

    def test_headbatch_vmem_prune_shrinks_with_heads(self):
        fam = search.FAMILIES["flash_headbatch"]
        few = fam.candidates((8, 1024, 1024, 4, 4, 128, True))
        many = fam.candidates((8, 1024, 1024, 32, 32, 128, True))
        # with every head's state resident, more heads must prune the
        # big-block corner of the space
        assert max(c["block_q"] for c in many) \
            <= max(c["block_q"] for c in few)
        assert len(many) < len(few)

    def test_headbatch_space_never_empty(self):
        fam = search.FAMILIES["flash_headbatch"]
        cands = fam.candidates((1, 64, 64, 64, 64, 128, True))
        assert cands  # fallback minimal config survives any h

    def test_paged_candidates_are_dead_strategies(self):
        fam = search.FAMILIES["paged_attention"]
        cands = fam.candidates((8, 128, 16, 12, 1, 128))
        assert {c["dead"] for c in cands} == {"clamp", "null"}

    def test_registered_families(self):
        assert {"flash", "flash_headbatch", "paged_attention"} \
            <= set(search.FAMILIES)

    def test_family_keys_encode_variants(self):
        base = head_flash.shape_key(8, 1024, 1024, 12, 12, 128, True)
        assert head_flash.shape_key(
            8, 1024, 1024, 12, 12, 128, True, dropout=True) != base
        assert head_flash.shape_key(
            8, 1024, 1024, 12, 12, 128, True, kmask=True) != base
        assert "kv4" in head_flash.shape_key(8, 1024, 1024, 12, 4, 128,
                                             True)
        assert pa.family_key(16, 12, 1, 128) == "B16_kv12_g1_d128"


# -- the search pipeline ------------------------------------------------------

class _StubFamily(search.KernelFamily):
    """Tiny synthetic family: two candidates, one mathematically WRONG
    — the parity pre-filter must reject it before timing ever sees it,
    and the persisted row must carry the good one."""

    name = "stub"
    grad = False
    parity_atol = 1e-6

    def shapes(self):
        return [(8,)]

    def key(self, shape):
        return f"n{shape[0]}"

    def candidates(self, shape):
        return [{"variant": "good"}, {"variant": "broken"}]

    def make_inputs(self, shape):
        return (jnp.arange(float(shape[0])).reshape(1, shape[0]),)

    def build(self, shape, config, interpret):
        if config["variant"] == "broken":
            return lambda x: x * 2.0 + 1.0  # fast but wrong
        return lambda x: x * 2.0

    def build_composite(self, shape):
        return lambda x: x + x


class TestSearchPipeline:
    def test_parity_filter_rejects_wrong_candidate(self, table):
        was = monitor.enabled()
        monitor.enable()
        try:
            base = monitor.snapshot()["counters"]
            entry = search.search_shape(_StubFamily(), (8,), iters=2,
                                        verbose=False)
            got = monitor.snapshot()["counters"]
        finally:
            if not was:
                monitor.disable()
        assert entry["config"] == {"variant": "good"}
        assert entry["rejects"] == 1
        assert entry["candidates"] == 2
        assert entry["candidates_timed"] == 1
        assert "ratio" in entry and "timestamp" in entry
        assert entry["backend"] == "cpu" and entry["interpret"]
        # counters account the run
        assert got.get("search/candidates_timed", 0) - base.get(
            "search/candidates_timed", 0) == 1
        assert got.get("search/rejects", 0) - base.get(
            "search/rejects", 0) == 1
        # persisted under the family namespace, loadable fresh
        search._table_cache = None
        row = search.lookup("stub", "n8")
        assert row is not None and row["config"]["variant"] == "good"
        # ...but a cpu/interpret row never engages
        assert search.engaged("stub", "n8") is None

    def test_all_candidates_wrong_raises(self, table):
        class AllBroken(_StubFamily):
            def candidates(self, shape):
                return [{"variant": "broken"}]

        with pytest.raises(RuntimeError, match="parity"):
            search.search_shape(AllBroken(), (8,), iters=2,
                                verbose=False)

    def test_flash_family_on_persist_mirrors_legacy(self, table):
        fam = search.FAMILIES["flash"]
        entry = {"config": {"block_q": 128, "block_k": 128},
                 "t_kernel_ms": 1.0, "t_composite_ms": 2.0,
                 "ratio": 2.0, "backend": "tpu",
                 "device": search._device_kind(),
                 "timestamp": "2026-08-03T00:00:00Z"}
        fam.on_persist((2, 128, 128, 8, True), entry)
        legacy = autotune.load_cache()["entries"][
            autotune._key(128, 128, 8, True)]
        assert legacy["block_q"] == 128
        assert legacy["ratio_fwd_bwd"] == 2.0
        assert legacy["via"] == "kernel_search"

    def test_flash_family_never_mirrors_cpu_rows(self, table):
        fam = search.FAMILIES["flash"]
        fam.on_persist((2, 128, 128, 8, True),
                       {"config": {"block_q": 128, "block_k": 128},
                        "t_kernel_ms": 1.0, "backend": "cpu",
                        "interpret": True})
        assert autotune.load_cache().get("entries", {}) == {}

    def test_headbatch_search_end_to_end_interpret(self, table):
        fam = search.FAMILIES["flash_headbatch"]
        entry = search.search_shape(fam, fam.smoke_shapes()[0], iters=2,
                                    verbose=False)
        assert entry["candidates_timed"] >= 1
        assert entry["parity_max_err"] <= fam.parity_atol
        assert search.lookup("flash_headbatch", entry["key"]) is not None


# -- tier-1 CLI smoke ---------------------------------------------------------

def test_kernel_search_cli_smoke_runs_full_pipeline(tmp_path):
    """Acceptance criterion: `python tools/kernel_search.py --smoke`
    runs enumerate -> parity filter -> timing on CPU and exits 0, with
    the one-JSON-line contract; its rows land in the given table marked
    cpu/interpret (engagement-inert)."""
    table = str(tmp_path / "t.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "tools/kernel_search.py", "--smoke",
         "--table", table],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("{"))
    rec = json.loads(line)
    assert rec["metric"] == "kernel_search_shapes"
    assert rec["value"] >= 3  # flash + headbatch + paged at least
    assert rec["failures"] == {}
    assert rec["note"] == "cpu smoke mode; not a TPU number"
    with open(table) as f:
        data = json.load(f)
    fams = data["families"]
    assert {"flash", "flash_headbatch", "paged_attention"} <= set(fams)
    for fam in ("flash_headbatch", "paged_attention"):
        for row in fams[fam]["entries"].values():
            assert row["backend"] == "cpu" and row["interpret"]


def test_monitor_audit_membership():
    # the None-slot zero-overhead-off audit in test_memory_numerics
    # parametrizes over this list — membership is the contract
    assert "paddle_tpu.ops.pallas.search" in monitor.INSTRUMENTED_MODULES


def test_monitor_report_renders_kernel_section(tmp_path):
    """`monitor_report` renders the pallas/search counters and a bench
    line's `kernels` engagement map."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "monitor_report_t", os.path.join(ROOT, "tools",
                                         "monitor_report.py"))
    mr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mr)
    jsonl = tmp_path / "run.jsonl"
    jsonl.write_text(json.dumps({"event": "run_begin", "meta": {}}) + "\n"
                     + json.dumps({
                         "event": "run_end", "wall_s": 1.0,
                         "totals": {"counters": {
                             "pallas/engaged": 3,
                             "pallas/fallback_composite": 1,
                             "pallas/engaged/flash": 3,
                             "search/candidates_timed": 7,
                             "search/rejects": 2},
                             "gauges": {
                                 "search/best_ratio/flash": 3.4},
                             "histograms": {}}}) + "\n")
    bench = tmp_path / "bench.log"
    bench.write_text(json.dumps({
        "metric": "serving_tokens_per_sec", "value": 10.0,
        "unit": "tokens/s",
        "kernels": {"paged_attention": True, "flash": False}}) + "\n")
    text = mr.render(str(jsonl), bench_path=str(bench))
    assert "pallas kernels (engagement + search)" in text
    assert "engaged 3   composite fallbacks 1" in text
    assert "candidates timed 7" in text
    assert "best ratio flash: 3.4" in text
    assert "paged_attention=engaged" in text
