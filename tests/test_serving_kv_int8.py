"""int8 KV-cache quantization (ISSUE 18 — the dtype-polymorphic block
pool, `ServingConfig(kv_int8=)` / `PT_SERVE_KV_INT8`).

Five layers:

- **Quant helpers** — `quantize_kv`/`dequantize_kv` round-trip within
  the per-(position, kv_head) amax step, scales are content-derived
  (same tokens → bit-equal scales, the prefix-sharing precondition).
- **Pool invariants in int8 mode** — the engine's pools store int8 K/V
  plus paired fp32 scale tensors indexed by the SAME block ids; the
  host ledger's accounting, double-free / cross-owner raises, and
  `free + used + cold == capacity` carry over untouched.
- **Tier-1 CPU end-to-end** — THE acceptance proofs: the int8 engine is
  token-identical to the quantize-aware `generate(kv_int8=True)`
  reference AND to the share-nothing int8 engine — under prefix
  sharing, speculative rollback, preemption-recompute churn, and a
  3-replica router — with exec-cache misses == 3, zero second-wave
  compiles, and `kv_int8=False` restoring today's engine exactly
  (scales are None, so the bf16 programs carry no dead buffers).
- **Capacity** — at equal `PT_SERVE_BLOCKS` byte budget the int8 pool
  reports >= 1.9x `allocatable_tokens` at head_dim=128 (2d/(d+4), the
  bench line's arithmetic) and the engine's resident pool bytes drop
  accordingly.
- **Kernel family** — `paged_attention_int8` passes interpret-parity
  against its dense dequant-then-attend composite, lowers for TPU, and
  ships disengaged until a hardware tune row exists (engagement flips
  on a measured-faster row, per convention).
"""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate
from paddle_tpu.serving import (
    RouterConfig, RouterEngine, ServingConfig, ServingEngine,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_by_path(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- quant helpers ------------------------------------------------------------

class TestQuantizeKv:
    def test_round_trip_within_one_step(self):
        import jax.numpy as jnp

        from paddle_tpu.quantization import dequantize_kv, quantize_kv

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(5, 7, 2, 16).astype(np.float32))
        q, s = quantize_kv(x)
        assert q.dtype == jnp.int8
        assert s.shape == x.shape[:-1]  # one scale per (pos, kv_head)
        err = np.abs(np.asarray(dequantize_kv(q, s, x.dtype)) - x)
        # symmetric round-to-nearest: error <= half the amax/127 step
        step = np.asarray(s)[..., None]
        assert (err <= 0.5 * step + 1e-7).all()

    def test_scales_are_content_derived(self):
        # identical content quantizes to bit-equal (q, s) — the
        # precondition for prefix sharing to share scale slots
        import jax.numpy as jnp

        from paddle_tpu.quantization import quantize_kv

        x = jnp.asarray(np.random.RandomState(1)
                        .randn(3, 4, 2, 8).astype(np.float32))
        q1, s1 = quantize_kv(x)
        q2, s2 = quantize_kv(jnp.array(x))
        np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))

    def test_zero_rows_survive(self):
        import jax.numpy as jnp

        from paddle_tpu.quantization import dequantize_kv, quantize_kv

        q, s = quantize_kv(jnp.zeros((2, 3, 1, 4)))
        out = np.asarray(dequantize_kv(q, s, jnp.float32))
        assert np.isfinite(out).all() and (out == 0).all()


# -- end-to-end (compiled; tier-1 CPU) ----------------------------------------

@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    m.eval()
    return m


def _reference_q(model, prompt, new):
    """The quantize-aware reference: generate() round-tripping K/V
    through the SAME quantize_kv/dequantize_kv the engine fuses into
    its compiled programs."""
    return generate(model, pt.to_tensor(np.asarray(prompt)[None, :]),
                    max_new_tokens=new, kv_int8=True).numpy()[0]


def _workload(model, seed, n=8, plen=(3, 13), new=(8, 25)):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        p = rng.randint(0, model.config.vocab_size,
                        (int(rng.randint(*plen)),)).astype(np.int32)
        out.append((p, int(rng.randint(*new))))
    return out


GEOM = dict(max_lanes=3, block_size=4, prefill_chunk=8, max_seq_len=48)


class TestConfigKnob:
    def test_env_default_and_explicit(self, monkeypatch):
        monkeypatch.delenv("PT_SERVE_KV_INT8", raising=False)
        assert ServingConfig().kv_int8 is False
        monkeypatch.setenv("PT_SERVE_KV_INT8", "1")
        assert ServingConfig().kv_int8 is True
        assert ServingConfig(kv_int8=False).kv_int8 is False
        monkeypatch.setenv("PT_SERVE_KV_INT8", "0")
        assert ServingConfig().kv_int8 is False
        assert ServingConfig(kv_int8=True).kv_int8 is True


class TestInt8PoolInvariants:
    def test_pools_and_scales_paired(self, model):
        eng = ServingEngine(model, ServingConfig(kv_int8=True, **GEOM))
        import jax.numpy as jnp

        assert eng._kpool.dtype == jnp.int8
        assert eng._vpool.dtype == jnp.int8
        # paired fp32 amax scales, one per (position, kv_head), the
        # null block included (its zero scale dequantizes to zero)
        assert eng._kscale.dtype == jnp.float32
        assert eng._kscale.shape == eng._kpool.shape[:-1]
        assert eng._vscale.shape == eng._vpool.shape[:-1]
        assert eng.kv_pool_bytes == (eng._kpool.nbytes + eng._vpool.nbytes
                                     + eng._kscale.nbytes
                                     + eng._vscale.nbytes)
        assert eng.stats()["kv_int8"] is True
        assert eng.stats()["kv_pool_bytes"] == eng.kv_pool_bytes

    def test_ledger_raises_unchanged_in_int8_mode(self, model):
        # the host ledger is the same object either way: accounting,
        # double-free and cross-owner raises hold on an engine that has
        # actually served int8 traffic
        eng = ServingEngine(model, ServingConfig(kv_int8=True, **GEOM))
        for p, n in _workload(model, seed=3, n=4):
            eng.submit(p, max_new_tokens=n)
        eng.run()
        pool = eng.scheduler.pool
        pool.check_invariant()
        assert pool.free_count + pool.used_count + pool.cold_count \
            == pool.capacity
        blocks = pool.alloc(2, "probe")
        pool.free(blocks, "probe")
        with pytest.raises(ValueError, match="double-free|not allocated"):
            pool.free(blocks, "probe")
        a = pool.alloc(1, "a")
        with pytest.raises(ValueError, match="owned by"):
            pool.free(a, "b")
        pool.free(a, "a")
        pool.check_invariant()


def test_int8_token_identity_three_compiles_no_retrace(model, tmp_path):
    """THE acceptance proof: the int8 engine's outputs are
    byte-identical to the quantize-aware generate(kv_int8=True)
    reference AND to the share-nothing int8 engine; exactly 3
    exec-cache misses (dtype is a static key — one prefill, one decode,
    one verify); a second wave and the share-nothing engine add ZERO
    fresh compiles."""
    from paddle_tpu.jit import exec_cache as ec

    work = _workload(model, seed=0)
    ec.enable(str(tmp_path))
    ec.clear()
    try:
        eng = ServingEngine(model, ServingConfig(kv_int8=True, **GEOM))
        assert eng.spec_active
        handles = [eng.submit(p, max_new_tokens=n) for p, n in work]
        outs = eng.run()
        assert ec.stats()["misses"] == 3, ec.stats()
        assert eng.counters["verify_steps"] > 0
        assert eng.counters["kv_quant_writes"] > 0
        assert eng.counters["kv_quant_tokens"] > 0
        for h, (p, n) in zip(handles, work):
            np.testing.assert_array_equal(
                outs[h.request_id], _reference_q(model, p, n),
                err_msg=f"request {h.request_id} diverged from the "
                        f"quantize-aware generate(kv_int8=True)")
        # second wave through the SAME engine: zero fresh compiles —
        # admission/eviction/draft churn never retraces in int8 mode
        h2 = [eng.submit(p, max_new_tokens=n) for p, n in work[:3]]
        outs2 = eng.run()
        assert ec.stats()["misses"] == 3, "int8 retrace!"
        for h, (p, n) in zip(h2, work[:3]):
            np.testing.assert_array_equal(
                outs2[h.request_id], _reference_q(model, p, n))
        # share-nothing int8 engine: same three programs (prefix cache
        # is host-side bookkeeping), identical tokens
        eng_sn = ServingEngine(model, ServingConfig(
            kv_int8=True, prefix_cache=False, **GEOM))
        h3 = [eng_sn.submit(p, max_new_tokens=n) for p, n in work]
        outs3 = eng_sn.run()
        assert ec.stats()["misses"] == 3, ec.stats()
        for h, hsn in zip(handles, h3):
            np.testing.assert_array_equal(
                outs3[hsn.request_id], outs[h.request_id])
    finally:
        ec.disable()
        ec.clear()


def test_int8_off_restores_baseline_engine(model):
    """kv_int8=False must be today's engine exactly: no scale tensors
    (None contributes nothing to the compiled programs), pool at the
    model dtype, quant counters parked at zero, tokens identical to
    plain generate()."""
    eng = ServingEngine(model, ServingConfig(**GEOM))
    assert eng._kscale is None and eng._vscale is None
    assert eng._kpool.dtype == np.dtype(model.config.dtype)
    assert eng.stats()["kv_int8"] is False
    work = _workload(model, seed=2, n=4)
    handles = [eng.submit(p, max_new_tokens=n) for p, n in work]
    outs = eng.run()
    assert eng.counters["kv_quant_writes"] == 0
    assert eng.counters["kv_quant_tokens"] == 0
    for h, (p, n) in zip(handles, work):
        np.testing.assert_array_equal(
            outs[h.request_id],
            generate(model, pt.to_tensor(np.asarray(p)[None, :]),
                     max_new_tokens=n).numpy()[0])


def test_int8_prefix_spec_preemption_churn_identity(model):
    """int8 × prefix-cache sharing × speculative rollback × a pool too
    small for the load (preemption-recompute): shared blocks share
    their content-derived scales, rejected drafts rewind pool_len past
    quantized tail slots, re-admissions re-quantize — and every output
    still matches the quantize-aware reference."""
    rng = np.random.RandomState(9)
    prefix = rng.randint(0, model.config.vocab_size,
                         (4,)).astype(np.int32)
    work = []
    for _ in range(8):
        motif = rng.randint(0, model.config.vocab_size, (3,))
        sfx = np.tile(motif, 3)[:int(rng.randint(2, 8))]
        work.append((np.concatenate([prefix, sfx]).astype(np.int32),
                     int(rng.randint(8, 17))))
    eng = ServingEngine(model, ServingConfig(
        kv_int8=True, max_lanes=3, block_size=2, num_blocks=14,
        prefill_chunk=4, max_seq_len=32))
    handles = [eng.submit(p, max_new_tokens=n) for p, n in work]
    outs = eng.run()
    st = eng.stats()
    assert st["preemptions"] > 0, "pressure config never preempted"
    assert st["prefix_hit_tokens"] > 0, "sharing never engaged"
    assert st["spec_proposed_tokens"] > 0, "speculation never proposed"
    # rollback exercised: not every proposed draft token was accepted
    assert st["spec_accepted_tokens"] < st["spec_proposed_tokens"]
    for h, (p, n) in zip(handles, work):
        np.testing.assert_array_equal(
            outs[h.request_id], _reference_q(model, p, n),
            err_msg=f"request {h.request_id} diverged under churn")
    eng.scheduler.pool.check_invariant()


def test_int8_router_token_identity(model):
    """A 3-replica router over int8 engines: same submit/step surface,
    outputs identical to the quantize-aware reference."""
    router = RouterEngine(
        model, ServingConfig(kv_int8=True, **GEOM),
        RouterConfig(replicas=3, mode="inproc"))
    work = _workload(model, seed=4, n=9)
    handles = [router.submit(p, max_new_tokens=n) for p, n in work]
    outs = router.run()
    assert router.stats()["kv_int8"] is True
    for h, (p, n) in zip(handles, work):
        np.testing.assert_array_equal(
            outs[h.request_id], _reference_q(model, p, n),
            err_msg=f"request {h.request_id} diverged through the router")


# -- capacity -----------------------------------------------------------------

class TestCapacity:
    def test_allocatable_tokens_ratio_at_d128(self):
        """ISSUE 18 acceptance: at equal PT_SERVE_BLOCKS byte budget,
        int8 reports >= 1.9x allocatable_tokens (2d/(d+4) = 1.939 at
        head_dim=128) — straight from the bench line's arithmetic."""
        import types

        sb = _load_by_path("serving_bench_cap_t",
                           "benchmarks/serving_bench.py")
        cfg = types.SimpleNamespace(
            num_hidden_layers=12, num_attention_heads=4,
            num_key_value_heads=4, hidden_size=512, dtype="bfloat16")
        per_bf16, alloc_bf16 = sb.kv_byte_model(cfg, 64, 16, 2, 0)
        per_int8, alloc_int8 = sb.kv_byte_model(cfg, 64, 16, 1, 4)
        assert alloc_bf16 == 64 * 16  # bf16 lands exactly on the pool
        assert alloc_int8 / alloc_bf16 >= 1.9
        assert per_int8 / per_bf16 == pytest.approx(
            (128 + 4) / (2 * 128))

    def test_engine_pool_bytes_shrink(self, model):
        # the resident pools themselves: int8 + scales is strictly
        # smaller than the unquantized pool at the same num_blocks
        bf = ServingEngine(model, ServingConfig(**GEOM))
        q = ServingEngine(model, ServingConfig(kv_int8=True, **GEOM))
        assert q.stats()["num_blocks"] == bf.stats()["num_blocks"]
        d = model.config.hidden_size // model.config.num_attention_heads
        el = np.dtype(model.config.dtype).itemsize
        expect = (d + 4) / (d * el)  # int8 + fp32 scale vs base dtype
        assert q.kv_pool_bytes / bf.kv_pool_bytes \
            == pytest.approx(expect)


# -- monitor ------------------------------------------------------------------

def test_kv_quant_monitor_counters(model):
    """serving/kv_quant_* counters mirror the engine's always-on ints
    and the pool-bytes gauge lands — all under the None-slot contract
    (a bf16 engine moves none of them)."""
    was = monitor.enabled()
    monitor.enable()
    try:
        base = monitor.snapshot()["counters"]
        eng = ServingEngine(model, ServingConfig(kv_int8=True, **GEOM))
        for p, n in _workload(model, seed=6, n=4):
            eng.submit(p, max_new_tokens=n)
        eng.run()
        got = monitor.snapshot()

        def delta(k):
            return got["counters"].get(k, 0) - base.get(k, 0)

        c = eng.counters
        assert delta("serving/kv_quant_writes") == c["kv_quant_writes"] > 0
        assert delta("serving/kv_quant_tokens") == c["kv_quant_tokens"] > 0
        assert got["gauges"]["serving/kv_pool_bytes"] == eng.kv_pool_bytes
        # bf16 engine: counters parked
        before = monitor.snapshot()["counters"]
        eng2 = ServingEngine(model, ServingConfig(**GEOM))
        eng2.submit(np.arange(5, dtype=np.int32), max_new_tokens=4)
        eng2.run()
        after = monitor.snapshot()["counters"]
        assert after.get("serving/kv_quant_writes", 0) \
            == before.get("serving/kv_quant_writes", 0)
    finally:
        if not was:
            monitor.disable()


def test_monitor_report_renders_kv_pool_line(tmp_path):
    """monitor_report's serving section renders the int8 pool: dtype,
    resident bytes, quantize-on-write totals."""
    mr = _load_by_path("monitor_report_kv_t", "tools/monitor_report.py")
    bench = tmp_path / "serving.log"
    bench.write_text(json.dumps({
        "metric": "serving_tokens_per_sec", "value": 100.0,
        "unit": "tokens/s", "telemetry": {"serving": {
            "admits": 4, "prefill_steps": 6, "decode_steps": 10,
            "kv_quant_writes": 24, "kv_quant_tokens": 87}}}) + "\n")
    jsonl = tmp_path / "run.jsonl"
    jsonl.write_text(json.dumps({"event": "run_begin", "meta": {}}) + "\n")
    text = mr.render(str(jsonl), bench_path=str(bench))
    assert "kv pool: int8" in text
    assert "24 quantizing write(s)" in text
    assert "87 token(s) quantized" in text


# -- kernel family ------------------------------------------------------------

class TestPagedAttentionInt8Family:
    def test_interpret_parity_and_ships_disengaged(self, tmp_path,
                                                   monkeypatch):
        from paddle_tpu.ops import pallas  # noqa: F401 — registers
        from paddle_tpu.ops.pallas import search

        monkeypatch.setenv("PT_KERNEL_TUNE_PATH",
                           str(tmp_path / "t.json"))
        monkeypatch.setattr(search, "_table_cache", None)
        fam = search.FAMILIES["paged_attention_int8"]
        shape = fam.smoke_shapes()[0]
        inp = fam.make_parity_inputs(shape)
        want = np.asarray(fam.build_composite(shape)(*inp),
                          dtype=np.float32)
        for cand in fam.candidates(shape):
            got = np.asarray(fam.build(shape, cand, interpret=True)(*inp),
                             dtype=np.float32)
            np.testing.assert_allclose(
                got, want, atol=2e-5, rtol=2e-5,
                err_msg=f"interpret parity failed for {cand}")
        # empty table: disengaged by convention (measurement-first)
        assert search.decide("paged_attention_int8",
                             fam.key(shape)) is False
        assert search.engagement_report()["paged_attention_int8"] is False

    def test_lowering_self_check_registered(self):
        from paddle_tpu.ops import pallas, registry

        names = [n for n, _ in registry.platform_kernels("tpu")]
        assert "paged_attention_int8" in names
        # the registry-driven audit covers it (a kernel without a
        # check_lowering attribute is a hard error in check_tpu_lowering)
        pallas.check_tpu_lowering()

    def test_engine_engages_only_on_int8_family_row(self, model,
                                                    tmp_path,
                                                    monkeypatch):
        """An int8 engine keys engagement on paged_attention_int8 — a
        measured bf16 paged_attention row must NOT flip it (different
        read path, different bytes), and vice versa a measured int8 row
        does."""
        from paddle_tpu.ops.pallas import paged_attention as pa
        from paddle_tpu.ops.pallas import search

        monkeypatch.delenv("PT_SERVE_PAGED", raising=False)
        monkeypatch.setenv("PT_KERNEL_TUNE_PATH",
                           str(tmp_path / "t.json"))
        monkeypatch.setattr(search, "_table_cache", None)
        cfg = model.config
        nh = cfg.num_attention_heads
        nkv = cfg.num_key_value_heads or nh
        key = pa.family_key(4, nkv, nh // nkv, cfg.hidden_size // nh)
        geom = dict(kv_int8=True, **GEOM)
        geom["block_size"] = 4
        eng = ServingEngine(model, ServingConfig(**geom))
        assert eng._paged_family == "paged_attention_int8"
        assert eng.paged_active is False
        # a bf16-family row alone: int8 engine stays dense
        search.update_table(
            lambda d: d.setdefault("families", {}).setdefault(
                "paged_attention", {"entries": {}})["entries"].update(
                {key: {"ratio": 1.4, "backend": "tpu",
                       "device": search._device_kind(),
                       "config": {"dead": "null"}}}))
        eng2 = ServingEngine(model, ServingConfig(**geom))
        assert eng2.paged_active is False
        # the int8 family's own measured-faster row flips it
        search.update_table(
            lambda d: d.setdefault("families", {}).setdefault(
                "paged_attention_int8", {"entries": {}})[
                "entries"].update(
                {key: {"ratio": 1.3, "backend": "tpu",
                       "device": search._device_kind(),
                       "config": {"dead": "null"}}}))
        eng3 = ServingEngine(model, ServingConfig(**geom))
        assert eng3.paged_active is True
        assert eng3.stats()["paged_family"] == "paged_attention_int8"
        # and the bf16 engine keys on its own family, not the int8 row
        eng4 = ServingEngine(model, ServingConfig(**GEOM))
        assert eng4._paged_family == "paged_attention"
        assert eng4.paged_active is True  # bf16 row from above


# -- bench contract -----------------------------------------------------------

def test_serving_bench_int8_contract_line():
    """ISSUE 18 acceptance via the bench: the int8 smoke line reports
    kv_int8, the pool-derived kv_bytes_per_token, an allocatable_tokens
    capacity >= 1.9x the embedded bf16 replay's, and the kv_bf16 A/B
    sub-object."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PT_SERVE_BENCH_REQUESTS"] = "6"
    env["PT_SERVE_BENCH_RATE"] = "200"
    env["PT_SERVE_KV_INT8"] = "1"
    env["PT_SERVE_BENCH_KV_AB"] = "1"
    proc = subprocess.run(
        [sys.executable, "benchmarks/serving_bench.py", "--smoke"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("{"))
    rec = json.loads(line)
    assert rec["metric"] == "serving_tokens_per_sec"
    assert rec["kv_int8"] is True
    ab = rec["kv_bf16"]
    assert rec["kv_bytes_per_token"] < ab["kv_bytes_per_token"]
    assert rec["allocatable_tokens"] >= 1.9 * ab["allocatable_tokens"]
    assert rec["kv_pool_bytes"] < ab["kv_pool_bytes"]
    assert ab["tokens_per_sec"] > 0 and ab["ttft_ms_p50"] is not None
    tel = rec["telemetry"]["serving"]
    assert tel["kv_quant_writes"] > 0 and tel["kv_quant_tokens"] > 0
    assert "paged_attention_int8" in rec["kernels"]
