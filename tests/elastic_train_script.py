"""Elastic kill/resume fixture: a tiny training run that checkpoints every
step, crashes mid-training on its first life, and resumes exactly from the
last checkpoint when the launcher relaunches it.

Used by tests/test_elastic.py::test_kill_relaunch_resume — the reference
contract is `ElasticManager` watch -> kill -> relaunch with rewritten env
(`fleet/elastic/manager.py:126`) + checkpoint resume; here the launcher's
babysit loop provides relaunch (PADDLE_RESTART_COUNT) and
`paddle.distributed.checkpoint` provides exact resume.
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
from paddle_tpu.distributed import checkpoint as dckpt  # noqa: E402

WORKDIR = sys.argv[1]
CRASH_AT = int(os.environ.get("ELASTIC_CRASH_AT", "-1"))
TOTAL_STEPS = 6
restart_count = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))

paddle.seed(0)
model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
opt = paddle.optimizer.AdamW(learning_rate=5e-2,
                             parameters=model.parameters())

rng = np.random.default_rng(0)
xs = rng.standard_normal((TOTAL_STEPS, 16, 8)).astype("float32")
w_true = rng.standard_normal((8, 1)).astype("float32")

ckpt_dir = os.path.join(WORKDIR, "ckpt")
meta_path = os.path.join(WORKDIR, "meta.json")
start_step = 0
if os.path.exists(meta_path):
    with open(meta_path) as f:
        meta = json.load(f)
    start_step = meta["step"]
    flat = dckpt.load_checkpoint(ckpt_dir)
    model.set_state_dict({k[len("model."):]: v for k, v in flat.items()
                          if k.startswith("model.")})
    opt_state = {k[len("opt."):]: v for k, v in flat.items()
                 if k.startswith("opt.")}
    opt_state.update(meta["opt_scalars"])  # global_step, per-param counts
    opt.set_state_dict(opt_state)

losses = []
for step in range(start_step, TOTAL_STEPS):
    x = paddle.to_tensor(xs[step])
    y = paddle.to_tensor(xs[step] @ w_true)
    loss = F.mse_loss(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    losses.append(float(loss.numpy()))
    # record incrementally so a life that crashes still leaves its trace
    with open(os.path.join(WORKDIR, f"losses_r{restart_count}.json"),
              "w") as f:
        json.dump({"start": start_step, "losses": losses}, f)
    flat = {}
    scalars = {}
    for k, v in model.state_dict().items():
        flat[f"model.{k}"] = v
    for k, v in opt.state_dict().items():
        if isinstance(v, (int, float)):
            scalars[k] = v
        else:
            flat[f"opt.{k}"] = v
    dckpt.save_state_dict(flat, ckpt_dir)
    with open(meta_path, "w") as f:
        json.dump({"step": step + 1, "opt_scalars": scalars}, f)
    if restart_count == 0 and step + 1 == CRASH_AT:
        os._exit(17)  # simulated hard failure mid-training
