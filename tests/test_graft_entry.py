"""Driver-contract tests: __graft_entry__.entry / dryrun_multichip."""
import sys

import jax
import pytest


@pytest.fixture(autouse=True)
def _clean_env():
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _graft():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    return g


def test_entry_jits():
    g = _graft()
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (2, 256, 8192)


def test_dryrun_multichip_8():
    g = _graft()
    g.dryrun_multichip(8)
