"""Driver-contract tests: __graft_entry__.entry / dryrun_multichip."""
import sys

import jax
import pytest


@pytest.fixture(autouse=True)
def _clean_env():
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _graft():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    return g


def test_entry_jits():
    g = _graft()
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (2, 256, 8192)


@pytest.mark.slow
def test_dryrun_multichip_8():
    g = _graft()
    g.dryrun_multichip(8)


def test_bench_smoke_emits_one_json_line():
    """Driver contract: bench.py prints exactly one parseable JSON line
    with the required keys, even in CPU smoke mode."""
    import json
    import os
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "/root/repo/bench.py"], env=env, cwd="/root/repo",
        capture_output=True, text=True, timeout=900)
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, proc.stdout + proc.stderr
    rec = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    assert rec["value"] > 0
