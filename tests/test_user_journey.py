"""End-to-end reference-user journey: the workflow a PaddlePaddle user
follows, executed start to finish through this framework's public API —
dataset + transforms -> DataLoader -> model-zoo model -> AMP training with
LR schedule + regularizer + grad clip -> metrics -> checkpoint ->
resume -> @to_static export -> inference Predictor."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles


class _TinyImages(paddle.io.Dataset):
    """Synthetic HWC uint8 images through the real transform stack."""

    def __init__(self, n=32, transform=None):
        self.n = n
        self.transform = transform

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        img = (rng.rand(16, 16, 3) * 255).astype(np.uint8)
        label = i % 4
        if self.transform:
            img = self.transform(img)
        return img.astype(np.float32), np.int64(label)


def test_full_training_journey(tmp_path):
    T = paddle.vision.transforms
    transform = T.Compose([
        T.RandomHorizontalFlip(),
        T.ColorJitter(0.1, 0.1, 0.1, 0.05),
        T.ToTensor(),  # HWC uint8 -> CHW float [0,1]
    ])
    ds = _TinyImages(transform=transform)
    loader = paddle.io.DataLoader(ds, batch_size=8, shuffle=True,
                                  num_workers=2, drop_last=True)

    net = paddle.vision.models.resnet18(num_classes=4)
    sched = paddle.optimizer.lr.CosineAnnealingDecay(
        learning_rate=1e-3, T_max=8)
    opt = paddle.optimizer.AdamW(
        learning_rate=sched, parameters=net.parameters(),
        weight_decay=paddle.regularizer.L2Decay(1e-4),
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    net, opt = paddle.amp.decorate(net, opt, level="O2", dtype="bfloat16")
    loss_fn = paddle.nn.CrossEntropyLoss()
    metric = paddle.metric.Accuracy()

    losses = []
    for epoch in range(2):
        for imgs, labels in loader:
            with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
                logits = net(imgs.astype("bfloat16"))
                loss = loss_fn(logits.astype("float32"),
                               labels.unsqueeze(-1))
            loss.backward()
            opt.step()
            opt.clear_grad()
            metric.update(
                metric.compute(logits.astype("float32"),
                               labels.unsqueeze(-1)))
            losses.append(float(loss.numpy()))
        sched.step()
    assert np.isfinite(losses).all()
    assert 0.0 <= metric.accumulate() <= 1.0

    # checkpoint -> fresh model -> resume
    ckpt = str(tmp_path / "model.pdparams")
    paddle.save(net.state_dict(), ckpt)
    net2 = paddle.vision.models.resnet18(num_classes=4)
    net2 = paddle.amp.decorate(net2, level="O2", dtype="bfloat16")
    net2.set_state_dict(paddle.load(ckpt))
    for (k1, v1), (k2, v2) in zip(sorted(net.state_dict().items()),
                                  sorted(net2.state_dict().items())):
        assert k1 == k2
        np.testing.assert_array_equal(np.asarray(v1._data),
                                      np.asarray(v2._data))

    # @to_static export -> jit.save -> inference Predictor
    net.eval()
    spec = [paddle.static.InputSpec([None, 3, 16, 16], "bfloat16", "x")]
    static_net = paddle.jit.to_static(net, input_spec=spec)
    prefix = str(tmp_path / "inference" / "model")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    paddle.jit.save(static_net, prefix)

    config = paddle.inference.Config(prefix + ".pdmodel",
                                     prefix + ".pdiparams")
    predictor = paddle.inference.create_predictor(config)
    x = np.random.rand(2, 3, 16, 16).astype(np.float32)
    in_names = predictor.get_input_names()
    h = predictor.get_input_handle(in_names[0])
    h.copy_from_cpu(x.astype(np.float32))
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
    assert out.shape == (2, 4)
    # predictor output matches eager eval
    eager = static_net(paddle.to_tensor(x).astype("bfloat16"))
    np.testing.assert_allclose(out.astype(np.float32),
                               np.asarray(eager.numpy(), np.float32),
                               atol=0.1)


def test_hapi_journey(tmp_path):
    ds = _TinyImages(n=16)
    model = paddle.Model(paddle.vision.models.LeNet(num_classes=4))
    model.prepare(
        optimizer=paddle.optimizer.Adam(1e-3,
                                        parameters=model.network.parameters()),
        loss=paddle.nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())

    class _Gray(paddle.io.Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            return (rng.rand(1, 28, 28).astype(np.float32),
                    np.int64(i % 4))

    gds = _Gray()
    model.fit(gds, epochs=1, batch_size=8, verbose=0,
              callbacks=[paddle.callbacks.EarlyStopping(
                  monitor="loss", patience=3)])
    ev = model.evaluate(gds, batch_size=8, verbose=0)
    assert "loss" in ev
    preds = model.predict(gds, batch_size=8, verbose=0)
    assert np.asarray(preds[0][0]).shape[-1] == 4
    model.save(str(tmp_path / "hapi_ckpt"))
