"""RoPE invariants for the shared helper (models/llama._rope).

The property that makes rotary embeddings work — and that any pairing
convention (half-split or interleaved) must satisfy — is that the
rotated dot product depends on positions only through their DIFFERENCE:
    <R(p) q, R(p') k> == <R(p+c) q, R(p'+c) k>  for any shift c.
These tests pin that identity for the half-split convention this build
uses (see docs/MIGRATION.md pitfall 5), plus norm preservation and the
decode path's explicit-position consistency."""
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.llama import _rope

B, S, H, D = 2, 16, 3, 32


def _qk(seed):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    return q, k


def _scores(qr, kr):
    # [b, h, s, s] attention scores from rotated q/k
    return jnp.einsum("bihd,bjhd->bhij", qr, kr)


def test_relative_position_identity():
    q, k = _qk(0)
    base = jnp.arange(S, dtype=jnp.float32)
    qr0, kr0 = _rope(q, k, 10000.0, jnp.float32, pos=base)
    for shift in (1.0, 7.0, 1000.0):
        qr, kr = _rope(q, k, 10000.0, jnp.float32, pos=base + shift)
        np.testing.assert_allclose(np.asarray(_scores(qr, kr)),
                                   np.asarray(_scores(qr0, kr0)),
                                   rtol=2e-4, atol=2e-4)


def test_norm_preserved():
    # rotation: per-position norms are unchanged
    q, k = _qk(1)
    qr, kr = _rope(q, k, 10000.0, jnp.float32)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qr), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-5)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(kr), axis=-1),
        np.linalg.norm(np.asarray(k), axis=-1), rtol=1e-5)


def test_position_zero_is_identity():
    q, k = _qk(2)
    qr, kr = _rope(q, k, 10000.0, jnp.float32,
                   pos=jnp.zeros((S,), jnp.float32))
    np.testing.assert_allclose(np.asarray(qr), np.asarray(q), atol=1e-6)
    np.testing.assert_allclose(np.asarray(kr), np.asarray(k), atol=1e-6)


def test_decode_position_slice_matches_full():
    # rotating position i alone (the cached-decode path) must equal row i
    # of the full-sequence rotation — train and decode cannot drift
    q, k = _qk(3)
    qr_full, kr_full = _rope(q, k, 10000.0, jnp.float32)
    i = 5
    qr_i, kr_i = _rope(q[:, i:i + 1], k[:, i:i + 1], 10000.0, jnp.float32,
                       pos=jnp.asarray([float(i)], jnp.float32))
    np.testing.assert_allclose(np.asarray(qr_i),
                               np.asarray(qr_full[:, i:i + 1]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(kr_i),
                               np.asarray(kr_full[:, i:i + 1]), atol=1e-6)


def test_half_split_pairing_layout():
    # the documented convention: lane i pairs with lane i + d/2 and the
    # pair rotates by freq_i — so zeroing the second half of a one-hot
    # vector must put the sine component exactly in lane i + d/2
    x = np.zeros((1, 1, 1, D), np.float32)
    x[..., 3] = 1.0  # one-hot in the first half
    pos = jnp.asarray([2.0], jnp.float32)
    xr, _ = _rope(jnp.asarray(x), jnp.asarray(x), 10000.0, jnp.float32,
                  pos=pos)
    xr = np.asarray(xr)[0, 0, 0]
    inv = 1.0 / (10000.0 ** (np.arange(0, D, 2, dtype=np.float32) / D))
    ang = 2.0 * inv[3]
    assert abs(xr[3] - np.cos(ang)) < 1e-6
    assert abs(xr[3 + D // 2] - np.sin(ang)) < 1e-6
    assert np.abs(np.delete(xr, [3, 3 + D // 2])).max() < 1e-6


def test_incubate_fused_rope_flag_semantics():
    # Paddle flag semantics (reference fused_rope_utils.h rotates adjacent
    # pairs 2i/2i+1 — that is use_neox_rotary_style=True, interleaved):
    # False = rotate_half (half-split) is what this build serves; True
    # (interleaved) raises with a conversion recipe. Guards against
    # re-inverting the mapping (round-4 advisor finding).
    import pytest

    from paddle_tpu.incubate.nn import functional as incubate_F
    from paddle_tpu.models.llama import apply_rotary_pos_emb

    q, k = _qk(4)
    q2, k2, v2 = incubate_F.fused_rotary_position_embedding(
        q, k, None, use_neox_rotary_style=False)
    qe, ke = apply_rotary_pos_emb(q, k)
    np.testing.assert_allclose(np.asarray(q2), np.asarray(qe), atol=1e-6)
    np.testing.assert_allclose(np.asarray(k2), np.asarray(ke), atol=1e-6)
    assert v2 is None

    from paddle_tpu.framework.errors import UnimplementedError
    with pytest.raises(UnimplementedError, match="interleaved"):
        incubate_F.fused_rotary_position_embedding(
            q, k, None, use_neox_rotary_style=True)


def test_incubate_fused_rope_v_and_position_ids():
    import pytest

    from paddle_tpu.incubate.nn import functional as incubate_F
    from paddle_tpu.framework.errors import UnimplementedError
    from paddle_tpu.models.llama import apply_rotary_pos_emb

    q, k = _qk(5)
    v, _ = _qk(6)
    # v rotates identically to q/k (reference fused_rope_utils.h rotates
    # every provided input)
    q2, k2, v2 = incubate_F.fused_rotary_position_embedding(
        q, k, v, use_neox_rotary_style=False)
    ve, _ = apply_rotary_pos_emb(v, v)
    np.testing.assert_allclose(np.asarray(v2), np.asarray(ve), atol=1e-6)

    # position_ids shifts positions (decode offset): row 0 of a
    # position_ids=[i] call equals row i of the full rotation
    i = 3
    qi, ki, _ = incubate_F.fused_rotary_position_embedding(
        q[:, i:i + 1], k[:, i:i + 1], None,
        position_ids=jnp.asarray([float(i)]), use_neox_rotary_style=False)
    qf, kf = apply_rotary_pos_emb(q, k)
    np.testing.assert_allclose(np.asarray(qi), np.asarray(qf[:, i:i + 1]),
                               atol=1e-6)

    # custom sin/cos tables raise rather than being silently dropped
    with pytest.raises(UnimplementedError, match="sin/cos"):
        incubate_F.fused_rotary_position_embedding(
            q, k, None, sin=np.zeros((S, D)), cos=np.ones((S, D)),
            use_neox_rotary_style=False)
