"""Round-3 second-level namespace completions: sparse.nn layers,
incubate.nn fused layers, folder datasets, fleet.utils fs clients,
utils helpers, Bilinear initializer, profiler enums."""
import os

import numpy as np
import pytest

import paddle_tpu as pt


def _t(a):
    return pt.to_tensor(np.asarray(a))


class TestSparseNN:
    def _sample(self):
        dense = np.zeros((1, 4, 4, 4, 3), np.float32)
        dense[0, 1, 1, 1] = [1.0, -2.0, 3.0]
        dense[0, 2, 3, 0] = [0.5, 0.5, 0.5]
        idx = np.array(np.nonzero(np.any(dense != 0, axis=-1)))
        vals = dense[tuple(idx)]
        sp = pt.sparse.sparse_coo_tensor(_t(idx), _t(vals),
                                         shape=list(dense.shape))
        return dense, sp

    def test_value_activations(self):
        _, sp = self._sample()
        r = pt.sparse.nn.ReLU()(sp)
        assert r.is_sparse() and float(r.values().numpy().min()) >= 0
        lr = pt.sparse.nn.LeakyReLU(0.1)(sp)
        assert lr.is_sparse()
        sm = pt.sparse.nn.Softmax()(sp)
        np.testing.assert_allclose(sm.values().numpy().sum(-1), 1.0,
                                   rtol=1e-5)

    def test_batch_norm(self):
        dense, sp = self._sample()
        out = pt.sparse.nn.BatchNorm(3)(sp)
        assert out.is_sparse() and out.shape == list(dense.shape)
        sync = pt.sparse.nn.SyncBatchNorm(3)(sp)
        assert sync.is_sparse()

    def test_conv_and_subm(self):
        dense, sp = self._sample()
        conv = pt.sparse.nn.Conv3D(3, 5, 3, padding=1)
        y = conv(sp)
        assert y.shape[-1] == 5
        # active set = kernel-REACHABLE sites, not value-nonzero sites: a
        # biased conv must not densify the COO (round-3 review)
        conv._conv.bias.set_value(np.full(5, 0.1, np.float32))
        yb = conv(sp)
        n_sites = int(np.prod(dense.shape[:-1]))
        assert yb.nnz < n_sites, "bias densified the sparse output"
        ys = pt.sparse.nn.SubmConv3D(3, 5, 3)(sp)
        active = np.any(ys.to_dense().numpy() != 0, axis=-1)
        orig = np.any(dense != 0, axis=-1)
        assert (active <= orig).all()  # subm never grows the active set
        # even kernels work (asymmetric same-padding keeps input dims)
        ye = pt.sparse.nn.SubmConv3D(3, 4, 2)(sp)
        assert ye.shape[:-1] == list(dense.shape[:-1])
        with pytest.raises(ValueError, match="stride"):
            pt.sparse.nn.SubmConv3D(3, 4, 3, stride=2)
        with pytest.raises(ValueError, match="padding"):
            pt.sparse.nn.SubmConv3D(3, 4, 3, padding=1)
        m = pt.sparse.nn.MaxPool3D(2)(sp)
        assert m.shape[1] == 2


class TestFusedLayers:
    def test_fused_linear(self):
        x = _t(np.random.randn(2, 4).astype(np.float32))
        fl = pt.incubate.nn.FusedLinear(4, 6)
        assert fl(x).shape == [2, 6]
        flt = pt.incubate.nn.FusedLinear(4, 6, transpose_weight=True)
        assert flt.weight.shape == [6, 4] and flt(x).shape == [2, 6]

    def test_fused_dropout_residual(self):
        x = _t(np.random.randn(2, 4).astype(np.float32))
        fd = pt.incubate.nn.FusedDropoutAdd(0.0)
        np.testing.assert_allclose(fd(x, x).numpy(), 2 * x.numpy(),
                                   rtol=1e-6)
        fb = pt.incubate.nn.FusedBiasDropoutResidualLayerNorm(4, 0.0)
        out = fb(x, x)
        assert out.shape == [2, 4]
        np.testing.assert_allclose(out.numpy().mean(-1), 0.0, atol=1e-5)

    def test_fused_ec_moe_and_stack(self):
        h = _t(np.random.randn(2, 8, 8).astype(np.float32))
        moe = pt.incubate.nn.FusedEcMoe(8, 16, 4)
        gate = pt.nn.Linear(8, 4)
        assert moe(h, gate(h)).shape == [2, 8, 8]
        fmt = pt.incubate.nn.FusedMultiTransformer(
            8, 2, 16, num_layers=2, normalize_before=True)
        assert fmt(h).shape == [2, 8, 8]
        with pytest.raises(ValueError):
            pt.incubate.nn.FusedMultiTransformer(8, 2, 16,
                                                 normalize_before=False)
        with pytest.raises(NotImplementedError, match="cache"):
            fmt(h, caches=[None, None])


class TestFolderDatasets:
    @pytest.fixture()
    def folder(self, tmp_path):
        from PIL import Image

        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                Image.fromarray(
                    np.full((4, 4, 3), i * 40, np.uint8)
                ).save(str(d / f"{cls}{i}.png"))
        return str(tmp_path)

    def test_dataset_folder(self, folder):
        ds = pt.vision.datasets.DatasetFolder(folder)
        assert len(ds) == 4 and ds.classes == ["cat", "dog"]
        img, label = ds[0]
        assert img.shape == (4, 4, 3) and label == 0
        assert ds.targets.count(1) == 2

    def test_image_folder_and_transform(self, folder):
        calls = []

        def tf(img):
            calls.append(1)
            return img

        ds = pt.vision.datasets.ImageFolder(folder, transform=tf)
        assert len(ds) == 4
        (img,) = ds[1]
        assert img.shape == (4, 4, 3) and calls

    def test_empty_folder_raises(self, tmp_path):
        (tmp_path / "empty").mkdir()
        with pytest.raises(RuntimeError):
            pt.vision.datasets.DatasetFolder(str(tmp_path))


class TestSmallCompletions:
    def test_utils(self):
        mod = pt.utils.try_import("math")
        assert mod.sqrt(4) == 2
        with pytest.raises(ImportError):
            pt.utils.try_import("definitely_not_a_module_xyz")
        assert pt.utils.require_version("0.0.1")
        with pytest.raises(Exception, match="required"):
            pt.utils.require_version("999.0.0")

        @pt.utils.deprecated(update_to="paddle.new_api", since="2.0")
        def old():
            return 42

        with pytest.warns(DeprecationWarning):
            assert old() == 42

    def test_bilinear_initializer(self):
        init = pt.nn.initializer.Bilinear()
        w = np.asarray(init([2, 2, 4, 4], "float32"))
        assert w.shape == (2, 2, 4, 4)
        # symmetric stencil, peak at center, every channel pair filled
        np.testing.assert_allclose(w[0, 0], w[0, 0][::-1, ::-1], atol=1e-6)
        assert w[0, 0].max() == w[0, 0][1:3, 1:3].max()
        np.testing.assert_allclose(w[1, 1], w[0, 0])
        np.testing.assert_allclose(w[0, 1], w[0, 0])
        with pytest.raises(ValueError):
            init([4, 4], "float32")
        with pytest.raises(ValueError, match="square"):
            init([2, 2, 3, 5], "float32")

    def test_profiler_enums(self):
        assert pt.profiler.SortedKeys.CPUTotal == 0
        assert pt.profiler.SummaryView.OverView == 1
        with pytest.raises(ValueError):
            pt.profiler.export_protobuf(None)

    def test_quantization_shells(self):
        assert issubclass(pt.quantization.FakeQuanterWithAbsMax, pt.nn.Layer)

        @pt.quantization.quanter("MyQ")
        class MyQ(pt.quantization.BaseQuanter):
            pass

        from paddle_tpu.quantization import _QUANTER_REGISTRY

        assert _QUANTER_REGISTRY["MyQ"] is MyQ
        # string configs resolve through the registry
        cfg = pt.quantization.QuantConfig(activation="MyQ", weight="MyQ")
        assert cfg.activation is MyQ and cfg.weight is MyQ
        with pytest.raises(ValueError, match="registered"):
            pt.quantization.QuantConfig(activation="NoSuchQ")

    def test_fleet_localfs(self, tmp_path):
        fs = pt.distributed.fleet.utils.LocalFS()
        d = str(tmp_path / "sub")
        fs.mkdirs(d)
        fs.touch(os.path.join(d, "f.txt"))
        dirs, files = fs.ls_dir(str(tmp_path))
        assert dirs == ["sub"] and files == []
        assert fs.is_dir(d) and not fs.is_file(d)
        # mv refuses to clobber unless overwrite=True (reference contract)
        fs.touch(os.path.join(d, "g.txt"))
        with pytest.raises(FileExistsError):
            fs.mv(os.path.join(d, "f.txt"), os.path.join(d, "g.txt"))
        fs.mv(os.path.join(d, "f.txt"), os.path.join(d, "g.txt"),
              overwrite=True)
        assert not fs.is_exist(os.path.join(d, "f.txt"))
        fs.delete(d)
        assert not fs.is_exist(d)


class TestDistributedSubNamespaces:
    def test_exposed_modules(self):
        assert pt.distributed.checkpoint is not None
        assert callable(pt.distributed.sharding.group_sharded_parallel)
        assert pt.amp.debugging.DebugMode is not None
        assert pt.nn.quant.Stub()(pt.ones([2])).shape == [2]

    def test_rpc_excluded(self):
        with pytest.raises(RuntimeError, match="excluded"):
            pt.distributed.rpc.init_rpc("worker0")

    def test_pass_framework(self):
        from paddle_tpu.distributed.passes import (
            PassBase, PassManager, new_pass, register_pass,
        )

        @register_pass("tag_program_test")
        class TagPass(PassBase):
            def __init__(self):
                super().__init__("tag_program_test")

            def apply(self, mains, startups=None, context=None):
                for m in mains:
                    m.random_seed = 1234
                context.set_attr("tagged", True)

        prog = pt.static.Program()
        pm = PassManager([new_pass("tag_program_test")])
        pm.apply(prog)
        assert prog.random_seed == 1234
        assert pm.context.get_attr("tagged")
        assert pm.names == ["tag_program_test"]
        with pytest.raises(ValueError, match="registered"):
            new_pass("no_such_pass")

    def test_compare_accuracy(self, tmp_path):
        import json

        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        a.write_text(json.dumps({"op": "matmul", "num_nan": 0}) + "\n")
        b.write_text(json.dumps({"op": "matmul", "num_nan": 3}) + "\n")
        out = pt.amp.debugging.compare_accuracy(
            str(a), str(b), str(tmp_path / "cmp.csv"))
        body = open(out).read()
        assert "matmul" in body and "num_nan" in body

    def test_incubate_autograd(self):
        assert not pt.incubate.autograd.prim_enabled()
        pt.incubate.autograd.enable_prim()
        try:
            assert pt.incubate.autograd.prim_enabled()
        finally:
            pt.incubate.autograd.disable_prim()
        H = pt.incubate.autograd.Hessian(
            lambda x: (x ** 2).sum(),
            pt.to_tensor(np.ones(3, np.float32)))
        h = H[:, :]
        np.testing.assert_allclose(np.asarray(h.numpy()), 2 * np.eye(3),
                                   atol=1e-5)
        with pytest.raises(NotImplementedError):
            pt.incubate.autograd.forward_grad(None, None)


class TestReviewRound2Fixes:
    def test_sparse_conv_same_padding(self):
        dense = np.zeros((1, 4, 4, 4, 3), np.float32)
        dense[0, 1, 1, 1] = [1.0, 1.0, 1.0]
        idx = np.array(np.nonzero(np.any(dense != 0, axis=-1)))
        sp = pt.sparse.sparse_coo_tensor(
            _t(idx), _t(dense[tuple(idx)]), shape=list(dense.shape))
        y = pt.sparse.nn.Conv3D(3, 2, 3, padding="same")(sp)
        assert y.shape == [1, 4, 4, 4, 2]
        y2 = pt.sparse.nn.Conv3D(
            3, 2, 3, padding=[[1, 1], [1, 1], [1, 1]])(sp)
        assert y2.shape[-1] == 2

    def test_pass_duck_typing(self):
        from paddle_tpu.distributed.passes import (
            PassManager, new_pass, register_pass,
        )

        @register_pass("duck_pass")
        class Duck:  # no PassBase subclassing
            def apply(self, mains, startups=None, context=None):
                for m in mains:
                    m.random_seed = 77

        prog = pt.static.Program()
        PassManager([new_pass("duck_pass")]).apply(prog)
        assert prog.random_seed == 77

    def test_bn_keeps_bf16_under_autocast(self):
        bn = pt.nn.BatchNorm2D(3)
        x = _t(np.random.randn(2, 3, 4, 4).astype(np.float32)) \
            .astype("bfloat16")
        with pt.amp.auto_cast(level="O2", dtype="bfloat16"):
            out = bn(x)
        assert "bfloat16" in str(out.dtype), out.dtype

    def test_quant_add_type_config_string(self):
        @pt.quantization.quanter("MyQ2")
        class MyQ2(pt.quantization.BaseQuanter):
            pass

        cfg = pt.quantization.QuantConfig()
        cfg.add_type_config(pt.nn.Conv2D, activation="MyQ2")
        assert cfg.activation is MyQ2

    def test_compare_accuracy_aggregates(self, tmp_path):
        import json

        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        a.write_text(json.dumps({"op": "matmul", "num_nan": 1}) + "\n"
                     + json.dumps({"op": "matmul", "num_nan": 2}) + "\n")
        b.write_text(json.dumps({"op": "matmul", "num_nan": 0}) + "\n")
        out = pt.amp.debugging.compare_accuracy(
            str(a), str(b), str(tmp_path / "c.csv"))
        body = open(out).read()
        # aggregated: run_a num_nan == 3 (1+2), not just the last record
        assert "3" in body and "matmul" in body
