"""Domain libraries: sparse, audio, text, quantization, distribution glue
(reference `test/quantization`, `test/legacy_test/test_sparse_*`,
`test/legacy_test/test_viterbi_decode_op.py`)."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle


class TestSparse:
    def test_coo_roundtrip(self):
        s = paddle.sparse.sparse_coo_tensor(
            [[0, 1, 2], [1, 2, 0]], [1.0, 2.0, 3.0], (3, 3))
        d = s.to_dense().numpy()
        assert d[0, 1] == 1.0 and d[2, 0] == 3.0
        assert s.nnz == 3
        assert s.indices().shape == [2, 3]

    def test_spmm(self):
        s = paddle.sparse.sparse_coo_tensor(
            [[0, 1], [1, 0]], [2.0, 3.0], (2, 2))
        dense = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
        out = paddle.sparse.matmul(s, dense)
        ref = s.to_dense().numpy() @ dense.numpy()
        np.testing.assert_allclose(out.numpy(), ref)

    def test_csr_construct(self):
        s = paddle.sparse.sparse_csr_tensor(
            [0, 1, 2], [1, 0], [5.0, 6.0], (2, 2))
        d = s.to_dense().numpy()
        assert d[0, 1] == 5.0 and d[1, 0] == 6.0

    def test_sparse_relu(self):
        s = paddle.sparse.sparse_coo_tensor(
            [[0, 0], [0, 1]], [-1.0, 2.0], (1, 2))
        out = paddle.sparse.relu(s).to_dense().numpy()
        np.testing.assert_allclose(out, [[0.0, 2.0]])


class TestAudio:
    def test_spectrogram_shapes(self):
        x = paddle.to_tensor(
            np.sin(np.linspace(0, 100, 2000)).astype(np.float32)[None])
        spec = paddle.audio.features.Spectrogram(n_fft=256)(x)
        assert spec.shape[1] == 129  # n_fft//2+1 bins

    def test_logmel_and_mfcc(self):
        x = paddle.to_tensor(
            np.random.randn(1, 2000).astype(np.float32))
        lm = paddle.audio.features.LogMelSpectrogram(
            sr=8000, n_fft=256, n_mels=32)(x)
        assert lm.shape[1] == 32
        mfcc = paddle.audio.features.MFCC(
            sr=8000, n_mfcc=13, n_fft=256, n_mels=32)(x)
        assert mfcc.shape[1] == 13

    def test_mel_scale_invertible(self):
        f = 1234.5
        assert abs(paddle.audio.functional.mel_to_hz(
            paddle.audio.functional.hz_to_mel(f)) - f) < 1e-6


class TestViterbi:
    def test_matches_brute_force(self):
        emis = np.random.RandomState(0).randn(1, 4, 5).astype(np.float32)
        trans = np.random.RandomState(1).randn(5, 5).astype(np.float32)
        scores, paths = paddle.text.viterbi_decode(
            paddle.to_tensor(emis), paddle.to_tensor(trans),
            include_bos_eos_tag=False)
        best, bp = -1e9, None
        for p in itertools.product(range(5), repeat=4):
            sc = emis[0, 0, p[0]] + sum(
                trans[p[i - 1], p[i]] + emis[0, i, p[i]]
                for i in range(1, 4))
            if sc > best:
                best, bp = sc, p
        np.testing.assert_allclose(scores.numpy()[0], best, rtol=1e-5)
        assert tuple(paths.numpy()[0]) == bp


class TestQuantization:
    def test_fake_quant_ste_gradient(self):
        from paddle_tpu.quantization import quant_dequant

        x = paddle.to_tensor(np.array([0.5, -0.3], np.float32),
                             stop_gradient=False)
        y = quant_dequant(x, paddle.to_tensor(1.0, "float32"))
        (y * 2).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])  # STE

    def test_qat_quantize_and_train(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import QAT, QuantConfig

        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        qnet = QAT(QuantConfig()).quantize(net)
        opt = paddle.optimizer.Adam(0.01, parameters=qnet.parameters())
        x = paddle.randn([4, 8])
        y = paddle.randn([4, 4])
        losses = []
        for _ in range(8):
            loss = ((qnet(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_ptq_calibrate_convert(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import PTQ, AbsmaxObserver

        net = nn.Sequential(nn.Linear(4, 4))
        ptq = PTQ()
        qnet = ptq.quantize(net)
        for _ in range(3):
            qnet(paddle.randn([2, 4]))
        qnet = ptq.convert(qnet)
        from paddle_tpu.quantization import FakeQuanterWithAbsMax

        quanters = [s for s in qnet.sublayers()
                    if isinstance(s, FakeQuanterWithAbsMax)]
        assert quanters and all(
            float(q.scale.numpy()) > 0 for q in quanters)
