"""ASP n:m structured sparsity (parity: `python/paddle/incubate/asp/` —
VERDICT r2 item 8: masked training preserves the 2:4 pattern across
steps, under both the eager optimizer and the compiled TrainStep)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate import asp
from paddle_tpu.jit.train_step import TrainStep


class TestMaskUtils:
    def test_get_mask_1d_reference_example(self):
        mat = np.asarray([[0, 1, 5, 4], [2, 7, 3, 6]], "float32")
        mask = asp.get_mask_1d(mat, 2, 4)
        np.testing.assert_array_equal(mask, [[0, 0, 1, 1], [0, 1, 0, 1]])
        assert asp.check_mask_1d(mask, 2, 4)

    def test_get_mask_1d_padding(self):
        mat = np.random.default_rng(0).standard_normal((3, 6)).astype("f4")
        mask = asp.get_mask_1d(mat, 2, 4)
        assert mask.shape == (3, 6)

    def test_mask_2d_greedy(self):
        mat = np.random.default_rng(1).standard_normal((8, 8)).astype("f4")
        mask = asp.get_mask_2d_greedy(mat, 2, 4)
        assert asp.check_mask_2d(mask, 2, 4)
        # 2:4 2-D pattern keeps at most half the entries per block (greedy
        # may keep one fewer when a deficit row faces only full columns)
        assert 28 <= mask.sum() <= 32

    def test_density_and_check_sparsity(self):
        mat = np.asarray([[0, 1, 5, 4], [2, 7, 3, 6]], "float32")
        pruned = mat * asp.get_mask_1d(mat, 2, 4)
        assert asp.calculate_density(pruned) == 0.5
        assert asp.check_sparsity(pruned, asp.CheckMethod.CHECK_1D, 2, 4)

    def test_create_mask_conv_shape(self):
        w = np.random.default_rng(2).standard_normal((8, 4, 3, 3)).astype("f4")
        mask = asp.create_mask(w, asp.MaskAlgo.MASK_1D, 2, 4)
        assert mask.shape == w.shape


def _check_model_2to4(model):
    for name, mask in model._asp_masks.items():
        m = mask.numpy()
        flat = m.T if m.ndim == 2 else m.reshape(m.shape[0], -1)
        assert asp.check_mask_1d(flat, 2, 4), name


class TestTrainingPreservation:
    def _model(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                             nn.Linear(32, 8))

    def _data(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((4, 16)).astype("f4"))
        y = paddle.to_tensor(rng.integers(0, 8, (4,)).astype("int64"))
        return x, y

    def test_prune_then_eager_training_preserves_pattern(self):
        model = self._model()
        opt = asp.decorate(paddle.optimizer.AdamW(
            learning_rate=1e-2, parameters=model.parameters()))
        masks = asp.prune_model(model, n=2, m=4)
        assert masks
        _check_model_2to4(model)
        x, y = self._data()
        for _ in range(3):
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        for name, p in model.named_parameters():
            if name in masks:
                w = p.numpy()
                # pruned positions stayed exactly zero
                assert (w[masks[name].numpy() == 0] == 0).all()
                assert asp.check_sparsity(
                    paddle.to_tensor(w.T), asp.CheckMethod.CHECK_1D, 2, 4)

    def test_prune_then_trainstep_preserves_pattern(self):
        model = self._model()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        masks = asp.prune_model(model, n=2, m=4)
        asp.decorate(opt)
        step = TrainStep(model, opt,
                         lambda m, a, b: F.cross_entropy(m(a), b))
        x, y = self._data()
        l0 = float(step(x, y).numpy())
        for _ in range(3):
            loss = step(x, y)
        assert float(loss.numpy()) < l0  # still actually training
        for name, p in model.named_parameters():
            if name in masks:
                assert (p.numpy()[masks[name].numpy() == 0] == 0).all()

    def test_excluded_layers(self):
        model = self._model()
        names = [n for n, _ in model.named_parameters() if "weight" in n]
        asp.set_excluded_layers([names[0]])
        try:
            masks = asp.prune_model(model, n=2, m=4)
            assert names[0] not in masks
            assert any(n != names[0] for n in masks)
        finally:
            asp.reset_excluded_layers()


    def test_decorate_then_prune_then_trainstep(self):
        # review finding: masks computed after decorate() must still reach
        # the compiled TrainStep (prune_model re-syncs decorated optimizers)
        model = self._model()
        opt = asp.decorate(paddle.optimizer.AdamW(
            learning_rate=1e-2, parameters=model.parameters()))
        masks = asp.prune_model(model, n=2, m=4)
        step = TrainStep(model, opt,
                         lambda m, a, b: F.cross_entropy(m(a), b))
        x, y = self._data()
        for _ in range(2):
            step(x, y)
        for name, p in model.named_parameters():
            if name in masks:
                assert (p.numpy()[masks[name].numpy() == 0] == 0).all()
