"""User-facing recompute API tests.

Parity contract (reference `fleet/recompute/recompute.py:69,334` +
`test/collective/fleet/test_dygraph_recompute*.py`): identical loss and
grads with/without recompute, deterministic dropout replay, and
`recompute_sequential` segmenting.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.utils import recompute, recompute_sequential

H = 16


class Block(nn.Layer):
    def __init__(self, h=H, dropout=0.0):
        super().__init__()
        self.fc1 = nn.Linear(h, 4 * h)
        self.fc2 = nn.Linear(4 * h, h)
        self.p = dropout

    def forward(self, x):
        y = pt.tanh(self.fc1(x))
        if self.p:
            y = nn.functional.dropout(y, self.p)
        return x + self.fc2(y)


class Net(nn.Layer):
    def __init__(self, n=3, use_recompute=False, dropout=0.0):
        super().__init__()
        self.blocks = nn.LayerList([Block(dropout=dropout) for _ in range(n)])
        self.head = nn.Linear(H, 2)
        self.use_recompute = use_recompute

    def forward(self, x):
        for b in self.blocks:
            x = recompute(b, x) if self.use_recompute else b(x)
        return self.head(x)


def _run(use_recompute, dropout=0.0, seed=7):
    pt.seed(seed)
    np.random.seed(seed)
    m = Net(use_recompute=use_recompute, dropout=dropout)
    x = pt.to_tensor(np.random.randn(4, H).astype(np.float32))
    loss = (m(x) ** 2).mean()
    loss.backward()
    grads = {n: p.grad.numpy().copy() for n, p in m.named_parameters()
             if p.grad is not None}
    return float(loss.numpy()), grads


def test_loss_and_grads_match():
    l0, g0 = _run(False)
    l1, g1 = _run(True)
    assert abs(l0 - l1) < 1e-6
    assert set(g0) == set(g1) and len(g0) > 0
    for k in g0:
        np.testing.assert_allclose(g0[k], g1[k], atol=1e-5, err_msg=k)


def test_dropout_deterministic_replay():
    # grads must be finite and reproducible across seeds: the recomputed
    # forward replays the same dropout mask (key is an operand, not state)
    l1, g1 = _run(True, dropout=0.5, seed=3)
    l2, g2 = _run(True, dropout=0.5, seed=3)
    assert l1 == l2
    for k in g1:
        np.testing.assert_allclose(g1[k], g2[k], atol=0)


def test_no_grad_passthrough():
    m = Block()
    x = pt.to_tensor(np.random.randn(2, H).astype(np.float32))
    with pt.no_grad():
        y = recompute(m, x)
    assert y.stop_gradient


def test_recompute_reduces_saved_residuals():
    # the taped node for a recomputed segment must store only the segment
    # inputs (params + x + key), not intermediate activations
    m = Block()
    x = pt.to_tensor(np.random.randn(2, H).astype(np.float32))
    y = recompute(m, x)
    node = y._grad_node
    assert node is not None and node.op_name == "recompute"


def test_recompute_sequential():
    pt.seed(11)
    blocks = nn.LayerList([Block() for _ in range(4)])
    x = pt.to_tensor(np.random.randn(2, H).astype(np.float32))
    y_ref = x
    for b in blocks:
        y_ref = b(y_ref)
    y = recompute_sequential({"segments": 2}, blocks, x)
    np.testing.assert_allclose(y.numpy(), y_ref.numpy(), atol=1e-6)
    (y ** 2).mean().backward()
    assert blocks[0].fc1.weight.grad is not None


def test_grad_matches_finite_difference():
    pt.seed(5)
    m = Block(h=4)
    x = pt.to_tensor(np.random.randn(2, 4).astype(np.float32))
    loss = (recompute(m, x) ** 2).mean()
    loss.backward()
    w = m.fc1.weight
    g = w.grad.numpy()
    eps = 1e-3
    wv = w.numpy().copy()
    idx = (0, 1)
    wplus = wv.copy(); wplus[idx] += eps
    wminus = wv.copy(); wminus[idx] -= eps
    outs = []
    for wa in (wplus, wminus):
        w.set_value(wa)
        outs.append(float(((m(x) ** 2).mean()).numpy()))
    w.set_value(wv)
    fd = (outs[0] - outs[1]) / (2 * eps)
    assert abs(fd - g[idx]) < 1e-2
