"""Op parity tests vs numpy (OpTest model, reference eager_op_test.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_output, check_grad


class TestCreation:
    def test_to_tensor(self):
        t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.shape == [2, 2]
        assert t.dtype == paddle.float32
        np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])

    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3], "int32").numpy().sum() == 6
        np.testing.assert_array_equal(paddle.full([2], 7).numpy(), [7, 7])

    def test_arange_linspace(self):
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(
            paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6
        )

    def test_eye_tril_triu(self):
        x = np.random.rand(4, 4).astype(np.float32)
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3))
        np.testing.assert_array_equal(
            paddle.tril(paddle.to_tensor(x)).numpy(), np.tril(x)
        )
        np.testing.assert_array_equal(
            paddle.triu(paddle.to_tensor(x), 1).numpy(), np.triu(x, 1)
        )

    def test_like_family(self):
        x = paddle.to_tensor(np.random.rand(3, 2).astype(np.float32))
        assert paddle.zeros_like(x).shape == [3, 2]
        assert paddle.ones_like(x).numpy().sum() == 6
        assert paddle.full_like(x, 3.0).numpy()[0, 0] == 3.0


class TestElementwise:
    @pytest.mark.parametrize(
        "op,np_op",
        [
            ("add", np.add), ("subtract", np.subtract),
            ("multiply", np.multiply), ("divide", np.divide),
            ("maximum", np.maximum), ("minimum", np.minimum),
        ],
    )
    def test_binary(self, op, np_op):
        x = np.random.rand(3, 4).astype(np.float32) + 0.5
        y = np.random.rand(3, 4).astype(np.float32) + 0.5
        check_output(getattr(paddle, op), np_op, [x, y])

    def test_broadcasting(self):
        x = np.random.rand(3, 1, 4).astype(np.float32)
        y = np.random.rand(2, 1).astype(np.float32)
        check_output(paddle.add, np.add, [x, y])

    def test_scalar_operands(self):
        x = paddle.to_tensor([1.0, 2.0])
        assert (x + 1).numpy().tolist() == [2.0, 3.0]
        assert (2 * x).numpy().tolist() == [2.0, 4.0]
        assert (1 - x).numpy().tolist() == [0.0, -1.0]
        assert (x / 2).dtype == paddle.float32

    @pytest.mark.parametrize(
        "op,np_op",
        [
            ("exp", np.exp), ("log", lambda a: np.log(a)),
            ("sqrt", np.sqrt), ("abs", np.abs), ("tanh", np.tanh),
            ("sin", np.sin), ("cos", np.cos), ("floor", np.floor),
            ("ceil", np.ceil), ("square", np.square),
        ],
    )
    def test_unary(self, op, np_op):
        x = np.random.rand(3, 4).astype(np.float32) + 0.5
        # XLA:CPU uses polynomial approximations for transcendentals; allow
        # a few ulp more than strict float32
        check_output(getattr(paddle, op), np_op, [x], rtol=1e-3, atol=1e-5)

    def test_clip_scale(self):
        x = np.linspace(-2, 2, 10).astype(np.float32)
        check_output(paddle.clip, lambda a, **k: np.clip(a, -1, 1), [x], min=-1, max=1)
        t = paddle.scale(paddle.to_tensor(x), scale=2.0, bias=1.0)
        np.testing.assert_allclose(t.numpy(), x * 2 + 1, rtol=1e-6)

    def test_pow_mod(self):
        x = np.random.rand(4).astype(np.float32) + 1
        y = np.random.rand(4).astype(np.float32) + 1
        check_output(paddle.pow, np.power, [x, y])
        check_output(paddle.mod, np.mod, [x, y])


class TestMatmul:
    def test_matmul_2d(self):
        x = np.random.rand(3, 4).astype(np.float32)
        y = np.random.rand(4, 5).astype(np.float32)
        check_output(paddle.matmul, np.matmul, [x, y], rtol=1e-4)

    def test_matmul_transpose_flags(self):
        x = np.random.rand(4, 3).astype(np.float32)
        y = np.random.rand(5, 4).astype(np.float32)
        out = paddle.matmul(
            paddle.to_tensor(x), paddle.to_tensor(y),
            transpose_x=True, transpose_y=True,
        )
        np.testing.assert_allclose(out.numpy(), x.T @ y.T, rtol=1e-4)

    def test_batched(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        y = np.random.rand(2, 4, 5).astype(np.float32)
        check_output(paddle.bmm, np.matmul, [x, y], rtol=1e-4)

    def test_einsum(self):
        x = np.random.rand(2, 3).astype(np.float32)
        y = np.random.rand(3, 4).astype(np.float32)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), x @ y, rtol=1e-4)


class TestReductions:
    @pytest.mark.parametrize(
        "op,np_op",
        [("sum", np.sum), ("mean", np.mean), ("max", np.max), ("min", np.min)],
    )
    def test_full_reduce(self, op, np_op):
        x = np.random.rand(3, 4).astype(np.float32)
        check_output(getattr(paddle, op), np_op, [x], rtol=1e-5)

    def test_axis_keepdim(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(
            paddle.sum(t, axis=1).numpy(), x.sum(1), rtol=1e-5
        )
        np.testing.assert_allclose(
            paddle.mean(t, axis=[0, 2], keepdim=True).numpy(),
            x.mean((0, 2), keepdims=True), rtol=1e-5,
        )

    def test_cumsum_logsumexp(self):
        x = np.random.rand(3, 4).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(
            paddle.cumsum(t, axis=1).numpy(), np.cumsum(x, 1), rtol=1e-5
        )
        from scipy.special import logsumexp as np_lse  # noqa
        np.testing.assert_allclose(
            paddle.logsumexp(t).numpy(), np_lse(x), rtol=1e-5
        )

    def test_prod_std_var(self):
        x = np.random.rand(3, 4).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.prod(t).numpy(), x.prod(), rtol=1e-4)
        np.testing.assert_allclose(
            paddle.tensor.std(t).numpy(), x.std(ddof=1), rtol=1e-4
        )
        np.testing.assert_allclose(
            paddle.tensor.var(t, axis=0).numpy(), x.var(0, ddof=1), rtol=1e-4
        )


class TestManipulation:
    def test_reshape_flatten(self):
        x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
        t = paddle.to_tensor(x)
        assert paddle.reshape(t, [4, 6]).shape == [4, 6]
        assert paddle.reshape(t, [-1, 4]).shape == [6, 4]
        assert paddle.flatten(t, 1, 2).shape == [2, 12]

    def test_transpose_squeeze(self):
        x = np.random.rand(2, 1, 3).astype(np.float32)
        t = paddle.to_tensor(x)
        assert paddle.transpose(t, [2, 0, 1]).shape == [3, 2, 1]
        assert paddle.squeeze(t, 1).shape == [2, 3]
        assert paddle.unsqueeze(t, 0).shape == [1, 2, 1, 3]

    def test_concat_stack_split(self):
        x = np.random.rand(2, 3).astype(np.float32)
        y = np.random.rand(2, 3).astype(np.float32)
        tx, ty = paddle.to_tensor(x), paddle.to_tensor(y)
        np.testing.assert_array_equal(
            paddle.concat([tx, ty], 0).numpy(), np.concatenate([x, y], 0)
        )
        np.testing.assert_array_equal(
            paddle.stack([tx, ty], 1).numpy(), np.stack([x, y], 1)
        )
        parts = paddle.split(paddle.to_tensor(np.arange(10)), [3, 3, 4])
        assert [p.shape[0] for p in parts] == [3, 3, 4]
        parts = paddle.split(paddle.to_tensor(np.arange(12).reshape(2, 6)), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == [2, 2]

    def test_gather_scatter(self):
        x = np.random.rand(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4])
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(
            paddle.gather(t, paddle.to_tensor(idx)).numpy(), x[idx]
        )
        upd = np.ones((3, 3), np.float32)
        out = paddle.tensor.scatter(t, paddle.to_tensor(idx), paddle.to_tensor(upd))
        expected = x.copy()
        expected[idx] = 1.0
        np.testing.assert_array_equal(out.numpy(), expected)

    def test_tile_expand(self):
        x = np.random.rand(1, 3).astype(np.float32)
        t = paddle.to_tensor(x)
        assert paddle.tile(t, [2, 2]).shape == [2, 6]
        assert paddle.expand(t, [4, 3]).shape == [4, 3]
        assert paddle.tensor.broadcast_to(t, [4, 3]).shape == [4, 3]

    def test_indexing(self):
        x = np.arange(24).reshape(4, 6).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(t[1].numpy(), x[1])
        np.testing.assert_array_equal(t[1:3, ::2].numpy(), x[1:3, ::2])
        np.testing.assert_array_equal(t[:, -1].numpy(), x[:, -1])
        idx = paddle.to_tensor(np.array([0, 2]))
        np.testing.assert_array_equal(t[idx].numpy(), x[[0, 2]])

    def test_setitem(self):
        x = np.zeros((3, 3), np.float32)
        t = paddle.to_tensor(x.copy())
        t[1] = 5.0
        assert t.numpy()[1].tolist() == [5.0, 5.0, 5.0]
        t[0, 0] = 1.0
        assert t.numpy()[0, 0] == 1.0

    def test_flip_roll(self):
        x = np.arange(6).reshape(2, 3).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(paddle.flip(t, [0]).numpy(), x[::-1])
        np.testing.assert_array_equal(
            paddle.roll(t, 1, axis=1).numpy(), np.roll(x, 1, 1)
        )

    def test_cast(self):
        t = paddle.to_tensor([1.7, 2.3])
        assert t.astype("int32").numpy().tolist() == [1, 2]
        assert t.astype(paddle.float16).dtype == paddle.float16


class TestLogicSearch:
    def test_comparisons(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0])
        y = paddle.to_tensor([2.0, 2.0, 2.0])
        assert (x < y).numpy().tolist() == [True, False, False]
        assert (x == y).numpy().tolist() == [False, True, False]
        assert paddle.tensor.allclose(x, x).item() is True

    def test_where(self):
        x = np.random.rand(3, 4).astype(np.float32)
        y = np.random.rand(3, 4).astype(np.float32)
        cond = x > 0.5
        out = paddle.where(
            paddle.to_tensor(cond), paddle.to_tensor(x), paddle.to_tensor(y)
        )
        np.testing.assert_array_equal(out.numpy(), np.where(cond, x, y))

    def test_argmax_sort_topk(self):
        x = np.random.rand(4, 5).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(
            paddle.argmax(t, axis=1).numpy(), x.argmax(1)
        )
        np.testing.assert_allclose(
            paddle.tensor.sort(t, axis=1).numpy(), np.sort(x, 1), rtol=1e-6
        )
        vals, idx = paddle.topk(t, 2, axis=1)
        np.testing.assert_allclose(
            vals.numpy(), np.sort(x, 1)[:, ::-1][:, :2], rtol=1e-6
        )

    def test_nonzero_masked(self):
        x = np.array([[0, 1], [2, 0]], np.float32)
        t = paddle.to_tensor(x)
        nz = paddle.tensor.nonzero(t)
        np.testing.assert_array_equal(nz.numpy(), np.stack(np.nonzero(x), 1))
        sel = paddle.tensor.masked_select(t, t > 0)
        np.testing.assert_array_equal(np.sort(sel.numpy()), [1, 2])

    def test_unique(self):
        x = np.array([3, 1, 2, 1, 3], np.int32)
        out = paddle.tensor.unique(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3])


class TestLinalg:
    def test_solve_inv(self):
        a = np.random.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
        b = np.random.rand(3, 2).astype(np.float32)
        out = paddle.tensor.solve(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), np.linalg.solve(a, b), rtol=1e-3, atol=1e-4)
        inv = paddle.tensor.inv(paddle.to_tensor(a))
        np.testing.assert_allclose(inv.numpy(), np.linalg.inv(a), rtol=1e-3, atol=1e-4)

    def test_norm_det(self):
        a = np.random.rand(3, 3).astype(np.float32)
        np.testing.assert_allclose(
            paddle.tensor.norm(paddle.to_tensor(a)).numpy(),
            np.linalg.norm(a), rtol=1e-5,
        )
        np.testing.assert_allclose(
            paddle.tensor.det(paddle.to_tensor(a)).numpy(),
            np.linalg.det(a), rtol=1e-4, atol=1e-5,
        )

    def test_cholesky_qr_svd(self):
        a = np.random.rand(4, 3).astype(np.float32)
        spd = a.T @ a + 3 * np.eye(3, dtype=np.float32)
        L = paddle.tensor.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(
            L.numpy() @ L.numpy().T, spd, rtol=1e-4, atol=1e-4
        )
        q, r = paddle.tensor.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, rtol=1e-4, atol=1e-4)
        u, s, vt = paddle.tensor.svd(paddle.to_tensor(a))
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()) @ vt.numpy(), a, rtol=1e-3, atol=1e-4
        )


class TestRandom:
    def test_reproducible(self):
        paddle.seed(7)
        a = paddle.randn([3, 3]).numpy()
        paddle.seed(7)
        b = paddle.randn([3, 3]).numpy()
        np.testing.assert_array_equal(a, b)

    def test_uniform_range(self):
        x = paddle.uniform([1000], min=2.0, max=3.0).numpy()
        assert x.min() >= 2.0 and x.max() < 3.0

    def test_randint_randperm(self):
        x = paddle.randint(0, 10, [100]).numpy()
        assert x.min() >= 0 and x.max() < 10
        p = paddle.randperm(16).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(16))

    def test_bernoulli_multinomial(self):
        probs = paddle.full([1000], 0.3)
        b = paddle.bernoulli(probs).numpy()
        assert 0.1 < b.mean() < 0.5
        m = paddle.multinomial(paddle.to_tensor([0.1, 0.0, 0.9]), 50, replacement=True)
        assert set(np.unique(m.numpy())).issubset({0, 2})


class TestDtypePromotion:
    def test_defaults(self):
        assert paddle.to_tensor(1.5).dtype == paddle.float32
        assert paddle.to_tensor([1, 2]).dtype in (paddle.int32, paddle.int64)

    def test_mixed(self):
        x = paddle.to_tensor([1.0, 2.0])
        y = paddle.to_tensor([1, 2], dtype="int32")
        assert (x + y).dtype == paddle.float32
