"""Elastic manager tests (reference `test/collective/fleet` elastic tests)."""
import time

from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus


def test_membership_and_restart_detection():
    m = ElasticManager(job_id="jt", rank=0, np=2, heartbeat_interval=0.2,
                       timeout=2.0)
    w = ElasticManager(job_id="jt", rank=1, np=2, host="127.0.0.1",
                       port=m.port, is_master=False,
                       heartbeat_interval=0.2, timeout=2.0)
    try:
        assert m.wait_for_np(2, timeout=5)
        assert set(m.alive_nodes()) == {0, 1}
        w.exit()
        assert m.watch() == ElasticStatus.RESTART
        m.mark_completed()
        assert m.watch() == ElasticStatus.COMPLETED
    finally:
        m.exit()
