"""Elastic manager tests (reference `test/collective/fleet` elastic tests)."""
import time

from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

import pytest

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles


def test_membership_and_restart_detection():
    m = ElasticManager(job_id="jt", rank=0, np=2, heartbeat_interval=0.2,
                       timeout=2.0)
    w = ElasticManager(job_id="jt", rank=1, np=2, host="127.0.0.1",
                       port=m.port, is_master=False,
                       heartbeat_interval=0.2, timeout=2.0)
    try:
        assert m.wait_for_np(2, timeout=5)
        assert set(m.alive_nodes()) == {0, 1}
        w.exit()
        assert m.watch() == ElasticStatus.RESTART
        m.mark_completed()
        assert m.watch() == ElasticStatus.COMPLETED
    finally:
        m.exit()


def test_kill_relaunch_resume(tmp_path):
    """End-to-end elastic capability (VERDICT r2 item 6): a worker dies
    mid-training with a non-zero exit, the launcher's babysit loop
    relaunches the pod (reference `ElasticManager` watch->kill->relaunch,
    `fleet/elastic/manager.py:126`), and the relaunched worker resumes
    from its `distributed.checkpoint` — the full loss trajectory must
    EXACTLY match an uninterrupted run (loss continuity)."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    script = str(Path(__file__).parent / "elastic_train_script.py")
    repo = str(Path(__file__).parent.parent)

    def run(workdir, crash_at):
        env = dict(os.environ)
        env["ELASTIC_CRASH_AT"] = str(crash_at)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("PADDLE_RESTART_COUNT", None)
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--max_restart", "2", "--log_dir", str(workdir / "log"),
             script, str(workdir)],
            cwd=repo, env=env, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, (
            f"launcher rc={proc.returncode}\n{proc.stderr[-2000:]}\n"
            + "".join(open(p).read()[-2000:]
                      for p in (workdir / "log").glob("workerlog.*")))
        losses = {}
        for f in sorted(workdir.glob("losses_r*.json")):
            data = json.loads(f.read_text())
            for i, l in enumerate(data["losses"]):
                losses[data["start"] + i] = l
        return losses

    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    crash_dir = tmp_path / "crash"
    crash_dir.mkdir()
    clean = run(clean_dir, crash_at=-1)
    crashed = run(crash_dir, crash_at=3)

    assert sorted(clean) == sorted(crashed) == list(range(6))
    # crashed run must have resumed at step 3 (not restarted from zero)
    r1 = json.loads(
        next(crash_dir.glob("losses_r1.json")).read_text())
    assert r1["start"] == 3
    for step in range(6):
        assert abs(clean[step] - crashed[step]) < 1e-6, (
            step, clean[step], crashed[step])


def test_kill_relaunch_resume_reshard(tmp_path):
    """Resume-with-reshard end to end (ISSUE 8 satellite): the worker
    saves with params sharded over a 2-device "mp" axis, dies mid-run,
    and the relaunched life rebuilds on a 4-device layout and resumes
    from the resilience checkpoint — losses must stay on the same curve
    as an uninterrupted 2-device run (loss-equivalence; the checkpoint
    reshards on load, so no conversion step exists to get wrong)."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    script = str(Path(__file__).parent / "elastic_reshard_script.py")
    repo = str(Path(__file__).parent.parent)

    def run(workdir, crash_at, mesh0, mesh1):
        env = dict(os.environ)
        env["ELASTIC_CRASH_AT"] = str(crash_at)
        env["RESHARD_MESH"] = str(mesh0)
        env["RESHARD_MESH_R1"] = str(mesh1)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("PADDLE_RESTART_COUNT", None)
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--max_restart", "2", "--log_dir", str(workdir / "log"),
             script, str(workdir)],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=300)
        assert proc.returncode == 0, (
            f"launcher rc={proc.returncode}\n{proc.stderr[-2000:]}\n"
            + "".join(open(p).read()[-2000:]
                      for p in (workdir / "log").glob("workerlog.*")))
        losses = {}
        for f in sorted(workdir.glob("losses_r*.json")):
            data = json.loads(f.read_text())
            for i, l in enumerate(data["losses"]):
                losses[data["start"] + i] = l  # later lives overwrite
        return losses

    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    crash_dir = tmp_path / "crash"
    crash_dir.mkdir()
    clean = run(clean_dir, crash_at=-1, mesh0=2, mesh1=2)
    crashed = run(crash_dir, crash_at=3, mesh0=2, mesh1=4)

    assert sorted(clean) == sorted(crashed) == list(range(6))
    r1 = json.loads(
        next(crash_dir.glob("losses_r1.json")).read_text())
    assert r1["start"] == 3  # resumed, not restarted
    assert r1["mesh"] == 4   # ...on the DIFFERENT mesh layout
    for step in range(6):
        # same curve, not bit-identical: the mesh change legitimately
        # reorders reductions
        assert abs(clean[step] - crashed[step]) <= 1e-4 * max(
            1.0, abs(clean[step])), (step, clean[step], crashed[step])
