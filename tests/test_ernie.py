"""ERNIE 3.0 family tests — BASELINE config 5 (semi-auto shard + pipeline)
on the virtual 8-device CPU mesh (dp=2 x mp=2 x pp=2).

Mirrors the reference's auto-parallel GPT/ERNIE fixtures
(`test/auto_parallel/get_gpt_model.py`, ERNIE passes in
`python/paddle/distributed/passes/auto_parallel_pipeline.py` tests).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import PipelineParallel
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.models import (
    ErnieConfig, ErnieForPretraining, ErnieForPretrainingPipe,
    ErnieForSequenceClassification,
)

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles


@pytest.fixture(scope="module", autouse=True)
def _mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2, "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _batch(cfg, b=4, s=16, mask_frac=0.2):
    rng = np.random.RandomState(0)
    ids = rng.randint(4, cfg.vocab_size, (b, s))
    mlm_labels = np.where(rng.rand(b, s) < mask_frac, ids, -100)
    lm_labels = ids.copy()
    return (pt.to_tensor(ids), pt.to_tensor(mlm_labels),
            pt.to_tensor(lm_labels))


class TestErnie:
    def test_forward_shapes_and_loss(self):
        cfg = ErnieConfig.tiny()
        model = ErnieForPretraining(cfg)
        ids, mlm, lm = _batch(cfg)
        mlm_logits, lm_logits = model(ids)
        assert mlm_logits.shape == [4, 16, cfg.vocab_size]
        assert lm_logits.shape == [4, 16, cfg.vocab_size]
        loss = model(ids, mlm_labels=mlm, lm_labels=lm)
        # two joint CE objectives at random init: each ~= ln(vocab)
        assert abs(float(loss.numpy()) - 2 * np.log(cfg.vocab_size)) < 1.5

    def test_branch_masks_differ(self):
        """NLG branch must be causal: flipping a late token changes an
        early NLU position but not an early NLG position."""
        cfg = ErnieConfig.tiny(hidden_dropout_prob=0.0,
                               attention_probs_dropout_prob=0.0)
        pt.seed(11)
        model = ErnieForPretraining(cfg)
        model.eval()
        ids, _, _ = _batch(cfg)
        mlm1, lm1 = model(ids)
        ids2 = ids.numpy().copy()
        ids2[:, -1] = (ids2[:, -1] + 1) % cfg.vocab_size
        mlm2, lm2 = model(pt.to_tensor(ids2))
        assert not np.allclose(mlm1.numpy()[:, 0], mlm2.numpy()[:, 0])
        np.testing.assert_allclose(lm1.numpy()[:, 0], lm2.numpy()[:, 0],
                                   atol=1e-5)

    def test_train_step_compiled_loss_decreases(self):
        cfg = ErnieConfig.tiny()
        model = ErnieForPretraining(cfg)
        opt = pt.optimizer.AdamW(
            learning_rate=1e-3, parameters=model.parameters(),
            grad_clip=pt.nn.ClipGradByGlobalNorm(1.0))
        step = TrainStep(model, opt,
                         lambda m, i, a, b: m(i, mlm_labels=a, lm_labels=b))
        ids, mlm, lm = _batch(cfg)
        losses = [float(step(ids, mlm, lm).numpy()) for _ in range(6)]
        assert losses[-1] < losses[0]
        assert step.compiled_count == 1

    def test_pipe_train_batch_nlg(self):
        # 2 trunk layers (1 per pp stage): the schedule/partition logic
        # under test is depth-independent, and the pipe compile bill is
        # the full suite's worst offender at 4 layers
        cfg = ErnieConfig.tiny(num_hidden_layers=2)
        m = ErnieForPretrainingPipe(cfg, task="nlg")
        assert m._pipelined and m._n_blocks == cfg.num_hidden_layers
        pp_model = fleet.distributed_model(m)
        assert isinstance(pp_model, PipelineParallel)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
        ids, _, lm = _batch(cfg)
        losses = [float(pp_model.train_batch((ids, lm), opt).numpy())
                  for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_pipe_train_batch_nlu(self):
        cfg = ErnieConfig.tiny(num_hidden_layers=2)
        m = ErnieForPretrainingPipe(cfg, task="nlu")
        pp_model = fleet.distributed_model(m)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
        ids, mlm, _ = _batch(cfg, mask_frac=0.5)
        losses = [float(pp_model.train_batch((ids, mlm), opt).numpy())
                  for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_sequence_classification(self):
        cfg = ErnieConfig.tiny()
        model = ErnieForSequenceClassification(cfg, num_classes=3)
        ids, _, _ = _batch(cfg)
        assert model(ids).shape == [4, 3]

    def test_10b_config_flops(self):
        cfg = ErnieConfig.ernie3_10b()
        assert cfg.hidden_size == 4096 and cfg.num_hidden_layers == 48
        assert cfg.task_hidden_size == 768
        shell = ErnieForPretraining.__new__(ErnieForPretraining)
        shell.config = cfg
        per_tok = ErnieForPretraining.flops_per_token(shell, 2048)
        # trunk dominates: 6 * 2 * N_trunk params is the right ballpark
        n_trunk = cfg.num_hidden_layers * (
            4 * cfg.hidden_size ** 2
            + 2 * cfg.hidden_size * cfg.intermediate_size)
        assert per_tok > 6 * n_trunk


def test_engine_semi_auto_finetune(tmp_path):
    """Semi-auto: the Engine shards data-parallel over the mesh and GSPMD
    propagates the model's mp annotations (BASELINE config 5's strategy on
    the non-pipe model)."""
    from paddle_tpu.distributed import auto_parallel as ap

    cfg = ErnieConfig.tiny(num_hidden_layers=2, num_task_layers=1)
    model = ErnieForSequenceClassification(cfg, num_classes=2)
    opt = pt.optimizer.AdamW(learning_rate=5e-4,
                             parameters=model.parameters())

    class DS(pt.io.Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            ids = rng.randint(4, cfg.vocab_size, (16,))
            return ids.astype(np.int64), np.array([i % 2], np.int64)

    eng = ap.Engine(model=model, loss=pt.nn.CrossEntropyLoss(),
                    optimizer=opt)
    hist = eng.fit(DS(), batch_size=8, epochs=8, log_freq=4)
    assert hist[-1] < hist[0]
