"""paddle.geometric + paddle.signal parity tests.

Reference vectors from the docstrings/examples in
`python/paddle/geometric/message_passing/send_recv.py` and
`python/paddle/signal.py`; gradient checks via finite differences.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric, signal


class TestSegment:
    def test_segment_sum(self):
        data = paddle.to_tensor(
            np.asarray([[1., 2., 3.], [3., 2., 1.], [4., 5., 6.]], "float32"))
        ids = paddle.to_tensor(np.asarray([0, 0, 1], "int32"))
        out = geometric.segment_sum(data, ids)
        np.testing.assert_allclose(
            out.numpy(), [[4., 4., 4.], [4., 5., 6.]])

    def test_segment_mean_min_max(self):
        data = paddle.to_tensor(
            np.asarray([[1., 2., 3.], [3., 2., 1.], [4., 5., 6.]], "float32"))
        ids = paddle.to_tensor(np.asarray([0, 0, 1], "int32"))
        np.testing.assert_allclose(
            geometric.segment_mean(data, ids).numpy(),
            [[2., 2., 2.], [4., 5., 6.]])
        np.testing.assert_allclose(
            geometric.segment_min(data, ids).numpy(),
            [[1., 2., 1.], [4., 5., 6.]])
        np.testing.assert_allclose(
            geometric.segment_max(data, ids).numpy(),
            [[3., 2., 3.], [4., 5., 6.]])

    def test_empty_segment_zero_filled(self):
        data = paddle.to_tensor(np.asarray([[1., 5.]], "float32"))
        ids = paddle.to_tensor(np.asarray([2], "int32"))
        out = geometric.segment_max(data, ids)
        np.testing.assert_allclose(
            out.numpy(), [[0., 0.], [0., 0.], [1., 5.]])

    def test_segment_sum_grad(self):
        data = paddle.to_tensor(
            np.asarray([[1., 2.], [3., 4.], [5., 6.]], "float32"),
            stop_gradient=False)
        ids = paddle.to_tensor(np.asarray([0, 1, 1], "int32"))
        out = geometric.segment_sum(data, ids)
        out.sum().backward()
        np.testing.assert_allclose(data.grad.numpy(), np.ones((3, 2)))


class TestSendRecv:
    def _xsd(self):
        x = paddle.to_tensor(
            np.asarray([[0., 2., 3.], [1., 4., 5.], [2., 6., 7.]], "float32"))
        src = paddle.to_tensor(np.asarray([0, 1, 2, 0], "int32"))
        dst = paddle.to_tensor(np.asarray([1, 2, 1, 0], "int32"))
        return x, src, dst

    def test_send_u_recv_sum_reference_example(self):
        x, src, dst = self._xsd()
        out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
        np.testing.assert_allclose(
            out.numpy(), [[0., 2., 3.], [2., 8., 10.], [1., 4., 5.]])

    def test_send_u_recv_mean_max_min(self):
        x, src, dst = self._xsd()
        np.testing.assert_allclose(
            geometric.send_u_recv(x, src, dst, reduce_op="mean").numpy(),
            [[0., 2., 3.], [1., 4., 5.], [1., 4., 5.]])
        np.testing.assert_allclose(
            geometric.send_u_recv(x, src, dst, reduce_op="max").numpy(),
            [[0., 2., 3.], [2., 6., 7.], [1., 4., 5.]])
        np.testing.assert_allclose(
            geometric.send_u_recv(x, src, dst, reduce_op="min").numpy(),
            [[0., 2., 3.], [0., 2., 3.], [1., 4., 5.]])

    def test_send_u_recv_out_size(self):
        x, src, dst = self._xsd()
        out = geometric.send_u_recv(x, src, dst, out_size=5)
        assert out.shape == [5, 3]
        np.testing.assert_allclose(out.numpy()[3:], np.zeros((2, 3)))

    def test_send_ue_recv(self):
        x, src, dst = self._xsd()
        y = paddle.to_tensor(np.asarray([1., 1., 1., 1.], "float32"))
        out = geometric.send_ue_recv(x, y, src, dst, "add", "sum")
        np.testing.assert_allclose(
            out.numpy(), [[1., 3., 4.], [4., 10., 12.], [2., 5., 6.]])

    def test_send_uv(self):
        x, src, dst = self._xsd()
        out = geometric.send_uv(x, x, src, dst, message_op="add")
        np.testing.assert_allclose(
            out.numpy(),
            [[1., 6., 8.], [3., 10., 12.], [3., 10., 12.], [0., 4., 6.]])

    def test_send_u_recv_grad(self):
        x, src, dst = self._xsd()
        x.stop_gradient = False
        geometric.send_u_recv(x, src, dst).sum().backward()
        # node 0 feeds 2 edges, nodes 1/2 one each
        np.testing.assert_allclose(
            x.grad.numpy(), [[2., 2., 2.], [1., 1., 1.], [1., 1., 1.]])

    def test_bad_ops_raise(self):
        x, src, dst = self._xsd()
        with pytest.raises(ValueError):
            geometric.send_u_recv(x, src, dst, reduce_op="prod")
        with pytest.raises(ValueError):
            geometric.send_uv(x, x, src, dst, message_op="pow")


class TestReindexSampling:
    def test_reindex_graph_reference_example(self):
        x = paddle.to_tensor(np.asarray([0, 1, 2], "int64"))
        neighbors = paddle.to_tensor(
            np.asarray([8, 9, 0, 4, 7, 6, 7], "int64"))
        count = paddle.to_tensor(np.asarray([2, 3, 2], "int32"))
        src, dst, nodes = geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
        np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
        np.testing.assert_array_equal(nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6])

    def test_sample_neighbors(self):
        # CSC: node i's neighbors are row[colptr[i]:colptr[i+1]]
        row = np.asarray([1, 2, 3, 0, 2, 0, 1], "int64")
        colptr = np.asarray([0, 3, 5, 7, 7], "int64")
        nb, cnt = geometric.sample_neighbors(
            row, colptr, np.asarray([0, 2, 3], "int64"), sample_size=2)
        assert list(cnt.numpy()) == [2, 2, 0]
        assert set(nb.numpy()[:2]) <= {1, 2, 3}
        assert set(nb.numpy()[2:4]) <= {0, 1}

    def test_weighted_sample_respects_support(self):
        row = np.asarray([1, 2, 3], "int64")
        colptr = np.asarray([0, 3], "int64")
        w = np.asarray([0.0, 0.0, 100.0], "float32")
        nb, cnt = geometric.weighted_sample_neighbors(
            row, colptr, w, np.asarray([0], "int64"), sample_size=1)
        assert list(cnt.numpy()) == [1]
        assert nb.numpy()[0] == 3  # only positive-weight neighbor


class TestSignal:
    def test_frame_axis_minus1(self):
        x = paddle.to_tensor(np.arange(8, dtype="float32"))
        y = signal.frame(x, frame_length=4, hop_length=2, axis=-1)
        np.testing.assert_allclose(
            y.numpy(),
            [[0, 2, 4], [1, 3, 5], [2, 4, 6], [3, 5, 7]])

    def test_frame_axis_0(self):
        x = paddle.to_tensor(np.arange(8, dtype="float32"))
        y = signal.frame(x, frame_length=4, hop_length=2, axis=0)
        np.testing.assert_allclose(
            y.numpy(), [[0, 1, 2, 3], [2, 3, 4, 5], [4, 5, 6, 7]])

    def test_frame_batched(self):
        x = paddle.to_tensor(
            np.arange(16, dtype="float32").reshape(2, 8))
        y = signal.frame(x, 4, 2, axis=-1)
        assert y.shape == [2, 4, 3]

    def test_overlap_add_inverts_frame_nonoverlap(self):
        x = paddle.to_tensor(np.arange(8, dtype="float32"))
        y = signal.frame(x, frame_length=4, hop_length=4, axis=-1)
        back = signal.overlap_add(y, hop_length=4, axis=-1)
        np.testing.assert_allclose(back.numpy(), x.numpy())

    def test_overlap_add_overlap_counts(self):
        ones = paddle.to_tensor(np.ones((4, 3), "float32"))  # [fl, n]
        out = signal.overlap_add(ones, hop_length=2, axis=-1)
        np.testing.assert_allclose(
            out.numpy(), [1, 1, 2, 2, 2, 2, 1, 1])

    def test_stft_matches_numpy(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal(128).astype("float32")
        spec = signal.stft(paddle.to_tensor(x), n_fft=32, hop_length=16,
                           center=False).numpy()
        n = 1 + (128 - 32) // 16
        ref = np.stack([np.fft.rfft(x[i * 16:i * 16 + 32]) for i in range(n)],
                       axis=-1)
        np.testing.assert_allclose(spec, ref, atol=1e-4)

    def test_stft_istft_roundtrip(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal(256).astype("float32")
        win = np.hanning(64).astype("float32")
        spec = signal.stft(paddle.to_tensor(x), n_fft=64, hop_length=16,
                           window=paddle.to_tensor(win), center=True)
        back = signal.istft(spec, n_fft=64, hop_length=16,
                            window=paddle.to_tensor(win), center=True,
                            length=256)
        np.testing.assert_allclose(back.numpy(), x, atol=1e-3)

    def test_frame_grad(self):
        x = paddle.to_tensor(np.arange(8, dtype="float32"),
                             stop_gradient=False)
        signal.frame(x, 4, 2, axis=-1).sum().backward()
        # element i participates in (number of frames covering i)
        np.testing.assert_allclose(
            x.grad.numpy(), [1, 1, 2, 2, 2, 2, 1, 1])

    def test_errors(self):
        x = paddle.to_tensor(np.arange(8, dtype="float32"))
        with pytest.raises(ValueError):
            signal.frame(x, 4, 0)
        with pytest.raises(ValueError):
            signal.frame(x, 9, 2)
        with pytest.raises(ValueError):
            signal.frame(x, 4, 2, axis=1)
