"""`paddle_tpu.fft` parity tests vs numpy.fft (OpTest-style numeric parity,
reference `test/fft/test_fft.py`)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import fft


def _x(shape=(4, 16), complex_=False, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(*shape).astype(np.float32)
    if complex_:
        a = (a + 1j * rng.randn(*shape)).astype(np.complex64)
    return a


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_fft_ifft_roundtrip(norm):
    a = _x(complex_=True)
    out = fft.fft(pt.to_tensor(a), norm=norm)
    np.testing.assert_allclose(out.numpy(), np.fft.fft(a, norm=norm),
                               rtol=1e-4, atol=1e-4)
    back = fft.ifft(out, norm=norm)
    np.testing.assert_allclose(back.numpy(), a, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("fn,nfn", [
    ("rfft", np.fft.rfft), ("hfft", np.fft.hfft),
])
def test_real_family(fn, nfn):
    a = _x() if fn == "rfft" else _x((4, 9), complex_=True)
    out = getattr(fft, fn)(pt.to_tensor(a))
    np.testing.assert_allclose(out.numpy(), nfn(a), rtol=1e-3, atol=1e-3)


def test_irfft_ihfft():
    a = _x((4, 9), complex_=True)
    np.testing.assert_allclose(fft.irfft(pt.to_tensor(a)).numpy(),
                               np.fft.irfft(a), rtol=1e-4, atol=1e-4)
    r = _x((4, 16))
    np.testing.assert_allclose(fft.ihfft(pt.to_tensor(r)).numpy(),
                               np.fft.ihfft(r), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_fft2_fftn(norm):
    a = _x((3, 8, 8), complex_=True)
    np.testing.assert_allclose(
        fft.fft2(pt.to_tensor(a), norm=norm).numpy(),
        np.fft.fft2(a, norm=norm), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(
        fft.fftn(pt.to_tensor(a), norm=norm).numpy(),
        np.fft.fftn(a, norm=norm), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(
        fft.rfftn(pt.to_tensor(a.real.copy()), norm=norm).numpy(),
        np.fft.rfftn(a.real, norm=norm), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(
        fft.irfftn(pt.to_tensor(np.fft.rfftn(a.real)), norm=norm).numpy(),
        np.fft.irfftn(np.fft.rfftn(a.real), norm=norm), rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_hfftn_matches_hfft_1d(norm):
    # hfftn/ihfftn are hand-normalized (jnp lacks them); pin to numpy's 1d
    a = _x((9,), complex_=True)
    np.testing.assert_allclose(
        fft.hfftn(pt.to_tensor(a), norm=norm).numpy(),
        np.fft.hfft(a, norm=norm), rtol=1e-3, atol=1e-3)
    r = _x((16,))
    np.testing.assert_allclose(
        fft.ihfftn(pt.to_tensor(r), norm=norm).numpy(),
        np.fft.ihfft(r, norm=norm), rtol=1e-4, atol=1e-4)


def test_helpers():
    np.testing.assert_allclose(fft.fftfreq(8, 0.5).numpy(),
                               np.fft.fftfreq(8, 0.5), atol=1e-6)
    np.testing.assert_allclose(fft.rfftfreq(8, 0.5).numpy(),
                               np.fft.rfftfreq(8, 0.5), atol=1e-6)
    a = _x((4, 8))
    np.testing.assert_allclose(fft.fftshift(pt.to_tensor(a)).numpy(),
                               np.fft.fftshift(a), atol=0)
    np.testing.assert_allclose(fft.ifftshift(pt.to_tensor(a)).numpy(),
                               np.fft.ifftshift(a), atol=0)


def test_norm_validation():
    with pytest.raises(ValueError, match="norm"):
        fft.fft(pt.to_tensor(_x()), norm="bogus")


def test_rfft_gradient():
    # grads flow through the op path (the reference implements conjugate
    # rules by hand; jax.vjp supplies them here)
    x = pt.to_tensor(_x((8,)), stop_gradient=False)
    y = fft.rfft(x)
    loss = (y.real() ** 2 + y.imag() ** 2).sum()
    loss.backward()
    g = x.grad.numpy()
    assert np.all(np.isfinite(g)) and np.abs(g).max() > 0


def test_namespace_attr():
    assert pt.fft.fft is fft.fft
