"""Subprocess body for the two-process exec-cache warm-start proof
(tests/test_exec_cache.py).

Runs a small deterministic TrainStep for two steps with the AOT
executable cache armed (``PT_EXEC_CACHE`` in the environment, set by the
parent) and the monitor on, then prints ONE JSON line with the losses,
the post-step parameter digest, and the monitor/cache counters — the
parent asserts a cold process compiles+serializes and a warm process
deserializes with zero fresh XLA compiles and bitwise-identical numbers.
"""
import hashlib
import json
import os
import sys

import jax

# the host sitecustomize pins jax_platforms; the env var alone is
# overridden (CLAUDE.md) — force CPU via config like tests/conftest.py
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import monitor, nn  # noqa: E402
from paddle_tpu.jit import exec_cache  # noqa: E402
from paddle_tpu.jit.train_step import TrainStep  # noqa: E402


class TinyModel(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 8)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def main():
    monitor.enable()
    pt.seed(1234)
    np.random.seed(1234)
    model = TinyModel()
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = TrainStep(model, opt, lambda m, x, y: ((m(x) - y) ** 2).mean())
    x = pt.to_tensor(np.random.RandomState(7).randn(4, 8).astype("float32"))
    y = pt.to_tensor(np.random.RandomState(8).randn(4, 8).astype("float32"))
    losses = [float(step(x, y).numpy()) for _ in range(2)]
    # bitwise digest of every post-step param: the cold-vs-warm identity
    # proof must cover the executable's real outputs, not a rounded loss
    h = hashlib.sha256()
    for p in model.parameters():
        h.update(np.asarray(p.numpy()).tobytes())
    snap = monitor.snapshot()
    print(json.dumps({
        "losses": losses,
        "param_digest": h.hexdigest(),
        "counters": snap.get("counters", {}),
        "exec_cache": exec_cache.stats(),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
