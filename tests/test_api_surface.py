"""Top-level API surface parity: every name in the reference's
`python/paddle/__init__.py` __all__ must exist on paddle_tpu, and the
round-3 additions behave (inplace module fns, math long tail, places,
static-mode flags, compat shims)."""
import ast
import os

import numpy as np
import pytest

import paddle_tpu as pt

_REF = "/root/reference/python/paddle/__init__.py"


def _ref_all(path):
    tree = ast.parse(open(path).read())
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for e in node.value.elts:
                        try:
                            names.append(ast.literal_eval(e))
                        except Exception:  # noqa: BLE001 — computed entry
                            pass
    return set(names)


@pytest.mark.skipif(not os.path.exists(_REF), reason="reference not mounted")
def test_reference_top_level_all_covered():
    names = _ref_all(_REF)
    assert names, "failed to parse reference __all__"
    missing = [n for n in sorted(names) if not hasattr(pt, n)]
    assert not missing, f"missing top-level names: {missing}"


_R = "/root/reference/python/paddle/"


@pytest.mark.skipif(not os.path.exists(_R), reason="reference not mounted")
def test_every_namespace_all_covered():
    """Reference __all__ of every major sub-namespace resolves here."""
    pairs = [
        ("optimizer/__init__.py", lambda: pt.optimizer),
        ("optimizer/lr.py", lambda: pt.optimizer.lr),
        ("io/__init__.py", lambda: pt.io),
        ("metric/__init__.py", lambda: pt.metric),
        ("amp/__init__.py", lambda: pt.amp),
        ("autograd/__init__.py", lambda: pt.autograd),
        ("jit/__init__.py", lambda: pt.jit),
        ("distribution/__init__.py", lambda: pt.distribution),
        ("vision/__init__.py", lambda: pt.vision),
        ("vision/transforms/__init__.py", lambda: pt.vision.transforms),
        ("vision/ops.py", lambda: pt.vision.ops),
        ("signal.py", lambda: pt.signal),
        ("fft.py", lambda: pt.fft),
        ("distributed/__init__.py", lambda: pt.distributed),
        ("distributed/fleet/__init__.py", lambda: pt.distributed.fleet),
        ("sparse/__init__.py", lambda: pt.sparse),
        ("static/__init__.py", lambda: pt.static),
        ("incubate/__init__.py", lambda: pt.incubate),
        ("text/__init__.py", lambda: pt.text),
        ("audio/__init__.py", lambda: pt.audio),
        ("geometric/__init__.py", lambda: pt.geometric),
        ("nn/__init__.py", lambda: pt.nn),
        ("nn/functional/__init__.py", lambda: pt.nn.functional),
        ("linalg.py", lambda: pt.linalg),
        ("nn/initializer/__init__.py", lambda: pt.nn.initializer),
        ("nn/utils/__init__.py", lambda: pt.nn.utils),
        ("profiler/__init__.py", lambda: pt.profiler),
        ("incubate/nn/__init__.py", lambda: pt.incubate.nn),
        ("sparse/nn/__init__.py", lambda: pt.sparse.nn),
        ("distribution/transform.py",
         lambda: pt.distribution.transform),
        ("vision/datasets/__init__.py", lambda: pt.vision.datasets),
        ("utils/__init__.py", lambda: pt.utils),
        ("distributed/fleet/utils/__init__.py",
         lambda: pt.distributed.fleet.utils),
        ("audio/functional/__init__.py", lambda: pt.audio.functional),
        ("quantization/__init__.py", lambda: pt.quantization),
    ]
    problems = {}
    for rel, get in pairs:
        obj = get()
        miss = sorted(n for n in _ref_all(_R + rel) if not hasattr(obj, n))
        if miss:
            problems[rel] = miss
    assert not problems, f"missing namespace members: {problems}"


@pytest.mark.skipif(not os.path.exists(_R), reason="reference not mounted")
def test_tensor_method_surface_covered():
    """Every name in the reference's tensor_method_func registry exists
    on a Tensor instance."""
    tree = ast.parse(open(_R + "tensor/__init__.py").read())
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in (
                        "tensor_method_func", "magic_method_func"):
                    for e in node.value.elts:
                        try:
                            v = ast.literal_eval(e)
                        except Exception:  # noqa: BLE001
                            continue
                        if isinstance(v, str):
                            names.append(v)
    assert names
    t = pt.to_tensor(np.ones((2, 2), np.float32))
    missing = sorted(n for n in set(names) if not hasattr(t, n))
    assert not missing, f"missing Tensor methods: {missing}"


def test_inplace_tensor_methods_behave():
    x = pt.to_tensor(np.array([1.5, 2.5], np.float32))
    assert x.log_() is x
    np.testing.assert_allclose(x.numpy(), np.log([1.5, 2.5]), rtol=1e-6)
    y = pt.to_tensor(np.array([4.0, 9.0], np.float32))
    y.pow_(0.5)
    np.testing.assert_allclose(y.numpy(), [2.0, 3.0], rtol=1e-6)
    t = pt.to_tensor(np.ones((2, 2), np.float32))
    assert t.is_floating_point() and not t.is_complex()
    assert int(t.rank().numpy()) == 2
    assert t.create_parameter([3, 3]).is_parameter


class TestNewMathOps:
    def test_inplace_module_fns(self):
        x = pt.to_tensor(np.array([3.0, -1.0], np.float32))
        y = pt.sin_(x)
        assert y is x
        np.testing.assert_allclose(x.numpy(), np.sin([3.0, -1.0]),
                                   atol=1e-6)
        z = pt.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        pt.tril_(z)
        assert z.numpy()[0, 1] == 0.0

    def test_frexp_trapezoid(self):
        m, e = pt.frexp(pt.to_tensor(np.array([8.0, 0.75], np.float32)))
        np.testing.assert_allclose(m.numpy(), [0.5, 0.75])
        assert e.numpy().tolist() == [4, 0]
        y = pt.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        assert float(pt.trapezoid(y).numpy()) == 4.0
        assert float(pt.trapezoid(y, dx=2.0).numpy()) == 8.0
        xs = pt.to_tensor(np.array([0.0, 1.0, 3.0], np.float32))
        assert float(pt.trapezoid(y, x=xs).numpy()) == 6.5
        np.testing.assert_allclose(
            pt.cumulative_trapezoid(y).numpy(), [1.5, 4.0])

    def test_sgn_vander(self):
        s = pt.sgn(pt.to_tensor(np.array([-2.0, 0.0, 5.0], np.float32)))
        assert s.numpy().tolist() == [-1.0, 0.0, 1.0]
        v = pt.vander(pt.to_tensor(np.array([2.0, 3.0], np.float32)), n=3)
        np.testing.assert_allclose(v.numpy(), [[4, 2, 1], [9, 3, 1]])
        vi = pt.vander(pt.to_tensor(np.array([2.0], np.float32)), n=3,
                       increasing=True)
        np.testing.assert_allclose(vi.numpy(), [[1, 2, 4]])

    def test_take_modes(self):
        x = pt.to_tensor(np.arange(6).reshape(2, 3))
        idx = pt.to_tensor(np.array([0, 5, -1]))
        assert pt.take(x, idx).numpy().tolist() == [0, 5, 5]
        assert pt.take(x, pt.to_tensor(np.array([7])),
                       mode="wrap").numpy().tolist() == [1]
        assert pt.take(x, pt.to_tensor(np.array([7])),
                       mode="clip").numpy().tolist() == [5]
        with pytest.raises(ValueError):
            pt.take(x, idx, mode="bogus")

    def test_unflatten_reverse(self):
        x = pt.to_tensor(np.arange(12).reshape(2, 6))
        assert pt.unflatten(x, 1, [2, 3]).shape == [2, 2, 3]
        assert pt.unflatten(x, 1, [-1, 2]).shape == [2, 3, 2]
        with pytest.raises(ValueError):
            pt.unflatten(x, 1, [-1, -1])
        r = pt.reverse(pt.to_tensor(np.array([1, 2, 3])), axis=0)
        assert r.numpy().tolist() == [3, 2, 1]

    def test_cdist(self):
        a = pt.to_tensor(np.zeros((2, 3), np.float32))
        b = pt.to_tensor(np.ones((4, 3), np.float32))
        c = pt.cdist(a, b)
        assert c.shape == [2, 4]
        np.testing.assert_allclose(c.numpy(), np.sqrt(3.0), rtol=1e-6)
        c1 = pt.cdist(a, b, p=1.0)
        np.testing.assert_allclose(c1.numpy(), 3.0, rtol=1e-6)
        cinf = pt.cdist(a, b, p=float("inf"))
        np.testing.assert_allclose(cinf.numpy(), 1.0, rtol=1e-6)


class TestCompatShims:
    def test_places(self):
        assert str(pt.CPUPlace()) == "cpu"
        assert str(pt.CUDAPlace(0)) == "tpu:0"
        assert pt.CPUPlace() == pt.CPUPlace() != pt.TPUPlace()

    def test_static_mode_flags(self):
        assert pt.in_dynamic_mode()
        pt.enable_static()
        try:
            assert pt.in_static_mode() and not pt.in_dynamic_mode()
        finally:
            pt.disable_static()
        assert pt.in_dynamic_mode()

    def test_shape_rank_tolist(self):
        x = pt.to_tensor(np.zeros((2, 3), np.float32))
        assert pt.shape(x).numpy().tolist() == [2, 3]
        assert int(pt.rank(x).numpy()) == 2
        assert pt.tolist(pt.to_tensor(np.array([1, 2]))) == [1, 2]

    def test_dtype_introspection(self):
        x = pt.to_tensor(np.zeros(2, np.float32))
        assert pt.is_floating_point(x)
        assert not pt.is_integer(x)
        assert not pt.is_complex(x)
        assert pt.finfo(pt.float32).max > 1e38
        assert pt.iinfo(pt.int32).max == 2**31 - 1

    def test_create_parameter_and_attr(self):
        p = pt.create_parameter([4, 3])
        assert p.shape == [4, 3] and p.is_parameter
        b = pt.create_parameter([4], is_bias=True)
        assert float(np.abs(b.numpy()).sum()) == 0.0
        attr = pt.ParamAttr(learning_rate=0.5)
        p2 = pt.create_parameter([2], attr=attr)
        assert p2.optimize_attr["learning_rate"] == 0.5

    def test_rng_state_alias(self):
        s = pt.get_cuda_rng_state()
        pt.set_cuda_rng_state(s)

    def test_check_shape_and_lazy_guard(self):
        pt.check_shape([1, 2, 3])
        with pytest.raises(TypeError):
            pt.check_shape([1, "x"])
        with pt.LazyGuard():
            net = pt.nn.Linear(2, 2)
        assert net.weight.shape == [2, 2]
        pt.disable_signal_handler()
