"""Optimizer + LR scheduler tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _quadratic_problem():
    # minimize ||w - target||^2
    w = paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False)
    target = paddle.to_tensor(np.array([1.0, -2.0, 3.0, 0.5], np.float32))
    return w, target


def _run(opt_cls, steps=200, **kwargs):
    w, target = _quadratic_problem()
    opt = opt_cls(parameters=[w], **kwargs)
    for _ in range(steps):
        loss = ((w - target) * (w - target)).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w, target, opt


class TestOptimizers:
    def test_sgd_converges(self):
        w, t, _ = _run(optimizer.SGD, learning_rate=0.1)
        np.testing.assert_allclose(w.numpy(), t.numpy(), atol=1e-3)

    def test_momentum_converges(self):
        w, t, _ = _run(optimizer.Momentum, learning_rate=0.05, momentum=0.9)
        np.testing.assert_allclose(w.numpy(), t.numpy(), atol=1e-3)

    def test_adam_converges(self):
        w, t, _ = _run(optimizer.Adam, learning_rate=0.1, steps=300)
        np.testing.assert_allclose(w.numpy(), t.numpy(), atol=1e-2)

    def test_adamw_converges_and_decays(self):
        w, t, _ = _run(optimizer.AdamW, learning_rate=0.1, weight_decay=0.0, steps=300)
        np.testing.assert_allclose(w.numpy(), t.numpy(), atol=1e-2)
        # decay pulls weights below target
        w2, t2, _ = _run(optimizer.AdamW, learning_rate=0.1, weight_decay=0.5, steps=300)
        assert np.abs(w2.numpy()).sum() < np.abs(t2.numpy()).sum()

    @pytest.mark.parametrize("cls,kw", [
        (optimizer.Adagrad, {"learning_rate": 0.5}),
        (optimizer.Adamax, {"learning_rate": 0.1}),
        (optimizer.RMSProp, {"learning_rate": 0.05}),
        (optimizer.Lamb, {"learning_rate": 0.05, "lamb_weight_decay": 0.0}),
        (optimizer.Adadelta, {"learning_rate": 5.0}),
    ])
    def test_other_optimizers_descend(self, cls, kw):
        w, t, _ = _run(cls, steps=300, **kw)
        final_loss = ((w.numpy() - t.numpy()) ** 2).sum()
        assert final_loss < 2.0  # started at 14.25

    def test_grad_clip_in_step(self):
        w, t, _ = _run(
            optimizer.SGD, learning_rate=0.1, steps=300,
            grad_clip=nn.ClipGradByGlobalNorm(0.5),
        )
        np.testing.assert_allclose(w.numpy(), t.numpy(), atol=1e-2)

    def test_weight_decay_l2(self):
        w, t, _ = _run(
            optimizer.SGD, learning_rate=0.1, weight_decay=10.0, steps=100
        )
        # fixed point of grad 2(w-t) + 10w = 0  =>  w = t/6
        np.testing.assert_allclose(w.numpy(), t.numpy() / 6, atol=1e-3)

    def test_state_dict_roundtrip(self):
        w, t, opt = _run(optimizer.Adam, learning_rate=0.1, steps=5)
        sd = opt.state_dict()
        w2, _ = _quadratic_problem()
        opt2 = optimizer.Adam(learning_rate=0.1, parameters=[w2])
        opt2.set_state_dict(sd)
        assert opt2._global_step == 5
        key = next(iter(opt._accumulators))
        key2 = next(iter(opt2._accumulators))
        np.testing.assert_allclose(
            np.asarray(opt._accumulators[key]["moment1"]),
            np.asarray(opt2._accumulators[key2]["moment1"]),
        )

    def test_multi_precision_bf16(self):
        w = paddle.to_tensor(
            np.ones(4, np.float32), dtype="bfloat16", stop_gradient=False
        )
        opt = optimizer.AdamW(
            learning_rate=0.01, parameters=[w], multi_precision=True
        )
        (w * w).sum().backward()
        opt.step()
        assert w.dtype == paddle.bfloat16
        assert id(w) in opt._master_weights


class TestLRSchedulers:
    def test_scheduler_drives_optimizer(self):
        sched = optimizer.lr.StepDecay(0.1, step_size=10, gamma=0.1)
        w, _ = _quadratic_problem()
        opt = optimizer.SGD(learning_rate=sched, parameters=[w])
        assert opt.get_lr() == pytest.approx(0.1)
        for _ in range(10):
            sched.step()
        assert opt.get_lr() == pytest.approx(0.01)

    def test_cosine(self):
        s = optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert s() == pytest.approx(1.0)
        for _ in range(10):
            s.step()
        assert s() == pytest.approx(0.0, abs=1e-6)

    def test_warmup(self):
        s = optimizer.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
        vals = []
        for _ in range(12):
            vals.append(s())
            s.step()
        assert vals[0] == pytest.approx(0.0)
        assert vals[5] == pytest.approx(0.05)
        assert vals[11] == pytest.approx(0.1)

    def test_piecewise(self):
        s = optimizer.lr.PiecewiseDecay([3, 6], [1.0, 0.5, 0.1])
        vals = []
        for _ in range(8):
            vals.append(s())
            s.step()
        assert vals[0] == 1.0 and vals[4] == 0.5 and vals[7] == 0.1

    def test_noam(self):
        s = optimizer.lr.NoamDecay(d_model=512, warmup_steps=100, learning_rate=1.0)
        peak_region = []
        for _ in range(200):
            s.step()
            peak_region.append(s())
        assert np.argmax(peak_region) == pytest.approx(99, abs=2)

    def test_reduce_on_plateau(self):
        s = optimizer.lr.ReduceOnPlateau(1.0, patience=2, factor=0.5)
        for _ in range(5):
            s.step(metrics=1.0)  # no improvement
        assert s() == pytest.approx(0.5)
