"""Training goodput plane tests (ISSUE 20 — monitor/goodput +
monitor/watchdog, docs/OBSERVABILITY.md "Training goodput plane").

Tier-1 proof of the tentpole invariants:

* the ledger telescopes EXACTLY — ``sum(buckets.values()) == wall_s``
  in float, through a real `fit()` with async stepping, a checkpoint,
  a skipped NaN batch, and a resume, with the monitor off AND on (the
  on-path is a subprocess so import-time enablement is real);
* ``PT_GOODPUT=0`` runs no ledger and produces byte-identical losses
  (the always-on plane never perturbs the numerics);
* the hang watchdog trips on a stalled step, writes a blackbox
  artifact naming the hung step with all-thread stacks, stands down
  during quiet buckets, and feeds ``/healthz`` liveness.
"""
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.monitor import exporter, goodput, watchdog

REPO = str(Path(__file__).parent.parent)


def _build(seed=0, lr=5e-2):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, nn.MSELoss())
    return model


def _dataset(n=48, poison_batch=None, batch=8):
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n, 8)).astype("float32")
    ys = xs @ rng.standard_normal((8, 1)).astype("float32")
    if poison_batch is not None:
        xs[poison_batch * batch:(poison_batch + 1) * batch] = np.nan
    return [(xs[i], ys[i]) for i in range(n)]


class _GrabLedger(paddle.callbacks.Callback):
    """Captures the run's active ledger (fit owns it; deactivation
    happens after on_train_end, so the hook window sees it armed)."""

    def __init__(self):
        self.ledger = None
        self.active_during_run = None

    def on_train_batch_end(self, step, logs=None):
        if self.ledger is None:
            self.ledger = goodput.active()
            self.active_during_run = self.ledger is not None


def _assert_telescopes(snap):
    assert set(snap["buckets"]) == set(goodput.BUCKETS)
    total = 0.0
    for b in goodput.BUCKETS:  # canonical order: the exactness contract
        total += snap["buckets"][b]
    assert total == snap["wall_s"], (total, snap["wall_s"])
    assert all(v >= 0.0 for v in snap["buckets"].values()), snap["buckets"]


# -- ledger unit -------------------------------------------------------------

def test_ledger_telescopes_exactly():
    led = goodput.Ledger()
    led.enter("productive_step")
    time.sleep(0.01)
    led.exit()
    led.enter("input_wait")
    led.exit()
    snap = led.snapshot()
    _assert_telescopes(snap)
    assert snap["steps"] == 1
    assert snap["buckets"]["productive_step"] >= 0.01
    assert snap["goodput_frac"] == (snap["buckets"]["productive_step"]
                                    / snap["wall_s"])


def test_ledger_nested_and_retro_charge_never_double_count():
    led = goodput.Ledger()
    led.enter("productive_step")
    led.enter("checkpoint_save_blocking")  # nested: parent is displaced
    time.sleep(0.01)
    led.exit()
    time.sleep(0.01)
    # part of the step's elapsed was really a compile: retro-charge it
    # out of the open frame (the TrainStep bracket's shape)
    led.charge("compile", 0.005)
    led.exit()
    snap = led.snapshot()
    _assert_telescopes(snap)
    assert snap["buckets"]["checkpoint_save_blocking"] >= 0.01
    assert snap["buckets"]["compile"] == 0.005
    assert snap["buckets"]["productive_step"] > 0.0  # exclusive remainder
    assert snap["steps"] == 1  # charge() never bumps the step count


def test_ledger_reclassify_exit_counts_nan_step():
    led = goodput.Ledger()
    led.enter("productive_step")
    led.exit("nan_replay_or_skip")  # the skip path re-labels the frame
    snap = led.snapshot()
    _assert_telescopes(snap)
    assert snap["steps"] == 0 and snap["nan_steps"] == 1


def test_ledger_rejects_unknown_bucket():
    led = goodput.Ledger()
    with pytest.raises(ValueError, match="unknown goodput bucket"):
        led.enter("coffee_break")


def test_open_frame_snapshot_still_telescopes():
    led = goodput.Ledger()
    led.enter("productive_step")
    time.sleep(0.005)
    snap = led.snapshot()  # mid-frame: exclusive elapsed-so-far counts
    _assert_telescopes(snap)
    assert snap["buckets"]["productive_step"] > 0.0
    led.exit()


# -- fit integration (monitor OFF: the always-on path) -----------------------

def test_fit_ledger_invariant_with_ckpt_nan_and_resume(tmp_path):
    """The acceptance fit: checkpointing + a poisoned batch under
    nan_policy='skip' + a resume — every phase lands in its bucket and
    the telescoping equality stays exact."""
    ck = str(tmp_path / "ck")
    grab = _GrabLedger()
    m = _build()
    m.fit(_dataset(poison_batch=3), batch_size=8, epochs=1, shuffle=False,
          verbose=0, log_freq=1, nan_policy="skip", checkpoint_dir=ck,
          callbacks=[grab])
    assert grab.active_during_run
    snap = grab.ledger.snapshot()
    _assert_telescopes(snap)
    assert snap["steps"] == 5          # 6 batches, one skipped
    assert snap["nan_steps"] == 1
    assert snap["buckets"]["productive_step"] > 0.0
    # the skipped batch's replay + discarded dispatch was re-labelled
    assert snap["buckets"]["nan_replay_or_skip"] > 0.0
    # fit ends with the ledger retired and every slot disarmed
    assert goodput.active() is None
    from paddle_tpu.jit import train_step as ts
    assert ts._goodput is None

    grab2 = _GrabLedger()
    m2 = _build(seed=1)
    # epochs=2: the checkpoint covers epoch 0, so the resume actually
    # trains (a fully-covered resume would run zero batches)
    m2.fit(_dataset(), batch_size=8, epochs=2, shuffle=False, verbose=0,
           resume_from=ck, callbacks=[grab2])
    snap2 = grab2.ledger.snapshot()
    _assert_telescopes(snap2)
    # restore-from-checkpoint time is its own bucket, not "other"
    assert snap2["buckets"]["restore_resume"] > 0.0
    assert goodput.active() is None


def test_goodput_off_no_ledger_and_byte_identical_losses(monkeypatch):
    """PT_GOODPUT=0 is the escape hatch: no ledger is created — and the
    ledgered run's losses are byte-identical to the unledgered run's
    (the plane is clock arithmetic only; it never touches the step)."""

    def _losses():
        sink = []

        class Cap(paddle.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                sink.append(float(logs["loss"]))

        m = _build()
        m.fit(_dataset(), batch_size=8, epochs=2, shuffle=False,
              verbose=0, log_freq=1, callbacks=[Cap()])
        return sink

    monkeypatch.setenv("PT_GOODPUT", "0")
    grab = _GrabLedger()
    m = _build()
    m.fit(_dataset(), batch_size=8, epochs=1, shuffle=False, verbose=0,
          callbacks=[grab])
    assert grab.active_during_run is False  # no ledger ever armed
    off = _losses()
    monkeypatch.setenv("PT_GOODPUT", "1")
    on = _losses()
    assert off == on  # float-exact, not approx: the plane is inert


# -- fit integration (monitor ON: run_end carries the account) ---------------

_MONITOR_ON_SCRIPT = r"""
import json, os, sys
os.environ["PT_MONITOR"] = "1"
os.environ["PT_MONITOR_SINK"] = sys.argv[1]
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn

paddle.seed(0)
net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
opt = paddle.optimizer.AdamW(learning_rate=5e-2,
                             parameters=net.parameters())
model = paddle.Model(net)
model.prepare(opt, nn.MSELoss())
rng = np.random.default_rng(0)
xs = rng.standard_normal((48, 8)).astype("float32")
ys = xs @ rng.standard_normal((8, 1)).astype("float32")
xs[24:32] = np.nan  # poison batch 3
ds = [(xs[i], ys[i]) for i in range(48)]
model.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
          log_freq=1, nan_policy="skip",
          checkpoint_dir=sys.argv[2])
print("FIT_OK")
"""


@pytest.mark.slow
def test_run_end_goodput_monitor_on(tmp_path):
    """With the monitor armed the StepLogger's run_end line embeds the
    final ledger account — and JSON round-trips floats exactly, so the
    telescoping proof survives the sink."""
    sink = str(tmp_path / "run.jsonl")
    proc = subprocess.run(
        [sys.executable, "-c", _MONITOR_ON_SCRIPT, sink,
         str(tmp_path / "ck")],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0 and "FIT_OK" in proc.stdout, (
        proc.stdout[-2000:], proc.stderr[-2000:])
    end = None
    with open(sink) as f:
        for raw in f:
            try:
                line = json.loads(raw)
            except ValueError:
                continue
            if line.get("event") == "run_end":
                end = line
    assert end is not None and "goodput" in end, end
    snap = end["goodput"]
    _assert_telescopes(snap)
    assert snap["steps"] == 5 and snap["nan_steps"] == 1
    # the compile bracket retro-charged the first step's XLA compile
    assert snap["buckets"]["compile"] > 0.0
    # checkpoint_dir forced at least the final blocking save cost
    assert snap["buckets"]["checkpoint_save_blocking"] > 0.0
    # the shared step EMA landed as the monitor/step_ms_ema gauge
    gauges = (end.get("totals") or {}).get("gauges") or {}
    assert gauges.get("monitor/step_ms_ema", 0) > 0.0


# -- hang watchdog -----------------------------------------------------------

@pytest.fixture
def _quiet_run():
    """A fresh EMA world + an active ledger, torn down afterwards."""
    goodput.reset_run()
    led = goodput.activate(goodput.Ledger())
    yield led
    goodput.deactivate(led)
    goodput.reset_run()


def test_watchdog_trips_and_blackbox_names_hung_step(
        tmp_path, monkeypatch, _quiet_run):
    art = str(tmp_path / "hang_blackbox.json")
    monkeypatch.setenv("PT_HANG_BLACKBOX", art)
    goodput.observe_step_ms(10.0, step=3)
    wd = watchdog.Watchdog(factor=1.0, min_s=0.05, policy="warn",
                           poll_s=0.02).start()
    try:
        deadline = time.time() + 5.0
        while wd._trips == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert wd._trips >= 1
        st = wd.state()
        assert st["hung"] is True and st["last_step"] == 3
        # /healthz carries the liveness verdict (satellite 2)
        h = exporter.health()
        assert h["hung"] is True
        assert h["last_step_age_s"] is not None
        assert h["degraded"] is True
        # the artifact parses and names the hung step with stacks
        with open(art) as f:
            hb = json.loads(f.read())
        assert hb["reason"] == "hang_watchdog"
        trip = hb["state"]["training_watchdog"]["last_trip"]
        assert trip["hung_step"] == 4
        assert trip["last_completed_step"] == 3
        assert trip["stacks"]  # all-thread dump: the diagnosable part
        # a completed step re-arms the latch (trip count is monotone,
        # the hung flag is not)
        goodput.observe_step_ms(10.0, step=4)
        deadline = time.time() + 5.0
        while wd.state()["hung"] and time.time() < deadline:
            time.sleep(0.02)
        assert wd.state()["hung"] is False or wd._trips >= 2
    finally:
        wd.stop()
    assert watchdog.state() == {}  # stopped: /healthz drops the fields


def test_watchdog_stands_down_during_quiet_buckets(_quiet_run):
    """A first-signature compile can dwarf any EMA — the judge must not
    call a legitimate slow phase a hang."""
    goodput.observe_step_ms(10.0, step=1)
    _quiet_run.enter("compile")
    wd = watchdog.Watchdog(factor=1.0, min_s=0.05, policy="warn",
                           poll_s=0.02).start()
    try:
        time.sleep(0.4)
        assert wd._trips == 0
    finally:
        wd.stop()
        _quiet_run.exit()


def test_watchdog_no_judgement_before_first_step(_quiet_run):
    wd = watchdog.Watchdog(factor=1.0, min_s=0.01, policy="warn",
                           poll_s=0.02)
    assert wd.deadline_s() is None  # no EMA: nothing to judge against
    wd.start()
    try:
        time.sleep(0.2)
        assert wd._trips == 0
    finally:
        wd.stop()


def test_watchdog_policy_off_never_starts():
    wd = watchdog.Watchdog(policy="off")
    assert wd.start() is wd
    assert wd._thread is None
    wd.stop()


def test_healthz_without_watchdog_has_no_liveness_fields():
    h = exporter.health()
    assert "hung" not in h and "last_step_age_s" not in h
