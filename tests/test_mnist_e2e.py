"""BASELINE config 1: MNIST-style MLP, eager dygraph training end-to-end.

Mirrors the reference's classic `test/book` end-to-end model tests: train a
small model on synthetic data, assert the loss actually drops and accuracy
rises — the full Python API -> op layer -> XLA path.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
import paddle_tpu.nn.functional as F

import pytest

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles


def make_synthetic_mnist(n=512, seed=0):
    """Linearly-separable-ish 10-class synthetic 28x28 data."""
    rng = np.random.RandomState(seed)
    protos = rng.rand(10, 784).astype(np.float32)
    labels = rng.randint(0, 10, n)
    imgs = protos[labels] + 0.1 * rng.randn(n, 784).astype(np.float32)
    return imgs.astype(np.float32), labels.astype(np.int64)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 64)
        self.fc3 = nn.Linear(64, 10)

    def forward(self, x):
        x = F.relu(self.fc1(x))
        x = F.relu(self.fc2(x))
        return self.fc3(x)


def test_mnist_mlp_trains():
    paddle.seed(0)
    xs, ys = make_synthetic_mnist()
    model = MLP()
    opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()

    losses = []
    bs = 64
    for epoch in range(3):
        perm = np.random.permutation(len(xs))
        for i in range(0, len(xs), bs):
            idx = perm[i:i + bs]
            x = paddle.to_tensor(xs[idx])
            y = paddle.to_tensor(ys[idx])
            logits = model(x)
            loss = loss_fn(logits, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))

    assert losses[0] > 1.5          # started near log(10)
    assert losses[-1] < 0.2         # learned

    # accuracy
    model.eval()
    with paddle.no_grad():
        logits = model(paddle.to_tensor(xs))
        preds = paddle.argmax(logits, axis=1).numpy()
    acc = (preds == ys).mean()
    assert acc > 0.95


def test_conv_classifier_trains():
    paddle.seed(0)
    rng = np.random.RandomState(1)
    # 2-class toy: horizontal vs vertical stripes 8x8
    n = 128
    xs = np.zeros((n, 1, 8, 8), np.float32)
    ys = rng.randint(0, 2, n)
    for i, y in enumerate(ys):
        if y == 0:
            xs[i, 0, ::2, :] = 1.0
        else:
            xs[i, 0, :, ::2] = 1.0
    xs += 0.05 * rng.randn(*xs.shape).astype(np.float32)

    model = nn.Sequential(
        nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2),
        nn.Flatten(), nn.Linear(4 * 4 * 4, 2),
    )
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    for _ in range(30):
        logits = model(paddle.to_tensor(xs))
        loss = F.cross_entropy(logits, paddle.to_tensor(ys))
        loss.backward()
        opt.step()
        opt.clear_grad()
    preds = paddle.argmax(model(paddle.to_tensor(xs)), axis=1).numpy()
    assert (preds == ys).mean() > 0.95
