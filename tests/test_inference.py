"""Inference Predictor tests (reference `test/inference` +
`analysis_predictor_tester.cc` behavior at the Python API surface)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.jit import InputSpec, save


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(pt.tanh(self.fc1(x)))


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    pt.seed(0)
    net = Net()
    path = str(tmp_path_factory.mktemp("infer") / "net")
    save(net, path, input_spec=[InputSpec([2, 8], "float32", "x")])
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    ref = net(pt.to_tensor(x)).numpy()
    return path, x, ref


def test_predictor_direct_run(saved_model):
    path, x, ref = saved_model
    pred = create_predictor(Config(path))
    outs = pred.run([x])
    assert len(outs) == 1
    np.testing.assert_allclose(outs[0], ref, atol=1e-5)


def test_predictor_handle_api(saved_model):
    path, x, ref = saved_model
    pred = create_predictor(Config(path + ".pdmodel"))
    names = pred.get_input_names()
    assert names == ["x"]
    h = pred.get_input_handle("x")
    h.copy_from_cpu(x)
    assert pred.run() is True
    out_names = pred.get_output_names()
    assert len(out_names) == 1
    out = pred.get_output_handle(out_names[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_predictor_precision_and_donation(saved_model):
    path, x, ref = saved_model
    cfg = Config(path)
    cfg.set_precision("bfloat16")
    cfg.enable_memory_optim()
    pred = create_predictor(cfg)
    out = pred.run([x])[0]
    # bf16 squeeze: close but not bit-equal
    np.testing.assert_allclose(out, ref, atol=0.1)
    assert np.abs(out - ref).max() > 0 or np.allclose(out, ref)


def test_predictor_device_cpu(saved_model):
    path, x, ref = saved_model
    cfg = Config(path)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    np.testing.assert_allclose(pred.run([x])[0], ref, atol=1e-5)


def test_config_summary(saved_model):
    path, _, _ = saved_model
    cfg = Config(path)
    cfg.set_precision("bfloat16")
    assert "bfloat16" in cfg.summary()
    assert cfg.prog_file().endswith(".pdmodel")
