"""Live telemetry plane tests (ISSUE 19 — monitor/live + monitor/exporter).

The acceptance spine:

- **Sketch honesty** — the fixed-boundary log-bucket quantile sketch
  agrees with exact numpy percentiles within one bucket width (5%)
  across distributions, and merging is EXACT (associative, order-free:
  any split of a stream merges back to byte-identical bucket state) —
  the property that makes fleet aggregation equality, not approximation.
- **Endpoint smoke** — a live engine scraped over real HTTP: /metrics
  parses as OpenMetrics (TYPE-declared families, # EOF), /healthz
  reports per-replica dead/alive through an injected replica death,
  /statusz renders.
- **SLO watchdog** — fast+slow burn-rate windows fire a breach on a
  sustained violation: monitor/slo_breach counter, StepLogger
  `slo_breach` event lines, `Callback.on_slo_breach` via the hapi
  bridge, run_end live snapshot.
- **Worker-mode parity** — the same seeded trace through an in-process
  fleet and a worker (subprocess) fleet yields byte-equal /metrics
  serving+router counter totals and live sketch counts (mergeable
  sketches + the router's per-step telemetry pulls), identical tokens,
  and the in-process fleet still compiles exactly 3 programs with the
  live plane armed.
- **Zero-overhead off** — `_live` slots are None in the tier-1 default
  environment (the parametrized audit in test_memory_numerics.py
  covers every INSTRUMENTED_MODULES entry), the exporter starts no
  thread at import, and enable/disable round-trips the slots.
"""
import json
import os
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import StepLogger
from paddle_tpu.monitor import exporter
from paddle_tpu.monitor import live
from paddle_tpu.monitor.live import GAMMA, QuantileSketch

GEOM = dict(max_lanes=3, block_size=4, prefill_chunk=8, max_seq_len=32)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    m.eval()
    return m


@pytest.fixture
def armed(monkeypatch):
    """Live plane enabled with clean state; restores disabled-off."""
    was = live.enabled()
    live.enable()
    live.reset()
    yield live
    live.reset()
    if not was:
        live.disable()


def _mixed_workload(vocab, rng, n):
    out = []
    for _ in range(n):
        plen, new = int(rng.randint(3, 13)), int(rng.randint(4, 10))
        out.append((rng.randint(0, vocab, (plen,)).astype(np.int32), new))
    return out


# -- the sketch ---------------------------------------------------------------

class TestQuantileSketch:
    @pytest.mark.parametrize("dist", ["uniform", "lognormal", "expo"])
    def test_quantiles_within_one_bucket_of_numpy(self, dist):
        rng = np.random.RandomState(0)
        vals = {
            "uniform": rng.uniform(0.5, 400.0, 4000),
            "lognormal": rng.lognormal(3.0, 1.2, 4000),
            "expo": rng.exponential(25.0, 4000),
        }[dist]
        sk = QuantileSketch()
        for v in vals:
            sk.observe(float(v))
        for p in (0.50, 0.90, 0.99):
            exact = float(np.percentile(vals, p * 100))
            approx = sk.quantile(p)
            # upper-boundary nearest-rank: within one bucket width of
            # the exact rank value (+ slack for numpy's interpolation)
            assert abs(approx - exact) / exact <= (GAMMA - 1) + 0.01, \
                f"{dist} p{p}: exact={exact} sketch={approx}"

    def test_merge_is_exact_and_associative(self):
        rng = np.random.RandomState(1)
        vals = rng.lognormal(2.0, 1.0, 3000)
        whole = QuantileSketch()
        parts = [QuantileSketch() for _ in range(3)]
        for i, v in enumerate(vals):
            whole.observe(float(v))
            parts[i % 3].observe(float(v))
        ab_c = parts[0].copy().merge(parts[1]).merge(parts[2])
        c_ab = parts[2].copy().merge(parts[0]).merge(parts[1])
        assert ab_c.to_dict() == c_ab.to_dict() == whole.to_dict()

    def test_json_roundtrip(self):
        sk = QuantileSketch()
        for v in (0.01, 1.0, 5.5, 1e6, 0.0, -3.0):
            sk.observe(v)
        rt = QuantileSketch.from_dict(json.loads(json.dumps(sk.to_dict())))
        assert rt.to_dict() == sk.to_dict()
        assert rt.quantile(0.5) == sk.quantile(0.5)

    def test_zero_and_empty(self):
        sk = QuantileSketch()
        assert sk.quantile(0.99) == 0.0
        sk.observe(0.0)
        sk.observe(-1.0)
        assert sk.count == 2 and sk.zero == 2
        assert sk.quantile(0.99) == 0.0

    def test_count_over_never_undercounts(self):
        sk = QuantileSketch()
        vals = [1.0, 5.0, 10.0, 50.0, 100.0, 200.0]
        for v in vals:
            sk.observe(v)
        for t in (4.0, 40.0, 99.0):
            exact = sum(1 for v in vals if v > t)
            assert sk.count_over(t) >= exact
            # ...and overshoots by at most the threshold's own bucket
            assert sk.count_over(t) <= sum(
                1 for v in vals if v > t / GAMMA)


# -- zero-overhead-off + enable/disable wiring --------------------------------

class TestZeroOverheadOff:
    def test_import_time_inert(self):
        """Tier-1 default env: live disabled, no exporter thread, and
        the serving slots are None (no live callable reachable)."""
        import paddle_tpu.serving.engine as eng
        import paddle_tpu.serving.router as rtr

        assert not live.enabled()
        assert exporter.port() is None
        assert eng._live is None
        assert rtr._live is None

    def test_enable_wires_slots_and_disable_clears(self):
        import paddle_tpu.serving.engine as eng
        import paddle_tpu.serving.router as rtr

        live.enable()
        try:
            assert eng._live is live and rtr._live is live
            # arming live must NOT arm the monitor (independent planes)
            assert not monitor.enabled()
            assert eng._monitor is None
        finally:
            live.disable()
        assert eng._live is None and rtr._live is None

    def test_live_slot_in_lint_contract(self):
        from paddle_tpu.analysis import lint

        assert "_live" in lint._SLOT_NAMES

    def test_slot_modules_in_audit_list(self):
        assert "paddle_tpu.serving.engine" in monitor.INSTRUMENTED_MODULES
        assert "paddle_tpu.serving.router" in monitor.INSTRUMENTED_MODULES


# -- the watchdog + breach plumbing -------------------------------------------

class TestSLOWatchdog:
    def _arm(self, monkeypatch, target="10"):
        monkeypatch.setenv("PT_SLO_TTFT_MS_P99", target)
        monkeypatch.setenv("PT_SLO_FAST_WINDOW", "2")
        monkeypatch.setenv("PT_SLO_SLOW_WINDOW", "4")
        live.enable()
        live.reset()  # re-reads the PT_SLO_* knobs

    def test_sustained_violation_fires_once_and_relatches(
            self, monkeypatch, armed):
        self._arm(monkeypatch)
        seen = []
        live.subscribe(seen.append)
        try:
            monitor.counter("monitor/slo_breach").reset()
            for _ in range(6):
                live.on_request_finished(50.0, 5.0, 1.0)  # 50ms >> 10ms
                live.on_engine_step()
            assert live.breach_count() == 1, "breach must latch, not spam"
            assert monitor.counter("monitor/slo_breach").value == 1
            assert seen and seen[0]["metric"] == "ttft_ms"
            assert seen[0]["burn_fast"] >= 14.0
            # recovery re-arms: healthy windows, then violations again
            for _ in range(6):
                live.on_request_finished(1.0, 1.0, 1.0)
                live.on_engine_step()
            for _ in range(6):
                live.on_request_finished(50.0, 5.0, 1.0)
                live.on_engine_step()
            assert live.breach_count() == 2
        finally:
            live.unsubscribe(seen.append)

    def test_no_target_no_breach(self, monkeypatch, armed):
        monkeypatch.delenv("PT_SLO_TTFT_MS_P99", raising=False)
        monkeypatch.delenv("PT_SLO_TPOT_MS_P99", raising=False)
        live.reset()
        for _ in range(20):
            live.on_request_finished(1e6, 1e6, 1.0)
            live.on_engine_step()
        assert live.breach_count() == 0

    def test_steplogger_writes_breach_events_and_run_end_snapshot(
            self, monkeypatch, armed, tmp_path):
        self._arm(monkeypatch)
        path = tmp_path / "steps.jsonl"
        log = StepLogger(str(path), meta={"source": "test"})
        for _ in range(4):
            live.on_request_finished(50.0, 5.0, 1.0)
            live.on_engine_step()
        log.log_step(loss=1.0)
        log.close()
        lines = [json.loads(ln) for ln in path.read_text().splitlines()]
        events = [ln for ln in lines if ln.get("event") == "slo_breach"]
        assert len(events) == 1
        assert events[0]["metric"] == "ttft_ms"
        assert events[0]["target_ms"] == 10.0
        end = lines[-1]
        assert end["event"] == "run_end"
        assert end["live"]["slo"]["breaches"] == 1
        assert end["live"]["sketches"]["ttft_ms"]["count"] == 4
        assert end["totals"]["counters"].get("monitor/slo_breach", 0) >= 1

    def test_callback_bridge_dispatches_on_slo_breach(
            self, monkeypatch, armed):
        from paddle_tpu.hapi.callbacks import (
            Callback, _SLOBridge, config_callbacks,
        )

        self._arm(monkeypatch)

        class Recorder(Callback):
            def __init__(self):
                self.breaches = []

            def on_slo_breach(self, breach=None):
                self.breaches.append(breach)

        rec = Recorder()
        bridge = _SLOBridge([rec])
        bridge.on_train_begin()
        try:
            for _ in range(4):
                live.on_request_finished(50.0, 5.0, 1.0)
                live.on_engine_step()
        finally:
            bridge.on_train_end()
        assert len(rec.breaches) == 1
        assert rec.breaches[0]["metric"] == "ttft_ms"
        # after unsubscribe the chain goes quiet
        for _ in range(8):
            live.on_request_finished(1.0, 1.0, 1.0)
            live.on_engine_step()
        for _ in range(4):
            live.on_request_finished(50.0, 5.0, 1.0)
            live.on_engine_step()
        assert len(rec.breaches) == 1
        # config_callbacks wires the bridge into every train chain
        lst = config_callbacks(callbacks=[rec], verbose=0)
        assert any(isinstance(c, _SLOBridge) for c in lst.callbacks)
        # the base class carries the hook (observation-only default)
        assert Callback().on_slo_breach({"metric": "x"}) is None


# -- live engine + endpoint smoke ---------------------------------------------

def _scrape(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.read().decode()


def _parse_openmetrics(body):
    """Minimal OpenMetrics check: returns {family: [sample lines]};
    asserts every sample rides a TYPE-declared family and the
    exposition terminates with # EOF."""
    lines = body.splitlines()
    assert lines[-1] == "# EOF"
    families, cur = {}, None
    for ln in lines[:-1]:
        if ln.startswith("# TYPE "):
            cur = ln.split()[2]
            families[cur] = []
            continue
        assert cur is not None and ln.startswith(cur), ln
        name = ln.split("{")[0].split()[0]
        base = name
        for suffix in ("_total", "_count", "_sum"):
            if base.endswith(suffix):
                base = base[:-len(suffix)]
        assert base == cur or name == cur, ln
        float(ln.rsplit(" ", 1)[1])  # every value parses
        families[cur].append(ln)
    return families


def test_engine_endpoint_smoke(model, armed):
    """Scrape a live engine over real HTTP: sketches fed from the
    always-on attribution handoffs (PT_MONITOR stays OFF), OpenMetrics
    parses, statusz renders the engine's registered provider."""
    from paddle_tpu.serving import ServingConfig, ServingEngine

    assert not monitor.enabled()
    monitor.reset()  # stale registry state from earlier tests is noise here
    engine = ServingEngine(model, ServingConfig(**GEOM))
    work = _mixed_workload(model.config.vocab_size,
                           np.random.RandomState(4), 5)
    for i, (p, n) in enumerate(work):
        engine.submit(p, max_new_tokens=n, request_id=f"r{i}")
    outs = engine.run()
    assert len(outs) == len(work)

    port = exporter.start(0)
    assert port
    try:
        fams = _parse_openmetrics(_scrape(port, "/metrics"))
        assert "pt_live_ttft_ms" in fams
        count_line = [ln for ln in fams["pt_live_ttft_ms"]
                      if ln.startswith("pt_live_ttft_ms_count")][0]
        assert int(count_line.split()[-1]) == len(work)
        # monitor off -> no monitor counters in the exposition, but the
        # live plane is fully populated (the PT_MONITOR=0 contract)
        assert "pt_serving_admits" not in fams

        h = json.loads(_scrape(port, "/healthz"))
        assert h["ok"] and h["live_enabled"]
        assert h["slo_breaches"] == 0

        sz = _scrape(port, "/statusz")
        assert "paddle_tpu /statusz" in sz
        assert "serving_engine" in sz
        assert "ttft_ms" in sz
    finally:
        exporter.stop()
    assert exporter.port() is None


def test_healthz_reports_replica_death(model, armed, monkeypatch):
    """The liveness endpoint's first adversarial proof, in-test: a
    replica killed mid-trace shows up dead in /healthz (the soak
    driver's --router leg polls the same surface through a real kill)."""
    from paddle_tpu.serving import (
        RouterConfig, RouterEngine, ServingConfig,
    )

    router = RouterEngine(
        model, ServingConfig(**GEOM),
        RouterConfig(replicas=2, mode="inproc"))
    work = _mixed_workload(model.config.vocab_size,
                           np.random.RandomState(6), 4)
    for i, (p, n) in enumerate(work):
        router.submit(p, max_new_tokens=n, request_id=f"r{i}")
    router.step()

    h = exporter.health()
    assert [r for r in h["replicas"] if r["dead"]] == []

    def boom():
        raise RuntimeError("injected replica failure")

    monkeypatch.setattr(router._replicas[0]._engine, "step", boom)
    router.step()  # the killing step: dead must be visible right after
    h = exporter.health()
    assert h["dead_replicas"] == [0]
    dead = [r for r in h["replicas"] if r["dead"]]
    assert dead and "injected replica failure" in dead[0]["reason"]
    router.run()  # survivors finish the drained work
    assert router.counters["finished"] == len(work)


# -- worker-mode fleet parity -------------------------------------------------

def _parity_lines(body):
    """The mode-invariant subset of /metrics: serving+router counter
    totals and live sketch observation counts. Quantile/sum lines carry
    wall-clock latencies that legitimately differ between process
    shapes, and monitor HISTOGRAM counts (ring-percentile state) stay
    per-process — the live sketches are the fleet-mergeable replacement
    and ARE held to parity here."""
    keep = []
    for ln in body.splitlines():
        name = ln.split("{")[0].split()[0]
        if name.startswith(("pt_serving", "pt_router")) \
                and name.endswith("_total"):
            keep.append(ln)
        elif name.startswith("pt_live") and name.endswith("_count"):
            keep.append(ln)
    return keep


@pytest.mark.slow
def test_worker_fleet_metrics_parity(model, armed, tmp_path, monkeypatch):
    """THE fleet-aggregation proof: the same seeded trace through an
    in-process 2-replica fleet and a worker (subprocess) 2-replica
    fleet produces byte-equal /metrics counter totals + sketch counts,
    identical tokens — worker-mode replica telemetry is no longer lost.
    The in-process fleet still pays exactly 3 fresh compiles with the
    live plane armed."""
    from paddle_tpu.jit import exec_cache as ec
    from paddle_tpu.serving import (
        RouterConfig, RouterEngine, ServingConfig,
    )

    factory = tmp_path / "lt_factory.py"
    factory.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import paddle_tpu as pt\n"
        "from paddle_tpu.models.llama import LlamaConfig, "
        "LlamaForCausalLM\n"
        "def build():\n"
        "    pt.seed(0)\n"
        "    m = LlamaForCausalLM(LlamaConfig.tiny("
        "num_hidden_layers=2))\n"
        "    m.eval()\n"
        "    return m\n")
    monkeypatch.setenv("PYTHONPATH", str(tmp_path) + os.pathsep
                       + os.environ.get("PYTHONPATH", ""))
    # counters need the monitor in BOTH shapes: here and in the workers
    monkeypatch.setenv("PT_MONITOR", "1")
    was = monitor.enabled()
    monitor.enable()
    work = _mixed_workload(model.config.vocab_size,
                           np.random.RandomState(2), 6)

    def run_fleet(router):
        for i, (p, n) in enumerate(work):
            router.submit(p, max_new_tokens=n, request_id=f"r{i}")
        outs = router.run()
        body = exporter.render_metrics()
        return outs, _parity_lines(body)

    try:
        monitor.reset()
        live.reset()
        ec.enable(str(tmp_path / "cache"))
        ec.clear()
        try:
            inproc = RouterEngine(
                model, ServingConfig(**GEOM),
                RouterConfig(replicas=2, mode="inproc"))
            inproc.warmup()
            assert ec.stats()["misses"] == 3, \
                "live plane must not add compiles"
            outs_in, lines_in = run_fleet(inproc)
            assert ec.stats()["misses"] == 3, "live plane retraced!"
        finally:
            ec.disable()
            ec.clear()

        monitor.reset()
        live.reset()
        worker = RouterEngine(
            config=GEOM,
            router_config=RouterConfig(
                replicas=2, mode="worker",
                worker_factory="lt_factory:build"))
        try:
            outs_wk, lines_wk = run_fleet(worker)
        finally:
            worker.close()
    finally:
        monitor.reset()
        if not was:
            monitor.disable()

    assert set(outs_in) == set(outs_wk)
    for rid in outs_in:
        np.testing.assert_array_equal(outs_in[rid], outs_wk[rid])
    assert lines_in, "parity subset must not be empty"
    assert any(ln.startswith("pt_live_ttft_ms_count") for ln in lines_in)
    assert any(ln.startswith("pt_serving_decoded_tokens_total")
               for ln in lines_in)
    assert lines_in == lines_wk, (
        "worker-mode fleet /metrics diverged from in-process:\n"
        + "\n".join(sorted(set(lines_in) ^ set(lines_wk))))
