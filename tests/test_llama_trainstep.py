"""Flagship model + compiled train step + MoE tests (virtual 8-device mesh).

Mirrors the reference's hybrid-parallel model tests
(`test/collective/fleet/hybrid_parallel_mp_model.py` etc.) with the tiny
Llama config as the GPT-fixture equivalent (`test/auto_parallel/get_gpt_model.py`).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, LlamaForCausalLMPipe

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles


@pytest.fixture(scope="module", autouse=True)
def _mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _batch(cfg, b=4, s=16):
    ids = pt.to_tensor(np.random.randint(0, cfg.vocab_size, (b, s)))
    labels = pt.to_tensor(np.random.randint(0, cfg.vocab_size, (b, s)))
    return ids, labels


class TestLlama:
    def test_forward_loss_sane(self):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        ids, labels = _batch(cfg)
        logits = model(ids)
        assert logits.shape == [4, 16, cfg.vocab_size]
        loss = model(ids, labels)
        # random init CE ~= ln(vocab)
        assert abs(float(loss.numpy()) - np.log(cfg.vocab_size)) < 0.7

    def test_gqa(self):
        cfg = LlamaConfig.tiny(num_key_value_heads=2)
        model = LlamaForCausalLM(cfg)
        ids, _ = _batch(cfg)
        assert model(ids).shape == [4, 16, cfg.vocab_size]

    def test_sequence_parallel_matches_dense(self):
        pt.seed(7)
        cfg = LlamaConfig.tiny(sequence_parallel=False)
        m1 = LlamaForCausalLM(cfg)
        pt.seed(7)
        cfg2 = LlamaConfig.tiny(sequence_parallel=True)
        m2 = LlamaForCausalLM(cfg2)
        ids, _ = _batch(cfg)
        np.testing.assert_allclose(
            m1(ids).numpy(), m2(ids).numpy(), atol=2e-4)

    def test_train_step_compiled(self):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        opt = pt.optimizer.AdamW(
            learning_rate=1e-3, parameters=model.parameters(),
            grad_clip=pt.nn.ClipGradByGlobalNorm(1.0))
        step = TrainStep(model, opt, lambda m, i, l: m(i, l))
        ids, labels = _batch(cfg)
        losses = [float(step(ids, labels).numpy()) for _ in range(6)]
        assert losses[-1] < losses[0]
        assert step.compiled_count == 1  # no retrace across steps

    def test_train_step_matches_eager(self):
        """One compiled step == one eager (backward + opt.step) step."""
        ids = np.random.randint(0, 128, (2, 8))
        labels = np.random.randint(0, 128, (2, 8))

        def build():
            pt.seed(3)
            cfg = LlamaConfig.tiny(num_hidden_layers=2)
            m = LlamaForCausalLM(cfg)
            o = pt.optimizer.SGD(learning_rate=0.1,
                                 parameters=m.parameters())
            return m, o

        m1, o1 = build()
        loss1 = m1(pt.to_tensor(ids), pt.to_tensor(labels))
        loss1.backward()
        o1.step()
        o1.clear_grad()

        m2, o2 = build()
        step = TrainStep(m2, o2, lambda m, i, l: m(i, l))
        loss2 = step(pt.to_tensor(ids), pt.to_tensor(labels))
        np.testing.assert_allclose(float(loss1.numpy()),
                                   float(loss2.numpy()), atol=1e-5)
        for (n1, p1), (n2, p2) in zip(m1.named_parameters(),
                                      m2.named_parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), atol=1e-5,
                                       err_msg=n1)

    def test_bf16_multi_precision(self):
        cfg = LlamaConfig.tiny(dtype="bfloat16")
        model = LlamaForCausalLM(cfg)
        for p in model.parameters():
            p._data = p._data.astype("bfloat16")
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters(),
                                 multi_precision=True)
        step = TrainStep(model, opt, lambda m, i, l: m(i, l))
        ids, labels = _batch(cfg)
        losses = [float(step(ids, labels).numpy()) for _ in range(4)]
        assert losses[-1] < losses[0]
        assert str(np.dtype(model.parameters()[0].dtype)) == "bfloat16"


class TestMoE:
    def test_moe_forward_backward(self):
        from paddle_tpu.incubate.distributed.models.moe import MoELayer

        moe = MoELayer(16, 32, num_experts=4, top_k=2, expert_axis="dp")
        x = pt.to_tensor(np.random.randn(2, 8, 16).astype(np.float32),
                         stop_gradient=False)
        y = moe(x)
        assert y.shape == [2, 8, 16]
        (y.mean() + moe.aux_loss * 0.01).backward()
        assert moe.w_in.grad is not None and x.grad is not None

    def test_moe_capacity_drops_tokens(self):
        from paddle_tpu.incubate.distributed.models.moe import MoELayer

        # capacity so small most tokens drop -> output mostly zero rows
        moe = MoELayer(8, 16, num_experts=2, top_k=1, gate="switch",
                       capacity_factor=0.1, expert_axis="dp")
        x = pt.to_tensor(np.random.randn(2, 16, 8).astype(np.float32))
        y = moe(x)
        zero_rows = np.all(np.abs(y.numpy()) < 1e-7, axis=-1).sum()
        assert zero_rows > 0

    def test_moe_in_llama(self):
        cfg = LlamaConfig.tiny(moe_num_experts=4)
        model = LlamaForCausalLM(cfg)
        ids, labels = _batch(cfg)
        loss = model(ids, labels)
        assert np.isfinite(float(loss.numpy()))


class TestTrainStepStateSync:
    def test_optimizer_sees_compiled_state(self):
        import paddle_tpu.nn as nn

        m = nn.Linear(4, 4)
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
        step = TrainStep(m, opt, lambda mm, x, y: ((mm(x) - y) ** 2).mean())
        x = pt.to_tensor(np.random.randn(4, 4).astype(np.float32))
        y = pt.to_tensor(np.random.randn(4, 4).astype(np.float32))
        for _ in range(3):
            step(x, y)
        # state_dict-visible accumulators exist and carry the step count
        assert opt._global_step == 3
        st = opt._accumulators[id(m.weight)]
        assert "moment1" in st
        assert float(np.abs(np.asarray(st["moment1"])).sum()) > 0

    def test_compiled_resumes_from_eager_state(self):
        import paddle_tpu.nn as nn

        ids = np.random.randn(4, 4).astype(np.float32)
        tgt = np.random.randn(4, 4).astype(np.float32)

        def build():
            pt.seed(9)
            m = nn.Linear(4, 4)
            o = pt.optimizer.Adam(learning_rate=1e-2,
                                  parameters=m.parameters())
            return m, o

        # eager 2 steps then compiled 1 step
        m1, o1 = build()
        for _ in range(2):
            loss = ((m1(pt.to_tensor(ids)) - pt.to_tensor(tgt)) ** 2).mean()
            loss.backward()
            o1.step()
            o1.clear_grad()
        s1 = TrainStep(m1, o1, lambda mm, x, y: ((mm(x) - y) ** 2).mean())
        s1(pt.to_tensor(ids), pt.to_tensor(tgt))

        # eager 3 steps
        m2, o2 = build()
        for _ in range(3):
            loss = ((m2(pt.to_tensor(ids)) - pt.to_tensor(tgt)) ** 2).mean()
            loss.backward()
            o2.step()
            o2.clear_grad()
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                                   atol=1e-5)
