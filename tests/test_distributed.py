"""Distributed stack tests on the virtual 8-device CPU mesh.

Mirrors the reference's strategy of exercising distributed logic without a
real cluster (SURVEY.md §4: `test_dist_base.py`, fake custom-device plugin) —
here the fake cluster is `--xla_force_host_platform_device_count=8`.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


@pytest.fixture(scope="module", autouse=True)
def _mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()  # other test files run mesh-free


class TestMeshEnv:
    def test_degrees(self):
        e = dist.get_env()
        assert e.degree("dp") == 2 and e.degree("mp") == 4
        assert e.world_size == 8

    def test_hcg(self):
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_group().nranks == 4


class TestCollectives:
    def test_all_reduce_sharded(self):
        x = pt.to_tensor(np.arange(8, dtype=np.float32).reshape(2, 4))
        xs = dist.shard_tensor(x, spec=("dp", None))
        y = dist.all_reduce(xs, group=dist.new_group(axes="dp"))
        np.testing.assert_allclose(y.numpy(), [[4.0, 6.0, 8.0, 10.0]])

    def test_all_reduce_world_replicated(self):
        # replicated tensor: every participant holds the value -> x * nranks
        x = pt.to_tensor(np.ones((3,), np.float32))
        y = dist.all_reduce(x, group=dist.new_group(axes="mp"))
        np.testing.assert_allclose(y.numpy(), 4.0 * np.ones(3))

    def test_all_reduce_max(self):
        x = dist.shard_tensor(
            pt.to_tensor(np.array([[1.0], [5.0]], np.float32)), spec=("dp",))
        y = dist.all_reduce(x, op=dist.ReduceOp.MAX, group="dp")
        np.testing.assert_allclose(y.numpy(), [[5.0]])

    def test_all_gather(self):
        z = dist.all_gather(pt.to_tensor(np.ones((4, 2), np.float32)),
                            group=dist.new_group(axes="mp"))
        assert z.shape == [16, 2]

    def test_all_gather_list_form(self):
        out = []
        dist.all_gather(out, pt.to_tensor(np.ones((2, 2), np.float32)),
                        group="mp")
        assert len(out) == 4 and out[0].shape == [2, 2]

    def test_all_to_all(self):
        a = pt.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
        r = dist.all_to_all(a, group="mp", split_axis=1, concat_axis=0)
        assert r.shape == [8, 8]
        # global semantics: block transpose [mp, s/mp, :] -> [s/mp, mp, :]
        blocks = a.numpy().reshape(4, 2, 8)
        expect = np.concatenate(np.split(blocks, 4, axis=2), 1).reshape(8, 8)
        got_blocks = r.numpy()
        assert got_blocks.shape == expect.shape

    def test_reduce_scatter(self):
        rs = dist.reduce_scatter(pt.to_tensor(np.ones((8, 2), np.float32)),
                                 group="mp")
        assert rs.shape == [8, 2]
        np.testing.assert_allclose(rs.numpy()[0, 0], 4.0)

    def test_broadcast_scatter(self):
        x = dist.scatter(pt.to_tensor(np.ones((8, 2), np.float32)), group="dp")
        assert x.shape == [8, 2]
        y = dist.broadcast(x, group="dp")
        assert y.shape == [8, 2]

    def test_grad_through_shard(self):
        w = pt.to_tensor(np.ones((4, 4), np.float32), stop_gradient=False)
        out = dist.shard_tensor(w * 3.0, spec=("dp", "mp"))
        out.sum().backward()
        np.testing.assert_allclose(w.grad.numpy(), 3.0 * np.ones((4, 4)))

    def test_in_trace_psum(self):
        import jax
        from jax.sharding import PartitionSpec as P

        e = dist.get_env()

        def f(x):
            y = dist.all_reduce(pt.Tensor(x), group="mp")
            return y._data

        from paddle_tpu.framework.jax_compat import shard_map

        fn = shard_map(f, mesh=e.mesh, in_specs=P("mp"),
                       out_specs=P(), check_vma=False)
        res = jax.jit(fn)(np.ones((8,), np.float32))
        # out_spec P(): per-shard shape (8/4,) with the mp-sum values
        np.testing.assert_allclose(np.asarray(res), 4.0 * np.ones(2))


class TestMpLayers:
    def test_column_row_parity(self):
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
            ColumnParallelLinear, RowParallelLinear,
        )

        col = ColumnParallelLinear(16, 32, gather_output=False)
        row = RowParallelLinear(32, 16, input_is_parallel=True)
        assert tuple(col.weight._data.sharding.spec) == (None, "mp")
        assert tuple(row.weight._data.sharding.spec) == ("mp", None)

        x = pt.to_tensor(np.random.randn(4, 8, 16).astype(np.float32),
                         stop_gradient=False)
        y = row(col(x))
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(y.numpy(), ref, atol=1e-4)

        y.mean().backward()
        # grads inherit the weight sharding (ZeRO-free memory scaling)
        assert tuple(col.weight.grad._data.sharding.spec) == (None, "mp")

    def test_vocab_parallel_embedding(self):
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
            VocabParallelEmbedding,
        )

        emb = VocabParallelEmbedding(100, 16)
        ids = pt.to_tensor(np.random.randint(0, 100, (4, 8)))
        out = emb(ids)
        assert out.shape == [4, 8, 16]
        np.testing.assert_allclose(
            out.numpy(), emb.weight.numpy()[ids.numpy()], atol=1e-6)

    def test_parallel_cross_entropy(self):
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
            ParallelCrossEntropy,
        )

        pce = ParallelCrossEntropy()
        logits = pt.to_tensor(np.random.randn(4, 100).astype(np.float32),
                              stop_gradient=False)
        lbl = pt.to_tensor(np.random.randint(0, 100, (4, 1)))
        loss = pce(logits, lbl)
        x = logits.numpy()
        lse = np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True)) \
            + x.max(-1, keepdims=True)
        ref = lse - np.take_along_axis(x, lbl.numpy(), 1)
        np.testing.assert_allclose(loss.numpy(), ref, atol=1e-4)
        loss.sum().backward()
        assert logits.grad is not None

    def test_sequence_parallel_linears(self):
        from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear, ScatterOp,
        )

        col = ColumnSequenceParallelLinear(16, 32, gather_output=False)
        row = RowSequenceParallelLinear(32, 16, input_is_parallel=True)
        x = pt.to_tensor(np.random.randn(2, 8, 16).astype(np.float32),
                         stop_gradient=False)
        xs = ScatterOp.apply(x)
        assert tuple(xs._data.sharding.spec)[1] == "mp"
        y = row(col(xs))
        assert tuple(y._data.sharding.spec)[1] == "mp"  # seq-sharded exit
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(y.numpy(), ref, atol=1e-4)

    def test_rng_tracker(self):
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.random import (
            RNGStatesTracker,
        )
        from paddle_tpu.framework import random as rng

        tr = RNGStatesTracker()
        tr.add("a", 100)
        with tr.rng_state("a"):
            k1 = rng.next_key()
        with tr.rng_state("a"):
            k2 = rng.next_key()
        assert not np.array_equal(
            np.asarray(jax_key_data(k1)), np.asarray(jax_key_data(k2)))

    def test_data_parallel_wrapper(self):
        import paddle_tpu.nn as nn

        m = nn.Linear(8, 4)
        dp = dist.DataParallel(m)
        x = pt.to_tensor(np.random.randn(4, 8).astype(np.float32))
        y = dp(x)
        assert y.shape == [4, 4]
        np.testing.assert_allclose(
            y.numpy(), x.numpy() @ m.weight.numpy() + m.bias.numpy(),
            atol=1e-5)


def jax_key_data(k):
    import jax

    return jax.random.key_data(k)
