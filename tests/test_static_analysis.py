"""Invariant auditor (ISSUE 12): source lint + compiled-program audit.

Per-rule violation fixtures (each rule fires on a known-bad snippet and
stays silent on the repaired version), the tier-1 clean-tree gate, the
program-audit HLO fixtures (replicated-dp, dropped-donation,
host-callback — each producing exactly its expected finding), the
exec-cache sidecar round-trip, and the perf-guard ``--audit`` gate.
"""
import importlib.util
import json
import os
import subprocess
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.analysis import lint
from paddle_tpu.analysis import program_audit as pa

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return [f.rule for f in findings]


# -- tier 1: per-rule fixtures ----------------------------------------------

class TestPTL001DevicePutInTrace:
    BAD = (
        "import jax\n"
        "def make(mesh, spec):\n"
        "    def place(x):\n"
        "        return jax.device_put(x, spec)\n"
        "    return place\n"
    )
    REPAIRED = (
        "import jax\n"
        "def make(mesh, spec):\n"
        "    def place(x):\n"
        "        if isinstance(x, jax.core.Tracer):\n"
        "            return jax.lax.with_sharding_constraint(x, spec)\n"
        "        return jax.device_put(x, spec)\n"
        "    return place\n"
    )

    def test_fires_on_nested_device_put(self):
        fs = lint.lint_text("paddle_tpu/ops/fake_op.py", self.BAD)
        assert _rules(fs) == ["PTL001"]
        assert fs[0].line == 4

    def test_silent_on_tracer_branch_idiom(self):
        assert lint.lint_text("paddle_tpu/ops/fake_op.py",
                              self.REPAIRED) == []

    def test_fires_inside_forward(self):
        src = ("import jax\n"
               "class L:\n"
               "    def forward(self, x):\n"
               "        return jax.device_put(x, self.s)\n")
        assert _rules(lint.lint_text("paddle_tpu/nn/fake.py", src)) \
            == ["PTL001"]

    def test_silent_in_eager_method_and_out_of_scope(self):
        src = ("import jax\n"
               "class L:\n"
               "    def to(self, dev):\n"
               "        self._data = jax.device_put(self._data, dev)\n")
        assert lint.lint_text("paddle_tpu/nn/fake.py", src) == []
        # same nested pattern outside the trace-reachable roots is fine
        assert lint.lint_text("paddle_tpu/io/fake.py", self.BAD) == []


class TestPTL002BlockUntilReady:
    BAD = (
        "import time, jax\n"
        "def bench(f, x):\n"
        "    t0 = time.perf_counter()\n"
        "    jax.block_until_ready(f(x))\n"
        "    return time.perf_counter() - t0\n"
    )
    REPAIRED = (
        "import time\n"
        "from paddle_tpu.utils.timing import device_sync\n"
        "def bench(f, x):\n"
        "    t0 = time.perf_counter()\n"
        "    device_sync(f(x))\n"
        "    return time.perf_counter() - t0\n"
    )

    def test_error_under_a_timer(self):
        fs = lint.lint_text("tools/fake_bench.py", self.BAD)
        assert _rules(fs) == ["PTL002"]
        assert fs[0].severity == "error"

    def test_warning_without_a_timer(self):
        src = "import jax\ndef warm(x):\n    jax.block_until_ready(x)\n"
        fs = lint.lint_text("tools/fake_bench.py", src)
        assert _rules(fs) == ["PTL002"]
        assert fs[0].severity == "warning"

    def test_silent_on_device_sync(self):
        assert lint.lint_text("tools/fake_bench.py", self.REPAIRED) == []


class TestPTL003MonitorSlots:
    BAD = (
        "from ..monitor import _register as _monitor_register\n"
        "_monitor = None\n"
        "def hot(x):\n"
        "    _monitor.on_thing(x)\n"
        "_monitor_register(None)\n"
    )
    REPAIRED = (
        "from ..monitor import _register as _monitor_register\n"
        "_monitor = None\n"
        "def hot(x):\n"
        "    m = _monitor\n"
        "    if m is not None:\n"
        "        m.on_thing(x)\n"
        "_monitor_register(None)\n"
    )

    def test_unguarded_use_fires(self):
        fs = lint.lint_text("paddle_tpu/fake/inst.py", self.BAD,
                            instrumented=("paddle_tpu.fake.inst",))
        assert _rules(fs) == ["PTL003"]
        assert "not guarded" in fs[0].message

    def test_guarded_alias_is_silent(self):
        assert lint.lint_text("paddle_tpu/fake/inst.py", self.REPAIRED,
                              instrumented=("paddle_tpu.fake.inst",)) == []

    def test_early_return_guard_is_silent(self):
        src = ("_spans = None\n"
               "def wrap(fn):\n"
               "    def w(*a):\n"
               "        sp = _spans\n"
               "        if sp is None:\n"
               "            return fn(*a)\n"
               "        sp.record('x')\n"
               "    return w\n"
               "_register(None)\n")
        assert lint.lint_text("paddle_tpu/fake/inst.py", src,
                              instrumented=("paddle_tpu.fake.inst",)) == []

    def test_missing_from_audit_list_fires(self):
        fs = lint.lint_text("paddle_tpu/fake/inst.py", self.REPAIRED,
                            instrumented=("paddle_tpu.other",))
        assert _rules(fs) == ["PTL003"]
        assert "INSTRUMENTED_MODULES" in fs[0].message

    def test_alias_in_sibling_function_is_not_a_slot(self):
        # hapi regression: `m` is a metric in one function, a monitor
        # alias in another — only the alias's own scope is slot-checked
        src = ("_monitor = None\n"
               "def a():\n"
               "    m = _monitor\n"
               "    if m is not None:\n"
               "        m.on_x()\n"
               "def b(metrics):\n"
               "    for m in metrics:\n"
               "        m.update()\n"
               "_register(None)\n")
        assert lint.lint_text("paddle_tpu/fake/inst.py", src,
                              instrumented=("paddle_tpu.fake.inst",)) == []


class TestPTL004PartialAxisConstraint:
    def test_fires_without_dp(self):
        src = ("from paddle_tpu.distributed import shard\n"
               "def forward(x):\n"
               "    return shard.sharding_constraint(x, None, 'mp', None)\n")
        fs = lint.lint_text("paddle_tpu/models/fake.py", src)
        assert _rules(fs) == ["PTL004"]

    def test_silent_with_all_live_axes(self):
        src = ("from paddle_tpu.distributed import shard\n"
               "def forward(x):\n"
               "    return shard.sharding_constraint(x, 'dp', 'mp', None)\n")
        assert lint.lint_text("paddle_tpu/models/fake.py", src) == []

    def test_dynamic_specs_are_not_judged(self):
        src = ("from paddle_tpu.distributed import shard\n"
               "def forward(x, spec):\n"
               "    return shard.sharding_constraint(x, *spec)\n")
        assert lint.lint_text("paddle_tpu/models/fake.py", src) == []


class TestPTL005Nondeterminism:
    BAD = (
        "import time\n"
        "import numpy as np\n"
        "def sweep(cands):\n"
        "    stamp = time.time()\n"
        "    pick = np.random.randint(0, 4)\n"
        "    order = list(set(cands))\n"
        "    for c in set(cands):\n"
        "        pass\n"
        "    return stamp, pick, order\n"
    )
    REPAIRED = (
        "import time\n"
        "import numpy as np\n"
        "def sweep(cands):\n"
        "    stamp = time.perf_counter()\n"
        "    pick = np.random.default_rng(0).integers(0, 4)\n"
        "    order = sorted(set(cands))\n"
        "    for c in sorted(set(cands)):\n"
        "        pass\n"
        "    return stamp, pick, order\n"
    )

    def test_fires_on_all_three_patterns(self):
        fs = lint.lint_text("paddle_tpu/autoshard/fake.py", self.BAD)
        assert sorted(set(_rules(fs))) == ["PTL005"]
        assert len(fs) == 4  # time.time, np.random, list(set), for-set

    def test_silent_on_repaired(self):
        assert lint.lint_text("paddle_tpu/autoshard/fake.py",
                              self.REPAIRED) == []

    def test_out_of_scope_is_silent(self):
        assert lint.lint_text("paddle_tpu/nn/fake.py", self.BAD) == []

    def test_seeded_jax_random_is_silent(self):
        src = ("import jax\n"
               "def probe():\n"
               "    return jax.random.normal(jax.random.PRNGKey(0), (4,))\n")
        assert lint.lint_text("paddle_tpu/ops/pallas/fake.py", src) == []

    def test_speculative_drafter_is_in_scope(self):
        # ISSUE 14: a nondeterministic drafter would break seeded
        # serving-trace replay byte-identity — the speculative module
        # lives under the same PTL005 contract as the planner/tuner
        src = ("import numpy as np\n"
               "def propose(tokens, k):\n"
               "    return np.random.randint(0, 100, (k,))\n")
        fs = lint.lint_text("paddle_tpu/serving/speculative.py", src)
        assert _rules(fs) == ["PTL005"]
        # the rest of serving/ (engine scheduling uses perf_counter
        # timestamps legitimately) stays out of the determinism scope
        assert lint.lint_text("paddle_tpu/serving/engine.py", src) == []


class TestEscapeHatch:
    def test_line_disable(self):
        src = ("import jax\n"
               "def make(spec):\n"
               "    def place(x):  # eager-only helper\n"
               "        return jax.device_put(x, spec)"
               "  # ptlint: disable=PTL001\n"
               "    return place\n")
        assert lint.lint_text("paddle_tpu/ops/fake.py", src) == []

    def test_bare_disable_silences_all(self):
        src = ("import jax\n"
               "def make(spec):\n"
               "    def place(x):\n"
               "        return jax.device_put(x, spec)  # ptlint: disable\n"
               "    return place\n")
        assert lint.lint_text("paddle_tpu/ops/fake.py", src) == []

    def test_skip_file(self):
        src = "# ptlint: skip-file\n" + TestPTL001DevicePutInTrace.BAD
        assert lint.lint_text("paddle_tpu/ops/fake.py", src) == []

    def test_other_rule_disable_does_not_silence(self):
        src = ("import jax\n"
               "def make(spec):\n"
               "    def place(x):\n"
               "        return jax.device_put(x, spec)"
               "  # ptlint: disable=PTL005\n"
               "    return place\n")
        assert _rules(lint.lint_text("paddle_tpu/ops/fake.py", src)) \
            == ["PTL001"]


# -- tier 1: the clean-tree gate ---------------------------------------------

def test_clean_tree_gate():
    """pt-lint over the whole tree reports zero errors — the standing
    guarantee that the incident patterns stay out of the codebase."""
    paths = [os.path.join(_ROOT, p)
             for p in ("paddle_tpu", "tools", "benchmarks",
                       "bench.py", "__graft_entry__.py")]
    findings = lint.lint_paths(paths, root=_ROOT)
    errors = [f for f in findings if f.severity == "error"]
    assert not errors, "\n".join(str(f) for f in errors)


def test_cli_exits_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "pt_lint.py"),
         "--json"], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    blob = json.loads(proc.stdout)
    assert blob["ok"] and blob["errors"] == 0


def test_cli_flags_a_violation(tmp_path):
    bad = tmp_path / "paddle_tpu" / "ops" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(TestPTL001DevicePutInTrace.BAD)
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "pt_lint.py"),
         "--json", "--root", str(tmp_path), str(bad)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    blob = json.loads(proc.stdout)
    assert blob["errors"] == 1
    assert blob["findings"][0]["rule"] == "PTL001"


def test_instrumented_modules_readable_statically():
    from paddle_tpu import monitor

    assert lint.load_instrumented_modules(_ROOT) \
        == monitor.INSTRUMENTED_MODULES


# -- tier 2: program-audit HLO fixtures --------------------------------------

@pytest.fixture(scope="module")
def dp_mesh():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "mp"))


class TestProgramAuditFixtures:
    """The three violation fixtures each produce EXACTLY their expected
    finding; the repaired programs are clean."""

    def test_replicated_dp_fixture(self, dp_mesh):
        from jax.sharding import NamedSharding, PartitionSpec

        x = jax.device_put(jnp.ones((8, 8)),
                           NamedSharding(dp_mesh, PartitionSpec("dp")))
        degrees = {"dp": 4, "mp": 2}
        # violation: dp-sharded input, elementwise program — zero
        # cross-dp collectives (the PR 10 lowering)
        bad = jax.jit(lambda a: a * 2).lower(x).compile()
        fs = pa.audit_hlo(bad.as_text(), degrees=degrees, expect_dp=True)
        assert [f["rule"] for f in fs] == ["PA001"]
        assert fs[0]["name"] == "replicated_dp"
        # repaired: a cross-dp reduction inserts the all-reduce
        good = jax.jit(lambda a: jnp.sum(a)).lower(x).compile()
        assert pa.audit_hlo(good.as_text(), degrees=degrees,
                            expect_dp=True) == []

    def test_dropped_donation_fixture(self):
        # violation: donation requested but the module has no alias
        # table (compiled without donate_argnums)
        bad = jax.jit(lambda a: a + 1.0).lower(jnp.ones((8, 8))).compile()
        fs = pa.audit_hlo(bad.as_text(), donate_expected=True)
        assert [f["rule"] for f in fs] == ["PA002"]
        assert fs[0]["name"] == "dropped_donation"
        # repaired: donation honored -> input_output_alias present
        good = jax.jit(lambda a: a + 1.0, donate_argnums=(0,)).lower(
            jnp.ones((8, 8))).compile()
        assert pa.audit_hlo(good.as_text(), donate_expected=True) == []

    def test_missing_pp_handoff_fixture(self):
        # PA005 (ISSUE 15): pp>1 train-step with no cross-pp
        # collective-permute = the stage handoff was compiled out.
        # Text fixtures (AXIS_ORDER dp,pp,sharding,sep,mp; dp2×pp2:
        # pp stride 1 → pairs (0,1),(2,3) cross pp only)
        degrees = {"dp": 2, "pp": 2}
        bad = ("HloModule step\n"
               "  %ar = f32[8]{0} all-reduce(f32[8]{0} %x), "
               "replica_groups={{0,2},{1,3}}, to_apply=%add\n")
        fs = pa.audit_hlo(bad, degrees=degrees, expect_pp=True)
        assert [f["rule"] for f in fs] == ["PA005"]
        assert fs[0]["name"] == "missing_pp_handoff"
        good = bad + (
            "  %cp = f32[8]{0} collective-permute(f32[8]{0} %y), "
            "source_target_pairs={{0,1},{1,0},{2,3},{3,2}}\n")
        assert pa.audit_hlo(good, degrees=degrees, expect_pp=True) == []
        # the ZeRO-style head/tail all-gather over pp is NOT a handoff
        gathered = bad + (
            "  %ag = f32[16]{0} all-gather(f32[8]{0} %z), "
            "replica_groups={{0,1},{2,3}}, dimensions={0}\n")
        fs = pa.audit_hlo(gathered, degrees=degrees, expect_pp=True)
        assert [f["rule"] for f in fs] == ["PA005"]

    def test_host_callback_fixture(self):
        def noisy(x):
            jax.debug.print("s={s}", s=x.sum())
            return x + 1

        bad = jax.jit(noisy).lower(jnp.ones((4,))).compile()
        fs = pa.audit_hlo(bad.as_text())
        assert [f["rule"] for f in fs] == ["PA003"]
        assert fs[0]["name"] == "host_callback"
        good = jax.jit(lambda a: a + 1).lower(jnp.ones((4,))).compile()
        assert pa.audit_hlo(good.as_text()) == []
        # a declared allowance passes the same program
        assert pa.audit_hlo(bad.as_text(), allowed_host_calls=1) == []


def test_retrace_budget_fires_once(monkeypatch):
    monkeypatch.setattr(pa, "RETRACE_BUDGET", 2)
    pa.reset()
    entry = types.SimpleNamespace(compiled=types.SimpleNamespace(
        as_text=lambda: "HloModule stub"))
    try:
        for _ in range(5):
            pa.on_compiled(entry, None, "train_step/Churny")
        rep = pa.report()
        pa004 = [f for f in rep["findings"] if f["rule"] == "PA004"]
        assert len(pa004) == 1  # fires once, at the crossing
        assert "3 distinct executables" in pa004[0]["detail"]
        assert rep["audits"] == 5
    finally:
        pa.reset()


def test_audit_entry_derives_context_from_key(dp_mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    x = jax.device_put(jnp.ones((8, 8)),
                       NamedSharding(dp_mesh, PartitionSpec("dp")))
    compiled = jax.jit(lambda a: a * 2).lower(x).compile()
    entry = types.SimpleNamespace(compiled=compiled)
    key = {"kind": "train_step", "donate": False,
           "mesh": (("dp", "mp"), (4, 2))}
    fs = pa.audit_entry(entry, key, "train_step/X")
    assert [f["rule"] for f in fs] == ["PA001"]
    # forward-only programs (any other kind) are not judged for dp
    assert pa.audit_entry(entry, {"kind": "predictor",
                                  "mesh": (("dp", "mp"), (4, 2))}) == []


def test_audit_entry_keyless_uses_label_and_live_env(dp_mesh):
    """PT_EXEC_CACHE unset => key=None at the chokepoint: train-step
    identity comes from the compile-site label and degrees from the
    live env, so PA001 stands without the cache."""
    from jax.sharding import NamedSharding, PartitionSpec

    from paddle_tpu.distributed import env as env_mod

    x = jax.device_put(jnp.ones((8, 8)),
                       NamedSharding(dp_mesh, PartitionSpec("dp")))
    compiled = jax.jit(lambda a: a * 2).lower(x).compile()
    entry = types.SimpleNamespace(compiled=compiled)
    env_mod.init_mesh(dp=4, mp=2)
    try:
        fs = pa.audit_entry(entry, None, "train_step/X")
        assert [f["rule"] for f in fs] == ["PA001"]
        # non-train-step labels are not judged for dp
        assert pa.audit_entry(entry, None, "serving/decode") == []
    finally:
        env_mod.reset_env()


def test_pa004_not_persisted_to_sidecar(armed_cache, monkeypatch):
    """PA004 is process-transient churn: it reaches the report and the
    counters but never the sidecar — a later healthy warm start must
    not replay it."""
    exec_cache = armed_cache
    monkeypatch.setattr(pa, "RETRACE_BUDGET", 0)
    key = {"kind": "fixture", "case": "churn"}
    exec_cache.get_or_compile(
        key, lambda: jax.jit(lambda a: a + 3.0).lower(jnp.ones((2,))),
        label="fixture/churn")
    assert [f["rule"] for f in pa.report()["findings"]] == ["PA004"]
    stored = exec_cache.meta_get(key)
    assert stored["program_audit"]["findings"] == []
    # warm start: the stored (clean) account is what gets re-reported
    exec_cache.clear()
    pa.reset()
    exec_cache.get_or_compile(
        key, lambda: jax.jit(lambda a: a + 3.0).lower(jnp.ones((2,))),
        label="fixture/churn")
    assert pa.report()["findings"] == []


def test_lint_covers_the_audit_slot_itself():
    """The _audit hook slot this PR adds to exec_cache is policed by the
    same PTL003 contract as the monitor slots."""
    src = ("_audit = None\n"
           "def get(key):\n"
           "    _audit.on_hit(key)\n"
           "_register(None)\n")
    fs = lint.lint_text("paddle_tpu/fake/cachey.py", src,
                        instrumented=("paddle_tpu.fake.cachey",))
    assert [f.rule for f in fs] == ["PTL003"]


# -- exec-cache hook + sidecar round-trip ------------------------------------

@pytest.fixture
def armed_cache(tmp_path):
    from paddle_tpu.jit import exec_cache

    exec_cache.clear()
    prev = exec_cache.cache_dir()
    exec_cache.enable(str(tmp_path / "ptxc"))
    pa.reset()
    pa.enable()
    try:
        yield exec_cache
    finally:
        pa.disable()
        pa.reset()
        if prev:
            exec_cache.enable(prev)
        else:
            exec_cache.disable()
        exec_cache.clear()


def test_sidecar_round_trip(armed_cache):
    """A fresh compile files its findings in the meta sidecar under the
    executable's key; a warm start re-reports them with NO re-parse."""
    exec_cache = armed_cache
    key = {"kind": "fixture", "donate": True, "case": "sidecar"}

    def lower():
        return jax.jit(lambda a: a + 1.0).lower(jnp.ones((4, 4)))

    entry = exec_cache.get_or_compile(key, lower, label="fixture/sidecar")
    assert entry.source == "compile"
    rep = pa.report()
    assert [f["rule"] for f in rep["findings"]] == ["PA002"]
    stored = exec_cache.meta_get(key)
    assert stored is not None
    assert [f["rule"] for f in stored["program_audit"]["findings"]] \
        == ["PA002"]

    # warm start: drop the mem tier, re-report from the sidecar alone
    exec_cache.clear()
    pa.reset()
    entry2 = exec_cache.get_or_compile(key, lower, label="fixture/sidecar")
    assert entry2.source == "disk"
    rep2 = pa.report()
    assert rep2["audits"] == 1
    assert [f["rule"] for f in rep2["findings"]] == ["PA002"]


def test_sidecar_merges_with_collectives(armed_cache):
    """The planner's comms sidecar entry and the audit entry share one
    meta blob — neither write clobbers the other."""
    exec_cache = armed_cache
    key = {"kind": "fixture", "case": "merge"}
    exec_cache.get_or_compile(
        key, lambda: jax.jit(lambda a: a * 2).lower(jnp.ones((2,))),
        label="fixture/merge")
    merged = dict(exec_cache.meta_get(key) or {})
    merged["collectives"] = {"total_wire_bytes": 0}
    exec_cache.meta_put(key, merged)
    meta = exec_cache.meta_get(key)
    assert "program_audit" in meta and "collectives" in meta


def test_audit_counters_ride_the_monitor(armed_cache):
    from paddle_tpu import monitor

    was = monitor.enabled()
    monitor.enable()
    try:
        base = monitor.snapshot()["counters"].get("analysis/audits", 0)
        armed_cache.get_or_compile(
            {"kind": "fixture", "donate": True, "case": "counters"},
            lambda: jax.jit(lambda a: a - 1.0).lower(jnp.ones((3,))),
            label="fixture/counters")
        c = monitor.snapshot()["counters"]
        assert c.get("analysis/audits", 0) == base + 1
        assert c.get("analysis/findings/PA002", 0) >= 1
    finally:
        if not was:
            monitor.disable()


def test_off_is_free():
    """PT_PROGRAM_AUDIT unset (tier-1 default): the exec-cache slot is
    None and the auditor reports disabled."""
    from paddle_tpu.jit import exec_cache

    assert exec_cache._audit is None
    assert not pa.enabled()


def test_audit_train_step_facts(dp_mesh):
    """Full-context audit of a live TrainStep on a dp>1 mesh: clean, dp
    moved real bytes (the dryrun_multichip proof leg's contract)."""
    import paddle_tpu as pt
    from paddle_tpu.distributed import env as env_mod
    from paddle_tpu.jit.train_step import TrainStep

    env_mod.init_mesh(dp=4, mp=2)
    try:
        from paddle_tpu.distributed import shard

        net = pt.nn.Linear(8, 8)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        step = TrainStep(net, opt,
                         lambda m, x, y: ((m(x) - y) ** 2).mean())
        # dp-shard the batch (what a planned run does): per-shard grads
        # now differ, so the program must all-reduce over dp
        x = shard.shard_tensor(
            pt.to_tensor(np.ones((8, 8), np.float32)), spec=("dp", None))
        y = shard.shard_tensor(
            pt.to_tensor(np.zeros((8, 8), np.float32)), spec=("dp", None))
        rep = pa.audit_train_step(step, x, y)
        assert rep["findings"] == []
        assert rep["facts"]["dp_collectives"] > 0
        assert rep["facts"]["host_calls"] == 0

        # and the tripwire side: a REPLICATED batch on the same mesh is
        # exactly the PR 10 smell — every device computes the same step
        step2 = TrainStep(net, opt,
                          lambda m, x, y: ((m(x) - y) ** 2).mean())
        rep2 = pa.audit_train_step(
            step2, pt.to_tensor(np.ones((8, 8), np.float32)),
            pt.to_tensor(np.zeros((8, 8), np.float32)))
        assert [f["rule"] for f in rep2["findings"]] == ["PA001"]
    finally:
        env_mod.reset_env()


# -- perf_guard --audit gate --------------------------------------------------

@pytest.fixture(scope="module")
def guard():
    spec = importlib.util.spec_from_file_location(
        "perf_guard_sa", os.path.join(_ROOT, "tools", "perf_guard.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _line(findings):
    return {"metric": "m", "value": 100.0, "unit": "u",
            "program_audit": {"audits": 3, "findings": findings}}


def _baseline(findings):
    return {"metric": "m", "value": 100.0, "backend": "tpu",
            "extra": {"program_audit": {"audits": 3,
                                        "findings": findings}}}


_F = {"rule": "PA001", "name": "replicated_dp", "severity": "error",
      "detail": "d", "label": "train_step/X"}


def test_guard_fails_on_new_finding(guard):
    v = guard.evaluate(_line([_F]), _baseline([]), hardware=True)
    chk = {c["name"]: c for c in v["checks"]}
    assert not chk["program_audit"]["ok"]
    assert "PA001" in chk["program_audit"]["detail"]
    assert not v["ok"]


def test_guard_passes_on_baseline_known_finding(guard):
    v = guard.evaluate(_line([_F]), _baseline([_F]), hardware=True)
    chk = {c["name"]: c for c in v["checks"]}
    assert chk["program_audit"]["ok"]


def test_guard_skips_without_subobject_or_on_cpu(guard):
    # baseline predates the audit -> no check emitted
    base = {"metric": "m", "value": 100.0, "backend": "tpu", "extra": {}}
    v = guard.evaluate(_line([_F]), base, hardware=True)
    assert "program_audit" not in {c["name"] for c in v["checks"]}
    # cpu smoke skips with the rest of the hardware comparisons
    v = guard.evaluate(_line([_F]), _baseline([]), hardware=False)
    assert "program_audit" not in {c["name"] for c in v["checks"]}


def test_guard_no_audit_flag_disables(guard):
    v = guard.evaluate(_line([_F]), _baseline([]),
                       thresholds={"audit": False}, hardware=True)
    assert "program_audit" not in {c["name"] for c in v["checks"]}
