"""Round-4 regression tests for the round-3 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as pt


def _t(x):
    return pt.to_tensor(x)


class TestLBFGSLineSearch:
    def _quadratic_setup(self, line_search_fn):
        # f(w) = 0.5 * w^T A w - b^T w, A SPD — unique minimum at A w = b
        A = np.array([[3.0, 0.5], [0.5, 1.0]], np.float32)
        b = np.array([1.0, -2.0], np.float32)
        w = pt.to_tensor(np.zeros(2, np.float32), stop_gradient=False)
        opt = pt.optimizer.LBFGS(learning_rate=1.0, max_iter=25,
                                 line_search_fn=line_search_fn,
                                 parameters=[w])
        tA, tb = _t(A), _t(b)

        def closure():
            loss = 0.5 * (w @ (tA @ w)) - tb @ w
            loss.backward()
            return loss

        return w, opt, closure, np.linalg.solve(A, b)

    @pytest.mark.parametrize("ls", [None, "strong_wolfe"])
    def test_converges_on_quadratic(self, ls):
        w, opt, closure, expected = self._quadratic_setup(ls)
        for _ in range(5):
            opt.step(closure)
        np.testing.assert_allclose(w.numpy(), expected, atol=1e-4)

    def test_strong_wolfe_rosenbrock(self):
        # the classic curved valley: strong-wolfe must make monotone-ish
        # progress where a fixed step diverges
        w = pt.to_tensor(np.array([-1.2, 1.0], np.float32),
                         stop_gradient=False)
        opt = pt.optimizer.LBFGS(learning_rate=1.0, max_iter=60,
                                 line_search_fn="strong_wolfe",
                                 parameters=[w])

        def closure():
            x, y = w[0], w[1]
            loss = (1 - x) ** 2 + 100 * (y - x * x) ** 2
            loss.backward()
            return loss

        for _ in range(8):
            loss = opt.step(closure)
        assert float(loss.numpy()) < 1e-3

    def test_invalid_line_search_fn_rejected(self):
        w = pt.to_tensor(np.zeros(2, np.float32), stop_gradient=False)
        with pytest.raises(ValueError, match="strong_wolfe"):
            pt.optimizer.LBFGS(parameters=[w], line_search_fn="armijo")

    def test_failed_search_restores_pre_step_point(self):
        # max_eval=1: the initial closure eval exhausts the budget, the
        # line search cannot run, and parameters must stay where they were
        w = pt.to_tensor(np.array([1.0, 1.0], np.float32),
                         stop_gradient=False)
        opt = pt.optimizer.LBFGS(learning_rate=1.0, max_iter=5, max_eval=1,
                                 parameters=[w])

        def closure():
            loss = (w * w).sum()
            loss.backward()
            return loss

        before = w.numpy().copy()
        opt.step(closure)
        np.testing.assert_array_equal(w.numpy(), before)


class TestLookAhead:
    def test_slow_weights_initialized_at_construction(self):
        # param p0=4.0, grad always 1.0, inner SGD lr=1 → fast: 3, 2
        # k=2 sync: slow = p0 + 0.5*(p2 - p0) = 4 + 0.5*(2-4) = 3
        # (the old behavior adopted p2=2 wholesale at the first sync)
        p = pt.to_tensor(np.array([4.0], np.float32), stop_gradient=False)
        inner = pt.optimizer.SGD(learning_rate=1.0, parameters=[p])
        la = pt.incubate.LookAhead(inner, alpha=0.5, k=2)
        for _ in range(2):
            loss = p.sum()
            loss.backward()
            la.step()
            la.clear_grad()
        np.testing.assert_allclose(p.numpy(), [3.0], atol=1e-6)


class TestCollectiveAdvice:
    def test_gather_fills_preallocated_placeholder_list(self):
        g = pt.distributed.get_group()
        x = _t(np.arange(8, dtype=np.float32))
        placeholder = [None] * g.nranks
        out = pt.distributed.gather(x, gather_list=placeholder)
        assert out is placeholder
        assert len(placeholder) == g.nranks  # replaced, not appended after
        assert all(v is not None for v in placeholder)

    def test_alltoall_single_out_is_differentiable(self):
        x = pt.to_tensor(np.arange(64, dtype=np.float32),
                         stop_gradient=False)
        out = pt.to_tensor(np.zeros(64, np.float32))
        y = pt.distributed.alltoall_single(out, x)
        assert y is out
        (y * y).sum().backward()
        assert x.grad is not None
        np.testing.assert_allclose(x.grad.numpy(),
                                   2 * np.arange(64, dtype=np.float32))


class TestDynamicDecodeImputeFinished:
    class CountingDecoder:
        """States count the steps; element 0 finishes immediately.
        Records the states it *receives* so the test can observe whether
        finished elements were frozen between steps."""

        end_token = -1

        def __init__(self):
            self.received = []

        def initialize(self, inits):
            state = _t(np.zeros((2, 1), np.float32))
            finished = _t(np.array([False, False]))
            inputs = _t(np.zeros((2,), np.float32))
            return inputs, state, finished

        def step(self, t, inputs, states, finished=None):
            self.received.append(states.numpy().copy())
            new_states = states + 1.0
            fin = _t(np.array([True, t >= 2]))
            return None, new_states, inputs, fin

        def finalize(self):
            ids = _t(np.zeros((1, 1, 1), np.int64))
            scores = _t(np.zeros((1, 1), np.float32))
            return ids, scores

    def test_finished_states_frozen(self):
        dec = self.CountingDecoder()
        pt.nn.dynamic_decode(dec, max_step_num=5, impute_finished=True)
        # t=2 receives elem0 frozen at its finish-step value (1), elem1
        # still counting (2)
        np.testing.assert_allclose(dec.received[2], [[1.0], [2.0]])

    def test_default_leaves_states_unfrozen(self):
        dec = self.CountingDecoder()
        pt.nn.dynamic_decode(dec, max_step_num=5, impute_finished=False)
        np.testing.assert_allclose(dec.received[2], [[2.0], [2.0]])
