"""Two-process distributed runtime test through the launcher.

Mirrors `/root/reference/test/legacy_test/test_dist_base.py:963`
(`TestDistBase._run_cluster`: trainer subprocesses on one host, loss
parity asserted) and
`/root/reference/test/collective/test_communication_api_base.py:39`
(launch-module subprocess): spawns
`python -m paddle_tpu.distributed.launch --nproc_per_node 2` over
`tests/launch_mp_worker.py`, with 4 virtual CPU devices per process —
`env.init_distributed_runtime` → `jax.distributed.initialize` actually
executes across a real process boundary.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port



def _run_launch(worker, tmp_path):
    """Shared two-process launcher harness: env, spawn, log collection."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_TRAINER_ID", None)
    env.pop("PADDLE_TRAINERS_NUM", None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{_free_port()}",
         "--log_dir", str(tmp_path / "log"), "--max_restart", "0",
         os.path.join(ROOT, "tests", worker), str(tmp_path)],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=600)
    logs = ""
    log_dir = tmp_path / "log"
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-3000:]
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:], logs)
    return logs

@pytest.mark.slow
def test_two_process_launch(tmp_path):
    logs = _run_launch("launch_mp_worker.py", tmp_path)

    ranks = []
    for r in (0, 1):
        path = tmp_path / f"rank{r}.json"
        assert path.exists(), logs
        ranks.append(json.loads(path.read_text()))

    for res in ranks:
        # the runtime really spans two processes x 4 devices
        assert res["process_count"] == 2, res
        assert res["device_count"] == 8, res
        assert res["local_device_count"] == 4, res
        # the collective crossed the boundary: sum of global device
        # indices 0..7, which no single process holds alone
        assert res["allreduce_sum"] == float(sum(range(8))), res

    # both ranks computed the identical loss trajectory (one logical
    # program), and it matches the single-process run of the same model
    assert ranks[0]["losses"] == ranks[1]["losses"]
    expected = _single_process_losses()
    np.testing.assert_allclose(ranks[0]["losses"], expected, rtol=1e-5,
                               atol=1e-6)


def _single_process_losses():
    """The same 3-step training run inside this (single) process on the
    8-device mesh — the parity reference, as in TestDistBase."""
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep

    pt.seed(0)
    model = pt.nn.Linear(4, 2)
    opt = pt.optimizer.SGD(learning_rate=0.1,
                           parameters=model.parameters())
    loss_fn = pt.nn.MSELoss()
    step = TrainStep(model, opt, lambda m, x, y: loss_fn(m(x), y),
                     donate=False)
    rng = np.random.RandomState(0)
    xs = rng.randn(8, 4).astype(np.float32)
    ys = rng.randn(8, 2).astype(np.float32)
    return [float(np.asarray(step(pt.to_tensor(xs),
                                  pt.to_tensor(ys)).numpy()))
            for _ in range(3)]


@pytest.mark.slow
def test_two_process_sharded_checkpoint(tmp_path):
    # multi-host checkpoint contract (SURVEY 5.4): disjoint per-process
    # shard writes, coordinator-gated ownerless tensors + index-after-
    # barrier, reshard-on-load from the shared directory
    logs = _run_launch("ckpt_mp_worker.py", tmp_path)
    for r in (0, 1):
        path = tmp_path / f"ckptrank{r}.json"
        assert path.exists(), logs
        res = json.loads(path.read_text())
        assert res["process_count"] == 2, res
        assert res["format"] == 2, res
        assert res["w_shards"] == 8, res  # one region per dp slot
        assert res["all_files_exist"], res
        assert res["w_roundtrip"], res
        assert res["scalar_roundtrip"] == 7.25, res
        assert res["host_roundtrip"], res
    # the ownerless host tensor was written once (coordinator), and the
    # sharded tensor's region files total 8
    import glob

    host_files = glob.glob(str(tmp_path / "ckpt" / "host.*.npy"))
    w_files = glob.glob(str(tmp_path / "ckpt" / "w.*.npy"))
    assert len(host_files) == 1 and len(w_files) == 8, (host_files,
                                                       w_files)
