"""OpTest-style base utilities.

Reference parity: `test/legacy_test/eager_op_test.py:378` (`OpTest`) — ops
declare numpy inputs and expected outputs; outputs are checked against numpy
and analytic grads are checked against numeric finite differences
(`get_numeric_gradient`, reference `eager_op_test.py:134`).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle


def check_output(fn, np_fn, inputs, rtol=1e-4, atol=1e-5, **kwargs):
    """Run `fn` on Tensors and `np_fn` on numpy arrays; compare."""
    tensors = [paddle.to_tensor(x) for x in inputs]
    out = fn(*tensors, **kwargs)
    expected = np_fn(*inputs, **kwargs)
    if not isinstance(out, (tuple, list)):
        out, expected = [out], [expected]
    for o, e in zip(out, expected):
        np.testing.assert_allclose(
            o.numpy().astype(np.float64) if np.issubdtype(np.asarray(e).dtype, np.floating) else o.numpy(),
            np.asarray(e),
            rtol=rtol, atol=atol,
        )
    return out


def numeric_grad(fn, inputs, idx=0, eps=1e-3, **kwargs):
    """Central finite differences of sum(fn(*inputs)) w.r.t. inputs[idx]."""
    inputs = [np.asarray(x)
              if np.issubdtype(np.asarray(x).dtype, np.integer)
              or np.asarray(x).dtype == np.bool_
              else np.asarray(x, np.float64) for x in inputs]
    base = inputs[idx]
    grad = np.zeros_like(base)
    it = np.nditer(base, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        orig = base[i]
        base[i] = orig + eps
        hi = float(np.sum(np.asarray(fn(*inputs, **kwargs), np.float64)))
        base[i] = orig - eps
        lo = float(np.sum(np.asarray(fn(*inputs, **kwargs), np.float64)))
        base[i] = orig
        grad[i] = (hi - lo) / (2 * eps)
        it.iternext()
    return grad


def check_grad(fn, np_fn, inputs, grad_idx=0, rtol=1e-3, atol=1e-3, **kwargs):
    """Analytic grad via the tape vs numeric finite differences.

    Integer inputs (indices, lengths) keep their dtype and take no grad;
    float inputs are cast to float32 leaves."""
    tensors = [
        paddle.to_tensor(np.asarray(x))
        if np.issubdtype(np.asarray(x).dtype, np.integer)
        or np.asarray(x).dtype == np.bool_
        else paddle.to_tensor(np.asarray(x, np.float32), stop_gradient=False)
        for x in inputs
    ]
    out = fn(*tensors, **kwargs)
    if isinstance(out, (tuple, list)):
        out = out[0]
    loss = out.sum()
    loss.backward()
    analytic = tensors[grad_idx].grad.numpy()
    numeric = numeric_grad(np_fn, inputs, idx=grad_idx, **kwargs)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)
    return analytic
