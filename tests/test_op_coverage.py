"""Guard the OP_COVERAGE audit: every alias target in
tools/gen_op_coverage.py must resolve to a real attribute, and the
committed docs/OP_COVERAGE.md must report zero absent ops."""
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_alias_targets_resolve():
    sys.path.insert(0, str(REPO / "tools"))
    import gen_op_coverage as g

    bad = [spec for spec in set(g.ALIASES.values())
           if not g.resolve_alias(spec)]
    assert not bad, f"alias targets missing: {bad}"


def test_committed_audit_has_no_absent_ops():
    doc = (REPO / "docs" / "OP_COVERAGE.md").read_text()
    m = re.search(r"\| absent \| (\d+) \|", doc)
    assert m, "absent row missing from OP_COVERAGE.md"
    assert int(m.group(1)) == 0, f"{m.group(1)} absent ops in the audit"
    m = re.search(r"= (\d+\.\d)%\*\*", doc)
    assert m and float(m.group(1)) >= 80.0
