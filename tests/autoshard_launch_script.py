"""Planner-driven launch/resume fixture — elastic_reshard_script.py's
successor with ZERO hand-written PartitionSpecs (ISSUE 10 acceptance):
every placement comes from the shard plan the launcher stamped into
``PT_SHARD_PLAN`` (`autoshard.apply_plan` initializes the planned mesh
and derives the Megatron conjugate pairing for the plain Sequential
model; the batch is dp-sharded by `autoshard.shard_batch`;
`autoshard.stage_model` wraps the repeated Block run into the staged
pipeline container whenever the plan says pp>1 — ISSUE 15).

Life 0 trains under plan A and crashes mid-run (AUTOSHARD_CRASH_AT).
The driver (tests/test_autoshard.py) then REPLANS for a different
topology and relaunches with ``PT_SHARD_RESUME`` pointing at the
checkpoint dir — reshard-on-load (distributed/checkpoint.py + the
canonical per-block keys of resilience/resume.py) rebuilds every param
at the new placements, including across stage moves. The stitched loss
trajectory must stay on the SAME curve as an uninterrupted single-plan
run.
"""
import json
import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
from paddle_tpu import autoshard, resilience  # noqa: E402
from paddle_tpu.resilience import resume as rez  # noqa: E402

WORKDIR = sys.argv[1]
CRASH_AT = int(os.environ.get("AUTOSHARD_CRASH_AT", "-1"))
TOTAL_STEPS = 6
resume_dir = os.environ.get("PT_SHARD_RESUME")
life = 1 if resume_dir else 0

plan = autoshard.load_plan(os.environ["PT_SHARD_PLAN"])


class Block(nn.Layer):
    """The repeated (stage-able) unit: a pp>1 plan stacks these."""

    def __init__(self, h):
        super().__init__()
        self.fc = nn.Linear(h, h)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


paddle.seed(0)
model = nn.Sequential(nn.Linear(8, 16), Block(16), Block(16),
                      nn.Linear(16, 1))
# the whole point: mesh + every param placement from the plan — no
# PartitionSpec appears anywhere in this file, and the pipeline
# staging (when planned) is the plan's decision too
env = autoshard.apply_plan(plan, model)
model = autoshard.stage_model(model, plan)
opt = paddle.optimizer.AdamW(learning_rate=5e-2,
                             parameters=model.parameters())

rng = np.random.default_rng(0)
xs = rng.standard_normal((TOTAL_STEPS, 16, 8)).astype("float32")
w_true = rng.standard_normal((8, 1)).astype("float32")

ckpt_dir = os.path.join(WORKDIR, "ckpt")
start_step = 0
scal = rez.restore_latest(model, opt, ckpt_dir, crash_resume=life > 0)
if scal is not None:
    start_step = int(scal.get("step", 0))

# sync saves: this fixture proves PLAN-driven reshard equivalence;
# torn-checkpoint fallback has its own test (test_resilience.py)
mgr = resilience.CheckpointManager(ckpt_dir, interval=1, keep=3,
                                   async_save=False)
losses = []
for step in range(start_step, TOTAL_STEPS):
    x = autoshard.shard_batch(paddle.to_tensor(xs[step]))
    y = autoshard.shard_batch(paddle.to_tensor(xs[step] @ w_true))
    loss = F.mse_loss(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    losses.append(float(np.asarray(loss.numpy()).reshape(-1)[0]))
    with open(os.path.join(WORKDIR, f"losses_r{life}.json"), "w") as f:
        json.dump({"start": start_step, "losses": losses,
                   "mesh": dict(plan.mesh)}, f)
    mgr.save(step + 1, rez.capture(model, opt, step=step + 1))
    if life == 0 and step + 1 == CRASH_AT:
        os._exit(17)  # simulated preemption mid-training
