"""Int8 execution tests (reference: quantized PHI kernels / TRT int8
subgraphs — SURVEY long-tail Quantization row)."""
import numpy as np
import pytest

import paddle_tpu as pt




class TestInt8Execution:
    """True int8 execution (reference: quantized kernels / TRT int8)."""

    def _model_and_x(self, seed=0):
        pt.seed(seed)
        m = pt.nn.Sequential(pt.nn.Linear(16, 32), pt.nn.ReLU(),
                             pt.nn.Linear(32, 4))
        x = pt.to_tensor(np.random.RandomState(0)
                         .randn(8, 16).astype(np.float32))
        return m, x

    def test_weight_only_close_and_int8_payload(self):
        from paddle_tpu.quantization import Int8Linear, convert_to_int8

        m, x = self._model_and_x()
        ref = m(x).numpy()
        m8 = convert_to_int8(m, mode="weight_only")
        assert isinstance(m8[0], Int8Linear)
        assert str(m8[0].w_q._data.dtype) == "int8"
        out = m8(x).numpy()
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.05, rel

    def test_ptq_to_full_int8_dot(self):
        import jax

        from paddle_tpu.quantization import (PTQ, Int8Linear,
                                             convert_to_int8)

        m, x = self._model_and_x(1)
        ref = m(x).numpy()
        ptq = PTQ()
        mq = ptq.quantize(m)
        mq(x)  # calibrate observers
        ptq.convert(mq)
        m8 = convert_to_int8(mq, mode="int8")
        assert m8[0].mode == "int8"  # calibrated scale available
        out = m8(x).numpy()
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.1, rel
        # the executed program really runs an s8 x s8 -> s32 dot
        txt = jax.jit(
            lambda a: m8[0](pt.Tensor(a))._data).lower(x._data).as_text()
        assert "xi8>" in txt and "xi32>" in txt and "dot_general" in txt

    def test_int8_model_exports_via_jit_save(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.quantization import convert_to_int8

        m, x = self._model_and_x(2)
        m8 = convert_to_int8(m, mode="weight_only")
        ref = m8(x).numpy()
        path = str(tmp_path / "int8_model")
        paddle.jit.save(m8, path, input_spec=[x])
        loaded = paddle.jit.load(path)
        out = np.asarray(loaded(x).numpy())
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_inference_config_int8_points_to_conversion(self):
        import pytest

        from paddle_tpu.inference import Config

        cfg = Config()
        with pytest.raises(Exception, match="convert_to_int8"):
            cfg.set_precision("int8")
