"""Mosaic (TPU) lowering tests for every registered Pallas kernel — run on
the CPU host via ``jax.export(..., platforms=['tpu'])``.

This closes the round-2 blind spot: interpret-mode tests
(test_pallas_flash.py) verify numerics but skip Mosaic's block-mapping
checks, so a kernel could pass the suite and still fail to lower on a real
chip (which is exactly what zeroed the round-2 bench — see
``_check_block_mappings`` in jax's pallas/mosaic/lowering.py rejecting the
old (1, block_q) lse BlockSpec). ``jax.export`` performs the full
platform lowering, including Mosaic kernel serialization, without needing
TPU hardware, so any BlockSpec/layout regression now fails CI loudly.

Reference contract: the flash-attn kernel must serve the BASELINE shapes —
BERT-base head_dim 64 (config 3) and Llama head_dim 128 (config 4) — like
`paddle/phi/kernels/gpu/flash_attn_kernel.cu` does for the CUDA reference.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.pallas.flash_attention import (
    _flash_bhsd, flash_attention_kernel)


def _export_for_tpu(fn, *args):
    """Lower ``fn`` for the TPU platform (Mosaic checks run here)."""
    return jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


# (bh, sq, sk, d) — covers BERT-base (d=64), Llama (d=128), cross-length,
# and a long-seq case where the sequence is tiled into multiple blocks.
SHAPES = [
    (8, 1024, 1024, 64),
    (8, 1024, 1024, 128),
    (4, 512, 1024, 128),
    (2, 4096, 4096, 64),
    (2, 128, 128, 64),
]


@pytest.mark.parametrize("bh,sq,sk,d", SHAPES)
@pytest.mark.parametrize("causal", [True, False])
def test_flash_fwd_lowers_for_tpu(bh, sq, sk, d, causal):
    q = jnp.zeros((bh, sq, d), jnp.bfloat16)
    kv = jnp.zeros((bh, sk, d), jnp.bfloat16)
    scale = 1.0 / math.sqrt(d)
    _export_for_tpu(
        lambda q, k, v: _flash_bhsd(q, k, v, causal, scale, False), q, kv, kv)


@pytest.mark.parametrize("bh,sq,sk,d", SHAPES)
def test_flash_bwd_lowers_for_tpu(bh, sq, sk, d):
    q = jnp.zeros((bh, sq, d), jnp.bfloat16)
    kv = jnp.zeros((bh, sk, d), jnp.bfloat16)
    scale = 1.0 / math.sqrt(d)

    def loss(q, k, v):
        out = _flash_bhsd(q, k, v, True, scale, False)
        return out.astype(jnp.float32).sum()

    _export_for_tpu(
        lambda q, k, v: jax.grad(loss, argnums=(0, 1, 2))(q, k, v), q, kv, kv)


def test_kernel_engages_for_bert_head_dim_64():
    """head_dim 64 must take the Pallas path, not the composite fallback
    (round-2 Weak #2: the d%128 gate silently excluded BERT-base)."""
    q = jnp.zeros((2, 128, 12, 64), jnp.bfloat16)

    import paddle_tpu.ops.pallas.flash_attention as fa

    calls = []
    orig = fa._flash_call

    def spy(*args, **kw):
        calls.append(args[0].shape)
        return orig(*args, **kw)

    fa_flash, fa._flash_call = fa._flash_call, spy
    try:
        flash_attention_kernel(q, q, q, causal=True, interpret=True)
    finally:
        fa._flash_call = fa_flash
    assert calls, "Pallas kernel did not engage for head_dim 64"


def test_entry_smoke_lowering_helper():
    """The driver-facing smoke helper lowers all registered kernels."""
    from paddle_tpu.ops.pallas import check_tpu_lowering

    check_tpu_lowering()


@pytest.mark.parametrize("group", [2, 4, 8])
def test_gqa_lowers_for_tpu(group):
    """GQA: bh % bh_kv == 0 — shared-KV index maps must Mosaic-lower."""
    bh, s, d = 8, 1024, 128
    q = jnp.zeros((bh, s, d), jnp.bfloat16)
    kv = jnp.zeros((bh // group, s, d), jnp.bfloat16)
    scale = 1.0 / math.sqrt(d)
    _export_for_tpu(
        lambda q, k, v: _flash_bhsd(q, k, v, True, scale, False), q, kv, kv)
    _export_for_tpu(
        lambda q, k, v: jax.grad(
            lambda *a: _flash_bhsd(*a, True, scale, False)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v),
        q, kv, kv)
