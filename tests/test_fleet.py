"""Fleet telemetry tests (ISSUE 20 — monitor/heartbeat + the launcher's
FleetMonitor, docs/OBSERVABILITY.md "Training goodput plane").

Tier-1 proof of the fleet half of the goodput plane: the three
detectors each latch a worker-NAMED verdict (straggler / dp desync /
silent worker), the launcher-side FleetMonitor surfaces them through
``fleet.json`` + the aggregated ``/statusz``, a real `fit()` under
``PT_HEARTBEAT_DIR`` heartbeats, and a genuine 2-process
`distributed.launch` run with injected faults lands both verdicts in
the launcher's artifacts (+ ``tools/monitor_report.py --fleet`` renders
them offline)."""
import json
import os
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.monitor import exporter, heartbeat, live

REPO = str(Path(__file__).parent.parent)


def _write_beats(directory, rank, rows, mode="w"):
    """rows: [(step, ts, step_ms, loss)] — loss/step_ms may be None."""
    os.makedirs(directory, exist_ok=True)
    with open(heartbeat.heartbeat_path(directory, rank), mode) as f:
        for step, ts, step_ms, loss in rows:
            line = {"rank": rank, "step": step, "ts": ts}
            if step_ms is not None:
                line["step_ms"] = step_ms
            if loss is not None:
                line["loss"] = loss
            f.write(json.dumps(line) + "\n")


# -- detectors (pure, synthetic by_rank dicts) -------------------------------

def test_straggler_detector_names_rank_and_step():
    by_rank = {
        0: [{"step": 1, "step_ms": 5.0}, {"step": 2, "step_ms": 5.0}],
        1: [{"step": 1, "step_ms": 5.0}, {"step": 2, "step_ms": 5.0}],
        2: [{"step": 1, "step_ms": 5.0}, {"step": 2, "step_ms": 50.0}],
    }
    v = heartbeat.detect_straggler(by_rank, factor=3.0)
    assert v is not None
    assert v["rank"] == 2 and v["step"] == 2
    assert v["step_ms"] == 50.0 and v["fleet_median_ms"] == 5.0
    # balanced fleet: no verdict
    assert heartbeat.detect_straggler(
        {0: by_rank[0], 1: by_rank[1]}, factor=3.0) is None


def test_straggler_needs_two_reporting_ranks():
    # one rank at a step can never be its own straggler
    assert heartbeat.detect_straggler(
        {0: [{"step": 1, "step_ms": 500.0}]}, factor=3.0) is None


def test_desync_detector_names_extreme_ranks():
    by_rank = {
        0: [{"step": 1, "loss": 2.5}, {"step": 2, "loss": 2.4}],
        1: [{"step": 1, "loss": 2.5}, {"step": 2, "loss": 9.9}],
    }
    v = heartbeat.detect_desync(by_rank, tol=1e-3)
    assert v is not None
    assert v["ranks"] == [0, 1] and v["step"] == 2
    assert v["rel_spread"] > 1e-3
    # within tolerance: no verdict (dp replicas agree)
    same = {0: [{"step": 1, "loss": 2.5}], 1: [{"step": 1, "loss": 2.5}]}
    assert heartbeat.detect_desync(same, tol=1e-3) is None


def test_silent_detector_names_victim():
    now = 1000.0
    by_rank = {
        0: [{"step": 5, "ts": now}],
        1: [{"step": 3, "ts": now - 120.0}],
    }
    v = heartbeat.detect_silent(by_rank, timeout_s=60.0, now=now)
    assert v is not None
    assert v["rank"] == 1 and v["last_step"] == 3
    assert v["silent_s"] == 120.0
    # a lone rank is never "silent" (nothing to compare against)
    assert heartbeat.detect_silent(
        {1: by_rank[1]}, timeout_s=60.0, now=now) is None


# -- FleetMonitor over synthetic heartbeat files -----------------------------

def test_fleet_monitor_latches_and_snapshots(tmp_path):
    hb_dir = str(tmp_path / "hb")
    now = time.time()
    _write_beats(hb_dir, 0, [(s, now, 5.0, 2.5 - 0.1 * s)
                             for s in (1, 2, 3)])
    _write_beats(hb_dir, 1, [(1, now, 5.0, 2.4), (2, now, 5.0, 2.3),
                             (3, now, 5.0, 2.2)])
    _write_beats(hb_dir, 2, [(1, now, 5.0, 2.4), (2, now, 50.0, 2.3),
                             (3, now, 5.0, 8.8)])
    fleet = heartbeat.FleetMonitor(hb_dir, 3, log_dir=str(tmp_path),
                                   straggler_factor=3.0, desync_tol=1e-3,
                                   heartbeat_timeout_s=3600.0)
    verdicts = fleet.poll()
    assert verdicts["straggler"]["rank"] == 2
    assert verdicts["straggler"]["step"] == 2
    # first offending step wins; the divergent rank is named
    assert verdicts["desync"]["step"] == 3
    assert verdicts["desync"]["ranks"] == [0, 2]
    assert verdicts["silent"] is None
    # latched: a later balanced poll never clears the verdicts
    _write_beats(hb_dir, 0, [(4, now, 5.0, 2.1)], mode="a")
    _write_beats(hb_dir, 1, [(4, now, 5.0, 2.1)], mode="a")
    _write_beats(hb_dir, 2, [(4, now, 5.0, 2.1)], mode="a")
    v2 = fleet.poll()
    assert v2["straggler"] == verdicts["straggler"]
    # fleet.json snapshot in the log dir, worker-keyed
    snap = json.loads((tmp_path / "fleet.json").read_text())
    assert set(snap["workers"]) >= {"0", "1", "2"}
    assert snap["verdicts"]["straggler"]["rank"] == 2
    st = fleet.status()
    assert st["fleet"]["min_step"] is not None


def test_fleet_monitor_silent_worker_postmortem(tmp_path):
    hb_dir = str(tmp_path / "hb")
    now = time.time()
    _write_beats(hb_dir, 0, [(5, now, 5.0, 2.0)])
    _write_beats(hb_dir, 1, [(2, now - 300.0, 5.0, 2.1)])
    fleet = heartbeat.FleetMonitor(hb_dir, 2, log_dir=str(tmp_path),
                                   heartbeat_timeout_s=60.0)
    verdicts = fleet.poll()
    assert verdicts["silent"]["rank"] == 1
    pm_path = tmp_path / "fleet_postmortem.rank1.json"
    assert pm_path.exists()
    pm = json.loads(pm_path.read_text())
    assert pm["reason"] == "heartbeat_timeout"
    assert pm["victim_rank"] == 1
    assert fleet.status()["postmortem"] == str(pm_path)


def test_fleet_monitor_tolerates_torn_tail(tmp_path):
    hb_dir = str(tmp_path / "hb")
    now = time.time()
    _write_beats(hb_dir, 0, [(1, now, 5.0, 2.5)])
    # rank 1's file ends mid-line (a worker mid-write): consumed later
    with open(heartbeat.heartbeat_path(hb_dir, 1), "w") as f:
        f.write(json.dumps({"rank": 1, "step": 1, "ts": now,
                            "step_ms": 5.0}) + "\n")
        f.write('{"rank": 1, "step": 2, "ts"')
    fleet = heartbeat.FleetMonitor(hb_dir, 2, log_dir=str(tmp_path),
                                   heartbeat_timeout_s=3600.0)
    fleet.poll()
    assert fleet._last[1]["step"] == 1
    # the torn tail completes: the buffered fragment + completion parse
    with open(heartbeat.heartbeat_path(hb_dir, 1), "a") as f:
        f.write(f': {now}, "step_ms": 6.0}}\n')
    fleet.poll()
    assert fleet._last[1]["step"] == 2


def test_statusz_aggregates_fleet_verdicts(tmp_path):
    """The launcher's aggregated /statusz carries the fleet provider's
    worker-named verdicts (acceptance: verdicts visible in /statusz)."""
    hb_dir = str(tmp_path / "hb")
    now = time.time()
    _write_beats(hb_dir, 0, [(1, now, 5.0, 2.5), (2, now, 5.0, 2.4)])
    _write_beats(hb_dir, 1, [(1, now, 5.0, 2.5), (2, now, 5.0, 7.7)])
    _write_beats(hb_dir, 2, [(1, now, 5.0, 2.5), (2, now, 60.0, 2.4)])
    fleet = heartbeat.FleetMonitor(hb_dir, 3, log_dir=str(tmp_path),
                                   straggler_factor=3.0, desync_tol=1e-3,
                                   heartbeat_timeout_s=3600.0)
    fleet.poll()
    fleet.attach()
    port = exporter.start(0)
    assert port
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz", timeout=10) as r:
            body = r.read().decode()
        assert "--- fleet ---" in body
        assert '"straggler"' in body and '"rank": 2' in body
        assert '"desync"' in body
    finally:
        # exporter.start() armed the live plane; restore the tier-1
        # import-inert default for later test files
        exporter.stop()
        live.disable()
        live.reset()


# -- fit() integration: workers heartbeat under PT_HEARTBEAT_DIR -------------

def test_fit_writes_heartbeats(tmp_path, monkeypatch):
    hb_dir = str(tmp_path / "hb")
    monkeypatch.setenv("PT_HEARTBEAT_DIR", hb_dir)
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = paddle.optimizer.AdamW(learning_rate=5e-2,
                                 parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, nn.MSELoss())
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((32, 8)).astype("float32")
    ys = xs @ rng.standard_normal((8, 1)).astype("float32")
    model.fit([(xs[i], ys[i]) for i in range(32)], batch_size=8,
              epochs=2, shuffle=False, verbose=0, log_freq=1)
    by_rank = heartbeat.read_heartbeats(hb_dir)
    assert list(by_rank) == [0]
    beats = by_rank[0]
    assert [b["step"] for b in beats] == list(range(1, 9))
    assert all(b.get("step_ms", 0) > 0 for b in beats)
    # log_freq=1 materializes every loss -> every beat carries it
    assert all(isinstance(b.get("loss"), float) for b in beats)
    # the cumulative sketch merges exactly: newest line carries them all
    assert beats[-1]["step_ms_sketch"]["count"] == 8
    # goodput buckets ride along for the fleet "gp%" column
    assert "productive_step" in beats[-1]["goodput"]


# -- the 2-process launcher e2e ----------------------------------------------

@pytest.mark.slow
def test_two_worker_launch_latches_fleet_verdicts(tmp_path):
    """Acceptance: a real `distributed.launch` pod of 2 fault-injected
    workers (rank 1 straggles at step 4 and desyncs at step 6) ends
    with both worker-named verdicts latched in the launcher's
    fleet.json, and `monitor_report --fleet` re-derives them offline
    from the raw heartbeat directory."""
    log_dir = tmp_path / "log"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_TRAINER_ID", None)
    env.pop("PADDLE_TRAINERS_NUM", None)
    env.pop("PT_HEARTBEAT_DIR", None)
    # 2 ranks: max/median is bounded by 2, so the injected 40ms-vs-5ms
    # straggler is judged at 1.5x (the knob exists for exactly this
    # fleet-width effect)
    env["PT_STRAGGLER_FACTOR"] = "1.5"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(log_dir),
         "--max_restart", "0",
         os.path.join(REPO, "tests", "fleet_worker.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    snap = json.loads((log_dir / "fleet.json").read_text())
    strag = snap["verdicts"]["straggler"]
    assert strag is not None and strag["rank"] == 1 and strag["step"] == 4
    desync = snap["verdicts"]["desync"]
    assert desync is not None and desync["step"] == 6
    assert desync["ranks"] == [0, 1]
    assert set(snap["workers"]) == {"0", "1"}
    assert snap["verdicts"]["silent"] is None

    # monitor_report --fleet over the raw heartbeat dir: the offline
    # detectors re-derive + render the same worker-named verdicts
    run_jsonl = tmp_path / "empty_run.jsonl"
    run_jsonl.write_text("")
    rep = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "monitor_report.py"),
         str(run_jsonl), "--fleet", str(log_dir / "heartbeats")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert rep.returncode == 0, rep.stderr[-2000:]
    assert "STRAGGLER: rank 1 at step 4" in rep.stdout
    assert "DP DESYNC: ranks [0, 1] at step 6" in rep.stdout


def test_monitor_report_fleet_json_input(tmp_path):
    """--fleet also accepts the launcher's fleet.json snapshot."""
    snap = {
        "nprocs": 2,
        "workers": {"0": {"step": 8, "step_ms": 5.0, "loss": 2.1},
                    "1": {"step": 8, "step_ms": 5.0, "loss": 2.1}},
        "fleet": {"min_step": 8, "max_step": 8, "step_ms": None},
        "verdicts": {"straggler": {"rank": 1, "step": 4, "step_ms": 40.0,
                                   "fleet_median_ms": 22.5, "factor": 1.5},
                     "desync": None, "silent": None},
        "postmortem": None,
    }
    fj = tmp_path / "fleet.json"
    fj.write_text(json.dumps(snap))
    run_jsonl = tmp_path / "empty_run.jsonl"
    run_jsonl.write_text("")
    rep = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "monitor_report.py"),
         str(run_jsonl), "--fleet", str(fj)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert rep.returncode == 0, rep.stderr[-2000:]
    assert "STRAGGLER: rank 1 at step 4" in rep.stdout
    assert "workers reporting: 2 / 2" in rep.stdout
