"""Test configuration.

Mirrors the reference's test strategy (SURVEY.md §4): single-host, no real
multi-chip hardware — distributed logic is exercised on a *virtual 8-device
CPU mesh* (`xla_force_host_platform_device_count`), the same trick as the
reference's fake `custom_cpu` plugin device (`test/custom_runtime/`).

IMPORTANT: these env vars must be set before jax initializes its backends,
hence this file must not import jax before setting them.
"""
import os

# force-override: the session env pins JAX_PLATFORMS=axon (the tunneled TPU);
# unit tests must run on the virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# persistent compilation cache: deep-model tests are compile-dominated on
# the CPU mesh (XLA:CPU only caches small executables today, so the win
# is modest here and real on TPU); dir survives across sessions
from paddle_tpu.utils.xla_cache import enable_compilation_cache  # noqa: E402

enable_compilation_cache("~/.cache/paddle_tpu_test_xla_cache")

# the axon sitecustomize pins jax_platforms="axon,cpu" at interpreter start
# (overriding env); force CPU-only here so tests never touch the TPU tunnel.
jax.config.update("jax_platforms", "cpu")

# blackbox postmortems off by default under pytest: many tests raise
# engine/NaN errors ON PURPOSE (often with the monitor enabled), and each
# would otherwise litter a serving_blackbox.json into the cwd. Tests that
# prove the dump path set PT_SERVE_BLACKBOX to a tmp path explicitly.
os.environ.setdefault("PT_SERVE_BLACKBOX", "0")

# numpy-parity tests need true fp32 contractions; production keeps the fast
# MXU default (bf16 inputs / fp32 accumulate), tunable via paddle flags.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield


@pytest.fixture(autouse=True, scope="module")
def _reset_auto_mesh():
    """Tear down any mesh a module left behind through the implicit
    ensure_env() path (one module's collective must not put the rest of
    the suite under a surprise 8-device mesh — pytest-randomly exposed
    this). Module-scoped, not per-test: a module fixture's model may
    legitimately live on the auto mesh for the whole module. Explicit
    fleet.init/init_mesh fixtures manage their own teardown."""
    yield
    from paddle_tpu.distributed import env as _env

    e = _env.get_env()
    if e is not None and getattr(e, "auto_initialized", False):
        _env.reset_env()


@pytest.fixture(scope="session")
def mesh_dp2_sep4():
    """The shared 2x4 (dp, sep) mesh for sequence-parallel attention
    tests (ring + ulysses)."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()).reshape(2, 4)
    return Mesh(devs, ("dp", "sep"))


def attn_qkv(b=2, s=64, h=2, d=16, seed=0):
    """Deterministic [b, s, h, d] q/k/v triples for attention parity."""
    rng = np.random.RandomState(seed)
    return (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
