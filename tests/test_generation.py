"""Compiled KV-cache generation (models/generation.py): greedy decode must
match naive full-forward argmax decode token for token."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _naive_greedy(model, prompt, n):
    ids = prompt.copy()
    for _ in range(n):
        logits = model(pt.to_tensor(ids)).numpy()
        ids = np.concatenate([ids, logits[:, -1].argmax(-1)[:, None]],
                             axis=1)
    return ids[:, prompt.shape[1]:]


def test_greedy_matches_full_forward(model):
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, model.config.vocab_size, (2, 5))
    ref = _naive_greedy(model, prompt, 6)
    got = generate(model, pt.to_tensor(prompt), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(got, ref)
    # method form
    got2 = model.generate(pt.to_tensor(prompt), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(got2, ref)


def test_gqa_greedy_matches(model):
    pt.seed(3)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, num_key_value_heads=2)
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, cfg.vocab_size, (1, 4))
    ref = _naive_greedy(m, prompt, 5)
    got = generate(m, pt.to_tensor(prompt), max_new_tokens=5).numpy()
    np.testing.assert_array_equal(got, ref)


def test_sampling_and_eos(model):
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, model.config.vocab_size, (2, 5))
    s1 = generate(model, pt.to_tensor(prompt), max_new_tokens=5,
                  do_sample=True, temperature=0.8, top_k=8,
                  seed=1).numpy()
    s2 = generate(model, pt.to_tensor(prompt), max_new_tokens=5,
                  do_sample=True, temperature=0.8, top_k=8,
                  seed=1).numpy()
    np.testing.assert_array_equal(s1, s2)  # seeded determinism
    s3 = generate(model, pt.to_tensor(prompt), max_new_tokens=5,
                  do_sample=True, temperature=0.8, top_p=0.9,
                  seed=2).numpy()
    assert s3.shape == (2, 5)
    # EOS masking: everything after the first EOS is EOS
    ref = _naive_greedy(model, prompt, 6)
    eos = int(ref[0, 0])
    ge = generate(model, pt.to_tensor(prompt), max_new_tokens=6,
                  eos_token_id=eos).numpy()
    first = int(np.argmax(ge[0] == eos))
    assert (ge[0][first:] == eos).all()


def test_bad_args(model):
    with pytest.raises(ValueError):
        generate(model, pt.to_tensor(np.zeros((1, 3), np.int64)),
                 max_new_tokens=0)


def test_moe_config_raises_clearly(model):
    from paddle_tpu.framework.errors import UnimplementedError

    pt.seed(5)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    m.config.moe_num_experts = 2  # the guard reads the config
    with pytest.raises(UnimplementedError, match="MoE"):
        generate(m, pt.to_tensor(np.zeros((1, 3), np.int64)))


def test_param_cache_reused(model):
    rng = np.random.RandomState(0)
    prompt = pt.to_tensor(rng.randint(0, model.config.vocab_size, (1, 4)))
    generate(model, prompt, max_new_tokens=2)
    cache1 = model._generation_params_cache
    generate(model, prompt, max_new_tokens=2)
    assert model._generation_params_cache is cache1  # no re-stack
    # big top_k clamps instead of crashing
    out = generate(model, prompt, max_new_tokens=2, do_sample=True,
                   top_k=10_000, seed=0)
    assert out.shape == [1, 2]


def test_config_jit_key_is_value_based(model):
    """The static jit key hashes the config FIELD VALUES: in-place
    mutation changes the key (no stale trace), while a fresh identical
    config hashes equal (no spurious retrace)."""
    from paddle_tpu.models.generation import _GenCfg

    c1 = _GenCfg(model.config)
    old = model.config.rope_theta
    try:
        model.config.rope_theta = 17.0
        c2 = _GenCfg(model.config)
    finally:
        model.config.rope_theta = old
    assert c1 != c2 and hash(c1) != hash(c2)
    fresh = _GenCfg(LlamaConfig.tiny(num_hidden_layers=2))
    assert fresh == _GenCfg(LlamaConfig.tiny(num_hidden_layers=2))
    assert hash(fresh) == hash(_GenCfg(LlamaConfig.tiny(num_hidden_layers=2)))


def test_left_padded_batch_matches_unpadded(model):
    """A left-padded batch with attention_mask generates exactly what each
    prompt produces alone (pad slots hidden, positions shifted)."""
    rng = np.random.RandomState(9)
    v = model.config.vocab_size
    p_short = rng.randint(0, v, (1, 3))
    p_long = rng.randint(0, v, (1, 5))
    ref_short = generate(model, pt.to_tensor(p_short),
                         max_new_tokens=4).numpy()
    ref_long = generate(model, pt.to_tensor(p_long),
                        max_new_tokens=4).numpy()
    pad = 0
    batch = np.concatenate(
        [np.concatenate([[[pad, pad]], p_short], axis=1), p_long], axis=0)
    mask = np.array([[0, 0, 1, 1, 1], [1, 1, 1, 1, 1]])
    got = generate(model, pt.to_tensor(batch), max_new_tokens=4,
                   attention_mask=pt.to_tensor(mask)).numpy()
    import jax

    if jax.default_backend() == "cpu":
        np.testing.assert_array_equal(got[0:1], ref_short)
        np.testing.assert_array_equal(got[1:2], ref_long)
    else:  # accelerator reduction orders can flip near-tied argmaxes
        assert (got[0] == ref_short[0]).mean() >= 0.75
        assert (got[1] == ref_long[0]).mean() >= 0.75
    # right padding is rejected loudly
    with pytest.raises(ValueError, match="LEFT"):
        generate(model, pt.to_tensor(batch), max_new_tokens=2,
                 attention_mask=pt.to_tensor(mask[:, ::-1].copy()))


def test_mask_validation(model):
    rng = np.random.RandomState(11)
    prompt = rng.randint(0, model.config.vocab_size, (1, 4))
    # interior zero rejected
    with pytest.raises(ValueError, match="LEFT"):
        generate(model, pt.to_tensor(prompt), max_new_tokens=2,
                 attention_mask=pt.to_tensor(np.array([[1, 0, 1, 1]])))
    # shape mismatch rejected
    with pytest.raises(ValueError, match="shape"):
        generate(model, pt.to_tensor(prompt), max_new_tokens=2,
                 attention_mask=pt.to_tensor(np.array([[1, 1, 1]])))
    # all-ones mask == no mask (same result, shared program)
    ref = generate(model, pt.to_tensor(prompt), max_new_tokens=3).numpy()
    got = generate(model, pt.to_tensor(prompt), max_new_tokens=3,
                   attention_mask=pt.to_tensor(np.ones((1, 4)))).numpy()
    np.testing.assert_array_equal(got, ref)
    # method form forwards the mask
    got2 = model.generate(pt.to_tensor(prompt), max_new_tokens=3,
                          attention_mask=pt.to_tensor(
                              np.ones((1, 4)))).numpy()
    np.testing.assert_array_equal(got2, ref)


def test_int8_weight_only_decode_close_to_fp():
    # weight-only per-channel int8 (decode bandwidth lever): logits of
    # the quantized forward stay close to fp, and generate() runs
    # end-to-end with int8 packs
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate
    from paddle_tpu.models import generation as gen

    pt.seed(3)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8))

    p_fp = gen._collect_params(model, int8_weights=False)
    p_q = gen._collect_params(model, int8_weights=True)
    # pack structure: int8 payload + fp scale, ~halved bytes on matmuls
    assert p_q["qkv"]["q"].dtype == jnp.int8
    b = ids.shape[0]
    ck = jnp.zeros((cfg.num_hidden_layers, b, 16,
                    cfg.num_key_value_heads or cfg.num_attention_heads,
                    cfg.hidden_size // cfg.num_attention_heads),
                   jnp.dtype(cfg.dtype))
    lf, _, _ = gen._forward(p_fp, jnp.asarray(ids), ck, ck, 8, cfg)
    lq, _, _ = gen._forward(p_q, jnp.asarray(ids), ck, ck, 8, cfg)
    a = np.asarray(lf).ravel()
    q = np.asarray(lq).ravel()
    cos = float(np.dot(a, q) / (np.linalg.norm(a) * np.linalg.norm(q)))
    assert cos > 0.995, cos

    out = generate(model, pt.to_tensor(ids), max_new_tokens=4,
                   int8_weights=True)
    assert np.asarray(out.numpy()).shape == (2, 4)
    out2 = generate(model, pt.to_tensor(ids), max_new_tokens=4,
                    int8_weights=True)
    np.testing.assert_array_equal(np.asarray(out.numpy()),
                                  np.asarray(out2.numpy()))


def test_int8_decode_with_left_padding():
    # int8 weight packs compose with the left-padded attention_mask path
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate

    pt.seed(3)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    ids = rng.randint(4, cfg.vocab_size, (2, 8))
    am = np.ones((2, 8), np.int64)
    am[0, :3] = 0  # row 0 left-padded
    out = generate(m, pt.to_tensor(ids), max_new_tokens=4,
                   attention_mask=pt.to_tensor(am), int8_weights=True)
    arr = np.asarray(out.numpy())
    assert arr.shape == (2, 4)
    assert (arr >= 0).all() and (arr < cfg.vocab_size).all()
