"""Profiler tests (reference `test/legacy_test/test_profiler.py`,
`test_newprofiler.py`)."""
import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler


class TestProfiler:
    def test_record_and_export(self, tmp_path):
        p = profiler.Profiler()
        p.start()
        with profiler.RecordEvent("my_region"):
            x = paddle.ones([4, 4])
            (x @ x).sum()
        p.stop()
        path = p.export(str(tmp_path / "trace.json"))
        data = json.load(open(path))
        names = [e["name"] for e in data["traceEvents"]]
        assert "my_region" in names
        assert "matmul" in names or any("matmul" in n for n in names)

    def test_summary_counts_ops(self, capsys):
        p = profiler.Profiler()
        p.start()
        x = paddle.ones([2, 2])
        for _ in range(3):
            x = x + 1
        p.stop()
        table = p.summary()
        assert "add" in table

    def test_scheduler_states(self):
        sched = profiler.make_scheduler(closed=1, ready=1, record=2, repeat=1)
        states = [sched(i) for i in range(4)]
        assert states[0] == profiler.ProfilerState.CLOSED
        assert states[1] == profiler.ProfilerState.READY
        assert states[2] == profiler.ProfilerState.RECORD
        assert states[3] == profiler.ProfilerState.RECORD_AND_RETURN

    def test_scheduler_period_one(self):
        # closed=0 ready=0 record=1: every step is the period's last ->
        # RECORD_AND_RETURN forever (no repeat cap)
        sched = profiler.make_scheduler()
        assert [sched(i) for i in range(3)] == \
            [profiler.ProfilerState.RECORD_AND_RETURN] * 3
        # with repeat=2 the scheduler closes after 2 periods
        sched = profiler.make_scheduler(record=1, repeat=2)
        assert sched(0) == profiler.ProfilerState.RECORD_AND_RETURN
        assert sched(1) == profiler.ProfilerState.RECORD_AND_RETURN
        assert sched(2) == profiler.ProfilerState.CLOSED
        assert sched(100) == profiler.ProfilerState.CLOSED

    def test_scheduler_skip_first_repeat_interaction(self):
        # repeat counts periods AFTER skip_first, not from step 0
        sched = profiler.make_scheduler(closed=1, record=1, repeat=2,
                                        skip_first=3)
        assert [sched(i) for i in range(3)] == \
            [profiler.ProfilerState.CLOSED] * 3          # skipped
        assert sched(3) == profiler.ProfilerState.CLOSED  # period 1 closed
        assert sched(4) == profiler.ProfilerState.RECORD_AND_RETURN
        assert sched(5) == profiler.ProfilerState.CLOSED  # period 2 closed
        assert sched(6) == profiler.ProfilerState.RECORD_AND_RETURN
        assert sched(7) == profiler.ProfilerState.CLOSED  # repeat exhausted
        assert sched(50) == profiler.ProfilerState.CLOSED

    def test_scheduler_zero_period_raises(self):
        import pytest

        with pytest.raises(ValueError):
            profiler.make_scheduler(closed=0, ready=0, record=0)

    def test_on_trace_ready_handler(self, tmp_path):
        handler = profiler.export_chrome_tracing(str(tmp_path))
        with profiler.Profiler(on_trace_ready=handler):
            paddle.ones([2]) + 1
        files = os.listdir(tmp_path)
        assert any(f.endswith(".json") for f in files)

    def test_monitor_counters_exported_as_chrome_counter_events(
            self, tmp_path):
        from paddle_tpu import monitor

        monitor.reset()
        monitor.enable()
        try:
            p = profiler.Profiler()
            p.start()
            x = paddle.ones([2, 2])
            x + 1
            p.step()
            p.stop()
            path = p.export(str(tmp_path / "trace.json"))
        finally:
            monitor.disable()
            monitor.reset()
        data = json.load(open(path))
        counters = [e for e in data["traceEvents"] if e.get("ph") == "C"
                    and e["name"].startswith("monitor/")]
        assert any(e["name"] == "monitor/dispatch/op_apply"
                   for e in counters)
        # Perfetto JSON-loader contract: every counter event carries
        # name/ph/ts/pid and a flat numeric args dict
        for e in counters:
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["pid"], int)
            assert e["args"] and all(
                isinstance(v, (int, float)) for v in e["args"].values())
        # the whole file still round-trips as one JSON object with
        # traceEvents (what Perfetto's JSON loader requires)
        assert isinstance(data["traceEvents"], list)

    def test_benchmark_ips(self):
        b = profiler.Benchmark()
        b.begin()
        import time

        for _ in range(3):
            time.sleep(0.01)
            b.step(num_samples=8)
        info = b.step_info()
        assert "ips" in info
        assert b.ips > 0
