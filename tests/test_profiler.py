"""Profiler tests (reference `test/legacy_test/test_profiler.py`,
`test_newprofiler.py`)."""
import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler


class TestProfiler:
    def test_record_and_export(self, tmp_path):
        p = profiler.Profiler()
        p.start()
        with profiler.RecordEvent("my_region"):
            x = paddle.ones([4, 4])
            (x @ x).sum()
        p.stop()
        path = p.export(str(tmp_path / "trace.json"))
        data = json.load(open(path))
        names = [e["name"] for e in data["traceEvents"]]
        assert "my_region" in names
        assert "matmul" in names or any("matmul" in n for n in names)

    def test_summary_counts_ops(self, capsys):
        p = profiler.Profiler()
        p.start()
        x = paddle.ones([2, 2])
        for _ in range(3):
            x = x + 1
        p.stop()
        table = p.summary()
        assert "add" in table

    def test_scheduler_states(self):
        sched = profiler.make_scheduler(closed=1, ready=1, record=2, repeat=1)
        states = [sched(i) for i in range(4)]
        assert states[0] == profiler.ProfilerState.CLOSED
        assert states[1] == profiler.ProfilerState.READY
        assert states[2] == profiler.ProfilerState.RECORD
        assert states[3] == profiler.ProfilerState.RECORD_AND_RETURN

    def test_on_trace_ready_handler(self, tmp_path):
        handler = profiler.export_chrome_tracing(str(tmp_path))
        with profiler.Profiler(on_trace_ready=handler):
            paddle.ones([2]) + 1
        files = os.listdir(tmp_path)
        assert any(f.endswith(".json") for f in files)

    def test_benchmark_ips(self):
        b = profiler.Benchmark()
        b.begin()
        import time

        for _ in range(3):
            time.sleep(0.01)
            b.step(num_samples=8)
        info = b.step_info()
        assert "ips" in info
        assert b.ips > 0
