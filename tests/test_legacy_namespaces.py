"""Top-level legacy namespace parity: paddle.batch / reader / dataset /
callbacks / regularizer / hub / sysconfig / cost_model
(reference `python/paddle/{batch,reader,dataset,callbacks,regularizer,
hub,sysconfig,cost_model}`)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt


class TestBatchAndReader:
    def test_batch(self):
        r = pt.batch(lambda: iter(range(10)), 3)
        batches = list(r())
        assert batches == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
        r2 = pt.batch(lambda: iter(range(10)), 3, drop_last=True)
        assert len(list(r2())) == 3
        with pytest.raises(ValueError):
            pt.batch(lambda: iter([]), 0)

    def test_reader_decorators(self):
        base = lambda: iter(range(8))  # noqa: E731
        assert list(pt.reader.firstn(base, 3)()) == [0, 1, 2]
        assert list(pt.reader.chain(base, base)()) == list(range(8)) * 2
        assert sorted(pt.reader.shuffle(base, 4)()) == list(range(8))
        assert list(pt.reader.map_readers(lambda a, b: a + b,
                                          base, base)()) == \
            [2 * i for i in range(8)]
        assert list(pt.reader.buffered(base, 2)()) == list(range(8))
        cached = pt.reader.cache(base)
        assert list(cached()) == list(cached()) == list(range(8))
        comp = pt.reader.compose(base, base)
        assert list(comp())[0] == (0, 0)
        got = sorted(pt.reader.xmap_readers(
            lambda x: x * 10, base, 2, 4)())
        assert got == [10 * i for i in range(8)]
        ordered = list(pt.reader.xmap_readers(
            lambda x: x * 10, base, 3, 4, order=True)())
        assert ordered == [10 * i for i in range(8)]
        multi = sorted(pt.reader.multiprocess_reader([base, base])())
        assert multi == sorted(list(range(8)) * 2)

    def test_compose_alignment(self):
        a = lambda: iter(range(3))  # noqa: E731
        b = lambda: iter(range(5))  # noqa: E731
        with pytest.raises(ValueError):
            list(pt.reader.compose(a, b)())

    def test_worker_exceptions_propagate(self):
        def bad():
            yield 1
            raise RuntimeError("reader broke")

        with pytest.raises(RuntimeError, match="reader broke"):
            list(pt.reader.buffered(bad, 2)())
        with pytest.raises(ZeroDivisionError):
            list(pt.reader.xmap_readers(lambda x: 1 // x,
                                        lambda: iter([1, 0]), 2, 4)())
        with pytest.raises(RuntimeError, match="reader broke"):
            list(pt.reader.multiprocess_reader([bad])())

    def test_dataset_import_forms(self):
        import importlib

        m = importlib.import_module("paddle_tpu.dataset.mnist")
        assert hasattr(m, "train")
        c = importlib.import_module("paddle_tpu.dataset.common")
        assert hasattr(c, "DATA_HOME")


class TestSmallNamespaces:
    def test_regularizer_alias(self):
        assert pt.regularizer.L2Decay is pt.optimizer.L2Decay
        reg = pt.regularizer.L2Decay(1e-4)
        assert reg is not None

    def test_callbacks_alias(self):
        assert issubclass(pt.callbacks.EarlyStopping, pt.callbacks.Callback)
        assert pt.callbacks.LRScheduler is not None

    def test_sysconfig(self):
        inc = pt.sysconfig.get_include()
        lib = pt.sysconfig.get_lib()
        assert "paddle_tpu" in inc and isinstance(lib, str)

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny_model(n=2):\n"
            "    '''build a tiny model'''\n"
            "    return ['layer'] * n\n")
        assert pt.hub.list(str(tmp_path)) == ["tiny_model"]
        assert "tiny" in pt.hub.help(str(tmp_path), "tiny_model")
        assert pt.hub.load(str(tmp_path), "tiny_model", n=3) == ["layer"] * 3
        with pytest.raises(RuntimeError, match="network"):
            pt.hub.load("user/repo", "m", source="github")

    def test_dataset_no_egress_error(self):
        with pytest.raises(RuntimeError, match="egress"):
            pt.dataset.common.download("http://x", "mnist")
        # readers exist and fail lazily (no local cache in CI)
        r = pt.dataset.mnist.train()
        with pytest.raises(Exception):  # noqa: B017 — absent local data
            next(iter(r()))


class TestCostModel:
    def test_profile_measure(self):
        cm = pt.cost_model.CostModel()
        startup, main = cm.build_program()
        table = cm.profile_measure(startup_program=startup,
                                   main_program=main, repeat=2)
        assert table and all({"op", "time_ms", "calls"} <= set(r) for r in table)
        data = cm.static_cost_data()
        ops = [r["op"] for r in data]
        assert "matmul" in ops or any("mean" in o for o in ops)
        t = cm.get_static_op_time(ops[0])
        assert t["op_time"] >= 0
