"""paddle.static.nn builders + control flow (reference
`python/paddle/static/nn/{common,control_flow}.py`)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import static


class TestBuilders:
    def test_fc_program(self):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("X", [None, 8], "float32")
            h = static.nn.fc(x, 16, activation="relu")
            h = static.nn.layer_norm(h)
            out = static.nn.fc(h, 4)
        exe = static.Executor()
        res = exe.run(main,
                      feed={"X": np.random.randn(5, 8).astype(np.float32)},
                      fetch_list=[out])
        assert res[0].shape == (5, 4)

    def test_conv_and_norms(self):
        x = pt.to_tensor(np.random.randn(2, 3, 8, 8).astype(np.float32))
        y = static.nn.conv2d(x, 6, 3, padding=1, act="relu")
        assert y.shape == [2, 6, 8, 8] and float(y.min().numpy()) >= 0
        y = static.nn.batch_norm(y)
        assert y.shape == [2, 6, 8, 8]
        y = static.nn.group_norm(y, groups=2)
        assert y.shape == [2, 6, 8, 8]
        y = static.nn.instance_norm(y)
        assert y.shape == [2, 6, 8, 8]
        up = static.nn.conv2d_transpose(x, 4, 2, stride=2)
        assert up.shape[1] == 4 and up.shape[2] == 16
        v = pt.to_tensor(np.random.randn(2, 3, 4, 4, 4).astype(np.float32))
        assert static.nn.conv3d(v, 5, 3, padding=1).shape == [2, 5, 4, 4, 4]

    def test_fc_flatten_dims(self):
        x = pt.to_tensor(np.random.randn(2, 3, 4).astype(np.float32))
        # nfd=1: trailing dims flatten into features -> [2, 5]
        assert static.nn.fc(x, 5).shape == [2, 5]
        # nfd=2: leading [2, 3] preserved -> [2, 3, 5]
        assert static.nn.fc(x, 5, num_flatten_dims=2).shape == [2, 3, 5]

    def test_batch_norm_3d(self):
        v = pt.to_tensor(np.random.randn(2, 3, 4, 4, 4).astype(np.float32))
        y = static.nn.batch_norm(static.nn.conv3d(v, 5, 3, padding=1))
        assert y.shape == [2, 5, 4, 4, 4]

    def test_batch_norm_nhwc(self):
        x = pt.to_tensor(np.random.randn(2, 8, 8, 3).astype(np.float32))
        y = static.nn.batch_norm(x, data_layout="NHWC")
        assert y.shape == [2, 8, 8, 3]
        g = static.nn.group_norm(
            pt.to_tensor(np.random.randn(2, 8, 8, 4).astype(np.float32)),
            groups=2, data_layout="NHWC")
        assert g.shape == [2, 8, 8, 4]

    def test_embedding_prelu_bilinear(self):
        ids = pt.to_tensor(np.array([[1, 2], [3, 0]], np.int64))
        e = static.nn.embedding(ids, size=[10, 6])
        assert e.shape == [2, 2, 6]
        x = pt.to_tensor(np.random.randn(2, 4).astype(np.float32))
        assert static.nn.prelu(x).shape == [2, 4]
        a = pt.to_tensor(np.random.randn(3, 4).astype(np.float32))
        b = pt.to_tensor(np.random.randn(3, 5).astype(np.float32))
        assert static.nn.bilinear_tensor_product(a, b, 7).shape == [3, 7]


class TestControlFlow:
    def test_cond(self):
        p = pt.to_tensor(np.array(1.0, np.float32))
        t = lambda: pt.to_tensor(np.float32(2.0)) * 3  # noqa: E731
        f = lambda: pt.to_tensor(np.float32(-1.0))  # noqa: E731
        assert float(static.nn.cond(p > 0, t, f).numpy()) == 6.0
        assert float(static.nn.cond(p < 0, t, f).numpy()) == -1.0

    def test_case_first_match_wins(self):
        p = pt.to_tensor(np.array(1.0, np.float32))
        got = static.nn.case(
            [(p > 0, lambda: pt.to_tensor(np.float32(1.0))),
             (p > -1, lambda: pt.to_tensor(np.float32(2.0)))],
            default=lambda: pt.to_tensor(np.float32(9.0)))
        assert float(got.numpy()) == 1.0
        got = static.nn.case(
            [(p < 0, lambda: pt.to_tensor(np.float32(1.0)))],
            default=lambda: pt.to_tensor(np.float32(9.0)))
        assert float(got.numpy()) == 9.0
        with pytest.raises(ValueError):
            static.nn.case([])

    def test_switch_case(self):
        fns = [lambda: pt.to_tensor(np.float32(10.0)),
               lambda: pt.to_tensor(np.float32(20.0)),
               lambda: pt.to_tensor(np.float32(30.0))]
        idx = pt.to_tensor(np.array(1, np.int32))
        assert float(static.nn.switch_case(idx, fns).numpy()) == 20.0
        # dict with sparse keys goes through the case() chain
        got = static.nn.switch_case(
            pt.to_tensor(np.array(7, np.int32)),
            {2: fns[0], 7: fns[1]},
            default=lambda: pt.to_tensor(np.float32(0.0)))
        assert float(got.numpy()) == 20.0
        # out-of-range (incl. negative) index dispatches to default
        neg = static.nn.switch_case(
            pt.to_tensor(np.array(-1, np.int32)), fns[:2],
            default=lambda: pt.to_tensor(np.float32(99.0)))
        assert float(neg.numpy()) == 99.0
        # no default: unmatched index runs the largest-index branch
        nd = static.nn.switch_case(
            pt.to_tensor(np.array(-1, np.int32)), fns)
        assert float(nd.numpy()) == 30.0

    def test_while_loop(self):
        i = pt.to_tensor(np.array(0, np.int32))
        s = pt.to_tensor(np.array(0.0, np.float32))
        iv, sv = static.nn.while_loop(
            lambda i, s: i < 5,
            lambda i, s: (i + 1, s + i.astype("float32")), (i, s))
        assert int(iv.numpy()) == 5 and float(sv.numpy()) == 10.0

    def test_py_func(self):
        out = pt.zeros([3], "float32")
        got = static.nn.py_func(
            lambda a: a * 2 + 1,
            pt.to_tensor(np.arange(3, dtype=np.float32)), out)
        np.testing.assert_allclose(got.numpy(), [1, 3, 5])
