"""Round-3 op-coverage additions: parity tests vs numpy/scipy references.

Ops audited against `phi/api/yaml/ops.yaml` (see docs/OP_COVERAGE.md):
logit, i0e/i1/i1e, polygamma, renorm, inverse, clip_by_norm,
squared_l2_norm, frobenius_norm, diag_embed, fill_diagonal(_tensor),
fill, thresholded_relu, gather_tree, temporal_shift, huber_loss,
edit_distance, hsigmoid_loss, max-pool-with-index, max_unpool1/2/3d.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


class TestMathAdditions:
    def test_logit(self):
        x = paddle.to_tensor(np.asarray([0.2, 0.5, 0.9], "float32"))
        np.testing.assert_allclose(
            paddle.logit(x).numpy(),
            np.log(np.asarray([0.2, 0.5, 0.9]) / (1 - np.asarray([0.2, 0.5, 0.9]))),
            rtol=1e-5)
        # eps clamps out-of-range values to finite results
        y = paddle.to_tensor(np.asarray([0.0, 1.0], "float32"))
        out = paddle.logit(y, eps=1e-3).numpy()
        assert np.isfinite(out).all()

    def test_bessel(self):
        from scipy import special

        x = np.linspace(0.1, 4.0, 7).astype("float32")
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.i0e(t).numpy(), special.i0e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1(t).numpy(), special.i1(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1e(t).numpy(), special.i1e(x), rtol=1e-5)

    def test_polygamma(self):
        from scipy import special

        x = np.linspace(0.5, 3.0, 5).astype("float32")
        t = paddle.to_tensor(x)
        for n in (0, 1, 2):
            np.testing.assert_allclose(
                paddle.polygamma(t, n).numpy(), special.polygamma(n, x),
                rtol=2e-4, atol=1e-5)
        with pytest.raises(ValueError):
            paddle.polygamma(t, -1)

    def test_renorm(self):
        x = paddle.to_tensor(np.asarray([[3., 4.], [0.3, 0.4]], "float32"))
        out = paddle.renorm(x, p=2.0, axis=0, max_norm=1.0).numpy()
        np.testing.assert_allclose(out[0], [0.6, 0.8], rtol=1e-5)
        np.testing.assert_allclose(out[1], [0.3, 0.4], rtol=1e-5)  # unchanged

    def test_inverse_and_grad(self):
        a = np.asarray([[2., 1.], [1., 3.]], "float32")
        x = paddle.to_tensor(a, stop_gradient=False)
        inv = paddle.inverse(x)
        np.testing.assert_allclose(inv.numpy(), np.linalg.inv(a), rtol=1e-5)
        inv.sum().backward()
        assert x.grad is not None

    def test_clip_by_norm_and_squared_l2(self):
        x = paddle.to_tensor(np.asarray([3., 4.], "float32"))
        np.testing.assert_allclose(
            paddle.clip_by_norm(x, 1.0).numpy(), [0.6, 0.8], rtol=1e-5)
        np.testing.assert_allclose(
            paddle.clip_by_norm(x, 10.0).numpy(), [3., 4.])
        np.testing.assert_allclose(
            float(paddle.squared_l2_norm(x).numpy()), 25.0)

    def test_frobenius_norm(self):
        a = np.random.default_rng(0).standard_normal((3, 4)).astype("float32")
        np.testing.assert_allclose(
            float(paddle.frobenius_norm(paddle.to_tensor(a)).numpy()),
            np.linalg.norm(a), rtol=1e-5)


class TestManipulationAdditions:
    def test_diag_embed(self):
        d = paddle.to_tensor(np.asarray([[1., 2.], [3., 4.]], "float32"))
        out = paddle.diag_embed(d)
        assert out.shape == [2, 2, 2]
        np.testing.assert_allclose(out.numpy()[0], np.diag([1., 2.]))
        out_off = paddle.diag_embed(d, offset=1)
        assert out_off.shape == [2, 3, 3]
        np.testing.assert_allclose(
            out_off.numpy()[1], np.diag([3., 4.], k=1))

    def test_diag_embed_dims(self):
        d = paddle.to_tensor(np.asarray([1., 2., 3.], "float32"))
        out = paddle.diag_embed(d, offset=0, dim1=0, dim2=1)
        np.testing.assert_allclose(out.numpy(), np.diag([1., 2., 3.]))

    def test_fill_diagonal_reference_example(self):
        x = paddle.ones([4, 3]) * 2
        x.fill_diagonal_(1.0)
        np.testing.assert_allclose(
            x.numpy(),
            [[1., 2., 2.], [2., 1., 2.], [2., 2., 1.], [2., 2., 2.]])

    def test_fill_diagonal_offset(self):
        x = paddle.zeros([3, 4])
        out = paddle.tensor.manipulation.fill_diagonal(x, 5.0, offset=1)
        np.testing.assert_allclose(out.numpy(), np.diag([5.] * 3, k=1)[:3])

    def test_fill_diagonal_tensor(self):
        x = paddle.zeros([3, 3])
        y = paddle.to_tensor(np.asarray([1., 2., 3.], "float32"))
        out = paddle.fill_diagonal_tensor(x, y)
        np.testing.assert_allclose(out.numpy(), np.diag([1., 2., 3.]))

    def test_fill(self):
        x = paddle.zeros([2, 2])
        np.testing.assert_allclose(
            paddle.tensor.manipulation.fill(x, 7.0).numpy(), np.full((2, 2), 7.0))


class TestFunctionalAdditions:
    def test_thresholded_relu(self):
        x = paddle.to_tensor(np.asarray([0.5, 1.5, -1.0], "float32"))
        np.testing.assert_allclose(
            F.thresholded_relu(x).numpy(), [0., 1.5, 0.])
        np.testing.assert_allclose(
            F.thresholded_relu(x, threshold=0.2).numpy(), [0.5, 1.5, 0.])

    def test_gather_tree_reference_example(self):
        ids = paddle.to_tensor(np.asarray(
            [[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]], "int64"))
        parents = paddle.to_tensor(np.asarray(
            [[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]], "int64"))
        out = F.gather_tree(ids, parents)
        np.testing.assert_array_equal(
            out.numpy(),
            [[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]])

    def test_temporal_shift(self):
        x = np.arange(2 * 2 * 4 * 1 * 1, dtype="float32").reshape(4, 4, 1, 1)
        out = F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                               shift_ratio=0.25).numpy()
        v = x.reshape(2, 2, 4, 1, 1)
        # channel 0 shifts backward (from t-1), channel 1 forward (t+1)
        assert out.reshape(2, 2, 4)[0, 0, 0] == 0  # t=0 gets zero pad
        assert out.reshape(2, 2, 4)[0, 1, 0] == v[0, 0, 0, 0, 0]
        assert out.reshape(2, 2, 4)[0, 0, 1] == v[0, 1, 1, 0, 0]

    def test_huber_loss(self):
        a = paddle.to_tensor(np.asarray([0.0, 2.0], "float32"))
        b = paddle.to_tensor(np.asarray([0.5, 0.0], "float32"))
        out = F.huber_loss(a, b, delta=1.0, reduction="none").numpy()
        np.testing.assert_allclose(out, [0.125, 1.5])

    def test_edit_distance(self):
        # "kitten" vs "sitting" = 3
        hyp = paddle.to_tensor(np.asarray(
            [[ord(c) for c in "kitten."]], "int64"))
        ref = paddle.to_tensor(np.asarray(
            [[ord(c) for c in "sitting"]], "int64"))
        dist, n = F.edit_distance(
            hyp, ref, normalized=False,
            input_length=paddle.to_tensor(np.asarray([6], "int64")),
            label_length=paddle.to_tensor(np.asarray([7], "int64")))
        assert float(dist.numpy()[0]) == 3.0
        assert int(n.numpy()[0]) == 1
        dn, _ = F.edit_distance(
            hyp, ref, normalized=True,
            input_length=paddle.to_tensor(np.asarray([6], "int64")),
            label_length=paddle.to_tensor(np.asarray([7], "int64")))
        np.testing.assert_allclose(float(dn.numpy()[0]), 3.0 / 7, rtol=1e-6)

    def test_edit_distance_batch_and_empty(self):
        hyp = paddle.to_tensor(np.asarray([[1, 2, 3], [1, 2, 3]], "int64"))
        ref = paddle.to_tensor(np.asarray([[1, 2, 3], [4, 5, 6]], "int64"))
        dist, _ = F.edit_distance(hyp, ref, normalized=False)
        np.testing.assert_allclose(dist.numpy()[:, 0], [0.0, 3.0])
        d0, _ = F.edit_distance(
            hyp, ref, normalized=False,
            input_length=paddle.to_tensor(np.asarray([0, 3], "int64")),
            label_length=paddle.to_tensor(np.asarray([3, 0], "int64")))
        np.testing.assert_allclose(d0.numpy()[:, 0], [3.0, 3.0])

    def test_hsigmoid_loss(self):
        rng = np.random.default_rng(0)
        num_classes, d, b = 6, 8, 4
        x = paddle.to_tensor(rng.standard_normal((b, d)).astype("float32"),
                             stop_gradient=False)
        w = paddle.to_tensor(
            rng.standard_normal((num_classes - 1, d)).astype("float32"),
            stop_gradient=False)
        bias = paddle.to_tensor(
            rng.standard_normal((num_classes - 1,)).astype("float32"))
        label = paddle.to_tensor(np.asarray([0, 1, 4, 5], "int64"))
        out = F.hsigmoid_loss(x, label, num_classes, w, bias)
        assert out.shape == [b, 1]
        assert np.isfinite(out.numpy()).all()
        assert (out.numpy() > 0).all()  # -log p is positive
        out.sum().backward()
        assert x.grad is not None and w.grad is not None
        # sum over all classes of p(class) == 1 for a complete binary tree
        probs = []
        for c in range(num_classes):
            lab_c = paddle.to_tensor(np.full((b,), c, "int64"))
            nll = F.hsigmoid_loss(
                paddle.to_tensor(x.numpy()), lab_c, num_classes,
                paddle.to_tensor(w.numpy()), bias)
            probs.append(np.exp(-nll.numpy()[:, 0]))
        np.testing.assert_allclose(np.sum(probs, axis=0), np.ones(b),
                                   rtol=1e-4)


class TestPoolIndexUnpool:
    def test_pool_index_matches_plain(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((2, 3, 8, 8)).astype("float32"))
        out, idx = F.max_pool2d(x, 2, 2, return_mask=True)
        np.testing.assert_allclose(
            out.numpy(), F.max_pool2d(x, 2, 2).numpy())
        # indices point at the max values
        flat = x.numpy().reshape(2, 3, 64)
        gathered = np.take_along_axis(flat, idx.numpy().reshape(2, 3, -1), -1)
        np.testing.assert_allclose(gathered, out.numpy().reshape(2, 3, -1))

    def test_pool_index_padded(self):
        rng = np.random.default_rng(1)
        x = paddle.to_tensor(rng.standard_normal((1, 2, 7, 7)).astype("float32"))
        out, idx = F.max_pool2d(x, 3, 2, padding=1, return_mask=True)
        np.testing.assert_allclose(
            out.numpy(), F.max_pool2d(x, 3, 2, padding=1).numpy())
        assert (idx.numpy() >= 0).all() and (idx.numpy() < 49).all()

    def test_unpool_roundtrip_2d(self):
        rng = np.random.default_rng(2)
        x = paddle.to_tensor(rng.standard_normal((2, 3, 8, 8)).astype("float32"))
        out, idx = F.max_pool2d(x, 2, 2, return_mask=True)
        back = F.max_unpool2d(out, idx, 2, 2)
        assert back.shape == [2, 3, 8, 8]
        nz = back.numpy() != 0
        assert nz.sum() == 2 * 3 * 16
        np.testing.assert_allclose(back.numpy()[nz].sum(),
                                   out.numpy().sum(), rtol=1e-5)

    def test_unpool_1d_3d(self):
        rng = np.random.default_rng(3)
        x1 = paddle.to_tensor(rng.standard_normal((2, 3, 16)).astype("float32"))
        o1, i1 = F.max_pool1d(x1, 4, 4, return_mask=True)
        assert F.max_unpool1d(o1, i1, 4, 4).shape == [2, 3, 16]
        x3 = paddle.to_tensor(
            rng.standard_normal((1, 2, 4, 4, 4)).astype("float32"))
        o3, i3 = F.max_pool3d(x3, 2, 2, return_mask=True)
        assert F.max_unpool3d(o3, i3, 2, 2).shape == [1, 2, 4, 4, 4]

    def test_unpool_grad(self):
        x = paddle.to_tensor(
            np.random.default_rng(4).standard_normal((1, 1, 4, 4))
            .astype("float32"), stop_gradient=False)
        out, idx = F.max_pool2d(x, 2, 2, return_mask=True)
        F.max_unpool2d(out, idx, 2, 2).sum().backward()
        # gradient flows only to the max positions, one per window
        assert x.grad.numpy().astype(bool).sum() == 4


class TestReviewRegressions:
    def test_pool_index_ceil_mode_matches_plain(self):
        rng = np.random.default_rng(5)
        x = paddle.to_tensor(rng.standard_normal((2, 1, 5, 5)).astype("f4"))
        plain = F.max_pool2d(x, 2, 2, ceil_mode=True)
        out, idx = F.max_pool2d(x, 2, 2, ceil_mode=True, return_mask=True)
        assert out.shape == plain.shape == [2, 1, 3, 3]
        np.testing.assert_allclose(out.numpy(), plain.numpy())

    def test_fill_diagonal_wrap_matches_numpy(self):
        a = np.zeros((7, 3), "float32")
        np.fill_diagonal(a, 4.0, wrap=True)
        x = paddle.zeros([7, 3])
        out = paddle.tensor.manipulation.fill_diagonal(x, 4.0, wrap=True)
        np.testing.assert_allclose(out.numpy(), a)

    def test_maxpool_layer_returns_mask(self):
        import paddle_tpu.nn as nn

        x = paddle.to_tensor(
            np.random.default_rng(6).standard_normal((1, 2, 4, 4))
            .astype("f4"))
        out, idx = nn.MaxPool2D(2, 2, return_mask=True)(x)
        assert out.shape == [1, 2, 2, 2] and idx.shape == [1, 2, 2, 2]

    def test_edit_distance_no_dtype_warning(self):
        import warnings

        hyp = paddle.to_tensor(np.asarray([[1, 2, 3]], "int64"))
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            F.edit_distance(hyp, hyp, normalized=False)

    def test_lu_unpack_nonsquare(self):
        for shape in [(4, 2), (2, 4)]:
            a = np.random.default_rng(7).standard_normal(shape).astype("f4")
            lu, piv = paddle.linalg.lu(paddle.to_tensor(a))
            P, L, U = paddle.linalg.lu_unpack(lu, piv)
            assert P.shape == [shape[0], shape[0]]
            np.testing.assert_allclose(
                P.numpy() @ L.numpy() @ U.numpy(), a, atol=1e-5)

    def test_fill_diagonal_nd_contract(self):
        x3 = paddle.zeros([3, 3, 3])
        out = paddle.tensor.manipulation.fill_diagonal(x3, 1.0)
        assert out.numpy()[1, 1, 1] == 1.0
        with pytest.raises(ValueError):
            paddle.tensor.manipulation.fill_diagonal(x3, 1.0, offset=1)
        with pytest.raises(ValueError):
            paddle.tensor.manipulation.fill_diagonal(
                paddle.zeros([4, 3, 3]), 1.0)

    def test_max_pool1d_mask_channel_last_rejected(self):
        x = paddle.to_tensor(np.zeros((1, 8, 2), "f4"))
        with pytest.raises(ValueError):
            F.max_pool1d(x, 2, 2, return_mask=True, data_format="NLC")

    def test_hsigmoid_table_cached(self):
        from paddle_tpu.nn.functional.loss import _simple_code_tables

        t1 = _simple_code_tables(64)
        t2 = _simple_code_tables(64)
        assert t1[0] is t2[0]  # same cached object, no per-call rebuild
