"""Automatic sharding planner (paddle_tpu/autoshard — ISSUE 10).

Tier-1 coverage:
- candidate enumeration + the HLO collective parser/axis classifier
  (pure units)
- GSPMD-style spec derivation (Megatron conjugate pairing from seed
  rules — zero hand-written PartitionSpecs)
- planner determinism: same inputs → byte-identical ``shard_plan.json``
- HBM-infeasible candidates rejected (no plan, exit-code-3 path)
- per-axis ``collective/bytes/<axis>`` monitor counters
- ``fit(shard_plan=)`` + ``apply_plan`` placement
- the ``tools/shard_plan.py plan --smoke`` CLI pipeline proof with the
  exec-cache-warm zero-fresh-compiles acceptance check

Slow tier: the 2-process launcher proof — plan at dp2×mp1, launch,
kill, REPLAN at dp1×mp2, resume through reshard-on-load, losses on the
same curve (extends the elastic_reshard_script fixture lineage).
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import autoshard
from paddle_tpu.autoshard import hlo_costs

_ROOT = str(Path(__file__).parent.parent)


# -- candidates (pure) -------------------------------------------------------

class TestCandidates:
    def test_enumeration_default_meshes(self):
        cands = autoshard.enumerate_candidates(8, None, "8")
        labels = [autoshard.candidate_label(c) for c in cands]
        assert labels == ["dp8·mp1 b8", "dp4·mp2 b8", "dp2·mp4 b8",
                          "dp1·mp8 b8"]

    def test_enumeration_cross_product_order_is_deterministic(self):
        cands = autoshard.enumerate_candidates(4, "dp4,dp2xmp2", "4,8")
        assert [(c["dp"], c["mp"], c["batch"]) for c in cands] == [
            (4, 1, 4), (4, 1, 8), (2, 2, 4), (2, 2, 8)]

    def test_bad_factorization_refused(self):
        with pytest.raises(ValueError, match="factorize"):
            autoshard.enumerate_candidates(16, "dp4xmp2", "8")

    def test_bad_token_refused(self):
        with pytest.raises(ValueError, match="bad mesh token"):
            autoshard.parse_mesh("pp2")

    def test_axis_order_copies_agree(self):
        # three deliberate literals (env.py is jax-heavy, hlo_costs and
        # monitor must stay import-light) — pinned here so a renamed or
        # added mesh axis cannot silently desynchronize the HLO
        # classifier or the per-axis counter labels
        from paddle_tpu import monitor
        from paddle_tpu.distributed import env as env_mod

        assert hlo_costs.AXIS_ORDER == env_mod.AXIS_ORDER
        assert monitor._COLL_AXIS_ORDER == env_mod.AXIS_ORDER


# -- HLO collective parsing (pure) -------------------------------------------

_HLO_EXPLICIT = """
  %all-reduce = f32[64,64]{1,0} all-reduce(f32[64,64]{1,0} %x), channel_id=1, replica_groups={{0,2,4,6},{1,3,5,7}}, use_global_device_ids=true, to_apply=%add
  %all-gather = f32[8,64]{1,0} all-gather(f32[2,64]{1,0} %y), channel_id=2, replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}
"""

_HLO_IOTA = """
  %all-reduce.1 = f32[16]{0} all-reduce(f32[16]{0} %z), channel_id=3, replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add
"""


class TestHloCosts:
    # mesh dp4×mp2 (AXIS_ORDER dp,pp,sharding,sep,mp): id = dp*2 + mp
    DEG = {"dp": 4, "pp": 1, "sharding": 1, "sep": 1, "mp": 2}

    def test_explicit_groups_classified_per_axis(self):
        colls = hlo_costs.parse_collectives(_HLO_EXPLICIT, self.DEG)
        assert [c["op"] for c in colls] == ["all-reduce", "all-gather"]
        # {0,2,4,6}: mp fixed, dp varies; {0,1}: dp fixed, mp varies
        assert colls[0]["axis"] == "dp"
        assert colls[1]["axis"] == "mp"

    def test_wire_factors(self):
        colls = hlo_costs.parse_collectives(_HLO_EXPLICIT, self.DEG)
        ar, ag = colls
        assert ar["payload_bytes"] == 64 * 64 * 4
        assert ar["wire_bytes"] == int(ar["payload_bytes"] * 2 * 3 / 4)
        assert ag["payload_bytes"] == 8 * 64 * 4
        assert ag["wire_bytes"] == int(ag["payload_bytes"] * 1 / 2)

    def test_iota_replica_groups_full_world(self):
        deg = {"dp": 8, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        colls = hlo_costs.parse_collectives(_HLO_IOTA, deg)
        assert len(colls) == 1
        assert colls[0]["axis"] == "dp"
        assert colls[0]["group_size"] == 8

    def test_fused_axes_label(self):
        # one group spanning the whole dp4×mp2 world
        hlo = ("  %all-reduce = f32[4]{0} all-reduce(f32[4]{0} %a), "
               "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add")
        colls = hlo_costs.parse_collectives(hlo, self.DEG)
        assert colls[0]["axis"] == "dp+mp"

    def test_aggregation_shape(self):
        agg = hlo_costs.collective_bytes_by_axis(_HLO_EXPLICIT, self.DEG)
        assert set(agg["per_axis_wire_bytes"]) == {"dp", "mp"}
        assert agg["total_wire_bytes"] == sum(
            agg["per_axis_wire_bytes"].values())
        assert agg["ops"] == {"all-gather": 1, "all-reduce": 1}

    def test_done_lines_not_double_counted(self):
        hlo = ("  %ar = f32[4]{0} all-reduce-start(f32[4]{0} %a), "
               "replica_groups={{0,1}}, to_apply=%add\n"
               "  %d = f32[4]{0} all-reduce-done(f32[4]{0} %ar)")
        deg = {"dp": 2, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        assert len(hlo_costs.parse_collectives(hlo, deg)) == 1

    def test_reduce_scatter_bills_pre_scatter_input(self):
        # the HLO result is the already-scattered shard — wire cost must
        # be (n-1)/n of the INPUT (= result × group size)
        deg = {"dp": 8, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        hlo = ("  %rs = f32[64]{0} reduce-scatter(f32[512]{0} %x), "
               "replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, "
               "to_apply=%add")
        (c,) = hlo_costs.parse_collectives(hlo, deg)
        assert c["payload_bytes"] == 512 * 4
        assert c["wire_bytes"] == int(512 * 4 * 7 / 8)

    def test_async_start_tuple_counts_results_only(self):
        # TPU HLO: async start ops are (operands, results) tuples — the
        # operand alias must not double the payload (never visible on
        # CPU, whose collectives are sync)
        deg = {"dp": 2, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        hlo = ("  %ar = (f32[16]{0}, f32[16]{0}) all-reduce-start("
               "f32[16]{0} %a), replica_groups={{0,1}}, to_apply=%add")
        (c,) = hlo_costs.parse_collectives(hlo, deg)
        assert c["payload_bytes"] == 16 * 4
        hlo_ag = ("  %ag = (f32[8]{0}, f32[16]{0}) all-gather-start("
                  "f32[8]{0} %a), replica_groups={{0,1}}, dimensions={0}")
        (g,) = hlo_costs.parse_collectives(hlo_ag, deg)
        assert g["payload_bytes"] == 16 * 4  # the gathered RESULT


# -- spec derivation (pure) --------------------------------------------------

class TestDeriveSpecs:
    def test_megatron_conjugate_pairing(self):
        import paddle_tpu.nn as nn

        model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                              nn.Linear(16, 1))
        specs = autoshard.derive_param_specs(model, mp_degree=2)
        by_suffix = {k.split(".", 1)[0] + "." + k.rsplit(".", 1)[1]: v
                     for k, v in specs.items()}
        # column-parallel first linear, row-parallel conjugate — exactly
        # the hand placement elastic_reshard_script used to write
        assert by_suffix["0.weight"] == [None, "mp"]
        assert by_suffix["0.bias"] == ["mp"]
        assert by_suffix["2.weight"] == ["mp", None]
        assert by_suffix["2.bias"] == [None]

    def test_non_divisible_dims_stay_replicated(self):
        import paddle_tpu.nn as nn

        model = nn.Sequential(nn.Linear(8, 7))  # 7 % 2 != 0
        specs = autoshard.derive_param_specs(model, mp_degree=2)
        assert all(set(v) <= {None} for v in specs.values())

    def test_embedding_shards_vocab(self):
        import paddle_tpu.nn as nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.embed_tokens = nn.Embedding(32, 8)

            def forward(self, x):
                return self.embed_tokens(x)

        specs = autoshard.derive_param_specs(M(), mp_degree=2)
        (name, spec), = specs.items()
        assert "embed" in name and spec == ["mp", None]


# -- plan schema (pure) ------------------------------------------------------

class TestPlanSchema:
    def _plan(self):
        return autoshard.ShardPlan(
            mesh={"dp": 2, "mp": 1}, batch=16,
            param_specs={"0.weight": [None, "mp"]},
            rows=[{"label": "dp2·mp1 b16", "dp": 2, "mp": 1, "batch": 16,
                   "fits": True}],
            winner="dp2·mp1 b16", seeds={"mfu": 0.4},
            provenance={"devices": 2})

    def test_round_trip_and_digest_stability(self, tmp_path):
        p = self._plan()
        path = p.save(str(tmp_path / "plan.json"))
        q = autoshard.load_plan(path)
        assert q.dumps() == p.dumps()
        assert q.digest() == p.digest()
        assert q.summary() == {"dp": 2, "mp": 1, "batch": 16,
                               "devices": 2, "digest": p.digest()}

    def test_version_skew_refused(self, tmp_path):
        d = self._plan().to_dict()
        d["plan_version"] = 999
        path = tmp_path / "bad.json"
        path.write_text(json.dumps(d))
        with pytest.raises(ValueError, match="version"):
            autoshard.load_plan(str(path))


# -- the planner on the virtual mesh -----------------------------------------

_TINY = dict(vocab=128, hidden=32, intermediate=0, layers=1, heads=2,
             seq=16)


class TestPlanner:
    @pytest.fixture(scope="class", autouse=True)
    def _exec_cache(self, tmp_path_factory):
        """Arm the exec cache for this class: the determinism and
        infeasibility tests re-lower the same candidates, and the warm
        path (mem tier + meta sidecar) is exactly what a repeat sweep
        pays in production — zero fresh XLA compiles."""
        from paddle_tpu.jit import exec_cache

        exec_cache.enable(str(tmp_path_factory.mktemp("autoshard_cache")))
        yield
        exec_cache.disable()
        exec_cache.clear()

    @pytest.fixture(scope="class")
    def sweep(self):
        spec = autoshard.ProbeSpec(**_TINY)
        plan, rows = autoshard.make_plan(
            8, 16.0, spec=spec, configs="dp8,dp2xmp4", batches="8")
        return plan, rows

    def test_winner_fits_and_rows_scored(self, sweep):
        plan, rows = sweep
        assert plan is not None
        assert all(r.get("fits") for r in rows if "error" not in r)
        winner_row = next(r for r in plan.rows
                          if r["label"] == plan.winner)
        assert winner_row["fits"]
        assert winner_row["est_step_ms"] > 0
        assert plan.devices == 8

    def test_rows_carry_per_axis_comms(self, sweep):
        _plan, rows = sweep
        hybrid = next(r for r in rows if r["mp"] == 4)
        per_axis = hybrid["collectives"]["per_axis_wire_bytes"]
        assert per_axis.get("mp", 0) > 0  # Megatron f/g traffic exists

    def test_determinism_byte_identical(self, sweep):
        plan, _rows = sweep
        spec = autoshard.ProbeSpec(**_TINY)
        plan2, _ = autoshard.make_plan(
            8, 16.0, spec=spec, configs="dp8,dp2xmp4", batches="8")
        assert plan2.dumps() == plan.dumps()

    def test_hbm_infeasible_rejected(self):
        spec = autoshard.ProbeSpec(**_TINY)
        plan, rows = autoshard.make_plan(
            8, 1e-9, spec=spec, configs="dp8", batches="8")
        assert plan is None
        assert rows and not any(r.get("fits") for r in rows)

    def test_param_specs_recorded_from_probe(self, sweep):
        plan, _rows = sweep
        assert plan.param_specs  # the probe model's propagated specs
        assert any("mp" in str(v) for v in plan.param_specs.values())


# -- per-axis collective counters --------------------------------------------

class TestPerAxisCollectiveBytes:
    def test_eager_collective_attributes_axis(self):
        from paddle_tpu import monitor
        from paddle_tpu import distributed as dist
        from paddle_tpu.distributed import env as env_mod

        env_mod.init_mesh(dp=4, mp=2)
        monitor.enable()
        try:
            monitor.reset()
            t = pt.to_tensor(np.ones((8, 8), np.float32))
            dist.all_reduce(t, group="mp")
            snap = monitor.snapshot()["counters"]
            assert snap.get("collective/bytes/mp") == 8 * 8 * 4
            assert snap.get("collective/bytes") == 8 * 8 * 4
            dist.all_reduce(t, group="dp")
            snap = monitor.snapshot()["counters"]
            assert snap.get("collective/bytes/dp") == 8 * 8 * 4
        finally:
            monitor.disable()
            monitor.reset()
            env_mod.reset_env()

    def test_zero_overhead_off(self):
        # the audit in test_memory_numerics covers import-time inertness;
        # here: with the monitor off, no per-axis counter appears
        from paddle_tpu import monitor
        from paddle_tpu import distributed as dist
        from paddle_tpu.distributed import env as env_mod

        env_mod.init_mesh(dp=4, mp=2)
        try:
            monitor.reset()
            t = pt.to_tensor(np.ones((4, 4), np.float32))
            dist.all_reduce(t, group="mp")
            snap = monitor.snapshot()["counters"]
            assert not any(k.startswith("collective/bytes/")
                           for k in snap)
        finally:
            monitor.reset()
            env_mod.reset_env()


# -- apply_plan + fit(shard_plan=) -------------------------------------------

class TestApplyPlan:
    def _plan(self, dp, mp, batch=16):
        return autoshard.ShardPlan(mesh={"dp": dp, "mp": mp}, batch=batch,
                                   param_specs={})

    def test_apply_places_params_by_derived_specs(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.distributed.shard import get_sharding

        try:
            model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                  nn.Linear(16, 1))
            env = autoshard.apply_plan(self._plan(2, 2), model)
            assert env.degree("dp") == 2 and env.degree("mp") == 2
            w0 = get_sharding(model[0].weight)
            w2 = get_sharding(model[2].weight)
            assert tuple(w0) == (None, "mp")
            assert tuple(w2) == ("mp",)  # trailing None trimmed
        finally:
            env_mod.reset_env()

    def test_shard_batch_scalar_replicates(self):
        from paddle_tpu.distributed import env as env_mod

        try:
            autoshard.apply_plan(self._plan(4, 2))
            t = autoshard.shard_batch(pt.to_tensor(3.0))  # 0-d: no
            assert float(t.numpy()) == 3.0                # batch dim
            b = autoshard.shard_batch(pt.to_tensor(
                np.ones((8, 2), np.float32)))
            assert "dp" in str(b._data.sharding)
        finally:
            env_mod.reset_env()

    def test_fit_shard_plan_trains_sharded(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.distributed.shard import get_sharding
        from paddle_tpu.hapi import Model

        try:
            plan_path = self._plan(2, 2, batch=8).save(
                str(tmp_path / "plan.json"))
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 4))
            m = Model(net)
            m.prepare(pt.optimizer.AdamW(
                learning_rate=1e-3, parameters=net.parameters()),
                pt.nn.CrossEntropyLoss())
            xs = np.random.randn(16, 8).astype("float32")
            ys = np.random.randint(0, 4, (16, 1))
            ds = [(xs[i], ys[i]) for i in range(16)]
            m.fit(ds, batch_size=8, epochs=1, verbose=0, log_freq=1,
                  shard_plan=plan_path)
            assert tuple(get_sharding(net[0].weight)) == (None, "mp")
            assert env_mod.get_env().degree("mp") == 2
            # data parallelism must be IN the compiled step: fit shards
            # batches over dp, so the grad sync appears as dp traffic
            # (the regression: replicated batches compile dp out)
            entry = next(iter(m._train_step._cache.values()))
            comms = hlo_costs.collective_bytes_by_axis(
                entry.compiled.as_text(),
                {"dp": 2, "pp": 1, "sharding": 1, "sep": 1, "mp": 2})
            assert any("dp" in ax.split("+")
                       for ax in comms["per_axis_wire_bytes"]), comms
        finally:
            env_mod.reset_env()


# -- CLI: the tier-1 pipeline proof ------------------------------------------

def _run_plan_cli(out, cache, extra=()):
    env = dict(os.environ)
    env["PT_EXEC_CACHE"] = str(cache)
    return subprocess.run(
        [sys.executable, "tools/shard_plan.py", "plan", "--smoke",
         "--out", str(out), *extra],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=900)


def test_cli_smoke_deterministic_and_exec_cache_warm(tmp_path):
    """Acceptance: `shard_plan.py plan` emits a deterministic plan whose
    winner fits, and a second invocation with PT_EXEC_CACHE set reports
    ZERO fresh XLA compiles."""
    cache = tmp_path / "cache"
    cold = _run_plan_cli(tmp_path / "p1.json", cache)
    assert cold.returncode == 0, cold.stderr[-2000:]
    assert "FITS" in cold.stdout and "winner:" in cold.stdout
    warm = _run_plan_cli(tmp_path / "p2.json", cache)
    assert warm.returncode == 0, warm.stderr[-2000:]
    line = json.loads([ln for ln in warm.stdout.splitlines()
                       if ln.startswith("{")][-1])
    assert line["shard_plan"]["fresh_compiles"] == 0, line
    assert (tmp_path / "p1.json").read_bytes() == \
        (tmp_path / "p2.json").read_bytes()
    plan = autoshard.load_plan(str(tmp_path / "p1.json"))
    winner_row = next(r for r in plan.rows if r["label"] == plan.winner)
    assert winner_row["fits"]


# -- the launcher proof (slow tier) ------------------------------------------

@pytest.mark.slow
def test_plan_launch_kill_replan_resume(tmp_path):
    """ISSUE 10 acceptance: plan at dp2×mp1 on the virtual mesh, launch
    through the launcher, kill mid-run, REPLAN at dp1×mp2, resume the
    checkpoint through reshard-on-load — losses on the same curve, with
    no hand-written PartitionSpecs anywhere in the test path."""
    script = str(Path(__file__).parent / "autoshard_launch_script.py")

    def make_plan_file(configs, path):
        proc = subprocess.run(
            [sys.executable, "tools/shard_plan.py", "plan",
             "--devices", "2", "--configs", configs, "--out", str(path),
             "--hidden", "32", "--layers", "1", "--heads", "2",
             "--seq", "16", "--vocab", "64", "--batches", "8"],
            cwd=_ROOT, capture_output=True, text=True, timeout=900)
        assert proc.returncode == 0, proc.stderr[-2000:]
        return str(path)

    plan_a = make_plan_file("dp2xmp1", tmp_path / "plan_a.json")
    plan_b = make_plan_file("dp1xmp2", tmp_path / "plan_b.json")
    assert autoshard.load_plan(plan_a).mesh == {"dp": 2, "mp": 1}
    assert autoshard.load_plan(plan_b).mesh == {"dp": 1, "mp": 2}

    def launch(workdir, plan, crash_at, resume=False):
        env = dict(os.environ)
        env["AUTOSHARD_CRASH_AT"] = str(crash_at)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("PADDLE_RESTART_COUNT", None)
        if resume:
            env["PT_SHARD_RESUME"] = str(workdir / "ckpt")
        else:
            env.pop("PT_SHARD_RESUME", None)
        return subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--max_restart", "0", "--shard_plan", plan,
             "--log_dir", str(workdir / "log"), script, str(workdir)],
            cwd=_ROOT, env=env, capture_output=True, text=True,
            timeout=300)

    def losses_of(workdir):
        out = {}
        for f in sorted(workdir.glob("losses_r*.json")):
            data = json.loads(f.read_text())
            for i, l in enumerate(data["losses"]):
                out[data["start"] + i] = l
        return out

    # clean single-plan run: the reference curve
    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    proc = launch(clean_dir, plan_a, crash_at=-1)
    assert proc.returncode == 0, proc.stderr[-2000:] + "".join(
        p.read_text()[-2000:] for p in (clean_dir / "log").glob("workerlog.*"))
    clean = losses_of(clean_dir)

    # crash run: life 0 under plan A dies at step 3 (launcher + worker =
    # the 2-process proof)...
    crash_dir = tmp_path / "crash"
    crash_dir.mkdir()
    proc = launch(crash_dir, plan_a, crash_at=3)
    assert proc.returncode == 17, proc.stderr[-2000:]
    # ...then the REPLANNED topology resumes the same checkpoints
    proc = launch(crash_dir, plan_b, crash_at=-1, resume=True)
    assert proc.returncode == 0, proc.stderr[-2000:] + "".join(
        p.read_text()[-2000:] for p in (crash_dir / "log").glob("workerlog.*"))
    crashed = losses_of(crash_dir)

    assert sorted(clean) == sorted(crashed) == list(range(6))
    r1 = json.loads((crash_dir / "losses_r1.json").read_text())
    assert r1["start"] == 3              # resumed, not restarted
    assert r1["mesh"] == {"dp": 1, "mp": 2}  # ...under the replanned mesh
    for step in range(6):
        # same curve, not bit-identical: the mesh change legitimately
        # reorders reductions
        assert abs(clean[step] - crashed[step]) <= 1e-4 * max(
            1.0, abs(clean[step])), (step, clean[step], crashed[step])
