"""Automatic sharding planner (paddle_tpu/autoshard — ISSUE 10 + the
pp axis of ISSUE 15).

Tier-1 coverage:
- candidate enumeration (incl. the dp×mp×pp sweep, the stage-depth pp
  cap, and the planned microbatch count) + the HLO collective
  parser/axis classifier (pure units)
- GSPMD-style spec derivation (Megatron conjugate pairing from seed
  rules — zero hand-written PartitionSpecs)
- planner determinism: same inputs → byte-identical ``shard_plan.json``
  (pp rows included)
- HBM-infeasible candidates rejected (no plan, exit-code-3 path)
- per-axis ``collective/bytes/<axis>`` monitor counters
- ``fit(shard_plan=)`` + ``apply_plan`` placement; a planned pp2 fit
  training on the pp=1 loss curve (the 1F1B-in-XLA correctness proof)
- stage-move reshard: a pp1 checkpoint resumed at pp2 (and back) stays
  on the same loss curve — canonical per-block checkpoint keys
- the ``tools/shard_plan.py plan --smoke`` CLI pipeline proof with a
  pp>1 candidate and the exec-cache-warm zero-fresh-compiles check

Slow tier: the 2-process launcher proofs — plan/launch/kill/replan/
resume across a dp→mp reshard AND across a pipelined dp2×pp2 →
dp1×mp2×pp2 stage-boundary move, losses on the same curve.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import autoshard
from paddle_tpu.autoshard import hlo_costs

_ROOT = str(Path(__file__).parent.parent)


# -- candidates (pure) -------------------------------------------------------

class TestCandidates:
    def test_enumeration_default_meshes(self):
        cands = autoshard.enumerate_candidates(8, None, "8")
        labels = [autoshard.candidate_label(c) for c in cands]
        assert labels == ["dp8·mp1 b8", "dp4·mp2 b8", "dp2·mp4 b8",
                          "dp1·mp8 b8"]

    def test_enumeration_cross_product_order_is_deterministic(self):
        cands = autoshard.enumerate_candidates(4, "dp4,dp2xmp2", "4,8")
        assert [(c["dp"], c["mp"], c["batch"]) for c in cands] == [
            (4, 1, 4), (4, 1, 8), (2, 2, 4), (2, 2, 8)]

    def test_bad_factorization_refused(self):
        with pytest.raises(ValueError, match="factorize"):
            autoshard.enumerate_candidates(16, "dp4xmp2", "8")

    def test_bad_token_refused(self):
        with pytest.raises(ValueError, match="bad mesh token"):
            autoshard.parse_mesh("xx2")

    def test_pp_tokens_parse(self):
        assert autoshard.parse_mesh("dp2xmp2xpp2") == {
            "dp": 2, "mp": 2, "pp": 2}
        assert autoshard.parse_mesh("dp4xpp2")["pp"] == 2

    def test_pp_enumeration_caps_at_stage_depth(self):
        # pp=1 rows first in the historical order, then the pipelines;
        # pp=4 absent: the 2-layer probe cannot stage over 4
        cands = autoshard.enumerate_candidates(8, None, "8", pp_max=8,
                                               stage_depth=2)
        labels = [autoshard.candidate_label(c) for c in cands]
        assert labels[:4] == ["dp8·mp1 b8", "dp4·mp2 b8", "dp2·mp4 b8",
                              "dp1·mp8 b8"]
        assert "dp4·mp1·pp2 b8" in labels
        assert not any("pp4" in l for l in labels)

    def test_pp_defaults_off_without_cap(self):
        # callers that predate the pp axis (pp_max default 1) see the
        # historical dp×mp space unchanged
        cands = autoshard.enumerate_candidates(8, None, "8")
        assert all(c["pp"] == 1 for c in cands)

    def test_plan_microbatches_deterministic_rules(self):
        # pp=1 pipelines nothing; pp>1 takes the largest batch divisor
        # ≤ 2·pp whose microbatch still dp-shards
        assert autoshard.plan_microbatches(1, 64) == 1
        assert autoshard.plan_microbatches(2, 8, dp=4) == 2
        assert autoshard.plan_microbatches(2, 8, dp=2) == 4
        assert autoshard.plan_microbatches(2, 16, dp=2) == 4
        assert autoshard.plan_microbatches(4, 64, dp=1) == 8

    def test_axis_order_copies_agree(self):
        # three deliberate literals (env.py is jax-heavy, hlo_costs and
        # monitor must stay import-light) — pinned here so a renamed or
        # added mesh axis cannot silently desynchronize the HLO
        # classifier or the per-axis counter labels
        from paddle_tpu import monitor
        from paddle_tpu.distributed import env as env_mod

        assert hlo_costs.AXIS_ORDER == env_mod.AXIS_ORDER
        assert monitor._COLL_AXIS_ORDER == env_mod.AXIS_ORDER


# -- HLO collective parsing (pure) -------------------------------------------

_HLO_EXPLICIT = """
  %all-reduce = f32[64,64]{1,0} all-reduce(f32[64,64]{1,0} %x), channel_id=1, replica_groups={{0,2,4,6},{1,3,5,7}}, use_global_device_ids=true, to_apply=%add
  %all-gather = f32[8,64]{1,0} all-gather(f32[2,64]{1,0} %y), channel_id=2, replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}
"""

_HLO_IOTA = """
  %all-reduce.1 = f32[16]{0} all-reduce(f32[16]{0} %z), channel_id=3, replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add
"""


class TestHloCosts:
    # mesh dp4×mp2 (AXIS_ORDER dp,pp,sharding,sep,mp): id = dp*2 + mp
    DEG = {"dp": 4, "pp": 1, "sharding": 1, "sep": 1, "mp": 2}

    def test_explicit_groups_classified_per_axis(self):
        colls = hlo_costs.parse_collectives(_HLO_EXPLICIT, self.DEG)
        assert [c["op"] for c in colls] == ["all-reduce", "all-gather"]
        # {0,2,4,6}: mp fixed, dp varies; {0,1}: dp fixed, mp varies
        assert colls[0]["axis"] == "dp"
        assert colls[1]["axis"] == "mp"

    def test_wire_factors(self):
        colls = hlo_costs.parse_collectives(_HLO_EXPLICIT, self.DEG)
        ar, ag = colls
        assert ar["payload_bytes"] == 64 * 64 * 4
        assert ar["wire_bytes"] == int(ar["payload_bytes"] * 2 * 3 / 4)
        assert ag["payload_bytes"] == 8 * 64 * 4
        assert ag["wire_bytes"] == int(ag["payload_bytes"] * 1 / 2)

    def test_iota_replica_groups_full_world(self):
        deg = {"dp": 8, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        colls = hlo_costs.parse_collectives(_HLO_IOTA, deg)
        assert len(colls) == 1
        assert colls[0]["axis"] == "dp"
        assert colls[0]["group_size"] == 8

    def test_fused_axes_label(self):
        # one group spanning the whole dp4×mp2 world
        hlo = ("  %all-reduce = f32[4]{0} all-reduce(f32[4]{0} %a), "
               "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add")
        colls = hlo_costs.parse_collectives(hlo, self.DEG)
        assert colls[0]["axis"] == "dp+mp"

    def test_aggregation_shape(self):
        agg = hlo_costs.collective_bytes_by_axis(_HLO_EXPLICIT, self.DEG)
        assert set(agg["per_axis_wire_bytes"]) == {"dp", "mp"}
        assert agg["total_wire_bytes"] == sum(
            agg["per_axis_wire_bytes"].values())
        assert agg["ops"] == {"all-gather": 1, "all-reduce": 1}

    def test_done_lines_not_double_counted(self):
        hlo = ("  %ar = f32[4]{0} all-reduce-start(f32[4]{0} %a), "
               "replica_groups={{0,1}}, to_apply=%add\n"
               "  %d = f32[4]{0} all-reduce-done(f32[4]{0} %ar)")
        deg = {"dp": 2, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        assert len(hlo_costs.parse_collectives(hlo, deg)) == 1

    def test_reduce_scatter_bills_pre_scatter_input(self):
        # the HLO result is the already-scattered shard — wire cost must
        # be (n-1)/n of the INPUT (= result × group size)
        deg = {"dp": 8, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        hlo = ("  %rs = f32[64]{0} reduce-scatter(f32[512]{0} %x), "
               "replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, "
               "to_apply=%add")
        (c,) = hlo_costs.parse_collectives(hlo, deg)
        assert c["payload_bytes"] == 512 * 4
        assert c["wire_bytes"] == int(512 * 4 * 7 / 8)

    def test_permute_pairs_classified_per_pair(self):
        # dp2×pp2×mp2 (AXIS_ORDER dp,pp,sharding,sep,mp): pp stride 2.
        # The roll of a pp-sharded stage state permutes (0↔2),(1↔3),...
        # — each {src,tgt} pair is its own hop, so the op classifies as
        # "pp", not smeared over the union of every pair's axes
        deg = {"dp": 2, "pp": 2, "sharding": 1, "sep": 1, "mp": 2}
        hlo = ("  %cp = f32[2,16]{1,0} collective-permute(f32[2,16]{1,0} "
               "%x), channel_id=5, source_target_pairs="
               "{{0,2},{2,0},{1,3},{3,1},{4,6},{6,4},{5,7},{7,5}}")
        (c,) = hlo_costs.parse_collectives(hlo, deg)
        assert c["op"] == "collective-permute"
        assert c["axis"] == "pp"
        assert c["wire_bytes"] == 2 * 16 * 4  # permute moves the payload

    def test_permute_self_pairs_ignored(self):
        deg = {"dp": 2, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        hlo = ("  %cp = f32[4]{0} collective-permute(f32[4]{0} %x), "
               "source_target_pairs={{0,0},{1,1}}")
        assert hlo_costs.parse_collectives(hlo, deg) == []

    def test_async_start_tuple_counts_results_only(self):
        # TPU HLO: async start ops are (operands, results) tuples — the
        # operand alias must not double the payload (never visible on
        # CPU, whose collectives are sync)
        deg = {"dp": 2, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
        hlo = ("  %ar = (f32[16]{0}, f32[16]{0}) all-reduce-start("
               "f32[16]{0} %a), replica_groups={{0,1}}, to_apply=%add")
        (c,) = hlo_costs.parse_collectives(hlo, deg)
        assert c["payload_bytes"] == 16 * 4
        hlo_ag = ("  %ag = (f32[8]{0}, f32[16]{0}) all-gather-start("
                  "f32[8]{0} %a), replica_groups={{0,1}}, dimensions={0}")
        (g,) = hlo_costs.parse_collectives(hlo_ag, deg)
        assert g["payload_bytes"] == 16 * 4  # the gathered RESULT


# -- spec derivation (pure) --------------------------------------------------

class TestDeriveSpecs:
    def test_megatron_conjugate_pairing(self):
        import paddle_tpu.nn as nn

        model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                              nn.Linear(16, 1))
        specs = autoshard.derive_param_specs(model, mp_degree=2)
        by_suffix = {k.split(".", 1)[0] + "." + k.rsplit(".", 1)[1]: v
                     for k, v in specs.items()}
        # column-parallel first linear, row-parallel conjugate — exactly
        # the hand placement elastic_reshard_script used to write
        assert by_suffix["0.weight"] == [None, "mp"]
        assert by_suffix["0.bias"] == ["mp"]
        assert by_suffix["2.weight"] == ["mp", None]
        assert by_suffix["2.bias"] == [None]

    def test_non_divisible_dims_stay_replicated(self):
        import paddle_tpu.nn as nn

        model = nn.Sequential(nn.Linear(8, 7))  # 7 % 2 != 0
        specs = autoshard.derive_param_specs(model, mp_degree=2)
        assert all(set(v) <= {None} for v in specs.values())

    def test_embedding_shards_vocab(self):
        import paddle_tpu.nn as nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.embed_tokens = nn.Embedding(32, 8)

            def forward(self, x):
                return self.embed_tokens(x)

        specs = autoshard.derive_param_specs(M(), mp_degree=2)
        (name, spec), = specs.items()
        assert "embed" in name and spec == ["mp", None]


# -- plan schema (pure) ------------------------------------------------------

class TestPlanSchema:
    def _plan(self):
        return autoshard.ShardPlan(
            mesh={"dp": 2, "mp": 1}, batch=16,
            param_specs={"0.weight": [None, "mp"]},
            rows=[{"label": "dp2·mp1 b16", "dp": 2, "mp": 1, "batch": 16,
                   "fits": True}],
            winner="dp2·mp1 b16", seeds={"mfu": 0.4},
            provenance={"devices": 2})

    def test_round_trip_and_digest_stability(self, tmp_path):
        p = self._plan()
        path = p.save(str(tmp_path / "plan.json"))
        q = autoshard.load_plan(path)
        assert q.dumps() == p.dumps()
        assert q.digest() == p.digest()
        assert q.summary() == {"dp": 2, "mp": 1, "pp": 1, "batch": 16,
                               "devices": 2, "digest": p.digest()}

    def test_pp_fields_round_trip(self, tmp_path):
        p = autoshard.ShardPlan(
            mesh={"dp": 2, "mp": 1, "pp": 2}, batch=8, param_specs={},
            n_micro=4, stage_assignment=[0, 0, 1, 1])
        q = autoshard.load_plan(p.save(str(tmp_path / "pp.json")))
        assert q.mesh == {"dp": 2, "mp": 1, "pp": 2}
        assert q.devices == 4
        assert q.n_micro == 4
        assert q.stage_assignment == [0, 0, 1, 1]

    def test_pre_pp_plan_files_still_load(self, tmp_path):
        # a plan written before the pp axis existed (no pp/n_micro/
        # stage_assignment keys) loads with pipeline defaults
        d = self._plan().to_dict()
        d["mesh"] = {"dp": 2, "mp": 1}
        for k in ("n_micro", "stage_assignment"):
            d.pop(k, None)
        path = tmp_path / "old.json"
        path.write_text(json.dumps(d))
        q = autoshard.load_plan(str(path))
        assert q.mesh["pp"] == 1 and q.n_micro == 1
        assert q.stage_assignment is None

    def test_version_skew_refused(self, tmp_path):
        d = self._plan().to_dict()
        d["plan_version"] = 999
        path = tmp_path / "bad.json"
        path.write_text(json.dumps(d))
        with pytest.raises(ValueError, match="version"):
            autoshard.load_plan(str(path))


# -- the planner on the virtual mesh -----------------------------------------

_TINY = dict(vocab=128, hidden=32, intermediate=0, layers=1, heads=2,
             seq=16)


class TestPlanner:
    @pytest.fixture(scope="class", autouse=True)
    def _exec_cache(self, tmp_path_factory):
        """Arm the exec cache for this class: the determinism and
        infeasibility tests re-lower the same candidates, and the warm
        path (mem tier + meta sidecar) is exactly what a repeat sweep
        pays in production — zero fresh XLA compiles."""
        from paddle_tpu.jit import exec_cache

        exec_cache.enable(str(tmp_path_factory.mktemp("autoshard_cache")))
        yield
        exec_cache.disable()
        exec_cache.clear()

    @pytest.fixture(scope="class")
    def sweep(self):
        spec = autoshard.ProbeSpec(**_TINY)
        plan, rows = autoshard.make_plan(
            8, 16.0, spec=spec, configs="dp8,dp2xmp4", batches="8")
        return plan, rows

    def test_winner_fits_and_rows_scored(self, sweep):
        plan, rows = sweep
        assert plan is not None
        assert all(r.get("fits") for r in rows if "error" not in r)
        winner_row = next(r for r in plan.rows
                          if r["label"] == plan.winner)
        assert winner_row["fits"]
        assert winner_row["est_step_ms"] > 0
        assert plan.devices == 8

    def test_rows_carry_per_axis_comms(self, sweep):
        _plan, rows = sweep
        hybrid = next(r for r in rows if r["mp"] == 4)
        per_axis = hybrid["collectives"]["per_axis_wire_bytes"]
        assert per_axis.get("mp", 0) > 0  # Megatron f/g traffic exists

    def test_determinism_byte_identical(self, sweep):
        plan, _rows = sweep
        spec = autoshard.ProbeSpec(**_TINY)
        plan2, _ = autoshard.make_plan(
            8, 16.0, spec=spec, configs="dp8,dp2xmp4", batches="8")
        assert plan2.dumps() == plan.dumps()

    def test_hbm_infeasible_rejected(self):
        spec = autoshard.ProbeSpec(**_TINY)
        plan, rows = autoshard.make_plan(
            8, 1e-9, spec=spec, configs="dp8", batches="8")
        assert plan is None
        assert rows and not any(r.get("fits") for r in rows)

    def test_param_specs_recorded_from_probe(self, sweep):
        plan, _rows = sweep
        assert plan.param_specs  # the probe model's propagated specs
        assert any("mp" in str(v) for v in plan.param_specs.values())


# -- cost-model fallback terms (pure) ----------------------------------------

class TestCostFallbackTerms:
    """The analytical comms fallback (no parsed HLO account) must carry
    the pipeline bubble/handoff and the MoE all-to-all terms — scoring
    zero comms would hand those candidates a free win."""

    # deliberately slow "hardware": the scored row rounds to 4 decimal
    # places, so the asserted quantities must land well above 1e-4 ms
    SEEDS = autoshard.CostSeeds(peak_tflops=1e-3, ici_gbps=0.01,
                                mfu=0.5, source="test")

    def _score(self, cand, spec):
        from paddle_tpu.autoshard import cost

        return cost.score_candidate(cand, {}, spec, self.SEEDS)

    def test_pp_bubble_stretches_compute(self):
        spec = autoshard.ProbeSpec(**_TINY)
        dense = self._score({"dp": 8, "mp": 1, "pp": 1, "batch": 8,
                             "n_micro": 1}, spec)
        piped = self._score({"dp": 4, "mp": 1, "pp": 2, "batch": 8,
                             "n_micro": 2}, spec)
        # same device count -> same raw compute; pp2/n_micro2 pays the
        # (1 + (pp-1)/n_micro) = 1.5x fill/drain bubble
        assert piped["est_compute_ms"] == pytest.approx(
            dense["est_compute_ms"] * 1.5, rel=1e-3)

    def test_pp_handoff_wire_term_charged_per_device(self):
        # dp=mp=1 isolates the pipeline term: ticks = n_micro + pp - 1
        # = 3; per tick each device ships its own [mb, seq, hidden]
        # slice of the pp-sharded state (NOT the whole stack), fwd+bwd
        # -> 2*3*mb_bytes on the wire
        spec = autoshard.ProbeSpec(**_TINY)
        piped = self._score({"dp": 1, "mp": 1, "pp": 2, "batch": 8,
                             "n_micro": 2}, spec)
        mb_bytes = 4.0 * (8 // 2) * spec.seq * spec.hidden
        expected_ms = 2 * 3 * mb_bytes / (0.01 * 1e9) * 1e3
        assert piped["est_comms_ms"] == pytest.approx(expected_ms,
                                                      rel=1e-3)

    def test_moe_probe_costs_expert_all_to_all(self):
        dense = autoshard.ProbeSpec(**_TINY)
        moe = autoshard.ProbeSpec(**{**_TINY, "moe_experts": 4})
        from paddle_tpu.autoshard import cost

        assert cost.probe_param_count(moe) > cost.probe_param_count(dense)
        cand = {"dp": 8, "mp": 1, "pp": 1, "batch": 8, "n_micro": 1}
        assert self._score(cand, moe)["est_comms_ms"] > \
            self._score(cand, dense)["est_comms_ms"]

    def test_moe_experts_flag_reaches_probe_spec(self):
        import argparse

        from paddle_tpu.autoshard import cli as _cli

        ap = argparse.ArgumentParser()
        _cli.add_probe_args(ap)
        args = ap.parse_args(["--moe-experts", "4"])
        assert autoshard.ProbeSpec.from_args(args).moe_experts == 4


# -- the pp axis on the virtual mesh (ISSUE 15) ------------------------------

_TINY_PP = dict(vocab=128, hidden=32, intermediate=0, layers=2, heads=2,
                seq=16)


class TestPlannerPP:
    @pytest.fixture(scope="class", autouse=True)
    def _exec_cache(self, tmp_path_factory):
        from paddle_tpu.jit import exec_cache

        exec_cache.enable(str(tmp_path_factory.mktemp("autoshard_pp")))
        yield
        exec_cache.disable()
        exec_cache.clear()

    @pytest.fixture(scope="class")
    def sweep(self):
        spec = autoshard.ProbeSpec(**_TINY_PP)
        return autoshard.make_plan(8, 16.0, spec=spec,
                                   configs="dp4xpp2", batches="8")

    def test_pp_candidate_lowers_and_scores(self, sweep):
        plan, rows = sweep
        (row,) = rows
        assert row["pp"] == 2 and row["n_micro"] == 2
        assert row.get("fits") and row["est_step_ms"] > 0

    def test_pp_row_carries_handoff_wire_bytes(self, sweep):
        # the compiled GPipe schedule's collective-permutes must show
        # up in the post-SPMD comms account attributed to the pp axis
        _plan, rows = sweep
        per_axis = rows[0]["collectives"]["per_axis_wire_bytes"]
        pp_bytes = sum(v for ax, v in per_axis.items()
                       if "pp" in ax.split("+"))
        assert pp_bytes > 0, per_axis

    def test_plan_records_pipeline_schedule(self, sweep):
        plan, _rows = sweep
        assert plan.mesh == {"dp": 4, "mp": 1, "pp": 2}
        assert plan.devices == 8
        assert plan.n_micro == 2
        assert plan.stage_assignment == [0, 1]  # 2 layers over 2 stages

    def test_pp_plan_byte_identical_on_repeat(self, sweep):
        plan, _rows = sweep
        spec = autoshard.ProbeSpec(**_TINY_PP)
        plan2, _ = autoshard.make_plan(8, 16.0, spec=spec,
                                       configs="dp4xpp2", batches="8")
        assert plan2.dumps() == plan.dumps()


# -- per-axis collective counters --------------------------------------------

class TestPerAxisCollectiveBytes:
    def test_eager_collective_attributes_axis(self):
        from paddle_tpu import monitor
        from paddle_tpu import distributed as dist
        from paddle_tpu.distributed import env as env_mod

        env_mod.init_mesh(dp=4, mp=2)
        monitor.enable()
        try:
            monitor.reset()
            t = pt.to_tensor(np.ones((8, 8), np.float32))
            dist.all_reduce(t, group="mp")
            snap = monitor.snapshot()["counters"]
            assert snap.get("collective/bytes/mp") == 8 * 8 * 4
            assert snap.get("collective/bytes") == 8 * 8 * 4
            dist.all_reduce(t, group="dp")
            snap = monitor.snapshot()["counters"]
            assert snap.get("collective/bytes/dp") == 8 * 8 * 4
        finally:
            monitor.disable()
            monitor.reset()
            env_mod.reset_env()

    def test_pipeline_forward_attributes_pp_bytes(self):
        # the compiled ppermute handoff never reaches the eager
        # collective hook — the pipeline container accounts it
        # analytically (pipeline/* + collective/bytes/pp), ISSUE 15
        from paddle_tpu import monitor
        from paddle_tpu.distributed import env as env_mod

        try:
            plan = _pp_plan(2, 1, 2, n_micro=2)
            net = _pp_net()
            autoshard.apply_plan(plan, net)
            net = autoshard.stage_model(net, plan)
            monitor.enable()
            monitor.reset()
            x = pt.to_tensor(np.random.randn(8, 8).astype(np.float32))
            net(x)
            snap = monitor.snapshot()
            c = snap["counters"]
            assert c.get("pipeline/forwards") == 1
            assert c.get("pipeline/microbatches") == 2
            assert c.get("pipeline/ticks") == 3  # n_micro + pp - 1
            # per tick: [pp=2, mb=4, 16] fp32 state permuted
            assert c.get("pipeline/p2p_bytes") == 3 * 2 * 4 * 16 * 4
            assert c.get("collective/bytes/pp") == \
                c.get("pipeline/p2p_bytes")
            assert snap["gauges"].get("pipeline/bubble_frac") == \
                pytest.approx(1 / 3)
        finally:
            monitor.disable()
            monitor.reset()
            env_mod.reset_env()

    def test_zero_overhead_off(self):
        # the audit in test_memory_numerics covers import-time inertness;
        # here: with the monitor off, no per-axis counter appears
        from paddle_tpu import monitor
        from paddle_tpu import distributed as dist
        from paddle_tpu.distributed import env as env_mod

        env_mod.init_mesh(dp=4, mp=2)
        try:
            monitor.reset()
            t = pt.to_tensor(np.ones((4, 4), np.float32))
            dist.all_reduce(t, group="mp")
            snap = monitor.snapshot()["counters"]
            assert not any(k.startswith("collective/bytes/")
                           for k in snap)
        finally:
            monitor.reset()
            env_mod.reset_env()


# -- apply_plan + fit(shard_plan=) -------------------------------------------

class TestApplyPlan:
    def _plan(self, dp, mp, batch=16):
        return autoshard.ShardPlan(mesh={"dp": dp, "mp": mp}, batch=batch,
                                   param_specs={})

    def test_apply_places_params_by_derived_specs(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.distributed.shard import get_sharding

        try:
            model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                  nn.Linear(16, 1))
            env = autoshard.apply_plan(self._plan(2, 2), model)
            assert env.degree("dp") == 2 and env.degree("mp") == 2
            w0 = get_sharding(model[0].weight)
            w2 = get_sharding(model[2].weight)
            assert tuple(w0) == (None, "mp")
            assert tuple(w2) == ("mp",)  # trailing None trimmed
        finally:
            env_mod.reset_env()

    def test_shard_batch_scalar_replicates(self):
        from paddle_tpu.distributed import env as env_mod

        try:
            autoshard.apply_plan(self._plan(4, 2))
            t = autoshard.shard_batch(pt.to_tensor(3.0))  # 0-d: no
            assert float(t.numpy()) == 3.0                # batch dim
            b = autoshard.shard_batch(pt.to_tensor(
                np.ones((8, 2), np.float32)))
            assert "dp" in str(b._data.sharding)
        finally:
            env_mod.reset_env()

    def test_fit_shard_plan_trains_sharded(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.distributed.shard import get_sharding
        from paddle_tpu.hapi import Model

        try:
            plan_path = self._plan(2, 2, batch=8).save(
                str(tmp_path / "plan.json"))
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 4))
            m = Model(net)
            m.prepare(pt.optimizer.AdamW(
                learning_rate=1e-3, parameters=net.parameters()),
                pt.nn.CrossEntropyLoss())
            xs = np.random.randn(16, 8).astype("float32")
            ys = np.random.randint(0, 4, (16, 1))
            ds = [(xs[i], ys[i]) for i in range(16)]
            m.fit(ds, batch_size=8, epochs=1, verbose=0, log_freq=1,
                  shard_plan=plan_path)
            assert tuple(get_sharding(net[0].weight)) == (None, "mp")
            assert env_mod.get_env().degree("mp") == 2
            # data parallelism must be IN the compiled step: fit shards
            # batches over dp, so the grad sync appears as dp traffic
            # (the regression: replicated batches compile dp out)
            entry = next(iter(m._train_step._cache.values()))
            comms = hlo_costs.collective_bytes_by_axis(
                entry.compiled.as_text(),
                {"dp": 2, "pp": 1, "sharding": 1, "sep": 1, "mp": 2})
            assert any("dp" in ax.split("+")
                       for ax in comms["per_axis_wire_bytes"]), comms
        finally:
            env_mod.reset_env()


# -- pp staging + stage-move reshard (ISSUE 15) ------------------------------

_nn = __import__("paddle_tpu.nn", fromlist=["nn"])


class _PPBlock(_nn.Layer):
    """The repeated (stage-able) unit — ONE class, so the pipeline
    container's repeated-run detection sees identical block types."""

    def __init__(self, width):
        super().__init__()
        self.fc = _nn.Linear(width, width)

    def forward(self, x):
        return pt.tanh(self.fc(x))


def _pp_net(out_dim=1):
    pt.seed(0)
    return _nn.Sequential(_nn.Linear(8, 16), _PPBlock(16), _PPBlock(16),
                          _nn.Linear(16, out_dim))


def _pp_plan(dp, mp, pp, n_micro=1, batch=8):
    return autoshard.ShardPlan(mesh={"dp": dp, "mp": mp, "pp": pp},
                               batch=batch, param_specs={},
                               n_micro=n_micro)


class TestPipelineStaging:
    def test_stage_model_wraps_block_run(self):
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers \
            .pp_layers import PipelineLayer

        try:
            plan = _pp_plan(2, 1, 2, n_micro=2)
            net = _pp_net()
            autoshard.apply_plan(plan, net)
            staged = autoshard.stage_model(net, plan)
            assert isinstance(staged, PipelineLayer) and staged._pipelined
            assert staged._n_blocks == 2
            names = dict(staged.named_parameters())
            assert any(n.startswith("stack__") for n in names)
            # pp=1 plans stage nothing
            env_mod.reset_env()
            plan1 = _pp_plan(2, 1, 1)
            net1 = _pp_net()
            autoshard.apply_plan(plan1, net1)
            assert autoshard.stage_model(net1, plan1) is net1
        finally:
            env_mod.reset_env()

    def test_canonical_state_dict_covers_block_buffers(self):
        # the staged container shares ONE buffer across blocks
        # (blocks[1:]'s copies are discarded at construction); the
        # canonical checkpoint surface must still write/read it under
        # every block's flat key so flat↔staged round trips never miss
        # a tensor
        from paddle_tpu.distributed import env as env_mod

        class BufBlock(_nn.Layer):
            def __init__(self, width):
                super().__init__()
                self.fc = _nn.Linear(width, width)
                self.register_buffer("scale",
                                     pt.to_tensor(np.float32(1.5)))

            def forward(self, x):
                return pt.tanh(self.fc(x)) * self.scale

        try:
            plan = _pp_plan(2, 1, 2, n_micro=2)
            pt.seed(0)
            net = _nn.Sequential(_nn.Linear(8, 16), BufBlock(16),
                                 BufBlock(16), _nn.Linear(16, 1))
            autoshard.apply_plan(plan, net)
            staged = autoshard.stage_model(net, plan)
            sd = staged.state_dict()
            assert "1.scale" in sd and "2.scale" in sd
            sd2 = {k: (pt.to_tensor(np.float32(2.0))
                       if k.endswith(".scale") else v)
                   for k, v in sd.items()}
            missing, unexpected = staged.set_state_dict(sd2)
            assert not missing and not unexpected
            assert float(staged._template.scale.numpy()) == 2.0
            # the canonical keys equal the flat (pp=1) container's
            env_mod.reset_env()
            autoshard.apply_plan(_pp_plan(2, 1, 1))
            pt.seed(0)
            flat = _nn.Sequential(_nn.Linear(8, 16), BufBlock(16),
                                  BufBlock(16), _nn.Linear(16, 1))
            from paddle_tpu.distributed.fleet.meta_parallel \
                .parallel_layers.pp_layers import PipelineLayer

            flat_pipe = PipelineLayer(
                [sub for _, sub in flat.named_children()])
            assert set(flat_pipe.state_dict()) == set(sd)
        finally:
            env_mod.reset_env()

    def test_stage_model_keeps_remat_knobs(self):
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.distributed.fleet.meta_parallel \
            .parallel_layers.pp_layers import PipelineLayer

        try:
            # a pp=1-built container with remat knobs set: re-staging
            # under a pp2 plan must carry them (the probe the plan
            # judged ran WITH remat — docs/AUTOSHARD.md)
            env_mod.init_mesh(dp=8)
            pre = PipelineLayer(
                [_PPBlock(16), _PPBlock(16)], recompute_interval=1,
                remat_ticks=True, loss_fn=lambda o, l: o.mean())
            assert not pre._pipelined
            env_mod.reset_env()
            plan = _pp_plan(4, 1, 2, n_micro=2)
            autoshard.apply_plan(plan)
            staged = autoshard.stage_model(pre, plan)
            assert staged._pipelined
            assert staged._recompute == 1
            assert staged._remat_ticks is True
            assert staged.loss_fn is pre.loss_fn
        finally:
            env_mod.reset_env()

    def test_stage_model_unstageable_raises_with_hint(self):
        from paddle_tpu.distributed import env as env_mod
        import paddle_tpu.nn as nn

        try:
            plan = _pp_plan(4, 1, 2, n_micro=2)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 1))  # no repeated run ≥ 2
            autoshard.apply_plan(plan, net)
            with pytest.raises(ValueError, match="PipelineLayer|Pipe"):
                autoshard.stage_model(net, plan)
        finally:
            env_mod.reset_env()

    def test_fit_planned_pp2_matches_pp1_curve(self, tmp_path):
        """ISSUE 15 acceptance: a planned pp2 fit() on the virtual
        8-device CPU mesh trains with losses matching the pp=1
        baseline curve — the 1F1B-in-XLA correctness proof."""
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.hapi import Model

        rng = np.random.default_rng(3)
        xs = rng.standard_normal((16, 8)).astype("float32")
        ys = rng.integers(0, 4, (16, 1))
        ds = [(xs[i], ys[i]) for i in range(16)]

        def run(plan):
            losses = []

            class Tap(pt.callbacks.Callback):
                def on_train_batch_end(self, step, logs=None):
                    losses.append(float(logs["loss"]))

            try:
                plan_path = plan.save(
                    str(tmp_path / f"plan_pp{plan.mesh['pp']}.json"))
                net = _pp_net(out_dim=4)
                m = Model(net)
                m.prepare(pt.optimizer.AdamW(
                    learning_rate=1e-2, parameters=net.parameters()),
                    pt.nn.CrossEntropyLoss())
                m.fit(ds, batch_size=8, epochs=1, verbose=0, log_freq=1,
                      shuffle=False, shard_plan=plan_path,
                      callbacks=[Tap()])
            finally:
                env_mod.reset_env()
            return losses

        base = run(_pp_plan(2, 1, 1))
        pp2 = run(_pp_plan(2, 1, 2, n_micro=2))
        assert len(base) == len(pp2) == 2
        for a, b in zip(base, pp2):
            assert abs(a - b) <= 1e-4 * max(1.0, abs(a)), (base, pp2)


class TestStageMoveReshard:
    """A checkpoint saved at one pp resumes at another ON THE SAME LOSS
    CURVE — the canonical per-block checkpoint keys + the stacked
    assemble/split in resilience/resume.py (docs/RESILIENCE.md)."""

    STEPS = 4
    MOVE_AT = 2

    def _train(self, plan, steps, data, workdir, resume=False,
               ckpt_at=None):
        import paddle_tpu.nn.functional as F
        from paddle_tpu import resilience
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.resilience import resume as rez

        xs, w_true = data
        try:
            net = _pp_net()
            autoshard.apply_plan(plan, net)
            net = autoshard.stage_model(net, plan)
            opt = pt.optimizer.AdamW(learning_rate=5e-2,
                                     parameters=net.parameters())
            start = 0
            if resume:
                scal = rez.restore_latest(net, opt, str(workdir))
                start = int(scal.get("step", 0))
            mgr = resilience.CheckpointManager(str(workdir), interval=1,
                                               keep=3, async_save=False)
            losses = []
            for step in range(start, steps):
                x = autoshard.shard_batch(pt.to_tensor(xs[step]))
                y = autoshard.shard_batch(pt.to_tensor(xs[step] @ w_true))
                loss = F.mse_loss(net(x), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(np.asarray(
                    loss.numpy()).reshape(-1)[0]))
                if ckpt_at is not None and step + 1 == ckpt_at:
                    mgr.save(step + 1,
                             rez.capture(net, opt, step=step + 1))
            return losses
        finally:
            env_mod.reset_env()

    @pytest.fixture(scope="class")
    def data(self):
        rng = np.random.default_rng(0)
        return (rng.standard_normal((self.STEPS, 8, 8)).astype("float32"),
                rng.standard_normal((8, 1)).astype("float32"))

    @pytest.fixture(scope="class")
    def reference(self, data, tmp_path_factory):
        wd = tmp_path_factory.mktemp("ref")
        return self._train(_pp_plan(2, 1, 1), self.STEPS, data, wd)

    def _assert_on_curve(self, ref, got):
        assert len(ref) == len(got)
        for i, (a, b) in enumerate(zip(ref, got)):
            assert abs(a - b) <= 1e-4 * max(1.0, abs(a)), (i, ref, got)

    def test_pp1_checkpoint_resumes_at_pp2(self, data, reference,
                                           tmp_path):
        first = self._train(_pp_plan(2, 1, 1), self.MOVE_AT, data,
                            tmp_path, ckpt_at=self.MOVE_AT)
        second = self._train(_pp_plan(2, 1, 2, n_micro=2), self.STEPS,
                             data, tmp_path, resume=True)
        self._assert_on_curve(reference, first + second)

    def test_pp2_checkpoint_resumes_at_pp1(self, data, reference,
                                           tmp_path):
        first = self._train(_pp_plan(2, 1, 2, n_micro=2), self.MOVE_AT,
                            data, tmp_path, ckpt_at=self.MOVE_AT)
        second = self._train(_pp_plan(4, 1, 1), self.STEPS, data,
                             tmp_path, resume=True)
        self._assert_on_curve(reference, first + second)

    def test_nested_pipe_checkpoints_round_trip_raw(self, tmp_path):
        """The canonical per-block layout is scoped to a TOP-LEVEL
        pipeline network: a pipe nested inside a wrapper model
        checkpoints its raw stacked tensors through the generic
        Layer.state_dict and restores in place (same-topology reshard,
        no stage-move conversion, no crash)."""
        from paddle_tpu import resilience
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.resilience import resume as rez

        class Wrapper(_nn.Layer):
            def __init__(self):
                super().__init__()
                plan = _pp_plan(2, 1, 2, n_micro=2)
                inner = _pp_net()
                self.pipe = autoshard.stage_model(inner, plan)

            def forward(self, x):
                return self.pipe(x)

        try:
            autoshard.apply_plan(_pp_plan(2, 1, 2, n_micro=2))
            w = Wrapper()
            assert any(".stack__" in k for k in w.state_dict())
            flat, scalars = rez.capture(w, None, step=1)
            mgr = resilience.CheckpointManager(str(tmp_path), interval=1,
                                               keep=1, async_save=False)
            mgr.save(1, (flat, scalars))
            autoshard.apply_plan(_pp_plan(2, 1, 2, n_micro=2))
            w2 = Wrapper()
            rez.restore_latest(w2, None, str(tmp_path))
            for (k, a), (_, b) in zip(w.state_dict().items(),
                                      w2.state_dict().items()):
                np.testing.assert_array_equal(
                    np.asarray(a._data), np.asarray(b._data), err_msg=k)
        finally:
            env_mod.reset_env()


# -- CLI: the tier-1 pipeline proof ------------------------------------------

def _run_plan_cli(out, cache, extra=()):
    env = dict(os.environ)
    env["PT_EXEC_CACHE"] = str(cache)
    return subprocess.run(
        [sys.executable, "tools/shard_plan.py", "plan", "--smoke",
         "--out", str(out), *extra],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=900)


def test_cli_smoke_deterministic_and_exec_cache_warm(tmp_path):
    """Acceptance (ISSUE 10 + 15): `shard_plan.py plan --smoke`
    enumerates and scores a pp>1 candidate next to the dp×mp ones,
    emits a deterministic plan whose winner fits, and a second
    invocation with PT_EXEC_CACHE set reports ZERO fresh XLA
    compiles."""
    cache = tmp_path / "cache"
    cold = _run_plan_cli(tmp_path / "p1.json", cache)
    assert cold.returncode == 0, cold.stderr[-2000:]
    assert "FITS" in cold.stdout and "winner:" in cold.stdout
    assert "·pp2" in cold.stdout  # the smoke sweep's pipeline candidate
    warm = _run_plan_cli(tmp_path / "p2.json", cache)
    assert warm.returncode == 0, warm.stderr[-2000:]
    line = json.loads([ln for ln in warm.stdout.splitlines()
                       if ln.startswith("{")][-1])
    assert line["shard_plan"]["fresh_compiles"] == 0, line
    assert (tmp_path / "p1.json").read_bytes() == \
        (tmp_path / "p2.json").read_bytes()
    plan = autoshard.load_plan(str(tmp_path / "p1.json"))
    winner_row = next(r for r in plan.rows if r["label"] == plan.winner)
    assert winner_row["fits"]
    pp_row = next(r for r in plan.rows if r.get("pp", 1) > 1)
    assert "error" not in pp_row and pp_row.get("fits")
    assert pp_row["est_step_ms"] > 0 and pp_row["n_micro"] > 1


# -- the launcher proofs (slow tier) -----------------------------------------

_SCRIPT = str(Path(__file__).parent / "autoshard_launch_script.py")


def _make_plan_file(configs, path, devices=2):
    proc = subprocess.run(
        [sys.executable, "tools/shard_plan.py", "plan",
         "--devices", str(devices), "--configs", configs,
         "--out", str(path),
         "--hidden", "32", "--layers", "2", "--heads", "2",
         "--seq", "16", "--vocab", "64", "--batches", "8"],
        cwd=_ROOT, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return str(path)


def _launch(workdir, plan, crash_at, resume=False):
    env = dict(os.environ)
    env["AUTOSHARD_CRASH_AT"] = str(crash_at)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_RESTART_COUNT", None)
    if resume:
        env["PT_SHARD_RESUME"] = str(workdir / "ckpt")
    else:
        env.pop("PT_SHARD_RESUME", None)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restart", "0", "--shard_plan", plan,
         "--log_dir", str(workdir / "log"), _SCRIPT, str(workdir)],
        cwd=_ROOT, env=env, capture_output=True, text=True,
        timeout=600)


def _losses_of(workdir):
    out = {}
    for f in sorted(workdir.glob("losses_r*.json")):
        data = json.loads(f.read_text())
        for i, l in enumerate(data["losses"]):
            out[data["start"] + i] = l
    return out


def _run_launch_proof(tmp_path, plan_a, plan_b, mesh_b, crash_at=3):
    """Shared plan→launch→kill→replan→resume scaffolding: returns
    nothing, asserts the stitched curve matches the clean plan-A run."""
    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    proc = _launch(clean_dir, plan_a, crash_at=-1)
    assert proc.returncode == 0, proc.stderr[-2000:] + "".join(
        p.read_text()[-2000:]
        for p in (clean_dir / "log").glob("workerlog.*"))
    clean = _losses_of(clean_dir)

    # crash run: life 0 under plan A dies mid-run (launcher + worker =
    # the 2-process proof)...
    crash_dir = tmp_path / "crash"
    crash_dir.mkdir()
    proc = _launch(crash_dir, plan_a, crash_at=crash_at)
    assert proc.returncode == 17, proc.stderr[-2000:]
    # ...then the REPLANNED topology resumes the same checkpoints
    proc = _launch(crash_dir, plan_b, crash_at=-1, resume=True)
    assert proc.returncode == 0, proc.stderr[-2000:] + "".join(
        p.read_text()[-2000:]
        for p in (crash_dir / "log").glob("workerlog.*"))
    crashed = _losses_of(crash_dir)

    assert sorted(clean) == sorted(crashed) == list(range(6))
    r1 = json.loads((crash_dir / "losses_r1.json").read_text())
    assert r1["start"] == crash_at       # resumed, not restarted
    assert r1["mesh"] == mesh_b          # ...under the replanned mesh
    for step in range(6):
        # same curve, not bit-identical: the mesh change legitimately
        # reorders reductions
        assert abs(clean[step] - crashed[step]) <= 1e-4 * max(
            1.0, abs(clean[step])), (step, clean[step], crashed[step])


@pytest.mark.slow
def test_plan_launch_kill_replan_resume(tmp_path):
    """ISSUE 10 acceptance: plan at dp2×mp1 on the virtual mesh, launch
    through the launcher, kill mid-run, REPLAN at dp1×mp2, resume the
    checkpoint through reshard-on-load — losses on the same curve, with
    no hand-written PartitionSpecs anywhere in the test path."""
    plan_a = _make_plan_file("dp2xmp1", tmp_path / "plan_a.json")
    plan_b = _make_plan_file("dp1xmp2", tmp_path / "plan_b.json")
    assert autoshard.load_plan(plan_a).mesh == {"dp": 2, "mp": 1, "pp": 1}
    assert autoshard.load_plan(plan_b).mesh == {"dp": 1, "mp": 2, "pp": 1}
    _run_launch_proof(tmp_path, plan_a, plan_b,
                      mesh_b={"dp": 1, "mp": 2, "pp": 1})


@pytest.mark.slow
def test_plan_launch_kill_replan_resume_pp(tmp_path):
    """ISSUE 15 acceptance: the launcher proof across a stage boundary
    — plan dp2×pp2 on 4 virtual devices, launch, kill mid-run, replan
    dp1×mp2×pp2, resume the PIPELINED checkpoints through the
    canonical per-block reshard — losses on the clean curve."""
    plan_a = _make_plan_file("dp2xpp2", tmp_path / "plan_a.json",
                             devices=4)
    plan_b = _make_plan_file("dp1xmp2xpp2", tmp_path / "plan_b.json",
                             devices=4)
    pa = autoshard.load_plan(plan_a)
    assert pa.mesh == {"dp": 2, "mp": 1, "pp": 2}
    assert pa.n_micro > 1 and pa.stage_assignment == [0, 1]
    assert autoshard.load_plan(plan_b).mesh == {"dp": 1, "mp": 2, "pp": 2}
    _run_launch_proof(tmp_path, plan_a, plan_b,
                      mesh_b={"dp": 1, "mp": 2, "pp": 2})
