"""Round-3 namespace completions behavior: vision transforms/ops layers,
incubate graph+fused ops, distributed comm additions, static compat,
fleet role makers, LBFGS, saved_tensors_hooks, jit flags, worker info."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.vision import transforms as T


def _t(a):
    return pt.to_tensor(np.asarray(a))


@pytest.fixture(scope="module", autouse=True)
def _mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


class TestTransforms:
    def test_color_ops(self):
        img = (np.random.RandomState(0).rand(8, 8, 3) * 255) \
            .astype(np.uint8)
        assert T.adjust_brightness(img, 1.0).dtype == np.uint8
        np.testing.assert_array_equal(T.adjust_brightness(img, 1.0), img)
        dark = T.adjust_brightness(img, 0.5)
        assert dark.mean() < img.mean()
        flat = T.adjust_contrast(img, 0.0)
        assert flat.std() < 2  # collapses toward the gray mean
        np.testing.assert_array_equal(T.adjust_hue(img, 0.0), img)
        g = T.to_grayscale(img, 3)
        assert g.shape == (8, 8, 3)
        assert np.abs(g[..., 0].astype(int) - g[..., 1].astype(int)).max() \
            == 0
        with pytest.raises(ValueError):
            T.adjust_hue(img, 0.7)

    def test_hue_roundtrip(self):
        img = (np.random.RandomState(1).rand(6, 6, 3) * 255).astype(np.uint8)
        back = T.adjust_hue(T.adjust_hue(img, 0.25), -0.25)
        assert np.abs(back.astype(int) - img.astype(int)).max() <= 3

    def test_crop_pad_erase(self):
        img = np.arange(64, dtype=np.uint8).reshape(8, 8, 1)
        c = T.crop(img, 2, 3, 4, 5)
        assert c.shape == (4, 5, 1) and c[0, 0, 0] == 2 * 8 + 3
        p = T.pad(img, 2)
        assert p.shape == (12, 12, 1) and p[0, 0, 0] == 0
        pr = T.pad(img, (1, 2), padding_mode="reflect")
        assert pr.shape == (12, 10, 1)
        e = T.erase(img, 1, 1, 3, 3, 7)
        assert (e[1:4, 1:4] == 7).all() and img[1, 1, 0] != 7

    def test_rotate_affine_perspective(self):
        img = np.zeros((9, 9, 1), np.float32)
        img[4, 6] = 1.0
        # 90-degree rotation moves (r=4, c=6) around center (4, 4)
        r = T.rotate(img, 90, interpolation="nearest")
        assert r.shape == (9, 9, 1) and r.sum() == 1.0
        ident = T.affine(img, 0.0, (0, 0), 1.0, (0.0, 0.0),
                         interpolation="bilinear")
        np.testing.assert_allclose(ident, img, atol=1e-4)
        shift = T.affine(img, 0.0, (1, 0), 1.0, (0.0, 0.0),
                         interpolation="nearest")
        assert shift[4, 7] == 1.0
        pts = [(0, 0), (8, 0), (8, 8), (0, 8)]
        np.testing.assert_allclose(
            T.perspective(img, pts, pts, interpolation="bilinear"), img,
            atol=1e-4)

    def test_random_transform_classes(self):
        img = (np.random.RandomState(2).rand(16, 16, 3) * 255) \
            .astype(np.uint8)
        for tr in [T.ColorJitter(0.2, 0.2, 0.2, 0.1), T.Grayscale(3),
                   T.Pad(2), T.RandomRotation(10),
                   T.RandomAffine(5, translate=(0.1, 0.1)),
                   T.RandomPerspective(prob=1.0),
                   T.RandomErasing(prob=1.0)]:
            out = tr(img)
            assert out is not None and np.asarray(out).ndim == 3

    def test_vision_backend_helpers(self):
        assert pt.vision.get_image_backend() == "pil"
        pt.vision.set_image_backend("numpy")
        try:
            from PIL import Image

            im = Image.fromarray(np.zeros((4, 4, 3), np.uint8))
            im.save("/tmp/_pt_img.png")
            arr = pt.vision.image_load("/tmp/_pt_img.png")
            assert arr.shape == (4, 4, 3)
        finally:
            pt.vision.set_image_backend("pil")
        with pytest.raises(ValueError):
            pt.vision.set_image_backend("bogus")


class TestVisionOpsLayers:
    def test_roi_layers(self):
        x = _t(np.random.randn(1, 4, 16, 16).astype(np.float32))
        boxes = _t(np.array([[2.0, 2.0, 10.0, 10.0]], np.float32))
        bnum = _t(np.array([1], np.int32))
        for cls in [pt.vision.ops.RoIAlign, pt.vision.ops.RoIPool]:
            layer = cls(output_size=4)
            out = layer(x, boxes, bnum)
            assert out.shape[0] == 1 and out.shape[2] == 4
        ps = pt.vision.ops.PSRoIPool(output_size=2)(x, boxes, bnum)
        assert ps.shape[2] == 2

    def test_deform_conv_layer(self):
        layer = pt.vision.ops.DeformConv2D(3, 6, 3, padding=1)
        x = _t(np.random.randn(2, 3, 8, 8).astype(np.float32))
        offset = _t(np.zeros((2, 18, 8, 8), np.float32))
        assert layer(x, offset).shape == [2, 6, 8, 8]


class TestIncubate:
    def test_segment_and_graph_aliases(self):
        data = _t(np.arange(6, dtype=np.float32).reshape(3, 2))
        seg = _t(np.array([0, 0, 1]))
        s = pt.incubate.segment_sum(data, seg)
        np.testing.assert_allclose(s.numpy(), [[2, 4], [4, 5]])
        out = pt.incubate.graph_send_recv(
            data, _t(np.array([0, 1, 2])), _t(np.array([1, 2, 0])))
        assert out.shape == [3, 2]

    def test_softmax_mask_fuse(self):
        x = _t(np.random.randn(2, 4, 4).astype(np.float32))
        m = _t(np.zeros((2, 4, 4), np.float32))
        out = pt.incubate.softmax_mask_fuse(x, m)
        np.testing.assert_allclose(out.numpy().sum(-1), 1.0, rtol=1e-5)
        tri = pt.incubate.softmax_mask_fuse_upper_triangle(x)
        t = tri.numpy()
        assert np.allclose(t.sum(-1), 1.0, rtol=1e-5)
        assert np.allclose(t[:, 0, 1:], 0.0, atol=1e-6)  # causal row 0

    def test_identity_loss_and_lookahead(self):
        x = _t(np.array([1.0, 3.0], np.float32))
        assert float(pt.incubate.identity_loss(x, "sum").numpy()) == 4.0
        p = pt.to_tensor(np.zeros(2, np.float32))
        p.stop_gradient = False
        p.is_parameter = True
        inner = pt.optimizer.SGD(learning_rate=0.1, parameters=[p])
        la = pt.incubate.LookAhead(inner, alpha=0.5, k=2)
        tgt = _t(np.array([1.0, 1.0], np.float32))
        for _ in range(4):
            loss = ((p - tgt) ** 2).sum()
            loss.backward()
            la.step()
            la.clear_grad()
        assert float(((p - tgt) ** 2).sum().numpy()) < 2.0

    def test_khop_sampler(self):
        # chain graph 0->1->2->3 in CSC
        row = _t(np.array([1, 2, 3, 0], np.int64))
        colptr = _t(np.array([0, 1, 2, 3, 4], np.int64))
        nodes = _t(np.array([0], np.int64))
        n, c, src, dst, out_nodes = pt.incubate.graph_khop_sampler(
            row, colptr, nodes, [1, 1])
        assert len(out_nodes.numpy()) >= 2
        assert len(src.numpy()) == len(dst.numpy())


class TestDistributedAdditions:
    def test_gather_and_alltoall_single(self):
        x = _t(np.arange(16, dtype=np.float32))
        parts = pt.distributed.gather(x)
        assert len(parts) >= 1
        y = pt.distributed.alltoall_single(None, x)
        assert y.shape == [16]
        with pytest.raises(NotImplementedError):
            pt.distributed.alltoall_single(None, x, in_split_sizes=[6, 10])

    def test_object_and_introspection(self):
        out = []
        pt.distributed.scatter_object_list(
            out, [{"a": 1}] * pt.distributed.get_group().nranks)
        assert out[0] == {"a": 1}
        assert pt.distributed.get_backend() == "XLA"
        assert pt.distributed.is_available()
        assert pt.distributed.ParallelMode.DATA_PARALLEL == 0

    def test_split_linear(self):
        x = _t(np.random.randn(4, 8).astype(np.float32))
        out = pt.distributed.split(x, (8, 6), "linear", axis=1,
                                   num_partitions=2)
        assert out.shape == [4, 6]
        emb = pt.distributed.split(_t(np.array([[1, 2]])), (10, 4),
                                   "embedding", num_partitions=2)
        assert emb.shape == [1, 2, 4]

    def test_ps_shims_raise(self):
        with pytest.raises(RuntimeError, match="parameter-server"):
            pt.distributed.InMemoryDataset()

    def test_fleet_surface(self):
        f = fleet.Fleet()
        assert f.is_worker() and not f.is_server()
        assert f.util.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]
        rm = fleet.PaddleCloudRoleMaker()
        assert rm.worker_index() == 0 and rm.is_worker()
        rm2 = fleet.UserDefinedRoleMaker(current_id=1, worker_num=4)
        assert rm2.worker_index() == 1 and rm2.worker_num() == 4

    def test_data_generator(self, capsys):
        class G(fleet.MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    yield [("words", [1, 2, 3]), ("label", [0])]

                return it

        g = G()
        g.set_batch(1)
        g.run_from_memory()
        out = capsys.readouterr().out
        assert out.strip() == "3 1 2 3 1 0"


class TestStaticCompat:
    def test_ema(self):
        p = pt.create_parameter([2])
        p.set_value(np.ones(2, np.float32))
        ema = pt.static.ExponentialMovingAverage(0.5)
        ema.update([p])
        p.set_value(np.zeros(2, np.float32))
        ema.update([p])
        with ema.apply():
            applied = p.numpy().copy()
        assert 0 < applied[0] < 1  # the EMA value
        np.testing.assert_allclose(p.numpy(), 0.0)

    def test_gradients_and_append_backward(self):
        x = _t(np.array([2.0], np.float32))
        x.stop_gradient = False
        y = (x ** 2).sum()
        (g,) = pt.static.gradients(y, x)
        np.testing.assert_allclose(g.numpy(), [4.0])

    def test_program_state_roundtrip(self, tmp_path):
        main = pt.static.Program()
        with pt.static.program_guard(main, pt.static.Program()):
            xv = pt.static.data("X", [None, 4], "float32")
            out = pt.static.nn.fc(xv, 2)  # noqa: F841
        path = str(tmp_path / "m")
        pt.static.save(main, path)
        state = pt.static.load_program_state(path)
        assert state
        pt.static.set_program_state(main, state)
        blob = pt.static.serialize_persistables([], [], program=main)
        pt.static.deserialize_persistables(main, blob)

    def test_compiled_program_and_strategies(self):
        bs = pt.static.BuildStrategy()
        bs.fuse_elewise_add_act_ops = True
        assert bs.fuse_elewise_add_act_ops is True
        assert bs.nonexistent_flag is None
        prog = pt.static.Program()
        cp = pt.static.CompiledProgram(prog, build_strategy=bs)
        assert cp.program is prog
        assert isinstance(pt.static.Variable, type)

    def test_excluded_raise(self):
        with pytest.raises(RuntimeError, match="IPU"):
            pt.static.IpuStrategy()
        with pytest.raises(RuntimeError, match="parameter-server"):
            pt.static.ctr_metric_bundle(None, None)

    def test_places(self):
        places = pt.static.cpu_places()
        assert places
        with pytest.raises(RuntimeError):
            pt.static.xpu_places()

    def test_exponential_decay_steps(self):
        s = pt.static.exponential_decay(0.1, decay_steps=100,
                                        decay_rate=0.96)
        for _ in range(50):
            s.step()
        assert abs(s.get_lr() - 0.1 * 0.96 ** 0.5) < 1e-6
        s2 = pt.static.exponential_decay(0.1, 100, 0.96, staircase=True)
        for _ in range(50):
            s2.step()
        assert s2.get_lr() == 0.1


class TestReviewFixRegressions:
    def test_eager_fallback_bound_layer(self):
        class Net(pt.nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = pt.nn.Linear(2, 2)

            @pt.jit.to_static
            def forward(self, x):
                return self.fc(x)

        net = Net()
        x = _t(np.ones((1, 2), np.float32))
        y1 = net(x).numpy()
        pt.jit.enable_to_static(False)
        try:
            y2 = net(x).numpy()
        finally:
            pt.jit.enable_to_static(True)
        np.testing.assert_allclose(y1, y2, rtol=1e-6)

    def test_multi_root_backward_shared_graph(self):
        a = _t(np.ones(3, np.float32))
        a.stop_gradient = False
        h = a * 2
        pt.autograd.backward([h.sum(), (h * 3).sum()])
        np.testing.assert_allclose(a.grad.numpy(), 8.0)

    def test_saved_tensors_hooks(self):
        packed = []

        def pack(arr):
            packed.append(arr.shape)
            return np.asarray(arr)

        def unpack(p):
            import jax.numpy as jnp

            return jnp.asarray(p)

        x = _t(np.random.randn(4, 3).astype(np.float32))
        x.stop_gradient = False
        w = _t(np.random.randn(3, 2).astype(np.float32))
        w.stop_gradient = False
        with pt.autograd.saved_tensors_hooks(pack, unpack):
            y = pt.matmul(x, w).sum()
        y.backward()
        assert packed
        x2 = _t(x.numpy())
        x2.stop_gradient = False
        w2 = _t(w.numpy())
        w2.stop_gradient = False
        pt.matmul(x2, w2).sum().backward()
        np.testing.assert_allclose(w.grad.numpy(), w2.grad.numpy(),
                                   rtol=1e-5)

    def test_jit_all_clean(self):
        bad = [n for n in pt.jit.__all__
               if n in ("json", "os", "np", "jax", "annotations")]
        assert not bad

    def test_lbfgs_quadratic(self):
        target = np.array([1.0, -2.0, 3.0], np.float32)
        p = pt.to_tensor(np.zeros(3, np.float32))
        p.stop_gradient = False
        p.is_parameter = True
        opt = pt.optimizer.LBFGS(parameters=[p], max_iter=30)

        def closure():
            loss = ((p - _t(target)) ** 2).sum()
            loss.backward()
            return loss

        loss = opt.step(closure)
        assert float(loss.numpy()) < 1e-6
        np.testing.assert_allclose(p.numpy(), target, atol=1e-3)

    def test_lookahead_state_roundtrip(self):
        p = pt.to_tensor(np.zeros(2, np.float32))
        p.stop_gradient = False
        p.is_parameter = True
        la = pt.incubate.LookAhead(
            pt.optimizer.SGD(0.1, parameters=[p]), k=1)
        ((p - 1.0) ** 2).sum().backward()
        la.step()
        la.clear_grad()
        sd = la.state_dict()
        la2 = pt.incubate.LookAhead(
            pt.optimizer.SGD(0.1, parameters=[p]), k=1)
        la2.set_state_dict(sd)
        assert la2._step_num == 1 and la2._slow

    def test_worker_info(self):
        class DS(pt.io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                wi = pt.io.get_worker_info()
                assert wi is not None and wi.num_workers == 2
                return np.float32(i)

        dl = pt.io.DataLoader(DS(), batch_size=4, num_workers=2)
        total = sum(float(b.numpy().sum()) for b in dl)
        assert total == 28.0
        assert pt.io.get_worker_info() is None
