"""Device memory observatory + numerics sentinel tests.

Covers the memory ledger (live-buffer censuses at StepLogger step
boundaries, per-executable records with mesh annotation on the virtual
8-device mesh), the OOM preflight planner (fits/doesn't-fit verdicts from
lowering-only cost data + the CLI smoke), the numerics sentinel (an
injected non-finite grad at a chosen step is caught and named, loss-level
failures isolate to "loss", the healthy path costs ≤ 1 extra host scalar
fetch per step — proven via the ``hapi/host_syncs`` guard counter), and
the extended zero-overhead audit: every module registering monitor slots
is import-time-inert while PT_MONITOR / PT_NANCHECK / PT_MONITOR_MEM are
unset.
"""
import importlib
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.monitor import memory as memobs
from paddle_tpu.monitor import numerics

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def mon(tmp_path, monkeypatch):
    """Enabled monitor with clean metrics; restores disabled-off state."""
    monkeypatch.setenv("PT_MONITOR_SINK", str(tmp_path / "steps.jsonl"))
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


@pytest.fixture
def mem():
    """Enabled memory observatory; always torn down."""
    led = memobs.enable()
    yield led
    memobs.disable()


@pytest.fixture
def mesh():
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _linear_step(donate=False, nan_check=None, lr=0.1):
    net = pt.nn.Linear(4, 4)
    opt = pt.optimizer.SGD(learning_rate=lr, parameters=net.parameters())
    return net, TrainStep(net, opt,
                          lambda m, x, y: ((m(x) - y) ** 2).mean(),
                          donate=donate, nan_check=nan_check)


# -- memory observatory ------------------------------------------------------

class TestMemoryLedger:
    def test_live_census_counts_buffers(self):
        keep = pt.to_tensor(np.ones((64, 64), np.float32))
        c = memobs.live_census()
        assert c["live_bytes"] >= 64 * 64 * 4
        assert c["live_buffers"] >= 1
        del keep

    def test_ledger_census_tracks_peak(self, mem):
        c1 = mem.census()
        big = pt.to_tensor(np.ones((256, 256), np.float32))
        c2 = mem.census()
        assert c2["live_bytes"] >= c1["live_bytes"] + 256 * 256 * 4
        assert mem.peak_live_bytes >= c2["live_bytes"]
        del big
        c3 = mem.census(tag="after_free")
        # peak survives the free; the live number drops
        assert mem.peak_live_bytes >= c3["live_bytes"]
        assert c3["tag"] == "after_free"
        assert mem.census_count == 3

    def test_census_sets_gauges(self, mon, mem):
        mem.census()
        g = mon.snapshot()["gauges"]
        assert g["memory/live_bytes"] > 0
        assert g["memory/peak_live_bytes"] >= g["memory/live_bytes"]

    def test_steplogger_embeds_census_per_step(self, mon, mem, tmp_path):
        path = str(tmp_path / "run.jsonl")
        _, step = _linear_step()
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        y = pt.to_tensor(np.zeros((2, 4), np.float32))
        with monitor.StepLogger(path) as log:
            for _ in range(3):
                loss = step(x, y)
                log.log_step(loss=float(loss.numpy()), num_samples=2)
        lines = [json.loads(ln) for ln in open(path)]
        steps = [ln for ln in lines if "step" in ln]
        assert len(steps) == 3
        for s in steps:
            assert s["memory"]["live_bytes"] > 0
            assert s["memory"]["peak_live_bytes"] >= s["memory"]["live_bytes"]
        end = lines[-1]
        assert end["event"] == "run_end"
        assert end["memory"]["peak_live_bytes"] > 0
        assert end["memory"]["censuses"] >= 3

    def test_steplogger_no_memory_when_off(self, mon, tmp_path):
        assert memobs._ledger is None
        path = str(tmp_path / "off.jsonl")
        with monitor.StepLogger(path) as log:
            log.log_step(loss=1.0)
        lines = [json.loads(ln) for ln in open(path)]
        assert all("memory" not in ln for ln in lines)

    def test_executable_record_structure(self, mem):
        _, step = _linear_step()
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        y = pt.to_tensor(np.zeros((2, 4), np.float32))
        rec = memobs.executable_record(step, x, y, name="linear")
        assert rec["name"] == "linear"
        for k in ("args_bytes", "output_bytes", "temp_bytes",
                  "generated_code_bytes", "peak_bytes"):
            assert rec[k] >= 0, k
        assert rec["peak_bytes"] == rec["args_bytes"] + rec["temp_bytes"]
        assert rec["peak_bytes"] > 0
        # landed in the ledger, and the run_end snapshot carries it
        snap = mem.snapshot()
        assert any(e.get("name") == "linear" for e in snap["executables"])

    def test_executable_record_mesh_annotation(self, mesh):
        net = pt.nn.Linear(8, 8)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        step = TrainStep(net, opt,
                         lambda m, x, y: ((m(x) - y) ** 2).mean())
        x = pt.to_tensor(np.ones((4, 8), np.float32))
        y = pt.to_tensor(np.zeros((4, 8), np.float32))
        rec = memobs.executable_record(step, x, y, name="mesh_step")
        assert rec["per_shard"] is True
        assert rec["mesh"] == {"dp": 2, "mp": 4}
        assert rec["peak_bytes"] > 0

    def test_fit_phase_bracket_census(self, mon, mem):
        net = pt.nn.Linear(4, 2)
        model = pt.Model(net)
        model.prepare(
            pt.optimizer.SGD(learning_rate=0.1,
                             parameters=net.parameters()),
            pt.nn.MSELoss())
        xs = np.ones((8, 4), np.float32)
        ys = np.zeros((8, 2), np.float32)
        ds = [(xs[i], ys[i]) for i in range(8)]
        before = mem.census_count
        model.fit(ds, batch_size=4, epochs=1, verbose=0)
        # at least the epoch-end phase bracket census fired (plus the
        # MonitorCallback's per-step ones)
        assert mem.census_count > before

    def test_per_shard_bytes_helper(self, mesh):
        from paddle_tpu.distributed.shard import per_shard_bytes, \
            shard_tensor

        t = pt.to_tensor(np.ones((8, 8), np.float32))
        assert per_shard_bytes(t) == 8 * 8 * 4  # unsharded: full cost
        s = shard_tensor(t, spec=("dp", "mp"))
        assert per_shard_bytes(s) == 8 * 8 * 4 // 8  # 2x4 mesh split

    def test_per_device_census_counts_shards_not_globals(self, mesh):
        from paddle_tpu.distributed.shard import shard_tensor

        t = pt.to_tensor(np.ones((64, 64), np.float32))
        s = shard_tensor(t, spec=("dp", "mp"))
        c = memobs.live_census(per_device=True)
        # the sharded array bills one shard toward the per-device bound,
        # its full size toward the global total
        assert c["max_device_bytes"] < c["live_bytes"]
        assert c["max_device_bytes"] > 0
        del t, s


# -- OOM preflight planner ---------------------------------------------------

class TestMemoryPlanner:
    @pytest.fixture(scope="class")
    def planner(self):
        return _load_tool("memory_planner")

    def _args(self, planner, **over):
        argv = ["--hbm-gb", str(over.pop("hbm_gb", 16.0)),
                "--configs", over.pop("configs", "dp8,dp4xmp2,dp2xmp4"),
                "--hidden", "64", "--layers", "2", "--heads", "4",
                "--seq", "32", "--vocab", "512", "--batches", "8"]
        return planner.build_argparser().parse_args(argv)

    def test_mesh_token_parsing(self, planner):
        assert planner.parse_mesh("dp4xmp2") == {"dp": 4, "mp": 2,
                                                 "pp": 1}
        assert planner.parse_mesh("dp8") == {"dp": 8, "mp": 1, "pp": 1}
        assert planner.parse_mesh("dp4xpp2") == {"dp": 4, "mp": 1,
                                                 "pp": 2}
        with pytest.raises(ValueError, match="bad mesh token"):
            planner.parse_mesh("xx2")

    def test_bad_factorization_refused(self, planner):
        args = self._args(planner, configs="dp4xmp2")
        with pytest.raises(ValueError, match="factorize"):
            planner.candidates(args, 16)

    def test_plan_verdicts_on_virtual_mesh(self, planner):
        args = self._args(planner)
        rows = planner.plan(args, 8)
        assert len(rows) >= 3
        assert all("error" not in r for r in rows), rows
        assert all(r["fits"] for r in rows)  # tiny model, 16 GiB budget
        # sharding works: more mp -> smaller per-device args
        by_mp = {r["mp"]: r["args_bytes"] for r in rows}
        assert by_mp[4] < by_mp[1]
        # a budget nothing meets flips every verdict, same cost data
        args_tiny = self._args(planner, hbm_gb=1e-6)
        rows_tiny = planner.plan(args_tiny, 8)
        assert not any(r.get("fits") for r in rows_tiny)
        out = planner.render(rows_tiny, 1e-6, 8)
        assert "DOES NOT FIT" in out and "0/3" in out

    def test_cli_smoke(self):
        """The acceptance-criterion invocation: the CLI on the virtual
        8-device mesh prints a fits table for ≥ 4 candidates (incl. the
        pp>1 pipeline column — ISSUE 15), from lowering-only data,
        rc 0."""
        proc = subprocess.run(
            [sys.executable, "tools/memory_planner.py",
             "--hbm-gb", "16", "--smoke"],
            cwd=_ROOT, capture_output=True, text=True, timeout=900)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = proc.stdout
        assert out.count("FITS") >= 4
        assert "·pp2" in out  # the pipeline candidate's row
        assert "memory planner: budget 16.00 GiB/device" in out
        assert "4/4 candidate config(s) fit" in out


# -- numerics sentinel -------------------------------------------------------

class _ScaledSum(pt.nn.Layer):
    """Scalar-weight model whose FORWARD stays finite on a huge batch
    (w * x is scaled down before the sum) while the GRADIENT wrt w is
    sum(x) — which overflows to inf for x = 4 × 3e38. The injected
    non-finite grad of the acceptance criterion."""

    def __init__(self):
        super().__init__()
        self.w = self.create_parameter(
            [1], default_initializer=pt.nn.initializer.Constant(1e-3))

    def forward(self, x):
        return (self.w * x).sum()


_POISON = np.full((4,), 3e38, np.float32)  # sum overflows fp32
_CLEAN = np.ones((4,), np.float32)


def _scaled_step(nan_check=True):
    net = _ScaledSum()
    opt = pt.optimizer.SGD(learning_rate=1e-4,
                           parameters=net.parameters())
    return net, TrainStep(net, opt, lambda m, x: m(x),
                          nan_check=nan_check)


class TestNumericsSentinel:
    def test_injected_inf_grad_names_step_and_leaf(self):
        _, step = _scaled_step()
        for _ in range(2):
            step(pt.to_tensor(_CLEAN))
        with pytest.raises(numerics.NonFiniteError) as ei:
            step(pt.to_tensor(_POISON))
        e = ei.value
        assert e.step == 3
        assert e.leaf == "grad/w"
        assert e.kind == "grad"
        assert "step 3" in str(e) and "grad/w" in str(e)

    def test_forward_inf_names_loss(self):
        net = pt.nn.Linear(4, 1)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        step = TrainStep(net, opt, lambda m, x: m(x).sum(),
                         nan_check=True)
        bad = pt.to_tensor(np.full((2, 4), np.inf, np.float32))
        with pytest.raises(numerics.NonFiniteError) as ei:
            step(bad)
        assert ei.value.kind == "loss"
        assert ei.value.step == 1

    def test_params_not_updated_by_failing_step(self):
        net, step = _scaled_step()
        step(pt.to_tensor(_CLEAN))
        w_before = float(np.asarray(net.w.numpy())[0])
        with pytest.raises(numerics.NonFiniteError):
            step(pt.to_tensor(_POISON))
        assert float(np.asarray(net.w.numpy())[0]) == w_before

    def test_healthy_run_one_extra_fetch_per_step(self, mon):
        """The ≤ 1-extra-host-scalar-fetch-per-step contract, proven via
        the hapi/host_syncs guard counter on a direct-step run (no fit
        windows: every sync here is the sentinel's)."""
        _, step = _scaled_step()
        x = pt.to_tensor(_CLEAN)
        before = mon.snapshot()["counters"].get("hapi/host_syncs", 0)
        for _ in range(5):
            step(x)
        c = mon.snapshot()["counters"]
        assert c["numerics/checks"] == 5
        assert c.get("numerics/failures", 0) == 0
        assert c.get("hapi/host_syncs", 0) - before == 5  # exactly 1/step
        # and one retrace total: the nan-check signature compiled once
        assert c["jit/retraces"] == 1

    def test_failure_counted_and_span_recorded(self, mon):
        _, step = _scaled_step()
        with pytest.raises(numerics.NonFiniteError):
            step(pt.to_tensor(_POISON))
        c = mon.snapshot()["counters"]
        assert c["numerics/failures"] == 1
        names = [s[0] for s in monitor.spans().snapshot()]
        assert "numerics/first_bad_step" in names

    def test_global_enable_wires_slot(self):
        from paddle_tpu.jit import train_step as ts_mod

        assert ts_mod._nancheck is None
        numerics.enable()
        try:
            assert ts_mod._nancheck is numerics
            assert numerics.enabled()
            # a step built with no instance flag follows the global
            _, step = _linear_step()
            assert step._nan_active() is True
        finally:
            numerics.disable()
        assert ts_mod._nancheck is None
        _, step = _linear_step()
        assert step._nan_active() is False

    def test_instance_false_overrides_global(self):
        numerics.enable()
        try:
            _, step = _linear_step(nan_check=False)
            assert step._nan_active() is False
        finally:
            numerics.disable()

    def test_donation_suspended_while_armed(self):
        """Replay needs the pre-step params: donate=True + nan_check
        must not invalidate them (the failing-step test above already
        read them; here the healthy path keeps stepping)."""
        net, step = _linear_step(donate=True, nan_check=True)
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        y = pt.to_tensor(np.zeros((2, 4), np.float32))
        l1 = float(step(x, y).numpy())
        l2 = float(step(x, y).numpy())
        assert np.isfinite([l1, l2]).all() and l2 < l1

    def test_fit_nan_check_catches_and_fires_on_train_error(self, mon,
                                                           tmp_path):
        from paddle_tpu.hapi.callbacks import Callback, MonitorCallback

        errors = []

        class Recorder(Callback):
            def on_train_error(self, error=None):
                errors.append(error)

        net = _ScaledSum()
        model = pt.Model(net)
        model.prepare(
            pt.optimizer.SGD(learning_rate=1e-4,
                             parameters=net.parameters()),
            loss=lambda outs, label: outs)
        data = [(_CLEAN, np.zeros(1, np.float32)) for _ in range(6)]
        data[3] = (_POISON, np.zeros(1, np.float32))  # poison step 4
        path = str(tmp_path / "nan_fit.jsonl")
        with pytest.raises(numerics.NonFiniteError) as ei:
            model.fit(data, batch_size=1, epochs=1, shuffle=False,
                      verbose=0, nan_check=True,
                      callbacks=[Recorder(), MonitorCallback(path)])
        assert ei.value.step == 4
        assert ei.value.leaf == "grad/w"
        # Callback.on_train_error fired with the sentinel's message
        assert len(errors) == 1 and "grad/w" in errors[0]
        # the StepLogger run_end line records the error (crashed-run
        # JSONL is distinguishable from a truncated one)
        lines = [json.loads(ln) for ln in open(path)]
        end = lines[-1]
        assert end["event"] == "run_end"
        assert "NonFiniteError" in end["error"]
        # fit's nan_check=True is per-fit: the TrainStep's own setting
        # is restored even on the error path
        assert model._train_step._nan_check is None

    def test_fit_nan_check_false_overrides_env(self, mon):
        numerics.enable()
        try:
            net = pt.nn.Linear(4, 2)
            model = pt.Model(net)
            model.prepare(
                pt.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters()),
                pt.nn.MSELoss())
            xs = np.ones((4, 4), np.float32)
            ys = np.zeros((4, 2), np.float32)
            model.fit([(xs[i], ys[i]) for i in range(4)], batch_size=2,
                      epochs=1, verbose=0, nan_check=False)
            assert mon.snapshot()["counters"].get("numerics/checks", 0) == 0
        finally:
            numerics.disable()


# -- zero-overhead audit (extended: every slot-carrying module) --------------

@pytest.mark.parametrize("modname", monitor.INSTRUMENTED_MODULES)
def test_zero_overhead_audit_import_time_inert(modname):
    """Single parametrized audit over monitor.INSTRUMENTED_MODULES: with
    PT_MONITOR / PT_NANCHECK / PT_MONITOR_MEM unset (tier-1 default),
    every registered slot on every instrumented module is None — no
    monitor/sentinel callable is reachable from any hot path. New
    instrumentation sites must join INSTRUMENTED_MODULES, so this audit
    covers them without edits here."""
    from paddle_tpu.monitor import live as live_telemetry

    assert not monitor.enabled()
    assert not numerics.enabled()
    assert not live_telemetry.enabled()
    assert memobs._ledger is None
    mod = importlib.import_module(modname)
    assert mod._monitor is None, f"{modname}._monitor"
    if hasattr(mod, "_spans"):
        assert mod._spans is None, f"{modname}._spans"
    if hasattr(mod, "_nancheck"):
        assert mod._nancheck is None, f"{modname}._nancheck"
    if hasattr(mod, "_live"):
        assert mod._live is None, f"{modname}._live"
    if hasattr(mod, "_goodput"):
        # the goodput slot is ledger-scoped, not PT_MONITOR-scoped: it
        # must be None whenever no fit() ledger is active (ISSUE 20)
        assert mod._goodput is None, f"{modname}._goodput"


def test_audit_list_covers_all_registered_sites():
    """Every module that actually registered a monitor slot is in the
    audit list — a new `_register` call can't silently dodge the audit."""
    registered = {m.__name__ for m in monitor._SITES}
    assert registered <= set(monitor.INSTRUMENTED_MODULES), (
        registered - set(monitor.INSTRUMENTED_MODULES))
    nan_sites = {m.__name__ for m in numerics._SITES}
    assert nan_sites <= set(monitor.INSTRUMENTED_MODULES), nan_sites


def test_program_audit_in_audit_list_and_import_inert():
    """The compiled-program auditor (ISSUE 12) is a slot-carrying module
    like the rest: it must be in INSTRUMENTED_MODULES (so the
    parametrized audit above covers its `_monitor` slot) AND leave the
    exec-cache `_audit` hook slot None while PT_PROGRAM_AUDIT is unset —
    arming telemetry must never arm the auditor."""
    assert "paddle_tpu.analysis.program_audit" \
        in monitor.INSTRUMENTED_MODULES
    assert os.environ.get("PT_PROGRAM_AUDIT", "0") in ("", "0")
    from paddle_tpu.analysis import program_audit
    from paddle_tpu.jit import exec_cache

    assert not program_audit.enabled()
    assert exec_cache._audit is None
    assert program_audit._monitor is None
    # PT_MONITOR wires _monitor but must NOT arm the audit slot
    monitor.enable()
    try:
        assert program_audit._monitor is monitor
        assert exec_cache._audit is None
    finally:
        monitor.disable()
    assert program_audit._monitor is None
