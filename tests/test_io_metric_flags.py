"""Tests: paddle.save/load, flags registry, metric, io.DataLoader.

Mirrors reference tests `test/legacy_test/test_paddle_save_load.py`,
`test_dataloader_*`, `python/paddle/tests/test_metrics.py`.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import io as pio
from paddle_tpu import metric as pmetric
from paddle_tpu import nn


class TestSaveLoad:
    def test_roundtrip_state_dict(self, tmp_path):
        layer = nn.Linear(4, 3)
        path = tmp_path / "model.pdparams"
        paddle.save(layer.state_dict(), path)
        loaded = paddle.load(path)
        for k, v in layer.state_dict().items():
            np.testing.assert_allclose(loaded[k].numpy(), v.numpy())
            assert loaded[k].is_parameter == v.is_parameter

    def test_nested_python_objects(self, tmp_path):
        obj = {"step": 7, "lr": 0.1, "t": paddle.to_tensor([1.0, 2.0]),
               "nested": [paddle.to_tensor(3), {"x": "y"}]}
        p = tmp_path / "ckpt"
        paddle.save(obj, p)
        back = paddle.load(p)
        assert back["step"] == 7
        np.testing.assert_allclose(back["t"].numpy(), [1.0, 2.0])
        assert back["nested"][1]["x"] == "y"

    def test_return_numpy(self, tmp_path):
        p = tmp_path / "t"
        paddle.save({"w": paddle.to_tensor([1.0])}, p)
        back = paddle.load(p, return_numpy=True)
        assert isinstance(back["w"], np.ndarray)

    def test_set_state_dict_after_load(self, tmp_path):
        l1 = nn.Linear(5, 5)
        l2 = nn.Linear(5, 5)
        p = tmp_path / "m"
        paddle.save(l1.state_dict(), p)
        missing, unexpected = l2.set_state_dict(paddle.load(p))
        assert not missing and not unexpected
        x = paddle.randn([2, 5])
        np.testing.assert_allclose(l1(x).numpy(), l2(x).numpy(), rtol=1e-6)


class TestFlags:
    def test_get_set(self):
        flags = paddle.get_flags()
        assert "check_nan_inf" in flags
        paddle.set_flags({"FLAGS_check_nan_inf_level": 1})
        assert paddle.get_flags("FLAGS_check_nan_inf_level")[
            "FLAGS_check_nan_inf_level"] == 1
        paddle.set_flags({"FLAGS_check_nan_inf_level": 0})

    def test_unknown_flag_raises(self):
        with pytest.raises(ValueError):
            paddle.set_flags({"FLAGS_not_a_flag": 1})

    def test_nan_check_hook(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor([1.0, 0.0])
            with pytest.raises(FloatingPointError):
                _ = x / paddle.to_tensor([1.0, 0.0])
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})
        # after disabling, no raise
        _ = paddle.to_tensor([1.0]) / paddle.to_tensor([0.0])


class TestMetrics:
    def test_accuracy_topk(self):
        m = pmetric.Accuracy(topk=(1, 2))
        pred = paddle.to_tensor(
            [[0.1, 0.7, 0.2], [0.5, 0.3, 0.2], [0.1, 0.2, 0.7]])
        label = paddle.to_tensor([1, 1, 2])
        correct = m.compute(pred, label)
        m.update(correct)
        acc1, acc2 = m.accumulate()
        assert abs(acc1 - 2 / 3) < 1e-6
        assert abs(acc2 - 1.0) < 1e-6

    def test_precision_recall(self):
        p = pmetric.Precision()
        r = pmetric.Recall()
        preds = [0.9, 0.8, 0.1, 0.4]
        labels = [1, 0, 1, 0]
        p.update(np.array(preds), np.array(labels))
        r.update(np.array(preds), np.array(labels))
        assert abs(p.accumulate() - 0.5) < 1e-6   # tp=1 fp=1
        assert abs(r.accumulate() - 0.5) < 1e-6   # tp=1 fn=1

    def test_auc_perfect(self):
        m = pmetric.Auc()
        m.update(np.array([[0.2, 0.8], [0.9, 0.1]]), np.array([1, 0]))
        assert m.accumulate() == 1.0

    def test_functional_accuracy(self):
        pred = paddle.to_tensor([[0.1, 0.9], [0.8, 0.2]])
        label = paddle.to_tensor([1, 0])
        acc = pmetric.accuracy(pred, label)
        assert abs(float(acc.numpy()) - 1.0) < 1e-6


class _SquareDataset(pio.Dataset):
    def __init__(self, n=20):
        self.n = n

    def __getitem__(self, i):
        return np.float32([i]), np.float32([i * i])

    def __len__(self):
        return self.n


class TestDataLoader:
    def test_basic_batching(self):
        ds = _SquareDataset(10)
        dl = pio.DataLoader(ds, batch_size=4, drop_last=False)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == [4, 1]
        np.testing.assert_allclose(y.numpy().flatten(), [0, 1, 4, 9])

    def test_shuffle_covers_all(self):
        ds = _SquareDataset(16)
        dl = pio.DataLoader(ds, batch_size=4, shuffle=True)
        seen = sorted(
            int(v) for x, _ in dl for v in x.numpy().flatten())
        assert seen == list(range(16))

    def test_workers_prefetch_ordered(self):
        ds = _SquareDataset(32)
        dl = pio.DataLoader(ds, batch_size=4, num_workers=2)
        flat = [int(v) for x, _ in dl for v in x.numpy().flatten()]
        assert flat == list(range(32))

    def test_worker_exception_propagates(self):
        class Bad(pio.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom")
                return np.float32([i])

        dl = pio.DataLoader(Bad(), batch_size=2, num_workers=2)
        with pytest.raises(ValueError):
            list(dl)

    def test_tensor_dataset_and_random_split(self):
        xs = paddle.randn([10, 3])
        ys = paddle.randn([10])
        ds = pio.TensorDataset([xs, ys])
        a, b = pio.random_split(ds, [7, 3])
        assert len(a) == 7 and len(b) == 3

    def test_iterable_dataset(self):
        class Stream(pio.IterableDataset):
            def __iter__(self):
                for i in range(7):
                    yield np.float32([i])

        dl = pio.DataLoader(Stream(), batch_size=3, drop_last=True)
        batches = list(dl)
        assert len(batches) == 2

    def test_distributed_batch_sampler_shards(self):
        ds = _SquareDataset(10)
        s0 = pio.DistributedBatchSampler(ds, batch_size=2, num_replicas=2,
                                         rank=0)
        s1 = pio.DistributedBatchSampler(ds, batch_size=2, num_replicas=2,
                                         rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert len(i0) == len(i1) == 5
        assert not (set(i0) & set(i1)) or len(set(i0 + i1)) == 10

    def test_concat_and_subset(self):
        d = pio.ConcatDataset([_SquareDataset(3), _SquareDataset(2)])
        assert len(d) == 5
        np.testing.assert_allclose(d[3][0], [0.0])
        sub = pio.Subset(_SquareDataset(5), [4, 2])
        np.testing.assert_allclose(sub[0][1], [16.0])


class TestMemoryStats:
    # VERDICT round-1 missing item 6: HBM observability (ref memory/stats.cc,
    # paddle.device.cuda.max_memory_allocated)
    def test_memory_api_shape(self):
        import paddle_tpu as pt
        from paddle_tpu.framework import device as dev

        a = pt.to_tensor(np.zeros((256, 256), np.float32))
        allocated = dev.memory_allocated()
        peak = dev.max_memory_allocated()
        assert isinstance(allocated, int) and isinstance(peak, int)
        assert peak >= allocated >= 0
        props = dev.get_device_properties()
        assert "total_memory" in props and "platform" in props
        dev.reset_max_memory_allocated()
        assert dev.max_memory_allocated() >= 0
        dev.empty_cache()
        del a

    def test_memory_tracks_allocation(self):
        import paddle_tpu as pt
        from paddle_tpu.framework import device as dev

        if not dev.memory_stats():
            import pytest

            pytest.skip("backend exposes no allocator stats")
        before = dev.memory_allocated()
        big = pt.to_tensor(np.ones((512, 512), np.float32))
        big.numpy()
        after = dev.memory_allocated()
        assert after >= before
