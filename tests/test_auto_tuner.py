"""Auto-tuner tests (reference `test/auto_tuner/` at the API surface:
candidate generation, pruning rules, search loop, history)."""
import json

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.auto_tuner import (
    AutoTuner, default_prunes, estimate_memory_bytes, generate_candidates,
)

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles

MODEL = {
    "hidden_size": 64, "num_hidden_layers": 4, "num_attention_heads": 4,
    "vocab_size": 128, "global_batch_size": 16, "seq_length": 16,
}


class TestCandidates:
    def test_world_size_pruning(self):
        t = AutoTuner(8, {"global_batch_size": 16}, MODEL, run_fn=lambda c: 0)
        cands = t.candidates()
        assert cands, "no candidates survived"
        for c in cands:
            assert (c["dp_degree"] * c["mp_degree"] * c["pp_degree"]
                    * c["sharding_degree"]) == 8
        reasons = [p["reason"] for p in t.pruned]
        assert any("world_size" in r for r in reasons)

    def test_divisibility_rules(self):
        prunes = default_prunes(8, MODEL)
        bad_mp = {"dp_degree": 1, "mp_degree": 8, "pp_degree": 1,
                  "sharding_degree": 1, "micro_batch_size": 1,
                  "use_recompute": False}
        msgs = [p(bad_mp) for p in prunes]
        assert any(m and "heads" in m for m in msgs)
        bad_pp = dict(bad_mp, mp_degree=1, pp_degree=8)
        # world=8 ok; layers=4 not divisible by pp=8
        msgs = [p(bad_pp) for p in prunes]
        assert any(m and "layers" in m for m in msgs)

    def test_memory_prune(self):
        prunes = default_prunes(8, MODEL, hbm_bytes=1)  # absurdly tiny
        c = {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
             "sharding_degree": 1, "micro_batch_size": 1,
             "use_recompute": False}
        assert any(p(c) and "HBM" in p(c) for p in prunes)
        assert estimate_memory_bytes(c, MODEL) > 0

    def test_explicit_axes(self):
        cands = generate_candidates(8, {"mp_degree": [2], "pp_degree": [2],
                                        "dp_degree": [2],
                                        "sharding_degree": [1],
                                        "micro_batch_size": [2],
                                        "use_recompute": [False]})
        assert cands == [{"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                          "sharding_degree": 1, "micro_batch_size": 2,
                          "use_recompute": False}]


class TestSearch:
    def test_finds_best_on_synthetic_surface(self, tmp_path):
        # synthetic cost model: mp=2 pp=1 is the sweet spot
        def run_fn(c):
            score = 100.0
            score -= abs(c["mp_degree"] - 2) * 10
            score -= (c["pp_degree"] - 1) * 5
            score += c["micro_batch_size"]
            if c["use_recompute"]:
                score -= 1
            return score

        hist = tmp_path / "tuner.json"
        t = AutoTuner(8, {"global_batch_size": 16}, MODEL, run_fn=run_fn,
                      history_path=str(hist))
        best, metric = t.tune()
        assert best["mp_degree"] == 2 and best["pp_degree"] == 1
        assert metric == max(r["metric"] for r in t.history if r["ok"])
        data = json.loads(hist.read_text())
        assert data["history"] and data["pruned"]

    def test_failed_trials_skipped(self):
        calls = []

        def run_fn(c):
            calls.append(c)
            if c["mp_degree"] > 1:
                raise RuntimeError("simulated OOM")
            return float(c["dp_degree"])

        t = AutoTuner(8, {"global_batch_size": 16}, MODEL, run_fn=run_fn,
                      max_trials=20)
        best, metric = t.tune()
        assert best["mp_degree"] == 1
        assert any(not r["ok"] for r in t.history)

    def test_real_trainstep_trials(self):
        # the TPU-shaped measurement: each candidate re-jits one train step
        # over a re-factorized mesh (no process relaunch)
        from paddle_tpu.distributed import env as env_mod
        from paddle_tpu.jit.train_step import TrainStep
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        def run_fn(c):
            env_mod.reset_env()
            env_mod.init_mesh(dp=c["dp_degree"], mp=c["mp_degree"],
                              pp=c["pp_degree"])
            cfg = LlamaConfig.tiny(num_hidden_layers=2,
                                   use_parallel_cross_entropy=False)
            model = LlamaForCausalLM(cfg)
            opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
            step = TrainStep(model, opt, lambda m, i, l: m(i, l))
            ids = pt.to_tensor(np.random.randint(0, cfg.vocab_size, (8, 16)))
            lbl = pt.to_tensor(np.random.randint(0, cfg.vocab_size, (8, 16)))
            loss = float(step(ids, lbl).numpy())
            assert np.isfinite(loss)
            return 1.0  # timing is meaningless on a virtual mesh

        t = AutoTuner(
            8,
            {"mp_degree": [1, 2], "pp_degree": [1], "sharding_degree": [1],
             "dp_degree": "auto", "micro_batch_size": [1],
             "use_recompute": [False], "global_batch_size": 8},
            {"hidden_size": 64, "num_attention_heads": 4,
             "num_hidden_layers": 2, "global_batch_size": 8},
            run_fn=run_fn)
        try:
            best, metric = t.tune()
        finally:
            env_mod.reset_env()
        ran = [r for r in t.history if r["ok"]]
        assert len(ran) == 2 and metric == 1.0
