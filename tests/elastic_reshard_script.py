"""Elastic kill/resume fixture WITH A MESH CHANGE: life 0 trains with
params sharded over a 2-device "mp" axis and crashes mid-run; the
launcher relaunch (PADDLE_RESTART_COUNT) rebuilds the model on a
DIFFERENT mesh layout (RESHARD_MESH_R1, default 4 devices) and resumes
from the resilience checkpoint — reshard-on-load by construction
(distributed/checkpoint.py assembles each destination region from the
overlapping saved shard files).

Used by tests/test_elastic.py::test_kill_relaunch_resume_reshard: the
stitched loss trajectory must stay on the SAME curve as an uninterrupted
single-mesh run (loss-equivalence under resharding; bit-exactness is the
same-topology test's job).
"""
import json
import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
from paddle_tpu import resilience  # noqa: E402
from paddle_tpu.resilience import resume as rez  # noqa: E402

WORKDIR = sys.argv[1]
CRASH_AT = int(os.environ.get("ELASTIC_CRASH_AT", "-1"))
TOTAL_STEPS = 6
restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
mesh_n = int(os.environ.get(
    "RESHARD_MESH_R1" if restart else "RESHARD_MESH", "2"))

paddle.seed(0)
model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
mesh = Mesh(np.array(jax.devices()[:mesh_n]), ("mp",))
lin1, lin2 = model[0], model[2]
# megatron-ish placement: column-parallel then row-parallel
lin1.weight._data = jax.device_put(lin1.weight._data,
                                   NamedSharding(mesh, P(None, "mp")))
lin1.bias._data = jax.device_put(lin1.bias._data,
                                 NamedSharding(mesh, P("mp")))
lin2.weight._data = jax.device_put(lin2.weight._data,
                                   NamedSharding(mesh, P("mp", None)))
lin2.bias._data = jax.device_put(lin2.bias._data,
                                 NamedSharding(mesh, P()))
opt = paddle.optimizer.AdamW(learning_rate=5e-2,
                             parameters=model.parameters())

rng = np.random.default_rng(0)
xs = rng.standard_normal((TOTAL_STEPS, 16, 8)).astype("float32")
w_true = rng.standard_normal((8, 1)).astype("float32")
repl = NamedSharding(mesh, P())

ckpt_dir = os.path.join(WORKDIR, "ckpt")
start_step = 0
scal = rez.restore_latest(model, opt, ckpt_dir,
                          crash_resume=restart > 0)
if scal is not None:
    start_step = int(scal.get("step", 0))

# sync saves: this fixture proves RESHARD equivalence; torn-checkpoint
# fallback has its own test (test_resilience.py)
mgr = resilience.CheckpointManager(ckpt_dir, interval=1, keep=3,
                                   async_save=False)
losses = []
for step in range(start_step, TOTAL_STEPS):
    x = paddle.Tensor(jax.device_put(xs[step], repl))
    y = paddle.Tensor(jax.device_put(xs[step] @ w_true, repl))
    loss = F.mse_loss(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    losses.append(float(np.asarray(loss.numpy()).reshape(-1)[0]))
    with open(os.path.join(WORKDIR, f"losses_r{restart}.json"), "w") as f:
        json.dump({"start": start_step, "losses": losses,
                   "mesh": mesh_n}, f)
    mgr.save(step + 1, rez.capture(model, opt, step=step + 1))
    if restart == 0 and step + 1 == CRASH_AT:
        os._exit(17)  # simulated preemption mid-training
