"""Ulysses all-to-all sequence-parallel attention — the second
exceed-reference long-context feature (SURVEY §2.6 lists Ulysses as
absent upstream). Numeric parity vs the dense composite and the ring."""
import jax
import numpy as np
import pytest

import paddle_tpu as pt
from conftest import attn_qkv
from paddle_tpu.nn.functional.attention import _sdpa_reference
from paddle_tpu.ops.ulysses_attention import make_ulysses_attention


@pytest.mark.parametrize("causal", [False, True])
def test_forward_parity(mesh_dp2_sep4, causal):
    q, k, v = attn_qkv(h=4)
    uly = make_ulysses_attention(mesh_dp2_sep4, axis="sep", causal=causal)
    out = uly(q, k, v)
    ref = _sdpa_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grad_parity(mesh_dp2_sep4, causal):
    q, k, v = attn_qkv(h=4, seed=1)
    w = np.random.RandomState(2).randn(*np.shape(q)).astype(np.float32)
    uly = make_ulysses_attention(mesh_dp2_sep4, axis="sep", causal=causal)
    g1 = jax.grad(lambda *a: (uly(*a) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (_sdpa_reference(*a, causal=causal)
                              * w).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        scale = np.abs(np.asarray(b)).max() + 1e-9
        np.testing.assert_allclose(np.asarray(a) / scale,
                                   np.asarray(b) / scale, atol=1e-4)


def test_flash_local_path_matches_composite(mesh_dp2_sep4):
    # h=4 over sep=4 -> 1 local head attending the full 64-seq: the flash
    # kernel's shape contract holds (s=64>=16, d=16%8==0)
    q, k, v = attn_qkv(h=4, seed=3)
    flash = make_ulysses_attention(mesh_dp2_sep4, axis="sep", causal=True,
                                   use_flash=True)
    plain = make_ulysses_attention(mesh_dp2_sep4, axis="sep", causal=True,
                                   use_flash=False)
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(plain(q, k, v)), atol=2e-5)


def test_head_divisibility_rejected(mesh_dp2_sep4):
    rng = np.random.RandomState(0)
    bad = rng.randn(2, 64, 3, 16).astype(np.float32)  # 3 heads over 4
    uly = make_ulysses_attention(mesh_dp2_sep4, axis="sep")
    with pytest.raises(ValueError, match="heads"):
        uly(bad, bad, bad)


def test_functional_surface(mesh_dp2_sep4):
    """F.ulysses_attention through the public Tensor path under a fleet
    mesh with a sep axis."""
    from paddle_tpu.distributed import env as env_mod

    env_mod.init_mesh(dp=2, sep=4)
    try:
        q, k, v = (pt.to_tensor(x) for x in attn_qkv(h=4, seed=4))
        out = pt.nn.functional.ulysses_attention(q, k, v, axis="sep")
        ref = _sdpa_reference(q.numpy(), k.numpy(), v.numpy(),
                              causal=True)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(ref), atol=2e-5)
    finally:
        env_mod.reset_env()


def test_llama_with_ulysses_context_parallel():
    """LlamaConfig(context_parallel=True, context_parallel_mode='ulysses')
    trains a compiled step on a dp x sep mesh."""
    from paddle_tpu.distributed import env as env_mod
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    env_mod.init_mesh(dp=2, sep=4)
    try:
        pt.seed(0)
        cfg = LlamaConfig.tiny(context_parallel=True,
                               context_parallel_mode="ulysses")
        model = LlamaForCausalLM(cfg)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        ids = pt.to_tensor(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 32)))
        step = TrainStep(model, opt, lambda m, i, l: m(i, l))
        losses = [float(np.asarray(step(ids, ids).numpy()))
                  for _ in range(3)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
    finally:
        env_mod.reset_env()


def test_bad_context_parallel_mode_rejected():
    from paddle_tpu.models import LlamaConfig

    with pytest.raises(ValueError, match="context_parallel_mode"):
        LlamaConfig.tiny(context_parallel=True,
                         context_parallel_mode="alltoall")
