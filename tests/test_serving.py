"""Continuous-batching serving runtime (`paddle_tpu/serving`).

Three layers, mirroring the subsystem's own split:

- **BlockPool safety** — the double-free/alias bug class a paged KV
  cache dies of is unrepresentable: every misuse raises, and the
  free+used==capacity identity holds through churn.
- **Scheduler policy properties** — pure-host simulation of the
  engine's scheduling round over seeded traces: byte-identical replay,
  termination (no starvation: preemption victims are always the NEWEST
  runner, so the oldest request always progresses), preempted requests
  keep their tokens and their blocks return to the pool.
- **Tier-1 CPU end-to-end** — the acceptance proof: ≥8 requests with
  unequal prompt/output lengths through :class:`ServingEngine` are
  token-identical to per-request ``generate()`` calls, with the decode
  step compiled exactly ONCE (exec-cache counters show no per-request
  retraces), plus the serving bench's one-JSON-line contract.
"""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate
from paddle_tpu.serving import (
    FINISHED, RUNNING, WAITING, BlockPool, FCFSScheduler, Request,
    ServingConfig, ServingEngine, blocks_needed, prefix_keys,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_by_path(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- block pool ---------------------------------------------------------------

class TestBlockPool:
    def test_blocks_needed(self):
        assert blocks_needed(0, 4) == 0
        assert blocks_needed(1, 4) == 1
        assert blocks_needed(4, 4) == 1
        assert blocks_needed(5, 4) == 2

    def test_null_block_reserved(self):
        pool = BlockPool(4, 2)
        got = pool.alloc(3, "a")
        assert got is not None and 0 not in got
        assert pool.alloc(1, "b") is None  # capacity is num_blocks - 1
        with pytest.raises(ValueError):
            BlockPool(1, 2)  # no room for the null block
        with pytest.raises(ValueError):
            BlockPool(4, 0)

    def test_double_free_raises(self):
        pool = BlockPool(8, 2)
        blocks = pool.alloc(2, "req")
        pool.free(blocks, "req")
        with pytest.raises(ValueError, match="double-free|not allocated"):
            pool.free(blocks, "req")
        pool.check_invariant()

    def test_cross_owner_free_raises(self):
        pool = BlockPool(8, 2)
        a = pool.alloc(2, "a")
        pool.alloc(2, "b")
        with pytest.raises(ValueError, match="owned by"):
            pool.free(a, "b")
        # the failed free must not have leaked anything
        pool.check_invariant()
        assert pool.used_count == 4

    def test_never_allocated_free_raises(self):
        pool = BlockPool(8, 2)
        with pytest.raises(ValueError):
            pool.free([3], "ghost")

    def test_lifo_reuse_and_accounting(self):
        pool = BlockPool(8, 2)
        a = pool.alloc(3, "a")
        pool.free(a, "a")
        b = pool.alloc(3, "b")
        assert b == a[::-1]  # LIFO: just-freed blocks hand out first
        assert pool.free_count + pool.used_count == pool.capacity
        pool.check_invariant()


# -- block pool: ref-counted prefix sharing -----------------------------------

def _publish_ctx(pool, tokens, blocks, owner):
    """Index ``owner``'s full context blocks under their chain keys —
    the scheduler's publish_prefix in miniature."""
    for i, key in enumerate(prefix_keys(tokens, pool.block_size)):
        pool.publish(key, blocks[i], owner)


class TestBlockPoolSharing:
    def test_prefix_keys_chain(self):
        # keys name the WHOLE context through their block: equal heads
        # share, a changed early token changes every later key too
        k1 = prefix_keys([1, 2, 3, 4, 5, 6], 2)
        k2 = prefix_keys([1, 2, 3, 4, 9, 9], 2)
        k3 = prefix_keys([9, 2, 3, 4, 5, 6], 2)
        assert len(k1) == 3
        assert k1[:2] == k2[:2] and k1[2] != k2[2]
        assert all(a != b for a, b in zip(k1, k3))
        # limit_tokens caps the keyed span to full blocks below it
        assert prefix_keys([1, 2, 3, 4], 2, limit_tokens=3) == k1[:1]
        assert prefix_keys([1], 2) == []

    def test_publish_lookup_acquire_roundtrip(self):
        pool = BlockPool(8, 2)
        toks = [1, 2, 3, 4, 5]  # 2 full blocks + 1 partial
        a_blocks = pool.alloc(3, "a")
        _publish_ctx(pool, toks, a_blocks, "a")
        keys = prefix_keys(toks, 2)
        assert pool.lookup(keys) == a_blocks[:2]
        # a different continuation matches only the shared head
        assert pool.lookup(prefix_keys([1, 2, 9, 9], 2)) == a_blocks[:1]
        pool.acquire(a_blocks[:2], "b")
        assert pool.refcount(a_blocks[0]) == 2
        assert pool.shared_count == 2
        pool.check_invariant()
        # both holders release; indexed blocks park cold, partial frees
        pool.free(a_blocks, "a")
        pool.free(a_blocks[:2], "b")
        assert pool.used_count == 0
        assert pool.cold_count == 2
        assert pool.free_count + pool.used_count + pool.cold_count \
            == pool.capacity
        pool.check_invariant()

    def test_shared_double_free_and_no_reference_raise(self):
        pool = BlockPool(8, 2)
        blocks = pool.alloc(2, "a")
        _publish_ctx(pool, [1, 2, 3, 4], blocks, "a")
        pool.acquire(blocks, "b")
        # "c" holds no reference: the cross-owner raise survives sharing
        with pytest.raises(ValueError, match="owned by"):
            pool.free(blocks, "c")
        pool.free(blocks, "a")
        # a's reference is spent — freeing again is a double-free even
        # though b still holds the (live, shared) blocks
        with pytest.raises(ValueError, match="owned by"):
            pool.free(blocks, "a")
        pool.free(blocks, "b")
        with pytest.raises(ValueError, match="not allocated|owned by"):
            pool.free(blocks, "b")
        pool.check_invariant()

    def test_accounting_with_live_shared_blocks(self):
        pool = BlockPool(10, 2)
        shared = pool.alloc(3, "a")
        _publish_ctx(pool, [1, 2, 3, 4, 5, 6], shared, "a")
        pool.acquire(shared, "b")
        pool.acquire(shared, "c")
        private = pool.alloc(2, "d")
        # a shared block counts ONCE however many holders it has
        assert pool.used_count == 5
        assert pool.free_count == pool.capacity - 5
        assert pool.refcount(shared[0]) == 3
        pool.check_invariant()
        pool.free(shared, "b")
        assert pool.used_count == 5  # still referenced by a and c
        pool.free(shared, "a")
        pool.free(shared, "c")
        assert pool.used_count == 2 and pool.cold_count == 3
        pool.free(private, "d")
        assert pool.used_count == 0
        pool.check_invariant()

    def test_cold_lru_reclaim_order_and_index_eviction(self):
        pool = BlockPool(6, 2)  # capacity 5
        a = pool.alloc(2, "a")
        b = pool.alloc(2, "b")
        _publish_ctx(pool, [1, 2, 3, 4], a, "a")
        _publish_ctx(pool, [7, 8, 9, 10], b, "b")
        pool.free(a, "a")   # cold, oldest
        pool.free(b, "b")   # cold, newest
        assert pool.cold_count == 4 and pool.free_count == 1
        # free list (1 block) serves first; then cold reclaims in
        # release order — a's blocks go before b's
        got = pool.alloc(3, "c")
        assert got[1:] == a
        assert pool.cold_count == 2
        # a's index entries are gone, b's survive
        assert pool.lookup(prefix_keys([1, 2, 3, 4], 2)) == []
        assert pool.lookup(prefix_keys([7, 8, 9, 10], 2)) == b
        pool.check_invariant()

    def test_pressure_never_reclaims_referenced_blocks(self):
        pool = BlockPool(6, 2)  # capacity 5
        shared = pool.alloc(2, "a")
        _publish_ctx(pool, [1, 2, 3, 4], shared, "a")
        pool.acquire(shared, "b")
        pool.free(shared, "a")  # b still holds both — NOT cold
        assert pool.cold_count == 0
        held = pool.alloc(3, "c")
        assert held is not None
        # pool is now fully referenced: alloc must refuse, not steal
        assert pool.alloc(1, "d") is None
        assert pool.lookup(prefix_keys([1, 2, 3, 4], 2)) == shared
        assert pool.refcount(shared[0]) == 1
        pool.check_invariant()
        pool.free(held, "c")
        pool.free(shared, "b")

    def test_acquire_revives_cold_and_rejects_stale(self):
        pool = BlockPool(6, 2)
        a = pool.alloc(2, "a")
        _publish_ctx(pool, [1, 2, 3, 4], a, "a")
        pool.free(a, "a")
        hits = pool.lookup(prefix_keys([1, 2, 3, 4], 2))
        pool.acquire(hits, "b")  # revive off the cold LRU
        assert pool.cold_count == 0 and pool.refcount(hits[0]) == 1
        # double-acquire by the same owner is a table bug upstream
        with pytest.raises(ValueError, match="already held"):
            pool.acquire(hits, "b")
        pool.free(hits, "b")
        # reclaim everything (the blocks are re-issued to "hog");
        # acquiring the stale lookup result must raise, not alias
        pool.alloc(pool.capacity, "hog")
        with pytest.raises(ValueError, match="acquire must follow"):
            pool.acquire(hits, "c")
        pool.check_invariant()

    def test_publish_validations(self):
        pool = BlockPool(8, 2)
        a = pool.alloc(2, "a")
        b = pool.alloc(2, "b")
        keys = prefix_keys([1, 2, 3, 4], 2)
        with pytest.raises(ValueError, match="not held"):
            pool.publish(keys[0], a[0], "b")
        assert pool.publish(keys[0], a[0], "a")
        # first publisher wins: b's same-content copy stays private
        assert not pool.publish(keys[0], b[0], "b")
        assert pool.lookup(keys[:1]) == [a[0]]
        # re-publishing the indexed block is a no-op
        assert pool.publish(keys[0], a[0], "a")
        # one block, two different content keys = immutability broken
        with pytest.raises(ValueError, match="different key"):
            pool.publish(keys[1], a[0], "a")
        pool.check_invariant()


# -- scheduler policy (pure host — no jax) ------------------------------------

def _sim_emit(sched, req, tok):
    """Engine's _emit without the device: append, finish when done."""
    req.output.append(tok)
    if len(req.output) >= req.max_new_tokens:
        sched.finish(req)


def _sim_round(sched, preempt_victims=None):
    """One ServingEngine.step in pure host logic: admit + fake-prefill
    (first token emitted unless the request is a recompute re-admission),
    growth walk in FCFS order with preemption, one decode emit per
    surviving lane. Token values are just output positions — the replay
    comparison rides on the scheduler's event log, not token content."""
    def on_preempt(victim):
        # no-starvation witness: at preemption time every still-running
        # request is OLDER (smaller admit seq) than the victim
        assert all(r._admit_seq <= victim._admit_seq
                   for r in sched.running())
        if preempt_victims is not None:
            preempt_victims.append(victim.request_id)

    for req in sched.admit():
        req.pool_len = len(req.prefill_tokens)
        if not req.output:
            _sim_emit(sched, req, 0)
    for req in sched.running():
        if req.state == RUNNING:
            sched.ensure_capacity(req, on_preempt=on_preempt)
    act = sched.running()
    for req in act:
        req.pool_len += 1
        _sim_emit(sched, req, len(req.output))
    sched.pool.check_invariant()
    return bool(act)


def _make_sched(num_blocks=9, block_size=2, max_lanes=3, max_seq_len=16):
    return FCFSScheduler(BlockPool(num_blocks, block_size), max_lanes,
                         blocks_needed(max_seq_len, block_size),
                         max_seq_len)


def _trace_requests(n, seed, max_seq_len=16):
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.randint(1, max_seq_len // 2))
        new = int(rng.randint(1, max_seq_len - plen + 1))
        reqs.append(Request(rng.randint(0, 100, (plen,)),
                            max_new_tokens=new, request_id=i))
    return reqs


def _replay(seed, n=12, **geom):
    sched = _make_sched(**geom)
    victims = []
    reqs = _trace_requests(n, seed,
                           max_seq_len=geom.get("max_seq_len", 16))
    for r in reqs:
        sched.submit(r)
    rounds = 0
    while sched.has_work():
        _sim_round(sched, victims)
        rounds += 1
        assert rounds < 10_000, "scheduler livelocked"
    return sched, reqs, victims


class TestScheduler:
    def test_deterministic_replay(self):
        # same seeded trace, two fresh schedulers: the event logs (every
        # admit/preempt/finish decision) must match byte for byte
        s1, _, v1 = _replay(seed=7)
        s2, _, v2 = _replay(seed=7)
        assert s1.events == s2.events
        assert v1 == v2

    def test_all_finish_under_pressure(self):
        # pool far too small for the offered load: preemption churn must
        # still drain every request (no starvation, no livelock)
        # capacity 8 = one max-size request; 3 lanes contend for it
        sched, reqs, victims = _replay(seed=3, n=16, num_blocks=9)
        assert victims, "pressure config never preempted — test is vacuous"
        assert all(r.state == FINISHED for r in reqs)
        assert all(len(r.output) == r.max_new_tokens for r in reqs)
        # everything returned: pool empty, lanes empty
        assert sched.pool.used_count == 0
        assert sched.lanes_occupied == 0

    def test_preempted_request_keeps_tokens(self):
        # each request needs 5 blocks total; capacity 5 forces the two
        # lanes to fight over growth
        sched = _make_sched(num_blocks=6, block_size=2, max_lanes=2,
                            max_seq_len=10)
        a = sched.submit(Request([1, 2], max_new_tokens=8, request_id="a"))
        b = sched.submit(Request([3, 4], max_new_tokens=8, request_id="b"))
        victims = []
        while sched.has_work():
            _sim_round(sched, victims)
        assert "b" in victims and "a" not in victims  # newest loses
        assert b.preemptions >= 1
        assert len(b.output) == 8
        # recompute contract: prefill_tokens replays prompt + kept output
        assert a.state == FINISHED and b.state == FINISHED

    def test_finished_lane_reclaimed_for_waiting(self):
        sched = _make_sched(max_lanes=1)
        a = sched.submit(Request([1], max_new_tokens=2, request_id="a"))
        b = sched.submit(Request([2], max_new_tokens=2, request_id="b"))
        _sim_round(sched)
        # the single lane serves a to completion before b ever runs
        assert a.state == FINISHED and b.state != RUNNING
        while sched.has_work():
            _sim_round(sched)
        order = [e for e in sched.events if e[0] in ("admit", "finish")]
        assert order == [("admit", "a", 0), ("finish", "a", None),
                         ("admit", "b", 0), ("finish", "b", None)]

    def test_submit_validates_at_the_door(self):
        sched = _make_sched(max_seq_len=16)
        with pytest.raises(ValueError, match="max_seq_len"):
            sched.submit(Request([0] * 10, max_new_tokens=10))
        small = FCFSScheduler(BlockPool(3, 2), 2, 2, 16)
        with pytest.raises(ValueError, match="KV blocks"):
            small.submit(Request([0] * 5, max_new_tokens=1))
        with pytest.raises(ValueError):
            Request([], max_new_tokens=1)
        with pytest.raises(ValueError):
            Request([1], max_new_tokens=0)

    def test_events_ring_is_bounded(self):
        # long-running servers must not grow with request history
        sched = FCFSScheduler(BlockPool(9, 2), 3, 8, 16, events_cap=8)
        for i in range(20):
            sched.submit(Request([1], max_new_tokens=1, request_id=i))
        while sched.has_work():
            _sim_round(sched)
        assert len(sched.events) == 8
        assert sched.events[-1][0] == "finish"

    def test_prefill_tokens_excludes_pending(self):
        r = Request([1, 2, 3], max_new_tokens=4)
        np.testing.assert_array_equal(r.prefill_tokens, [1, 2, 3])
        r.output = [10, 11]
        np.testing.assert_array_equal(r.prefill_tokens, [1, 2, 3, 10])


# -- scheduler + prefix cache (pure host) -------------------------------------

def _sim_round_sharing(sched, victims=None):
    """_sim_round with the engine's publish step AND its one-lane-at-a-
    time admission: each fake prefill publishes before the next
    admission's lookup, so same-round burst arrivals (and recompute
    re-admissions) share."""
    while True:
        batch = sched.admit(limit=1)
        if not batch:
            break
        req = batch[0]
        req.pool_len = len(req.prefill_tokens)
        sched.publish_prefix(req)
        if not req.output:
            _sim_emit(sched, req, 0)
    for req in sched.running():
        if req.state == RUNNING:
            sched.ensure_capacity(req, on_preempt=(
                victims.append if victims is not None else None))
    act = sched.running()
    for req in act:
        req.pool_len += 1
        _sim_emit(sched, req, len(req.output))
    sched.pool.check_invariant()
    return bool(act)


def _shared_prefix_requests(n, seed, prefix_len=4, max_seq_len=16):
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, 100, (prefix_len,))
    reqs = []
    for i in range(n):
        plen = int(rng.randint(1, max_seq_len // 2 - prefix_len))
        new = int(rng.randint(1, max_seq_len - prefix_len - plen + 1))
        prompt = np.concatenate([prefix, rng.randint(0, 100, (plen,))])
        reqs.append(Request(prompt, max_new_tokens=new, request_id=i))
    return reqs


def _replay_sharing(seed, n=12, prefix_len=4, **geom):
    sched = _make_sched(**geom)
    reqs = _shared_prefix_requests(
        n, seed, prefix_len=prefix_len,
        max_seq_len=geom.get("max_seq_len", 16))
    for r in reqs:
        sched.submit(r)
    rounds = 0
    while sched.has_work():
        _sim_round_sharing(sched)
        rounds += 1
        assert rounds < 10_000, "scheduler livelocked"
    return sched, reqs


class TestSchedulerPrefixCache:
    def test_sharing_engages_and_replays_deterministically(self):
        s1, r1 = _replay_sharing(seed=11)
        s2, _ = _replay_sharing(seed=11)
        hits = [e for e in s1.events if e[0] == "prefix_hit"]
        assert hits, "shared-prefix trace never hit the cache"
        # the full decision log — admits, prefix hits, preemptions,
        # finishes — replays byte-identically (blake2b keys, no hash())
        assert list(s1.events) == list(s2.events)
        assert all(r.state == FINISHED for r in r1)
        assert s1.pool.used_count == 0
        assert s1.pool.cold_count > 0  # released prefixes parked, not freed

    def test_sharing_under_pressure_drains_and_accounts(self):
        # pool far too small for the offered load: preemption + cold-LRU
        # reclaim churn must still drain every request with the
        # free+used+cold identity intact (checked every round)
        sched, reqs = _replay_sharing(seed=3, n=16, num_blocks=9)
        assert any(r.preemptions for r in reqs), \
            "pressure config never preempted — test is vacuous"
        assert all(r.state == FINISHED for r in reqs)
        assert all(len(r.output) == r.max_new_tokens for r in reqs)
        assert sched.pool.used_count == 0
        assert sched.lanes_occupied == 0

    def test_prefix_cache_off_restores_share_nothing_pool(self):
        sched = _make_sched()
        sched.prefix_cache = False
        reqs = _shared_prefix_requests(6, seed=5)
        for r in reqs:
            sched.submit(r)
        while sched.has_work():
            _sim_round_sharing(sched)
        assert not any(e[0] == "prefix_hit" for e in sched.events)
        assert sched.pool.cold_count == 0
        assert sched.pool.indexed_count == 0
        assert all(r.prefix_cached_tokens == 0 for r in reqs)

    def test_ttft_grouping_key_is_first_admission_only(self):
        # a cold-admitted request later re-admitted through the cache
        # keeps ttft_cached_tokens == 0: the bench's cached-vs-cold
        # TTFT A/B must group by the prefill that set t_first
        sched = _make_sched(num_blocks=9, block_size=2, max_lanes=2,
                            max_seq_len=12)
        a = sched.submit(Request([1, 2, 3, 4], max_new_tokens=6,
                                 request_id="a"))
        b = sched.submit(Request([1, 2, 3, 4], max_new_tokens=6,
                                 request_id="b"))
        _sim_round_sharing(sched)
        assert a.ttft_cached_tokens == 0  # first publisher: cold
        assert b.ttft_cached_tokens > 0   # same-trace follower: cached
        while sched.has_work():
            _sim_round_sharing(sched)
        if a.preemptions or b.preemptions:
            # recompute credit accrues to the lifetime counter only
            assert a.ttft_cached_tokens == 0
        assert b.prefix_cached_tokens >= b.ttft_cached_tokens

    def test_admit_failure_returns_hits_to_cold(self):
        # geometry: block 2, lane table 8 blocks, capacity 8
        sched = _make_sched(num_blocks=9, block_size=2, max_lanes=3)
        a = sched.submit(Request([1, 2, 3, 4, 5], max_new_tokens=3,
                                 request_id="a"))
        while not a.finished:  # a publishes [1,2] / [3,4], then frees
            _sim_round_sharing(sched)
        # hog the pool so the next admit's PRIVATE alloc fails after its
        # prefix hits were acquired
        hog = sched.submit(Request([9] * 9, max_new_tokens=4,
                                   request_id="hog"))
        sched.admit()
        assert hog.state == RUNNING
        b = sched.submit(Request([1, 2, 3, 4, 9, 9, 9, 9, 9, 9, 9],
                                 max_new_tokens=3, request_id="b"))
        sched.admit()
        sched.pool.check_invariant()
        assert b.state == WAITING  # 2 hits acquired, private alloc failed
        assert b.blocks == []  # ...and the hits were fully released
        # the matched prefix is back on the cold LRU, still indexed
        assert sched.pool.lookup(prefix_keys([1, 2, 3, 4], 2)) != []
        while sched.has_work():
            _sim_round_sharing(sched)
        assert b.state == FINISHED
        sched.pool.check_invariant()


# -- config / knobs -----------------------------------------------------------

class TestServingConfig:
    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("PT_SERVE_LANES", "5")
        monkeypatch.setenv("PT_SERVE_BLOCK", "8")
        monkeypatch.setenv("PT_SERVE_BLOCKS", "33")
        monkeypatch.setenv("PT_SERVE_PREFILL_CHUNK", "16")
        monkeypatch.setenv("PT_SERVE_MAX_LEN", "64")
        monkeypatch.setenv("PT_DECODE_INT8", "1")
        cfg = ServingConfig()
        assert (cfg.max_lanes, cfg.block_size, cfg.num_blocks,
                cfg.prefill_chunk, cfg.max_seq_len,
                cfg.int8_weights) == (5, 8, 33, 16, 64, True)
        assert cfg.prefix_cache is True  # auto on
        monkeypatch.setenv("PT_SERVE_PREFIX_CACHE", "0")
        assert ServingConfig().prefix_cache is False
        assert ServingConfig(prefix_cache=True).prefix_cache is True

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv("PT_SERVE_LANES", "5")
        assert ServingConfig(max_lanes=2).max_lanes == 2
        with pytest.raises(ValueError):
            ServingConfig(max_lanes=0)

    def test_monitor_audit_membership(self):
        # the None-slot zero-overhead-off audit in test_memory_numerics
        # parametrizes over this list — membership is the contract
        assert "paddle_tpu.serving.engine" in monitor.INSTRUMENTED_MODULES
        # the scheduler's _spans slot (queue-wait/preempt trace spans)
        # joined the same contract in ISSUE 16
        assert "paddle_tpu.serving.scheduler" in monitor.INSTRUMENTED_MODULES


# -- bench trace / probe helpers (pure) ---------------------------------------

class TestBenchHelpers:
    def test_trace_is_seeded_and_sorted(self):
        sb = _load_by_path("serving_bench_t", "benchmarks/serving_bench.py")
        t1 = sb.build_trace(16, 4.0, 100, (3, 12), (4, 12), seed=5)
        t2 = sb.build_trace(16, 4.0, 100, (3, 12), (4, 12), seed=5)
        assert len(t1) == 16
        assert [a for a, _, _ in t1] == sorted(a for a, _, _ in t1)
        for (a1, p1, n1), (a2, p2, n2) in zip(t1, t2):
            assert a1 == a2 and n1 == n2
            np.testing.assert_array_equal(p1, p2)
        t3 = sb.build_trace(16, 4.0, 100, (3, 12), (4, 12), seed=6)
        assert any(not np.array_equal(p1, p3) for (_, p1, _), (_, p3, _)
                   in zip(t1, t3))

    def test_tunnel_probe_summarize(self):
        probe = _load_by_path("ec_probe_t", "tools/exec_cache_tunnel_probe.py")
        cold = {"metric": "m", "telemetry": {
            "compile_ms_total": 900.0, "exec_cache": {"serialized": 3}}}
        warm = {"metric": "m", "telemetry": {
            "compile_ms_total": 40.0,
            "exec_cache": {"disk_hits": 3, "errors": 0}}}
        rec = probe.summarize(cold, warm)
        assert rec["serialize_executable_ok"]
        assert rec["value"] == 860.0
        # a backend whose executables don't round-trip fails the verdict
        warm_bad = {"metric": "m", "telemetry": {
            "compile_ms_total": 900.0,
            "exec_cache": {"disk_hits": 0, "errors": 3}}}
        rec2 = probe.summarize(cold, warm_bad)
        assert not rec2["serialize_executable_ok"]
        assert rec2["deserialize_errors_warm"] == 3


# -- end-to-end (compiled; tier-1 CPU) ----------------------------------------

@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    m.eval()
    return m


def _reference(model, prompt, new):
    return generate(model, pt.to_tensor(np.asarray(prompt)[None, :]),
                    max_new_tokens=new).numpy()[0]


def test_engine_token_identical_and_single_compile(model, tmp_path):
    """THE acceptance proof: 8 requests, unequal prompt/output lengths,
    outputs token-identical to sequential generate() calls, and the
    exec-cache counters show exactly one compile per phase program —
    admission/eviction/growth never retraces."""
    from paddle_tpu.jit import exec_cache as ec

    ec.enable(str(tmp_path))
    ec.clear()
    try:
        eng = ServingEngine(model, ServingConfig(
            max_lanes=3, block_size=4, prefill_chunk=8, max_seq_len=32))
        rng = np.random.RandomState(0)
        reqs = []
        for _ in range(8):
            plen, new = int(rng.randint(3, 13)), int(rng.randint(4, 13))
            prompt = rng.randint(0, model.config.vocab_size,
                                 (plen,)).astype(np.int32)
            reqs.append((eng.submit(prompt, max_new_tokens=new),
                         prompt, new))
        assert len({p.size for _, p, _ in reqs}) > 1, "prompts all equal"
        assert len({n for _, _, n in reqs}) > 1, "output lengths all equal"
        outs = eng.run()
        assert eng.counters["decode_steps"] \
            + eng.counters["verify_steps"] > 0
        misses = ec.stats()["misses"]
        # speculation is auto-on: prefill + decode + verify, once each
        assert misses == 3, f"prefill+decode+verify should compile " \
                            f"once each: {ec.stats()}"
        for r, prompt, new in reqs:
            np.testing.assert_array_equal(
                outs[r.request_id], _reference(model, prompt, new),
                err_msg=f"request {r.request_id} diverged from generate()")
        # a second wave through the SAME engine: zero fresh compiles
        r2 = eng.submit(rng.randint(0, model.config.vocab_size, (7,)),
                        max_new_tokens=6)
        outs2 = eng.run()
        assert ec.stats()["misses"] == misses, "per-request retrace!"
        np.testing.assert_array_equal(
            outs2[r2.request_id],
            _reference(model, r2.prompt, 6))
    finally:
        ec.disable()
        ec.clear()


def test_engine_preemption_recompute_token_identical(model):
    """A pool too small for the offered load forces preemption; the
    recompute path (re-prefill prompt+kept output on re-admission) must
    still reproduce generate() bit for bit."""
    eng = ServingEngine(model, ServingConfig(
        max_lanes=3, block_size=2, num_blocks=12, prefill_chunk=4,
        max_seq_len=20))
    rng = np.random.RandomState(1)
    reqs = []
    for _ in range(6):
        plen, new = int(rng.randint(2, 9)), int(rng.randint(6, 12))
        prompt = rng.randint(0, model.config.vocab_size,
                             (plen,)).astype(np.int32)
        reqs.append((eng.submit(prompt, max_new_tokens=new), prompt, new))
    outs = eng.run()
    assert eng.counters["preemptions"] > 0, \
        "pressure config never preempted — test is vacuous"
    for r, prompt, new in reqs:
        np.testing.assert_array_equal(
            outs[r.request_id], _reference(model, prompt, new),
            err_msg=f"request {r.request_id} (preemptions="
                    f"{r.preemptions}) diverged")
    assert eng.scheduler.pool.used_count == 0  # evicted KV reclaimed


def _shared_prefix_workload(model, rng, n, prefix_len=8, sfx=(1, 6),
                            new=(4, 10)):
    prefix = rng.randint(0, model.config.vocab_size,
                         (prefix_len,)).astype(np.int32)
    out = []
    for _ in range(n):
        suffix = rng.randint(0, model.config.vocab_size,
                             (int(rng.randint(*sfx)),)).astype(np.int32)
        out.append((np.concatenate([prefix, suffix]),
                    int(rng.randint(*new))))
    return out


def test_engine_prefix_cache_token_identity_and_fewer_prefills(
        model, tmp_path):
    """ISSUE 13 acceptance: ≥8 requests sharing a common prefix are
    token-identical to per-request generate() AND to the cache-off
    engine, with strictly fewer prefill chunks — and with ZERO new
    compiled programs (the same two exec-cached executables serve
    cache-on, cache-off, and a second wave; no retraces)."""
    from paddle_tpu.jit import exec_cache as ec

    geom = dict(max_lanes=3, block_size=4, prefill_chunk=8,
                max_seq_len=32)
    work = _shared_prefix_workload(model, np.random.RandomState(7), 8)
    ec.enable(str(tmp_path))
    ec.clear()
    try:
        results, chunks = {}, {}
        for label, pc in (("on", True), ("off", False)):
            eng = ServingEngine(model, ServingConfig(
                prefix_cache=pc, **geom))
            handles = [eng.submit(p, max_new_tokens=n) for p, n in work]
            outs = eng.run()
            results[label] = [outs[h.request_id] for h in handles]
            chunks[label] = eng.counters["prefill_chunks"]
            if pc:
                assert eng.counters["prefix_hit_tokens"] > 0
                assert eng.stats()["prefix_cache"] is True
                # released prefixes parked on the cold LRU, not freed
                assert eng.stats()["cold_blocks"] > 0
                # a second wave through the SAME engine hits the now-
                # warm index from the first token on
                hit0 = eng.counters["prefix_hit_tokens"]
                h2 = [eng.submit(p, max_new_tokens=n)
                      for p, n in work[:3]]
                outs2 = eng.run()
                assert eng.counters["prefix_hit_tokens"] > hit0
                for h, (p, n) in zip(h2, work[:3]):
                    np.testing.assert_array_equal(
                        outs2[h.request_id], _reference(model, p, n))
            eng.scheduler.pool.check_invariant()
        # the tentpole claim: sharing removed prefill compute...
        assert chunks["on"] < chunks["off"], chunks
        # ...without touching a single emitted token
        for i, (p, n) in enumerate(work):
            ref = _reference(model, p, n)
            np.testing.assert_array_equal(results["on"][i], ref)
            np.testing.assert_array_equal(results["off"][i], ref)
        # zero new compiled programs: one prefill + one decode + one
        # verify compile served every engine and wave above (cache
        # on/off share keys — sharing is host bookkeeping, invisible to
        # the programs)
        assert ec.stats()["misses"] == 3, ec.stats()
    finally:
        ec.disable()
        ec.clear()


def test_engine_same_round_burst_shares(model):
    """A burst that fills every lane in ONE scheduling round still
    shares: the engine admits one lane at a time with the prefill (and
    publish) in between, so lanes 2..L hit lane 1's blocks."""
    eng = ServingEngine(model, ServingConfig(
        max_lanes=3, block_size=4, prefill_chunk=8, max_seq_len=32))
    work = _shared_prefix_workload(model, np.random.RandomState(13), 3)
    handles = [eng.submit(p, max_new_tokens=n) for p, n in work]
    eng.step()  # one round admits (and prefills) all three lanes
    assert eng.scheduler.lanes_occupied == 3
    assert eng.counters["prefix_hit_tokens"] >= 2 * 8, eng.counters
    outs = eng.run()
    for h, (p, n) in zip(handles, work):
        np.testing.assert_array_equal(
            outs[h.request_id], _reference(model, p, n))


def test_engine_prefix_cache_preemption_churn_and_replay(model):
    """Token identity + determinism under the worst case: a pool too
    small for the shared-prefix load, so admission hits, cold-LRU
    reclaims, preemptions, and recompute re-admissions (which re-hit
    the victim's own published blocks) interleave. Two identical
    engines must also replay byte-identical event logs — blake2b chain
    keys keep sharing decisions deterministic."""
    work = _shared_prefix_workload(model, np.random.RandomState(9), 8,
                                   prefix_len=4, sfx=(1, 5), new=(6, 11))

    def run_once():
        eng = ServingEngine(model, ServingConfig(
            max_lanes=3, block_size=2, num_blocks=12, prefill_chunk=4,
            max_seq_len=20, prefix_cache=True))
        handles = [eng.submit(p, max_new_tokens=n, request_id=i)
                   for i, (p, n) in enumerate(work)]
        outs = eng.run()
        return eng, [outs[h.request_id] for h in handles]

    eng1, out1 = run_once()
    assert eng1.counters["preemptions"] > 0, \
        "pressure config never preempted — test is vacuous"
    assert eng1.counters["prefix_hit_tokens"] > 0, \
        "pressure config never shared — test is vacuous"
    for (p, n), got in zip(work, out1):
        np.testing.assert_array_equal(got, _reference(model, p, n))
    eng1.scheduler.pool.check_invariant()
    assert eng1.scheduler.pool.used_count == 0
    eng2, out2 = run_once()
    assert list(eng1.scheduler.events) == list(eng2.scheduler.events)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)


def test_engine_prefix_monitor_counters(model):
    """serving/prefix_* counters mirror the engine's always-on ints,
    and the shared/cold gauges land."""
    was = monitor.enabled()
    monitor.enable()
    try:
        base = monitor.snapshot()["counters"]
        eng = ServingEngine(model, ServingConfig(
            max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
        for p, n in _shared_prefix_workload(
                model, np.random.RandomState(4), 5):
            eng.submit(p, max_new_tokens=n)
        eng.run()
        got = monitor.snapshot()["counters"]

        def delta(k):
            return got.get(k, 0) - base.get(k, 0)

        assert delta("serving/prefix_hit_tokens") == \
            eng.counters["prefix_hit_tokens"] > 0
        assert delta("serving/prefix_miss_tokens") == \
            eng.counters["prefix_miss_tokens"] > 0
        gauges = monitor.snapshot()["gauges"]
        assert "serving/shared_blocks" in gauges
        assert "serving/cold_blocks" in gauges
    finally:
        if not was:
            monitor.disable()


def test_engine_eos_early_stop(model):
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, model.config.vocab_size, (5,)).astype(np.int32)
    ref = _reference(model, prompt, 8)
    eos = int(ref[3])
    eng = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    req = eng.submit(prompt, max_new_tokens=8, eos_token_id=eos)
    out = eng.run()[req.request_id]
    assert int(out[-1]) == eos
    np.testing.assert_array_equal(out, ref[:len(out)])
    assert len(out) <= 4  # stopped at the eos, not at max_new_tokens


def test_engine_monitor_counters(model):
    """PT_MONITOR wiring: serving/* counters account the run; the
    always-on plain-int ServingEngine.counters agree."""
    was = monitor.enabled()
    monitor.enable()
    try:
        base = monitor.snapshot()["counters"]
        eng = ServingEngine(model, ServingConfig(
            max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
        rng = np.random.RandomState(3)
        for _ in range(3):
            eng.submit(rng.randint(0, model.config.vocab_size, (4,)),
                       max_new_tokens=4)
        eng.run()
        got = monitor.snapshot()["counters"]

        def delta(k):
            return got.get(k, 0) - base.get(k, 0)

        assert delta("serving/admits") == 3
        assert delta("serving/evictions") == 3  # all finished → reclaimed
        assert delta("serving/decode_steps") == eng.counters["decode_steps"]
        assert delta("serving/prefill_steps") == \
            eng.counters["prefill_chunks"]
        hist = monitor.snapshot()["histograms"].get("serving/queue_wait_ms")
        assert hist and hist["count"] >= 3
    finally:
        if not was:
            monitor.disable()


def test_engine_rejects_duplicates_and_oversize(model):
    eng = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=16))
    eng.submit([1, 2, 3], max_new_tokens=2, request_id="dup")
    with pytest.raises(ValueError, match="duplicate"):
        eng.submit([4, 5], max_new_tokens=2, request_id="dup")
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(list(range(15)), max_new_tokens=4)
    # finished-but-uncollected ids are still taken — a reuse would
    # silently overwrite the uncollected result
    while eng.has_work():
        eng.step()
    with pytest.raises(ValueError, match="uncollected"):
        eng.submit([6], max_new_tokens=2, request_id="dup")
    eng.pop_finished()
    eng.submit([6], max_new_tokens=2, request_id="dup")  # now reusable
    eng.run()


def test_engine_retires_collected_requests(model):
    """run()/pop_finished() collect-and-retire: the engine keeps no
    reference to a collected request (flat host memory under continuous
    feed) and its id becomes reusable."""
    eng = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    eng.submit([1, 2, 3], max_new_tokens=3, request_id="r")
    out1 = eng.run()
    assert list(out1) == ["r"]
    assert eng.run() == {}  # already collected
    st = eng.stats()
    assert st["requests"] == 0 and st["uncollected"] == 0
    eng.submit([4, 5], max_new_tokens=2, request_id="r")  # id reusable
    out2 = eng.run()
    assert list(out2) == ["r"] and len(out2["r"]) == 2


def test_paged_kernel_parity_vs_attend_lanes():
    """The Pallas paged-attention read (interpret mode) reproduces the
    dense `_attend_lanes` gather over a ragged block pool — both dead-
    iteration strategies, GQA, live lengths from 0 (idle lane) to
    full."""
    import jax.numpy as jnp

    from paddle_tpu.serving.engine import _attend_lanes
    from paddle_tpu.ops.pallas.paged_attention import paged_attend

    L, M, B, nkv, g, d = 4, 4, 8, 2, 2, 16
    nh = nkv * g
    rng = np.random.RandomState(0)
    q = rng.randn(L, nh, d).astype(np.float32)
    kpool = rng.randn(L * M + 1, B, nkv, d).astype(np.float32)
    vpool = rng.randn(L * M + 1, B, nkv, d).astype(np.float32)
    tables = (np.arange(L * M, dtype=np.int32).reshape(L, M) + 1)
    pos = np.array([0, 5, B + 3, M * B - 1], np.int32)

    kc = kpool[tables].reshape(L, M * B, nkv, d)
    vc = vpool[tables].reshape(L, M * B, nkv, d)
    ref = np.asarray(_attend_lanes(
        jnp.asarray(q)[:, None], jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(pos)[:, None], nh, nkv))[:, 0]
    for dead in ("clamp", "null"):
        out = paged_attend(jnp.asarray(q), jnp.asarray(kpool),
                           jnp.asarray(vpool), jnp.asarray(tables),
                           jnp.asarray(pos), dead=dead, interpret=True)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5,
                                   err_msg=f"dead={dead}")
    # sliding window masks the low slots too
    ref_w = np.asarray(_attend_lanes(
        jnp.asarray(q)[:, None], jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(pos)[:, None], nh, nkv, sliding_window=6))[:, 0]
    out_w = paged_attend(jnp.asarray(q), jnp.asarray(kpool),
                         jnp.asarray(vpool), jnp.asarray(tables),
                         jnp.asarray(pos), window=6, interpret=True)
    np.testing.assert_allclose(np.asarray(out_w), ref_w, atol=2e-5)


def test_engine_paged_token_identical(model):
    """The serving token-identity proof, extended to the paged read
    path (ISSUE 9): the engine with the Pallas paged-attention kernel
    forced on reproduces per-request generate() bit for bit — through
    unequal lengths, growth, and preemption-recompute churn."""
    eng = ServingEngine(model, ServingConfig(
        max_lanes=3, block_size=2, num_blocks=12, prefill_chunk=4,
        max_seq_len=20, paged=True))
    assert eng.paged_active
    rng = np.random.RandomState(5)
    reqs = []
    for _ in range(6):
        plen, new = int(rng.randint(2, 9)), int(rng.randint(6, 12))
        prompt = rng.randint(0, model.config.vocab_size,
                             (plen,)).astype(np.int32)
        reqs.append((eng.submit(prompt, max_new_tokens=new), prompt,
                     new))
    outs = eng.run()
    assert eng.counters["preemptions"] > 0, \
        "pressure config never preempted — test is vacuous"
    for r, prompt, new in reqs:
        np.testing.assert_array_equal(
            outs[r.request_id], _reference(model, prompt, new),
            err_msg=f"request {r.request_id} diverged on the paged path")
    # the bench's hbm_util delta inputs: dense reads full tables, the
    # paged path only live prefixes
    assert 0 < eng.counters["kv_read_tokens"] \
        < eng.counters["kv_dense_read_tokens"]
    assert eng.stats()["paged_attention"] is True


def test_paged_knob_and_measured_engagement(model, tmp_path,
                                            monkeypatch):
    """PT_SERVE_PAGED=0/1 forces; auto engages ONLY on a measured-
    faster hardware tune-table row for this exact geometry
    (measurement-first — a CPU box with no row stays dense)."""
    from paddle_tpu.ops.pallas import paged_attention as pa
    from paddle_tpu.ops.pallas import search

    monkeypatch.setenv("PT_SERVE_PAGED", "1")
    assert ServingConfig().paged == "on"
    monkeypatch.setenv("PT_SERVE_PAGED", "0")
    assert ServingConfig().paged == "off"
    monkeypatch.delenv("PT_SERVE_PAGED")
    assert ServingConfig().paged == "auto"
    assert ServingConfig(paged=True).paged == "on"

    # auto on CPU with an empty table: dense
    monkeypatch.setenv("PT_KERNEL_TUNE_PATH",
                       str(tmp_path / "t.json"))
    monkeypatch.setattr(search, "_table_cache", None)
    eng = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    assert eng.paged_active is False
    # a measured-faster row for the exact geometry flips auto on
    cfg = model.config
    nh = cfg.num_attention_heads
    nkv = cfg.num_key_value_heads or nh
    key = pa.family_key(4, nkv, nh // nkv, cfg.hidden_size // nh)
    search.update_table(
        lambda d: d.setdefault("families", {}).setdefault(
            "paged_attention", {"entries": {}})["entries"].update(
            {key: {"ratio": 1.4, "backend": "tpu",
                   "device": search._device_kind(),
                   "config": {"dead": "null"}}}))
    eng2 = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    assert eng2.paged_active is True
    # the row's WINNING dead-iteration strategy is what actually runs
    # (and what the bench's stats line reports)
    assert eng2._paged_dead == "null"
    assert eng2.stats()["paged_dead"] == "null"
    # a sliding-window model carries a different key (the window is an
    # engagement-relevant variant) — the window=0 row must not engage it
    assert pa.family_key(4, nkv, nh // nkv,
                         cfg.hidden_size // nh, window=8) != key
    # a measured LOSS stays dense
    search.update_table(
        lambda d: d["families"]["paged_attention"]["entries"][key]
        .update({"ratio": 0.8}))
    eng3 = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    assert eng3.paged_active is False


def test_monitor_report_renders_bench_serving_section(tmp_path):
    """`monitor_report --bench serving.log` must render the serving
    counters serving_bench embeds in its telemetry."""
    mr = _load_by_path("monitor_report_t", "tools/monitor_report.py")
    bench = tmp_path / "serving.log"
    bench.write_text(json.dumps({
        "metric": "serving_tokens_per_sec", "value": 100.0,
        "unit": "tokens/s", "telemetry": {"serving": {
            "admits": 4, "evictions": 4, "prefill_steps": 6,
            "decode_steps": 11, "prefix_hit_tokens": 30,
            "prefix_miss_tokens": 10}}}) + "\n")
    jsonl = tmp_path / "run.jsonl"
    jsonl.write_text(json.dumps({"event": "run_begin", "meta": {}}) + "\n")
    text = mr.render(str(jsonl), bench_path=str(bench))
    assert "serving (continuous batching) (bench)" in text
    assert "decode steps 11" in text
    assert "prefix cache: 30 cached + 10 prefilled" in text
    assert "75% hit rate" in text


def test_serving_bench_smoke_emits_contract_line():
    """`python benchmarks/serving_bench.py --smoke` prints one parseable
    JSON line carrying the acceptance keys."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PT_SERVE_BENCH_REQUESTS"] = "8"
    env["PT_SERVE_BENCH_RATE"] = "200"
    env["PT_SERVE_BENCH_SHARED"] = "8"  # shared-system-prompt mode
    proc = subprocess.run(
        [sys.executable, "benchmarks/serving_bench.py", "--smoke"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("{"))
    rec = json.loads(line)
    assert rec["metric"] == "serving_tokens_per_sec"
    assert rec["tokens_per_sec"] > 0
    assert rec["ttft_ms_p50"] is not None
    assert rec["ttft_ms_p99"] is not None
    assert rec["ttft_ms_p99"] >= rec["ttft_ms_p50"]
    assert rec["completed"] == rec["requests"] == 8
    assert rec["note"] == "cpu smoke mode; not a TPU number"
    # prefix-cache contract fields (ISSUE 13): hit rate + the
    # cached-vs-cold TTFT A/B parse out of the line
    assert rec["prefix_cache"] is True
    assert rec["shared_prefix_tokens"] == 8
    assert 0 < rec["prefix_hit_rate"] <= 1
    assert rec["prefix_hit_tokens"] > 0
    assert rec["prefix_miss_tokens"] > 0
    assert rec["ttft_ms_p50_cached"] is not None
    assert rec["ttft_ms_p50_cold"] is not None
