"""Continuous-batching serving runtime (`paddle_tpu/serving`).

Three layers, mirroring the subsystem's own split:

- **BlockPool safety** — the double-free/alias bug class a paged KV
  cache dies of is unrepresentable: every misuse raises, and the
  free+used==capacity identity holds through churn.
- **Scheduler policy properties** — pure-host simulation of the
  engine's scheduling round over seeded traces: byte-identical replay,
  termination (no starvation: preemption victims are always the NEWEST
  runner, so the oldest request always progresses), preempted requests
  keep their tokens and their blocks return to the pool.
- **Tier-1 CPU end-to-end** — the acceptance proof: ≥8 requests with
  unequal prompt/output lengths through :class:`ServingEngine` are
  token-identical to per-request ``generate()`` calls, with the decode
  step compiled exactly ONCE (exec-cache counters show no per-request
  retraces), plus the serving bench's one-JSON-line contract.
"""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, generate
from paddle_tpu.serving import (
    FINISHED, RUNNING, BlockPool, FCFSScheduler, Request, ServingConfig,
    ServingEngine, blocks_needed,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_by_path(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- block pool ---------------------------------------------------------------

class TestBlockPool:
    def test_blocks_needed(self):
        assert blocks_needed(0, 4) == 0
        assert blocks_needed(1, 4) == 1
        assert blocks_needed(4, 4) == 1
        assert blocks_needed(5, 4) == 2

    def test_null_block_reserved(self):
        pool = BlockPool(4, 2)
        got = pool.alloc(3, "a")
        assert got is not None and 0 not in got
        assert pool.alloc(1, "b") is None  # capacity is num_blocks - 1
        with pytest.raises(ValueError):
            BlockPool(1, 2)  # no room for the null block
        with pytest.raises(ValueError):
            BlockPool(4, 0)

    def test_double_free_raises(self):
        pool = BlockPool(8, 2)
        blocks = pool.alloc(2, "req")
        pool.free(blocks, "req")
        with pytest.raises(ValueError, match="double-free|not allocated"):
            pool.free(blocks, "req")
        pool.check_invariant()

    def test_cross_owner_free_raises(self):
        pool = BlockPool(8, 2)
        a = pool.alloc(2, "a")
        pool.alloc(2, "b")
        with pytest.raises(ValueError, match="owned by"):
            pool.free(a, "b")
        # the failed free must not have leaked anything
        pool.check_invariant()
        assert pool.used_count == 4

    def test_never_allocated_free_raises(self):
        pool = BlockPool(8, 2)
        with pytest.raises(ValueError):
            pool.free([3], "ghost")

    def test_lifo_reuse_and_accounting(self):
        pool = BlockPool(8, 2)
        a = pool.alloc(3, "a")
        pool.free(a, "a")
        b = pool.alloc(3, "b")
        assert b == a[::-1]  # LIFO: just-freed blocks hand out first
        assert pool.free_count + pool.used_count == pool.capacity
        pool.check_invariant()


# -- scheduler policy (pure host — no jax) ------------------------------------

def _sim_emit(sched, req, tok):
    """Engine's _emit without the device: append, finish when done."""
    req.output.append(tok)
    if len(req.output) >= req.max_new_tokens:
        sched.finish(req)


def _sim_round(sched, preempt_victims=None):
    """One ServingEngine.step in pure host logic: admit + fake-prefill
    (first token emitted unless the request is a recompute re-admission),
    growth walk in FCFS order with preemption, one decode emit per
    surviving lane. Token values are just output positions — the replay
    comparison rides on the scheduler's event log, not token content."""
    def on_preempt(victim):
        # no-starvation witness: at preemption time every still-running
        # request is OLDER (smaller admit seq) than the victim
        assert all(r._admit_seq <= victim._admit_seq
                   for r in sched.running())
        if preempt_victims is not None:
            preempt_victims.append(victim.request_id)

    for req in sched.admit():
        req.pool_len = len(req.prefill_tokens)
        if not req.output:
            _sim_emit(sched, req, 0)
    for req in sched.running():
        if req.state == RUNNING:
            sched.ensure_capacity(req, on_preempt=on_preempt)
    act = sched.running()
    for req in act:
        req.pool_len += 1
        _sim_emit(sched, req, len(req.output))
    sched.pool.check_invariant()
    return bool(act)


def _make_sched(num_blocks=9, block_size=2, max_lanes=3, max_seq_len=16):
    return FCFSScheduler(BlockPool(num_blocks, block_size), max_lanes,
                         blocks_needed(max_seq_len, block_size),
                         max_seq_len)


def _trace_requests(n, seed, max_seq_len=16):
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.randint(1, max_seq_len // 2))
        new = int(rng.randint(1, max_seq_len - plen + 1))
        reqs.append(Request(rng.randint(0, 100, (plen,)),
                            max_new_tokens=new, request_id=i))
    return reqs


def _replay(seed, n=12, **geom):
    sched = _make_sched(**geom)
    victims = []
    reqs = _trace_requests(n, seed,
                           max_seq_len=geom.get("max_seq_len", 16))
    for r in reqs:
        sched.submit(r)
    rounds = 0
    while sched.has_work():
        _sim_round(sched, victims)
        rounds += 1
        assert rounds < 10_000, "scheduler livelocked"
    return sched, reqs, victims


class TestScheduler:
    def test_deterministic_replay(self):
        # same seeded trace, two fresh schedulers: the event logs (every
        # admit/preempt/finish decision) must match byte for byte
        s1, _, v1 = _replay(seed=7)
        s2, _, v2 = _replay(seed=7)
        assert s1.events == s2.events
        assert v1 == v2

    def test_all_finish_under_pressure(self):
        # pool far too small for the offered load: preemption churn must
        # still drain every request (no starvation, no livelock)
        # capacity 8 = one max-size request; 3 lanes contend for it
        sched, reqs, victims = _replay(seed=3, n=16, num_blocks=9)
        assert victims, "pressure config never preempted — test is vacuous"
        assert all(r.state == FINISHED for r in reqs)
        assert all(len(r.output) == r.max_new_tokens for r in reqs)
        # everything returned: pool empty, lanes empty
        assert sched.pool.used_count == 0
        assert sched.lanes_occupied == 0

    def test_preempted_request_keeps_tokens(self):
        # each request needs 5 blocks total; capacity 5 forces the two
        # lanes to fight over growth
        sched = _make_sched(num_blocks=6, block_size=2, max_lanes=2,
                            max_seq_len=10)
        a = sched.submit(Request([1, 2], max_new_tokens=8, request_id="a"))
        b = sched.submit(Request([3, 4], max_new_tokens=8, request_id="b"))
        victims = []
        while sched.has_work():
            _sim_round(sched, victims)
        assert "b" in victims and "a" not in victims  # newest loses
        assert b.preemptions >= 1
        assert len(b.output) == 8
        # recompute contract: prefill_tokens replays prompt + kept output
        assert a.state == FINISHED and b.state == FINISHED

    def test_finished_lane_reclaimed_for_waiting(self):
        sched = _make_sched(max_lanes=1)
        a = sched.submit(Request([1], max_new_tokens=2, request_id="a"))
        b = sched.submit(Request([2], max_new_tokens=2, request_id="b"))
        _sim_round(sched)
        # the single lane serves a to completion before b ever runs
        assert a.state == FINISHED and b.state != RUNNING
        while sched.has_work():
            _sim_round(sched)
        order = [e for e in sched.events if e[0] in ("admit", "finish")]
        assert order == [("admit", "a", 0), ("finish", "a", None),
                         ("admit", "b", 0), ("finish", "b", None)]

    def test_submit_validates_at_the_door(self):
        sched = _make_sched(max_seq_len=16)
        with pytest.raises(ValueError, match="max_seq_len"):
            sched.submit(Request([0] * 10, max_new_tokens=10))
        small = FCFSScheduler(BlockPool(3, 2), 2, 2, 16)
        with pytest.raises(ValueError, match="KV blocks"):
            small.submit(Request([0] * 5, max_new_tokens=1))
        with pytest.raises(ValueError):
            Request([], max_new_tokens=1)
        with pytest.raises(ValueError):
            Request([1], max_new_tokens=0)

    def test_events_ring_is_bounded(self):
        # long-running servers must not grow with request history
        sched = FCFSScheduler(BlockPool(9, 2), 3, 8, 16, events_cap=8)
        for i in range(20):
            sched.submit(Request([1], max_new_tokens=1, request_id=i))
        while sched.has_work():
            _sim_round(sched)
        assert len(sched.events) == 8
        assert sched.events[-1][0] == "finish"

    def test_prefill_tokens_excludes_pending(self):
        r = Request([1, 2, 3], max_new_tokens=4)
        np.testing.assert_array_equal(r.prefill_tokens, [1, 2, 3])
        r.output = [10, 11]
        np.testing.assert_array_equal(r.prefill_tokens, [1, 2, 3, 10])


# -- config / knobs -----------------------------------------------------------

class TestServingConfig:
    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("PT_SERVE_LANES", "5")
        monkeypatch.setenv("PT_SERVE_BLOCK", "8")
        monkeypatch.setenv("PT_SERVE_BLOCKS", "33")
        monkeypatch.setenv("PT_SERVE_PREFILL_CHUNK", "16")
        monkeypatch.setenv("PT_SERVE_MAX_LEN", "64")
        monkeypatch.setenv("PT_DECODE_INT8", "1")
        cfg = ServingConfig()
        assert (cfg.max_lanes, cfg.block_size, cfg.num_blocks,
                cfg.prefill_chunk, cfg.max_seq_len,
                cfg.int8_weights) == (5, 8, 33, 16, 64, True)

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv("PT_SERVE_LANES", "5")
        assert ServingConfig(max_lanes=2).max_lanes == 2
        with pytest.raises(ValueError):
            ServingConfig(max_lanes=0)

    def test_monitor_audit_membership(self):
        # the None-slot zero-overhead-off audit in test_memory_numerics
        # parametrizes over this list — membership is the contract
        assert "paddle_tpu.serving.engine" in monitor.INSTRUMENTED_MODULES


# -- bench trace / probe helpers (pure) ---------------------------------------

class TestBenchHelpers:
    def test_trace_is_seeded_and_sorted(self):
        sb = _load_by_path("serving_bench_t", "benchmarks/serving_bench.py")
        t1 = sb.build_trace(16, 4.0, 100, (3, 12), (4, 12), seed=5)
        t2 = sb.build_trace(16, 4.0, 100, (3, 12), (4, 12), seed=5)
        assert len(t1) == 16
        assert [a for a, _, _ in t1] == sorted(a for a, _, _ in t1)
        for (a1, p1, n1), (a2, p2, n2) in zip(t1, t2):
            assert a1 == a2 and n1 == n2
            np.testing.assert_array_equal(p1, p2)
        t3 = sb.build_trace(16, 4.0, 100, (3, 12), (4, 12), seed=6)
        assert any(not np.array_equal(p1, p3) for (_, p1, _), (_, p3, _)
                   in zip(t1, t3))

    def test_tunnel_probe_summarize(self):
        probe = _load_by_path("ec_probe_t", "tools/exec_cache_tunnel_probe.py")
        cold = {"metric": "m", "telemetry": {
            "compile_ms_total": 900.0, "exec_cache": {"serialized": 3}}}
        warm = {"metric": "m", "telemetry": {
            "compile_ms_total": 40.0,
            "exec_cache": {"disk_hits": 3, "errors": 0}}}
        rec = probe.summarize(cold, warm)
        assert rec["serialize_executable_ok"]
        assert rec["value"] == 860.0
        # a backend whose executables don't round-trip fails the verdict
        warm_bad = {"metric": "m", "telemetry": {
            "compile_ms_total": 900.0,
            "exec_cache": {"disk_hits": 0, "errors": 3}}}
        rec2 = probe.summarize(cold, warm_bad)
        assert not rec2["serialize_executable_ok"]
        assert rec2["deserialize_errors_warm"] == 3


# -- end-to-end (compiled; tier-1 CPU) ----------------------------------------

@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    m.eval()
    return m


def _reference(model, prompt, new):
    return generate(model, pt.to_tensor(np.asarray(prompt)[None, :]),
                    max_new_tokens=new).numpy()[0]


def test_engine_token_identical_and_single_compile(model, tmp_path):
    """THE acceptance proof: 8 requests, unequal prompt/output lengths,
    outputs token-identical to sequential generate() calls, and the
    exec-cache counters show exactly one compile per phase program —
    admission/eviction/growth never retraces."""
    from paddle_tpu.jit import exec_cache as ec

    ec.enable(str(tmp_path))
    ec.clear()
    try:
        eng = ServingEngine(model, ServingConfig(
            max_lanes=3, block_size=4, prefill_chunk=8, max_seq_len=32))
        rng = np.random.RandomState(0)
        reqs = []
        for _ in range(8):
            plen, new = int(rng.randint(3, 13)), int(rng.randint(4, 13))
            prompt = rng.randint(0, model.config.vocab_size,
                                 (plen,)).astype(np.int32)
            reqs.append((eng.submit(prompt, max_new_tokens=new),
                         prompt, new))
        assert len({p.size for _, p, _ in reqs}) > 1, "prompts all equal"
        assert len({n for _, _, n in reqs}) > 1, "output lengths all equal"
        outs = eng.run()
        assert eng.counters["decode_steps"] > 0
        misses = ec.stats()["misses"]
        assert misses == 2, f"prefill+decode should compile once each: " \
                            f"{ec.stats()}"
        for r, prompt, new in reqs:
            np.testing.assert_array_equal(
                outs[r.request_id], _reference(model, prompt, new),
                err_msg=f"request {r.request_id} diverged from generate()")
        # a second wave through the SAME engine: zero fresh compiles
        r2 = eng.submit(rng.randint(0, model.config.vocab_size, (7,)),
                        max_new_tokens=6)
        outs2 = eng.run()
        assert ec.stats()["misses"] == misses, "per-request retrace!"
        np.testing.assert_array_equal(
            outs2[r2.request_id],
            _reference(model, r2.prompt, 6))
    finally:
        ec.disable()
        ec.clear()


def test_engine_preemption_recompute_token_identical(model):
    """A pool too small for the offered load forces preemption; the
    recompute path (re-prefill prompt+kept output on re-admission) must
    still reproduce generate() bit for bit."""
    eng = ServingEngine(model, ServingConfig(
        max_lanes=3, block_size=2, num_blocks=12, prefill_chunk=4,
        max_seq_len=20))
    rng = np.random.RandomState(1)
    reqs = []
    for _ in range(6):
        plen, new = int(rng.randint(2, 9)), int(rng.randint(6, 12))
        prompt = rng.randint(0, model.config.vocab_size,
                             (plen,)).astype(np.int32)
        reqs.append((eng.submit(prompt, max_new_tokens=new), prompt, new))
    outs = eng.run()
    assert eng.counters["preemptions"] > 0, \
        "pressure config never preempted — test is vacuous"
    for r, prompt, new in reqs:
        np.testing.assert_array_equal(
            outs[r.request_id], _reference(model, prompt, new),
            err_msg=f"request {r.request_id} (preemptions="
                    f"{r.preemptions}) diverged")
    assert eng.scheduler.pool.used_count == 0  # evicted KV reclaimed


def test_engine_eos_early_stop(model):
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, model.config.vocab_size, (5,)).astype(np.int32)
    ref = _reference(model, prompt, 8)
    eos = int(ref[3])
    eng = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    req = eng.submit(prompt, max_new_tokens=8, eos_token_id=eos)
    out = eng.run()[req.request_id]
    assert int(out[-1]) == eos
    np.testing.assert_array_equal(out, ref[:len(out)])
    assert len(out) <= 4  # stopped at the eos, not at max_new_tokens


def test_engine_monitor_counters(model):
    """PT_MONITOR wiring: serving/* counters account the run; the
    always-on plain-int ServingEngine.counters agree."""
    was = monitor.enabled()
    monitor.enable()
    try:
        base = monitor.snapshot()["counters"]
        eng = ServingEngine(model, ServingConfig(
            max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
        rng = np.random.RandomState(3)
        for _ in range(3):
            eng.submit(rng.randint(0, model.config.vocab_size, (4,)),
                       max_new_tokens=4)
        eng.run()
        got = monitor.snapshot()["counters"]

        def delta(k):
            return got.get(k, 0) - base.get(k, 0)

        assert delta("serving/admits") == 3
        assert delta("serving/evictions") == 3  # all finished → reclaimed
        assert delta("serving/decode_steps") == eng.counters["decode_steps"]
        assert delta("serving/prefill_steps") == \
            eng.counters["prefill_chunks"]
        hist = monitor.snapshot()["histograms"].get("serving/queue_wait_ms")
        assert hist and hist["count"] >= 3
    finally:
        if not was:
            monitor.disable()


def test_engine_rejects_duplicates_and_oversize(model):
    eng = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=16))
    eng.submit([1, 2, 3], max_new_tokens=2, request_id="dup")
    with pytest.raises(ValueError, match="duplicate"):
        eng.submit([4, 5], max_new_tokens=2, request_id="dup")
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(list(range(15)), max_new_tokens=4)
    # finished-but-uncollected ids are still taken — a reuse would
    # silently overwrite the uncollected result
    while eng.has_work():
        eng.step()
    with pytest.raises(ValueError, match="uncollected"):
        eng.submit([6], max_new_tokens=2, request_id="dup")
    eng.pop_finished()
    eng.submit([6], max_new_tokens=2, request_id="dup")  # now reusable
    eng.run()


def test_engine_retires_collected_requests(model):
    """run()/pop_finished() collect-and-retire: the engine keeps no
    reference to a collected request (flat host memory under continuous
    feed) and its id becomes reusable."""
    eng = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    eng.submit([1, 2, 3], max_new_tokens=3, request_id="r")
    out1 = eng.run()
    assert list(out1) == ["r"]
    assert eng.run() == {}  # already collected
    st = eng.stats()
    assert st["requests"] == 0 and st["uncollected"] == 0
    eng.submit([4, 5], max_new_tokens=2, request_id="r")  # id reusable
    out2 = eng.run()
    assert list(out2) == ["r"] and len(out2["r"]) == 2


def test_paged_kernel_parity_vs_attend_lanes():
    """The Pallas paged-attention read (interpret mode) reproduces the
    dense `_attend_lanes` gather over a ragged block pool — both dead-
    iteration strategies, GQA, live lengths from 0 (idle lane) to
    full."""
    import jax.numpy as jnp

    from paddle_tpu.serving.engine import _attend_lanes
    from paddle_tpu.ops.pallas.paged_attention import paged_attend

    L, M, B, nkv, g, d = 4, 4, 8, 2, 2, 16
    nh = nkv * g
    rng = np.random.RandomState(0)
    q = rng.randn(L, nh, d).astype(np.float32)
    kpool = rng.randn(L * M + 1, B, nkv, d).astype(np.float32)
    vpool = rng.randn(L * M + 1, B, nkv, d).astype(np.float32)
    tables = (np.arange(L * M, dtype=np.int32).reshape(L, M) + 1)
    pos = np.array([0, 5, B + 3, M * B - 1], np.int32)

    kc = kpool[tables].reshape(L, M * B, nkv, d)
    vc = vpool[tables].reshape(L, M * B, nkv, d)
    ref = np.asarray(_attend_lanes(
        jnp.asarray(q)[:, None], jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(pos)[:, None], nh, nkv))[:, 0]
    for dead in ("clamp", "null"):
        out = paged_attend(jnp.asarray(q), jnp.asarray(kpool),
                           jnp.asarray(vpool), jnp.asarray(tables),
                           jnp.asarray(pos), dead=dead, interpret=True)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5,
                                   err_msg=f"dead={dead}")
    # sliding window masks the low slots too
    ref_w = np.asarray(_attend_lanes(
        jnp.asarray(q)[:, None], jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(pos)[:, None], nh, nkv, sliding_window=6))[:, 0]
    out_w = paged_attend(jnp.asarray(q), jnp.asarray(kpool),
                         jnp.asarray(vpool), jnp.asarray(tables),
                         jnp.asarray(pos), window=6, interpret=True)
    np.testing.assert_allclose(np.asarray(out_w), ref_w, atol=2e-5)


def test_engine_paged_token_identical(model):
    """The serving token-identity proof, extended to the paged read
    path (ISSUE 9): the engine with the Pallas paged-attention kernel
    forced on reproduces per-request generate() bit for bit — through
    unequal lengths, growth, and preemption-recompute churn."""
    eng = ServingEngine(model, ServingConfig(
        max_lanes=3, block_size=2, num_blocks=12, prefill_chunk=4,
        max_seq_len=20, paged=True))
    assert eng.paged_active
    rng = np.random.RandomState(5)
    reqs = []
    for _ in range(6):
        plen, new = int(rng.randint(2, 9)), int(rng.randint(6, 12))
        prompt = rng.randint(0, model.config.vocab_size,
                             (plen,)).astype(np.int32)
        reqs.append((eng.submit(prompt, max_new_tokens=new), prompt,
                     new))
    outs = eng.run()
    assert eng.counters["preemptions"] > 0, \
        "pressure config never preempted — test is vacuous"
    for r, prompt, new in reqs:
        np.testing.assert_array_equal(
            outs[r.request_id], _reference(model, prompt, new),
            err_msg=f"request {r.request_id} diverged on the paged path")
    # the bench's hbm_util delta inputs: dense reads full tables, the
    # paged path only live prefixes
    assert 0 < eng.counters["kv_read_tokens"] \
        < eng.counters["kv_dense_read_tokens"]
    assert eng.stats()["paged_attention"] is True


def test_paged_knob_and_measured_engagement(model, tmp_path,
                                            monkeypatch):
    """PT_SERVE_PAGED=0/1 forces; auto engages ONLY on a measured-
    faster hardware tune-table row for this exact geometry
    (measurement-first — a CPU box with no row stays dense)."""
    from paddle_tpu.ops.pallas import paged_attention as pa
    from paddle_tpu.ops.pallas import search

    monkeypatch.setenv("PT_SERVE_PAGED", "1")
    assert ServingConfig().paged == "on"
    monkeypatch.setenv("PT_SERVE_PAGED", "0")
    assert ServingConfig().paged == "off"
    monkeypatch.delenv("PT_SERVE_PAGED")
    assert ServingConfig().paged == "auto"
    assert ServingConfig(paged=True).paged == "on"

    # auto on CPU with an empty table: dense
    monkeypatch.setenv("PT_KERNEL_TUNE_PATH",
                       str(tmp_path / "t.json"))
    monkeypatch.setattr(search, "_table_cache", None)
    eng = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    assert eng.paged_active is False
    # a measured-faster row for the exact geometry flips auto on
    cfg = model.config
    nh = cfg.num_attention_heads
    nkv = cfg.num_key_value_heads or nh
    key = pa.family_key(4, nkv, nh // nkv, cfg.hidden_size // nh)
    search.update_table(
        lambda d: d.setdefault("families", {}).setdefault(
            "paged_attention", {"entries": {}})["entries"].update(
            {key: {"ratio": 1.4, "backend": "tpu",
                   "device": search._device_kind(),
                   "config": {"dead": "null"}}}))
    eng2 = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    assert eng2.paged_active is True
    # the row's WINNING dead-iteration strategy is what actually runs
    # (and what the bench's stats line reports)
    assert eng2._paged_dead == "null"
    assert eng2.stats()["paged_dead"] == "null"
    # a sliding-window model carries a different key (the window is an
    # engagement-relevant variant) — the window=0 row must not engage it
    assert pa.family_key(4, nkv, nh // nkv,
                         cfg.hidden_size // nh, window=8) != key
    # a measured LOSS stays dense
    search.update_table(
        lambda d: d["families"]["paged_attention"]["entries"][key]
        .update({"ratio": 0.8}))
    eng3 = ServingEngine(model, ServingConfig(
        max_lanes=2, block_size=4, prefill_chunk=8, max_seq_len=32))
    assert eng3.paged_active is False


def test_monitor_report_renders_bench_serving_section(tmp_path):
    """`monitor_report --bench serving.log` must render the serving
    counters serving_bench embeds in its telemetry."""
    mr = _load_by_path("monitor_report_t", "tools/monitor_report.py")
    bench = tmp_path / "serving.log"
    bench.write_text(json.dumps({
        "metric": "serving_tokens_per_sec", "value": 100.0,
        "unit": "tokens/s", "telemetry": {"serving": {
            "admits": 4, "evictions": 4, "prefill_steps": 6,
            "decode_steps": 11}}}) + "\n")
    jsonl = tmp_path / "run.jsonl"
    jsonl.write_text(json.dumps({"event": "run_begin", "meta": {}}) + "\n")
    text = mr.render(str(jsonl), bench_path=str(bench))
    assert "serving (continuous batching) (bench)" in text
    assert "decode steps 11" in text


def test_serving_bench_smoke_emits_contract_line():
    """`python benchmarks/serving_bench.py --smoke` prints one parseable
    JSON line carrying the acceptance keys."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PT_SERVE_BENCH_REQUESTS"] = "8"
    env["PT_SERVE_BENCH_RATE"] = "200"
    proc = subprocess.run(
        [sys.executable, "benchmarks/serving_bench.py", "--smoke"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("{"))
    rec = json.loads(line)
    assert rec["metric"] == "serving_tokens_per_sec"
    assert rec["tokens_per_sec"] > 0
    assert rec["ttft_ms_p50"] is not None
    assert rec["ttft_ms_p99"] is not None
    assert rec["ttft_ms_p99"] >= rec["ttft_ms_p50"]
    assert rec["completed"] == rec["requests"] == 8
    assert rec["note"] == "cpu smoke mode; not a TPU number"
