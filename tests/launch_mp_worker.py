"""Payload for the two-process distributed launch test.

Run by `python -m paddle_tpu.distributed.launch --nproc_per_node 2` (see
test_launch_multiprocess.py). Mirrors the reference's multi-process
trainer scripts (`test/legacy_test/test_dist_base.py:963` spawns trainers
with hand-set PADDLE_TRAINER_ID/endpoints): each process owns 4 virtual
CPU devices, rendezvouses through `init_parallel_env` →
`jax.distributed.initialize`, then proves the cross-process boundary with
one collective and a tiny DP-sharded train step.
"""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import paddle_tpu as pt  # noqa: E402
from paddle_tpu.framework.core import Tensor  # noqa: E402


def main():
    out_dir = sys.argv[1]
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    pt.distributed.init_parallel_env()  # rendezvous + dp mesh, all devices

    res = {
        "rank": rank,
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "local_device_count": len(jax.local_devices()),
    }

    # -- collective across the process boundary --------------------------
    # each DEVICE contributes its global index; the all-reduce must sum
    # contributions living in the *other* process too
    from paddle_tpu.distributed import env as dist_env

    mesh = dist_env.get_env().mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("dp"))

    def per_shard(index):
        # index is the global slice this device owns: encode its start
        start = index[0].start or 0
        return np.array([float(start)], np.float32)

    arr = jax.make_array_from_callback((jax.device_count(),), sharding,
                                       per_shard)
    t = Tensor(arr)
    out = pt.distributed.all_reduce(t)
    # all_reduce over the dp axis sums the 8 per-device values 0..7
    res["allreduce_sum"] = float(np.asarray(
        out._data.addressable_data(0)).ravel()[0])

    # -- tiny DP train step ----------------------------------------------
    pt.seed(0)
    model = pt.nn.Linear(4, 2)
    opt = pt.optimizer.SGD(learning_rate=0.1,
                           parameters=model.parameters())
    loss_fn = pt.nn.MSELoss()
    from paddle_tpu.jit.train_step import TrainStep

    step = TrainStep(model, opt, lambda m, x, y: loss_fn(m(x), y),
                     donate=False)
    rng = np.random.RandomState(0)
    xs = rng.randn(8, 4).astype(np.float32)
    ys = rng.randn(8, 2).astype(np.float32)
    losses = []
    for _ in range(3):
        loss = step(pt.to_tensor(xs), pt.to_tensor(ys))
        losses.append(float(np.asarray(
            loss._data.addressable_data(0)).ravel()[0]))
    res["losses"] = losses

    with open(os.path.join(out_dir, f"rank{rank}.json"), "w") as f:
        json.dump(res, f)
    print("WORKER_OK", rank, flush=True)


if __name__ == "__main__":
    main()
