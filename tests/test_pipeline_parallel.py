"""Pipeline-parallel tests on the virtual 8-device CPU mesh (pp=4, dp=2).

Mirrors the reference's hybrid-parallel PP integration tests
(`test/collective/fleet/hybrid_parallel_pp_*.py`): numeric parity of the
pipelined forward vs a sequential run, and loss decrease under train_batch.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, PipelineLayer, PipelineParallel,
)

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles


class Block(nn.Layer):
    def __init__(self, h):
        super().__init__()
        self.fc = nn.Linear(h, h)

    def forward(self, x):
        return pt.tanh(self.fc(x))


H = 8


@pytest.fixture(scope="module", autouse=True)
def _mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1, "pp_degree": 4}
    strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _model():
    descs = ([LayerDesc(nn.Linear, H, H)]
             + [LayerDesc(Block, H) for _ in range(8)]
             + [LayerDesc(nn.Linear, H, 4)])
    return PipelineLayer(
        layers=descs, loss_fn=lambda out, lbl: ((out - lbl) ** 2).mean())


class TestPipelineLayer:
    def test_partition(self):
        m = _model()
        assert m._pipelined and m._n_blocks == 8 and m._blocks_per_stage == 2
        names = dict(m.named_parameters())
        assert names["stack__fc_weight"].shape == [8, H, H]
        assert tuple(names["stack__fc_weight"]._data.sharding.spec)[0] == "pp"
        # template params are hidden from the optimizer-facing list
        assert not any(n.startswith("block_template") for n in names)

    def test_forward_parity(self):
        m = _model()
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        y = m(x)
        p = dict(m.named_parameters())
        ref = x.numpy() @ p["head_0.weight"].numpy() + p["head_0.bias"].numpy()
        sw, sb = p["stack__fc_weight"].numpy(), p["stack__fc_bias"].numpy()
        for i in range(8):
            ref = np.tanh(ref @ sw[i] + sb[i])
        ref = ref @ p["tail_0.weight"].numpy() + p["tail_0.bias"].numpy()
        np.testing.assert_allclose(y.numpy(), ref, atol=1e-4)

    def test_train_batch_loss_decreases(self):
        m = _model()
        pp_model = fleet.distributed_model(m)
        assert isinstance(pp_model, PipelineParallel)
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        lbl = pt.to_tensor(np.random.randn(8, 4).astype(np.float32))
        losses = [float(pp_model.train_batch((x, lbl), opt).numpy())
                  for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_eval_batch(self):
        m = _model()
        pp_model = fleet.distributed_model(m)
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        lbl = pt.to_tensor(np.random.randn(8, 4).astype(np.float32))
        loss = pp_model.eval_batch((x, lbl))
        assert loss.ndim == 0

    def test_recompute_matches(self):
        m = _model()
        m._recompute = 1
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        y = m(x)
        m._recompute = 0
        y2 = m(x)
        np.testing.assert_allclose(y.numpy(), y2.numpy(), atol=1e-5)


class TestDegenerate:
    def test_pp1_sequential(self):
        # with pp degree 1 (fresh env), PipelineLayer is a Sequential
        from paddle_tpu.distributed import env as env_mod

        env_mod.init_mesh(dp=-1)
        try:
            m = PipelineLayer(layers=[LayerDesc(nn.Linear, 4, 4),
                                      LayerDesc(Block, 4)])
            assert not m._pipelined
            x = pt.to_tensor(np.random.randn(2, 4).astype(np.float32))
            assert m(x).shape == [2, 4]
        finally:
            env_mod.init_mesh(dp=2, mp=1, pp=4)


class TestSchedules:
    def test_schedule_gpipe_length(self):
        # v=1 is plain GPipe: T = n_micro + pp - 1 ticks
        chunks, enters, exits = PipelineLayer._make_schedule(8, 4, 1)
        assert len(chunks) == 8 + 4 - 1
        assert sorted(e for e in enters if e >= 0) == list(range(8))
        assert sorted(e for e in exits if e >= 0) == list(range(8))

    def test_schedule_interleaved_properties(self):
        # v laps through the ring; every microbatch enters once, exits once,
        # and sees chunks 0..v-1 in order
        n_micro, pp, v = 8, 4, 2
        chunks, enters, exits = PipelineLayer._make_schedule(n_micro, pp, v)
        assert sorted(e for e in enters if e >= 0) == list(range(n_micro))
        assert sorted(e for e in exits if e >= 0) == list(range(n_micro))
        # steady-state length ~ v*n_micro + pp - 1 (waves of pp)
        assert len(chunks) <= v * n_micro + v * pp
        # replay: track each microbatch through the ring, assert chunk order
        lap_seen = {m: [] for m in range(n_micro)}
        slots = [-1] * pp
        for t, (ch, en, ex) in enumerate(zip(chunks, enters, exits)):
            if en >= 0:
                slots[0] = en
            for d in range(pp):
                if slots[d] >= 0:
                    lap_seen[slots[d]].append((d, ch[d]))
            if ex >= 0:
                slots[pp - 1] = -1
            slots = [slots[-1]] + slots[:-1]
        for m, seen in lap_seen.items():
            assert len(seen) == pp * v
            # chunk index is the lap count: 0 for first pp hops, then 1, ...
            assert [c for _, c in seen] == [i // pp for i in range(pp * v)]

    def test_interleaved_forward_parity(self):
        descs = ([LayerDesc(nn.Linear, H, H)]
                 + [LayerDesc(Block, H) for _ in range(8)]
                 + [LayerDesc(nn.Linear, H, 4)])
        m = PipelineLayer(layers=descs, num_virtual_pipeline_stages=2,
                          loss_fn=lambda o, l: ((o - l) ** 2).mean())
        assert m._blocks_per_chunk == 1
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        y = m(x)
        p = dict(m.named_parameters())
        ref = x.numpy() @ p["head_0.weight"].numpy() + p["head_0.bias"].numpy()
        # stacked storage is (device, chunk, intra) order; undo to block order
        order = m._block_order
        sw = p["stack__fc_weight"].numpy()
        sb = p["stack__fc_bias"].numpy()
        for b in range(8):
            s = order.index(b)
            ref = np.tanh(ref @ sw[s] + sb[s])
        ref = ref @ p["tail_0.weight"].numpy() + p["tail_0.bias"].numpy()
        np.testing.assert_allclose(y.numpy(), ref, atol=1e-4)

    def test_remat_ticks_parity(self):
        m = _model()
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        m._remat_ticks = True
        y1 = m(x)
        m._remat_ticks = False
        y2 = m(x)
        np.testing.assert_allclose(y1.numpy(), y2.numpy(), atol=1e-5)

    def test_remat_ticks_grad_parity(self):
        m = _model()
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        lbl = pt.to_tensor(np.random.randn(8, 4).astype(np.float32))
        grads = []
        for rt in (True, False):
            m._remat_ticks = rt
            loss = m.loss_fn(m(x), lbl)
            loss.backward()
            p = dict(m.named_parameters())["stack__fc_weight"]
            grads.append(np.asarray(p.grad.numpy()).copy())
            for _, q in m.named_parameters():
                q.clear_grad()
        np.testing.assert_allclose(grads[0], grads[1], atol=1e-5)

    def test_compile_time_bounded(self):
        # VERDICT round-1 criterion: pp=4, n_micro=16 compiles in seconds
        # (the round-1 unrolled loop scaled compile time with n_micro).
        import time

        m = _model()
        x = pt.to_tensor(np.random.randn(32, H).astype(np.float32))
        t0 = time.time()
        y = m(x, n_microbatches=16)
        y.numpy()
        dt = time.time() - t0
        assert dt < 60, f"pipeline compile took {dt:.1f}s"


class TestHeadTailSharding:
    def test_big_head_param_sharded_over_pp(self):
        descs = ([LayerDesc(nn.Linear, 256, 512)]   # 128K params > 2**16
                 + [LayerDesc(Block, 512) for _ in range(4)])
        m = PipelineLayer(layers=descs)
        p = dict(m.named_parameters())["head_0.weight"]
        assert "pp" in tuple(p._data.sharding.spec)
