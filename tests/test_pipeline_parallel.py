"""Pipeline-parallel tests on the virtual 8-device CPU mesh (pp=4, dp=2).

Mirrors the reference's hybrid-parallel PP integration tests
(`test/collective/fleet/hybrid_parallel_pp_*.py`): numeric parity of the
pipelined forward vs a sequential run, and loss decrease under train_batch.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, PipelineLayer, PipelineParallel,
)


class Block(nn.Layer):
    def __init__(self, h):
        super().__init__()
        self.fc = nn.Linear(h, h)

    def forward(self, x):
        return pt.tanh(self.fc(x))


H = 8


@pytest.fixture(scope="module", autouse=True)
def _mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1, "pp_degree": 4}
    strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    from paddle_tpu.distributed import env as env_mod

    env_mod.reset_env()


def _model():
    descs = ([LayerDesc(nn.Linear, H, H)]
             + [LayerDesc(Block, H) for _ in range(8)]
             + [LayerDesc(nn.Linear, H, 4)])
    return PipelineLayer(
        layers=descs, loss_fn=lambda out, lbl: ((out - lbl) ** 2).mean())


class TestPipelineLayer:
    def test_partition(self):
        m = _model()
        assert m._pipelined and m._n_blocks == 8 and m._blocks_per_stage == 2
        names = dict(m.named_parameters())
        assert names["stack__fc_weight"].shape == [8, H, H]
        assert tuple(names["stack__fc_weight"]._data.sharding.spec)[0] == "pp"
        # template params are hidden from the optimizer-facing list
        assert not any(n.startswith("block_template") for n in names)

    def test_forward_parity(self):
        m = _model()
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        y = m(x)
        p = dict(m.named_parameters())
        ref = x.numpy() @ p["head_0.weight"].numpy() + p["head_0.bias"].numpy()
        sw, sb = p["stack__fc_weight"].numpy(), p["stack__fc_bias"].numpy()
        for i in range(8):
            ref = np.tanh(ref @ sw[i] + sb[i])
        ref = ref @ p["tail_0.weight"].numpy() + p["tail_0.bias"].numpy()
        np.testing.assert_allclose(y.numpy(), ref, atol=1e-4)

    def test_train_batch_loss_decreases(self):
        m = _model()
        pp_model = fleet.distributed_model(m)
        assert isinstance(pp_model, PipelineParallel)
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        lbl = pt.to_tensor(np.random.randn(8, 4).astype(np.float32))
        losses = [float(pp_model.train_batch((x, lbl), opt).numpy())
                  for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_eval_batch(self):
        m = _model()
        pp_model = fleet.distributed_model(m)
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        lbl = pt.to_tensor(np.random.randn(8, 4).astype(np.float32))
        loss = pp_model.eval_batch((x, lbl))
        assert loss.ndim == 0

    def test_recompute_matches(self):
        m = _model()
        m._recompute = 1
        x = pt.to_tensor(np.random.randn(8, H).astype(np.float32))
        y = m(x)
        m._recompute = 0
        y2 = m(x)
        np.testing.assert_allclose(y.numpy(), y2.numpy(), atol=1e-5)


class TestDegenerate:
    def test_pp1_sequential(self):
        # with pp degree 1 (fresh env), PipelineLayer is a Sequential
        from paddle_tpu.distributed import env as env_mod

        env_mod.init_mesh(dp=-1)
        try:
            m = PipelineLayer(layers=[LayerDesc(nn.Linear, 4, 4),
                                      LayerDesc(Block, 4)])
            assert not m._pipelined
            x = pt.to_tensor(np.random.randn(2, 4).astype(np.float32))
            assert m(x).shape == [2, 4]
        finally:
            env_mod.init_mesh(dp=2, mp=1, pp=4)
