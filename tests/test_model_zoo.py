"""Vision model zoo: forward shapes + compiled-train-step smoke for every
architecture family (VERDICT r2 item 7: >=4 new architectures training
under TrainStep). Reference: `python/paddle/vision/models/`."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.vision import models as M

pytestmark = pytest.mark.slow  # integration tier: heavy XLA compiles

NC = 7  # small head to keep tests fast


def _img(b=2, s=64):
    return paddle.to_tensor(
        np.random.default_rng(0).standard_normal((b, 3, s, s))
        .astype("float32"))


FORWARD_CASES = [
    ("vgg11", lambda: M.vgg11(num_classes=NC), 64),
    ("vgg16_bn", lambda: M.vgg16(batch_norm=True, num_classes=NC), 64),
    ("mobilenet_v1", lambda: M.mobilenet_v1(num_classes=NC, scale=0.25), 64),
    ("mobilenet_v2", lambda: M.mobilenet_v2(num_classes=NC, scale=0.25), 64),
    ("mobilenet_v3_small",
     lambda: M.mobilenet_v3_small(num_classes=NC, scale=0.5), 64),
    ("mobilenet_v3_large",
     lambda: M.mobilenet_v3_large(num_classes=NC, scale=0.5), 64),
    ("densenet121", lambda: M.densenet121(num_classes=NC), 64),
    ("alexnet", lambda: M.alexnet(num_classes=NC), 224),
    ("squeezenet1_1", lambda: M.squeezenet1_1(num_classes=NC), 64),
    ("shufflenet_v2_x0_25",
     lambda: M.shufflenet_v2_x0_25(num_classes=NC), 64),
    ("inception_v3", lambda: M.inception_v3(num_classes=NC), 128),
]


@pytest.mark.parametrize("name,ctor,size", FORWARD_CASES,
                         ids=[c[0] for c in FORWARD_CASES])
def test_forward_shape(name, ctor, size):
    paddle.seed(0)
    model = ctor()
    model.eval()
    out = model(_img(2, size))
    assert out.shape == [2, NC]
    assert np.isfinite(out.numpy()).all()


def test_googlenet_aux_heads():
    paddle.seed(0)
    model = M.googlenet(num_classes=NC)
    model.train()
    out, aux1, aux2 = model(_img(2, 224))
    assert out.shape == [2, NC]
    assert aux1.shape == [2, NC] and aux2.shape == [2, NC]
    model.eval()
    out, aux1, aux2 = model(_img(2, 224))
    assert aux1 is None and aux2 is None


TRAIN_CASES = [
    ("vgg11", lambda: M.vgg11(num_classes=NC), 64),
    ("mobilenet_v2", lambda: M.mobilenet_v2(num_classes=NC, scale=0.25), 64),
    ("mobilenet_v3_small",
     lambda: M.mobilenet_v3_small(num_classes=NC, scale=0.5), 64),
    ("densenet121", lambda: M.densenet121(num_classes=NC), 64),
    ("shufflenet_v2_x0_25",
     lambda: M.shufflenet_v2_x0_25(num_classes=NC), 64),
    ("squeezenet1_1", lambda: M.squeezenet1_1(num_classes=NC), 64),
]


@pytest.mark.parametrize("name,ctor,size", TRAIN_CASES,
                         ids=[c[0] for c in TRAIN_CASES])
def test_trains_under_trainstep(name, ctor, size):
    paddle.seed(0)
    model = ctor()
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    import paddle_tpu.nn.functional as F

    step = TrainStep(model, opt, lambda m, x, y: F.cross_entropy(m(x), y))
    x = _img(2, size)
    y = paddle.to_tensor(np.asarray([0, 1], "int64"))
    l0 = float(step(x, y).numpy())
    for _ in range(3):
        loss = step(x, y)
    l1 = float(loss.numpy())
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0  # same-batch loss must drop in 4 steps
